"""Paged KV-cache accounting: slot pool, page ladder, prefix reuse.

Generation state is the one serving resource that OUTLIVES a
micro-batch: a session's key/value cache must stay resident on device
between decode steps, so HBM is committed for the session's lifetime —
admission control has to happen at session start, not per batch.  This
module is the accounting half of the generation subsystem (ISSUE 16):

* :class:`KVSlotPool` — a fixed pool of decode slots (one slot = one
  row of the fixed-shape decode micro-batch).  ``acquire`` charges the
  session's **bucket-laddered page reservation** — ``ceil((prompt +
  max_new) / page_tokens)`` pages, each ``page_tokens *
  bytes_per_token`` — to the PR 13 resource ledger under
  ``(owner, "kv_pages")``, so committed KV bytes are visible in
  ``LEDGER``/``/fleet.json`` next to executor-cache and train-state
  footprints.  A full pool or a blown budget sheds **typed**
  (:class:`KVPoolExhaustedError`, a :class:`ServingOverloadError`) —
  the same fail-fast contract as the batcher's queue watermark.
  ``release`` is idempotent and returns every page: the zero-leak
  invariant the ``replica_kill_mid_generation`` chaos scenario asserts.
* :class:`PrefixCache` — the ``ExecutorCache`` idiom applied to
  activations: an LRU keyed ``(model, version, sha1(prefix tokens))``
  holding host copies of page-aligned prompt-prefix KV.  A hit writes
  the cached pages into the session's slot and skips recomputing the
  shared prefix; entries charge ``(owner, "prefix_cache")`` in the
  ledger and ``evict_stale_versions`` retires a flipped version's
  activations so they can never serve again (ISSUE 16 small fix).

The ledger is an estimator, not an allocator (resources.py): pages
bound what generation may COMMIT, the arena itself is allocated once at
engine construction with a fixed ``[slots, max_len]`` shape.
"""
from __future__ import annotations

import collections
import hashlib
import threading

import numpy as np

from ..base import MXNetError
from .batcher import ServingOverloadError


def _ledger():
    from ..telemetry.resources import LEDGER
    return LEDGER


class KVPoolExhaustedError(ServingOverloadError):
    """Session admission shed: every decode slot is busy, or the page
    reservation would blow the KV HBM budget.  Typed and retryable —
    back off and resubmit once a sibling session finishes."""

    def __init__(self, pool, kind, in_use, capacity):
        self.batcher = pool
        self.queue_depth = in_use
        self.watermark = capacity
        self.predicted_p99_ms = None
        self.slo_ms = None
        self.kind = kind
        MXNetError.__init__(
            self,
            f"generation[{pool}]: KV {kind} exhausted ({in_use}/{capacity}"
            f" {kind} committed); session shed — retry with backoff, or "
            "lower max_new_tokens so the page reservation fits "
            "(MXNET_GENERATION_SLOTS / MXNET_GENERATION_KV_BUDGET_MB)")


def pages_for(tokens, page_tokens):
    """Bucket-laddered page count: tokens rounded up to whole pages
    (minimum one page — an admitted session always holds a slot row)."""
    return max(1, -(-int(tokens) // max(1, int(page_tokens))))


class KVSlot:
    """One decode-slot lease: the arena row index plus the session's
    charged page reservation."""

    __slots__ = ("index", "session_id", "pages", "nbytes", "released")

    def __init__(self, index, session_id, pages, nbytes):
        self.index = index
        self.session_id = session_id
        self.pages = pages
        self.nbytes = nbytes
        self.released = False


class KVSlotPool:
    """Admission-controlled pool of decode slots with ledger-charged
    page reservations."""

    def __init__(self, owner, slots, page_tokens, bytes_per_token,
                 budget_bytes):
        self.owner = str(owner)
        self.slots = int(slots)
        self.page_tokens = int(page_tokens)
        self.bytes_per_token = int(bytes_per_token)
        self.budget_bytes = int(budget_bytes)
        self._lock = threading.Lock()
        self._free = list(range(self.slots - 1, -1, -1))
        self._leases = {}          # index -> KVSlot
        self.acquires = 0
        self.releases = 0
        self.sheds = 0

    def page_bytes(self):
        return self.page_tokens * self.bytes_per_token

    def acquire(self, session_id, reserve_tokens):
        """Lease a slot charging ``reserve_tokens`` worth of pages;
        sheds typed when no slot is free or the budget cannot fit the
        reservation."""
        pages = pages_for(reserve_tokens, self.page_tokens)
        nbytes = pages * self.page_bytes()
        with self._lock:
            if not self._free:
                self.sheds += 1
                raise KVPoolExhaustedError(self.owner, "slots",
                                           len(self._leases), self.slots)
            committed = sum(s.nbytes for s in self._leases.values())
            if committed + nbytes > self.budget_bytes:
                self.sheds += 1
                raise KVPoolExhaustedError(
                    self.owner, "page budget bytes",
                    committed + nbytes, self.budget_bytes)
            slot = KVSlot(self._free.pop(), session_id, pages, nbytes)
            self._leases[slot.index] = slot
            self.acquires += 1
        _ledger().add(self.owner, "kv_pages", nbytes)
        return slot

    def grow(self, slot, total_tokens):
        """Extend ``slot``'s reservation to cover ``total_tokens``
        (no-op when already covered); sheds typed on a blown budget —
        the caller fails the SESSION, never a sibling."""
        pages = pages_for(total_tokens, self.page_tokens)
        if pages <= slot.pages:
            return 0
        extra = (pages - slot.pages) * self.page_bytes()
        with self._lock:
            committed = sum(s.nbytes for s in self._leases.values())
            if committed + extra > self.budget_bytes:
                self.sheds += 1
                raise KVPoolExhaustedError(
                    self.owner, "page budget bytes",
                    committed + extra, self.budget_bytes)
            slot.pages = pages
            slot.nbytes += extra
        _ledger().add(self.owner, "kv_pages", extra)
        return extra

    def release(self, slot):
        """Return the slot and every charged page (idempotent)."""
        with self._lock:
            if slot.released or self._leases.get(slot.index) is not slot:
                return False
            slot.released = True
            del self._leases[slot.index]
            self._free.append(slot.index)
            self.releases += 1
        _ledger().release(self.owner, "kv_pages", slot.nbytes)
        return True

    def stats(self):
        with self._lock:
            leases = list(self._leases.values())
            return {
                "slots": self.slots,
                "slots_in_use": len(leases),
                "pages_in_use": sum(s.pages for s in leases),
                "kv_bytes": sum(s.nbytes for s in leases),
                "budget_bytes": self.budget_bytes,
                "page_tokens": self.page_tokens,
                "bytes_per_token": self.bytes_per_token,
                "acquires": self.acquires,
                "releases": self.releases,
                "sheds": self.sheds,
            }


def prefix_key(model, version, tokens, length):
    """Content-hash cache key for a token prefix: the activation
    analogue of the executor cache's ``(model, version, signature)``."""
    digest = hashlib.sha1(
        np.ascontiguousarray(np.asarray(tokens[:length],
                                        np.int64))).hexdigest()
    return (str(model), int(version), int(length), digest)


class PrefixCache:
    """LRU of page-aligned prompt-prefix KV activations (host copies)."""

    def __init__(self, owner, capacity, page_tokens):
        self.owner = str(owner)
        self.capacity = int(capacity)
        self.page_tokens = int(page_tokens)
        self._lock = threading.Lock()
        self._entries = collections.OrderedDict()  # key -> (kv, nbytes)
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def enabled(self):
        return self.capacity > 0

    def _hit_lengths(self, n_tokens):
        """Candidate page-aligned prefix lengths, longest first.  The
        final prompt token is always recomputed (its decode step is what
        produces the first sampled-token logits), so a full-prompt hit
        caps at ``n_tokens - 1`` rounded down to a page boundary."""
        longest = ((int(n_tokens) - 1) // self.page_tokens) \
            * self.page_tokens
        return range(longest, 0, -self.page_tokens)

    def lookup(self, model, version, tokens):
        """Longest cached page-aligned prefix of ``tokens`` for this
        (model, version) — ``(length, kv_dict)`` or ``(0, None)``."""
        if not self.enabled():
            return 0, None
        for length in self._hit_lengths(len(tokens)):
            key = prefix_key(model, version, tokens, length)
            with self._lock:
                entry = self._entries.get(key)
                if entry is not None:
                    self._entries.move_to_end(key)
                    self.hits += 1
                    return length, entry[0]
        with self._lock:
            self.misses += 1
        return 0, None

    def store(self, model, version, tokens, kv):
        """Insert host KV for the longest page-aligned prefix of
        ``tokens`` (``kv`` leaves are ``[prompt_len, ...]`` host
        arrays, truncated here).  Skips sub-page prompts."""
        if not self.enabled():
            return 0
        lengths = list(self._hit_lengths(len(tokens)))
        if not lengths:
            return 0
        length = lengths[0]
        key = prefix_key(model, version, tokens, length)
        clipped = {name: np.ascontiguousarray(
            np.asarray(arr)[:length]) for name, arr in kv.items()}
        nbytes = sum(a.nbytes for a in clipped.values())
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return length
            self._entries[key] = (clipped, nbytes)
            doomed = []
            while len(self._entries) > self.capacity:
                _k, gone = self._entries.popitem(last=False)
                self.evictions += 1
                doomed.append(gone[1])
        _ledger().add(self.owner, "prefix_cache", nbytes)
        for freed in doomed:
            _ledger().release(self.owner, "prefix_cache", freed)
        return length

    def evict_stale_versions(self, model, keep_versions):
        """Version-flip retirement: a stale version's activations must
        never seed a new session's KV (ISSUE 16 small fix)."""
        keep = {int(v) for v in keep_versions}
        with self._lock:
            doomed = [k for k in self._entries
                      if k[0] == str(model) and k[1] not in keep]
            freed = 0
            for k in doomed:
                freed += self._entries.pop(k)[1]
                self.evictions += 1
        if freed:
            _ledger().release(self.owner, "prefix_cache", freed)
        return len(doomed)

    def clear(self):
        with self._lock:
            freed = sum(n for _kv, n in self._entries.values())
            self._entries.clear()
        if freed:
            _ledger().release(self.owner, "prefix_cache", freed)

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def stats(self):
        with self._lock:
            return {"size": len(self._entries), "capacity": self.capacity,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "bytes": sum(n for _kv, n in self._entries.values())}
