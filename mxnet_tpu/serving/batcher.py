"""DynamicBatcher: deadline-bounded request coalescing.

Requests (one sample each, no batch dim) enter a bounded queue; worker
threads drain it into batches under the policy

* flush when ``max_batch_size`` requests have coalesced, OR
* flush when ``max_latency_ms`` has elapsed since the oldest queued
  request started waiting (a lone request never waits longer than the
  deadline — the throughput-vs-p99 knob, see docs/serving.md);
* a burst larger than ``max_batch_size`` is split into micro-batches:
  each worker pass takes at most ``max_batch_size`` requests and the
  remainder stays queued for the next pass (or another worker).

Robustness contract:

* the queue is bounded — ``submit`` on a queue at the shed watermark
  fails fast with ``ServingOverloadError`` (an ``MXNetError`` carrying
  ``queue_depth``/``watermark``/``batcher`` fields) instead of letting
  latency grow without bound;
* malformed requests fail ALONE: ``submit`` normalizes inputs to host
  arrays, runs the optional ``validator`` (rejecting synchronously with
  a structured error), and workers group requests by input signature
  (names + per-sample shapes + dtypes) so a request that could not
  stack with its neighbours executes in its own cohort instead of
  poisoning the whole micro-batch;
* per-request timeouts: a request whose deadline expires while queued
  is failed with ``RequestTimeoutError`` without wasting a batch slot;
* ``close(drain=True)`` stops intake, lets workers drain everything
  in flight, then joins them; ``drain=False`` fails queued requests
  immediately (structured error, never a hang).
"""
from __future__ import annotations

import collections
import threading
import time

import numpy as np

from ..base import MXNetError
from ..chaos.failpoints import failpoint as _failpoint
from ..telemetry import watchdog as _watchdog
from .metrics import ServingMetrics


class ServingOverloadError(MXNetError):
    """Load shed: queue depth reached the watermark (backpressure)."""

    def __init__(self, batcher, queue_depth, watermark):
        self.batcher = batcher
        self.queue_depth = queue_depth
        self.watermark = watermark
        super().__init__(
            f"serving[{batcher}]: queue depth {queue_depth} >= shed "
            f"watermark {watermark}; request shed — retry with backoff "
            "(load-shedding keeps p99 bounded instead of queueing "
            "unboundedly)")


class RequestTimeoutError(MXNetError):
    """The request's deadline expired before (or while) it was served."""

    def __init__(self, batcher, waited_ms, timeout_ms):
        self.batcher = batcher
        self.waited_ms = waited_ms
        self.timeout_ms = timeout_ms
        super().__init__(
            f"serving[{batcher}]: request timed out after "
            f"{waited_ms:.1f}ms (timeout {timeout_ms:.1f}ms)")


class ServingClosedError(MXNetError):
    """Submit after shutdown (or request abandoned by drain=False)."""

    def __init__(self, batcher):
        self.batcher = batcher
        super().__init__(f"serving[{batcher}]: server is shut down")


class ServingWorkerError(MXNetError):
    """A batch worker thread died executing this request's batch.

    ``retryable`` is True: the request itself was well-formed — the
    worker crashed around it (and was restarted, budget permitting), so
    resubmitting is the right client response.  When the restart budget
    is exhausted the batcher fails fast with this error too
    (``exhausted=True``) rather than letting requests queue into a hang.
    """

    retryable = True

    def __init__(self, batcher, cause=None, exhausted=False):
        self.batcher = batcher
        self.cause = cause
        self.exhausted = exhausted
        if exhausted:
            msg = (f"serving[{batcher}]: worker restart budget exhausted "
                   "(MXNET_SERVING_WORKER_RESTARTS); batcher failed fast "
                   "— requests are rejected, never silently queued")
        else:
            msg = (f"serving[{batcher}]: worker thread died executing "
                   f"this batch ({type(cause).__name__}: {cause}); the "
                   "worker was restarted — retry the request")
        super().__init__(msg)


class ServeFuture:
    """Minimal future for one request (threading.Event based).

    Resolution is first-write-wins: a request failed from OUTSIDE its
    worker (the in-flight sweep failing requests stuck on a wedged
    thread) must not be re-resolved when that thread eventually comes
    back and reports its stale outcome.
    """

    __slots__ = ("_event", "_result", "_exc", "_resolve_lock")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._exc = None
        self._resolve_lock = threading.Lock()

    def _set_result(self, value):
        with self._resolve_lock:
            if self._event.is_set():
                return
            self._result = value
            self._event.set()

    def _set_exception(self, exc):
        with self._resolve_lock:
            if self._event.is_set():
                return
            self._exc = exc
            self._event.set()

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise MXNetError(
                f"serving: no response within {timeout}s (request still "
                "queued or executing)")
        with self._resolve_lock:
            exc, value = self._exc, self._result
        if exc is not None:
            raise exc
        return value


class _Request:
    __slots__ = ("inputs", "sig", "future", "t_enqueue", "deadline")

    def __init__(self, inputs, sig, deadline):
        self.inputs = inputs
        self.sig = sig
        self.future = ServeFuture()
        self.t_enqueue = time.perf_counter()
        self.deadline = deadline


class DynamicBatcher:
    """Queue + worker threads draining it through ``runner``.

    ``runner(feed, n_real)`` receives ``{input_name: np.ndarray}`` with
    the requests stacked on a new leading axis (``n_real`` rows, NOT yet
    padded — shape bucketing is the runner's concern, see
    executor_cache) and returns a list of batch-leading output arrays;
    row ``i`` of every output answers request ``i``.
    """

    def __init__(self, runner, max_batch_size=None, max_latency_ms=None,
                 num_workers=None, max_queue_depth=None, shed_watermark=None,
                 default_timeout_ms=None, name="batcher", metrics=None,
                 validator=None):
        from .. import config as _config
        cfg = _config.get
        self.name = name
        self._runner = runner
        # validator(inputs) runs at submit time with the normalized host
        # arrays; raising rejects THAT request synchronously before it
        # can join (and poison) a batch
        self._validator = validator
        self.max_batch_size = int(max_batch_size
                                  if max_batch_size is not None
                                  else cfg("MXNET_SERVING_MAX_BATCH"))
        self.max_latency_ms = float(max_latency_ms
                                    if max_latency_ms is not None
                                    else cfg("MXNET_SERVING_MAX_LATENCY_MS"))
        self.max_queue_depth = int(max_queue_depth
                                   if max_queue_depth is not None
                                   else cfg("MXNET_SERVING_QUEUE_DEPTH"))
        watermark = (shed_watermark if shed_watermark is not None
                     else cfg("MXNET_SERVING_SHED_WATERMARK"))
        # 0 = "at queue capacity"; the watermark may sit below capacity so
        # sheds start before the queue is physically full
        self.shed_watermark = int(watermark) or self.max_queue_depth
        self.default_timeout_ms = float(
            default_timeout_ms if default_timeout_ms is not None
            else cfg("MXNET_SERVING_TIMEOUT_MS"))
        n_workers = int(num_workers if num_workers is not None
                        else cfg("MXNET_SERVING_NUM_WORKERS"))
        if self.max_batch_size <= 0 or n_workers <= 0:
            raise MXNetError("serving: max_batch_size and num_workers "
                             "must be positive")
        self.metrics = metrics or ServingMetrics(name)
        self._queue = collections.deque()
        self._cond = threading.Condition()
        self._closed = False
        # worker self-healing: a crashed worker restarts in place until
        # the budget runs dry, then the batcher fails fast (never hangs)
        self._restart_budget = int(cfg("MXNET_SERVING_WORKER_RESTARTS"))
        self._restarts = 0
        self._failed = False
        # batches claimed by a worker but not yet finished, by worker
        # thread ident — the sweep fails their expired-deadline requests
        # with RequestTimeoutError when the claiming thread is wedged
        # (a wedged worker must never silently hold requests forever)
        self._inflight = {}
        self._workers = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"mx-serving-{name}-{i}")
            for i in range(n_workers)]
        for t in self._workers:
            t.start()

    # -- intake -------------------------------------------------------------
    def submit(self, inputs, timeout_ms=None):
        """Enqueue one request; returns its ``ServeFuture``.

        Raises ``ServingOverloadError`` (shed) / ``ServingClosedError``
        synchronously — backpressure is an admission decision, not a
        queued outcome.  A malformed request (per the ``validator``, or
        inputs that cannot become host arrays) is likewise rejected
        here, individually, with a structured ``MXNetError``.
        """
        try:
            inputs = {k: np.asarray(v) for k, v in inputs.items()}
            if self._validator is not None:
                self._validator(inputs)
        except MXNetError:
            self.metrics.incr("invalid_total")
            raise
        except Exception as e:  # noqa: BLE001 — normalized to structured
            self.metrics.incr("invalid_total")
            raise MXNetError(
                f"serving[{self.name}]: invalid request: "
                f"{type(e).__name__}: {e}") from e
        sig = tuple(sorted((k, v.shape, v.dtype.str)
                           for k, v in inputs.items()))
        timeout_ms = (self.default_timeout_ms if timeout_ms is None
                      else float(timeout_ms))
        deadline = (time.perf_counter() + timeout_ms / 1e3
                    if timeout_ms > 0 else None)
        req = _Request(inputs, sig, deadline)
        _failpoint("serving/batcher/submit")
        with self._cond:
            if self._failed:
                self.metrics.incr("rejected_total")
                raise ServingWorkerError(self.name, exhausted=True)
            if self._closed:
                self.metrics.incr("rejected_total")
                raise ServingClosedError(self.name)
            depth = len(self._queue)
            if depth >= self.shed_watermark:
                self.metrics.incr("shed_total")
                raise ServingOverloadError(self.name, depth,
                                           self.shed_watermark)
            self._queue.append(req)
            self.metrics.gauge("queue_depth", len(self._queue))
            self._sweep_inflight_locked()
            self._cond.notify()
        self.metrics.incr("requests_total")
        return req.future

    # -- worker -------------------------------------------------------------
    def _take_batch(self):
        """Block for the first request, then coalesce up to
        ``max_batch_size`` under the ``max_latency_ms`` deadline.
        Returns [] only at shutdown with an empty queue."""
        with self._cond:
            while not self._queue and not self._closed:
                self._cond.wait(0.05)
                # idle tick: an otherwise-quiet batcher still fails
                # expired requests stuck on a wedged sibling worker
                self._sweep_inflight_locked()
            if not self._queue:
                return []
            batch = [self._queue.popleft()]
            # the deadline anchors at the OLDEST member's enqueue: a
            # request never waits for stragglers longer than the policy
            flush_at = batch[0].t_enqueue + self.max_latency_ms / 1e3
            while len(batch) < self.max_batch_size:
                if self._queue:
                    batch.append(self._queue.popleft())
                    continue
                remaining = flush_at - time.perf_counter()
                if remaining <= 0 or self._closed:
                    break
                self._cond.wait(remaining)
            self.metrics.gauge("queue_depth", len(self._queue))
            self._sweep_inflight_locked()
            return batch

    def _sweep_inflight_locked(self):
        """Fail expired-deadline requests held by OTHER (wedged) worker
        threads — called under ``self._cond`` from the live paths, so a
        worker stuck in compile/execute never turns its claimed batch
        into silently-lost requests.  First-write-wins futures make the
        eventual resolution from the stuck thread a no-op."""
        now = time.perf_counter()
        me = threading.get_ident()
        timeouts = 0
        # graftlint: disable=lock-discipline -- callers hold self._cond (the _locked suffix is the contract, as in _take_batch/submit)
        for ident, batch in self._inflight.items():
            if ident == me:
                continue
            for req in batch:
                if req.deadline is not None and now > req.deadline and \
                        not req.future.done():
                    waited = (now - req.t_enqueue) * 1e3
                    timeout = (req.deadline - req.t_enqueue) * 1e3
                    req.future._set_exception(RequestTimeoutError(
                        self.name, waited, timeout))
                    timeouts += 1
        if timeouts:
            self.metrics.incr("timeouts_total", timeouts)

    def _worker_loop(self):
        while True:
            batch = []
            try:
                batch = self._take_batch()
                if not batch:
                    return  # closed and drained
                with self._cond:
                    self._inflight[threading.get_ident()] = batch
                try:
                    with _watchdog.arm(f"serving/{self.name}"):
                        # the chaos hook sits INSIDE the watchdog arm: a
                        # wedge here is exactly a runner stuck in compile
                        # — the watchdog must see (and name) it
                        _failpoint("serving/batcher/worker")
                        self._run_batch(batch)
                finally:
                    with self._cond:
                        self._inflight.pop(threading.get_ident(), None)
            except BaseException as e:  # noqa: BLE001 — worker self-healing
                if not self._survive_crash(batch, e):
                    return

    def _survive_crash(self, batch, exc):
        """A worker thread crashed OUTSIDE the per-cohort error fences
        (runner errors are already fanned out per request by
        ``_run_batch``).  Fail the in-flight batch with a retryable
        typed error, restart in place while the budget lasts; when it
        runs dry, fail everything queued and refuse new submits —
        a dying worker must never become a silent hang."""
        import logging
        log = logging.getLogger("mxnet_tpu.serving")
        err = ServingWorkerError(self.name, cause=exc)
        for req in batch:
            if not req.future.done():
                req.future._set_exception(err)
        if batch:
            self.metrics.incr("errors_total", len(batch))
        with self._cond:
            self._restarts += 1
            restarts = self._restarts
            self.metrics.incr("worker_restarts_total")
            exhausted = restarts > self._restart_budget
            if exhausted:
                self._failed = True
                doomed = list(self._queue)
                self._queue.clear()
                self.metrics.gauge("queue_depth", 0)
                self._cond.notify_all()
        if not exhausted:
            log.warning(
                "serving[%s]: worker died (%s: %s); restarting in place "
                "(%d/%d restarts used)", self.name, type(exc).__name__,
                exc, restarts, self._restart_budget)
            return True
        log.error(
            "serving[%s]: worker restart budget (%d) exhausted — failing "
            "%d queued request(s) and rejecting new submits", self.name,
            self._restart_budget, len(doomed))
        fail = ServingWorkerError(self.name, exhausted=True)
        for req in doomed:
            if not req.future.done():
                req.future._set_exception(fail)
        if doomed:
            self.metrics.incr("errors_total", len(doomed))
        return False

    def _run_batch(self, batch):
        """Execute one taken batch (hang-watchdog armed by the caller:
        a runner wedged in compile/execute for MXNET_WATCHDOG_S seconds
        gets an all-thread stack dump instead of a silent stall)."""
        now = time.perf_counter()
        live = []
        for req in batch:
            if req.deadline is not None and now > req.deadline:
                waited = (now - req.t_enqueue) * 1e3
                timeout = (req.deadline - req.t_enqueue) * 1e3
                req.future._set_exception(RequestTimeoutError(
                    self.name, waited, timeout))
                self.metrics.incr("timeouts_total")
            else:
                live.append(req)
        if not live:
            return
        # cohorts: requests only share a runner call with requests
        # of the SAME input signature, so a mismatched/malformed
        # request fails alone instead of poisoning its neighbours
        cohorts = collections.OrderedDict()
        for req in live:
            cohorts.setdefault(req.sig, []).append(req)
        for cohort in cohorts.values():
            try:
                names = list(cohort[0].inputs)
                feed = {k: np.stack([r.inputs[k] for r in cohort])
                        for k in names}
                outputs = self._runner(feed, len(cohort))
            except Exception as e:  # noqa: BLE001 — fanned out per req
                exc = e if isinstance(e, MXNetError) else MXNetError(
                    f"serving[{self.name}]: batch execution failed: "
                    f"{type(e).__name__}: {e}")
                for req in cohort:
                    req.future._set_exception(exc)
                self.metrics.incr("errors_total", len(cohort))
                continue
            done = time.perf_counter()
            for i, req in enumerate(cohort):
                req.future._set_result([out[i] for out in outputs])
                self.metrics.observe_latency(
                    (done - req.t_enqueue) * 1e3)
            _watchdog.beat(f"serving/{self.name}")
            self.metrics.incr("responses_total", len(cohort))

    # -- lifecycle ----------------------------------------------------------
    def close(self, drain=True, timeout=30.0):
        """Stop intake; drain (default) or fail what is queued; join
        workers.  Idempotent."""
        with self._cond:
            already = self._closed
            self._closed = True
            if not drain:
                while self._queue:
                    req = self._queue.popleft()
                    req.future._set_exception(ServingClosedError(self.name))
                    self.metrics.incr("rejected_total")
                self.metrics.gauge("queue_depth", 0)
            self._cond.notify_all()
        if already:
            return
        for t in self._workers:
            t.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
