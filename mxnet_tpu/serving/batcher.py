"""DynamicBatcher: continuously-batched, deadline-bounded coalescing.

Requests (one sample each, no batch dim) enter a bounded queue; worker
pipelines drain it into micro-batches under the policy

* flush when ``max_batch_size`` requests have coalesced, OR
* flush when ``max_latency_ms`` has elapsed since the oldest queued
  request started waiting (a lone request never waits longer than the
  deadline — the throughput-vs-p99 knob, see docs/serving.md);
* a burst larger than ``max_batch_size`` is split into micro-batches:
  each stage pass takes at most ``max_batch_size`` requests and the
  remainder stays queued for the next pass (or another worker).

Continuous batching (ISSUE 10 tentpole):

* **cohort-aware admission** — a forming micro-batch anchors on the
  OLDEST queued request and admits only requests with the same input
  signature; a mismatched arrival stays queued for the *next*
  micro-batch (a sibling worker dispatches it concurrently) instead of
  being drained into the cohort and serialized behind it.  Arrivals
  with the anchor's signature keep joining the forming batch until it
  is full or the anchor's deadline flushes it — admission never stops
  while a batch forms.
* **stage/dispatch pipeline** — each worker is a thread pair: the
  *stage* thread coalesces micro-batch N+1 and stacks its host arrays
  while the *dispatch* thread still executes micro-batch N (the
  ``io.stage_batch`` double-buffer trick from PR 4, applied to
  serving).  Staged batches hand off through one shared bounded buffer,
  so a wedged dispatch thread never strands work a stage thread
  claimed — any healthy dispatch picks it up.

Robustness contract:

* the queue is bounded — ``submit`` on a queue at the shed watermark
  fails fast with ``ServingOverloadError`` (an ``MXNetError`` carrying
  ``queue_depth``/``watermark``/``batcher`` fields) instead of letting
  latency grow without bound;
* malformed requests fail ALONE: ``submit`` normalizes inputs to host
  arrays, runs the optional ``validator`` (rejecting synchronously with
  a structured error), and workers group requests by input signature
  (names + per-sample shapes + dtypes) so a request that could not
  stack with its neighbours executes in its own cohort instead of
  poisoning the whole micro-batch;
* per-request timeouts: a request whose deadline expires while queued
  is failed with ``RequestTimeoutError`` without wasting a batch slot;
* ``close(drain=True)`` stops intake, lets workers drain everything
  in flight, then joins them; ``drain=False`` fails queued requests
  immediately (structured error, never a hang).
"""
from __future__ import annotations

import collections
import queue
import threading
import time

import numpy as np

from ..base import MXNetError, NonFiniteError
from ..chaos.failpoints import failpoint as _failpoint
from ..telemetry import flight as _flight
from ..telemetry import numerics as _numerics
from ..telemetry import trace as _trace
from ..telemetry import watchdog as _watchdog
from .metrics import ServingMetrics


class ServingOverloadError(MXNetError):
    """Load shed: queue depth reached the watermark (backpressure), or
    the router's SLO admission controller predicted a p99 breach
    (``predicted_p99_ms``/``slo_ms`` are set in that case)."""

    def __init__(self, batcher, queue_depth, watermark,
                 predicted_p99_ms=None, slo_ms=None):
        self.batcher = batcher
        self.queue_depth = queue_depth
        self.watermark = watermark
        self.predicted_p99_ms = predicted_p99_ms
        self.slo_ms = slo_ms
        if predicted_p99_ms is not None:
            msg = (f"serving[{batcher}]: predicted p99 "
                   f"{predicted_p99_ms:.1f}ms exceeds the "
                   f"{slo_ms:.1f}ms SLO at occupancy {queue_depth}; "
                   "request shed — retry with backoff (admission "
                   "control sheds on PREDICTED latency so the p99 of "
                   "admitted requests stays inside the SLO)")
        else:
            msg = (f"serving[{batcher}]: queue depth {queue_depth} >= "
                   f"shed watermark {watermark}; request shed — retry "
                   "with backoff (load-shedding keeps p99 bounded "
                   "instead of queueing unboundedly)")
        super().__init__(msg)


class RequestTimeoutError(MXNetError):
    """The request's deadline expired before (or while) it was served."""

    def __init__(self, batcher, waited_ms, timeout_ms):
        self.batcher = batcher
        self.waited_ms = waited_ms
        self.timeout_ms = timeout_ms
        super().__init__(
            f"serving[{batcher}]: request timed out after "
            f"{waited_ms:.1f}ms (timeout {timeout_ms:.1f}ms)")


class ServingClosedError(MXNetError):
    """Submit after shutdown (or request abandoned by drain=False)."""

    def __init__(self, batcher):
        self.batcher = batcher
        super().__init__(f"serving[{batcher}]: server is shut down")


class ServingWorkerError(MXNetError):
    """A batch worker thread died executing this request's batch.

    ``retryable`` is True: the request itself was well-formed — the
    worker crashed around it (and was restarted, budget permitting), so
    resubmitting is the right client response.  When the restart budget
    is exhausted the batcher fails fast with this error too
    (``exhausted=True``) rather than letting requests queue into a hang.
    """

    retryable = True

    def __init__(self, batcher, cause=None, exhausted=False):
        self.batcher = batcher
        self.cause = cause
        self.exhausted = exhausted
        if exhausted:
            msg = (f"serving[{batcher}]: worker restart budget exhausted "
                   "(MXNET_SERVING_WORKER_RESTARTS); batcher failed fast "
                   "— requests are rejected, never silently queued")
        else:
            msg = (f"serving[{batcher}]: worker thread died executing "
                   f"this batch ({type(cause).__name__}: {cause}); the "
                   "worker was restarted — retry the request")
        super().__init__(msg)


class ServeFuture:
    """Minimal future for one request (threading.Event based).

    Resolution is first-write-wins: a request failed from OUTSIDE its
    worker (the in-flight sweep failing requests stuck on a wedged
    thread) must not be re-resolved when that thread eventually comes
    back and reports its stale outcome.
    """

    __slots__ = ("_event", "_result", "_exc", "_resolve_lock")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._exc = None
        self._resolve_lock = threading.Lock()

    def _set_result(self, value):
        with self._resolve_lock:
            if self._event.is_set():
                return
            self._result = value
            self._event.set()

    def _set_exception(self, exc):
        with self._resolve_lock:
            if self._event.is_set():
                return
            self._exc = exc
            self._event.set()

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise MXNetError(
                f"serving: no response within {timeout}s (request still "
                "queued or executing)")
        with self._resolve_lock:
            exc, value = self._exc, self._result
        if exc is not None:
            raise exc
        return value


class _Request:
    __slots__ = ("inputs", "sig", "future", "t_enqueue", "deadline",
                 "trace")

    def __init__(self, inputs, sig, deadline, trace=None):
        self.inputs = inputs
        self.sig = sig
        self.future = ServeFuture()
        self.t_enqueue = time.perf_counter()
        self.deadline = deadline
        # the end-to-end trace context riding this request (ISSUE 12);
        # the shared NULL_TRACE makes every stage record a no-op when
        # tracing is off, so the pipeline records unconditionally
        self.trace = trace if trace is not None else _trace.NULL_TRACE


class DynamicBatcher:
    """Queue + worker threads draining it through ``runner``.

    ``runner(feed, n_real)`` receives ``{input_name: np.ndarray}`` with
    the requests stacked on a new leading axis (``n_real`` rows, NOT yet
    padded — shape bucketing is the runner's concern, see
    executor_cache) and returns a list of batch-leading output arrays;
    row ``i`` of every output answers request ``i``.
    """

    def __init__(self, runner, max_batch_size=None, max_latency_ms=None,
                 num_workers=None, max_queue_depth=None, shed_watermark=None,
                 default_timeout_ms=None, name="batcher", metrics=None,
                 validator=None):
        from .. import config as _config
        cfg = _config.get
        self.name = name
        self._runner = runner
        # validator(inputs) runs at submit time with the normalized host
        # arrays; raising rejects THAT request synchronously before it
        # can join (and poison) a batch
        self._validator = validator
        self.max_batch_size = int(max_batch_size
                                  if max_batch_size is not None
                                  else cfg("MXNET_SERVING_MAX_BATCH"))
        self.max_latency_ms = float(max_latency_ms
                                    if max_latency_ms is not None
                                    else cfg("MXNET_SERVING_MAX_LATENCY_MS"))
        self.max_queue_depth = int(max_queue_depth
                                   if max_queue_depth is not None
                                   else cfg("MXNET_SERVING_QUEUE_DEPTH"))
        watermark = (shed_watermark if shed_watermark is not None
                     else cfg("MXNET_SERVING_SHED_WATERMARK"))
        # 0 = "at queue capacity"; the watermark may sit below capacity so
        # sheds start before the queue is physically full
        self.shed_watermark = int(watermark) or self.max_queue_depth
        self.default_timeout_ms = float(
            default_timeout_ms if default_timeout_ms is not None
            else cfg("MXNET_SERVING_TIMEOUT_MS"))
        n_workers = int(num_workers if num_workers is not None
                        else cfg("MXNET_SERVING_NUM_WORKERS"))
        if self.max_batch_size <= 0 or n_workers <= 0:
            raise MXNetError("serving: max_batch_size and num_workers "
                             "must be positive")
        self.metrics = metrics or ServingMetrics(name)
        self._queue = collections.deque()
        self._cond = threading.Condition()
        self._closed = False
        # worker self-healing: a crashed worker restarts in place until
        # the budget runs dry, then the batcher fails fast (never hangs)
        self._restart_budget = int(cfg("MXNET_SERVING_WORKER_RESTARTS"))
        self._restarts = 0
        self._failed = False
        # batches claimed but not yet finished — int keys are dispatch
        # thread idents (executing), ("staged", seq) keys are batches
        # coalesced by a stage thread but not yet picked up.  The sweep
        # fails their expired-deadline requests with RequestTimeoutError
        # when the claiming thread is wedged (a wedged worker must never
        # silently hold requests forever)
        self._inflight = {}
        # requests claimed by the stage pipeline (staged or stage-held)
        # but not yet executing: still counted against the shed
        # watermark, so continuous batching does not widen admission
        self._staged_n = 0
        self._staged_seq = 0
        # stage -> dispatch handoff: SHARED bounded buffer (not
        # per-worker slots) so a wedged dispatch thread never strands a
        # staged batch — any healthy dispatch drains it
        self._staged_q = queue.Queue(maxsize=n_workers)
        self.num_workers = n_workers
        self._workers = []
        for i in range(n_workers):
            self._workers.append(threading.Thread(
                target=self._stage_loop, daemon=True,
                name=f"mx-serving-{name}-{i}-stage"))
            self._workers.append(threading.Thread(
                target=self._dispatch_loop, daemon=True,
                name=f"mx-serving-{name}-{i}"))
        for t in self._workers:
            t.start()

    # -- intake -------------------------------------------------------------
    def submit(self, inputs, timeout_ms=None, trace=None):
        """Enqueue one request; returns its ``ServeFuture``.

        Raises ``ServingOverloadError`` (shed) / ``ServingClosedError``
        synchronously — backpressure is an admission decision, not a
        queued outcome.  A malformed request (per the ``validator``, or
        inputs that cannot become host arrays) is likewise rejected
        here, individually, with a structured ``MXNetError``.
        """
        try:
            inputs = {k: np.asarray(v) for k, v in inputs.items()}
            if self._validator is not None:
                self._validator(inputs)
        except MXNetError:
            self.metrics.incr("invalid_total")
            raise
        except Exception as e:  # noqa: BLE001 — normalized to structured
            self.metrics.incr("invalid_total")
            raise MXNetError(
                f"serving[{self.name}]: invalid request: "
                f"{type(e).__name__}: {e}") from e
        sig = tuple(sorted((k, v.shape, v.dtype.str)
                           for k, v in inputs.items()))
        timeout_ms = (self.default_timeout_ms if timeout_ms is None
                      else float(timeout_ms))
        deadline = (time.perf_counter() + timeout_ms / 1e3
                    if timeout_ms > 0 else None)
        req = _Request(inputs, sig, deadline, trace)
        _failpoint("serving/batcher/submit")
        with self._cond:
            if self._failed:
                self.metrics.incr("rejected_total")
                raise ServingWorkerError(self.name, exhausted=True)
            if self._closed:
                self.metrics.incr("rejected_total")
                raise ServingClosedError(self.name)
            # staged-but-not-executing requests still count against the
            # watermark: the pipeline must not quietly deepen admission
            depth = len(self._queue) + self._staged_n
            if depth >= self.shed_watermark:
                self.metrics.incr("shed_total")
                req.trace.event("shed", replica=self.name, depth=depth)
                _flight.record("serving", "shed", severity="warn",
                               batcher=self.name, depth=depth,
                               watermark=self.shed_watermark)
                raise ServingOverloadError(self.name, depth,
                                           self.shed_watermark)
            self._queue.append(req)
            self.metrics.gauge("queue_depth",
                               len(self._queue) + self._staged_n)
            self._sweep_inflight_locked()
            self._cond.notify()
        self.metrics.incr("requests_total")
        return req.future

    # -- stage (coalesce + stack) -------------------------------------------
    def _take_batch(self):
        """Block for the oldest request, then coalesce a same-signature
        cohort up to ``max_batch_size`` under the ``max_latency_ms``
        deadline (anchored at the OLDEST member's enqueue: a request
        never waits for stragglers longer than the policy).

        Continuous admission: requests that arrive while the batch forms
        JOIN it when they carry the anchor's signature; a mismatched
        arrival stays queued for the next micro-batch — a sibling worker
        dispatches it concurrently instead of it riding (and being
        serialized behind) this cohort.  Returns ``(token, batch)`` with
        the batch claimed as staged, or ``(None, [])`` at shutdown /
        fail-fast with nothing left to take."""
        with self._cond:
            while not self._queue and not self._closed and not self._failed:
                self._cond.wait(0.05)
                # idle tick: an otherwise-quiet batcher still fails
                # expired requests stuck on a wedged sibling worker
                self._sweep_inflight_locked()
            if self._failed or not self._queue:
                return None, []
            batch = [self._queue.popleft()]
            sig = batch[0].sig
            flush_at = batch[0].t_enqueue + self.max_latency_ms / 1e3
            while len(batch) < self.max_batch_size:
                if self._take_matching_locked(batch, sig):
                    continue
                remaining = flush_at - time.perf_counter()
                if remaining <= 0 or self._closed or self._failed:
                    break
                self._cond.wait(remaining)
            token = self._claim_staged_locked(batch)
            self.metrics.gauge("queue_depth",
                               len(self._queue) + self._staged_n)
            self._sweep_inflight_locked()
            return token, batch

    def _take_matching_locked(self, batch, sig):
        """Move the oldest queued request with ``sig`` into ``batch``;
        False when none is queued.  Mismatched requests keep their queue
        position (and their own deadline anchor) for the next pass."""
        # graftlint: disable=lock-discipline -- callers hold self._cond (the _locked suffix is the contract, as in _sweep_inflight_locked)
        for idx, req in enumerate(self._queue):
            if req.sig == sig:
                # graftlint: disable=lock-discipline -- callers hold self._cond (the _locked suffix is the contract)
                del self._queue[idx]
                batch.append(req)
                return True
        return False

    def _claim_staged_locked(self, batch):
        """Register a freshly-coalesced batch as staged: it has left the
        queue but not yet reached a dispatch thread, so it must stay
        visible to both the shed watermark and the in-flight sweep."""
        # graftlint: disable=lock-discipline -- callers hold self._cond (the _locked suffix is the contract, as in _sweep_inflight_locked)
        self._staged_seq += 1
        token = ("staged", self._staged_seq)
        # graftlint: disable=lock-discipline -- callers hold self._cond (the _locked suffix is the contract)
        self._inflight[token] = batch
        # graftlint: disable=lock-discipline -- callers hold self._cond (the _locked suffix is the contract)
        self._staged_n += len(batch)
        return token

    def _unclaim_staged(self, token, batch):
        with self._cond:
            if self._inflight.pop(token, None) is not None:
                self._staged_n -= len(batch)

    def _sweep_inflight_locked(self):
        """Fail expired-deadline requests held by OTHER (wedged) worker
        threads — called under ``self._cond`` from the live paths, so a
        worker stuck in compile/execute never turns its claimed batch
        into silently-lost requests.  First-write-wins futures make the
        eventual resolution from the stuck thread a no-op."""
        now = time.perf_counter()
        me = threading.get_ident()
        timeouts = 0
        # graftlint: disable=lock-discipline -- callers hold self._cond (the _locked suffix is the contract, as in _take_batch/submit)
        for ident, batch in self._inflight.items():
            if ident == me:
                continue
            for req in batch:
                if req.deadline is not None and now > req.deadline and \
                        not req.future.done():
                    waited = (now - req.t_enqueue) * 1e3
                    timeout = (req.deadline - req.t_enqueue) * 1e3
                    req.future._set_exception(RequestTimeoutError(
                        self.name, waited, timeout))
                    req.trace.event("timeout_swept", replica=self.name,
                                    waited_ms=round(waited, 3))
                    req.trace.finish(status="timeout")
                    timeouts += 1
        if timeouts:
            self.metrics.incr("timeouts_total", timeouts)
            _flight.record("serving", "wedged_sweep", severity="warn",
                           batcher=self.name, timeouts=timeouts)

    def _stage_feed(self, batch):
        """Stack one same-signature cohort into the runner feed — the
        host-side work the pipeline overlaps with the dispatch thread's
        in-flight runner call."""
        names = list(batch[0].inputs)
        return {k: np.stack([r.inputs[k] for r in batch]) for k in names}

    def _stage_loop(self):
        """Coalesce + stack micro-batch N+1 while a dispatch thread
        executes micro-batch N; hand off through the shared staged
        buffer.  Exits by enqueueing one shutdown sentinel (None) so
        exactly one dispatch thread retires with it."""
        while True:
            batch = []
            try:
                token, batch = self._take_batch()
                if not batch:
                    self._put_staged(None)
                    return  # closed and drained (or failed fast)
                # trace: the queue wait ends the moment this stage
                # thread claimed the cohort (recorded by the claimer —
                # the waiting thread could not have closed the span)
                t_claim = time.perf_counter()
                for req in batch:
                    req.trace.add_stage("queue_wait", req.t_enqueue,
                                        t_claim)
                try:
                    feed = self._stage_feed(batch)
                except Exception as e:  # noqa: BLE001 — fails this batch alone
                    self._unclaim_staged(token, batch)
                    exc = MXNetError(
                        f"serving[{self.name}]: batch staging failed: "
                        f"{type(e).__name__}: {e}")
                    for req in batch:
                        if not req.future.done():
                            req.future._set_exception(exc)
                    self.metrics.incr("errors_total", len(batch))
                    continue
                t_staged = time.perf_counter()
                for req in batch:
                    req.trace.add_stage("stage", t_claim, t_staged)
                if not self._put_staged((token, batch, feed, t_staged)):
                    # batcher failed fast while we held a staged batch
                    self._unclaim_staged(token, batch)
                    err = ServingWorkerError(self.name, exhausted=True)
                    for req in batch:
                        if not req.future.done():
                            req.future._set_exception(err)
                    self.metrics.incr("errors_total", len(batch))
            except BaseException as e:  # noqa: BLE001 — worker self-healing
                if not self._survive_crash(batch, e):
                    return

    def _put_staged(self, item):
        """Bounded put into the staged buffer; gives up (False) only
        when the batcher has failed fast — never blocks forever behind
        dead dispatch threads."""
        while True:
            # graftlint: disable=lock-discipline -- _failed is a monotonic False->True latch; a stale read here only delays the fail-fast exit by one 50ms tick
            if self._failed:
                return False
            try:
                self._staged_q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue

    def _get_staged(self):
        while True:
            try:
                return self._staged_q.get(timeout=0.1)
            except queue.Empty:
                # graftlint: disable=lock-discipline -- _failed is a monotonic False->True latch; a stale read here only delays the fail-fast exit by one 100ms tick
                if self._failed:
                    return None

    def _dispatch_loop(self):
        while True:
            batch = []
            try:
                item = self._get_staged()
                if item is None:
                    return  # stage sentinel (drained) or failed fast
                token, batch, feed, t_staged = item
                with self._cond:
                    # claim moves staged -> executing atomically: the
                    # batch stays sweepable throughout
                    self._inflight[threading.get_ident()] = batch
                    if self._inflight.pop(token, None) is not None:
                        self._staged_n -= len(batch)
                t_picked = time.perf_counter()
                for req in batch:
                    req.trace.add_stage("staged_wait", t_staged, t_picked)
                try:
                    with _watchdog.arm(f"serving/{self.name}"):
                        # the chaos hook sits INSIDE the watchdog arm: a
                        # wedge here is exactly a runner stuck in compile
                        # — the watchdog must see (and name) it
                        _failpoint("serving/batcher/worker")
                        self._run_batch(batch, feed)
                finally:
                    with self._cond:
                        self._inflight.pop(threading.get_ident(), None)
            except BaseException as e:  # noqa: BLE001 — worker self-healing
                if not self._survive_crash(batch, e):
                    return

    def _survive_crash(self, batch, exc):
        """A worker thread crashed OUTSIDE the per-cohort error fences
        (runner errors are already fanned out per request by
        ``_run_batch``).  Fail the in-flight batch with a retryable
        typed error, restart in place while the budget lasts; when it
        runs dry, fail everything queued and refuse new submits —
        a dying worker must never become a silent hang."""
        import logging
        log = logging.getLogger("mxnet_tpu.serving")
        err = ServingWorkerError(self.name, cause=exc)
        for req in batch:
            if not req.future.done():
                req.future._set_exception(err)
        if batch:
            self.metrics.incr("errors_total", len(batch))
        with self._cond:
            self._restarts += 1
            restarts = self._restarts
            self.metrics.incr("worker_restarts_total")
            exhausted = restarts > self._restart_budget
            if exhausted:
                self._failed = True
                doomed = list(self._queue)
                self._queue.clear()
                self.metrics.gauge("queue_depth", 0)
                self._cond.notify_all()
        if not exhausted:
            log.warning(
                "serving[%s]: worker died (%s: %s); restarting in place "
                "(%d/%d restarts used)", self.name, type(exc).__name__,
                exc, restarts, self._restart_budget)
            _flight.record("serving", "worker_restart", severity="warn",
                           batcher=self.name, cause=type(exc).__name__,
                           restarts=restarts,
                           budget=self._restart_budget)
            return True
        _flight.record("serving", "worker_fail_fast", severity="error",
                       batcher=self.name, cause=type(exc).__name__,
                       restarts=restarts, doomed=len(doomed))
        log.error(
            "serving[%s]: worker restart budget (%d) exhausted — failing "
            "%d queued request(s) and rejecting new submits", self.name,
            self._restart_budget, len(doomed))
        fail = ServingWorkerError(self.name, exhausted=True)
        # staged batches would otherwise sit unexecuted behind dead
        # dispatch threads: drain the handoff buffer and fail them too
        doomed += self._drain_staged()
        for req in doomed:
            if not req.future.done():
                req.future._set_exception(fail)
        if doomed:
            self.metrics.incr("errors_total", len(doomed))
        return False

    def _drain_staged(self):
        """Empty the stage->dispatch buffer (fail-fast path); returns
        the requests of every staged batch it removed."""
        out = []
        while True:
            try:
                item = self._staged_q.get_nowait()
            except queue.Empty:
                return out
            if item is None:
                continue
            token, batch, _feed, _t = item
            self._unclaim_staged(token, batch)
            out.extend(batch)

    def _run_batch(self, batch, feed):
        """Execute one staged same-signature cohort (hang-watchdog armed
        by the caller: a runner wedged in compile/execute for
        MXNET_WATCHDOG_S seconds gets an all-thread stack dump instead
        of a silent stall).  ``feed`` was stacked by the stage thread;
        it is re-stacked here only when a member expired (or was swept)
        between staging and dispatch, so a dead request never occupies a
        batch row."""
        now = time.perf_counter()
        live, dropped = [], False
        for req in batch:
            if req.future.done():
                # already resolved from outside (in-flight sweep on a
                # wedged thread, fail-fast) — must not be re-counted
                dropped = True
            elif req.deadline is not None and now > req.deadline:
                waited = (now - req.t_enqueue) * 1e3
                timeout = (req.deadline - req.t_enqueue) * 1e3
                req.future._set_exception(RequestTimeoutError(
                    self.name, waited, timeout))
                req.trace.event("timeout", replica=self.name,
                                waited_ms=round(waited, 3))
                req.trace.finish(status="timeout")
                self.metrics.incr("timeouts_total")
                dropped = True
            else:
                live.append(req)
        if not live:
            return
        try:
            if dropped:
                feed = self._stage_feed(live)
            t_run = time.perf_counter()
            outputs = self._runner(feed, len(live))
        except Exception as e:  # noqa: BLE001 — fanned out per req
            exc = e if isinstance(e, MXNetError) else MXNetError(
                f"serving[{self.name}]: batch execution failed: "
                f"{type(e).__name__}: {e}")
            for req in live:
                req.future._set_exception(exc)
                req.trace.event("error", error=type(e).__name__)
                req.trace.finish(status="error")
            self.metrics.incr("errors_total", len(live))
            return
        done = time.perf_counter()
        # output-health guard (ISSUE 14): rows whose float outputs carry
        # NaN/Inf fail typed and are never served; healthy cohort
        # members still resolve — one vectorized isfinite pass per float
        # output, an empty tuple when MXNET_NUMERICS_SERVING=0
        bad_rows = _numerics.guard_rows(outputs, len(live))
        if bad_rows:
            _numerics.record_serving_nonfinite(self.name, len(bad_rows))
            self.metrics.incr("nonfinite_total", len(bad_rows))
        for i, req in enumerate(live):
            if i in bad_rows:
                req.future._set_exception(NonFiniteError(
                    where=f"serving[{self.name}] output",
                    stat="nonfinite_output", value=True,
                    detail="the model produced non-finite values for "
                           "this request; it was not served"))
                req.trace.event("nonfinite_output", replica=self.name)
                req.trace.finish(status="nonfinite")
                continue
            req.future._set_result([out[i] for out in outputs])
            if req.trace is not _trace.NULL_TRACE:
                # resolve ends at THIS request's future resolution;
                # the whole cohort shares one dispatch interval
                req.trace.add_stage("dispatch", t_run, done)
                req.trace.add_stage("resolve", done, time.perf_counter())
                req.trace.finish()
            self.metrics.observe_latency((done - req.t_enqueue) * 1e3)
        _watchdog.beat(f"serving/{self.name}")
        if len(live) > len(bad_rows):
            self.metrics.incr("responses_total",
                              len(live) - len(bad_rows))

    # -- load introspection (the router's routing signal) --------------------
    def occupancy(self):
        """Requests this batcher owns right now: queued + staged +
        executing.  The ReplicaPool routes on this (occupancy x the
        pool's drain-time EWMA = predicted wait behind this replica)."""
        with self._cond:
            n = len(self._queue) + self._staged_n
            # graftlint: disable=lock-discipline -- self._cond is held (same contract as the other _locked readers)
            for key, batch in self._inflight.items():
                if isinstance(key, int):  # claimed by a dispatch thread
                    n += len(batch)
            return n

    @property
    def failed(self):
        """True once the worker restart budget is exhausted — the
        batcher rejects all traffic and a router must route around it."""
        # graftlint: disable=lock-discipline -- monotonic False->True latch; lock-free read keeps the router's per-submit health probe off this batcher's hot lock
        return self._failed

    # -- lifecycle ----------------------------------------------------------
    def close(self, drain=True, timeout=30.0):
        """Stop intake; drain (default) or fail what is queued; join
        workers.  Idempotent.  Staged and executing batches always run
        to completion on drain — a closing replica never drops a request
        it admitted."""
        with self._cond:
            already = self._closed
            self._closed = True
            if not drain:
                while self._queue:
                    req = self._queue.popleft()
                    req.future._set_exception(ServingClosedError(self.name))
                    self.metrics.incr("rejected_total")
                self.metrics.gauge("queue_depth", self._staged_n)
            self._cond.notify_all()
        if already:
            return
        for t in self._workers:
            t.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class CohortQueue:
    """The batcher's anchor/join admission, extracted as a reusable
    cohort former (ISSUE 16): anchor on the OLDEST pending item, claim
    every currently-queued item with the anchor's signature (bounded by
    ``max_cohort``), and leave mismatched items in place — they keep
    their queue position (and become the next anchor) instead of being
    serialized behind a cohort they cannot join.

    The generation engine's prefill queue is the first client: pending
    sessions coalesce into same-prompt-bucket prefill cohorts between
    decode ticks, exactly the way ``_take_batch`` forms same-signature
    micro-batches — but decoupled from the batcher's deadline/shed
    policy, because generation admission control lives in the KV slot
    pool instead of a queue watermark."""

    def __init__(self, sig_fn, max_cohort):
        self._sig_fn = sig_fn
        self.max_cohort = max(1, int(max_cohort))
        self._items = collections.deque()
        self._cond = threading.Condition()

    def put(self, item):
        with self._cond:
            self._items.append(item)
            self._cond.notify_all()

    def __len__(self):
        with self._cond:
            return len(self._items)

    def take(self, timeout=None):
        """Claim one cohort: block up to ``timeout`` for an anchor
        (``timeout=0`` polls), then join every queued same-signature
        item.  Returns a possibly-empty list."""
        with self._cond:
            if not self._items and timeout:
                self._cond.wait(timeout)
            if not self._items:
                return []
            cohort = [self._items.popleft()]
            sig = self._sig_fn(cohort[0])
            idx = 0
            while len(cohort) < self.max_cohort and idx < len(self._items):
                if self._sig_fn(self._items[idx]) == sig:
                    # graftlint: disable=lock-discipline -- self._cond is held for the whole scan
                    item = self._items[idx]
                    del self._items[idx]
                    cohort.append(item)
                else:
                    idx += 1
            return cohort

    def drain(self):
        """Remove and return everything queued (crash/close fan-out)."""
        with self._cond:
            items = list(self._items)
            self._items.clear()
            return items
