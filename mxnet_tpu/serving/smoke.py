"""Serving smoke: concurrent burst + autoscaling hot-swap under load.

CI entry point (``python -m mxnet_tpu.serving.smoke``), two phases:

1. **burst contract** — spin up a ModelServer (2-replica pools) on the
   virtual 8-device CPU mesh, fire 64 concurrent requests through a
   deliberately small queue so SOME of them shed, and assert the
   robustness contract: every request is either answered with a
   numerically correct output or fails fast with a structured
   MXNetError — nothing hangs, nothing crashes the server.
2. **autoscaling hot-swap** (ISSUE 10) — ``ModelRepository.watch`` a
   checkpoint directory while sustained client load runs against the
   replica pool; commit a new step mid-traffic and assert the swap is
   invisible: ZERO dropped non-shed requests, the new version serves,
   and ZERO executor-cache misses after the flip (the warm hooks
   compiled the new version's full bucket ladder BEFORE the pointer
   moved — composing ISSUE 7's warm-before-flip with the pool).
3. **output-health guard** (ISSUE 14) — a model producing NaN logits
   fails those requests with typed ``NonFiniteError`` (never served),
   bumps ``mxnet_numerics_serving_nonfinite_total``, and the pool's
   survivors keep answering healthy requests.
4. **generation hot reload** (ISSUE 16) — ``server.load_generator`` a
   tiny LM, AOT-warm the decode step + prefill ladder, stream N
   concurrent sessions (more than the slot pool holds, so some shed
   typed), hot-reload a new model version MID-STREAM, and assert:
   zero non-shed drops, ZERO decode-step compiles after the flip
   returns (warm-before-flip), and the KV slot pool + resource-ledger
   page accounting back at exactly zero afterwards.

Prints one JSON summary line; exit code 0 iff all contracts held.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
# hermetic compile-cache namespace: the smoke's warm/flip accounting
# must not depend on what earlier local runs persisted
os.environ.setdefault("MXNET_COMPILE_CACHE_DIR",
                      tempfile.mkdtemp(prefix="mx-serve-smoke-cache-"))

N_CLIENTS = 64
IN_DIM = 16


def output_health_guard():
    """Phase 3: non-finite logits fail typed, never serve, pool
    survives.  Returns (summary dict, failure list)."""
    import mxnet_tpu as mx
    from mxnet_tpu import serving
    from mxnet_tpu.base import NonFiniteError
    from mxnet_tpu.telemetry import numerics

    failures = []
    # log(x): positive inputs are healthy, negative inputs produce NaN
    sym = mx.sym.log(mx.sym.Variable("data"))
    server = serving.ModelServer(max_batch_size=4, max_latency_ms=2.0,
                                 num_replicas=2, name="nf-smoke")
    server.load("m", symbol=sym, params={})
    nf0 = numerics.summary()  # noqa: F841 — arm check only
    healthy = server.predict("m", {"data": np.ones(IN_DIM, np.float32)})
    if not np.allclose(np.asarray(healthy[0]), 0.0):
        failures.append("guard smoke: healthy request served wrong")
    typed = 0
    try:
        server.predict("m", {"data": -np.ones(IN_DIM, np.float32)})
        failures.append("guard smoke: NaN output was SERVED")
    except NonFiniteError:
        typed = 1
    except Exception as e:  # noqa: BLE001 — wrong error type = failure
        failures.append(f"guard smoke: wrong error type "
                        f"{type(e).__name__}: {e}")
    # survivors keep serving after the guard fired
    try:
        again = server.predict(
            "m", {"data": 2 * np.ones(IN_DIM, np.float32)})
        if not np.allclose(np.asarray(again[0]), np.log(2.0)):
            failures.append("guard smoke: post-guard answer wrong")
    except Exception as e:  # noqa: BLE001 — survivors must serve
        failures.append(f"guard smoke: pool stopped serving after the "
                        f"guard fired: {type(e).__name__}: {e}")
    counter = 0
    from mxnet_tpu.telemetry import REGISTRY
    fam = REGISTRY.get("mxnet_numerics_serving_nonfinite_total")
    if fam is not None:
        counter = sum(s[2] for s in fam._samples())
    if counter < 1:
        failures.append("guard smoke: serving_nonfinite counter did "
                        "not bump")
    server.shutdown()
    return {"typed_failures": typed,
            "serving_nonfinite_total": counter}, failures


def autoscaling_hot_swap():
    """Phase 2: ModelRepository.watch hot-swaps a committed step under
    sustained replica-pool load — zero dropped non-shed requests, zero
    post-flip cold compiles.  Returns (summary dict, failure list)."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, serving
    from mxnet_tpu.checkpoint import CheckpointManager
    from mxnet_tpu.serving import (RequestTimeoutError, ServingClosedError,
                                   ServingOverloadError)

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(24, activation="relu"), gluon.nn.Dense(4))
    net.initialize()
    net(mx.nd.zeros((1, IN_DIM)))
    if not getattr(net, "_cached_graph", None):
        net._build_sym_graph()
    sym = net._cached_graph[1]
    params = {f"arg:{k}": p._reduce()
              for k, p in net.collect_params().items()}
    x = np.random.RandomState(1).randn(IN_DIM).astype(np.float32)

    ckdir = tempfile.mkdtemp(prefix="mx-serve-smoke-ck-")
    failures = []
    served = [0]
    sheds = [0]
    stop = threading.Event()
    server = serving.ModelServer(max_batch_size=8, max_latency_ms=3.0,
                                 max_queue_depth=64, num_replicas=2,
                                 name="smoke-swap")
    repo = server.repository
    with CheckpointManager(ckdir, keep_last=0) as mgr:
        mgr.save(1, arrays=params, symbol=sym, block=True)
        assert repo.poll_checkpoint("swapm", ckdir) == 1

        def client():
            while not stop.is_set():
                try:
                    server.predict("swapm", {"data": x}, wait_s=30.0)
                    served[0] += 1
                except (ServingOverloadError, RequestTimeoutError,
                        ServingClosedError):
                    sheds[0] += 1
                except Exception as e:  # noqa: BLE001 — contract violation
                    failures.append(f"{type(e).__name__}: {e}")
                    return

        clients = [threading.Thread(target=client) for _ in range(4)]
        for t in clients:
            t.start()
        try:
            time.sleep(0.5)   # v1 traffic feeds the shape census
            repo.watch("swapm", ckdir, interval=0.05)
            mgr.save(2, arrays=params, symbol=sym, block=True)
            deadline = time.time() + 30
            while repo.latest_version("swapm") != 2:
                if time.time() > deadline:
                    failures.append("watcher never flipped to step 2")
                    break
                time.sleep(0.02)
            # the flip is live: warmup compiled the v2 ladder pre-flip,
            # so continued load must be a pure executor-cache hit
            misses_at_flip = server._cache.stats()["misses"]
            served_at_flip = served[0]
            time.sleep(0.5)
        finally:
            repo.unwatch("swapm")
            stop.set()
            for t in clients:
                t.join(timeout=30)
        post_flip_misses = (server._cache.stats()["misses"]
                            - misses_at_flip)
        served_post_flip = served[0] - served_at_flip
        server.shutdown()
    if post_flip_misses:
        failures.append(
            f"{post_flip_misses} executor-cache miss(es) AFTER the "
            "version flip — a request paid a cold compile")
    if served_post_flip <= 0:
        failures.append("no traffic completed after the hot swap")
    if served[0] <= 0:
        failures.append("no traffic completed at all during the swap")
    summary = {
        "served": served[0], "shed": sheds[0],
        "served_post_flip": served_post_flip,
        "post_flip_misses": post_flip_misses,
        "final_version": repo.latest_version("swapm"),
        "pool": server.stats()["pools"].get("swapm"),
    }
    return summary, failures


def generation_hot_reload():
    """Phase 4: stateful generation sessions across a mid-stream hot
    reload — zero non-shed drops, zero post-flip decode compiles, KV
    ledger provably zero after.  Returns (summary dict, failure list)."""
    from mxnet_tpu import serving
    from mxnet_tpu.serving import (RequestTimeoutError, ServingClosedError,
                                   ServingOverloadError)
    from mxnet_tpu.serving.generation import tiny_lm
    from mxnet_tpu.telemetry.resources import LEDGER

    failures = []
    server = serving.ModelServer(num_replicas=1, name="gen-smoke")
    server.load_generator("lm", tiny_lm(vocab=32, d_model=8, max_len=128,
                                        seed=5),
                          warm=True, slots=8, page_tokens=16,
                          kv_budget_mb=8, prefix_cache_entries=8,
                          max_len=128)
    eng = server.generator("lm")
    rng = np.random.RandomState(0)
    shared = rng.randint(1, 31, size=24).astype(np.int32)  # prefix-reuse head
    completed = [0]
    shed = [0]
    stop = threading.Event()

    def client(i):
        r = np.random.RandomState(100 + i)
        sheds_in_a_row = 0
        while not stop.is_set():
            tail = r.randint(1, 31, size=r.randint(2, 8)).astype(np.int32)
            prompt = np.concatenate([shared, tail]) if i % 2 else tail
            try:
                toks = server.generate("lm", prompt, timeout=30.0,
                                       max_new_tokens=8)
                if len(toks) != 8:
                    failures.append(f"gen client {i}: {len(toks)} tokens")
                completed[0] += 1
                sheds_in_a_row = 0
            except (ServingOverloadError, RequestTimeoutError,
                    ServingClosedError):
                shed[0] += 1   # typed admission shed: the contract allows it
                sheds_in_a_row += 1
                if sheds_in_a_row > 400:   # persistently full: give up
                    return
                time.sleep(0.005 * 2 ** min(sheds_in_a_row, 4)
                           * (1.0 + 0.25 * r.rand()))
            except Exception as e:  # noqa: BLE001 — contract violation
                failures.append(f"gen client {i}: {type(e).__name__}: {e}")
                return

    clients = [threading.Thread(target=client, args=(i,)) for i in range(12)]
    for t in clients:
        t.start()
    try:
        time.sleep(0.6)   # v1 streams
        flip_version = server.load_generator(
            "lm", tiny_lm(vocab=32, d_model=8, max_len=128, seed=6))
        compiles_at_flip = eng.stats()["decode_compiles"]
        time.sleep(0.6)   # v2 streams, in-flight v1 sessions finish on it
    finally:
        stop.set()
        for t in clients:
            t.join(timeout=30)
    post_flip_compiles = eng.stats()["decode_compiles"] - compiles_at_flip
    stats = eng.stats()
    server.shutdown()
    if post_flip_compiles:
        failures.append(f"{post_flip_compiles} decode-step compile(s) "
                        "AFTER the generation hot reload — a session "
                        "paid a cold compile mid-stream")
    if completed[0] <= 0:
        failures.append("no generation session completed at all")
    if stats["version"] != flip_version:
        failures.append(f"engine never flipped to v{flip_version}")
    kv = stats["kv"]
    ledger_kv = LEDGER.snapshot()["owners"].get(
        f"generation/{eng.name}", {}).get("kv_pages", 0)
    if kv["slots_in_use"] or kv["kv_bytes"] or ledger_kv:
        failures.append(f"generation leaked KV state after shutdown: "
                        f"{kv['slots_in_use']} slots, {kv['kv_bytes']} "
                        f"bytes, ledger={ledger_kv} pages")
    return {"completed": completed[0], "shed": shed[0],
            "flipped_to": stats["version"],
            "post_flip_decode_compiles": post_flip_compiles,
            "max_active": stats["max_active"],
            "prefix_cache": stats["prefix_cache"],
            "kv": kv}, failures


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu import serving
    from mxnet_tpu.serving import (RequestTimeoutError, ServingClosedError,
                                   ServingOverloadError)

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(4))
    net.initialize()
    xs = np.random.RandomState(0).randn(N_CLIENTS, IN_DIM).astype(np.float32)
    ref = net(mx.nd.array(xs)).asnumpy()

    server = serving.ModelServer(max_batch_size=8, max_latency_ms=4.0,
                                 max_queue_depth=16, num_replicas=2,
                                 name="smoke")
    server.load("mlp", block=net)
    # prime the hot bucket so concurrent clients race a warm server, not
    # one giant first-call XLA compile
    server.predict("mlp", {"data": xs[0]})

    results = [None] * N_CLIENTS  # ("ok", out) | ("shed", e) | ("bad", why)
    barrier = threading.Barrier(N_CLIENTS)

    def client(i):
        barrier.wait(timeout=60)  # a stuck sibling breaks the barrier typed
        try:
            out = server.predict("mlp", {"data": xs[i]}, wait_s=60.0)
            results[i] = ("ok", out[0])
        except (ServingOverloadError, RequestTimeoutError,
                ServingClosedError) as e:
            # the ONLY acceptable failures under the contract: a
            # structured shed/timeout/shutdown.  Any other MXNetError —
            # notably ServeFuture.result's no-response timeout, i.e. a
            # wedged server — is a contract violation, not a shed.
            results[i] = ("shed", e)
        except Exception as e:  # noqa: BLE001 — contract violation
            results[i] = ("bad", f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(N_CLIENTS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)

    ok = shed = 0
    failures = []
    for i, r in enumerate(results):
        if r is None:
            failures.append(f"client {i}: hung (no result)")
        elif r[0] == "ok":
            if not np.allclose(r[1], ref[i], atol=1e-5):
                failures.append(f"client {i}: wrong answer")
            else:
                ok += 1
        elif r[0] == "shed":
            shed += 1
        else:
            failures.append(f"client {i}: unstructured failure: {r[1]}")

    server.shutdown()
    snap = server.stats()
    if ok == 0:
        failures.append("no request was answered at all")

    # phase 2: autoscaling hot-swap under sustained load
    try:
        swap_summary, swap_failures = autoscaling_hot_swap()
    except Exception as e:  # noqa: BLE001 — smoke must report, not crash
        swap_summary = {"error": f"{type(e).__name__}: {e}"}
        swap_failures = [f"autoscaling phase crashed: "
                         f"{type(e).__name__}: {e}"]
    failures += swap_failures

    # phase 3: output-health guard (numerics observatory, ISSUE 14)
    try:
        guard_summary, guard_failures = output_health_guard()
    except Exception as e:  # noqa: BLE001 — smoke must report, not crash
        guard_summary = {"error": f"{type(e).__name__}: {e}"}
        guard_failures = [f"output-health phase crashed: "
                          f"{type(e).__name__}: {e}"]
    failures += guard_failures

    # phase 4: stateful generation across a mid-stream hot reload
    try:
        gen_summary, gen_failures = generation_hot_reload()
    except Exception as e:  # noqa: BLE001 — smoke must report, not crash
        gen_summary = {"error": f"{type(e).__name__}: {e}"}
        gen_failures = [f"generation phase crashed: "
                        f"{type(e).__name__}: {e}"]
    failures += gen_failures

    summary = {
        "smoke": "serving", "clients": N_CLIENTS, "answered": ok,
        "shed": shed, "failures": failures,
        "output_health": guard_summary,
        "throughput_rps": snap.get("throughput_rps"),
        "p99_ms": snap.get("latency_ms", {}).get("p99"),
        "batch_occupancy": snap.get("batch_occupancy"),
        "executor_cache": snap.get("executor_cache"),
        "pools": snap.get("pools"),
        "autoscaling": swap_summary,
        "generation": gen_summary,
    }
    print(json.dumps(summary), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
