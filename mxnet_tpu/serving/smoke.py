"""Serving smoke: 64 concurrent clients against a live ModelServer.

CI entry point (``python -m mxnet_tpu.serving.smoke``): spin up a
ModelServer on the virtual 8-device CPU mesh, fire 64 concurrent
requests through a deliberately small queue so SOME of them shed, and
assert the robustness contract: every request is either answered with a
numerically correct output or fails fast with a structured MXNetError —
nothing hangs, nothing crashes the server.  Prints one JSON summary
line; exit code 0 iff the contract held.
"""
from __future__ import annotations

import json
import os
import sys
import threading

import numpy as np

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

N_CLIENTS = 64
IN_DIM = 16


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu import serving
    from mxnet_tpu.serving import (RequestTimeoutError, ServingClosedError,
                                   ServingOverloadError)

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(4))
    net.initialize()
    xs = np.random.RandomState(0).randn(N_CLIENTS, IN_DIM).astype(np.float32)
    ref = net(mx.nd.array(xs)).asnumpy()

    server = serving.ModelServer(max_batch_size=8, max_latency_ms=4.0,
                                 max_queue_depth=16, name="smoke")
    server.load("mlp", block=net)
    # prime the hot bucket so concurrent clients race a warm server, not
    # one giant first-call XLA compile
    server.predict("mlp", {"data": xs[0]})

    results = [None] * N_CLIENTS  # ("ok", out) | ("shed", e) | ("bad", why)
    barrier = threading.Barrier(N_CLIENTS)

    def client(i):
        barrier.wait()
        try:
            out = server.predict("mlp", {"data": xs[i]}, wait_s=60.0)
            results[i] = ("ok", out[0])
        except (ServingOverloadError, RequestTimeoutError,
                ServingClosedError) as e:
            # the ONLY acceptable failures under the contract: a
            # structured shed/timeout/shutdown.  Any other MXNetError —
            # notably ServeFuture.result's no-response timeout, i.e. a
            # wedged server — is a contract violation, not a shed.
            results[i] = ("shed", e)
        except Exception as e:  # noqa: BLE001 — contract violation
            results[i] = ("bad", f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(N_CLIENTS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)

    ok = shed = 0
    failures = []
    for i, r in enumerate(results):
        if r is None:
            failures.append(f"client {i}: hung (no result)")
        elif r[0] == "ok":
            if not np.allclose(r[1], ref[i], atol=1e-5):
                failures.append(f"client {i}: wrong answer")
            else:
                ok += 1
        elif r[0] == "shed":
            shed += 1
        else:
            failures.append(f"client {i}: unstructured failure: {r[1]}")

    server.shutdown()
    snap = server.stats()
    if ok == 0:
        failures.append("no request was answered at all")
    summary = {
        "smoke": "serving", "clients": N_CLIENTS, "answered": ok,
        "shed": shed, "failures": failures,
        "throughput_rps": snap.get("throughput_rps"),
        "p99_ms": snap.get("latency_ms", {}).get("p99"),
        "batch_occupancy": snap.get("batch_occupancy"),
        "executor_cache": snap.get("executor_cache"),
    }
    print(json.dumps(summary), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
