"""Stateful autoregressive generation: sessions, prefill/decode
cohorts, one fixed-shape jit decode step per micro-batch (ISSUE 16).

The serving plane so far is stateless one-shot inference; generation is
the workload that stresses continuous batching hardest, because every
session carries device state (its KV cache) across THOUSANDS of
micro-batches.  The design follows the compile-once/stream-many
argument the whole repo is built on (PAPERS.md: PyGraph's
capture-once/replay-many, µ-cuDNN's closed shape families):

* **one decode program, total**: the decode step is a single jitted
  function over the whole ``[slots, max_len]`` KV arena —
  ``(params, arena, tokens[S], pos[S]) -> (logits[S, V], arena')`` —
  whose shapes never depend on how many sessions are active.  Every
  micro-batch is ONE dispatch serving ALL active slots; after
  :meth:`GenerationEngine.warm` there are zero decode-step compiles
  (test-pinned), so dispatches/token <= 1.
* **prefill cohorts**: pending prompts coalesce through the batcher's
  anchor/join machinery (:class:`~.batcher.CohortQueue`, the PR 10
  admission idiom extracted for reuse): anchor on the OLDEST pending
  session, join arrivals whose prompt falls in the same length bucket,
  pad to the bucket ladder, one prefill dispatch per cohort.  Prefill
  and decode interleave on the engine loop, so a long prompt never
  starves streaming sessions for more than one prefill dispatch.
* **paged KV admission** (kv_cache.py): ``start_session`` leases a
  decode slot and charges the session's page reservation to the PR 13
  resource ``LEDGER`` — a full pool sheds typed
  ``ServingOverloadError``; release at session end/evict is provably
  leak-free (chaos-asserted).
* **prefix reuse** (kv_cache.py): a content-hash LRU of page-aligned
  prompt-prefix activations; a hit seeds the slot's arena rows from
  the cache and the un-hit tail streams through the decode step
  (chunked prefill with chunk = 1), so shared prompt heads are
  computed once per (model, version).
* **observability**: each session rides a PR 12 trace context (kind
  ``"generation"``) whose per-token stages decompose a slow token
  (``decode_wait`` / ``decode_step`` / ``sample`` / ``deliver``); the
  PR 14 output-health guard screens every sampled logits row — a
  non-finite row fails THAT session typed (:class:`NonFiniteError`),
  cohort siblings keep streaming; ``mxnet_generation_*`` telemetry
  families ride the registry collector.

Sampling happens on HOST, per session (greedy argmax or a seeded
``np.random.Generator``), which is what makes a batched decode run
bitwise-identical to an unbatched single-session reference: the jitted
step computes each slot row independently, and the sampler consumes
exactly the same logits bytes and RNG stream either way.
"""
from __future__ import annotations

import collections
import itertools
import logging
import queue as _queue_mod
import threading
import time
import weakref

import numpy as np

from ..base import MXNetError, NonFiniteError
from ..chaos.failpoints import failpoint as _failpoint
from ..telemetry import flight as _flight
from ..telemetry import numerics as _numerics
from ..telemetry import trace as _trace
from .batcher import (CohortQueue, RequestTimeoutError, ServingClosedError,
                      ServingWorkerError)
from .kv_cache import KVSlotPool, PrefixCache
from .metrics import ServingMetrics

log = logging.getLogger("mxnet_tpu.serving")

_session_seq = itertools.count(1)

# all live engines, for module-level stats() + the telemetry collector
_ENGINES = weakref.WeakValueDictionary()
_ENGINES_LOCK = threading.Lock()


# -- model contract -----------------------------------------------------------
class GenerationModel:
    """The pure-function contract a generation engine drives.

    ``prefill_fn(params, tokens[B, L], mask[B, L]) -> (kv, logits)``
        causal self-attention over a padded prompt cohort; ``kv`` is a
        dict of ``[B, L, ...]`` arrays (the rows written into the
        arena), ``logits`` is ``[B, L, vocab]`` (the engine reads the
        last REAL position per row).
    ``decode_fn(params, arena, tokens[S], pos[S]) -> (logits, arena')``
        one token per slot: write this token's k/v at ``pos``, attend
        over the arena masked to ``<= pos``, return ``[S, vocab]``
        logits and the functionally-updated arena.
    ``init_arena_fn(slots, max_len) -> arena``
        dict of zeroed ``[slots, max_len, ...]`` arrays, one per KV
        tensor (multi-layer models use one pair per layer).

    ``jit=True`` wraps both functions in ``jax.jit`` (the serving
    configuration); ``jit=False`` runs them as plain host callables —
    the relay-proof configuration bench.py's per-token-cost runner
    uses, so the machinery gate never depends on device timing.
    """

    def __init__(self, params, prefill_fn, decode_fn, init_arena_fn,
                 vocab, max_len, jit=True, eos_id=None):
        self.params = params
        self.prefill_fn = prefill_fn
        self.decode_fn = decode_fn
        self.init_arena_fn = init_arena_fn
        self.vocab = int(vocab)
        self.max_len = int(max_len)
        self.jit = bool(jit)
        self.eos_id = eos_id

    def bytes_per_token(self):
        """Ledger page costing: KV bytes one slot commits per token."""
        probe = self.init_arena_fn(1, 1)
        return int(sum(np.asarray(a).dtype.itemsize
                       * int(np.prod(np.asarray(a).shape[2:] or (1,)))
                       for a in probe.values()))


def _np_softmax(x):
    x = x - x.max(axis=-1, keepdims=True)
    e = np.exp(x)
    return e / e.sum(axis=-1, keepdims=True)


def tiny_lm(vocab=32, d_model=16, max_len=256, seed=0, jit=True,
            eos_id=None, per_token_cost_s=0.0):
    """A deterministic single-layer-attention LM for tests, smokes and
    benches.  ``jit=True`` builds jax functions (the serving config);
    ``jit=False`` builds numpy twins — same math, pure host — plus an
    optional ``per_token_cost_s`` busy-wait so bench.py can model a
    fixed per-token device cost without any device in the loop."""
    rng = np.random.RandomState(seed)
    scale = 1.0 / np.sqrt(d_model)
    params = {
        "emb": (rng.randn(vocab, d_model) * 0.5).astype(np.float32),
        "pos": (rng.randn(max_len, d_model) * 0.1).astype(np.float32),
        "wq": (rng.randn(d_model, d_model) * scale).astype(np.float32),
        "wk": (rng.randn(d_model, d_model) * scale).astype(np.float32),
        "wv": (rng.randn(d_model, d_model) * scale).astype(np.float32),
        "wo": (rng.randn(d_model, d_model) * scale).astype(np.float32),
        "w_out": (rng.randn(d_model, vocab) * scale).astype(np.float32),
    }

    if jit:
        import jax.numpy as jnp

        # resolve the optional fused prefill attention ONCE, at model
        # build (host side): the traced prefill body below must contain
        # zero lookups/metrics.  None (MXNET_KERNELS=off) keeps the
        # einsum path.  Right-padded cohorts make the causal mask
        # subsume the key-padding mask at every consumed query row, so
        # the flash kernel is drop-in for the rows the engine reads.
        from .. import kernels as _kernels
        attn_kernel = _kernels.get("attention", (1, 1, max_len, d_model),
                                   np.float32)
        attn_scale = float(scale)   # static kernel param, host-resolved

        def prefill_fn(p, tokens, mask):
            L = tokens.shape[1]
            x = p["emb"][tokens] + p["pos"][:L][None, :, :]
            q = x @ p["wq"]
            k = x @ p["wk"]
            v = x @ p["wv"]
            if attn_kernel is not None:
                y = attn_kernel(q[:, None], k[:, None], v[:, None],
                                causal=True, sm_scale=attn_scale)[:, 0]
            else:
                att = jnp.einsum("bid,bjd->bij", q, k) * scale
                allowed = (jnp.arange(L)[None, :, None]
                           >= jnp.arange(L)[None, None, :]) \
                    & (mask[:, None, :] > 0)
                att = jnp.where(allowed, att, -jnp.inf)
                att = att - att.max(axis=-1, keepdims=True)
                w = jnp.exp(att)
                w = jnp.where(allowed, w, 0.0)
                w = w / w.sum(axis=-1, keepdims=True)
                y = jnp.einsum("bij,bjd->bid", w, v)
            h = x + y @ p["wo"]
            return {"k": k, "v": v}, h @ p["w_out"]

        def decode_fn(p, arena, tokens, pos):
            S, Lmax = arena["k"].shape[:2]
            x = p["emb"][tokens] + p["pos"][pos]
            q = x @ p["wq"]
            k_new = x @ p["wk"]
            v_new = x @ p["wv"]
            rows = jnp.arange(S)
            k_arena = arena["k"].at[rows, pos].set(k_new)
            v_arena = arena["v"].at[rows, pos].set(v_new)
            att = jnp.einsum("sd,sld->sl", q, k_arena) * scale
            allowed = jnp.arange(Lmax)[None, :] <= pos[:, None]
            att = jnp.where(allowed, att, -jnp.inf)
            att = att - att.max(axis=-1, keepdims=True)
            w = jnp.exp(att)
            w = jnp.where(allowed, w, 0.0)
            w = w / w.sum(axis=-1, keepdims=True)
            y = jnp.einsum("sl,sld->sd", w, v_arena)
            h = x + y @ p["wo"]
            return h @ p["w_out"], {"k": k_arena, "v": v_arena}

        def init_arena_fn(slots, L):
            return {"k": jnp.zeros((slots, L, d_model), jnp.float32),
                    "v": jnp.zeros((slots, L, d_model), jnp.float32)}
    else:
        def prefill_fn(p, tokens, mask):
            if per_token_cost_s:
                time.sleep(per_token_cost_s * tokens.shape[1])
            L = tokens.shape[1]
            x = p["emb"][tokens] + p["pos"][:L][None, :, :]
            q = x @ p["wq"]
            k = x @ p["wk"]
            v = x @ p["wv"]
            att = np.einsum("bid,bjd->bij", q, k) * scale
            allowed = (np.arange(L)[None, :, None]
                       >= np.arange(L)[None, None, :]) \
                & (mask[:, None, :] > 0)
            att = np.where(allowed, att, -np.inf)
            att = att - att.max(axis=-1, keepdims=True)
            w = np.exp(att)
            w = np.where(allowed, w, 0.0)
            w = w / w.sum(axis=-1, keepdims=True)
            y = np.einsum("bij,bjd->bid", w, v)
            h = x + y @ p["wo"]
            return {"k": k, "v": v}, h @ p["w_out"]

        def decode_fn(p, arena, tokens, pos):
            if per_token_cost_s:
                time.sleep(per_token_cost_s)
            S, Lmax = arena["k"].shape[:2]
            x = p["emb"][tokens] + p["pos"][pos]
            q = x @ p["wq"]
            rows = np.arange(S)
            k_arena = np.array(arena["k"])
            v_arena = np.array(arena["v"])
            k_arena[rows, pos] = x @ p["wk"]
            v_arena[rows, pos] = x @ p["wv"]
            att = np.einsum("sd,sld->sl", q, k_arena) * scale
            allowed = np.arange(Lmax)[None, :] <= pos[:, None]
            att = np.where(allowed, att, -np.inf)
            att = att - att.max(axis=-1, keepdims=True)
            w = np.exp(att)
            w = np.where(allowed, w, 0.0)
            w = w / w.sum(axis=-1, keepdims=True)
            y = np.einsum("sl,sld->sd", w, v_arena)
            h = x + y @ p["wo"]
            return h @ p["w_out"], {"k": k_arena, "v": v_arena}

        def init_arena_fn(slots, L):
            return {"k": np.zeros((slots, L, d_model), np.float32),
                    "v": np.zeros((slots, L, d_model), np.float32)}

    return GenerationModel(params, prefill_fn, decode_fn, init_arena_fn,
                           vocab=vocab, max_len=max_len, jit=jit,
                           eos_id=eos_id)


# -- session ------------------------------------------------------------------
class GenerationSession:
    """One streaming generation request: iterate it for tokens as they
    decode, or block on :meth:`result` for the full list.  Failures are
    TYPED — the iterator/``result`` raise the structured error the
    engine failed the session with (never a hang: every wait is
    bounded)."""

    PENDING, ACTIVE, DONE, FAILED = "pending", "active", "done", "failed"

    def __init__(self, engine, prompt, max_new_tokens, greedy, seed,
                 slot, version, trace):
        self.session_id = f"{engine.name}#{next(_session_seq)}"
        self.engine = engine
        self.prompt = np.asarray(prompt, np.int32)
        self.max_new_tokens = int(max_new_tokens)
        self.greedy = bool(greedy)
        self.rng = (None if greedy
                    else np.random.Generator(np.random.PCG64(int(seed))))
        self.slot = slot
        self.version = version
        self.trace = trace
        self.state = self.PENDING
        self.pos = 0                       # next arena write position
        self.pending = collections.deque()  # prompt tail fed via decode
        self.tokens = []                   # generated tokens, in order
        self.error = None
        self.t_enqueue = time.perf_counter()
        self.t_last_emit = None
        self._out = _queue_mod.Queue()
        self._done = threading.Event()
        self._cancelled = False

    # -- engine side ---------------------------------------------------------
    def _emit(self, token):
        now = time.perf_counter()
        if self.t_last_emit is not None:
            self.engine.metrics.observe(
                "intertoken_ms", (now - self.t_last_emit) * 1e3)
        self.t_last_emit = now
        self.tokens.append(int(token))
        self.trace.add_stage("deliver", now, time.perf_counter())
        self._out.put(("tok", int(token)))

    def _finish(self, state, error=None):
        if self._done.is_set():
            return
        self.state = state
        self.error = error
        self.engine._release_session(self)
        if error is not None:
            self.trace.event("failed", error=type(error).__name__)
            self._out.put(("err", error))
        else:
            self._out.put(("end", None))
        self.trace.finish(status="ok" if error is None else "error")
        self._done.set()

    # -- client side ---------------------------------------------------------
    def __iter__(self):
        yielded = 0
        while True:
            try:
                kind, payload = self._out.get(
                    timeout=self.engine.session_timeout_s)
            except _queue_mod.Empty:
                waited = (time.perf_counter() - self.t_enqueue) * 1e3
                raise RequestTimeoutError(
                    self.engine.name, waited,
                    self.engine.session_timeout_s * 1e3) from None
            if kind == "tok":
                yielded += 1
                yield payload
            elif kind == "err":
                raise payload
            else:
                return

    def result(self, timeout=None):
        """Block for the complete generation; returns the token list."""
        timeout = (self.engine.session_timeout_s if timeout is None
                   else timeout)
        if not self._done.wait(timeout):
            waited = (time.perf_counter() - self.t_enqueue) * 1e3
            raise RequestTimeoutError(self.engine.name, waited,
                                      timeout * 1e3)
        if self.error is not None:
            raise self.error
        return list(self.tokens)

    def cancel(self):
        """Ask the engine to drop this session at the next tick; the
        slot and its pages release there (or immediately if the session
        never reached the loop)."""
        self._cancelled = True

    def done(self):
        return self._done.is_set()


# -- engine -------------------------------------------------------------------
class GenerationEngine:
    """Prefill/decode loop over a fixed slot arena (the tentpole).

    One background thread interleaves (a) prefill cohorts formed by
    anchor/join over the pending queue and (b) ONE decode dispatch per
    tick covering every active slot.  The loop has a restart budget
    (like the batcher's worker budget): a crash fails the ACTIVE
    sessions typed-retryable (they can resume on a sibling engine —
    the chaos scenario's contract) and restarts the loop; an exhausted
    budget fails the engine fast, releasing every slot and page."""

    def __init__(self, model, name="generator", slots=None,
                 page_tokens=None, kv_budget_mb=None,
                 prefix_cache_entries=None, max_len=None,
                 prefill_max_batch=4, session_timeout_s=60.0,
                 loop_restarts=None, metrics=None, version=1):
        from .. import config as _config
        self.name = str(name)
        self.model = model
        self.slots = int(slots if slots is not None
                         else _config.get("MXNET_GENERATION_SLOTS"))
        self.max_len = int(max_len if max_len is not None
                           else min(model.max_len,
                                    _config.get("MXNET_GENERATION_MAX_LEN")))
        page_tokens = int(page_tokens if page_tokens is not None
                          else _config.get("MXNET_GENERATION_PAGE_TOKENS"))
        budget_mb = (kv_budget_mb if kv_budget_mb is not None
                     else _config.get("MXNET_GENERATION_KV_BUDGET_MB"))
        prefix_entries = int(
            prefix_cache_entries if prefix_cache_entries is not None
            else _config.get("MXNET_GENERATION_PREFIX_CACHE"))
        self.prefill_max_batch = int(prefill_max_batch)
        self.session_timeout_s = float(session_timeout_s)
        self._restart_budget = int(
            loop_restarts if loop_restarts is not None
            else _config.get("MXNET_GENERATION_LOOP_RESTARTS"))
        self.metrics = metrics or ServingMetrics(self.name)
        self.pool = KVSlotPool(
            f"generation/{self.name}", self.slots, page_tokens,
            model.bytes_per_token(), int(budget_mb) * (1 << 20))
        self.prefix_cache = PrefixCache(
            f"generation/{self.name}", prefix_entries, page_tokens)

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._versions = {int(version): model}
        self._version = int(version)
        self._prefill_fns = {}   # version -> fn
        self._decode_fns = {}    # version -> fn
        self._arena = model.init_arena_fn(self.slots, self.max_len)
        # prompt-length ladder: powers of two, page-aligned tail
        self._prompt_ladder = []
        b = 8
        while b < self.max_len:
            self._prompt_ladder.append(b)
            b *= 2
        self._prompt_ladder.append(self.max_len)

        # anchor/join prefill admission (PR 10 machinery, extracted)
        self._pending = CohortQueue(
            lambda s: self._prompt_bucket(len(s.prompt) - s.pos),
            self.prefill_max_batch)
        self._active = {}        # slot index -> session
        self._closed = False
        self._failed = False
        # compile accounting: the counters increment inside the traced
        # function bodies, so they move ONLY when XLA (re)traces — the
        # "0 decode-step compiles post-warm" acceptance pin reads them
        self.decode_compiles = 0
        self.prefill_compiles = 0
        self.decode_steps = 0
        self.tokens_emitted = 0
        self.sessions_started = 0
        self.sessions_failed = 0
        self.max_active = 0
        self._build_fns(self._version)
        self._thread = threading.Thread(
            target=self._loop_forever, daemon=True,
            name=f"generation-{self.name}")
        self._thread.start()
        with _ENGINES_LOCK:
            _ENGINES[self.name] = self
        _register_collector()

    # -- shape ladder --------------------------------------------------------
    def _prompt_bucket(self, n):
        for b in self._prompt_ladder:
            if n <= b:
                return b
        return self._prompt_ladder[-1]

    # -- per-version compiled functions --------------------------------------
    def _build_fns(self, version):
        with self._lock:
            model = self._versions[version]
        if not model.jit:
            with self._lock:
                self._prefill_fns[version] = self._host_prefill(model)
                self._decode_fns[version] = model.decode_fn
            return
        import jax

        def prefill_step(params, arena, tokens, mask, slot_rows):
            self.prefill_compiles += 1   # moves at trace time only
            kv, logits = model.prefill_fn(params, tokens, mask)
            L = tokens.shape[1]
            # padding cohort rows carry slot_rows == slots (out of
            # bounds): mode="drop" discards their junk k/v instead of
            # scattering it over a live session's slot
            for tname in arena:
                arena[tname] = arena[tname].at[slot_rows, :L].set(
                    kv[tname], mode="drop")
            return arena, logits, kv

        def decode_step(params, arena, tokens, pos):
            self.decode_compiles += 1    # moves at trace time only
            return model.decode_fn(params, arena, tokens, pos)

        with self._lock:   # jax.jit wrapping is lazy: no compile held here
            self._prefill_fns[version] = jax.jit(prefill_step)
            self._decode_fns[version] = jax.jit(decode_step)

    @staticmethod
    def _host_prefill(model):
        def prefill_step(params, arena, tokens, mask, slot_rows):
            kv, logits = model.prefill_fn(params, tokens, mask)
            L = tokens.shape[1]
            real = slot_rows < next(iter(arena.values())).shape[0]
            for tname in arena:
                arena[tname][slot_rows[real], :L] = kv[tname][real]
            return arena, logits, kv
        return prefill_step

    # -- warmup (PR 7 idiom: compile the ladder before traffic) --------------
    def warm(self, version=None):
        """AOT-compile the decode step and every prefill prompt bucket
        for ``version`` (default: latest).  Returns the warmed bucket
        list; after this, steady-state decode performs ZERO compiles —
        ``stats()['decode_compiles']`` is the pin."""
        with self._lock:
            version = self._version if version is None else int(version)
            model = self._versions[version]
            decode_fn = self._decode_fns[version]
            prefill_fn = self._prefill_fns[version]
        B = self.prefill_max_batch
        arena = model.init_arena_fn(self.slots, self.max_len)
        tokens = np.zeros(self.slots, np.int32)
        pos = np.zeros(self.slots, np.int32)
        params = model.params
        decode_fn(params, arena, tokens, pos)
        warmed = []
        for bucket in self._prompt_ladder:
            if bucket > self.max_len:
                continue
            ptoks = np.zeros((B, bucket), np.int32)
            # padding rows keep position 0 unmasked so the row softmax
            # normalizer never sees an all-masked (NaN) row
            mask = np.zeros((B, bucket), np.float32)
            mask[:, 0] = 1.0
            rows = np.full(B, self.slots, np.int32)  # all padding
            prefill_fn(params, arena, ptoks, mask, rows)
            warmed.append(bucket)
        _flight.record("serving", "generation_warm", engine=self.name,
                       version=version, buckets=len(warmed))
        return warmed

    # -- hot reload ----------------------------------------------------------
    def load(self, model, version=None, warm=True):
        """Hot-reload: build + AOT-warm the new version's functions
        BEFORE the served-version pointer flips (the PR 7
        warm-before-flip contract), then flip and retire the stale
        version's ladders + prefix-cache activations.  In-flight
        sessions keep streaming; their next decode step serves the new
        version (per-micro-batch resolution, like the batcher), their
        KV computed under the old version stays — the standard
        mid-stream reload semantics."""
        with self._lock:
            new_version = (self._version + 1 if version is None
                           else int(version))
            prev = self._version
            self._versions[new_version] = model
        self._build_fns(new_version)
        if warm:
            self.warm(new_version)
        with self._lock:
            self._version = new_version
        self.retire_stale({new_version, prev})
        _flight.record("serving", "generation_flip", engine=self.name,
                       version=new_version, prev=prev)
        return new_version

    def retire_stale(self, keep_versions):
        """Drop per-version decode/prefill ladders and prefix-cache
        activations for every version not in ``keep_versions`` (the
        ISSUE 16 small fix: a stale version's compiled ladder or cached
        activations must never serve after a flip)."""
        keep = {int(v) for v in keep_versions}
        with self._lock:
            doomed = [v for v in self._versions
                      if v not in keep and v != self._version]
            for v in doomed:
                self._versions.pop(v, None)
                self._prefill_fns.pop(v, None)
                self._decode_fns.pop(v, None)
        model = self.name.rsplit("/", 1)[-1]
        self.prefix_cache.evict_stale_versions(model, keep)
        return len(doomed)

    # -- admission -----------------------------------------------------------
    def start_session(self, prompt, max_new_tokens=16, greedy=True,
                      seed=0):
        """Admit one session: validates the prompt, leases a slot +
        charges the full page reservation (prompt + max_new tokens) to
        the ledger — sheds typed when the pool/budget cannot hold it —
        and queues the session for the next prefill cohort."""
        if self._closed or self._failed:
            raise ServingClosedError(self.name)
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise MXNetError(f"generation[{self.name}]: empty prompt")
        with self._lock:
            version = self._version
            model = self._versions[version]
        if prompt.size + int(max_new_tokens) > self.max_len:
            raise MXNetError(
                f"generation[{self.name}]: prompt ({prompt.size}) + "
                f"max_new_tokens ({max_new_tokens}) exceeds max_len "
                f"{self.max_len}")
        if int(prompt.max()) >= model.vocab or int(prompt.min()) < 0:
            raise MXNetError(
                f"generation[{self.name}]: prompt token out of range "
                f"[0, {model.vocab})")
        tr = _trace.start("generation", self.name)
        try:
            with tr.stage("admit"):
                slot = self.pool.acquire(
                    f"s{next(_session_seq)}",
                    prompt.size + int(max_new_tokens))
        except BaseException as e:
            # shed (pool exhausted / page budget): the trace still
            # finishes, typed — rejected admissions are traceable too,
            # and the span must not leak into the tracer's active set
            try:
                tr.event("rejected", error=type(e).__name__)
            finally:
                tr.finish(status="rejected")
            raise
        sess = GenerationSession(self, prompt, max_new_tokens, greedy,
                                 seed, slot, version, tr)
        with self._lock:
            self.sessions_started += 1
        self.metrics.incr("sessions_total")
        self._pending.put(sess)
        with self._cond:
            self._cond.notify_all()
        return sess

    def generate(self, prompt, **kw):
        """Blocking convenience: the full token list."""
        return self.start_session(prompt, **kw).result()  # graftlint: disable=unbounded-wait -- result() defaults its wait to engine.session_timeout_s and raises typed RequestTimeoutError

    # -- the loop ------------------------------------------------------------
    def _loop_forever(self):
        restarts_left = self._restart_budget
        while True:
            try:
                self._loop()
                return
            except Exception as e:  # noqa: BLE001 — typed fan-out below
                if self._closed:
                    return
                failed = self._fail_active(e)
                with self._lock:
                    self.sessions_failed += failed
                _flight.record("serving", "generation_loop_crash",
                               severity="error", engine=self.name,
                               error=type(e).__name__,
                               restarts_left=restarts_left)
                if restarts_left <= 0:
                    self._fail_engine(e)
                    return
                restarts_left -= 1
                log.exception(
                    "generation[%s]: loop crashed (%s); restarting "
                    "(%d restart(s) left)", self.name,
                    type(e).__name__, restarts_left)

    def _loop(self):
        while not self._closed:
            progressed = self._prefill_tick()
            progressed = self._decode_tick() or progressed
            if not progressed:
                with self._cond:
                    if (self._closed or self._active
                            or len(self._pending)):
                        continue
                    self._cond.wait(0.005)

    # -- prefill -------------------------------------------------------------
    def _prefill_tick(self):
        cohort = self._pending.take(timeout=0.0)
        cohort = [s for s in cohort if not self._drop_if_cancelled(s)]
        if not cohort:
            return False
        with self._lock:
            version = self._version
            model = self._versions[version]
            prefill_fn = self._prefill_fns[version]
        mname = self.name.rsplit("/", 1)[-1]

        # prefix-cache pass: a hit seeds the arena rows from cached
        # activations; the remaining tail streams through decode steps
        need_prefill = []
        for sess in cohort:
            with sess.trace.stage("prefix_lookup"):
                hit_len, kv = self.prefix_cache.lookup(
                    mname, version, sess.prompt)
            if hit_len:
                self._write_prefix(sess.slot.index, kv, model)
                sess.pos = hit_len
                sess.pending.extend(sess.prompt[hit_len:].tolist())
                sess.trace.event("prefix_hit", tokens=hit_len)
                self.metrics.incr("prefix_hits")
                self._activate(sess)
            else:
                self.metrics.incr("prefix_misses")
                need_prefill.append(sess)
        if not need_prefill:
            return True

        bucket = max(self._prompt_bucket(len(s.prompt))
                     for s in need_prefill)
        B = self.prefill_max_batch
        tokens = np.zeros((B, bucket), np.int32)
        mask = np.zeros((B, bucket), np.float32)
        mask[:, 0] = 1.0  # padding rows: see warm()
        rows = np.full(B, self.slots, np.int32)  # padding -> dropped
        for i, sess in enumerate(need_prefill):
            L = len(sess.prompt)
            tokens[i, :L] = sess.prompt
            mask[i] = 0.0
            mask[i, :L] = 1.0
            rows[i] = sess.slot.index
        t0 = time.perf_counter()
        self._arena, logits, kv = prefill_fn(  # graftlint: disable=lock-discipline -- loop-thread-owned device state: only the serve loop touches the arena after start(); holding the lock across a device dispatch would serialize admission with prefill
            model.params, self._arena, tokens, mask, rows)
        logits_host = np.asarray(logits)
        t1 = time.perf_counter()
        for i, sess in enumerate(need_prefill):
            sess.trace.add_stage("prefill", t0, t1)
            L = len(sess.prompt)
            sess.pos = L
            if self.prefix_cache.enabled():
                host_kv = {tname: np.asarray(kv[tname][i])
                           for tname in kv}
                stored = self.prefix_cache.store(
                    mname, version, sess.prompt, host_kv)
                if stored:
                    sess.trace.event("prefix_store", tokens=stored)
            row = logits_host[i, L - 1]
            self._activate(sess, until=t0)
            self._consume_logits(sess, row)
        self.metrics.observe_batch(len(need_prefill), B)
        return True

    def _write_prefix(self, slot_index, kv, model):
        """Seed one slot's arena rows from cached host activations."""
        if model.jit:
            for tname, host in kv.items():
                self._arena[tname] = self._arena[tname] \
                    .at[slot_index, :host.shape[0]].set(host)  # graftlint: disable=lock-discipline -- loop-thread-owned device state (see _prefill_tick)
        else:
            for tname, host in kv.items():
                self._arena[tname][slot_index, :host.shape[0]] = host  # graftlint: disable=lock-discipline -- loop-thread-owned device state (see _prefill_tick)

    def _activate(self, sess, until=None):
        sess.state = GenerationSession.ACTIVE
        now = time.perf_counter()
        with self._lock:
            self._active[sess.slot.index] = sess
            self.max_active = max(self.max_active, len(self._active))
            self.metrics.gauge("sessions_active", len(self._active))
        sess.trace.add_stage("prefill_wait", sess.t_enqueue,
                             now if until is None else until)
        sess.t_mark = now

    # -- decode --------------------------------------------------------------
    def _decode_tick(self):
        with self._lock:
            active = dict(self._active)
            version = self._version
            model = self._versions[version]
            decode_fn = self._decode_fns[version]
        if not active:
            return False
        for sess in list(active.values()):
            if self._drop_if_cancelled(sess):
                active.pop(sess.slot.index, None)
        if not active:
            return True
        tokens = np.zeros(self.slots, np.int32)
        pos = np.zeros(self.slots, np.int32)
        feeding = {}   # slot index -> ("tail"|"gen", session)
        for idx, sess in active.items():
            if sess.pending:
                tokens[idx] = sess.pending.popleft()
                feeding[idx] = ("tail", sess)
            else:
                tokens[idx] = (sess.tokens[-1] if sess.tokens
                               else int(sess.prompt[-1]))
                feeding[idx] = ("gen", sess)
            pos[idx] = sess.pos
        _failpoint("serving/generation/decode")
        t0 = time.perf_counter()
        logits, self._arena = decode_fn(model.params, self._arena,  # graftlint: disable=lock-discipline -- loop-thread-owned device state (see _prefill_tick)
                                        tokens, pos)
        logits_host = np.asarray(logits)
        t1 = time.perf_counter()
        self.decode_steps += 1
        self.metrics.incr("decode_steps")
        # PR 14 output-health guard, generalized to per-step logits:
        # a non-finite row fails THAT session typed, siblings stream on
        bad = set(_numerics.guard_rows([logits_host], self.slots))
        for idx, (mode, sess) in feeding.items():
            sess.trace.add_stage("decode_wait",
                                 getattr(sess, "t_mark", t0), t0)
            sess.trace.add_stage("decode_step", t0, t1)
            sess.t_mark = t1
            sess.pos += 1
            if sess.pending:
                continue   # mid-tail: logits are internal, not served
            if idx in bad:
                with self._lock:
                    self.sessions_failed += 1
                _numerics.record_serving_nonfinite(self.name, 1)
                sess._finish(GenerationSession.FAILED, NonFiniteError(
                    f"generation[{self.name}] session "
                    f"{sess.session_id}", stat="logits",
                    value="nan/inf",
                    detail="non-finite decode logits; the session "
                           "failed typed, cohort siblings keep "
                           "streaming (docs/serving.md)"))
                continue
            self._consume_logits(sess, logits_host[idx])
        return True

    def _consume_logits(self, sess, row):
        """Sample the next token from one served logits row (host-side,
        per-session RNG), emit it, and finish the session at
        max_new_tokens/EOS."""
        if not np.isfinite(row).all():
            with self._lock:
                self.sessions_failed += 1
            _numerics.record_serving_nonfinite(self.name, 1)
            sess._finish(GenerationSession.FAILED, NonFiniteError(
                f"generation[{self.name}] session {sess.session_id}",
                stat="logits", value="nan/inf",
                detail="non-finite prefill logits"))
            return
        t0 = time.perf_counter()
        if sess.greedy:
            token = int(np.argmax(row))
        else:
            probs = _np_softmax(row.astype(np.float64))
            token = int(sess.rng.choice(row.shape[0], p=probs))
        sess.trace.add_stage("sample", t0, time.perf_counter())
        self.tokens_emitted += 1
        self.metrics.incr("tokens_total")
        sess._emit(token)
        with self._lock:
            model = self._versions[self._version]
        if (len(sess.tokens) >= sess.max_new_tokens
                or (model.eos_id is not None and token == model.eos_id)):
            sess._finish(GenerationSession.DONE)

    # -- failure fan-out / lifecycle -----------------------------------------
    def _drop_if_cancelled(self, sess):
        if sess._cancelled and not sess.done():
            sess._finish(GenerationSession.FAILED,
                         ServingClosedError(self.name))
            return True
        return False

    def _release_session(self, sess):
        self.pool.release(sess.slot)
        with self._cond:
            self._active.pop(sess.slot.index, None)
            self.metrics.gauge("sessions_active", len(self._active))
            self._cond.notify_all()

    def _fail_active(self, cause, exhausted=False):
        """Crash fan-out: every admitted session fails typed-retryable
        (``ServingWorkerError`` — the client resumes on a sibling
        engine with ``prompt + tokens`` as the new prompt, which the
        sibling's prefix cache makes cheap) and provably releases its
        slot and pages."""
        with self._lock:
            doomed = list(self._active.values())
        doomed += self._pending.drain()
        for sess in doomed:
            if not sess.done():
                err = (cause if isinstance(cause, ServingClosedError)
                       else ServingWorkerError(self.name, cause=cause,
                                               exhausted=exhausted))
                sess._finish(GenerationSession.FAILED, err)
        return len(doomed)

    def _fail_engine(self, cause):
        self._failed = True
        failed = self._fail_active(cause, exhausted=True)
        with self._lock:
            self.sessions_failed += failed
        log.error("generation[%s]: loop restart budget exhausted; "
                  "engine failed fast (%s: %s)", self.name,
                  type(cause).__name__, cause)

    def close(self, timeout=10.0):
        """Stop the loop and fail anything still queued/active typed;
        idempotent.  Every slot and ledger page releases."""
        if self._closed:
            return
        self._closed = True
        with self._cond:
            self._cond.notify_all()
        self._thread.join(timeout)
        self._fail_active(ServingClosedError(self.name))
        self.prefix_cache.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- observability -------------------------------------------------------
    def stats(self):
        with self._lock:
            active = len(self._active)
            version = self._version
            versions = sorted(self._versions)
            started = self.sessions_started
            failed = self.sessions_failed
            max_active = self.max_active
        return {
            "engine": self.name, "version": version,
            "versions_resident": versions,
            "sessions_active": active,
            "sessions_pending": len(self._pending),
            "sessions_started": started,
            "sessions_failed": failed,
            "max_active": max_active,
            "tokens_emitted": self.tokens_emitted,
            "decode_steps": self.decode_steps,
            "decode_compiles": self.decode_compiles,
            "prefill_compiles": self.prefill_compiles,
            "failed": self._failed, "closed": self._closed,
            "kv": self.pool.stats(),
            "prefix_cache": self.prefix_cache.stats(),
        }


# -- module-level stats + telemetry collector ---------------------------------
def stats():
    """{engine name: stats dict} for every live engine — the payload
    behind ``telemetry.snapshot()['generation']``."""
    with _ENGINES_LOCK:
        engines = list(_ENGINES.values())
    return {e.name: e.stats() for e in engines}


def _generation_samples():
    gauges = {
        "sessions_active": ("mxnet_generation_sessions_active",
                            "active generation sessions (decode slots "
                            "streaming), by engine"),
        "decode_compiles": ("mxnet_generation_decode_compiles",
                            "decode-step XLA traces — flat after warm "
                            "or the ladder regressed"),
        "max_active": ("mxnet_generation_max_active",
                       "high-water concurrent sessions in one decode "
                       "micro-batch"),
    }
    counters = {
        "sessions_started": ("mxnet_generation_sessions_total",
                             "admitted generation sessions, by engine"),
        "sessions_failed": ("mxnet_generation_sessions_failed_total",
                            "sessions failed typed (guard, crash, "
                            "shed), by engine"),
        "tokens_emitted": ("mxnet_generation_tokens_total",
                           "tokens sampled and streamed, by engine"),
        "decode_steps": ("mxnet_generation_decode_steps_total",
                         "fixed-shape decode dispatches, by engine"),
    }
    out = []
    for name, snap in sorted(stats().items()):
        labels = {"engine": name}
        for field, (fam, help_) in gauges.items():
            out.append((fam, "gauge", help_, labels, snap[field]))
        for field, (fam, help_) in counters.items():
            out.append((fam, "counter", help_, labels, snap[field]))
        kv = snap["kv"]
        out.append(("mxnet_generation_kv_pages", "gauge",
                    "KV-cache pages committed to live sessions",
                    labels, kv["pages_in_use"]))
        out.append(("mxnet_generation_kv_bytes", "gauge",
                    "KV-cache bytes committed to live sessions "
                    "(mirrors the resource ledger's kv_pages rows)",
                    labels, kv["kv_bytes"]))
        out.append(("mxnet_generation_sheds_total", "counter",
                    "sessions shed typed at admission (pool full / "
                    "budget)", labels, kv["sheds"]))
        pc = snap["prefix_cache"]
        out.append(("mxnet_generation_prefix_hits_total", "counter",
                    "prefix-cache hits (prompt heads served from "
                    "cached activations)", labels, pc["hits"]))
        out.append(("mxnet_generation_prefix_misses_total", "counter",
                    "prefix-cache misses (full prefill paid)",
                    labels, pc["misses"]))
    return out


_collector_registered = False


def _register_collector():
    global _collector_registered
    if _collector_registered:
        return
    from .. import telemetry as _telemetry
    _telemetry.register_collector("generation", stats,
                                  _generation_samples)
    _collector_registered = True
