"""Generic class registry helpers.

Parity: python/mxnet/registry.py — register/alias/create factories used by
Initializer, Optimizer, EvalMetric, LRScheduler registries. create() accepts
a name string, a (name, kwargs) json string, or an instance.
"""
from __future__ import annotations

import json

from .base import MXNetError

_REGISTRIES = {}


def get_register_func(base_class, nickname, registry=None):
    if registry is None:
        registry = _REGISTRIES.setdefault(nickname, {})
    _REGISTRIES[nickname] = registry

    def register(klass, name=None):
        assert issubclass(klass, base_class), \
            f"Can only register subclass of {base_class.__name__}"
        key = (name or klass.__name__).lower()
        if key in registry and registry[key] is not klass:
            import logging
            logging.getLogger(__name__).warning(
                "New %s %s.%s registered with name %s is overriding existing %s",
                nickname, klass.__module__, klass.__name__, key,
                registry[key].__name__)
        registry[key] = klass
        return klass

    register.__doc__ = f"Register a {nickname} to the {nickname} registry"
    return register


def get_alias_func(base_class, nickname, registry=None):
    register = get_register_func(base_class, nickname, registry)

    def alias(*aliases):
        def reg(klass):
            for a in aliases:
                register(klass, a)
            return klass
        return reg

    return alias


def get_create_func(base_class, nickname, registry=None):
    if registry is None:
        registry = _REGISTRIES.setdefault(nickname, {})

    def create(*args, **kwargs):
        if len(args) == 0:
            raise MXNetError(f"{nickname} name required")
        name = args[0]
        args = args[1:]
        if isinstance(name, base_class):
            if args or kwargs:
                raise MXNetError(
                    f"{nickname} is already an instance; no extra args allowed")
            return name
        if not isinstance(name, str):
            raise MXNetError(f"{nickname} must be str or {base_class.__name__}")
        if name.startswith("["):
            if args or kwargs:
                raise MXNetError("no positional/kwargs with json spec")
            name, kwargs = json.loads(name)
        key = name.lower()
        if key not in registry:
            raise MXNetError(f"Cannot find {nickname} {name} in registry "
                             f"({sorted(registry)})")
        return registry[key](*args, **kwargs)

    return create
