"""Subgraph partition / graph-rewrite framework.

Parity: reference src/operator/subgraph/ — SubgraphProperty +
SubgraphSelector walk the graph, claim regions, and replace each with a
single subgraph op (build_subgraph.cc); backends select via
MXNET_SUBGRAPH_BACKEND. That machinery is the basis of the reference's
MKLDNN fusion, TensorRT offload and quantized-graph passes.

TPU re-design: XLA already fuses elementwise chains into matmuls, so the
framework's value here is *semantic* rewriting — swapping a matched
region for a different implementation (a Pallas kernel, a quantized op,
a precision-cast region) rather than micro-fusion. A claimed region is
replaced by one `_subgraph` node whose attrs carry the inner graph as
MXNet JSON; its fcompute re-traces the inner graph, so under jit the
whole region still compiles into the enclosing XLA computation.

Region contract (v1): single external output — the selector grows
producer-into-consumer from a seed, and a producer joins only if every
consumer lies inside the region. This makes cycles impossible by
construction (no internal node is visible outside except the seed).
Random / aux-mutating ops (Dropout, BatchNorm) never join a region.
"""
from __future__ import annotations

import json

from .base import MXNetError
from .ops import registry as _registry

_PROPERTIES = {}


def register_subgraph_property(name, prop_cls=None):
    """Register a SubgraphProperty under ``name`` (decorator or direct)."""
    def deco(cls):
        _PROPERTIES[name] = cls
        return cls
    if prop_cls is not None:
        return deco(prop_cls)
    return deco


def list_backends():
    return sorted(_PROPERTIES)


def get_property(name):
    if name not in _PROPERTIES:
        raise MXNetError(f"unknown subgraph backend '{name}' "
                         f"(registered: {list_backends()})")
    return _PROPERTIES[name]()


class SubgraphSelector:
    """Per-region growth policy (parity: subgraph_property.h
    SubgraphSelector). The partitioner seeds a region at a node where
    ``select`` is true, then repeatedly offers producers via
    ``select_input``."""

    def select(self, node):
        return False

    def select_input(self, node, input_node):
        return False


class SubgraphProperty:
    """A rewrite backend (parity: SubgraphProperty)."""

    #: smallest region worth rewriting; 1 enables single-node op
    #: substitution (e.g. swapping a matched op for a Pallas kernel)
    min_subgraph_size = 2

    def create_selector(self):
        raise NotImplementedError

    def create_subgraph_node(self, subgraph_sym, input_syms, subgraph_id):
        """Default replacement: a `_subgraph` op carrying the inner JSON."""
        from .symbol.symbol import Symbol
        return Symbol._create(
            "_subgraph", input_syms,
            {"subgraph_json": subgraph_sym.tojson(),
             "subgraph_backend": type(self).__name__,
             "subgraph_id": subgraph_id})


# --- the generic subgraph op -----------------------------------------------
_SUBGRAPH_CACHE = {}


def _inner_symbol(json_str):
    sym = _SUBGRAPH_CACHE.get(json_str)
    if sym is None:
        from .symbol.symbol import load_json
        sym = load_json(json_str)
        _SUBGRAPH_CACHE[json_str] = sym
    return sym


def exec_subgraph(sym, in_map, all_outputs=False):
    """Trace an inner graph on jax values. ``in_map``: name -> value for
    every variable. Returns the first output, or all outputs as a list.
    Shared by the fusion backend (`_subgraph`) and the control-flow ops
    (symbol/control_flow.py) — the cut-out graph executes as plain jax
    inside whatever lax combinator the caller wraps it in."""
    env = {}
    for node in sym._topo():
        if node.is_variable():
            env[(node, 0)] = in_map[node.name]
            continue
        op = _registry.get(node.op)
        attrs = {k: v for k, v in node.attrs.items()
                 if not k.startswith("__")}
        ins = [env[e] for e in node.inputs]
        out = op.grad_aware(attrs)(*ins)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        for i, o in enumerate(outs):
            env[(node, i)] = o
    if all_outputs:
        return [env[e] for e in sym._outputs]
    return env[sym._outputs[0]]


def _exec_inner(sym, inputs):
    """Trace the inner graph (inputs in list_inputs order)."""
    return exec_subgraph(sym, dict(zip(sym.list_inputs(), inputs)))


@_registry.register("_subgraph")
def _subgraph_fcompute(attrs, *inputs):
    sym = _inner_symbol(attrs["subgraph_json"])
    return _exec_inner(sym, inputs)


# --- partitioner ------------------------------------------------------------
def partition(sym, prop):
    """Apply ``prop`` to ``sym``: claim regions, replace each with one
    subgraph node. Returns the rewritten Symbol (parity:
    build_subgraph.cc BuildSubgraph)."""
    from .symbol.symbol import Symbol, _SymNode

    if isinstance(prop, str):
        prop = get_property(prop)

    topo = sym._topo()
    consumers = {}  # node -> set of consumer nodes
    for n in topo:
        for (src, _i) in n.inputs:
            consumers.setdefault(id(src), set()).add(id(n))
    output_nodes = {id(n) for (n, _i) in sym._outputs}

    claimed = set()
    regions = []  # (seed_node, set_of_member_ids, members_topo_list)
    for seed in reversed(topo):  # consumers first: largest fusions win
        if id(seed) in claimed or seed.is_variable():
            continue
        selector = prop.create_selector()
        if not selector.select(seed):
            continue
        if _is_stateful(seed):
            continue
        region = {id(seed)}
        members = [seed]
        frontier = [seed]
        while frontier:
            node = frontier.pop()
            for (src, _i) in node.inputs:
                if src.is_variable() or id(src) in region \
                        or id(src) in claimed or _is_stateful(src):
                    continue
                # single-output contract: every consumer of the producer
                # must already be inside the region, and it must not be a
                # graph output itself
                if id(src) in output_nodes:
                    continue
                if not consumers.get(id(src), set()) <= region:
                    continue
                if selector.select_input(node, src):
                    region.add(id(src))
                    members.append(src)
                    frontier.append(src)
        if len(region) >= prop.min_subgraph_size:
            claimed |= region
            regions.append((seed, region))

    if not regions:
        return sym

    # rebuild the graph bottom-up, swapping claimed regions
    region_of = {}
    for seed, region in regions:
        for nid in region:
            region_of[nid] = id(seed)
    seed_by_id = {id(seed): (seed, region) for seed, region in regions}

    new_nodes = {}       # id(old_node) -> new _SymNode
    subgraph_out = {}    # id(seed) -> replacement Symbol

    def map_entry(entry):
        src, i = entry
        rid = region_of.get(id(src))
        if rid is not None:
            rep = subgraph_out[rid]
            return rep._outputs[0]
        return (new_nodes[id(src)], i)

    sub_count = 0
    for n in topo:
        rid = region_of.get(id(n))
        if rid is not None and rid != id(n):
            continue  # interior region node: swallowed by its seed
        if rid == id(n):
            seed, region = seed_by_id[rid]
            inner_sym, ext_inputs = _extract(sym, seed, region)
            input_syms = [Symbol([map_entry(e)]) for e in ext_inputs]
            rep = prop.create_subgraph_node(inner_sym, input_syms, sub_count)
            sub_count += 1
            subgraph_out[rid] = rep
            continue
        node = _SymNode(n.op, n.name, dict(n.attrs))
        new_nodes[id(n)] = node
        node.inputs = [map_entry(e) for e in n.inputs]

    outs = []
    for (n, i) in sym._outputs:
        rid = region_of.get(id(n))
        if rid is not None:
            outs.append(subgraph_out[rid]._outputs[0])
        else:
            outs.append((new_nodes[id(n)], i))
    return Symbol(outs)


def _is_stateful(node):
    if node.is_variable():
        return False
    op = _registry.get(node.op)
    return op.is_random or bool(op.resolve_mutate_aux(node.attrs)) or \
        op.resolve_num_outputs(node.attrs) > 1


def _extract(sym, seed, region):
    """Inner symbol of a region: external entries become fresh variables
    named _in0.. in first-use order. Returns (inner_sym, ext_entries)."""
    from .symbol.symbol import Symbol, _SymNode

    ext_entries = []
    ext_map = {}
    clones = {}

    def clone(node):
        c = clones.get(id(node))
        if c is not None:
            return c
        c = _SymNode(node.op, node.name, dict(node.attrs))
        clones[id(node)] = c
        ins = []
        for (src, i) in node.inputs:
            if id(src) in region:
                ins.append((clone(src), i))
            else:
                key = (id(src), i)
                if key not in ext_map:
                    v = _SymNode(None, f"_in{len(ext_entries)}", {})
                    ext_map[key] = v
                    ext_entries.append((src, i))
                ins.append((ext_map[key], 0))
        c.inputs = ins
        return c

    inner = Symbol([(clone(seed), 0)])
    return inner, ext_entries


# --- built-in properties ----------------------------------------------------
class _DenseActSelector(SubgraphSelector):
    _ELEMWISE = {"Activation", "relu", "sigmoid", "tanh", "LeakyReLU",
                 "clip", "_plus_scalar", "_mul_scalar"}

    def select(self, node):
        return node.op in self._ELEMWISE

    def select_input(self, node, input_node):
        return input_node.op == "FullyConnected" or \
            input_node.op in self._ELEMWISE


@register_subgraph_property("dense_act")
class DenseActivationFusion(SubgraphProperty):
    """Fuse FullyConnected + trailing elementwise chain into one subgraph
    op (the reference's MKLDNN fc+act fusion analogue; under XLA this is
    a semantic grouping that guarantees one fused kernel)."""

    def create_selector(self):
        return _DenseActSelector()


def apply_backend(sym, backend=None):
    """Apply the env-selected backend (MXNET_SUBGRAPH_BACKEND) to a
    Symbol; identity when unset/unknown-empty."""
    if backend is None:
        from .config import get as _cfg
        backend = _cfg("MXNET_SUBGRAPH_BACKEND")
    if not backend:
        return sym
    return partition(sym, backend)
