"""KVStore: key-value store for parameter synchronization.

Re-design of reference src/kvstore/* + python/mxnet/kvstore.py. The reference
stack (CommDevice GPU trees: comm.h:451, NCCL: kvstore_nccl.h, ps-lite
workers/servers: kvstore_dist.h) is replaced by:

- 'local'/'device'/'nccl': single-process store; cross-device reduce is an
  explicit sum (device count on one TPU host is 1 chip under axon; under a
  mesh the SPMD path in mxnet_tpu.parallel does reduction as XLA psum and
  this store only orchestrates).
- 'ici': SPMD facade — parameters live sharded on a DeviceMesh; push/pull
  are no-ops because the train step's psum already synchronized gradients
  (the reference's "comm overlaps compute" falls out of one fused program).
- 'dist_sync'/'dist_async'/'dist_device_sync': multi-worker semantics.
  Rank/size come from DMLC_ROLE/DMLC_NUM_WORKER env (same contract as
  ps-lite); the transport is the mxnet_tpu.kvstore_server socket protocol
  on localhost/DCN. With a single worker they degrade to 'local'.

Updater semantics preserved: set_optimizer installs the optimizer in-store
(update_on_kvstore), matching kvstore_dist_server.h ApplyUpdates.
"""
from __future__ import annotations

import os
import pickle

from . import ndarray as nd
from . import optimizer as opt
from .base import MXNetError
from .ndarray import NDArray


def _ctx_key(ctx):
    return (ctx.device_type, ctx.device_id)


def _account_wire(op, grouped_values):
    """Telemetry: logical payload bytes entering/leaving the store
    (``mxnet_kvstore_bytes_total{op=push|pull}``).  Shape x itemsize host
    arithmetic only — never a device sync; sparse arrays count their
    logical (dense) shape."""
    import numpy as _np

    from . import telemetry as _telemetry
    total = n = 0
    for vlist in grouped_values:
        if not isinstance(vlist, (list, tuple)):
            vlist = [vlist]
        for v in vlist:
            shape = getattr(v, "shape", None)
            dtype = getattr(v, "dtype", None)
            if shape is None or dtype is None:
                continue
            total += int(_np.prod(shape, dtype=_np.int64)) * \
                _np.dtype(dtype).itemsize
            n += 1
    _telemetry.record_kvstore(op, total, n)
    # the store path IS gradient communication: mirror it into the
    # collective families so mxnet_collective_bytes_total{kind} covers
    # both the mesh-fused step and this residual per-param path
    _telemetry.record_collective(f"kvstore_{op}", total, 0.0, n)


class KVStore:
    """Single-process key-value store (parity: include/mxnet/kvstore.h:59 +
    kvstore_local.h)."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._str_key_dict = {}
        self._compression_params = None
        self._compression = None

    # -- identity ----------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    @property
    def mesh_fusible(self):
        """True when ``Module.fit`` may absorb this store's per-step
        gradient synchronization into the mesh-fused train step
        (parallel/fused.py): the store then serves only init/broadcast
        and optimizer-state fetch, and gradient reduction runs as
        bucketed XLA collectives inside the donated window.  False when
        the store carries semantics the traced collectives would drop
        (gradient compression's quantize/residual cycle)."""
        return getattr(self, "_compression", None) is None

    # -- data --------------------------------------------------------------
    def init(self, key, value):
        keys, values = _key_value(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            self._store[k] = v.copy()

    def push(self, key, value, priority=0):
        """Sum values across devices, optionally run the in-store updater
        (parity: KVStoreLocal::Push → Comm*::Reduce; row_sparse values
        reduce sparsely and reach the updater as row_sparse so lazy
        optimizer updates touch only the pushed rows)."""
        from .ndarray import sparse as _sp
        keys, values = _key_grouped(key, value)
        _account_wire("push", values)
        for k, vlist in zip(keys, values):
            if k not in self._store:
                raise MXNetError(f"key {k} was not init()ed")
            stored = self._store[k]
            if any(isinstance(v, _sp.BaseSparseNDArray) for v in vlist):
                merged = vlist[0]
                for v in vlist[1:]:
                    merged = _sp.elemwise_add(merged, v)
                if self._updater is not None:
                    self._updater(_updater_key(k), merged, stored)
                elif isinstance(merged, _sp.BaseSparseNDArray) and \
                        not isinstance(stored, _sp.BaseSparseNDArray):
                    stored._set_data(merged.todense()._data)
                else:
                    self._store[k] = merged.copy()
                continue
            if getattr(self, "_compression", None) is not None:
                vlist = [self._compress_cycle(k, i, v)
                         for i, v in enumerate(vlist)]
            merged = vlist[0].copyto(stored.ctx) if len(vlist) == 1 else \
                nd.add_n(*[v.as_in_context(stored.ctx) for v in vlist])
            if self._updater is not None:
                self._updater(_updater_key(k), merged, stored)
            else:
                stored._set_data(merged._data)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Broadcast stored value to out arrays (parity: pull → Broadcast).

        Sparse outs are skipped when ignore_sparse (reference behavior) and
        rejected otherwise — a dense broadcast into a RowSparseNDArray would
        desync its indices; use row_sparse_pull."""
        from .ndarray.sparse import BaseSparseNDArray
        assert out is not None
        keys, outs = _key_grouped(key, out)
        _account_wire("pull", outs)
        for k, olist in zip(keys, outs):
            stored = self._store[k]
            for o in olist:
                if isinstance(o, BaseSparseNDArray):
                    if ignore_sparse:
                        continue
                    raise MXNetError(
                        "pull into a sparse NDArray is not defined; use "
                        "row_sparse_pull(key, out, row_ids=...)")
                o._set_data(stored.as_in_context(o.ctx)._data)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull ONLY the requested rows as RowSparseNDArray(s) (parity:
        KVStore::PullRowSparse, kvstore_dist.h:243 — the bandwidth win for
        embedding-style parameters)."""
        import numpy as np
        import jax.numpy as jnp
        from .ndarray.sparse import RowSparseNDArray
        assert out is not None and row_ids is not None
        keys, outs = _key_grouped(key, out)
        ids_list = row_ids if isinstance(row_ids, (list, tuple)) else \
            [row_ids] * len(keys)
        for k, olist, rid in zip(keys, outs, ids_list):
            stored = self._store[k]
            rows = np.unique(np.asarray(
                rid.asnumpy() if isinstance(rid, NDArray) else rid
            ).astype(np.int64).ravel())
            vals = self._fetch_rows(k, stored, rows)
            for o in olist:
                if not isinstance(o, RowSparseNDArray):
                    raise MXNetError(
                        "row_sparse_pull requires row_sparse out arrays "
                        "(a dense scatter would zero the un-pulled rows)")
                o._indices = jnp.asarray(rows)
                o._set_data(jnp.asarray(vals))

    def _fetch_rows(self, k, stored, rows):
        import jax.numpy as jnp
        data = stored.todense()._data \
            if getattr(stored, "stype", "default") != "default" \
            else stored._data
        return data[jnp.asarray(rows)]

    # -- updater / optimizer ----------------------------------------------
    def set_optimizer(self, optimizer):
        """Run this optimizer in-store on push (parity: update_on_kvstore;
        dist servers receive it pickled, kvstore_dist_server.h:155)."""
        self._optimizer = optimizer
        self._set_updater(opt.get_updater(optimizer))

    def _set_updater(self, updater):
        self._updater = updater

    def _send_command_to_servers(self, head, body):
        pass  # single-process: nothing to send

    def get_num_dead_node(self, node_id=0, timeout=60):
        """Non-dist stores have no remote peers to lose (parity:
        KVStore::get_num_dead_node, include/mxnet/kvstore.h:353)."""
        return 0

    def get_optimizer_states(self, dump_optimizer=False):
        """Optimizer state as bytes — the file-free primitive the
        checkpoint subsystem stores in its manifest-tracked blobs (dist
        stores fetch from the server, where the updater actually ran)."""
        assert self._updater is not None, "updater is not set"
        return self._updater.get_states(dump_optimizer)

    def set_optimizer_states(self, states):
        """Install optimizer state bytes (inverse of
        get_optimizer_states)."""
        assert self._updater is not None, "updater is not set"
        self._updater.set_states(states)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        data = self.get_optimizer_states(dump_optimizer)
        # atomic temp + os.replace: same no-torn-writes contract as
        # nd.save / the checkpoint subsystem
        tmp = f"{fname}.tmp-{os.getpid()}"
        with open(tmp, "wb") as fout:
            fout.write(data)
        os.replace(tmp, fname)

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            self.set_optimizer_states(f.read())

    # -- compression / barrier --------------------------------------------
    def set_gradient_compression(self, compression_params):
        """Arm 2-bit gradient compression (parity: kvstore.py
        set_gradient_compression — device/dist stores only; the reference
        raises for plain local too)."""
        if not ("device" in self._type or "dist" in self._type):
            raise MXNetError(
                "gradient compression is only supported for 'device' and "
                "'dist*' kvstores")
        from . import gradient_compression as gc
        self._compression_params = dict(compression_params)
        self._compression = gc.create(compression_params)

    def _compress_cycle(self, k, i, value):
        """Local stores quantize+dequantize each pushed value (with
        per-(key, device) residual) so compressed training semantics are
        identical whether the grads cross a wire or not (parity: the
        reference's CommDevice compressed reduce path)."""
        import numpy as np
        gc = getattr(self, "_compression", None)
        if gc is None:
            return value
        deq = gc.dequantize(gc.quantize((k, i), value.asnumpy()),
                            tuple(value.shape), np.float32)
        return nd.array(deq, ctx=value.ctx, dtype=value.dtype)

    def barrier(self):
        nd.waitall()


class KVStoreICI(KVStore):
    """XLA-collective store (SURVEY.md §5 'KVStore(ici)' north star).

    Gradient allreduce runs as ONE jitted XLA computation over the devices
    holding the pushed copies: per-device arrays are assembled into a
    sharded jax.Array over a throwaway 1-axis mesh and summed with
    replicated out_shardings — XLA lowers that to an all-reduce riding the
    ICI torus (CommDevice/NCCL equivalent, zero host round-trips). pull
    hands back each device's replicated shard without any transfer.
    gluon.Trainer / Module.fit select it with kvstore='ici'."""

    def __init__(self):
        super().__init__("ici")
        self._fn_cache = {}
        self._replicated = {}  # key -> replicated jax.Array after push

    def _allreduce(self, vlist):
        import jax
        import numpy as _np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        devs = tuple(next(iter(v._data.devices())) for v in vlist)
        if len(set(devs)) != len(devs):
            # duplicate devices (e.g. tests faking multi-device on one
            # chip): reduce on the first copy's device — mixed partial
            # duplication would otherwise feed jit incompatible devices
            total = vlist[0]._data
            for v in vlist[1:]:
                total = total + jax.device_put(v._data, devs[0])  # graftlint: disable=per-param-collective -- duplicate-device fallback (tests faking multi-device): a handful of copies once, not a per-step loop
            return None, total
        shape = tuple(vlist[0].shape)
        ckey = (devs, shape, str(vlist[0].dtype))
        entry = self._fn_cache.get(ckey)
        if entry is None:
            mesh = Mesh(_np.array(devs), ("dp",))
            fn = jax.jit(lambda x: x.sum(0),
                         out_shardings=NamedSharding(mesh, P()))
            entry = (mesh, fn)
            self._fn_cache[ckey] = entry
        mesh, fn = entry
        shards = [v._data[None] for v in vlist]  # (1,)+shape, on-device
        stacked = jax.make_array_from_single_device_arrays(
            (len(vlist),) + shape, NamedSharding(mesh, P("dp")), shards)
        return fn(stacked), None

    def push(self, key, value, priority=0):
        from .ndarray import sparse as _sp
        keys, values = _key_grouped(key, value)
        for k, vlist in zip(keys, values):
            if k not in self._store:
                raise MXNetError(f"key {k} was not init()ed")
            if any(isinstance(v, _sp.BaseSparseNDArray) for v in vlist) or \
                    len(vlist) == 1:
                # sparse or single-device: the local reduction is optimal
                # (super().push accounts these bytes itself)
                self._replicated.pop(k, None)
                super().push(k, vlist, priority)  # graftlint: disable=per-param-collective -- per-KEY delegation of the multi-key API; each key reduces once in-store
                continue
            _account_wire("push", [vlist])
            replicated, plain = self._allreduce(vlist)
            stored = self._store[k]
            if replicated is None:
                merged_dev0 = plain
            else:
                # the shard on the stored array's device (no transfer)
                sdev = next(iter(stored._data.devices()))
                merged_dev0 = None
                for shard in replicated.addressable_shards:
                    if shard.device == sdev:
                        merged_dev0 = shard.data
                        break
                if merged_dev0 is None:
                    merged_dev0 = replicated.addressable_shards[0].data
            merged = NDArray(merged_dev0, stored.ctx)
            if self._updater is not None:
                self._replicated.pop(k, None)  # weights changed: rebroadcast
                self._updater(_updater_key(k), merged, stored)
            else:
                stored._set_data(merged._data)
                if replicated is not None:
                    self._replicated[k] = replicated

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        from .ndarray.sparse import BaseSparseNDArray
        assert out is not None
        keys, outs = _key_grouped(key, out)
        _account_wire("pull", outs)
        for k, olist in zip(keys, outs):
            replicated = self._replicated.get(k)
            stored = self._store[k]
            for o in olist:
                if isinstance(o, BaseSparseNDArray):
                    if ignore_sparse:
                        continue
                    raise MXNetError("pull into sparse: use row_sparse_pull")
                odev = next(iter(o._data.devices()))
                shard_data = None
                if replicated is not None:
                    for shard in replicated.addressable_shards:
                        if shard.device == odev:
                            shard_data = shard.data
                            break
                if shard_data is not None:
                    o._set_data(shard_data)
                else:
                    import jax
                    o._set_data(jax.device_put(stored._data, odev))  # graftlint: disable=per-param-collective -- boundary transfer per out array after the in-store allreduce; the mesh fused step removes pulls from eligible hot paths


class KVStoreDist(KVStore):
    """Multi-worker store. Rank/size from DMLC_* env (contract parity with
    ps-lite, ps::StartAsync); transport via kvstore_server when a scheduler
    address is configured, else single-worker degradation."""

    def __init__(self, kv_type):
        super().__init__(kv_type)
        self._rank = int(os.environ.get("DMLC_RANK",
                                        os.environ.get("DMLC_WORKER_ID", 0)))
        self._num_workers = int(os.environ.get("DMLC_NUM_WORKER", 1))
        self._client = None
        self._chunked = {}  # key -> chunk layout (None = unchunked)
        root_uri = os.environ.get("DMLC_PS_ROOT_URI")
        if self._num_workers > 1 and root_uri:
            from .kvstore_server import KVClient
            port = int(os.environ.get("DMLC_PS_ROOT_PORT", 9091))
            self._client = KVClient(root_uri, port, self._rank,
                                    self._num_workers)

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    @property
    def mesh_fusible(self):
        """Only the single-process degradation may fuse: with a live
        multi-worker client the server-side sum over DCN is the sync
        mechanism and must keep running per push."""
        return self._client is None and \
            getattr(self, "_compression", None) is None

    @staticmethod
    def _layout_from_rows_per(k, shape, rows_per):
        """Materialize the chunk-key plan for a given rows-per-chunk.
        The single authority for the ``k#chunkN`` namespace — used both by
        the local bound computation and by workers adopting rank 0's
        recorded layout, so the namespaces cannot diverge."""
        if rows_per <= 0:
            return [(k, 0, shape[0] if shape else 0)]
        return [(f"{k}#chunk{i}", start, min(start + rows_per, shape[0]))
                for i, start in enumerate(range(0, shape[0], rows_per))]

    @classmethod
    def _chunk_layout(cls, k, shape):
        """Row-chunk plan for a big dense array under derived keys
        (parity: kvstore_dist.h big-array key sharding over servers,
        MXNET_KVSTORE_BIGARRAY_BOUND). Bounds the wire frame size and
        lets chunk pushes pipeline through the server. Returns
        [(key, row_start, row_stop)] — a single entry means unchunked."""
        from .config import get as _cfg
        import numpy as np
        bound = _cfg("MXNET_KVSTORE_BIGARRAY_BOUND")
        size = int(np.prod(shape)) if shape else 1
        if size <= bound or not shape or shape[0] < 2:
            return cls._layout_from_rows_per(k, shape, 0)
        rows_per = max(int(bound // max(size // shape[0], 1)), 1)
        return cls._layout_from_rows_per(k, shape, rows_per)

    def init(self, key, value):
        if self._client is None:
            return super().init(key, value)
        import numpy as np
        keys, values = _key_value(key, value)
        batch = []  # one init_many RPC for all keys + layout records
        for k, v in zip(keys, values):
            self._store[k] = v.copy()
            # the chunk decision is made ONCE here and remembered: every
            # later access (push/pull/row_sparse/compressed) must agree on
            # the server key namespace. Compression writes whole keys, so
            # a compressed store never chunks.
            if self._compression is None:
                layout = self._chunk_layout(k, tuple(v.shape))
            else:
                layout = [(k, 0, v.shape[0] if v.shape else 0)]
            self._chunked[k] = layout if len(layout) > 1 else None
            if self._rank == 0:
                # record the chosen layout server-side: workers launched
                # with a different MXNET_KVSTORE_BIGARRAY_BOUND would
                # otherwise address a divergent k vs k#chunkN namespace and
                # deadlock dist_sync push aggregation with no diagnostic.
                rows_per = (layout[0][2] - layout[0][1]
                            if self._chunked[k] is not None else 0)
                batch.append((f"__layout__{k}",
                              np.array([rows_per], dtype=np.int64)))
                if self._chunked[k] is None:
                    batch.append((k, v.asnumpy()))
                else:
                    arr = v.asnumpy()
                    batch.extend((ck, arr[b:e]) for ck, b, e in layout)
        if batch:
            self._client.init_many(batch)
        self._client.barrier()
        if self._rank != 0 and keys:
            # adopt rank 0's layout so every worker agrees on the namespace
            recs = self._client.pull_many(
                [f"__layout__{k}" for k in keys])
            for k, rec in zip(keys, recs):
                layout = self._layout_from_rows_per(
                    k, tuple(self._store[k].shape), int(rec[0]))
                self._chunked[k] = layout if len(layout) > 1 else None

    def attach(self, key, value):
        """Adopt already-initialized server state for ``key`` WITHOUT the
        init barrier — the elastic-resume path.

        ``init`` ends in a full-group barrier, which can never complete
        for a replacement worker joining after its peers initialized (or
        exited): the round-5 failure-recovery contract (kvstore.h:353
        dead-node surfacing) needs rejoining workers to come up solo.
        ``value`` supplies only the shape/dtype for the local layout
        record; the live weights stay whatever the server holds.
        """
        if self._client is None:
            return super().init(key, value)
        keys, values = _key_value(key, value)
        for k, v in zip(keys, values):
            self._store[k] = v.copy()
            rec = self._client.pull_many([f"__layout__{k}"])[0]
            layout = self._layout_from_rows_per(
                k, tuple(v.shape), int(rec[0]))
            self._chunked[k] = layout if len(layout) > 1 else None

    def push(self, key, value, priority=0):
        if self._client is None:
            return super().push(key, value, priority)
        from .ndarray import sparse as _sp
        keys, values = _key_grouped(key, value)
        _account_wire("push", values)
        sync = self._type in ("dist_sync", "dist_device_sync")
        for k, vlist in zip(keys, values):
            if any(isinstance(v, _sp.BaseSparseNDArray) for v in vlist):
                if getattr(self, "_compression", None) is not None:
                    raise MXNetError(
                        "gradient compression does not support row_sparse "
                        "pushes (reference kvstore_dist parity)")
                merged = vlist[0]
                for v in vlist[1:]:
                    merged = _sp.elemwise_add(merged, v)
                import numpy as np
                idx = np.asarray(merged._indices).astype(np.int64)
                vals = np.asarray(merged._data)
                layout = self._chunked.get(k)
                if layout is None:
                    self._client.push_rs(k, idx, vals,
                                         tuple(merged.shape), sync=sync)
                else:
                    # chunked key: split rows by chunk range; EVERY chunk
                    # gets a (possibly empty) push so sync aggregation
                    # counts line up across workers
                    for ck, b, e in layout:
                        m = (idx >= b) & (idx < e)
                        self._client.push_rs(
                            ck, idx[m] - b, vals[m],
                            (e - b,) + tuple(merged.shape[1:]), sync=sync)
                continue
            merged = vlist[0] if len(vlist) == 1 else nd.add_n(
                *[v.as_in_context(vlist[0].ctx) for v in vlist])
            gc = getattr(self, "_compression", None)
            if gc is not None:
                # 2-bit codes + error-feedback residual on this worker
                # (parity: KVStoreDist::PushCompressed)
                self._check_not_chunked(k, "compressed push")
                self._client.push_compressed(
                    k, gc.encode_push(k, merged.asnumpy()), sync=sync)
            else:
                layout = self._chunked.get(k)
                if layout is None:
                    self._client.push(k, merged.asnumpy(), sync=sync)  # graftlint: disable=per-param-collective -- one wire frame per key is the multi-worker protocol; big keys batch via push_many, and mesh-fusible setups bypass this loop entirely
                else:  # pipelined chunk pushes: one in-flight window
                    arr = merged.asnumpy()
                    self._client.push_many(
                        [(ck, arr[b:e]) for ck, b, e in layout], sync=sync)

    def _check_not_chunked(self, k, what):
        if self._chunked.get(k) is not None:
            raise MXNetError(
                f"{what} on key {k!r} is incompatible with big-array "
                "chunking (array exceeds MXNET_KVSTORE_BIGARRAY_BOUND "
                "elements); raise the bound for this key's workflow, or "
                "enable compression before init")

    def _fetch_rows(self, k, stored, rows):
        # only the requested rows cross the wire (kvstore_dist.h:243);
        # on a chunked key each chunk serves its own row range
        if self._client is None:
            return super()._fetch_rows(k, stored, rows)
        import numpy as np
        import jax.numpy as jnp
        rows_np = np.asarray(rows).astype(np.int64)
        layout = self._chunked.get(k)
        if layout is None:
            return jnp.asarray(self._client.pull_rows(k, rows_np))
        out = np.empty((len(rows_np),) + tuple(stored.shape[1:]),
                       np.dtype(str(stored.dtype)))
        for ck, b, e in layout:
            m = (rows_np >= b) & (rows_np < e)
            if not m.any():
                continue
            out[m] = self._client.pull_rows(ck, rows_np[m] - b)
        return jnp.asarray(out)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if self._client is None:
            return super().pull(key, out, priority, ignore_sparse)
        import numpy as np
        keys, outs = _key_grouped(key, out)
        _account_wire("pull", outs)
        for k, olist in zip(keys, outs):
            layout = self._chunked.get(k)
            if layout is None:
                arr = self._client.pull(k)  # graftlint: disable=per-param-collective -- one wire frame per key is the multi-worker protocol; chunked keys batch via pull_many
            else:  # big array: pipelined chunk pulls, reassembled
                parts = self._client.pull_many([ck for ck, _b, _e in layout])
                arr = np.concatenate(parts, axis=0)
            for o in olist:
                o[:] = arr

    def set_optimizer(self, optimizer):
        if self._client is None:
            return super().set_optimizer(optimizer)
        if self._rank == 0:
            self._client.send_command("set_optimizer",
                                      pickle.dumps(optimizer))
        self._client.barrier()

    def get_optimizer_states(self, dump_optimizer=False):
        """Dist resume: fetch the SERVER-side optimizer state (that is
        where update_on_kvstore ran the updater), so a rank-0 checkpoint
        can capture momentum/Adam state that never existed worker-side."""
        if self._client is None:
            return super().get_optimizer_states(dump_optimizer)
        resp = self._client.command("get_optimizer_states",
                                    pickle.dumps(bool(dump_optimizer)))
        return resp["value"]

    def set_optimizer_states(self, states):
        """Dist resume: install checkpointed optimizer state into the
        live server (requires set_optimizer to have run there)."""
        if self._client is None:
            return super().set_optimizer_states(states)
        self._client.command("set_optimizer_states", states)

    def _send_command_to_servers(self, head, body):
        """Generic server command (parity: KVStore::SendCommandToServers,
        include/mxnet/kvstore.h:377; carries e.g. the profiler commands —
        see profiler.set_kvstore_handle)."""
        if self._client is not None:
            self._client.send_command(head, body)

    def get_num_dead_node(self, node_id=0, timeout=60):
        """Number of workers whose heartbeats stopped (parity:
        KVStore::get_num_dead_node, include/mxnet/kvstore.h:353)."""
        if self._client is None:
            return 0
        return self._client.num_dead_node(timeout)

    def barrier(self):
        if self._client is not None:
            self._client.barrier()
        nd.waitall()


def _updater_key(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def _key_value(key, value):
    if isinstance(key, (str, int)):
        if isinstance(value, (list, tuple)):
            # init with one value per key is the contract; a list for a
            # single key means per-device copies — take the first
            return [key], [value[0]]
        return [key], [value]
    assert isinstance(value, (list, tuple)) and len(key) == len(value)
    return list(key), list(value)


def _key_grouped(key, value):
    """Normalize (key(s), value(s)) to (keys, list-of-lists)."""
    if isinstance(key, (str, int)):
        if isinstance(value, NDArray):
            return [key], [[value]]
        return [key], [list(value)]
    out_keys, out_vals = [], []
    n_per = len(value) // len(key)
    for i, k in enumerate(key):
        v = value[i]
        if isinstance(v, NDArray):
            out_vals.append([v])
        else:
            out_vals.append(list(v))
        out_keys.append(k)
    return out_keys, out_vals


def create(name="local"):
    """Create a KVStore (parity: kvstore.py create / factory
    src/kvstore/kvstore.cc:48-64)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if name in ("local", "local_update_cpu", "local_allreduce_cpu",
                "local_allreduce_device", "device", "nccl"):
        return KVStore("device" if name in ("device", "nccl") else "local")
    if name == "ici":
        return KVStoreICI()
    if name.startswith("dist"):
        return KVStoreDist(name)
    raise MXNetError(f"unknown KVStore type {name!r}")
