"""Weight initializers.

Re-design of reference python/mxnet/initializer.py (758 LoC): registry of
named initializers applied by parameter-name pattern. Initialization here is
pure — each initializer produces a jax array via the framework RNG, so a
seeded init is reproducible across hosts (important for SPMD: every host
computes identical initial weights without a broadcast).
"""
from __future__ import annotations

import json
import math
import re

import numpy as np

from . import ndarray as nd
from .base import MXNetError
from .registry import get_register_func, get_alias_func, get_create_func

_INITIALIZER_REGISTRY = {}


class InitDesc(str):
    """Parameter name + attrs hint passed to initializers
    (parity: initializer.py InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base class. Callable on (InitDesc|str, NDArray)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func or (lambda x: None)
        return self

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("desc must be str or InitDesc")
        if isinstance(desc, InitDesc) and desc.global_init is None:
            desc.global_init = self
        init = getattr(desc, "attrs", {}).get("__init__", "")
        if init:
            create(init)._init_weight(desc, arr)
        else:
            name = str(desc)
            if name.endswith("weight"):
                self._init_weight(name, arr)
            elif name.endswith("bias"):
                self._init_bias(name, arr)
            elif name.endswith("gamma"):
                self._init_gamma(name, arr)
            elif name.endswith("beta"):
                self._init_beta(name, arr)
            elif name.endswith("running_mean") or name.endswith("moving_mean"):
                self._init_zero(name, arr)
            elif name.endswith("running_var") or name.endswith("moving_var"):
                self._init_one(name, arr)
            elif name.endswith("moving_inv_var") or name.endswith("moving_avg"):
                self._init_zero(name, arr)
            elif name.endswith("min") or name.endswith("max"):
                self._init_zero(name, arr)
            else:
                self._init_default(name, arr)
        if self._verbose and self._print_func:
            self._print_func(f"Initialized {desc}")

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_weight(self, name, arr):
        raise NotImplementedError()

    def _init_default(self, name, arr):
        raise MXNetError(
            f"Unknown policy for parameter {name!r}: MXNet-convention names "
            "(*_weight/_bias/_gamma/_beta/...) get default policies; others "
            "need an explicit init")

    def __repr__(self):
        return f"{self.__class__.__name__}({self._kwargs})"


register = get_register_func(Initializer, "initializer", _INITIALIZER_REGISTRY)
alias = get_alias_func(Initializer, "initializer", _INITIALIZER_REGISTRY)
create = get_create_func(Initializer, "initializer", _INITIALIZER_REGISTRY)


@register
@alias("zeros")
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0


@register
@alias("ones")
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value


@register
class Uniform(Initializer):
    """U(-scale, scale) (parity: initializer.py Uniform)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        arr[:] = nd.random.uniform(-self.scale, self.scale, arr.shape,
                                   dtype=arr.dtype, ctx=arr.ctx)


@register
class Normal(Initializer):
    """N(0, sigma^2)."""

    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        arr[:] = nd.random.normal(0, self.sigma, arr.shape,
                                  dtype=arr.dtype, ctx=arr.ctx)


@register
class Orthogonal(Initializer):
    """Orthogonal matrix init (parity: initializer.py Orthogonal; Saxe et al. 2013)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:])) if len(arr.shape) > 1 else 1
        if self.rand_type == "uniform":
            tmp = nd.random.uniform(-1.0, 1.0, (nout, nin)).asnumpy()
        else:
            tmp = nd.random.normal(0.0, 1.0, (nout, nin)).asnumpy()
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = self.scale * q.reshape(arr.shape)


@register
class Xavier(Initializer):
    """Glorot init, uniform/gaussian, avg/in/out fan (parity: initializer.py Xavier)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        if len(shape) < 2:
            raise MXNetError(f"Xavier requires ndim>=2, got {shape} for {name}")
        hw_scale = float(np.prod(shape[2:])) if len(shape) > 2 else 1.0
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError("Incorrect factor type")
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = nd.random.uniform(-scale, scale, shape, dtype=arr.dtype,
                                       ctx=arr.ctx)
        elif self.rnd_type == "gaussian":
            arr[:] = nd.random.normal(0, scale, shape, dtype=arr.dtype,
                                      ctx=arr.ctx)
        else:
            raise MXNetError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    """He init for PReLU nets (parity: initializer.py MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (for Deconvolution upsampling)."""

    def _init_weight(self, _, arr):
        weight = np.zeros(arr.shape, dtype="float32")
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.ravel()[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight


@register
class LSTMBias(Initializer):
    """Forget-gate bias = forget_bias, others 0 (parity: initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        arr[:] = 0.0
        num_hidden = int(arr.shape[0] / 4)
        arr[num_hidden:2 * num_hidden] = self.forget_bias


@register
class FusedRNN(Initializer):
    """Initialize fused RNN parameter blobs by unpacking per-gate inits."""

    def __init__(self, init, num_hidden, num_layers, mode, bidirectional=False,
                 forget_bias=1.0):
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = _INITIALIZER_REGISTRY[klass.lower()](**kwargs)
        super().__init__(init=init.dumps() if init else None,
                         num_hidden=num_hidden, num_layers=num_layers,
                         mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):  # simple policy: treat as one blob
        if self._init is not None:
            self._init._init_weight(desc, arr)
        else:
            Uniform()._init_weight(desc, arr)


class Load:
    """Initialize by copying from a dict of arrays (parity: initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {k[4:] if k.startswith(("arg:", "aux:")) else k: v
                      for k, v in param.items()}
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            src = self.param[name]
            if src.shape != arr.shape:
                raise MXNetError(f"Parameter {name} shape mismatch: "
                                 f"{src.shape} vs {arr.shape}")
            arr[:] = src
        else:
            if self.default_init is None:
                raise MXNetError(f"Cannot init parameter {name}: not found "
                                 "in loaded params and no default_init")
            self.default_init(name, arr)


class Mixed:
    """Patterns → initializers; first match wins (parity: initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers must have same length")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError(f"Parameter {name} did not match any pattern; "
                         'add a ".*" catch-all')
