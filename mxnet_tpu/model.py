"""Model checkpoint helpers + legacy FeedForward
(parity: python/mxnet/model.py — save_checkpoint:394, load_checkpoint:426,
BatchEndParam, kvstore helpers _create_kvstore:82)."""
from __future__ import annotations

import collections
import logging

from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError

BatchEndParam = collections.namedtuple(
    "BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Save prefix-symbol.json + prefix-NNNN.params
    (parity: model.py:394; format-compatible with the reference)."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{name}": v for name, v in arg_params.items()}
    save_dict.update({f"aux:{name}": v for name, v in aux_params.items()})
    param_name = f"{prefix}-{epoch:04d}.params"
    nd.save(param_name, save_dict)
    logging.info('Saved checkpoint to "%s"', param_name)


def load_params(prefix, epoch):
    """Load params file into (arg_params, aux_params)."""
    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """Load symbol + params (parity: model.py:426)."""
    symbol = sym_mod.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


def _create_kvstore(kvstore, num_device, arg_params):
    """Create kvstore from spec (parity: model.py:82)."""
    from . import kvstore as kvs_mod
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs_mod.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs_mod.create(kvstore)
            if kvstore == "local":
                max_size = max(v.size for v in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """Initialize kvstore (parity: model.py:121)."""
    for idx, param_on_devs in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore,
                              param_names):
    """Push grads, pull updated weights (parity: model.py:150).

    All pushes are issued BEFORE any pull: a dist pull blocks until every
    worker's push for that key arrived, so interleaving push/pull per key
    would serialize the sync round key by key across the cluster."""
    live = []
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        name = param_names[index]
        kvstore.push(name, grad_list, priority=-index)  # graftlint: disable=per-param-collective -- the RESIDUAL per-param dist path: mesh-ineligible setups and real multi-worker clients; eligible fits route through parallel/fused.MeshFusedTrainStep (docs/parallel.md)
        live.append((index, name, arg_list))
    for index, name, arg_list in live:
        kvstore.pull(name, arg_list, priority=-index)  # graftlint: disable=per-param-collective -- residual per-param dist path (see push above)


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None, param_names=None):
    """Local update path (parity: model.py _update_params)."""
    updates = [[] for _ in range(num_device)]
    for i, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        index = i
        if kvstore:
            name = param_names[index]
            kvstore.push(name, grad_list, priority=-index)  # graftlint: disable=per-param-collective -- legacy FeedForward local-aggregation path, kept for API parity
            kvstore.pull(name, grad_list, priority=-index)  # graftlint: disable=per-param-collective -- legacy FeedForward local-aggregation path, kept for API parity
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updates[k].append((index * num_device + k, g, w))
    for dev_updates in updates:
        for i, g, w in dev_updates:
            updater(i, g, w)


class FeedForward:
    """Legacy model API (parity: model.py FeedForward) — thin adapter over
    Module; kept for source compatibility."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .initializer import Uniform
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer or Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self.kwargs = kwargs
        self._module = None

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        from . import io as mx_io
        from .module import Module
        if not isinstance(X, mx_io.DataIter):
            X = mx_io.NDArrayIter(X, y, self.numpy_batch_size)
        label_names = [n for n in self.symbol.list_arguments()
                       if n.endswith("label")] or ["softmax_label"]
        data_names = [X.provide_data[0].name]
        self._module = Module(self.symbol, data_names=data_names,
                              label_names=label_names, context=self.ctx)
        self._module.fit(X, eval_data=eval_data, eval_metric=eval_metric,
                         epoch_end_callback=epoch_end_callback,
                         batch_end_callback=batch_end_callback,
                         kvstore=kvstore, optimizer=self.optimizer,
                         optimizer_params=self.kwargs,
                         initializer=self.initializer,
                         arg_params=self.arg_params,
                         aux_params=self.aux_params,
                         begin_epoch=self.begin_epoch,
                         num_epoch=self.num_epoch)
        self.arg_params, self.aux_params = self._module.get_params()

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        from . import io as mx_io
        if not isinstance(X, mx_io.DataIter):
            X = mx_io.NDArrayIter(X, None, self.numpy_batch_size)
        return self._module.predict(X, num_batch=num_batch,
                                    reset=reset).asnumpy()

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params,
                        self.aux_params)

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)
