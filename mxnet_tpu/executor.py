"""mx.executor — Executor re-export (parity: python/mxnet/executor.py)."""
from .symbol.executor import Executor  # noqa: F401
