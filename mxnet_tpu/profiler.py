"""Profiler (parity: python/mxnet/profiler.py + src/profiler/profiler.h:260).

The reference writes chrome://tracing JSON from an in-engine profiler with
device/engine lanes + an aggregate stats table. TPU redesign: the heavy
lifting is jax.profiler (XLA xplane → TensorBoard/perfetto); this module
keeps the mx.profiler API surface (set_config/start/stop/dump/dumps) and
adds a lightweight host-side op-dispatch recorder producing the same
chrome-trace JSON + aggregate table the reference emits.
"""
from __future__ import annotations

import json
import os
import threading
import time

from .base import MXNetError

_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "aggregate_stats": False,
    # block after each profiled op so durations include device execution
    # (reference per-opr profiling also serialises the engine)
    "profile_device_sync": True,
}
_state = {"running": False, "jax_trace_dir": None}
_records = []
_records_lock = threading.Lock()
_t0 = None

KWARGS = _config  # parity alias


def set_config(**kwargs):
    """Configure the profiler (parity: profiler.py set_config)."""
    for k, v in kwargs.items():
        if k in _config:
            _config[k] = v
        elif k in ("continuous_dump", "dump_period", "profile_process"):
            pass  # accepted for API parity
        else:
            raise MXNetError(f"unknown profiler option {k}")


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """Deprecated API (parity: profiler.py profiler_set_config)."""
    set_config(filename=filename)


def set_state(state="stop", profile_process="worker"):
    if state == "run":
        start()
    else:
        stop()


def start(profile_process="worker"):
    """Start profiling (parity: profiler.py start). Also starts a JAX/XLA
    device trace when a directory is configured via MXNET_PROFILER_XPLANE_DIR."""
    global _t0
    _t0 = time.perf_counter()
    _state["running"] = True
    xdir = os.environ.get("MXNET_PROFILER_XPLANE_DIR")
    if xdir:
        import jax
        jax.profiler.start_trace(xdir)
        _state["jax_trace_dir"] = xdir


def stop(profile_process="worker"):
    """Stop profiling."""
    _state["running"] = False
    if _state["jax_trace_dir"]:
        import jax
        jax.profiler.stop_trace()
        _state["jax_trace_dir"] = None


def is_running():
    return _state["running"]


def _reset_after_fork():
    """Clear per-process profiling state in a forked child (called by
    initialize.py's at-fork handler): the child must not append to the
    parent's trace buffers or try to stop the parent's jax trace."""
    _state["running"] = False
    _state["jax_trace_dir"] = None
    with _records_lock:
        _records.clear()


def device_sync_enabled():
    return _config["profile_device_sync"]


def record_synced(name, t0, arrays):
    """Block on ``arrays`` (when device-sync profiling is on) and record
    the op with duration measured from ``t0``.  Errors re-surface at the
    user's sync point as MXNetError, not here."""
    import time as _time
    if _config["profile_device_sync"]:
        try:
            import jax
            jax.block_until_ready(
                [a for a in arrays
                 if not isinstance(a, jax.core.Tracer)])
        except Exception:
            pass
    record_op(name, (_time.perf_counter() - t0) * 1e6)


def record_op(name, dur_us, cat="operator"):
    """Internal hook: record one op dispatch (called from ndarray.invoke
    when profiling is on)."""
    if not _state["running"]:
        return
    with _records_lock:
        _records.append({
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": (time.perf_counter() - _t0) * 1e6 - dur_us,
            "dur": dur_us,
            "pid": os.getpid(),
            "tid": threading.get_ident() % 100000,
        })


def pause(profile_process="worker"):
    _state["running"] = False


def resume(profile_process="worker"):
    _state["running"] = True


def dump(finished=True, profile_process="worker"):
    """Write chrome://tracing JSON (parity: profiler.py dump →
    profile.json format of src/profiler/profiler.h:460)."""
    with _records_lock:
        events = list(_records)
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(_config["filename"], "w") as f:
        json.dump(doc, f)


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Return aggregate stats as an ASCII table
    (parity: profiler.py dumps → aggregate_stats.cc table)."""
    with _records_lock:
        events = list(_records)
        if reset:
            _records.clear()
    agg = {}
    for e in events:
        st = agg.setdefault(e["name"], [0, 0.0, float("inf"), 0.0])
        st[0] += 1
        st[1] += e["dur"]
        st[2] = min(st[2], e["dur"])
        st[3] = max(st[3], e["dur"])
    lines = ["Profile Statistics:",
             f"{'Name':<40}{'Total Count':>12}{'Time (ms)':>14}"
             f"{'Min (ms)':>12}{'Max (ms)':>12}{'Avg (ms)':>12}"]
    items = sorted(agg.items(),
                   key=lambda kv: kv[1][1] if sort_by == "total" else kv[1][0],
                   reverse=not ascending)
    for name, (cnt, tot, mn, mx) in items:
        lines.append(f"{name:<40}{cnt:>12}{tot/1e3:>14.4f}"
                     f"{mn/1e3:>12.4f}{mx/1e3:>12.4f}{tot/cnt/1e3:>12.4f}")
    return "\n".join(lines)


class Profiler:
    """Context-manager convenience."""

    def __init__(self, **kwargs):
        set_config(**kwargs)

    def __enter__(self):
        start()
        return self

    def __exit__(self, *args):
        stop()


# -- scoped domains / tasks / frames / markers (API parity) ------------------
class Domain:
    def __init__(self, name):
        self.name = name

    def __str__(self):
        return self.name

    def new_task(self, name):
        return Task(self, name)

    def new_frame(self, name):
        return Frame(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class _Span:
    def __init__(self, domain, name):
        self.name = name
        self.domain = domain
        self._start = None

    def start(self):
        self._start = time.perf_counter()

    def stop(self):
        if self._start is not None and _state["running"]:
            dur_us = (time.perf_counter() - self._start) * 1e6
            record_op(f"{self.domain}:{self.name}", dur_us, cat="task")
        self._start = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *args):
        self.stop()


class Task(_Span):
    pass


class Frame(_Span):
    pass


class Event(_Span):
    def __init__(self, name):
        super().__init__("event", name)


class Counter:
    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self.value = value or 0

    def set_value(self, value):
        self.value = value

    def increment(self, delta=1):
        self.value += delta

    def decrement(self, delta=1):
        self.value -= delta

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self


class Marker:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        record_op(f"{self.domain}:{self.name}", 0, cat="marker")
