"""Profiler (parity: python/mxnet/profiler.py + src/profiler/profiler.h:260).

The reference writes chrome://tracing JSON from an in-engine profiler with
device/engine lanes + an aggregate stats table. TPU redesign: the heavy
lifting is jax.profiler (XLA xplane → TensorBoard/perfetto); this module
keeps the mx.profiler API surface (set_config/start/stop/dump/dumps) and
adds a lightweight host-side op-dispatch recorder producing the same
chrome-trace JSON + aggregate table the reference emits.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time

from .base import MXNetError

_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "aggregate_stats": False,
    # block after each profiled op so durations include device execution
    # (reference per-opr profiling also serialises the engine)
    "profile_device_sync": True,
    "continuous_dump": False,
    "dump_period": 1.0,
}
_state = {"running": False, "jax_trace_dir": None, "dump_timer": None,
          "kvstore": None, "last_mem_sample": 0.0}
_records = []
_records_lock = threading.Lock()
_last_counters = {}
_t0 = None

KWARGS = _config  # parity alias


def set_config(**kwargs):
    """Configure the profiler (parity: profiler.py set_config). Forwards
    to the kvstore servers too once ``set_kvstore_handle`` was called
    (reference KVStoreServerProfilerCommand::kSetConfig)."""
    for k, v in kwargs.items():
        if k in _config:
            _config[k] = v
        elif k in ("profile_process",):
            pass  # accepted for API parity
        else:
            raise MXNetError(f"unknown profiler option {k}")
    _forward_to_server("profiler_set_config", kwargs)


def set_kvstore_handle(kv):
    """Route subsequent profiler set_config/set_state/dump calls to the
    dist kvstore servers as well (parity: reference profiler.py
    set_kvstore_handle + KVStoreServerProfilerCommand,
    include/mxnet/kvstore.h:49)."""
    _state["kvstore"] = kv


def _forward_to_server(head, payload):
    kv = _state["kvstore"]
    if kv is None:
        return
    try:
        import pickle
        kv._send_command_to_servers(head, pickle.dumps(payload))
    except Exception as e:  # noqa: BLE001 — best-effort forwarding
        logging.getLogger("mxnet_tpu.profiler").debug(
            "server-side profiler command %r dropped: %s", head, e)


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """Deprecated API (parity: profiler.py profiler_set_config)."""
    set_config(filename=filename)


def set_state(state="stop", profile_process="worker"):
    if state == "run":
        start()
    else:
        stop()


def start(profile_process="worker"):
    """Start profiling (parity: profiler.py start). Also starts a JAX/XLA
    device trace when a directory is configured via MXNET_PROFILER_XPLANE_DIR."""
    global _t0
    _t0 = time.perf_counter()
    _state["running"] = True
    _state["dump_deadline"] = None  # re-anchor the continuous-dump grid
    xdir = os.environ.get("MXNET_PROFILER_XPLANE_DIR")
    if xdir:
        import jax
        jax.profiler.start_trace(xdir)
        _state["jax_trace_dir"] = xdir
    if _config["continuous_dump"]:
        _schedule_dump()
    _forward_to_server("profiler_set_state", "run")


def _next_dump_deadline(deadline, period, now):
    """The next monotonic dump deadline: ``deadline + period`` normally;
    when a dump overran one or more whole periods, realign to the
    original grid without firing a catch-up burst."""
    nxt = deadline + period
    if nxt <= now:
        nxt = now + period - ((now - deadline) % period)
    return nxt


def _schedule_dump():
    """Background periodic dump (reference continuous_dump/dump_period).

    Each timer re-arms from a MONOTONIC deadline carried in
    ``_state["dump_deadline"]`` — the old ``Timer(period)``-after-dump
    scheme added every dump's own write time to the cadence, so a 50 ms
    dump on a 1 s period drifted ~3 min/hour."""
    t = _state.get("dump_timer")
    if t is not None:
        t.cancel()
    now = time.monotonic()
    if _state.get("dump_deadline") is None:
        _state["dump_deadline"] = now + float(_config["dump_period"])

    def tick():
        if not _state["running"]:
            return
        try:
            dump(finished=False)
        except Exception as e:  # noqa: BLE001 — keep the timer alive
            logging.getLogger("mxnet_tpu.profiler").warning(
                "continuous profiler dump failed: %s", e)
        _state["dump_deadline"] = _next_dump_deadline(
            _state["dump_deadline"], float(_config["dump_period"]),
            time.monotonic())
        _arm()

    def _arm():
        delay = max(0.0, _state["dump_deadline"] - time.monotonic())
        timer = threading.Timer(delay, tick)
        timer.daemon = True
        timer.start()
        _state["dump_timer"] = timer

    _arm()


def stop(profile_process="worker"):
    """Stop profiling."""
    _state["running"] = False
    t = _state.get("dump_timer")
    if t is not None:
        t.cancel()
        _state["dump_timer"] = None
    _state["dump_deadline"] = None
    if _state["jax_trace_dir"]:
        import jax
        jax.profiler.stop_trace()
        _state["jax_trace_dir"] = None
    _forward_to_server("profiler_set_state", "stop")


def is_running():
    return _state["running"]


def jax_trace_dir():
    """Directory of the live jax xplane trace (None when no device trace
    is running) — telemetry spans mirror themselves into it."""
    return _state["jax_trace_dir"]


def _reset_after_fork():
    """Clear per-process profiling state in a forked child (called by
    initialize.py's at-fork handler): the child must not append to the
    parent's trace buffers or try to stop the parent's jax trace."""
    _state["running"] = False
    _state["jax_trace_dir"] = None
    with _records_lock:
        _records.clear()
        _dispatch_counts.clear()


def device_sync_enabled():
    return _config["profile_device_sync"]


def record_synced(name, t0, arrays):
    """Block on ``arrays`` (when device-sync profiling is on) and record
    the op with duration measured from ``t0``.  Errors re-surface at the
    user's sync point as MXNetError, not here."""
    import time as _time
    if _config["profile_device_sync"]:
        try:
            import jax
            jax.block_until_ready(
                [a for a in arrays
                 if not isinstance(a, jax.core.Tracer)])
        except Exception:
            pass
    record_op(name, (_time.perf_counter() - t0) * 1e6)


def record_op(name, dur_us, cat="operator"):
    """Internal hook: record one op dispatch (called from ndarray.invoke
    when profiling is on)."""
    if not _state["running"]:
        return
    with _records_lock:
        _records.append({
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": (time.perf_counter() - _t0) * 1e6 - dur_us,
            "dur": dur_us,
            "pid": os.getpid(),
            "tid": threading.get_ident() % 100000,
        })
    if _config["profile_memory"]:
        _sample_device_memory()


def record_counter(name, value, args_key="value"):
    """Append one counter-lane sample ("C" event) to the trace (parity:
    the reference profiler's counter lanes, src/profiler/profiler.h
    ProfileCounter).  Module-level entry point so subsystems (serving
    metrics, checkpoint, storage, …) can emit counters without holding a
    Domain/Counter object.  The last value per counter is always kept
    (``last_counters()``) so bench/monitoring can read e.g.
    ``checkpoint:save_blocking_ms`` without a running trace; trace
    events are only appended while the profiler runs."""
    with _records_lock:
        _last_counters[name] = value
    if not _state["running"]:
        return
    with _records_lock:
        _records.append({
            "name": name, "cat": "counter", "ph": "C",
            "ts": (time.perf_counter() - _t0) * 1e6,
            "pid": os.getpid(), "args": {args_key: value},
        })


_dispatch_counts = {}


def record_dispatch(kind="op"):
    """Count one framework-issued XLA computation launch (an eager op
    ``invoke``, a compiled executor forward/backward, a fused train
    step).  Unlike trace events these are counted even while the
    profiler is stopped, so bench/CI can measure dispatches-per-step
    (docs/perf_notes.md "dispatch overhead") without arming a trace.
    Host<->device transfers are deliberately NOT counted — they overlap
    compute under PJRT; this lane measures computation launches."""
    with _records_lock:
        _dispatch_counts[kind] = _dispatch_counts.get(kind, 0) + 1
        _dispatch_counts["total"] = _dispatch_counts.get("total", 0) + 1


def dispatch_counts():
    """Snapshot of launch counts by kind plus a running ``total``."""
    with _records_lock:
        return dict(_dispatch_counts)


def reset_dispatch_counts():
    with _records_lock:
        _dispatch_counts.clear()


def last_counters():
    """Snapshot of the most recent value of every counter ever recorded
    (e.g. ``checkpoint:save_blocking_ms``, ``serving:*``) — maintained
    even while the profiler is stopped, so save-latency/bytes lanes are
    observable without arming a trace."""
    with _records_lock:
        return dict(_last_counters)


def record_api(name, dur_us=0.0):
    """Record a frontend/API event (waitall, asnumpy, bind, …) when
    profile_api is on (parity: the reference's MXAPIThreadLocal API-call
    profiling under profile_api, src/c_api/c_api_profile.cc)."""
    if _config["profile_api"] or _config["profile_all"]:
        record_op(name, dur_us, cat="api")


_MEM_SAMPLE_PERIOD_S = 0.01  # at most 100 samples/s — PJRT stats aren't free


def _sample_device_memory():
    """Append a chrome-trace counter sample of device bytes in use
    (parity: the reference memory profiler, src/profiler/storage_profiler.h,
    rendered as a counter lane). Throttled; silently skipped when the
    backend exposes no allocator stats."""
    now = time.perf_counter()
    if now - _state["last_mem_sample"] < _MEM_SAMPLE_PERIOD_S:
        return
    _state["last_mem_sample"] = now
    try:
        from .context import device_memory_info
        info = device_memory_info()
        used = int(info.get("bytes_in_use", 0))
    except Exception:
        return
    with _records_lock:
        _records.append({
            "name": "device_memory",
            "cat": "memory",
            "ph": "C",
            "ts": (now - _t0) * 1e6,
            "pid": os.getpid(),
            "args": {"bytes_in_use": used},
        })


def pause(profile_process="worker"):
    _state["running"] = False
    t = _state.get("dump_timer")
    if t is not None:
        t.cancel()
        _state["dump_timer"] = None
    _state["dump_deadline"] = None


def resume(profile_process="worker"):
    _state["running"] = True
    if _config["continuous_dump"]:
        _schedule_dump()


def dump(finished=True, profile_process="worker"):
    """Write chrome://tracing JSON (parity: profiler.py dump →
    profile.json format of src/profiler/profiler.h:460)."""
    with _records_lock:
        events = list(_records)
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    # atomic temp + os.replace: the continuous-dump timer rewrites this
    # file periodically — chrome://tracing must never load a torn JSON
    fname = _config["filename"]
    tmp = f"{fname}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, fname)
    _forward_to_server("profiler_dump", bool(finished))


def dumps(reset=False, format="table", sort_by="total", ascending=False,
          aggregate=False):
    """Return aggregate stats as an ASCII table, or a dict when
    format="json" (parity: profiler.py dumps → aggregate_stats.cc table
    and json dump modes).  ``aggregate=True`` additionally folds the
    dispatch-count lanes (``record_dispatch``) into the output — without
    it only per-op duration rows make the table, so launches-per-step
    was invisible in the very output meant to summarize the trace
    (json: under the ``"dispatch_counts"`` key; table: a trailing
    "Dispatch Counts" section)."""
    with _records_lock:
        events = list(_records)
        if reset:
            _records.clear()
    counts = dispatch_counts() if aggregate else {}
    agg = {}
    for e in events:
        if e.get("ph") != "X":
            continue  # counter/memory samples have no duration
        st = agg.setdefault(e["name"], [0, 0.0, float("inf"), 0.0])
        st[0] += 1
        st[1] += e["dur"]
        st[2] = min(st[2], e["dur"])
        st[3] = max(st[3], e["dur"])
    if format == "json":
        out = {name: {"count": c, "total_ms": t / 1e3, "min_ms": mn / 1e3,
                      "max_ms": mx / 1e3, "avg_ms": t / c / 1e3}
               for name, (c, t, mn, mx) in agg.items()}
        if counts:
            out["dispatch_counts"] = counts
        return out
    lines = ["Profile Statistics:",
             f"{'Name':<40}{'Total Count':>12}{'Time (ms)':>14}"
             f"{'Min (ms)':>12}{'Max (ms)':>12}{'Avg (ms)':>12}"]
    items = sorted(agg.items(),
                   key=lambda kv: kv[1][1] if sort_by == "total" else kv[1][0],
                   reverse=not ascending)
    for name, (cnt, tot, mn, mx) in items:
        lines.append(f"{name:<40}{cnt:>12}{tot/1e3:>14.4f}"
                     f"{mn/1e3:>12.4f}{mx/1e3:>12.4f}{tot/cnt/1e3:>12.4f}")
    if counts:
        lines.append("")
        lines.append("Dispatch Counts:")
        lines.append(f"{'Kind':<40}{'Count':>12}")
        for kind in sorted(counts):
            lines.append(f"{kind:<40}{counts[kind]:>12}")
    return "\n".join(lines)


class Profiler:
    """Context-manager convenience."""

    def __init__(self, **kwargs):
        set_config(**kwargs)

    def __enter__(self):
        start()
        return self

    def __exit__(self, *args):
        stop()


# -- scoped domains / tasks / frames / markers (API parity) ------------------
class Domain:
    def __init__(self, name):
        self.name = name

    def __str__(self):
        return self.name

    def new_task(self, name):
        return Task(self, name)

    def new_frame(self, name):
        return Frame(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class _Span:
    def __init__(self, domain, name):
        self.name = name
        self.domain = domain
        self._start = None

    def start(self):
        self._start = time.perf_counter()

    def stop(self):
        if self._start is not None and _state["running"]:
            dur_us = (time.perf_counter() - self._start) * 1e6
            record_op(f"{self.domain}:{self.name}", dur_us, cat="task")
        self._start = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *args):
        self.stop()


class Task(_Span):
    pass


class Frame(_Span):
    pass


class Event(_Span):
    def __init__(self, name):
        super().__init__("event", name)


class Counter:
    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self.value = value or 0

    def _emit(self):
        # counters render as a chrome-trace counter lane ("C" events),
        # like the reference's profiler counters
        record_counter(f"{self.domain}:{self.name}", self.value)

    def set_value(self, value):
        self.value = value
        self._emit()

    def increment(self, delta=1):
        self.value += delta
        self._emit()

    def decrement(self, delta=1):
        self.value -= delta
        self._emit()

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self


class Marker:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        record_op(f"{self.domain}:{self.name}", 0, cat="marker")


# -- env autostart (parity: MXNET_PROFILER_AUTOSTART / MXNET_PROFILER_MODE,
#    reference docs/faq/env_var.md:193-197). Parsed through the config
#    registry so every documented bool spelling (1/true/yes/on) works.
from .config import get as _cfg_get  # noqa: E402

if _cfg_get("MXNET_PROFILER_AUTOSTART"):
    if _cfg_get("MXNET_PROFILER_MODE") in ("all", "1"):
        _config["profile_all"] = True
        _config["profile_api"] = True
    start()
