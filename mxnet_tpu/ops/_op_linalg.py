"""Linear-algebra operator family (reference: src/operator/tensor/la_op.cc
— _linalg_gemm:40 … _linalg_inverse:892, BLAS/LAPACK dispatch via
linalg_impl.h).  TPU redesign: thin emissions over jax.lax.linalg /
jnp.linalg — XLA lowers to MXU-tiled kernels on TPU and LAPACK on CPU; all
ops are batched over leading dims for free (the reference hand-loops
batched GEMM).  Registered under the reference's public aliases
(``linalg_gemm`` etc., exposed as mx.nd.linalg.* in the frontends).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _tri_lower(x, lower=True):
    return jnp.tril(x) if lower else jnp.triu(x)


@register("_linalg_gemm", alias=("linalg_gemm",),
          scalar_args=("alpha", "beta"))
def _linalg_gemm(attrs, a, b, c):
    ta = bool(attrs.get("transpose_a", False))
    tb = bool(attrs.get("transpose_b", False))
    alpha = float(attrs.get("alpha", 1.0))
    beta = float(attrs.get("beta", 1.0))
    aa = jnp.swapaxes(a, -1, -2) if ta else a
    bb = jnp.swapaxes(b, -1, -2) if tb else b
    return alpha * jnp.matmul(aa, bb) + beta * c


@register("_linalg_gemm2", alias=("linalg_gemm2",), scalar_args=("alpha",))
def _linalg_gemm2(attrs, a, b):
    ta = bool(attrs.get("transpose_a", False))
    tb = bool(attrs.get("transpose_b", False))
    alpha = float(attrs.get("alpha", 1.0))
    aa = jnp.swapaxes(a, -1, -2) if ta else a
    bb = jnp.swapaxes(b, -1, -2) if tb else b
    return alpha * jnp.matmul(aa, bb)


@register("_linalg_potrf", alias=("linalg_potrf",))
def _linalg_potrf(attrs, a):
    l = jnp.linalg.cholesky(a)
    if not bool(attrs.get("lower", True)):
        return jnp.swapaxes(l, -1, -2)
    return l


@register("_linalg_potri", alias=("linalg_potri",))
def _linalg_potri(attrs, a):
    # inverse of the matrix whose cholesky factor is a:
    # A = L Lᵀ  =>  A⁻¹ = L⁻ᵀ L⁻¹
    lower = bool(attrs.get("lower", True))
    l = a if lower else jnp.swapaxes(a, -1, -2)
    eye = jnp.broadcast_to(jnp.eye(l.shape[-1], dtype=l.dtype), l.shape)
    linv = jax.lax.linalg.triangular_solve(
        l, eye, left_side=True, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@register("_linalg_trmm", alias=("linalg_trmm",), scalar_args=("alpha",))
def _linalg_trmm(attrs, a, b):
    alpha = float(attrs.get("alpha", 1.0))
    lower = bool(attrs.get("lower", True))
    transpose = bool(attrs.get("transpose", False))
    rightside = bool(attrs.get("rightside", False))
    t = _tri_lower(a, lower)
    if transpose:
        t = jnp.swapaxes(t, -1, -2)
    return alpha * (jnp.matmul(b, t) if rightside else jnp.matmul(t, b))


@register("_linalg_trsm", alias=("linalg_trsm",), scalar_args=("alpha",))
def _linalg_trsm(attrs, a, b):
    alpha = float(attrs.get("alpha", 1.0))
    lower = bool(attrs.get("lower", True))
    transpose = bool(attrs.get("transpose", False))
    rightside = bool(attrs.get("rightside", False))
    out = jax.lax.linalg.triangular_solve(
        a, alpha * b, left_side=not rightside, lower=lower,
        transpose_a=transpose)
    return out


@register("_linalg_sumlogdiag", alias=("linalg_sumlogdiag",))
def _linalg_sumlogdiag(attrs, a):
    diag = jnp.diagonal(a, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(diag), axis=-1)


@register("_linalg_extractdiag", alias=("linalg_extractdiag",))
def _linalg_extractdiag(attrs, a):
    offset = int(attrs.get("offset", 0))
    return jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1)


@register("_linalg_makediag", alias=("linalg_makediag",))
def _linalg_makediag(attrs, a):
    offset = int(attrs.get("offset", 0))
    n = a.shape[-1] + abs(offset)
    out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    idx = jnp.arange(a.shape[-1])
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    return out.at[..., r, c].set(a)


def _trian_indices(n, offset, lower):
    """Reference la_op semantics: a nonzero offset picks the triangle by
    its sign (offset>0 upper, offset<0 lower); `lower` applies only at
    offset 0.  The selected band excludes |offset|-1 diagonals."""
    if offset > 0:
        return jnp.triu_indices(n, k=offset)
    if offset < 0:
        return jnp.tril_indices(n, k=offset)
    return jnp.tril_indices(n) if lower else jnp.triu_indices(n)


@register("_linalg_extracttrian", alias=("linalg_extracttrian",))
def _linalg_extracttrian(attrs, a):
    offset = int(attrs.get("offset", 0))
    lower = bool(attrs.get("lower", True))
    rows, cols = _trian_indices(a.shape[-1], offset, lower)
    return a[..., rows, cols]


@register("_linalg_maketrian", alias=("linalg_maketrian",))
def _linalg_maketrian(attrs, a):
    offset = int(attrs.get("offset", 0))
    lower = bool(attrs.get("lower", True))
    m = a.shape[-1]
    # triangle at |offset| of an n×n has (n-k)(n-k+1)/2 entries; invert
    import math
    k = abs(offset)
    n = int((math.isqrt(8 * m + 1) - 1) // 2) + k
    rows, cols = _trian_indices(n, offset, lower)
    out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    return out.at[..., rows, cols].set(a)


@register("_linalg_syrk", alias=("linalg_syrk",), scalar_args=("alpha",))
def _linalg_syrk(attrs, a):
    alpha = float(attrs.get("alpha", 1.0))
    transpose = bool(attrs.get("transpose", False))
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))


@register("_linalg_gelqf", alias=("linalg_gelqf",), num_outputs=2)
def _linalg_gelqf(attrs, a):
    # LQ factorization: A = L·Q with Q orthonormal rows (reference
    # la_op.cc:752); computed via QR of Aᵀ
    q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2), mode="reduced")
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("_linalg_syevd", alias=("linalg_syevd",), num_outputs=2)
def _linalg_syevd(attrs, a):
    w, u = jnp.linalg.eigh(a)
    # reference returns (U, L) with rows of U the eigenvectors: A = Uᵀ·L·U
    return jnp.swapaxes(u, -1, -2), w


@register("_linalg_inverse", alias=("linalg_inverse", "inverse"))
def _linalg_inverse(attrs, a):
    return jnp.linalg.inv(a)


@register("_linalg_det", alias=("linalg_det", "det"))
def _linalg_det(attrs, a):
    return jnp.linalg.det(a)


@register("_linalg_slogdet", alias=("linalg_slogdet", "slogdet"),
          num_outputs=2)
def _linalg_slogdet(attrs, a):
    sign, logabs = jnp.linalg.slogdet(a)
    return sign, logabs


@register("moments", num_outputs=2)
def _moments(attrs, x):
    axes = attrs.get("axes")
    keepdims = bool(attrs.get("keepdims", False))
    axes = tuple(axes) if axes is not None else None
    mean = jnp.mean(x, axis=axes, keepdims=keepdims)
    var = jnp.var(x, axis=axes, keepdims=keepdims)
    return mean, var
