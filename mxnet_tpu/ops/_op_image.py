"""Image operator family (reference: src/operator/image/ — crop.cc,
resize.cc, image_random.cc `_image_*` registrations).

The reference implements these as per-pixel OMP/CUDA kernels over HWC
uint8/float tensors; here each is a vectorized jnp program (XLA fuses the
whole augmentation chain into one kernel). All ops accept HWC (3-d) or
batched NHWC (4-d) inputs like the reference's ImageShape checks.

The random variants draw from the op-RNG key plumbing (`is_random=True`
— the registry threads a fresh counter-derived key per call, parity with
the reference's kRandom resource requests).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

import numpy as _np

from .registry import register
from ..base import MXNetError

# luma coefficients (reference image_random-inl.h RGB2GrayConvert / the
# python augmenters) and the YIQ hue-rotation basis. NumPy at module
# level — device arrays here would force backend init on package import.
_GRAY = (0.299, 0.587, 0.114)
_TYIQ = _np.array([[0.299, 0.587, 0.114],
                   [0.596, -0.274, -0.321],
                   [0.211, -0.523, 0.311]], _np.float32)
_ITYIQ = _np.linalg.inv(_TYIQ)

# AlexNet PCA lighting eigen basis (reference image_random-inl.h
# AdjustLightingImpl `eig`)
_EIG = _np.array([
    [55.46 * -0.5675, 4.794 * 0.7192, 1.148 * 0.4009],
    [55.46 * -0.5808, 4.794 * -0.0045, 1.148 * -0.8140],
    [55.46 * -0.5836, 4.794 * -0.6948, 1.148 * 0.4203]], _np.float32)


def _check_hwc(x):
    if x.ndim not in (3, 4):
        raise MXNetError(f"image op expects HWC or NHWC input, got {x.shape}")
    return x.ndim == 4


def _gray(x):
    """Per-pixel luma, channel dim kept (last axis = C)."""
    r, g, b = _GRAY
    coef = jnp.array([r, g, b], jnp.float32)
    return (x.astype(jnp.float32) * coef).sum(-1, keepdims=True)


@register("_image_to_tensor", alias=("image_to_tensor",))
def _image_to_tensor(attrs, x):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference:
    image_random.cc _image_to_tensor:41)."""
    batched = _check_hwc(x)
    y = x.astype(jnp.float32) / 255.0
    return y.transpose(0, 3, 1, 2) if batched else y.transpose(2, 0, 1)


@register("_image_normalize", alias=("image_normalize",),
          scalar_args=("mean", "std"))
def _image_normalize(attrs, x):
    """(x - mean) / std per channel on CHW/NCHW float input (reference:
    image_random.cc _image_normalize:104)."""
    mean = attrs.get("mean", (0.0,))
    std = attrs.get("std", (1.0,))
    mean = jnp.asarray(mean if isinstance(mean, (tuple, list)) else (mean,),
                       jnp.float32)
    std = jnp.asarray(std if isinstance(std, (tuple, list)) else (std,),
                      jnp.float32)
    nd_ = x.ndim
    shape = (-1, 1, 1) if nd_ == 3 else (1, -1, 1, 1)
    return ((x.astype(jnp.float32) - mean.reshape(shape)) /
            std.reshape(shape)).astype(x.dtype if
                                       jnp.issubdtype(x.dtype, jnp.floating)
                                       else jnp.float32)


@register("_image_crop", alias=("image_crop",),
          scalar_args=("x", "y", "width", "height"))
def _image_crop(attrs, data):
    """Crop [y:y+height, x:x+width] of an HWC/NHWC image (reference:
    image/crop.cc _image_crop:37)."""
    batched = _check_hwc(data)
    x0 = int(attrs["x"])
    y0 = int(attrs["y"])
    w = int(attrs["width"])
    h = int(attrs["height"])
    if batched:
        return data[:, y0:y0 + h, x0:x0 + w, :]
    return data[y0:y0 + h, x0:x0 + w, :]


@register("_image_resize", alias=("image_resize",),
          scalar_args=("size", "keep_ratio", "interp"))
def _image_resize(attrs, data):
    """Resize HWC/NHWC (reference: image/resize.cc _image_resize:36;
    size int = shorter-side-with-keep_ratio or square, (w, h) pair
    otherwise). Bilinear for interp=1 (default), nearest for 0."""
    batched = _check_hwc(data)
    size = attrs.get("size", 0)
    keep = bool(attrs.get("keep_ratio", False))
    interp = int(attrs.get("interp", 1))
    shape = data.shape
    ih, iw = (shape[1], shape[2]) if batched else (shape[0], shape[1])
    if isinstance(size, (tuple, list)):
        ow, oh = int(size[0]), int(size[1])
    elif keep:
        s = int(size)
        if ih < iw:
            oh, ow = s, max(1, round(iw * s / ih))
        else:
            ow, oh = s, max(1, round(ih * s / iw))
    else:
        ow = oh = int(size)
    method = "nearest" if interp == 0 else "linear"
    if batched:
        out_shape = (shape[0], oh, ow, shape[3])
    else:
        out_shape = (oh, ow, shape[2])
    out = jax.image.resize(data.astype(jnp.float32), out_shape, method)
    if jnp.issubdtype(data.dtype, jnp.integer):
        out = jnp.clip(jnp.rint(out), 0, 255)
    return out.astype(data.dtype)


def _flip(x, axis_hwc):
    batched = _check_hwc(x)
    return jnp.flip(x, axis=axis_hwc + 1 if batched else axis_hwc)


register("_image_flip_left_right", alias=("image_flip_left_right",))(
    lambda attrs, x: _flip(x, 1))
register("_image_flip_top_bottom", alias=("image_flip_top_bottom",))(
    lambda attrs, x: _flip(x, 0))


@register("_image_random_flip_left_right",
          alias=("image_random_flip_left_right",), is_random=True)
def _image_random_flip_lr(attrs, key, x):
    return jnp.where(jax.random.bernoulli(key), _flip(x, 1), x)


@register("_image_random_flip_top_bottom",
          alias=("image_random_flip_top_bottom",), is_random=True)
def _image_random_flip_tb(attrs, key, x):
    return jnp.where(jax.random.bernoulli(key), _flip(x, 0), x)


def _minmax(attrs):
    # identity at 1.0 when factors are omitted (the reference declares
    # min/max_factor as required fields; omitting them here is a no-op
    # augmentation rather than a surprise U(0,1) darkening)
    return (float(attrs.get("min_factor", 1.0)),
            float(attrs.get("max_factor", 1.0)))


def _apply_brightness(x, alpha):
    out = x.astype(jnp.float32) * alpha
    return out


def _apply_contrast(x, alpha):
    xf = x.astype(jnp.float32)
    gray_mean = _gray(xf).mean()
    return xf * alpha + (1.0 - alpha) * gray_mean


def _apply_saturation(x, alpha):
    xf = x.astype(jnp.float32)
    return xf * alpha + _gray(xf) * (1.0 - alpha)


def _apply_hue(x, alpha):
    """YIQ-basis hue rotation by alpha (in turns of pi), the python
    HueJitterAug formulation; the reference's HLS roundtrip
    (image_random-inl.h RGB2HLSConvert) is branch-heavy and
    TPU-hostile, this is the standard vectorizable equivalent."""
    xf = x.astype(jnp.float32)
    u = jnp.cos(alpha * jnp.pi)
    w = jnp.sin(alpha * jnp.pi)
    bt = jnp.array([[1.0, 0.0, 0.0], [0.0, 1.0, 0.0], [0.0, 0.0, 1.0]],
                   jnp.float32)
    bt = bt.at[1, 1].set(u).at[1, 2].set(-w).at[2, 1].set(w).at[2, 2].set(u)
    t = jnp.asarray(_ITYIQ) @ bt @ jnp.asarray(_TYIQ)
    return (xf.reshape(-1, 3) @ t.T).reshape(xf.shape)


def _saturate_like(out, ref):
    if jnp.issubdtype(ref.dtype, jnp.integer):
        return jnp.clip(jnp.rint(out), 0, 255).astype(ref.dtype)
    return out.astype(ref.dtype)


@register("_image_random_brightness", alias=("image_random_brightness",),
          is_random=True, scalar_args=("min_factor", "max_factor"))
def _image_random_brightness(attrs, key, x):
    lo, hi = _minmax(attrs)
    alpha = jax.random.uniform(key, minval=lo, maxval=hi)
    return _saturate_like(_apply_brightness(x, alpha), x)


@register("_image_random_contrast", alias=("image_random_contrast",),
          is_random=True, scalar_args=("min_factor", "max_factor"))
def _image_random_contrast(attrs, key, x):
    lo, hi = _minmax(attrs)
    alpha = jax.random.uniform(key, minval=lo, maxval=hi)
    return _saturate_like(_apply_contrast(x, alpha), x)


@register("_image_random_saturation", alias=("image_random_saturation",),
          is_random=True, scalar_args=("min_factor", "max_factor"))
def _image_random_saturation(attrs, key, x):
    lo, hi = _minmax(attrs)
    alpha = jax.random.uniform(key, minval=lo, maxval=hi)
    return _saturate_like(_apply_saturation(x, alpha), x)


@register("_image_random_hue", alias=("image_random_hue",), is_random=True,
          scalar_args=("min_factor", "max_factor"))
def _image_random_hue(attrs, key, x):
    """min/max_factor follow the reference's multiplicative convention
    (image_random.cc random_hue: factor ~ U(min, max), identity at 1.0 —
    typical call (0.9, 1.1)). The rotation fraction is (factor - 1):
    identical at the identity point and a small-angle match nearby,
    but as one vectorized YIQ rotation instead of the reference's
    branch-heavy per-pixel HLS roundtrip."""
    lo = float(attrs.get("min_factor", 1.0))
    hi = float(attrs.get("max_factor", 1.0))
    factor = jax.random.uniform(key, minval=lo, maxval=hi)
    return _saturate_like(_apply_hue(x, factor - 1.0), x)


@register("_image_random_color_jitter", alias=("image_random_color_jitter",),
          is_random=True,
          scalar_args=("brightness", "contrast", "saturation", "hue"))
def _image_random_color_jitter(attrs, key, x):
    """Brightness/contrast/saturation/hue jitter in random order is the
    python-side behavior; the op applies them in fixed order like the
    reference's RandomColorJitter kernel (image_random.cc:234)."""
    kb, kc, ks, kh = jax.random.split(key, 4)
    out = x.astype(jnp.float32)
    b = float(attrs.get("brightness", 0.0))
    c = float(attrs.get("contrast", 0.0))
    s = float(attrs.get("saturation", 0.0))
    h = float(attrs.get("hue", 0.0))
    if b > 0:
        out = _apply_brightness(
            out, jax.random.uniform(kb, minval=1 - b, maxval=1 + b))
    if c > 0:
        out = _apply_contrast(
            out, jax.random.uniform(kc, minval=1 - c, maxval=1 + c))
    if s > 0:
        out = _apply_saturation(
            out, jax.random.uniform(ks, minval=1 - s, maxval=1 + s))
    if h > 0:
        out = _apply_hue(out, jax.random.uniform(kh, minval=-h, maxval=h))
    return _saturate_like(out, x)


def _lighting(x, alpha):
    pca = jnp.asarray(_EIG) @ alpha.reshape(3)
    return x.astype(jnp.float32) + pca.reshape((1,) * (x.ndim - 1) + (3,))


@register("_image_adjust_lighting", alias=("image_adjust_lighting",),
          scalar_args=("alpha",))
def _image_adjust_lighting(attrs, x):
    """AlexNet-style PCA lighting with explicit alphas (reference:
    image_random.cc _image_adjust_lighting:241)."""
    alpha = jnp.asarray(tuple(attrs["alpha"]), jnp.float32)
    return _saturate_like(_lighting(x, alpha), x)


@register("_image_random_lighting", alias=("image_random_lighting",),
          is_random=True, scalar_args=("alpha_std",))
def _image_random_lighting(attrs, key, x):
    std = float(attrs.get("alpha_std", 0.05))
    alpha = jax.random.normal(key, (3,)) * std
    return _saturate_like(_lighting(x, alpha), x)
