"""Fused LayerNorm — Pallas TPU kernel #2.

Reference capability anchor: nn/layer_norm.cc computes mean/variance and
the affine transform as separate kernels over HBM; XLA fuses most of the
chain already, but the canonical fused-row kernel keeps each row resident
in VMEM for exactly one read and one write of HBM per element — the
bandwidth floor. Rows are processed in (BLOCK_ROWS, D) tiles; statistics
are computed in f32 regardless of input dtype (bf16-safe).

Forward runs as a Pallas kernel (interpreted off-TPU so tests exercise
the same path); backward is a custom_vjp in plain XLA using the saved
per-row mean/rstd — the standard analytic LayerNorm gradient, fused by
XLA into two row reductions.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _ln_kernel(x_ref, g_ref, b_ref, o_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x - mean) * rstd
    o_ref[:] = (y * g_ref[:].astype(jnp.float32)
                + b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)
    mean_ref[:] = mean[:, 0]
    rstd_ref[:] = rstd[:, 0]


def _use_interpret():
    return jax.default_backend() != "tpu"


@functools.partial(jax.jit, static_argnames=("eps", "block_rows",
                                             "interpret"))
def _ln_fwd(x2, gamma, beta, *, eps, block_rows, interpret):
    n, d = x2.shape
    grid = (n // block_rows,)
    out, mean, rstd = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((block_rows,), lambda i: (i,)),
            pl.BlockSpec((block_rows,), lambda i: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x2.dtype),
            jax.ShapeDtypeStruct((n,), jnp.float32),
            jax.ShapeDtypeStruct((n,), jnp.float32),
        ],
        interpret=interpret,
    )(x2, gamma, beta)
    return out, mean, rstd


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _layer_norm(x2, gamma, beta, eps, block_rows):
    out, _m, _r = _ln_core(x2, gamma, beta, eps, block_rows)
    return out


def _pick_block_rows(n):
    for b in (256, 128, 64, 32, 16, 8, 4, 2, 1):
        if n % b == 0:
            return b
    return 1


def _resolve_block_rows(n, block_rows):
    # a tuned block size only applies when it tiles THIS n exactly (a
    # shard_map body sees the shard-local row count, not the tuned one)
    if block_rows and n % block_rows == 0:
        return block_rows
    return _pick_block_rows(n)


def _ln_core(x2, gamma, beta, eps, block_rows=None):
    return _ln_fwd(x2, gamma, beta, eps=eps,
                   block_rows=_resolve_block_rows(x2.shape[0], block_rows),
                   interpret=_use_interpret())


def _ln_vjp_fwd(x2, gamma, beta, eps, block_rows):
    out, mean, rstd = _ln_core(x2, gamma, beta, eps, block_rows)
    return out, (x2, gamma, beta, mean, rstd)


def _ln_vjp_bwd(eps, block_rows, res, ct):
    x2, gamma, beta, mean, rstd = res
    xf = x2.astype(jnp.float32)
    ctf = ct.astype(jnp.float32)
    xhat = (xf - mean[:, None]) * rstd[:, None]
    gctf = ctf * gamma.astype(jnp.float32)[None, :]
    d = x2.shape[-1]
    # analytic LN gradient: dx = rstd * (g·ct - mean(g·ct) - xhat*mean(g·ct*xhat))
    m1 = jnp.mean(gctf, axis=-1, keepdims=True)
    m2 = jnp.mean(gctf * xhat, axis=-1, keepdims=True)
    dx = (gctf - m1 - xhat * m2) * rstd[:, None]
    dgamma = jnp.sum(ctf * xhat, axis=0)
    dbeta = jnp.sum(ctf, axis=0)
    return (dx.astype(x2.dtype), dgamma.astype(gamma.dtype),
            dbeta.astype(beta.dtype))


_layer_norm.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


def fused_layer_norm(x, gamma, beta, eps=1e-5, axis=-1, block_rows=None):
    """Fused LayerNorm over the trailing axis (differentiable).

    x: any shape; normalization along ``axis`` (must be the last axis or
    movable there). gamma/beta: (d,).  ``block_rows`` is the tunable row
    tile (kernels autotuner config); None picks the built-in heuristic.
    """
    if axis not in (-1, x.ndim - 1):
        x = jnp.moveaxis(x, axis, -1)
    shape = x.shape
    out = _layer_norm(x.reshape(-1, shape[-1]), gamma, beta, float(eps),
                      block_rows)
    out = out.reshape(shape)
    if axis not in (-1, len(shape) - 1):
        out = jnp.moveaxis(out, -1, axis)
    return out


def plain_layer_norm(x, gamma, beta, eps=1e-5, axis=-1):
    """The pure-XLA LayerNorm the op path uses when the kernel is off —
    and, verbatim, the kernel registry's reference implementation.  One
    definition on purpose: ``MXNET_KERNELS=reference`` must be bitwise
    identical to kernels-off, which only holds if both modes lower the
    exact same jaxpr."""
    from jax import lax
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + eps)
    bshape = tuple(x.shape[i] if i == (axis % x.ndim) else 1
                   for i in range(x.ndim))
    return out * gamma.reshape(bshape) + beta.reshape(bshape)
