"""Blockwise (flash) attention — the framework's first Pallas TPU kernel.

Reference capability anchor: src/operator/contrib/transformer-inl.h ships
interleaved-matmul self-attention ops that materialise the (S, S) score
matrix in HBM; SURVEY.md §7 step 8 calls for the TPU-native replacement.
This kernel computes softmax(q·kᵀ)·v with the online-softmax recurrence:
scores never leave VMEM, HBM traffic is O(S·D) instead of O(S²), and the
MXU sees (BLOCK_Q × D) @ (D × BLOCK_K) tiles.

Design (canonical TPU flash pattern):
  grid = (batch·heads, S/BLOCK_Q, S/BLOCK_K); the innermost grid axis is
  sequential on TPU, so f32 scratch (acc, running max m, running sum l)
  persists across the K sweep — initialised at k==0, finalised (acc/l)
  at the last k block.  Causal masking compares global q/k indices from
  broadcasted_iota; fully-masked k blocks are skipped with @pl.when.

Backward: custom_vjp that recomputes attention row-blocks in plain XLA
(rematerialisation trades FLOPs for HBM, same recipe as jax.checkpoint);
a dedicated Pallas backward kernel is a later optimisation.

On non-TPU backends the same kernel runs under the Pallas interpreter so
unit tests exercise the identical code path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports fail on CPU-only builds of jaxlib
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover  # graftlint: disable=swallowed-error -- optional-backend probe; any import failure means "no TPU pallas"
    pltpu = None
    _HAS_PLTPU = False

from .registry import register

_NEG_INF = -1e30


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, m_out_ref, l_out_ref,
                 acc_ref, m_ref, l_ref, *,
                 block_q, block_k, s_actual, sm_scale, causal):
    """One (q-block, k-block) grid step of online-softmax attention."""
    kb = pl.program_id(2)
    n_kb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = pl.program_id(1) * block_q
    k_start = kb * block_k

    # causal: a k block strictly above the diagonal contributes nothing
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)            # (BQ, D)
        k = k_ref[0].astype(jnp.float32)            # (BK, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (BQ, BK)

        q_ids = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_ids = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_ids < s_actual                      # padded keys
        if causal:
            mask &= k_ids <= q_ids
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, :1]                        # (BQ, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                       # (BQ, BK)
        correction = jnp.exp(m_prev - m_new)         # (BQ, 1)
        l_new = l_ref[:, :1] * correction + jnp.sum(p, axis=1,
                                                    keepdims=True)
        acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kb == n_kb - 1)
    def _finalize():
        # padded q rows have l == 0; emit 0 there rather than NaN
        l = l_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        m_out_ref[0] = m_ref[:]
        l_out_ref[0] = l_ref[:]


def _round_up(x, m):
    return (x + m - 1) // m * m


@functools.partial(jax.jit, static_argnames=("causal", "sm_scale",
                                             "block_q", "block_k",
                                             "interpret"))
def _flash_fwd(q, k, v, *, causal, sm_scale, block_q, block_k, interpret):
    import math
    b, h, s, d = q.shape
    bq = min(block_q, _round_up(s, 128))
    bk = min(block_k, _round_up(s, 128))
    # pad to a common multiple of BOTH block sizes — a floor-divided grid
    # would silently drop tail key blocks
    s_pad = _round_up(s, math.lcm(bq, bk))
    if s_pad != s:
        pad = [(0, 0), (0, 0), (0, s_pad - s), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    bh = b * h
    qf = q.reshape(bh, s_pad, d)
    kf = k.reshape(bh, s_pad, d)
    vf = v.reshape(bh, s_pad, d)

    kernel = functools.partial(
        _attn_kernel, block_q=bq, block_k=bk, s_actual=s,
        sm_scale=sm_scale, causal=causal)
    grid = (bh, s_pad // bq, s_pad // bk)
    scratch_shapes = [
        pltpu.VMEM((bq, d), jnp.float32),       # acc
        pltpu.VMEM((bq, 128), jnp.float32),     # running max (lane-bcast)
        pltpu.VMEM((bq, 128), jnp.float32),     # running sum (lane-bcast)
    ]

    q_spec = pl.BlockSpec((1, bq, d), lambda bh_, qi, ki: (bh_, qi, 0))
    stat_spec = pl.BlockSpec((1, bq, 128), lambda bh_, qi, ki: (bh_, qi, 0))
    out, m_out, l_out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            q_spec,
            pl.BlockSpec((1, bk, d), lambda bh_, qi, ki: (bh_, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh_, qi, ki: (bh_, ki, 0)),
        ],
        out_specs=(q_spec, stat_spec, stat_spec),
        out_shape=(
            jax.ShapeDtypeStruct((bh, s_pad, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s_pad, 128), jnp.float32),
            jax.ShapeDtypeStruct((bh, s_pad, 128), jnp.float32),
        ),
        scratch_shapes=scratch_shapes,
        interpret=interpret,
    )(qf, kf, vf)
    out = out.reshape(b, h, s_pad, d)[:, :, :s, :]
    m_out = m_out[:, :, 0].reshape(b, h, s_pad)[:, :, :s]
    l_out = l_out[:, :, 0].reshape(b, h, s_pad)[:, :, :s]
    return out, m_out, l_out


def _reference_attention(q, k, v, causal, sm_scale):
    """Plain XLA attention (used by the recompute backward)."""
    s = q.shape[2]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale
    if causal:
        qi = jnp.arange(s)[:, None]
        ki = jnp.arange(s)[None, :]
        logits = jnp.where(ki <= qi, logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _use_interpret():
    return jax.default_backend() != "tpu"


def _static_sm_scale(sm_scale, head_dim):
    """Resolve the softmax scale to a static python float.

    The scale parameterizes the kernel (a jit static argument), so a
    traced value here is a contract violation — rejecting it with a
    TypeError replaces the suppressed ``float(sm_scale)`` host escape
    of the original kernel (a concretization that graftlint's
    trace-host-escape rule rightly flagged)."""
    if sm_scale is None:
        return head_dim ** -0.5
    if not isinstance(sm_scale, (int, float)):
        raise TypeError(
            "flash_attention: sm_scale must be a static python scalar "
            f"(got {type(sm_scale).__name__}); it is baked into the "
            "kernel grid, not traced")
    return sm_scale


def reference_attention(q, k, v, causal=False, sm_scale=None):
    """Public plain-XLA attention with flash_attention's signature —
    the kernel registry's reference implementation."""
    return _reference_attention(q, k, v, causal,
                                _static_sm_scale(sm_scale, q.shape[-1]))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, sm_scale=None, block_q=128,
                    block_k=128):
    """softmax(q kᵀ / √d) v with O(S·D) memory.

    q, k, v: (batch, heads, seq, head_dim).  sm_scale defaults to
    1/sqrt(head_dim).

    ``sm_scale`` is a STATIC kernel parameter (baked into the pallas
    grid function), so it must be a python scalar, never a traced
    array — the old ``float(sm_scale)`` host conversion would silently
    concretize a tracer inside jit/shard_map bodies.
    """
    sm_scale = _static_sm_scale(sm_scale, q.shape[-1])
    out, _, _ = _flash_fwd(q, k, v, causal=causal, sm_scale=sm_scale,
                           block_q=block_q, block_k=block_k,
                           interpret=_use_interpret())
    return out


def _flash_fwd_rule(q, k, v, causal, sm_scale, block_q, block_k):
    out = flash_attention(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v)


def _flash_bwd_rule(causal, sm_scale, block_q, block_k, res, g):
    q, k, v = res
    sm_scale = _static_sm_scale(sm_scale, q.shape[-1])

    def ref(q_, k_, v_):
        return _reference_attention(q_, k_, v_, causal, sm_scale)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@register("_contrib_flash_attention", alias=("flash_attention",))
def _contrib_flash_attention(attrs, q, k, v):
    causal = bool(attrs.get("causal", False))
    sm_scale = attrs.get("sm_scale")
    sm_scale = float(sm_scale) if sm_scale is not None else None
    return flash_attention(q, k, v, causal, sm_scale,
                           int(attrs.get("block_q", 128)),
                           int(attrs.get("block_k", 128)))
