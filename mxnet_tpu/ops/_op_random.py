"""Random sampling ops.

Reference: src/operator/random/sample_op.{h,cc,cu} with per-context mshadow
PRNG resources (kRandom/kParallelRandom). TPU redesign: counter-based
jax.random with explicit keys — the imperative layer threads a key from the
global mx.random state (mxnet_tpu/random.py) into ops flagged is_random, so
seeded runs are reproducible across devices by construction.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register
from ..base import np_dtype


def _shape_dtype(attrs):
    shape = tuple(attrs.get("shape", ()) or ())
    dtype = np_dtype(attrs.get("dtype", "float32"))
    return shape, dtype


@register("_random_uniform", is_random=True, alias=("uniform",))
def _uniform(attrs, key):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.uniform(key, shape, dtype=dtype,
                              minval=float(attrs.get("low", 0.0)),
                              maxval=float(attrs.get("high", 1.0)))


@register("_random_normal", is_random=True, alias=("normal",))
def _normal(attrs, key):
    shape, dtype = _shape_dtype(attrs)
    return (jax.random.normal(key, shape, dtype=dtype)
            * float(attrs.get("scale", 1.0)) + float(attrs.get("loc", 0.0)))


@register("_random_gamma", is_random=True)
def _gamma(attrs, key):
    shape, dtype = _shape_dtype(attrs)
    return (jax.random.gamma(key, float(attrs.get("alpha", 1.0)), shape, dtype=dtype)
            * float(attrs.get("beta", 1.0)))


@register("_random_exponential", is_random=True)
def _exponential(attrs, key):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.exponential(key, shape, dtype=dtype) / float(attrs.get("lam", 1.0))


@register("_random_poisson", is_random=True)
def _poisson(attrs, key):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.poisson(key, float(attrs.get("lam", 1.0)), shape).astype(dtype)


@register("_random_negative_binomial", is_random=True)
def _neg_binomial(attrs, key):
    shape, dtype = _shape_dtype(attrs)
    k = float(attrs.get("k", 1.0))
    p = float(attrs.get("p", 1.0))
    lam = jax.random.gamma(key, k, shape) * (1 - p) / p
    return jax.random.poisson(jax.random.fold_in(key, 1), lam, shape).astype(dtype)


@register("_random_randint", is_random=True)
def _randint(attrs, key):
    shape = tuple(attrs.get("shape", ()) or ())
    dtype = np_dtype(attrs.get("dtype", "int32"))
    return jax.random.randint(key, shape, int(attrs["low"]), int(attrs["high"]),
                              dtype=dtype)


@register("_sample_multinomial", is_random=True, alias=("multinomial",))
def _multinomial(attrs, key, data):
    shape = attrs.get("shape", ())
    n = 1
    if shape:
        n = int(shape[0]) if isinstance(shape, (tuple, list)) else int(shape)
    logits = jnp.log(jnp.maximum(data, 1e-30))
    if data.ndim == 1:
        out = jax.random.categorical(key, logits, shape=(n,))
        return out.astype(np_dtype(attrs.get("dtype", "int32")))
    out = jax.random.categorical(key, logits[:, None, :], axis=-1,
                                 shape=(data.shape[0], n))
    if not shape:
        out = out[:, 0]
    return out.astype(np_dtype(attrs.get("dtype", "int32")))


@register("_shuffle", is_random=True, alias=("shuffle",))
def _shuffle(attrs, key, data):
    return jax.random.permutation(key, data, axis=0)


@register("_sample_unique_zipfian", is_random=True)
def _sample_unique_zipfian(attrs, key):
    n = int(attrs["range_max"])
    shape = tuple(attrs.get("shape", (1,)))
    u = jax.random.uniform(key, shape)
    out = (jnp.exp(u * jnp.log(n + 1.0)) - 1.0).astype(jnp.int64)
    return jnp.clip(out, 0, n - 1)


# GPU-free bernoulli helper used by gluon (not in reference op set by this name)
@register("_random_bernoulli", is_random=True)
def _bernoulli(attrs, key):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.bernoulli(key, float(attrs.get("p", 0.5)), shape).astype(dtype)


# --- scalar generalized negative binomial (reference sample_op.cc:166) ------
@register("_random_generalized_negative_binomial", is_random=True)
def _gen_neg_binomial(attrs, key):
    shape, dtype = _shape_dtype(attrs)
    mu = float(attrs.get("mu", 1.0))
    alpha = float(attrs.get("alpha", 1.0))
    # NB(limit=1/alpha, prob=1/(mu*alpha+1)) via the gamma-Poisson mixture:
    # lambda ~ Gamma(shape=1/alpha, scale=mu*alpha); x ~ Poisson(lambda)
    lam = jax.random.gamma(key, 1.0 / alpha, shape) * (mu * alpha)
    return jax.random.poisson(jax.random.fold_in(key, 1), lam, shape).astype(dtype)


# --- per-element ("multisample") family -------------------------------------
# Reference: src/operator/random/multisample_op.{h,cc} — each element of the
# distribution-parameter tensors parameterizes its own block of samples; the
# output shape is params.shape + attrs['shape'].  TPU redesign: one shaped
# draw with the parameter tensors broadcast over the trailing sample dims —
# a single fused XLA kernel, no per-distribution loop.

def _msample_prep(attrs, *params):
    sshape = attrs.get("shape", ()) or ()
    if isinstance(sshape, int):
        sshape = (sshape,)
    sshape = tuple(int(s) for s in sshape)
    oshape = params[0].shape + sshape
    bcast = tuple(p.reshape(p.shape + (1,) * len(sshape)) for p in params)
    dt = attrs.get("dtype")
    dtype = np_dtype(dt) if dt not in (None, "None", -1) else params[0].dtype
    return oshape, bcast, dtype


@register("_sample_uniform", is_random=True, alias=("sample_uniform",))
def _sample_uniform_op(attrs, key, low, high):
    oshape, (lb, hb), dtype = _msample_prep(attrs, low, high)
    u = jax.random.uniform(key, oshape, dtype=jnp.float32)
    return (lb + u * (hb - lb)).astype(dtype)


@register("_sample_normal", is_random=True, alias=("sample_normal",))
def _sample_normal_op(attrs, key, mu, sigma):
    oshape, (mb, sb), dtype = _msample_prep(attrs, mu, sigma)
    return (mb + sb * jax.random.normal(key, oshape, jnp.float32)).astype(dtype)


@register("_sample_gamma", is_random=True, alias=("sample_gamma",))
def _sample_gamma_op(attrs, key, alpha, beta):
    # beta is the SCALE (matches the scalar _random_gamma convention)
    oshape, (ab, bb), dtype = _msample_prep(attrs, alpha, beta)
    return (jax.random.gamma(key, ab, oshape) * bb).astype(dtype)


@register("_sample_exponential", is_random=True,
          alias=("sample_exponential",))
def _sample_exponential_op(attrs, key, lam):
    oshape, (lb,), dtype = _msample_prep(attrs, lam)
    return (jax.random.exponential(key, oshape, jnp.float32) / lb).astype(dtype)


@register("_sample_poisson", is_random=True, alias=("sample_poisson",))
def _sample_poisson_op(attrs, key, lam):
    oshape, (lb,), dtype = _msample_prep(attrs, lam)
    return jax.random.poisson(key, lb, oshape).astype(dtype)


@register("_sample_negative_binomial", is_random=True,
          alias=("sample_negative_binomial",))
def _sample_neg_binomial_op(attrs, key, k, p):
    # gamma-Poisson mixture; p is the SUCCESS probability of the stopping
    # criterion: mean = k(1-p)/p (matches scalar _random_negative_binomial)
    oshape, (kb, pb), dtype = _msample_prep(attrs, k, p)
    lam = jax.random.gamma(key, kb, oshape) * (1 - pb) / pb
    return jax.random.poisson(jax.random.fold_in(key, 1), lam,
                              oshape).astype(dtype)


@register("_sample_generalized_negative_binomial", is_random=True,
          alias=("sample_generalized_negative_binomial",))
def _sample_gen_neg_binomial_op(attrs, key, mu, alpha):
    oshape, (mb, ab), dtype = _msample_prep(attrs, mu, alpha)
    lam = jax.random.gamma(key, 1.0 / ab, oshape) * (mb * ab)
    return jax.random.poisson(jax.random.fold_in(key, 1), lam,
                              oshape).astype(dtype)


# --- *_like family (reference sample_op.cc:197-262) -------------------------
@register("_random_uniform_like", is_random=True)
def _uniform_like(attrs, key, data):
    return jax.random.uniform(key, data.shape, dtype=jnp.float32,
                              minval=float(attrs.get("low", 0.0)),
                              maxval=float(attrs.get("high", 1.0))
                              ).astype(data.dtype)


@register("_random_normal_like", is_random=True)
def _normal_like(attrs, key, data):
    return (jax.random.normal(key, data.shape, jnp.float32)
            * float(attrs.get("scale", 1.0))
            + float(attrs.get("loc", 0.0))).astype(data.dtype)


@register("_random_gamma_like", is_random=True)
def _gamma_like(attrs, key, data):
    return (jax.random.gamma(key, float(attrs.get("alpha", 1.0)), data.shape)
            * float(attrs.get("beta", 1.0))).astype(data.dtype)


@register("_random_exponential_like", is_random=True)
def _exponential_like(attrs, key, data):
    return (jax.random.exponential(key, data.shape, jnp.float32)
            / float(attrs.get("lam", 1.0))).astype(data.dtype)


@register("_random_poisson_like", is_random=True)
def _poisson_like(attrs, key, data):
    return jax.random.poisson(key, float(attrs.get("lam", 1.0)),
                              data.shape).astype(data.dtype)


@register("_random_negative_binomial_like", is_random=True)
def _neg_binomial_like(attrs, key, data):
    k = float(attrs.get("k", 1.0))
    p = float(attrs.get("p", 1.0))
    lam = jax.random.gamma(key, k, data.shape) * (1 - p) / p
    return jax.random.poisson(jax.random.fold_in(key, 1), lam,
                              data.shape).astype(data.dtype)


@register("_random_generalized_negative_binomial_like", is_random=True)
def _gen_neg_binomial_like(attrs, key, data):
    mu = float(attrs.get("mu", 1.0))
    alpha = float(attrs.get("alpha", 1.0))
    lam = jax.random.gamma(key, 1.0 / alpha, data.shape) * (mu * alpha)
    return jax.random.poisson(jax.random.fold_in(key, 1), lam,
                              data.shape).astype(data.dtype)


# --- pdf ops (reference random/pdf_op.{h,cc}) -------------------------------
# random_pdf_<distr>(sample, *params, is_log): the parameter tensors describe
# a batch of distributions (shape P); sample has shape P + T and each sample
# element is evaluated under its row's distribution.  Deterministic jnp
# formulas — gradients come from JAX autodiff of the closed forms (the
# reference hand-writes *_Grad kernels; pdf_op.h).  Formula conventions
# follow the reference exactly: gamma's beta is a RATE here (pdf_op.h
# PDF_Gamma), negative_binomial's p is the failure probability.

def _pdf_bcast(sample, params, vector=False):
    """Reshape each param from P (or P+(k,)) to broadcast against sample."""
    tail = 1 if vector else 0
    extra = sample.ndim - params[0].ndim
    outs = []
    for p in params:
        core = p.shape[:p.ndim - tail]
        vec = p.shape[p.ndim - tail:]
        outs.append(p.reshape(core + (1,) * extra + vec))
    return outs


def _pdf_out(lpdf, attrs):
    return lpdf if bool(attrs.get("is_log", False)) else jnp.exp(lpdf)


@register("_random_pdf_uniform", alias=("random_pdf_uniform",))
def _pdf_uniform(attrs, sample, low, high):
    lb, hb = _pdf_bcast(sample, (low, high))
    # no support check — parity with reference PDF_Uniform
    lpdf = jnp.broadcast_to(-jnp.log(hb - lb), sample.shape)
    return _pdf_out(lpdf, attrs)


@register("_random_pdf_normal", alias=("random_pdf_normal",))
def _pdf_normal(attrs, sample, mu, sigma):
    mb, sb = _pdf_bcast(sample, (mu, sigma))
    lpdf = (-0.5 * jnp.square(sample - mb) / jnp.square(sb)
            - jnp.log(sb * jnp.sqrt(2 * jnp.pi)))
    return _pdf_out(lpdf, attrs)


@register("_random_pdf_gamma", alias=("random_pdf_gamma",))
def _pdf_gamma(attrs, sample, alpha, beta):
    from jax.scipy.special import gammaln
    ab, bb = _pdf_bcast(sample, (alpha, beta))
    lpdf = (ab * jnp.log(bb) + (ab - 1) * jnp.log(sample) - bb * sample
            - gammaln(ab))
    return _pdf_out(lpdf, attrs)


@register("_random_pdf_exponential", alias=("random_pdf_exponential",))
def _pdf_exponential(attrs, sample, lam):
    (lb,) = _pdf_bcast(sample, (lam,))
    return _pdf_out(jnp.log(lb) - lb * sample, attrs)


@register("_random_pdf_poisson", alias=("random_pdf_poisson",))
def _pdf_poisson(attrs, sample, lam):
    from jax.scipy.special import gammaln
    (lb,) = _pdf_bcast(sample, (lam,))
    lpdf = sample * jnp.log(lb) - gammaln(sample + 1) - lb
    return _pdf_out(lpdf, attrs)


def _nb_lpdf(limit, prob, x):
    """log NB pmf with prob = FAILURE probability (reference pdf_op.h)."""
    from jax.scipy.special import gammaln
    return (gammaln(x + limit) - gammaln(x + 1) - gammaln(limit)
            + limit * jnp.log(prob) + x * jnp.log1p(-prob))


@register("_random_pdf_negative_binomial",
          alias=("random_pdf_negative_binomial",))
def _pdf_neg_binomial(attrs, sample, k, p):
    kb, pb = _pdf_bcast(sample, (k, p))
    return _pdf_out(_nb_lpdf(kb, pb, sample), attrs)


@register("_random_pdf_generalized_negative_binomial",
          alias=("random_pdf_generalized_negative_binomial",))
def _pdf_gen_neg_binomial(attrs, sample, mu, alpha):
    mb, ab = _pdf_bcast(sample, (mu, alpha))
    limit = 1.0 / ab
    prob = 1.0 / (mb * ab + 1.0)
    return _pdf_out(_nb_lpdf(limit, prob, sample), attrs)


@register("_random_pdf_dirichlet", alias=("random_pdf_dirichlet",))
def _pdf_dirichlet(attrs, sample, alpha):
    from jax.scipy.special import gammaln
    (ab,) = _pdf_bcast(sample, (alpha,), vector=True)
    lpdf = (jnp.sum((ab - 1) * jnp.log(sample), axis=-1)
            + gammaln(jnp.sum(ab, axis=-1))
            - jnp.sum(gammaln(ab), axis=-1))
    return _pdf_out(lpdf, attrs)
