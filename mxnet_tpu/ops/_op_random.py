"""Random sampling ops.

Reference: src/operator/random/sample_op.{h,cc,cu} with per-context mshadow
PRNG resources (kRandom/kParallelRandom). TPU redesign: counter-based
jax.random with explicit keys — the imperative layer threads a key from the
global mx.random state (mxnet_tpu/random.py) into ops flagged is_random, so
seeded runs are reproducible across devices by construction.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register
from ..base import np_dtype


def _shape_dtype(attrs):
    shape = tuple(attrs.get("shape", ()) or ())
    dtype = np_dtype(attrs.get("dtype", "float32"))
    return shape, dtype


@register("_random_uniform", is_random=True, alias=("uniform",))
def _uniform(attrs, key):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.uniform(key, shape, dtype=dtype,
                              minval=float(attrs.get("low", 0.0)),
                              maxval=float(attrs.get("high", 1.0)))


@register("_random_normal", is_random=True, alias=("normal",))
def _normal(attrs, key):
    shape, dtype = _shape_dtype(attrs)
    return (jax.random.normal(key, shape, dtype=dtype)
            * float(attrs.get("scale", 1.0)) + float(attrs.get("loc", 0.0)))


@register("_random_gamma", is_random=True)
def _gamma(attrs, key):
    shape, dtype = _shape_dtype(attrs)
    return (jax.random.gamma(key, float(attrs.get("alpha", 1.0)), shape, dtype=dtype)
            * float(attrs.get("beta", 1.0)))


@register("_random_exponential", is_random=True)
def _exponential(attrs, key):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.exponential(key, shape, dtype=dtype) / float(attrs.get("lam", 1.0))


@register("_random_poisson", is_random=True)
def _poisson(attrs, key):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.poisson(key, float(attrs.get("lam", 1.0)), shape).astype(dtype)


@register("_random_negative_binomial", is_random=True)
def _neg_binomial(attrs, key):
    shape, dtype = _shape_dtype(attrs)
    k = float(attrs.get("k", 1.0))
    p = float(attrs.get("p", 1.0))
    lam = jax.random.gamma(key, k, shape) * (1 - p) / p
    return jax.random.poisson(jax.random.fold_in(key, 1), lam, shape).astype(dtype)


@register("_random_randint", is_random=True)
def _randint(attrs, key):
    shape = tuple(attrs.get("shape", ()) or ())
    dtype = np_dtype(attrs.get("dtype", "int32"))
    return jax.random.randint(key, shape, int(attrs["low"]), int(attrs["high"]),
                              dtype=dtype)


@register("_sample_multinomial", is_random=True, alias=("multinomial",))
def _multinomial(attrs, key, data):
    shape = attrs.get("shape", ())
    n = 1
    if shape:
        n = int(shape[0]) if isinstance(shape, (tuple, list)) else int(shape)
    logits = jnp.log(jnp.maximum(data, 1e-30))
    if data.ndim == 1:
        out = jax.random.categorical(key, logits, shape=(n,))
        return out.astype(np_dtype(attrs.get("dtype", "int32")))
    out = jax.random.categorical(key, logits[:, None, :], axis=-1,
                                 shape=(data.shape[0], n))
    if not shape:
        out = out[:, 0]
    return out.astype(np_dtype(attrs.get("dtype", "int32")))


@register("_shuffle", is_random=True, alias=("shuffle",))
def _shuffle(attrs, key, data):
    return jax.random.permutation(key, data, axis=0)


@register("_sample_unique_zipfian", is_random=True)
def _sample_unique_zipfian(attrs, key):
    n = int(attrs["range_max"])
    shape = tuple(attrs.get("shape", (1,)))
    u = jax.random.uniform(key, shape)
    out = (jnp.exp(u * jnp.log(n + 1.0)) - 1.0).astype(jnp.int64)
    return jnp.clip(out, 0, n - 1)


# GPU-free bernoulli helper used by gluon (not in reference op set by this name)
@register("_random_bernoulli", is_random=True)
def _bernoulli(attrs, key):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.bernoulli(key, float(attrs.get("p", 0.5)), shape).astype(dtype)
