"""Fused optimizer update ops.

Reference: src/operator/optimizer_op.cc (+contrib/adamw.cc) — fused
sgd/sgd_mom/adam/... updates, including multi-precision (fp32 master weights
for fp16 params) variants. Here each update is one jitted XLA computation;
"fused" comes free from XLA fusion. Multi-precision maps to bf16 params with
f32 master copies (the TPU-idiomatic mixed-precision recipe).

All ops return the updated weight (plus updated state tensors) functionally;
the NDArray layer writes results back into the originals so the MXNet
"in-place update" API is preserved (SURVEY.md §7 hard part 1: aliasing via
donation happens inside jit through input-output aliasing when shapes match).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


def _common(attrs):
    lr = float(attrs["lr"])
    wd = float(attrs.get("wd", 0.0))
    rescale = float(attrs.get("rescale_grad", 1.0))
    clip = attrs.get("clip_gradient", None)
    clip = None if clip in (None, -1, -1.0) else float(clip)
    return lr, wd, rescale, clip


def _prep_grad(grad, rescale, clip, dtype=None):
    g = grad.astype(dtype or grad.dtype) * rescale
    if clip is not None:
        g = jnp.clip(g, -clip, clip)
    return g


@register("sgd_update")
def _sgd_update(attrs, weight, grad):
    lr, wd, rescale, clip = _common(attrs)
    g = _prep_grad(grad, rescale, clip)
    return weight - lr * (g + wd * weight)


@register("sgd_mom_update", num_outputs=2, mutate_aux=(2,))
def _sgd_mom_update(attrs, weight, grad, mom):
    lr, wd, rescale, clip = _common(attrs)
    momentum = float(attrs.get("momentum", 0.0))
    g = _prep_grad(grad, rescale, clip)
    new_mom = momentum * mom - lr * (g + wd * weight)
    return weight + new_mom, new_mom


@register("mp_sgd_update", num_outputs=2, mutate_aux=(2,))
def _mp_sgd_update(attrs, weight, grad, weight32):
    lr, wd, rescale, clip = _common(attrs)
    g = _prep_grad(grad, rescale, clip, jnp.float32)
    w32 = weight32 - lr * (g + wd * weight32)
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", num_outputs=3, mutate_aux=(2, 3))
def _mp_sgd_mom_update(attrs, weight, grad, mom, weight32):
    lr, wd, rescale, clip = _common(attrs)
    momentum = float(attrs.get("momentum", 0.0))
    g = _prep_grad(grad, rescale, clip, jnp.float32)
    new_mom = momentum * mom - lr * (g + wd * weight32)
    w32 = weight32 + new_mom
    return w32.astype(weight.dtype), new_mom, w32


@register("nag_mom_update", num_outputs=2, mutate_aux=(2,))
def _nag_mom_update(attrs, weight, grad, mom):
    lr, wd, rescale, clip = _common(attrs)
    momentum = float(attrs.get("momentum", 0.0))
    g = _prep_grad(grad, rescale, clip) + wd * weight
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("adam_update", num_outputs=3, mutate_aux=(2, 3))
def _adam_update(attrs, weight, grad, mean, var):
    lr, wd, rescale, clip = _common(attrs)
    beta1 = float(attrs.get("beta1", 0.9))
    beta2 = float(attrs.get("beta2", 0.999))
    eps = float(attrs.get("epsilon", 1e-8))
    g = _prep_grad(grad, rescale, clip) + wd * weight
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    if bool(attrs.get("lazy_update", False)):
        pass  # dense path identical under XLA
    w = weight - lr * m / (jnp.sqrt(v) + eps)
    return w, m, v


@register("adamw_update", num_outputs=3, mutate_aux=(2, 3))
def _adamw_update(attrs, weight, grad, mean, var):
    """Decoupled weight decay (reference: src/operator/contrib/adamw.cc)."""
    lr, wd, rescale, clip = _common(attrs)
    eta = float(attrs.get("eta", 1.0))
    beta1 = float(attrs.get("beta1", 0.9))
    beta2 = float(attrs.get("beta2", 0.999))
    eps = float(attrs.get("epsilon", 1e-8))
    g = _prep_grad(grad, rescale, clip)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - eta * (lr * m / (jnp.sqrt(v) + eps) + wd * weight)
    return w, m, v


@register("rmsprop_update", num_outputs=2, mutate_aux=(2,))
def _rmsprop_update(attrs, weight, grad, n):
    lr, wd, rescale, clip = _common(attrs)
    gamma1 = float(attrs.get("gamma1", 0.95))
    eps = float(attrs.get("epsilon", 1e-8))
    g = _prep_grad(grad, rescale, clip) + wd * weight
    n2 = gamma1 * n + (1 - gamma1) * jnp.square(g)
    return weight - lr * g / (jnp.sqrt(n2) + eps), n2


@register("rmspropalex_update", num_outputs=4, mutate_aux=(2, 3, 4))
def _rmspropalex_update(attrs, weight, grad, n, g_avg, delta):
    lr, wd, rescale, clip = _common(attrs)
    gamma1 = float(attrs.get("gamma1", 0.95))
    gamma2 = float(attrs.get("gamma2", 0.9))
    eps = float(attrs.get("epsilon", 1e-8))
    g = _prep_grad(grad, rescale, clip) + wd * weight
    n2 = gamma1 * n + (1 - gamma1) * jnp.square(g)
    gavg2 = gamma1 * g_avg + (1 - gamma1) * g
    d2 = gamma2 * delta - lr * g / jnp.sqrt(n2 - jnp.square(gavg2) + eps)
    return weight + d2, n2, gavg2, d2


@register("ftrl_update", num_outputs=3, mutate_aux=(2, 3))
def _ftrl_update(attrs, weight, grad, z, n):
    lr, wd, rescale, clip = _common(attrs)
    lamda1 = float(attrs.get("lamda1", 0.01))
    beta = float(attrs.get("beta", 1.0))
    g = _prep_grad(grad, rescale, clip)
    n2 = n + jnp.square(g)
    sigma = (jnp.sqrt(n2) - jnp.sqrt(n)) / lr
    z2 = z + g - sigma * weight
    w = jnp.where(jnp.abs(z2) > lamda1,
                  -(z2 - jnp.sign(z2) * lamda1) / ((beta + jnp.sqrt(n2)) / lr + wd),
                  jnp.zeros_like(weight))
    return w, z2, n2


@register("signsgd_update")
def _signsgd_update(attrs, weight, grad):
    lr, wd, rescale, clip = _common(attrs)
    g = _prep_grad(grad, rescale, clip)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", num_outputs=2, mutate_aux=(2,))
def _signum_update(attrs, weight, grad, mom):
    lr, wd, rescale, clip = _common(attrs)
    momentum = float(attrs.get("momentum", 0.0))
    wd_lh = float(attrs.get("wd_lh", 0.0))
    g = _prep_grad(grad, rescale, clip)
    new_mom = momentum * mom - (1 - momentum) * (g + wd * weight)
    w = (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return w, new_mom


@register("lamb_update_phase1", num_outputs=3, mutate_aux=(2, 3))
def _lamb_phase1(attrs, weight, grad, mean, var):
    beta1 = float(attrs.get("beta1", 0.9))
    beta2 = float(attrs.get("beta2", 0.999))
    eps = float(attrs.get("epsilon", 1e-6))
    wd = float(attrs.get("wd", 0.0))
    t = int(attrs.get("t", 1))
    rescale = float(attrs.get("rescale_grad", 1.0))
    g = grad * rescale
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    if bool(attrs.get("bias_correction", True)):
        mhat = m / (1 - beta1 ** t)
        vhat = v / (1 - beta2 ** t)
    else:
        mhat, vhat = m, v
    return mhat / (jnp.sqrt(vhat) + eps) + wd * weight, m, v


@register("all_finite")
def _all_finite(attrs, *arrays):
    ok = jnp.asarray(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(a.astype(jnp.float32))))
    return ok.astype(jnp.float32).reshape(1)


@register("multi_all_finite")
def _multi_all_finite(attrs, *arrays):
    return _all_finite(attrs, *arrays)


# --- aggregated multi-tensor updates (reference: optimizer_op.cc:320-406,
# MXNET_OPTIMIZER_AGGREGATION_SIZE) ------------------------------------------
# One op updates N weights in a single dispatch; XLA fuses the per-weight
# elementwise updates into one kernel pass, which is exactly what the
# reference's hand-rolled MultiSGDKernel buys on GPU.

def _multi_common(attrs):
    n = int(attrs.get("num_weights", 1))
    def _floats(v):
        if isinstance(v, (int, float)):
            return [float(v)] * n
        return [float(x) for x in v]
    lrs = _floats(attrs["lrs"])
    wds = _floats(attrs["wds"])
    rescale = float(attrs.get("rescale_grad", 1.0))
    clip = attrs.get("clip_gradient", None)
    clip = None if clip in (None, -1, -1.0) else float(clip)
    return n, lrs, wds, rescale, clip


@register("multi_sgd_update",
          num_outputs=lambda a: int(a.get("num_weights", 1)))
def _multi_sgd_update(attrs, *args):
    n, lrs, wds, rescale, clip = _multi_common(attrs)
    outs = []
    for i in range(n):
        w, g = args[2 * i], args[2 * i + 1]
        gi = _prep_grad(g, rescale, clip)
        outs.append(w - lrs[i] * (gi + wds[i] * w))
    return tuple(outs)


@register("multi_sgd_mom_update",
          num_outputs=lambda a: 2 * int(a.get("num_weights", 1)),
          mutate_aux=lambda a: tuple(
              3 * i + 2 for i in range(int(a.get("num_weights", 1)))))
def _multi_sgd_mom_update(attrs, *args):
    n, lrs, wds, rescale, clip = _multi_common(attrs)
    momentum = float(attrs.get("momentum", 0.0))
    ws, ms = [], []
    for i in range(n):
        w, g, m = args[3 * i], args[3 * i + 1], args[3 * i + 2]
        gi = _prep_grad(g, rescale, clip)
        nm = momentum * m - lrs[i] * (gi + wds[i] * w)
        ws.append(w + nm)
        ms.append(nm)
    return tuple(ws) + tuple(ms)


@register("multi_mp_sgd_update",
          num_outputs=lambda a: 2 * int(a.get("num_weights", 1)),
          mutate_aux=lambda a: tuple(
              3 * i + 2 for i in range(int(a.get("num_weights", 1)))))
def _multi_mp_sgd_update(attrs, *args):
    n, lrs, wds, rescale, clip = _multi_common(attrs)
    ws, w32s = [], []
    for i in range(n):
        w, g, w32 = args[3 * i], args[3 * i + 1], args[3 * i + 2]
        gi = _prep_grad(g, rescale, clip, jnp.float32)
        nw32 = w32 - lrs[i] * (gi + wds[i] * w32)
        ws.append(nw32.astype(w.dtype))
        w32s.append(nw32)
    return tuple(ws) + tuple(w32s)


@register("multi_mp_sgd_mom_update",
          num_outputs=lambda a: 3 * int(a.get("num_weights", 1)),
          mutate_aux=lambda a: tuple(
              4 * i + 2 for i in range(int(a.get("num_weights", 1))))
          + tuple(4 * i + 3 for i in range(int(a.get("num_weights", 1)))))
def _multi_mp_sgd_mom_update(attrs, *args):
    n, lrs, wds, rescale, clip = _multi_common(attrs)
    momentum = float(attrs.get("momentum", 0.0))
    ws, ms, w32s = [], [], []
    for i in range(n):
        w, g, m, w32 = (args[4 * i], args[4 * i + 1],
                        args[4 * i + 2], args[4 * i + 3])
        gi = _prep_grad(g, rescale, clip, jnp.float32)
        nm = momentum * m - lrs[i] * (gi + wds[i] * w32)
        nw32 = w32 + nm
        ws.append(nw32.astype(w.dtype))
        ms.append(nm)
        w32s.append(nw32)
    return tuple(ws) + tuple(ms) + tuple(w32s)


# --- round-4 named-op gap closers -------------------------------------------

@register("ftml_update", num_outputs=4, mutate_aux=(2, 3, 4))
def _ftml_update(attrs, weight, grad, d, v, z):
    """FTML (reference: optimizer_op-inl.h FTMLKernel:~1215). Note the
    reference clips AFTER adding wd*weight (clip_grad applies to the
    regularized gradient), unlike sgd's clip-then-decay."""
    lr = float(attrs["lr"])
    beta1 = float(attrs.get("beta1", 0.6))
    beta2 = float(attrs.get("beta2", 0.999))
    eps = float(attrs.get("epsilon", 1e-8))
    t = float(attrs["t"])
    wd = float(attrs.get("wd", 0.0))
    rescale = float(attrs.get("rescale_grad", 1.0))
    clip = attrs.get("clip_grad", None)
    clip = None if clip in (None, -1, -1.0) else float(clip)
    g = rescale * grad + wd * weight
    if clip is not None:
        g = jnp.clip(g, -clip, clip)
    v2 = beta2 * v + (1 - beta2) * jnp.square(g)
    d_t = (1 - beta1 ** t) / lr * (jnp.sqrt(v2 / (1 - beta2 ** t)) + eps)
    z2 = beta1 * z + (1 - beta1) * g - (d_t - beta1 * d) * weight
    return -z2 / d_t, d_t, v2, z2


@register("mp_nag_mom_update", num_outputs=3, mutate_aux=(2, 3))
def _mp_nag_mom_update(attrs, weight, grad, mom, weight32):
    """Multi-precision NAG: math in the f32 master copy (reference:
    optimizer_op.cc mp_nag_mom_update)."""
    lr, wd, rescale, clip = _common(attrs)
    momentum = float(attrs.get("momentum", 0.0))
    g = _prep_grad(grad, rescale, clip, jnp.float32) + wd * weight32
    new_mom = momentum * mom + g
    w32 = weight32 - lr * (g + momentum * new_mom)
    return w32.astype(weight.dtype), new_mom, w32


@register("_mp_adamw_update", alias=("mp_adamw_update",),
          num_outputs=4, mutate_aux=(2, 3, 4))
def _mp_adamw_update(attrs, weight, grad, mean, var, weight32):
    """Multi-precision AdamW (reference: contrib/adamw.cc
    _mp_adamw_update): adamw math on the f32 master weights."""
    lr, wd, rescale, clip = _common(attrs)
    eta = float(attrs.get("eta", 1.0))
    beta1 = float(attrs.get("beta1", 0.9))
    beta2 = float(attrs.get("beta2", 0.999))
    eps = float(attrs.get("epsilon", 1e-8))
    g = _prep_grad(grad, rescale, clip, jnp.float32)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w32 = weight32 - eta * (lr * m / (jnp.sqrt(v) + eps) + wd * weight32)
    return w32.astype(weight.dtype), m, v, w32


@register("_sparse_adagrad_update", alias=("sparse_adagrad_update",),
          num_outputs=2, mutate_aux=(2,))
def _sparse_adagrad_update(attrs, weight, grad, history):
    """AdaGrad with per-row lazy semantics (reference: optimizer_op.cc
    _sparse_adagrad_update — there grad is row_sparse and only touched
    rows update; densely a zero grad row leaves w/h unchanged, which this
    reproduces exactly: h += 0, w -= lr*0/... = w)."""
    lr = float(attrs["lr"])
    eps = float(attrs.get("epsilon", 1e-7))
    rescale = float(attrs.get("rescale_grad", 1.0))
    clip = attrs.get("clip_gradient", None)
    clip = None if clip in (None, -1, -1.0) else float(clip)
    g = _prep_grad(grad, rescale, clip)
    h2 = history + jnp.square(g)
    return weight - lr * g / (jnp.sqrt(h2) + eps), h2
