"""Operator registry + built-in operator library.

Importing this package registers all built-in ops (the reference's
src/operator/ static registration via NNVM_REGISTER_OP happens at library
load; here it happens at import).
"""
from . import registry
from .registry import Operator, get, exists, list_ops, register, register_simple

# built-in op library — import order irrelevant, names must be unique
from . import _op_tensor  # noqa: F401
from . import _op_nn  # noqa: F401
from . import _op_random  # noqa: F401
from . import _op_optimizer  # noqa: F401
from . import _op_linalg  # noqa: F401
from . import _op_contrib  # noqa: F401
from . import _op_quantization  # noqa: F401
from . import _op_image  # noqa: F401
from . import _op_spatial  # noqa: F401
from . import pallas_attention  # noqa: F401
