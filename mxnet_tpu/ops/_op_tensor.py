"""Tensor ops: elementwise, scalar, broadcast, reduce, matrix manipulation,
indexing, init.

Covers the capability of reference src/operator/tensor/* (~55k LoC of
C++/CUDA: elemwise_unary_op*, elemwise_binary_op*, broadcast_reduce_op,
matrix_op, indexing_op, init_op, ordering_op, dot) as JAX emissions — XLA
supplies kernels, fusion and dtype dispatch that the reference hand-writes
via mshadow expression templates and Kernel<OP,xpu>::Launch.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, register_simple
from ..base import MXNetError, np_dtype


# --- unary zoo (reference: elemwise_unary_op_basic/_trig/_pow .cc/.cu) ------
_UNARY = {
    "abs": jnp.abs, "sign": jnp.sign, "rint": jnp.rint, "round": jnp.round,
    "ceil": jnp.ceil, "floor": jnp.floor, "trunc": jnp.trunc,
    "fix": jnp.trunc, "square": jnp.square, "sqrt": jnp.sqrt,
    "rsqrt": lambda x: lax.rsqrt(x), "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp, "log": jnp.log, "log10": jnp.log10, "log2": jnp.log2,
    "log1p": jnp.log1p, "expm1": jnp.expm1,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "arcsin": jnp.arcsin, "arccos": jnp.arccos, "arctan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "sigmoid": jax.nn.sigmoid, "softsign": jax.nn.soft_sign,
    "relu": jax.nn.relu, "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "negative": jnp.negative, "reciprocal": lambda x: 1.0 / x,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "logical_not": lambda x: (x == 0).astype(x.dtype),
    "identity": lambda x: x,
}
for _name, _fn in _UNARY.items():
    register_simple(_name, _fn)

register("_copy")(lambda attrs, x: x)
register("stop_gradient", alias=("BlockGrad",))(lambda attrs, x: lax.stop_gradient(x))
register("make_loss", alias=("MakeLoss",))(lambda attrs, x: x)


@register("clip", scalar_args=("a_min", "a_max"))
def _clip(attrs, x):
    return jnp.clip(x, attrs["a_min"], attrs["a_max"])


@register("cast", alias=("Cast",))
def _cast(attrs, x):
    from ..base import np_dtype
    return x.astype(np_dtype(attrs["dtype"]))


# --- binary (elementwise, same-shape) and broadcast variants ----------------
_BINARY = {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "div": jnp.divide, "mod": jnp.mod, "power": jnp.power,
    "maximum": jnp.maximum, "minimum": jnp.minimum, "hypot": jnp.hypot,
}
_BINARY_LOGIC = {
    "equal": jnp.equal, "not_equal": jnp.not_equal,
    "greater": jnp.greater, "greater_equal": jnp.greater_equal,
    "lesser": jnp.less, "lesser_equal": jnp.less_equal,
    "logical_and": jnp.logical_and, "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
}


def _as_out_dtype(fn):
    def wrapped(a, b):
        return fn(a, b).astype(a.dtype)
    return wrapped


for _name, _fn in _BINARY.items():
    register_simple(f"elemwise_{_name}", _fn)
    register_simple(f"broadcast_{_name}", _fn)
for _name, _fn in _BINARY_LOGIC.items():
    register_simple(f"broadcast_{_name}", _as_out_dtype(_fn))
    register_simple(f"_{_name}", _as_out_dtype(_fn))

register_simple("_grad_add", jnp.add)
register_simple("dot_product", lambda a, b: jnp.vdot(a, b))


def _scalar_op(name, fn, reverse_fn=None):
    @register(f"_{name}_scalar")
    def _f(attrs, x, _fn=fn):
        return _fn(x, jnp.asarray(attrs["scalar"], dtype=x.dtype))
    if reverse_fn is not None:
        @register(f"_r{name}_scalar")
        def _rf(attrs, x, _fn=reverse_fn):
            return _fn(x, jnp.asarray(attrs["scalar"], dtype=x.dtype))


_scalar_op("plus", jnp.add)
_scalar_op("minus", jnp.subtract, lambda x, s: s - x)
_scalar_op("mul", jnp.multiply)
_scalar_op("div", jnp.divide, lambda x, s: s / x)
_scalar_op("mod", jnp.mod, lambda x, s: jnp.mod(s, x))
_scalar_op("power", jnp.power, lambda x, s: jnp.power(s, x))
_scalar_op("maximum", jnp.maximum)
_scalar_op("minimum", jnp.minimum)
_scalar_op("hypot", jnp.hypot)
for _name, _fn in _BINARY_LOGIC.items():
    _scalar_op(_name, _as_out_dtype(_fn))


# --- reductions (reference: broadcast_reduce_op.h) --------------------------
def _norm_axis(attrs):
    axis = attrs.get("axis", None)
    if axis is None or axis == ():
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return int(axis)


def _reduce(fn):
    def compute(attrs, x):
        axis = _norm_axis(attrs)
        keepdims = bool(attrs.get("keepdims", False))
        out = fn(x, axis=axis, keepdims=keepdims)
        if bool(attrs.get("exclude", False)):
            raise NotImplementedError("exclude=True")
        return out
    return compute


for _name, _fn in {
    "sum": jnp.sum, "mean": jnp.mean, "prod": jnp.prod,
    "nansum": jnp.nansum, "nanprod": jnp.nanprod,
    "max": jnp.max, "min": jnp.min,
}.items():
    register(_name)(_reduce(_fn))


@register("norm")
def _norm(attrs, x):
    ord_ = attrs.get("ord", 2)
    axis = _norm_axis(attrs)
    keepdims = bool(attrs.get("keepdims", False))
    if ord_ == 1:
        return jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims))


def _arg_reduce(fn):
    def compute(attrs, x):
        axis = attrs.get("axis", None)
        out = fn(x, axis=None if axis is None else int(axis))
        return out.astype(jnp.float32)  # MXNet returns float indices
    return compute


register("argmax")(_arg_reduce(jnp.argmax))
register("argmin")(_arg_reduce(jnp.argmin))
register("argmax_channel")(lambda attrs, x: jnp.argmax(x, axis=1).astype(jnp.float32))


# --- dot / linalg front door (reference: dot-inl.h, la_op) ------------------
@register("dot")
def _dot(attrs, a, b):
    if attrs.get("transpose_a", False):
        a = a.T if a.ndim == 2 else jnp.moveaxis(a, 0, -1)
    if attrs.get("transpose_b", False):
        b = b.T if b.ndim == 2 else jnp.moveaxis(b, -1, 0)
    if a.ndim == 1 and b.ndim == 1:
        return jnp.vdot(a, b)
    # MXNet dot contracts last axis of a with first axis of b
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register("batch_dot")
def _batch_dot(attrs, a, b):
    if attrs.get("transpose_a", False):
        a = jnp.swapaxes(a, -1, -2)
    if attrs.get("transpose_b", False):
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


# --- shape manipulation (reference: matrix_op.cc) ---------------------------
@register("reshape", alias=("Reshape",))
def _reshape(attrs, x):
    shape = attrs.get("shape")
    if bool(attrs.get("reverse", False)):
        raise NotImplementedError("reshape(reverse=True)")
    # MXNet special codes: 0 copy dim, -1 infer, -2 copy rest, -3 merge two,
    # -4 split (consumes following dims)
    out, src = [], list(x.shape)
    i = 0
    it = iter(range(len(shape)))
    si = 0
    shape = list(shape)
    j = 0
    while j < len(shape):
        s = shape[j]
        if s == 0:
            out.append(src[si]); si += 1
        elif s == -1:
            out.append(-1); si += 1
        elif s == -2:
            out.extend(src[si:]); si = len(src)
        elif s == -3:
            out.append(src[si] * src[si + 1]); si += 2
        elif s == -4:
            d1, d2 = shape[j + 1], shape[j + 2]
            if d1 == -1:
                d1 = src[si] // d2
            if d2 == -1:
                d2 = src[si] // d1
            out.extend([d1, d2]); si += 1; j += 2
        else:
            out.append(s)
            if si < len(src):
                si += 1
        j += 1
    if -1 in out:
        # resolve -1 here: jnp's inference divides by the product of the
        # other dims, which raises ZeroDivisionError for 0-size arrays
        known = 1
        for d in out:
            if d != -1:
                known *= d
        if known:
            out[out.index(-1)] = x.size // known
        elif x.size == 0:
            out[out.index(-1)] = 0
        else:
            raise MXNetError(
                f"cannot infer -1 in reshape {attrs.get('shape')} for "
                f"input shape {x.shape}")
    return jnp.reshape(x, tuple(out))


@register("flatten", alias=("Flatten",))
def _flatten(attrs, x):
    return jnp.reshape(x, (x.shape[0], -1))


@register("transpose")
def _transpose(attrs, x):
    axes = attrs.get("axes", None)
    if axes is None or axes == ():
        axes = tuple(reversed(range(x.ndim)))
    return jnp.transpose(x, axes)


register("expand_dims", scalar_args=("axis",))(
    lambda attrs, x: jnp.expand_dims(x, int(attrs["axis"])))


@register("squeeze")
def _squeeze(attrs, x):
    axis = attrs.get("axis", None)
    return jnp.squeeze(x, axis if axis is None else tuple(
        [axis] if isinstance(axis, int) else axis))


@register("swapaxes", alias=("SwapAxis",), scalar_args=("dim1", "dim2"))
def _swapaxes(attrs, x):
    return jnp.swapaxes(x, int(attrs.get("dim1", 0)), int(attrs.get("dim2", 0)))


@register("concat", alias=("Concat",))
def _concat(attrs, *xs):
    return jnp.concatenate(xs, axis=int(attrs.get("dim", 1)))


@register("stack")
def _stack(attrs, *xs):
    return jnp.stack(xs, axis=int(attrs.get("axis", 0)))


@register("split", alias=("SliceChannel",), num_outputs="num_outputs")
def _split(attrs, x):
    axis = int(attrs.get("axis", 1))
    num = int(attrs["num_outputs"])
    squeeze = bool(attrs.get("squeeze_axis", False))
    parts = jnp.split(x, num, axis=axis)
    if squeeze:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("slice", alias=("crop",))
def _slice(attrs, x):
    begin, end = attrs["begin"], attrs["end"]
    step = attrs.get("step", None) or (1,) * len(begin)
    idx = tuple(slice(b, e, s) for b, e, s in
                zip(begin, end, step))
    return x[idx]


@register("slice_axis", scalar_args=("axis", "begin", "end"))
def _slice_axis(attrs, x):
    axis = int(attrs["axis"])
    begin = int(attrs["begin"])
    end = attrs.get("end")  # absent/None means to-the-end (invoke strips None)
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(begin, None if end is None else int(end))
    return x[tuple(idx)]


@register("slice_like")
def _slice_like(attrs, x, like):
    axes = attrs.get("axes", None) or tuple(range(x.ndim))
    idx = [slice(None)] * x.ndim
    for ax in axes:
        idx[ax] = slice(0, like.shape[ax])
    return x[tuple(idx)]


@register("tile")
def _tile(attrs, x):
    return jnp.tile(x, attrs["reps"])


@register("repeat", scalar_args=("repeats", "axis"))
def _repeat(attrs, x):
    return jnp.repeat(x, int(attrs["repeats"]), axis=attrs.get("axis", None))


@register("flip", alias=("reverse",))
def _flip(attrs, x):
    axis = attrs["axis"]
    return jnp.flip(x, axis if isinstance(axis, int) else tuple(axis))


@register("pad", alias=("Pad",))
def _pad(attrs, x):
    pw = attrs["pad_width"]
    pairs = [(int(pw[2 * i]), int(pw[2 * i + 1])) for i in range(len(pw) // 2)]
    mode = attrs.get("mode", "constant")
    if mode == "constant":
        return jnp.pad(x, pairs, constant_values=attrs.get("constant_value", 0))
    return jnp.pad(x, pairs, mode={"edge": "edge", "reflect": "reflect"}[mode])


@register("depth_to_space")
def _d2s(attrs, x):
    b = int(attrs["block_size"])
    n, c, h, w = x.shape
    x = x.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


@register("space_to_depth")
def _s2d(attrs, x):
    b = int(attrs["block_size"])
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


@register("broadcast_to")
def _broadcast_to(attrs, x):
    shape = tuple(s if s != 0 else x.shape[i] for i, s in enumerate(attrs["shape"]))
    return jnp.broadcast_to(x, shape)


register("broadcast_like")(lambda attrs, x, like: jnp.broadcast_to(x, like.shape))
register("broadcast_axis", alias=("broadcast_axes",))(
    lambda attrs, x: jnp.broadcast_to(x, tuple(
        int(s) if i in ((attrs["axis"],) if isinstance(attrs["axis"], int)
                        else tuple(attrs["axis"])) else x.shape[i]
        for i, s in enumerate(
            [dict(zip((attrs["axis"],) if isinstance(attrs["axis"], int)
                      else tuple(attrs["axis"]),
                      (attrs["size"],) if isinstance(attrs["size"], int)
                      else tuple(attrs["size"]))).get(i, x.shape[i])
             for i in range(x.ndim)]))))


# --- indexing (reference: indexing_op.h) ------------------------------------
@register("take")
def _take(attrs, a, indices):
    axis = int(attrs.get("axis", 0))
    mode = attrs.get("mode", "clip")
    idx = indices.astype(jnp.int32)
    if mode == "clip":
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    elif mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    return jnp.take(a, idx, axis=axis)


def _embedding_grad(attrs, prims, cts):
    """Custom FGradient: with sparse_grad=True the weight cotangent is a
    row-sparse SparseCot over just the looked-up rows (parity: reference
    Embedding backward emits a row_sparse grad, indexing_op.h)."""
    data, weight = prims
    ct = cts[0]
    idx = jnp.clip(data.astype(jnp.int32), 0, weight.shape[0] - 1).reshape(-1)
    vals = ct.reshape(-1, weight.shape[1])
    if attrs.get("sparse_grad"):
        from ..autograd import SparseCot
        return (None, SparseCot(idx, vals, weight.shape))
    dense = jnp.zeros_like(weight).at[idx].add(vals.astype(weight.dtype))
    return (None, dense)


@register("Embedding", fgradient=_embedding_grad,
          input_names=("data", "weight"))
def _embedding(attrs, data, weight):
    idx = data.astype(jnp.int32)
    out = jnp.take(weight, jnp.clip(idx, 0, weight.shape[0] - 1), axis=0)
    return out


@register("pick")
def _pick(attrs, x, index):
    axis = int(attrs.get("axis", -1))
    idx = index.astype(jnp.int32)
    idx = jnp.clip(idx, 0, x.shape[axis] - 1)
    out = jnp.take_along_axis(x, jnp.expand_dims(idx, axis), axis=axis)
    if not bool(attrs.get("keepdims", False)):
        out = jnp.squeeze(out, axis=axis)
    return out


@register("gather_nd")
def _gather_nd(attrs, data, indices):
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@register("scatter_nd")
def _scatter_nd(attrs, data, indices):
    shape = tuple(attrs["shape"])
    out = jnp.zeros(shape, dtype=data.dtype)
    idx = tuple(indices.astype(jnp.int32))
    return out.at[idx].set(data)


@register("one_hot", scalar_args=("depth",))
def _one_hot(attrs, indices):
    depth = int(attrs["depth"])
    on = attrs.get("on_value", 1.0)
    off = attrs.get("off_value", 0.0)
    from ..base import np_dtype
    dtype = np_dtype(attrs.get("dtype", "float32"))
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth)
    return (oh * (on - off) + off).astype(dtype)


@register("where")
def _where(attrs, cond, x, y):
    # 1-D condition over an N-D x selects ROWS (reference
    # control_flow.cc WhereOpShape: csr/1-D condition broadcast on axis 0)
    if cond.ndim == 1 and x.ndim > 1 and cond.shape[0] == x.shape[0]:
        cond = cond.reshape((cond.shape[0],) + (1,) * (x.ndim - 1))
    return jnp.where(cond.astype(bool), x, y)


@register("boolean_mask_fill")
def _boolean_mask_fill(attrs, data, mask):
    """Static-shape-friendly boolean_mask: keeps shape, fills masked-out
    entries with `value` (TPU redesign of contrib.boolean_mask whose output
    shape is data-dependent; see SURVEY.md §7 hard part 8)."""
    value = attrs.get("value", 0.0)
    m = mask.astype(bool)
    m = m.reshape(m.shape + (1,) * (data.ndim - m.ndim))
    return jnp.where(m, data, jnp.asarray(value, dtype=data.dtype))


# --- ordering (reference: ordering_op.cc) -----------------------------------
@register("sort")
def _sort(attrs, x):
    axis = attrs.get("axis", -1)
    out = jnp.sort(x, axis=None if axis is None else int(axis))
    if bool(attrs.get("is_ascend", True)):
        return out
    return jnp.flip(out, axis=-1 if axis is None else int(axis))


@register("argsort")
def _argsort(attrs, x):
    axis = attrs.get("axis", -1)
    idx = jnp.argsort(x, axis=None if axis is None else int(axis))
    if not bool(attrs.get("is_ascend", True)):
        idx = jnp.flip(idx, axis=-1 if axis is None else int(axis))
    from ..base import np_dtype
    return idx.astype(np_dtype(attrs.get("dtype", "float32")))


@register("topk", num_outputs="_dynamic")
def _topk(attrs, x):
    axis = int(attrs.get("axis", -1))
    k = int(attrs.get("k", 1))
    ret_typ = attrs.get("ret_typ", "indices")
    largest = bool(attrs.get("is_ascend", False)) is False
    xm = x if largest else -x
    xm = jnp.moveaxis(xm, axis, -1)
    vals, idxs = lax.top_k(xm, k)
    if not largest:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idxs = jnp.moveaxis(idxs, -1, axis)
    from ..base import np_dtype
    idxs = idxs.astype(np_dtype(attrs.get("dtype", "float32")))
    if ret_typ == "value":
        return vals
    if ret_typ == "indices":
        return idxs
    if ret_typ == "both":
        return vals, idxs
    if ret_typ == "mask":
        oh = jax.nn.one_hot(jnp.moveaxis(idxs.astype(jnp.int32), axis, -1),
                            x.shape[axis]).sum(-2)
        return jnp.moveaxis(oh, -1, axis).astype(x.dtype)
    raise ValueError(ret_typ)


# --- init ops (reference: init_op.cc) ---------------------------------------
def _init_attrs(attrs):
    from ..base import np_dtype
    return tuple(attrs["shape"]), np_dtype(attrs.get("dtype", "float32"))


@register("_zeros")
def _zeros(attrs):
    shape, dtype = _init_attrs(attrs)
    return jnp.zeros(shape, dtype)


@register("_ones")
def _ones(attrs):
    shape, dtype = _init_attrs(attrs)
    return jnp.ones(shape, dtype)


@register("_full")
def _full(attrs):
    shape, dtype = _init_attrs(attrs)
    return jnp.full(shape, attrs["value"], dtype)


@register("_eye")
def _eye(attrs):
    from ..base import np_dtype
    return jnp.eye(int(attrs["N"]), int(attrs.get("M", 0)) or None,
                   k=int(attrs.get("k", 0)),
                   dtype=np_dtype(attrs.get("dtype", "float32")))


@register("_arange")
def _arange(attrs):
    from ..base import check_int32_range, np_dtype
    import math as _math
    start = float(attrs.get("start", 0))
    stop = attrs.get("stop", None)
    step = float(attrs.get("step", 1.0))
    repeat = int(attrs.get("repeat", 1))
    if step:  # host-parameterized size: guard it (stop=None => [0, start))
        hi, lo = (float(stop), start) if stop is not None else (start, 0.0)
        count = max(0, _math.ceil((hi - lo) / step))
        check_int32_range(count * max(repeat, 1), "arange length")
    out = jnp.arange(start, stop, step,
                     dtype=np_dtype(attrs.get("dtype", "float32")))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_linspace")
def _linspace(attrs):
    from ..base import check_int32_range, np_dtype
    num = check_int32_range(int(attrs["num"]), "linspace length")
    return jnp.linspace(attrs["start"], attrs["stop"], num,
                        endpoint=bool(attrs.get("endpoint", True)),
                        dtype=np_dtype(attrs.get("dtype", "float32")))


register("zeros_like")(lambda attrs, x: jnp.zeros_like(x))
register("ones_like")(lambda attrs, x: jnp.ones_like(x))


@register("shape_array")
def _shape_array(attrs, x):
    # the reference emits int64 (src/operator/tensor/elemwise_unary_op.h
    # ShapeComputeCPU); this backend narrows to int32 — LOUDLY: any dim
    # beyond int32 raises instead of letting JAX truncate with a warning
    from ..base import check_int32_range
    for d in x.shape:
        check_int32_range(int(d), "dimension")
    return jnp.asarray(x.shape, dtype=jnp.int32)


@register("size_array")
def _size_array(attrs, x):
    from ..base import check_int32_range
    check_int32_range(int(x.size), "array size")
    return jnp.asarray([x.size], dtype=jnp.int32)


@register("diag")
def _diag(attrs, x):
    k = int(attrs.get("k", 0))
    if x.ndim == 1:
        return jnp.diag(x, k)
    return jnp.diagonal(x, offset=k, axis1=int(attrs.get("axis1", 0)),
                        axis2=int(attrs.get("axis2", 1)))


@register("smooth_l1")
def _smooth_l1(attrs, x):
    sigma = float(attrs.get("scalar", 1.0))
    s2 = sigma * sigma
    return jnp.where(jnp.abs(x) < 1.0 / s2,
                     0.5 * s2 * jnp.square(x),
                     jnp.abs(x) - 0.5 / s2)


@register("reshape_like")
def _reshape_like(attrs, x, like):
    return jnp.reshape(x, like.shape)


@register("histogram", num_outputs=2)
def _histogram(attrs, x, bins):
    cnt, edges = jnp.histogram(x, bins=bins)
    return cnt.astype(jnp.int64), edges


# --- ravel / unravel (reference: src/operator/tensor/ravel.cc) --------------
@register("_ravel_multi_index", alias=("ravel_multi_index",))
def _ravel_multi_index_op(attrs, data):
    shape = tuple(int(s) for s in attrs["shape"])
    # data: (ndim, N) coordinate rows -> (N,) flat indices (row-major)
    strides = []
    acc = 1
    for s in reversed(shape):
        strides.append(acc)
        acc *= s
    strides = jnp.asarray(list(reversed(strides)), data.dtype)
    return jnp.tensordot(strides, data, axes=([0], [0]))


@register("_unravel_index", alias=("unravel_index",))
def _unravel_index_op(attrs, data):
    shape = tuple(int(s) for s in attrs["shape"])
    # data: (N,) flat indices -> (ndim, N) coordinates (row-major)
    coords = []
    rem = data.astype(jnp.int32)
    for s in reversed(shape):
        coords.append(rem % s)
        rem = rem // s
    return jnp.stack(list(reversed(coords))).astype(data.dtype)


# --- AMP cast ops (reference: src/operator/tensor/amp_cast.cc) --------------
def _amp_cast_grad(attrs, primals, cotangents):
    # gradient is the identity cast back to the input dtype (amp_cast.cc
    # registers the backward as another amp_cast)
    return (cotangents[0].astype(primals[0].dtype),)


@register("amp_cast", fgradient=_amp_cast_grad)
def _amp_cast(attrs, data):
    return data.astype(np_dtype(attrs["dtype"]))


def _amp_multicast_grad(attrs, primals, cotangents):
    return tuple(ct.astype(p.dtype) for ct, p in zip(cotangents, primals))


@register("amp_multicast", num_outputs="num_outputs",
          fgradient=_amp_multicast_grad)
def _amp_multicast(attrs, *data):
    # cast every input to the widest floating dtype among them
    # (amp_cast.cc AMPMultiCastType: common widest type)
    widest = jnp.result_type(*[d.dtype for d in data])
    if bool(attrs.get("cast_narrow", False)):
        narrow = min((d.dtype for d in data),
                     key=lambda t: jnp.dtype(t).itemsize)
        widest = narrow
    return tuple(d.astype(widest) for d in data)


# --- add_n / ElementWiseSum (reference: tensor/elemwise_sum.cc:137) ---------
@register("add_n", alias=("ElementWiseSum", "elemwise_sum"))
def _add_n(attrs, *args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


# --- elemwise max/min (reference: tensor/elemwise_binary_op_extended.cc) ----
@register("_maximum", alias=("maximum",))
def _maximum_op(attrs, lhs, rhs):
    return jnp.maximum(lhs, rhs)


@register("_minimum", alias=("minimum",))
def _minimum_op(attrs, lhs, rhs):
    return jnp.minimum(lhs, rhs)


# --- round-4 named-op gap closers -------------------------------------------
# Forward-facing reference registrations that were still missing from the
# registry (VERDICT r03 coverage audit). Each cites its reference source.

@register("hypot", alias=("_hypot",))
def _hypot_binary(attrs, x, y):
    """sqrt(x^2 + y^2) elementwise (reference:
    tensor/elemwise_binary_op_extended.cc _hypot)."""
    return jnp.hypot(x, y)


# Non-broadcast elemwise mod/power (reference registers _mod/_power as the
# same-shape variants of broadcast_mod/broadcast_power,
# tensor/elemwise_binary_op_extended.cc). MXNet mod is fmod-style
# (truncated, sign follows the dividend).
register("_mod")(lambda attrs, x, y: jnp.fmod(x, y))
register("_power")(lambda attrs, x, y: jnp.power(x, y))


@register("batch_take")
def _batch_take(attrs, a, indices):
    """out[i] = a[i, indices[i]] (reference: tensor/indexing_op.cc
    batch_take — a is (N, K), indices (N,))."""
    idx = jnp.clip(indices.astype(jnp.int32), 0, a.shape[1] - 1)
    return jnp.take_along_axis(a, idx[:, None], axis=1).squeeze(1)


def _split_v2_norm(attrs):
    """Normalize indices_or_sections: an int in the indices slot (the
    python-frontend calling convention) means equal sections. A leading 0
    in the indices tuple is the reference backend convention (its python
    frontend prepends it, ndarray.py split_v2) — strip it so both the
    with-0 (serialized reference graphs) and without-0 (direct calls)
    forms yield the same splits and output count."""
    ind = attrs.get("indices", ())
    sections = int(attrs.get("sections", 0))
    if isinstance(ind, (int, float)) and sections == 0:
        sections, ind = int(ind), ()
    ind = tuple(int(i) for i in ind)
    if ind and ind[0] == 0:
        ind = ind[1:]
    return ind, sections


def _split_v2_outs(attrs):
    ind, sections = _split_v2_norm(attrs)
    return sections if sections > 0 else len(ind) + 1


@register("_split_v2", alias=("split_v2",), num_outputs=_split_v2_outs,
          scalar_args=("indices", "axis", "squeeze_axis", "sections"))
def _split_v2(attrs, x):
    """Split by equal sections OR at explicit indices (reference:
    tensor/matrix_op.cc _split_v2; python frontend split_v2)."""
    axis = int(attrs.get("axis", 0))
    squeeze = bool(attrs.get("squeeze_axis", False))
    ind, sections = _split_v2_norm(attrs)
    if sections > 0:
        parts = jnp.split(x, sections, axis=axis)
    else:
        parts = jnp.split(x, list(ind), axis=axis)
    if squeeze:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


def _slice_assign_idx(attrs, lhs):
    begin, end = attrs["begin"], attrs["end"]
    step = attrs.get("step", None) or (1,) * len(begin)
    return tuple(slice(b, e, s) for b, e, s in zip(begin, end, step))


@register("_slice_assign", alias=("_crop_assign",))
def _slice_assign(attrs, lhs, rhs):
    """Functional x[begin:end] = rhs — returns lhs with the cropped region
    replaced (reference: tensor/matrix_op.cc _slice_assign:529, backing
    NDArray.__setitem__'s non-trivial path)."""
    return lhs.at[_slice_assign_idx(attrs, lhs)].set(rhs.astype(lhs.dtype))


@register("_slice_assign_scalar", alias=("_crop_assign_scalar",))
def _slice_assign_scalar(attrs, lhs):
    return lhs.at[_slice_assign_idx(attrs, lhs)].set(
        jnp.asarray(float(attrs.get("scalar", 0.0)), lhs.dtype))


@register("_scatter_set_nd")
def _scatter_set_nd_op(attrs, lhs, rhs, indices):
    """scatter_nd that keeps non-indexed elements of lhs (reference:
    tensor/indexing_op.cc _scatter_set_nd:1008, backing x[idx_nd] = v)."""
    return lhs.at[tuple(indices.astype(jnp.int32))].set(rhs.astype(lhs.dtype))


# Scatter-mode elemwise variants (reference: tensor/elemwise_scatter_op.cc).
# There they exist to keep row_sparse storage on the result; dense numerics
# are identical to the plain ops, and the NDArray sparse layer preserves
# stype. Registered so frontends/serialized graphs that name them resolve.
register("_scatter_elemwise_div")(lambda attrs, x, y: x / y)
register("_scatter_plus_scalar")(
    lambda attrs, x: x + jnp.asarray(float(attrs.get("scalar", 0.0)), x.dtype))
register("_scatter_minus_scalar")(
    lambda attrs, x: x - jnp.asarray(float(attrs.get("scalar", 0.0)), x.dtype))


@register("_identity_with_attr_like_rhs")
def _identity_with_attr_like_rhs(attrs, lhs, rhs):
    """Identity on lhs whose output storage attrs follow rhs (reference:
    tensor/elemwise_unary_op_basic.cc — used by the gradient pass for
    stype-preserving zeros). Storage type is an NDArray-layer concern here;
    the dense value is lhs unchanged."""
    return lhs


@register("_zeros_without_dtype")
def _zeros_without_dtype(attrs):
    """zeros() with inferred-later dtype (reference: tensor/init_op.cc
    _zeros_without_dtype, dtype=-1 → default float32)."""
    dt = attrs.get("dtype", None)
    dtype = np_dtype(dt) if dt not in (None, -1, "-1") else jnp.float32
    return jnp.zeros(tuple(attrs["shape"]), dtype)


@register("_rnn_param_concat")
def _rnn_param_concat(attrs, *xs):
    """Concat specialized for RNN parameter packing (reference:
    tensor/matrix_op.cc _rnn_param_concat — same kernel as concat, shape
    inference tolerates unknown param dims; here shapes are always known)."""
    return jnp.concatenate(xs, axis=int(attrs.get("dim", 0)))


@register("hard_sigmoid")
def _hard_sigmoid(attrs, x):
    """clip(alpha*x + beta, 0, 1) (reference:
    tensor/elemwise_unary_op_basic.cc hard_sigmoid)."""
    alpha = float(attrs.get("alpha", 0.2))
    beta = float(attrs.get("beta", 0.5))
    return jnp.clip(alpha * x + beta, 0.0, 1.0)


@register("square_sum", alias=("_square_sum",))
def _square_sum(attrs, x):
    """sum(x*x) over axis (reference: tensor/square_sum-inl.h — fused
    square+sum written for row_sparse gradients; XLA fuses the dense form)."""
    axis = attrs.get("axis", None)
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    elif axis is not None:
        axis = int(axis)
    return jnp.sum(jnp.square(x), axis=axis,
                   keepdims=bool(attrs.get("keepdims", False)))


@register("sparse_retain", alias=("_sparse_retain",))
def _sparse_retain_op(attrs, data, indices):
    """Keep only the rows named by indices, zero the rest (reference:
    tensor/sparse_retain-inl.h — there data is row_sparse; the dense
    semantics are a row mask)."""
    idx = jnp.clip(indices.astype(jnp.int32), 0, data.shape[0] - 1)
    mask = jnp.zeros((data.shape[0],), jnp.bool_).at[idx].set(True)
    return jnp.where(mask.reshape((-1,) + (1,) * (data.ndim - 1)), data,
                     jnp.zeros((), data.dtype))


@register("cast_storage")
def _cast_storage_op(attrs, x):
    """Dense compute of cast_storage (reference: tensor/cast_storage-inl.h).
    The value is unchanged; actual dense<->row_sparse/csr container
    conversion happens in ndarray.sparse.cast_storage, which the NDArray
    frontend routes to for stype != 'default'."""
    return x
