"""Detection / contrib operator family.

Reference: src/operator/contrib/bounding_box.cc (_contrib_box_nms:36,
_contrib_box_iou:117, _contrib_bipartite_matching:158),
multibox_prior.cc, multibox_target.cc:71 (MultiBoxTargetForward),
multibox_detection.cc:83 (MultiBoxDetectionForward), roi_align.cc and
src/operator/roi_pooling.cc.

TPU redesign (SURVEY.md §7 hard part 8 — dynamic-shape ops under XLA
static shapes): every op here is a *bounded-shape + masking* program.
Where the reference compacts variable-length results with CopyIf /
std::sort on the host, these emit fixed-shape sort + prefix-sum-scatter
programs: invalid slots carry -1 sentinels exactly like the reference's
output contract, so downstream consumers see the same API.  Sequential
dependencies (greedy NMS, bipartite matching) lower to one
``lax.fori_loop``/``lax.scan`` — a single XLA While op — instead of host
loops; everything is vmapped over the batch and differentiable where the
reference defines gradients (NMS backward = scatter of the kept rows,
ROIAlign backward = bilinear scatter-add, both produced by JAX AD from
the gather-based forwards).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _floats(v, default):
    if v is None:
        return tuple(float(x) for x in default)
    if isinstance(v, (int, float)):
        return (float(v),)
    return tuple(float(x) for x in v)


def _center_to_corner(b):
    x, y, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2], axis=-1)


def _corner_to_center(b):
    l, t, r, bo = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([(l + r) / 2, (t + bo) / 2, r - l, bo - t], axis=-1)


def _box_area(b, fmt="corner"):
    if fmt == "corner":
        w = b[..., 2] - b[..., 0]
        h = b[..., 3] - b[..., 1]
    else:
        w = b[..., 2]
        h = b[..., 3]
    return jnp.where((w < 0) | (h < 0), 0.0, w * h)


def _pairwise_iou(a, b, fmt="corner"):
    """IoU of (N,4) x (M,4) -> (N,M), matching CalculateOverlap
    (multibox_detection.cc:73): union<=0 -> 0."""
    ac = a if fmt == "corner" else _center_to_corner(a)
    bc = b if fmt == "corner" else _center_to_corner(b)
    tl = jnp.maximum(ac[:, None, :2], bc[None, :, :2])
    br = jnp.minimum(ac[:, None, 2:], bc[None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = (_box_area(ac, "corner")[:, None]
             + _box_area(bc, "corner")[None, :] - inter)
    return jnp.where(union <= 0, 0.0, inter / union)


# ---------------------------------------------------------------------------
# box_iou
# ---------------------------------------------------------------------------
@register("_contrib_box_iou", alias=("box_iou",))
def _contrib_box_iou(attrs, lhs, rhs):
    fmt = attrs.get("format", "corner")
    lsh, rsh = lhs.shape[:-1], rhs.shape[:-1]
    out = _pairwise_iou(lhs.reshape(-1, 4), rhs.reshape(-1, 4), fmt)
    return out.reshape(lsh + rsh)


# ---------------------------------------------------------------------------
# box_nms
# ---------------------------------------------------------------------------
def _nms_one(data, *, overlap_thresh, valid_thresh, topk, coord_start,
             score_index, id_index, background_id, force_suppress,
             in_format, out_format):
    """Greedy NMS on one batch element (N, W) -> (out (N, W), record (N,)).

    Kept boxes are compacted to the front in descending-score order;
    dropped slots are -1 (bounding_box-inl.h nms_assign).
    """
    n, w = data.shape
    scores = data[:, score_index]
    valid = scores > valid_thresh
    if id_index >= 0:
        valid &= data[:, id_index] != background_id

    # stable desc sort of valid scores; invalid slots sink to the end
    order = jnp.argsort(jnp.where(valid, -scores, jnp.inf), stable=True)
    sdata = data[order]
    svalid = valid[order]
    topk_eff = n if topk < 0 else min(n, topk)
    cand = svalid & (jnp.arange(n) < topk_eff)

    boxes = sdata[:, coord_start:coord_start + 4]
    iou = _pairwise_iou(boxes, boxes, in_format)
    if id_index >= 0 and not force_suppress:
        cls = sdata[:, id_index]
        suppress_ok = cls[:, None] == cls[None, :]
        sup_mat = (iou > overlap_thresh) & suppress_ok
    else:
        sup_mat = iou > overlap_thresh

    later = jnp.arange(n)[None, :] > jnp.arange(n)[:, None]

    def body(i, keep):
        sup = sup_mat[i] & later[i] & keep
        return jnp.where(keep[i], keep & ~sup, keep)

    keep = lax.fori_loop(0, topk_eff, body, cand)

    if in_format != out_format:
        conv = (_center_to_corner if out_format == "corner"
                else _corner_to_center)
        sdata = sdata.at[:, coord_start:coord_start + 4].set(conv(boxes))

    # prefix-sum scatter: kept rows compact to the front, others dropped
    pos = jnp.cumsum(keep) - 1
    idx = jnp.where(keep, pos, n)
    out = jnp.full((n, w), -1.0, data.dtype).at[idx].set(sdata, mode="drop")
    rec = jnp.full((n,), -1.0, data.dtype).at[idx].set(
        order.astype(data.dtype), mode="drop")
    return out, rec


@register("_contrib_box_nms", alias=("box_nms",), num_outputs=2,
          num_visible=1)
def _contrib_box_nms(attrs, data):
    kw = dict(
        overlap_thresh=float(attrs.get("overlap_thresh", 0.5)),
        valid_thresh=float(attrs.get("valid_thresh", 0.0)),
        topk=int(attrs.get("topk", -1)),
        coord_start=int(attrs.get("coord_start", 2)),
        score_index=int(attrs.get("score_index", 1)),
        id_index=int(attrs.get("id_index", -1)),
        background_id=int(attrs.get("background_id", -1)),
        force_suppress=bool(attrs.get("force_suppress", False)),
        in_format=attrs.get("in_format", "corner"),
        out_format=attrs.get("out_format", "corner"),
    )
    shape = data.shape
    n, w = shape[-2], shape[-1]
    flat = data.reshape(-1, n, w)
    out, rec = jax.vmap(lambda d: _nms_one(d, **kw))(flat)
    # record holds the ORIGINAL index flattened over (batch, num_elem)
    # (bounding_box-inl.h nms_assign: record[i*num+count] = location)
    offs = jnp.arange(flat.shape[0], dtype=data.dtype) * n
    rec = jnp.where(rec >= 0, rec + offs[:, None], -1.0)
    return out.reshape(shape), rec.reshape(shape[:-1] + (1,))


# ---------------------------------------------------------------------------
# bipartite_matching
# ---------------------------------------------------------------------------
def _bipartite_one(score, *, is_ascend, threshold, topk):
    n, m = score.shape
    k = min(n, m) if topk < 0 else min(topk, min(n, m))
    big = jnp.inf
    sgn = 1.0 if is_ascend else -1.0  # minimise sgn*score

    def body(carry, _):
        row_free, col_free, row_match, col_match = carry
        masked = jnp.where(row_free[:, None] & col_free[None, :],
                           sgn * score, big)
        flat = jnp.argmin(masked)
        ri, ci = flat // m, flat % m
        val = score[ri, ci]
        ok = jnp.where(is_ascend, val <= threshold, val >= threshold)
        ok &= masked[ri, ci] < big
        r_sel = (jnp.arange(n) == ri) & ok
        c_sel = (jnp.arange(m) == ci) & ok
        row_free = row_free & ~r_sel
        col_free = col_free & ~c_sel
        row_match = jnp.where(r_sel, ci, row_match)
        col_match = jnp.where(c_sel, ri, col_match)
        return (row_free, col_free, row_match, col_match), 0

    init = (jnp.ones(n, bool), jnp.ones(m, bool),
            jnp.full(n, -1.0, score.dtype), jnp.full(m, -1.0, score.dtype))
    (rf, cf, rm, cm), _ = lax.scan(body, init, None, length=k)
    return rm, cm


@register("_contrib_bipartite_matching", alias=("bipartite_matching",),
          num_outputs=2)
def _contrib_bipartite_matching(attrs, data):
    kw = dict(is_ascend=bool(attrs.get("is_ascend", False)),
              threshold=float(attrs.get("threshold", 0.0)),
              topk=int(attrs.get("topk", -1)))
    shape = data.shape
    n, m = shape[-2], shape[-1]
    flat = data.reshape(-1, n, m)
    rm, cm = jax.vmap(lambda s: _bipartite_one(s, **kw))(flat)
    return rm.reshape(shape[:-1]), cm.reshape(shape[:-2] + (m,))


# ---------------------------------------------------------------------------
# MultiBoxPrior
# ---------------------------------------------------------------------------
@register("_contrib_MultiBoxPrior", alias=("MultiBoxPrior",))
def _contrib_multibox_prior(attrs, data):
    """Anchor generation (multibox_prior.cc:31 MultiBoxPriorForward).

    Output (1, H*W*(num_sizes+num_ratios-1), 4) corner boxes; per
    location the order is [each size with ratio0, then each extra ratio
    with size0] in row-major (y, x) scan — byte-for-byte the reference's
    layout.
    """
    sizes = _floats(attrs.get("sizes"), (1.0,))
    ratios = _floats(attrs.get("ratios"), (1.0,))
    steps = _floats(attrs.get("steps"), (-1.0, -1.0))
    offsets = _floats(attrs.get("offsets"), (0.5, 0.5))
    clip = bool(attrs.get("clip", False))
    h, w = data.shape[-2], data.shape[-1]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    dt = data.dtype if jnp.issubdtype(data.dtype, jnp.floating) \
        else jnp.float32

    cy = (jnp.arange(h, dtype=dt) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=dt) + offsets[1]) * step_x
    # per-location anchor half-sizes, reference order
    half = []
    r0 = jnp.sqrt(jnp.asarray(ratios[0], dt))
    for s in sizes:
        half.append((s * h / w * r0 / 2, s / r0 / 2))
    for r in ratios[1:]:
        rs = jnp.sqrt(jnp.asarray(r, dt))
        half.append((sizes[0] * h / w * rs / 2, sizes[0] / rs / 2))
    hw = jnp.stack([jnp.asarray(a, dt) for a, _ in half])  # (K,) half-width
    hh = jnp.stack([jnp.asarray(b, dt) for _, b in half])  # (K,) half-height

    cyg = cy[:, None, None]      # (H,1,1)
    cxg = cx[None, :, None]      # (1,W,1)
    boxes = jnp.stack([
        jnp.broadcast_to(cxg - hw, (h, w, hw.shape[0])),
        jnp.broadcast_to(cyg - hh, (h, w, hw.shape[0])),
        jnp.broadcast_to(cxg + hw, (h, w, hw.shape[0])),
        jnp.broadcast_to(cyg + hh, (h, w, hw.shape[0])),
    ], axis=-1)                  # (H, W, K, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes.reshape(1, -1, 4)


# ---------------------------------------------------------------------------
# MultiBoxTarget
# ---------------------------------------------------------------------------
def _encode_loc(anchor, gt, variances):
    """(gx-ax)/aw/vx ... log(gw/aw)/vw (multibox_target.cc:32
    AssignLocTargets)."""
    vx, vy, vw, vh = variances
    aw = anchor[..., 2] - anchor[..., 0]
    ah = anchor[..., 3] - anchor[..., 1]
    ax = (anchor[..., 0] + anchor[..., 2]) * 0.5
    ay = (anchor[..., 1] + anchor[..., 3]) * 0.5
    gw = gt[..., 2] - gt[..., 0]
    gh = gt[..., 3] - gt[..., 1]
    gx = (gt[..., 0] + gt[..., 2]) * 0.5
    gy = (gt[..., 1] + gt[..., 3]) * 0.5
    eps = jnp.finfo(anchor.dtype).tiny
    return jnp.stack([
        (gx - ax) / aw / vx,
        (gy - ay) / ah / vy,
        jnp.log(jnp.maximum(gw / aw, eps)) / vw,
        jnp.log(jnp.maximum(gh / ah, eps)) / vh,
    ], axis=-1)


def _mbox_target_one(anchors, label, cls_pred, *, overlap_threshold,
                     ignore_label, negative_mining_ratio,
                     negative_mining_thresh, variances):
    """One batch element of MultiBoxTargetForward (multibox_target.cc:71).

    anchors (A,4) corner, label (L,>=5) [cls,x1,y1,x2,y2,...] with -1
    padding rows, cls_pred (C,A).  Returns loc_target (A*4), loc_mask
    (A*4), cls_target (A).
    """
    a, l = anchors.shape[0], label.shape[0]
    dt = anchors.dtype
    # reference stops scanning labels at the first -1 class row
    valid_gt = jnp.cumprod(label[:, 0] != -1.0).astype(bool)
    n_valid = valid_gt.sum()

    iou = _pairwise_iou(anchors, label[:, 1:5], "corner")   # (A, L)
    iou = jnp.where(valid_gt[None, :], iou, -1.0)

    # stage 1: greedy bipartite matching, one gt per iteration
    def body(carry, _):
        a_free, g_free, match_gt, match_iou = carry
        masked = jnp.where(a_free[:, None] & g_free[None, :], iou, -1e9)
        flat = jnp.argmax(masked)
        ai, gi = flat // l, flat % l
        val = masked.reshape(-1)[flat]
        ok = val > 1e-6
        a_sel = (jnp.arange(a) == ai) & ok
        g_sel = (jnp.arange(l) == gi) & ok
        return (a_free & ~a_sel, g_free & ~g_sel,
                jnp.where(a_sel, gi, match_gt),
                jnp.where(a_sel, val, match_iou)), 0

    init = (jnp.ones(a, bool), jnp.ones(l, bool),
            jnp.zeros(a, jnp.int32), jnp.full(a, -1.0, dt))
    (a_free, _, match_gt, match_iou), _ = lax.scan(body, init, None,
                                                   length=l)

    # stage 2: threshold matching for still-free anchors
    best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)
    best_iou = jnp.max(iou, axis=1)
    stage2 = a_free & (best_iou > overlap_threshold) & (n_valid > 0)
    match_gt = jnp.where(stage2, best_gt, match_gt)
    pos = (~a_free) | stage2
    # per-anchor best overlap regardless of matching (negative mining key)
    any_iou = jnp.where(a_free, best_iou, match_iou)

    if negative_mining_ratio > 0:
        num_pos = pos.sum()
        num_neg = jnp.minimum(
            (num_pos * negative_mining_ratio).astype(jnp.int32),
            a - num_pos)
        cand = (~pos) & (any_iou < negative_mining_thresh)
        # hardest negatives = lowest background (class 0) probability
        logits = cls_pred.astype(jnp.float32)
        prob_bg = jax.nn.softmax(logits, axis=0)[0]
        key = jnp.where(cand, -prob_bg, -jnp.inf)
        desc = jnp.argsort(-key, stable=True)
        rank = jnp.argsort(desc, stable=True)
        neg = cand & (rank < num_neg)
    else:
        neg = ~pos

    gt_cls = label[match_gt, 0]
    gt_box = label[match_gt, 1:5]
    cls_target = jnp.where(
        pos, gt_cls + 1.0,
        jnp.where(neg, 0.0, float(ignore_label))).astype(dt)
    loc = _encode_loc(anchors, gt_box, variances)
    loc_target = jnp.where(pos[:, None], loc, 0.0).astype(dt)
    loc_mask = jnp.where(pos[:, None],
                         jnp.ones((a, 4), dt), jnp.zeros((a, 4), dt))
    # no valid gt: reference leaves everything at init
    # (loc 0 / mask 0 / cls ignore_label)
    has_gt = n_valid > 0
    cls_target = jnp.where(has_gt, cls_target, float(ignore_label))
    loc_target = jnp.where(has_gt, loc_target, 0.0)
    loc_mask = jnp.where(has_gt, loc_mask, 0.0)
    return loc_target.reshape(-1), loc_mask.reshape(-1), cls_target


@register("_contrib_MultiBoxTarget", alias=("MultiBoxTarget",),
          num_outputs=3)
def _contrib_multibox_target(attrs, anchor, label, cls_pred):
    kw = dict(
        overlap_threshold=float(attrs.get("overlap_threshold", 0.5)),
        ignore_label=float(attrs.get("ignore_label", -1.0)),
        negative_mining_ratio=float(attrs.get("negative_mining_ratio",
                                              -1.0)),
        negative_mining_thresh=float(attrs.get("negative_mining_thresh",
                                               0.5)),
        variances=_floats(attrs.get("variances"), (0.1, 0.1, 0.2, 0.2)),
    )
    anchors = anchor.reshape(-1, 4)
    lt, lm, ct = jax.vmap(
        lambda lb, cp: _mbox_target_one(anchors, lb, cp, **kw))(
            label, cls_pred)
    return lt, lm, ct


# ---------------------------------------------------------------------------
# MultiBoxDetection
# ---------------------------------------------------------------------------
def _decode_loc(anchors, loc_pred, variances, clip):
    """TransformLocations (multibox_detection.cc:46)."""
    vx, vy, vw, vh = variances
    al, at, ar, ab = (anchors[:, 0], anchors[:, 1],
                      anchors[:, 2], anchors[:, 3])
    aw, ah = ar - al, ab - at
    ax, ay = (al + ar) / 2, (at + ab) / 2
    p = loc_pred.reshape(-1, 4)
    ox = p[:, 0] * vx * aw + ax
    oy = p[:, 1] * vy * ah + ay
    ow = jnp.exp(p[:, 2] * vw) * aw / 2
    oh = jnp.exp(p[:, 3] * vh) * ah / 2
    out = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _mbox_detection_one(cls_prob, loc_pred, anchors, *, clip, threshold,
                        nms_threshold, force_suppress, variances, nms_topk):
    c, a = cls_prob.shape
    dt = cls_prob.dtype
    # class 0 is background (multibox_detection.cc:112 scans classes
    # from 1; the reference kernel likewise ignores its background_id
    # param — the python wrapper below rejects non-zero values instead
    # of silently mis-classifying)
    fg = cls_prob[1:, :]
    score = jnp.max(fg, axis=0)
    cid = jnp.argmax(fg, axis=0).astype(dt)           # 0-based fg class
    cid = jnp.where(score < threshold, -1.0, cid)
    boxes = _decode_loc(anchors, loc_pred, variances, clip)
    det = jnp.concatenate([cid[:, None], score[:, None], boxes], axis=1)

    valid = cid >= 0
    order = jnp.argsort(jnp.where(valid, -score, jnp.inf), stable=True)
    sdet = det[order]
    svalid = valid[order]
    nkeep = a if nms_topk < 0 else min(nms_topk, a)
    # beyond-topk detections are discarded (id -> -1), rows remain
    sdet = sdet.at[:, 0].set(
        jnp.where(svalid & (jnp.arange(a) >= nkeep), -1.0, sdet[:, 0]))
    # blank out invalid rows entirely (reference preinitialises out to -1)
    sdet = jnp.where(svalid[:, None], sdet, -1.0)

    iou = _pairwise_iou(sdet[:, 2:6], sdet[:, 2:6], "corner")
    if force_suppress:
        same = jnp.ones((a, a), bool)
    else:
        same = sdet[:, 0][:, None] == sdet[:, 0][None, :]
    sup_mat = (iou >= nms_threshold) & same
    later = jnp.arange(a)[None, :] > jnp.arange(a)[:, None]

    def body(i, ids):
        alive_i = ids[i] >= 0
        sup = sup_mat[i] & later[i] & (ids >= 0)
        return jnp.where(alive_i, jnp.where(sup, -1.0, ids), ids)

    ids = lax.fori_loop(0, nkeep, body, sdet[:, 0])
    return sdet.at[:, 0].set(ids)


@register("_contrib_MultiBoxDetection", alias=("MultiBoxDetection",))
def _contrib_multibox_detection(attrs, cls_prob, loc_pred, anchor):
    if int(attrs.get("background_id", 0)) != 0:
        raise NotImplementedError(
            "MultiBoxDetection: only background_id=0 is supported (the "
            "reference CPU/GPU kernels also hardcode class 0 as background)")
    kw = dict(
        clip=bool(attrs.get("clip", True)),
        threshold=float(attrs.get("threshold", 0.01)),
        nms_threshold=float(attrs.get("nms_threshold", 0.5)),
        force_suppress=bool(attrs.get("force_suppress", False)),
        variances=_floats(attrs.get("variances"), (0.1, 0.1, 0.2, 0.2)),
        nms_topk=int(attrs.get("nms_topk", -1)),
    )
    anchors = anchor.reshape(-1, 4)
    return jax.vmap(
        lambda cp, lp: _mbox_detection_one(cp, lp, anchors, **kw))(
            cls_prob, loc_pred)


# ---------------------------------------------------------------------------
# ROIAlign
# ---------------------------------------------------------------------------
def _roi_align_one(data, roi, *, pooled_h, pooled_w, spatial_scale,
                   sample_ratio, position_sensitive):
    """One ROI of ROIAlignForward (roi_align.cc:150): average of bilinear
    samples per bin; batch index in roi[0].

    Deviation (documented): sample_ratio <= 0 means an adaptive
    per-roi grid in the reference (ceil(roi_size/pooled)); XLA needs a
    static grid, so <=0 falls back to 2 samples per bin axis.
    """
    b, c, h, w = data.shape
    sg = sample_ratio if sample_ratio > 0 else 2
    feat = jnp.take(data, roi[0].astype(jnp.int32), axis=0,
                    mode="clip")                       # (C, H, W)
    start_w = roi[1] * spatial_scale
    start_h = roi[2] * spatial_scale
    end_w = roi[3] * spatial_scale
    end_h = roi[4] * spatial_scale
    roi_w = jnp.maximum(end_w - start_w, 1.0)
    roi_h = jnp.maximum(end_h - start_h, 1.0)
    bin_w = roi_w / pooled_w
    bin_h = roi_h / pooled_h

    def axis_coords(start, bin_sz, pooled):
        # sample centres: start + p*bin + (i+.5)*bin/sg
        p = jnp.arange(pooled, dtype=data.dtype)[:, None]
        i = jnp.arange(sg, dtype=data.dtype)[None, :]
        return (start + p * bin_sz + (i + 0.5) * bin_sz / sg).reshape(-1)

    ys = axis_coords(start_h, bin_h, pooled_h)          # (Ph*sg,)
    xs = axis_coords(start_w, bin_w, pooled_w)          # (Pw*sg,)

    def bilinear(coords, size):
        # outside [-1, size] contributes zero; clamp<0 to 0 (roi_align.cc
        # bilinear_interpolate edge handling)
        inside = (coords >= -1.0) & (coords <= size)
        cc = jnp.clip(coords, 0.0, size - 1)
        lo = jnp.floor(cc)
        hi = jnp.minimum(lo + 1, size - 1)
        frac = cc - lo
        return (lo.astype(jnp.int32), hi.astype(jnp.int32), frac,
                inside.astype(data.dtype))

    y0, y1, fy, my = bilinear(ys, h)
    x0, x1, fx, mx = bilinear(xs, w)

    def gather(yi, xi):
        return feat[:, yi[:, None], xi[None, :]]        # (C, Ny, Nx)

    val = ((1 - fy)[None, :, None] * (1 - fx)[None, None, :] * gather(y0, x0)
           + (1 - fy)[None, :, None] * fx[None, None, :] * gather(y0, x1)
           + fy[None, :, None] * (1 - fx)[None, None, :] * gather(y1, x0)
           + fy[None, :, None] * fx[None, None, :] * gather(y1, x1))
    val = val * my[None, :, None] * mx[None, None, :]
    val = val.reshape(-1, pooled_h, sg, pooled_w, sg).mean(axis=(2, 4))

    if position_sensitive:
        c_out = c // (pooled_h * pooled_w)
        ph = jnp.arange(pooled_h)[:, None]
        pw = jnp.arange(pooled_w)[None, :]
        chan = (jnp.arange(c_out)[:, None, None] * pooled_h * pooled_w
                + ph[None] * pooled_w + pw[None])       # (Co,Ph,Pw)
        val = jnp.take_along_axis(
            val[None].repeat(c_out, 0).reshape(c_out, c, pooled_h,
                                               pooled_w),
            chan[:, None], axis=1).squeeze(1)
    return val


@register("_contrib_ROIAlign", alias=("ROIAlign",))
def _contrib_roi_align(attrs, data, rois):
    pooled = attrs["pooled_size"]
    ph, pw = int(pooled[0]), int(pooled[1])
    kw = dict(pooled_h=ph, pooled_w=pw,
              spatial_scale=float(attrs.get("spatial_scale", 1.0)),
              sample_ratio=int(attrs.get("sample_ratio", -1)),
              position_sensitive=bool(attrs.get("position_sensitive",
                                                False)))
    return jax.vmap(lambda r: _roi_align_one(data, r, **kw))(rois)


# ---------------------------------------------------------------------------
# ROIPooling (legacy top-level op, src/operator/roi_pooling.cc)
# ---------------------------------------------------------------------------
def _roi_pool_one(data, roi, *, pooled_h, pooled_w, spatial_scale):
    b, c, h, w = data.shape
    dt = data.dtype
    feat = jnp.take(data, roi[0].astype(jnp.int32), axis=0, mode="clip")
    start_w = jnp.round(roi[1] * spatial_scale)
    start_h = jnp.round(roi[2] * spatial_scale)
    end_w = jnp.round(roi[3] * spatial_scale)
    end_h = jnp.round(roi[4] * spatial_scale)
    roi_h = jnp.maximum(end_h - start_h + 1, 1.0)
    roi_w = jnp.maximum(end_w - start_w + 1, 1.0)

    def bin_bounds(p, roi_sz, start, pooled, size):
        lo = jnp.floor(p * roi_sz / pooled) + start
        hi = jnp.ceil((p + 1) * roi_sz / pooled) + start
        return (jnp.clip(lo, 0, size), jnp.clip(hi, 0, size))

    prange_h = jnp.arange(pooled_h, dtype=dt)
    prange_w = jnp.arange(pooled_w, dtype=dt)
    h0, h1 = bin_bounds(prange_h, roi_h, start_h, pooled_h, h)  # (Ph,)
    w0, w1 = bin_bounds(prange_w, roi_w, start_w, pooled_w, w)
    hi = jnp.arange(h, dtype=dt)
    wi = jnp.arange(w, dtype=dt)
    mask_h = (hi[None, :] >= h0[:, None]) & (hi[None, :] < h1[:, None])
    mask_w = (wi[None, :] >= w0[:, None]) & (wi[None, :] < w1[:, None])
    m = mask_h[:, None, :, None] & mask_w[None, :, None, :]  # (Ph,Pw,H,W)
    neg = jnp.asarray(-jnp.inf, dt)
    vals = jnp.where(m[None], feat[:, None, None], neg)      # (C,Ph,Pw,H,W)
    out = vals.max(axis=(3, 4))
    empty = ~m.any(axis=(2, 3))
    return jnp.where(empty[None], jnp.zeros((), dt), out)


@register("ROIPooling")
def _roi_pooling(attrs, data, rois):
    pooled = attrs["pooled_size"]
    kw = dict(pooled_h=int(pooled[0]), pooled_w=int(pooled[1]),
              spatial_scale=float(attrs.get("spatial_scale", 1.0)))
    return jax.vmap(lambda r: _roi_pool_one(data, r, **kw))(rois)


# ---------------------------------------------------------------------------
# transformer helpers (src/operator/contrib/transformer.cc)
# ---------------------------------------------------------------------------
@register("_contrib_div_sqrt_dim", alias=("div_sqrt_dim",))
def _contrib_div_sqrt_dim(attrs, data):
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], data.dtype))


# ---------------------------------------------------------------------------
# Proposal / MultiProposal (RPN)
# ---------------------------------------------------------------------------
def _generate_base_anchors(stride, scales, ratios):
    """py_faster_rcnn anchor generation (proposal.cc GenerateAnchors):
    base box [0,0,stride-1,stride-1], ratio sweep then scale sweep."""
    base = stride
    x_ctr = (base - 1) * 0.5
    size = base * base
    anchors = []
    for r in ratios:
        size_r = size / r
        ws = round(size_r ** 0.5)
        hs = round(ws * r)
        for s in scales:
            w, h = ws * s, hs * s
            anchors.append([x_ctr - 0.5 * (w - 1), x_ctr - 0.5 * (h - 1),
                            x_ctr + 0.5 * (w - 1), x_ctr + 0.5 * (h - 1)])
    import numpy as np
    return np.asarray(anchors, np.float32)        # (A, 4)


def _proposal_one(scores, bbox_deltas, im_info, anchors, *, stride,
                  pre_nms, post_nms, nms_thresh, min_size):
    """One image of ProposalForward (proposal.cc:316-414).

    scores (A,H,W) foreground scores, bbox_deltas (4A,H,W), im_info
    (3,) = [height, width, scale]; anchors (A,4) base anchors.
    Returns rois (post_nms, 4) and scores (post_nms,)."""
    a, h, w = scores.shape
    sx = jnp.arange(w, dtype=jnp.float32) * stride
    sy = jnp.arange(h, dtype=jnp.float32) * stride
    shift = jnp.stack(
        [jnp.tile(sx[None, :], (h, 1)), jnp.tile(sy[:, None], (1, w)),
         jnp.tile(sx[None, :], (h, 1)), jnp.tile(sy[:, None], (1, w))],
        axis=-1)                                     # (H,W,4)
    all_anchors = (anchors[None, None] + shift[:, :, None]) \
        .reshape(-1, 4)                              # (H*W*A, 4)

    deltas = bbox_deltas.reshape(a, 4, h, w).transpose(2, 3, 0, 1) \
        .reshape(-1, 4)                              # (H*W*A, 4)
    score = scores.transpose(1, 2, 0).reshape(-1)    # (H*W*A,)

    # decode (pixel convention with the +1 widths, proposal.cc
    # BBoxTransformInv)
    ws = all_anchors[:, 2] - all_anchors[:, 0] + 1.0
    hs = all_anchors[:, 3] - all_anchors[:, 1] + 1.0
    cx = all_anchors[:, 0] + 0.5 * (ws - 1.0)
    cy = all_anchors[:, 1] + 0.5 * (hs - 1.0)
    pcx = deltas[:, 0] * ws + cx
    pcy = deltas[:, 1] * hs + cy
    pw = jnp.exp(deltas[:, 2]) * ws
    ph = jnp.exp(deltas[:, 3]) * hs
    x1 = pcx - 0.5 * (pw - 1.0)
    y1 = pcy - 0.5 * (ph - 1.0)
    x2 = pcx + 0.5 * (pw - 1.0)
    y2 = pcy + 0.5 * (ph - 1.0)
    # clip to image
    x1 = jnp.clip(x1, 0, im_info[1] - 1.0)
    y1 = jnp.clip(y1, 0, im_info[0] - 1.0)
    x2 = jnp.clip(x2, 0, im_info[1] - 1.0)
    y2 = jnp.clip(y2, 0, im_info[0] - 1.0)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)

    # min-size filter (scaled by im_info[2])
    msize = min_size * im_info[2]
    valid = ((x2 - x1 + 1.0) >= msize) & ((y2 - y1 + 1.0) >= msize)
    score = jnp.where(valid, score, -jnp.inf)

    n = boxes.shape[0]
    k_pre = min(pre_nms, n) if pre_nms > 0 else n
    order = jnp.argsort(-score)[:k_pre]
    sboxes = boxes[order]
    sscore = score[order]
    svalid = jnp.isfinite(sscore)

    # pixel-convention IoU (+1 widths) matching proposal.cc NMS, not the
    # normalised-corner IoU the rest of the contrib family uses.
    # The IoU ROW is computed inside the loop body — O(n) live memory
    # instead of materializing the k_pre x k_pre matrix (at the default
    # pre_nms=6000 that matrix is ~144MB per image under vmap; the
    # reference uses an O(n^2/64) bitmask workspace, nms.cu)
    area = ((sboxes[:, 2] - sboxes[:, 0] + 1.0)
            * (sboxes[:, 3] - sboxes[:, 1] + 1.0))
    idxs = jnp.arange(k_pre)

    def body(i, keep):
        bi = sboxes[i]
        tl = jnp.maximum(bi[:2], sboxes[:, :2])
        br = jnp.minimum(bi[2:], sboxes[:, 2:])
        wh = jnp.maximum(br - tl + 1.0, 0.0)
        inter = wh[:, 0] * wh[:, 1]
        union = area + area[i] - inter
        iou_row = jnp.where(union <= 0, 0.0, inter / union)
        sup_row = (iou_row > nms_thresh) & (idxs > i)
        return jnp.where(keep[i], keep & ~sup_row, keep)

    keep = lax.fori_loop(0, k_pre, body, svalid)
    # compact kept indices to the front; pad by cycling (proposal.cc:414
    # keep[i % out_size])
    pos = jnp.cumsum(keep) - 1
    kept_idx = jnp.zeros(k_pre, jnp.int32).at[
        jnp.where(keep, pos, k_pre)].set(jnp.arange(k_pre),
                                         mode="drop")
    out_size = jnp.maximum(keep.sum(), 1)
    sel = kept_idx[jnp.mod(jnp.arange(post_nms), out_size)]
    return sboxes[sel], sscore[sel]


@register("_contrib_Proposal", alias=("Proposal", "_contrib_MultiProposal",
                                      "MultiProposal"),
          num_outputs="_dynamic")
def _contrib_proposal(attrs, cls_prob, bbox_pred, im_info):
    """RPN proposals (proposal.cc / multi_proposal.cc): cls_prob
    (B,2A,H,W) with foreground scores in the second half, bbox_pred
    (B,4A,H,W), im_info (B,3).  Returns rois (B*post_nms, 5) with batch
    index; + scores when output_score."""
    import numpy as np
    stride = int(attrs.get("feature_stride", 16))
    scales = tuple(float(s) for s in attrs.get("scales", (4, 8, 16, 32)))
    ratios = tuple(float(r) for r in attrs.get("ratios", (0.5, 1, 2)))
    pre_nms = int(attrs.get("rpn_pre_nms_top_n", 6000))
    post_nms = int(attrs.get("rpn_post_nms_top_n", 300))
    nms_thresh = float(attrs.get("threshold", 0.7))
    min_size = float(attrs.get("rpn_min_size", 16))
    if bool(attrs.get("iou_loss", False)):
        raise NotImplementedError("Proposal: iou_loss decoding is not "
                                  "supported")
    anchors = jnp.asarray(_generate_base_anchors(stride, scales, ratios))
    a = anchors.shape[0]
    fg = cls_prob[:, a:, :, :]                       # (B,A,H,W)

    rois, scores = jax.vmap(
        lambda s, d, ii: _proposal_one(
            s, d, ii, anchors, stride=stride, pre_nms=pre_nms,
            post_nms=post_nms, nms_thresh=nms_thresh,
            min_size=min_size))(fg, bbox_pred, im_info)
    b = rois.shape[0]
    batch_idx = jnp.repeat(jnp.arange(b, dtype=rois.dtype), post_nms)
    rois_out = jnp.concatenate(
        [batch_idx[:, None], rois.reshape(-1, 4)], axis=1)
    if bool(attrs.get("output_score", False)):
        return rois_out, scores.reshape(-1, 1)
    return rois_out


# --- resize / pooling family ------------------------------------------------
@register("_contrib_AdaptiveAvgPooling2D")
def _adaptive_avg_pool2d(attrs, x):
    """Adaptive average pool to a target (H,W)
    (reference: contrib/adaptive_avg_pooling.cc). Emitted as a pair of
    interval-overlap matmuls — fully dense, MXU-friendly, differentiable."""
    out_hw = attrs.get("output_size", ())
    if isinstance(out_hw, (int, float)):
        out_hw = (int(out_hw), int(out_hw))
    if not out_hw:
        out_hw = (1, 1)
    oh, ow = (int(out_hw[0]), int(out_hw[-1]))
    n, c, h, w = x.shape

    def weights(in_size, out_size):
        # row r covers input interval [r*in/out, (r+1)*in/out); fractional
        # overlap with each input cell gives the averaging weight
        starts = jnp.arange(out_size) * in_size / out_size
        ends = (jnp.arange(out_size) + 1) * in_size / out_size
        cells = jnp.arange(in_size)
        overlap = jnp.clip(
            jnp.minimum(ends[:, None], cells[None, :] + 1.0)
            - jnp.maximum(starts[:, None], cells[None, :]), 0.0, 1.0)
        return (overlap / (in_size / out_size)).astype(x.dtype)

    wh = weights(h, oh)            # (oh, h)
    ww = weights(w, ow)            # (ow, w)
    out = jnp.einsum("nchw,oh->ncow", x, wh)
    return jnp.einsum("ncow,pw->ncop", out, ww)


@register("_contrib_BilinearResize2D")
def _bilinear_resize2d(attrs, x, *maybe_like):
    """Bilinear upsample/downsample (reference: bilinear_resize.cc).

    Align-corners sampling (src = i*(in-1)/(out-1)), matching the
    reference kernel — NOT jax.image.resize's half-pixel convention."""
    if maybe_like:
        oh, ow = maybe_like[0].shape[2], maybe_like[0].shape[3]
    else:
        oh = int(attrs.get("height", 0))
        ow = int(attrs.get("width", 0))
        sh = float(attrs.get("scale_height", 0) or 0)
        sw = float(attrs.get("scale_width", 0) or 0)
        if oh <= 0 and sh > 0:
            oh = int(x.shape[2] * sh)
        if ow <= 0 and sw > 0:
            ow = int(x.shape[3] * sw)
    h, w = x.shape[2], x.shape[3]

    def axis_weights(in_size, out_size):
        if out_size == 1:
            src = jnp.zeros((1,), x.dtype)
        else:
            src = jnp.arange(out_size, dtype=x.dtype) * \
                ((in_size - 1) / (out_size - 1))
        lo = jnp.clip(jnp.floor(src).astype(jnp.int32), 0, in_size - 1)
        hi = jnp.clip(lo + 1, 0, in_size - 1)
        frac = src - lo.astype(x.dtype)
        return lo, hi, frac

    ylo, yhi, fy = axis_weights(h, oh)
    xlo, xhi, fx = axis_weights(w, ow)
    top = x[:, :, ylo, :] * (1 - fy)[None, None, :, None] + \
        x[:, :, yhi, :] * fy[None, None, :, None]
    out = top[:, :, :, xlo] * (1 - fx)[None, None, None, :] + \
        top[:, :, :, xhi] * fx[None, None, None, :]
    return out


# --- deformable family ------------------------------------------------------
def _bilinear_gather(img, ys, xs):
    """Sample img (C,H,W) at fractional (ys, xs) [any shape] with zero
    padding outside — the deformable-conv sampling kernel
    (deformable_im2col.h DmcnIm2colBilinear)."""
    c, h, w = img.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy1 = ys - y0
    wx1 = xs - x0
    out = 0.0
    for dy, wy in ((0, 1.0 - wy1), (1, wy1)):
        for dx, wx in ((0, 1.0 - wx1), (1, wx1)):
            yy = (y0 + dy).astype(jnp.int32)
            xx = (x0 + dx).astype(jnp.int32)
            valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
            yc = jnp.clip(yy, 0, h - 1)
            xc = jnp.clip(xx, 0, w - 1)
            val = img[:, yc, xc]                    # (C, *idx_shape)
            out = out + val * (wy * wx * valid)[None]
    return out


@register("_contrib_DeformableConvolution", alias=("DeformableConvolution",))
def _deformable_convolution(attrs, x, offset, weight, *maybe_bias):
    """Deformable convolution v1 (reference:
    contrib/deformable_convolution.cc + deformable_im2col.h): each kernel
    tap samples the input at its grid position plus a learned offset,
    bilinearly. Lowered to one fused gather + tensordot per image."""
    kernel = tuple(int(k) for k in attrs["kernel"])
    kh, kw = kernel
    stride = attrs.get("stride") or (1, 1)
    pad = attrs.get("pad") or (0, 0)
    dilate = attrs.get("dilate") or (1, 1)
    sh, sw = (int(s) for s in stride)
    ph, pw = (int(p) for p in pad)
    dh, dw = (int(d) for d in dilate)
    groups = int(attrs.get("num_group", 1))
    defg = int(attrs.get("num_deformable_group", 1))
    if groups != 1 or defg != 1:
        raise NotImplementedError(
            "DeformableConvolution: groups > 1 not supported")
    n, c, h, w = x.shape
    oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1

    base_y = (jnp.arange(oh) * sh - ph)[:, None, None]      # (oh,1,1)
    base_x = (jnp.arange(ow) * sw - pw)[None, :, None]      # (1,ow,1)
    ky = (jnp.arange(kh) * dh)[None, None, :, None]          # (1,1,kh,1)
    kx = (jnp.arange(kw) * dw)[None, None, None, :]          # (1,1,1,kw)
    grid_y = base_y[..., None] + ky                          # (oh,ow,kh,1)
    grid_x = base_x[..., None] + kx                          # (oh,ow,1,kw)
    grid_y = jnp.broadcast_to(grid_y, (oh, ow, kh, kw)).astype(x.dtype)
    grid_x = jnp.broadcast_to(grid_x, (oh, ow, kh, kw)).astype(x.dtype)

    def one(img, off):
        # off: (2*kh*kw, oh, ow) ordered (y0,x0,y1,x1,...) per tap
        off = off.reshape(kh * kw, 2, oh, ow)
        oy = off[:, 0].transpose(1, 2, 0).reshape(oh, ow, kh, kw)
        ox = off[:, 1].transpose(1, 2, 0).reshape(oh, ow, kh, kw)
        ys = grid_y + oy
        xs = grid_x + ox
        col = _bilinear_gather(img, ys, xs)       # (C,oh,ow,kh,kw)
        return jnp.tensordot(weight, col, axes=[[1, 2, 3], [0, 3, 4]])

    out = jax.vmap(one)(x, offset)                # (N,Cout,oh,ow)
    if maybe_bias and not bool(attrs.get("no_bias", False)):
        out = out + maybe_bias[0].reshape(1, -1, 1, 1)
    return out


@register("_contrib_PSROIPooling", alias=("PSROIPooling",))
def _psroi_pooling(attrs, data, rois):
    """Position-sensitive ROI pooling (reference: contrib/psroi_pooling.cc):
    output channel c at bin (i,j) pools input channel c*P*P + i*P + j over
    that bin (R-FCN)."""
    spatial_scale = float(attrs["spatial_scale"])
    out_dim = int(attrs["output_dim"])
    group = int(attrs.get("group_size", attrs.get("pooled_size")))
    pooled = int(attrs.get("pooled_size", group))
    n, c, h, w = data.shape

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale, roi[2] * spatial_scale, \
            roi[3] * spatial_scale, roi[4] * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_h = rh / pooled
        bin_w = rw / pooled
        img = data[bidx]
        ys = jnp.arange(h, dtype=data.dtype)
        xs = jnp.arange(w, dtype=data.dtype)

        def bin_val(ci, bi, bj):
            gy1 = y1 + bi * bin_h
            gx1 = x1 + bj * bin_w
            my = (ys[None, :] >= jnp.floor(gy1)) & \
                 (ys[None, :] < jnp.ceil(gy1 + bin_h))
            mx = (xs[None, :] >= jnp.floor(gx1)) & \
                 (xs[None, :] < jnp.ceil(gx1 + bin_w))
            mask = (my.reshape(-1, 1) & mx.reshape(1, -1)).astype(data.dtype)
            # bin -> position-sensitive group cell (psroi_pooling.cc:
            # gh = floor(ph * group / pooled)); differs from the bin
            # index whenever group_size != pooled_size
            gh = (bi * group) // pooled
            gw = (bj * group) // pooled
            chan = ci * group * group + gh * group + gw
            s = (img[chan] * mask).sum()
            cnt = jnp.maximum(mask.sum(), 1.0)
            return s / cnt

        ci, bi, bj = jnp.meshgrid(jnp.arange(out_dim), jnp.arange(pooled),
                                  jnp.arange(pooled), indexing="ij")
        return jax.vmap(lambda a, b, c_: bin_val(a, b, c_))(
            ci.ravel(), bi.ravel(), bj.ravel()).reshape(
                out_dim, pooled, pooled)

    return jax.vmap(one_roi)(rois)


# --- sync batch norm --------------------------------------------------------
@register("_contrib_SyncBatchNorm", num_outputs=3, mutate_aux=(3, 4),
          alias=("SyncBatchNorm",))
def _sync_batch_norm(attrs, x, gamma, beta, moving_mean, moving_var):
    """Cross-device BatchNorm (reference: contrib/sync_batch_norm-inl.h —
    allreduce of batch statistics across GPUs).

    TPU redesign: inside shard_map/pmap the ``axis_name`` attr names the
    mesh axis to psum statistics over; in single-program execution (the
    usual pjit data-parallel case) XLA already sees the GLOBAL batch, so
    plain BN statistics are exactly the synchronized ones and no attr is
    needed."""
    eps = float(attrs.get("eps", 1e-3))
    momentum = float(attrs.get("momentum", 0.9))
    training = bool(attrs.get("_training", False)) and not bool(
        attrs.get("use_global_stats", False))
    fix_gamma = bool(attrs.get("fix_gamma", True))
    axis_name = attrs.get("axis_name", None)
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    red = tuple(i for i in range(x.ndim) if i != 1)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    if training:
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=red)
        sq = jnp.mean(xf * xf, axis=red)
        if axis_name:
            mean = lax.pmean(mean, axis_name)
            sq = lax.pmean(sq, axis_name)
        var = sq - mean * mean
        new_mm = moving_mean * momentum + mean.astype(moving_mean.dtype) \
            * (1 - momentum)
        new_mv = moving_var * momentum + var.astype(moving_var.dtype) \
            * (1 - momentum)
    else:
        mean, var = moving_mean, moving_var
        new_mm, new_mv = moving_mean, moving_var
    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    out = (x.astype(jnp.float32) - mean.reshape(bshape)) * inv.reshape(bshape)
    out = out * gamma.reshape(bshape) + beta.reshape(bshape)
    return out.astype(x.dtype), new_mm, new_mv


# --- small contrib ops ------------------------------------------------------
@register("_contrib_quadratic")
def _quadratic(attrs, x):
    """a*x^2 + b*x + c (reference: contrib/quadratic_op.cc — the tutorial
    example op)."""
    a = float(attrs.get("a", 0.0))
    b = float(attrs.get("b", 0.0))
    c = float(attrs.get("c", 0.0))
    return a * x * x + b * x + c


@register("_contrib_index_array")
def _index_array(attrs, x):
    """Coordinates of every element (reference: contrib/index_array.cc);
    optional ``axes`` selects coordinate dims."""
    axes = attrs.get("axes", None)
    shape = x.shape
    # int32 by design: TPU integer width (the reference emits int64;
    # int64 narrows to int32 throughout this framework)
    coords = jnp.stack(
        jnp.meshgrid(*[jnp.arange(s) for s in shape], indexing="ij"),
        axis=-1).astype(jnp.int32)
    if axes is not None:
        axes = [int(a) for a in (axes if isinstance(axes, (tuple, list))
                                 else (axes,))]
        coords = coords[..., axes]
    return coords


@register("_contrib_index_copy")
def _index_copy(attrs, old, index, new):
    """Copy rows of ``new`` into ``old`` at ``index``
    (reference: contrib/index_copy.cc)."""
    return old.at[index.astype(jnp.int32)].set(new)


@register("_contrib_count_sketch")
def _count_sketch(attrs, data, h, s):
    """Count sketch projection (reference: contrib/count_sketch.cc):
    out[:, h[i]] += s[i] * data[:, i], out_dim columns."""
    out_dim = int(attrs["out_dim"])
    n = data.shape[0]
    hh = h.reshape(-1).astype(jnp.int32)
    ss = s.reshape(-1).astype(data.dtype)
    contrib = data * ss[None, :]
    out = jnp.zeros((n, out_dim), data.dtype)
    return out.at[:, hh].add(contrib)


@register("_contrib_getnnz")
def _getnnz(attrs, data):
    """Number of stored values (reference: contrib/nnz.cc for CSR; dense
    inputs count non-zeros)."""
    axis = attrs.get("axis", None)
    nz = (data != 0)
    if axis is None:
        return nz.sum().astype(jnp.int32)
    return nz.sum(axis=int(axis)).astype(jnp.int32)


@register("khatri_rao")
def _khatri_rao(attrs, *mats):
    """Column-wise Khatri-Rao product (reference: contrib/krprod.cc)."""
    out = mats[0]
    for m in mats[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape(
            out.shape[0] * m.shape[0], out.shape[1])
    return out


@register("_contrib_hawkesll", num_outputs=2)
def _hawkesll(attrs, lda, alpha, beta, state, lags, marks, valid_length,
              max_time):
    """Univariate Hawkes process log likelihood over ragged sequences
    (reference: contrib/hawkes_ll-inl.h hawkesll_forward +
    hawkesll_forward_compensator, exact per-mark last-event-time
    recurrence). Returns (ll per sample (N,), end-of-window state (N,K));
    the event recurrence is one lax.scan per sample."""
    k = alpha.shape[-1]
    n, t = lags.shape
    marks_i = marks.astype(jnp.int32)

    def sample_ll(mu_i, state_i, lags_i, marks_row, vl, mt):
        def step(carry, inp):
            state_c, last_c, t_c, ll_c = carry
            lag, mark, idx = inp
            valid = idx < vl
            t2 = t_c + lag
            d = t2 - last_c[mark]
            ed = jnp.exp(-beta[mark] * d)
            lam = mu_i[mark] + alpha[mark] * beta[mark] * state_c[mark] * ed
            comp = mu_i[mark] * d + alpha[mark] * state_c[mark] * (1 - ed)
            ll2 = ll_c + jnp.where(
                valid, jnp.log(jnp.maximum(lam, 1e-30)) - comp, 0.0)
            state2 = state_c.at[mark].set(1.0 + state_c[mark] * ed)
            last2 = last_c.at[mark].set(t2)
            return (jnp.where(valid, state2, state_c),
                    jnp.where(valid, last2, last_c),
                    jnp.where(valid, t2, t_c), ll2), None

        (state_f, last_f, _tf, ll), _ = lax.scan(
            step,
            (state_i.astype(jnp.float32), jnp.zeros(k, jnp.float32),
             jnp.float32(0.0), jnp.float32(0.0)),
            (lags_i.astype(jnp.float32), marks_row, jnp.arange(t)))
        # remaining compensators over (t_last_k, T] + state decay to T
        d = mt - last_f
        ed = jnp.exp(-beta * d)
        rem = mu_i * d + alpha * state_f * (1.0 - ed)
        return ll - rem.sum(), state_f * ed

    ll, new_state = jax.vmap(sample_ll)(
        jnp.broadcast_to(lda, (n, k)).astype(jnp.float32), state, lags,
        marks_i, valid_length.astype(jnp.int32),
        max_time.astype(jnp.float32).reshape(-1))
    return ll.astype(lda.dtype), new_state.astype(state.dtype)


@register("_contrib_group_adagrad_update", num_outputs=2, mutate_aux=(2,))
def _group_adagrad_update(attrs, weight, grad, history):
    """Group AdaGrad (reference: contrib/optimizer_op.cc — per-row
    accumulated squared norm). The history accumulator is a mutated
    state input (same contract as sgd_mom_update's momentum)."""
    lr = float(attrs["lr"])
    eps = float(attrs.get("epsilon", 1e-5))
    rescale = float(attrs.get("rescale_grad", 1.0))
    clip = float(attrs.get("clip_gradient", -1.0))
    g = grad * rescale
    if clip > 0:
        g = jnp.clip(g, -clip, clip)
    red = tuple(range(1, g.ndim))
    hist_new = history + jnp.mean(g * g, axis=red, keepdims=True)
    # eps INSIDE the sqrt (reference GroupAdagradDnsRspDnsImpl)
    return weight - lr * g / jnp.sqrt(hist_new + eps), hist_new


def _gradientmultiplier_grad(attrs, primals, cotangents):
    scalar = float(attrs.get("scalar", 1.0))
    return (cotangents[0] * scalar,)


@register("_contrib_gradientmultiplier",
          fgradient=_gradientmultiplier_grad,
          alias=("gradientmultiplier",))
def _gradientmultiplier(attrs, x):
    """Identity forward, gradient scaled by `scalar` on backward
    (reference: contrib/gradient_multiplier_op.cc:73-92 — the
    gradient-reversal trick for domain-adversarial training when
    scalar < 0)."""
    return x


@register("_contrib_arange_like", alias=("arange_like",))
def _arange_like(attrs, x):
    """Evenly spaced values shaped by the input (reference:
    tensor/init_op.cc:104 _contrib_arange_like, RangeLikeParam
    init_op.h:177). axis=None fills the whole (flattened) shape;
    otherwise the length follows that axis."""
    start = float(attrs.get("start", 0.0))
    step = float(attrs.get("step", 1.0))
    repeat = int(attrs.get("repeat", 1))
    axis = attrs.get("axis")
    if axis is None:
        n = 1
        for d in x.shape:
            n *= d
        vals = start + step * (jnp.arange(n, dtype=jnp.float32) // repeat)
        return vals.reshape(x.shape)
    ax = int(axis) % x.ndim
    n = x.shape[ax]
    vals = start + step * (jnp.arange(n, dtype=jnp.float32) // repeat)
    return vals


# --- round-4 named-op gap closers -------------------------------------------

def _boolean_mask_grad(attrs, prims, cts):
    """Backward: scatter the kept rows' cotangents to their source
    positions (reference: boolean_mask-inl.h BooleanMaskBackward).
    Runs eagerly at tape playback, so the dynamic keep-set is fine."""
    data, index = prims
    axis = int(attrs.get("axis", 0))
    keep = jnp.nonzero(index.astype(bool))[0]
    ct = jnp.moveaxis(cts[0], axis, 0)
    g = jnp.zeros(jnp.moveaxis(data, axis, 0).shape, data.dtype)
    g = g.at[keep].set(ct.astype(data.dtype))
    return (jnp.moveaxis(g, 0, axis), None)


@register("_contrib_boolean_mask", alias=("boolean_mask",), eager_only=True,
          fgradient=_boolean_mask_grad)
def _contrib_boolean_mask(attrs, data, index):
    """Compact the rows of `data` where `index` is nonzero (reference:
    contrib/boolean_mask.cc — a dynamic-output-shape FComputeEx op).
    Output shape depends on the VALUES of index, so this op is
    eager-only; traced graphs use the static-shape redesign
    `boolean_mask_fill` instead (same file, TPU pattern)."""
    axis = int(attrs.get("axis", 0))
    keep = jnp.nonzero(index.astype(bool))[0]
    return jnp.take(data, keep, axis=axis)


@register("_contrib_edge_id")
def _contrib_edge_id(attrs, indptr, indices, data, u, v):
    """CSR edge-id lookup: out[i] = data[e] if edge (u[i], v[i]) exists in
    the CSR adjacency, else -1 (reference: contrib/dgl_graph.cc
    _contrib_edge_id). The CSR container is unpacked by the NDArray
    frontend (ndarray/contrib.py edge_id); here the three aux arrays are
    explicit inputs — FComputeEx-over-CSR re-expressed functionally."""
    u = u.astype(jnp.int32)
    v = v.astype(jnp.int32)
    row_start = indptr[u]
    row_end = indptr[u + 1]

    def lookup(rs, re, vv):
        # masked probe over the row's column span — fixed bound, XLA
        # vectorizes; nnz is small for graph adjacency data
        offs = jnp.arange(indices.shape[0], dtype=jnp.int32)
        inrow = (offs >= rs) & (offs < re)
        hit = inrow & (indices.astype(jnp.int32) == vv)
        eid = jnp.argmax(hit)
        return jnp.where(jnp.any(hit), data[eid].astype(jnp.float32), -1.0)

    return jax.vmap(lookup)(row_start, row_end, v)


def _sparse_embedding_grad(attrs, prims, cts):
    from ._op_tensor import _embedding_grad
    a = dict(attrs)
    a["sparse_grad"] = True
    return _embedding_grad(a, prims, cts)


@register("_contrib_SparseEmbedding", fgradient=_sparse_embedding_grad,
          input_names=("data", "weight"))
def _contrib_sparse_embedding(attrs, data, weight):
    """Embedding whose weight gradient is row_sparse (reference:
    indexing_op.cc SparseEmbedding). Same forward as Embedding; the
    gradient rule forces the row-sparse cotangent path."""
    idx = data.astype(jnp.int32)
    return jnp.take(weight, jnp.clip(idx, 0, weight.shape[0] - 1), axis=0)


def _kl_sparse_reg_grad(attrs, prims, cts):
    data, moving_avg = prims
    momentum = float(attrs.get("momentum", 0.9))
    target = float(attrs.get("sparseness_target", 0.1))
    penalty = float(attrs.get("penalty", 0.001))
    flat = data.reshape(data.shape[0], -1)
    avg = momentum * moving_avg + (1 - momentum) * flat.mean(axis=0)
    pen = penalty * (-target / avg + (1 - target) / (1 - avg))
    return (cts[0] + pen.reshape((1,) + data.shape[1:]).astype(data.dtype),
            None)


@register("IdentityAttachKLSparseReg", num_outputs=2, num_visible=1,
          mutate_aux=(1,), fgradient=_kl_sparse_reg_grad)
def _identity_attach_kl_sparse_reg(attrs, data, moving_avg):
    """Identity that attaches a KL sparseness penalty to the gradient
    (reference: identity_attach_KL_sparse_reg-inl.h). The running mean
    of activations updates on forward here (the reference updates it in
    backward; forward-update matches how BatchNorm running stats are
    handled on this runtime) and the backward adds
    penalty * (-rho/rho_hat + (1-rho)/(1-rho_hat))."""
    momentum = float(attrs.get("momentum", 0.9))
    flat = data.reshape(data.shape[0], -1)
    new_avg = momentum * moving_avg + (1 - momentum) * flat.mean(axis=0)
    return data, new_avg


@register("_contrib_MoEFFN", num_outputs=2,
          alias=("_contrib_moe_ffn",))
def _contrib_moe_ffn(attrs, x, gate_weight, w1, b1, w2, b2):
    """Mixture-of-Experts FFN (greenfield — no reference analog; see
    parallel/moe.py for the sharded version). Inputs: tokens (n, d) or
    (batch, seq, d); outputs (same-shape y, scalar load-balance aux).
    Attr capacity_factor bounds per-expert slots (static shapes)."""
    from ..parallel.moe import moe_ffn
    cf = float(attrs.get("capacity_factor", 2.0))
    shape = x.shape
    tokens = x.reshape(-1, shape[-1])
    params = {"wg": gate_weight, "w1": w1, "b1": b1, "w2": w2, "b2": b2}
    y, aux = moe_ffn(params, tokens, capacity_factor=cf)
    return y.reshape(shape), aux
