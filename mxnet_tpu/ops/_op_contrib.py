"""Detection / contrib operator family.

Reference: src/operator/contrib/bounding_box.cc (_contrib_box_nms:36,
_contrib_box_iou:117, _contrib_bipartite_matching:158),
multibox_prior.cc, multibox_target.cc:71 (MultiBoxTargetForward),
multibox_detection.cc:83 (MultiBoxDetectionForward), roi_align.cc and
src/operator/roi_pooling.cc.

TPU redesign (SURVEY.md §7 hard part 8 — dynamic-shape ops under XLA
static shapes): every op here is a *bounded-shape + masking* program.
Where the reference compacts variable-length results with CopyIf /
std::sort on the host, these emit fixed-shape sort + prefix-sum-scatter
programs: invalid slots carry -1 sentinels exactly like the reference's
output contract, so downstream consumers see the same API.  Sequential
dependencies (greedy NMS, bipartite matching) lower to one
``lax.fori_loop``/``lax.scan`` — a single XLA While op — instead of host
loops; everything is vmapped over the batch and differentiable where the
reference defines gradients (NMS backward = scatter of the kept rows,
ROIAlign backward = bilinear scatter-add, both produced by JAX AD from
the gather-based forwards).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _floats(v, default):
    if v is None:
        return tuple(float(x) for x in default)
    if isinstance(v, (int, float)):
        return (float(v),)
    return tuple(float(x) for x in v)


def _center_to_corner(b):
    x, y, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2], axis=-1)


def _corner_to_center(b):
    l, t, r, bo = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([(l + r) / 2, (t + bo) / 2, r - l, bo - t], axis=-1)


def _box_area(b, fmt="corner"):
    if fmt == "corner":
        w = b[..., 2] - b[..., 0]
        h = b[..., 3] - b[..., 1]
    else:
        w = b[..., 2]
        h = b[..., 3]
    return jnp.where((w < 0) | (h < 0), 0.0, w * h)


def _pairwise_iou(a, b, fmt="corner"):
    """IoU of (N,4) x (M,4) -> (N,M), matching CalculateOverlap
    (multibox_detection.cc:73): union<=0 -> 0."""
    ac = a if fmt == "corner" else _center_to_corner(a)
    bc = b if fmt == "corner" else _center_to_corner(b)
    tl = jnp.maximum(ac[:, None, :2], bc[None, :, :2])
    br = jnp.minimum(ac[:, None, 2:], bc[None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = (_box_area(ac, "corner")[:, None]
             + _box_area(bc, "corner")[None, :] - inter)
    return jnp.where(union <= 0, 0.0, inter / union)


# ---------------------------------------------------------------------------
# box_iou
# ---------------------------------------------------------------------------
@register("_contrib_box_iou", alias=("box_iou",))
def _contrib_box_iou(attrs, lhs, rhs):
    fmt = attrs.get("format", "corner")
    lsh, rsh = lhs.shape[:-1], rhs.shape[:-1]
    out = _pairwise_iou(lhs.reshape(-1, 4), rhs.reshape(-1, 4), fmt)
    return out.reshape(lsh + rsh)


# ---------------------------------------------------------------------------
# box_nms
# ---------------------------------------------------------------------------
def _nms_one(data, *, overlap_thresh, valid_thresh, topk, coord_start,
             score_index, id_index, background_id, force_suppress,
             in_format, out_format):
    """Greedy NMS on one batch element (N, W) -> (out (N, W), record (N,)).

    Kept boxes are compacted to the front in descending-score order;
    dropped slots are -1 (bounding_box-inl.h nms_assign).
    """
    n, w = data.shape
    scores = data[:, score_index]
    valid = scores > valid_thresh
    if id_index >= 0:
        valid &= data[:, id_index] != background_id

    # stable desc sort of valid scores; invalid slots sink to the end
    order = jnp.argsort(jnp.where(valid, -scores, jnp.inf), stable=True)
    sdata = data[order]
    svalid = valid[order]
    topk_eff = n if topk < 0 else min(n, topk)
    cand = svalid & (jnp.arange(n) < topk_eff)

    boxes = sdata[:, coord_start:coord_start + 4]
    iou = _pairwise_iou(boxes, boxes, in_format)
    if id_index >= 0 and not force_suppress:
        cls = sdata[:, id_index]
        suppress_ok = cls[:, None] == cls[None, :]
        sup_mat = (iou > overlap_thresh) & suppress_ok
    else:
        sup_mat = iou > overlap_thresh

    later = jnp.arange(n)[None, :] > jnp.arange(n)[:, None]

    def body(i, keep):
        sup = sup_mat[i] & later[i] & keep
        return jnp.where(keep[i], keep & ~sup, keep)

    keep = lax.fori_loop(0, topk_eff, body, cand)

    if in_format != out_format:
        conv = (_center_to_corner if out_format == "corner"
                else _corner_to_center)
        sdata = sdata.at[:, coord_start:coord_start + 4].set(conv(boxes))

    # prefix-sum scatter: kept rows compact to the front, others dropped
    pos = jnp.cumsum(keep) - 1
    idx = jnp.where(keep, pos, n)
    out = jnp.full((n, w), -1.0, data.dtype).at[idx].set(sdata, mode="drop")
    rec = jnp.full((n,), -1.0, data.dtype).at[idx].set(
        order.astype(data.dtype), mode="drop")
    return out, rec


@register("_contrib_box_nms", alias=("box_nms",), num_outputs=2,
          num_visible=1)
def _contrib_box_nms(attrs, data):
    kw = dict(
        overlap_thresh=float(attrs.get("overlap_thresh", 0.5)),
        valid_thresh=float(attrs.get("valid_thresh", 0.0)),
        topk=int(attrs.get("topk", -1)),
        coord_start=int(attrs.get("coord_start", 2)),
        score_index=int(attrs.get("score_index", 1)),
        id_index=int(attrs.get("id_index", -1)),
        background_id=int(attrs.get("background_id", -1)),
        force_suppress=bool(attrs.get("force_suppress", False)),
        in_format=attrs.get("in_format", "corner"),
        out_format=attrs.get("out_format", "corner"),
    )
    shape = data.shape
    n, w = shape[-2], shape[-1]
    flat = data.reshape(-1, n, w)
    out, rec = jax.vmap(lambda d: _nms_one(d, **kw))(flat)
    # record holds the ORIGINAL index flattened over (batch, num_elem)
    # (bounding_box-inl.h nms_assign: record[i*num+count] = location)
    offs = jnp.arange(flat.shape[0], dtype=data.dtype) * n
    rec = jnp.where(rec >= 0, rec + offs[:, None], -1.0)
    return out.reshape(shape), rec.reshape(shape[:-1] + (1,))


# ---------------------------------------------------------------------------
# bipartite_matching
# ---------------------------------------------------------------------------
def _bipartite_one(score, *, is_ascend, threshold, topk):
    n, m = score.shape
    k = min(n, m) if topk < 0 else min(topk, min(n, m))
    big = jnp.inf
    sgn = 1.0 if is_ascend else -1.0  # minimise sgn*score

    def body(carry, _):
        row_free, col_free, row_match, col_match = carry
        masked = jnp.where(row_free[:, None] & col_free[None, :],
                           sgn * score, big)
        flat = jnp.argmin(masked)
        ri, ci = flat // m, flat % m
        val = score[ri, ci]
        ok = jnp.where(is_ascend, val <= threshold, val >= threshold)
        ok &= masked[ri, ci] < big
        r_sel = (jnp.arange(n) == ri) & ok
        c_sel = (jnp.arange(m) == ci) & ok
        row_free = row_free & ~r_sel
        col_free = col_free & ~c_sel
        row_match = jnp.where(r_sel, ci, row_match)
        col_match = jnp.where(c_sel, ri, col_match)
        return (row_free, col_free, row_match, col_match), 0

    init = (jnp.ones(n, bool), jnp.ones(m, bool),
            jnp.full(n, -1.0, score.dtype), jnp.full(m, -1.0, score.dtype))
    (rf, cf, rm, cm), _ = lax.scan(body, init, None, length=k)
    return rm, cm


@register("_contrib_bipartite_matching", alias=("bipartite_matching",),
          num_outputs=2)
def _contrib_bipartite_matching(attrs, data):
    kw = dict(is_ascend=bool(attrs.get("is_ascend", False)),
              threshold=float(attrs.get("threshold", 0.0)),
              topk=int(attrs.get("topk", -1)))
    shape = data.shape
    n, m = shape[-2], shape[-1]
    flat = data.reshape(-1, n, m)
    rm, cm = jax.vmap(lambda s: _bipartite_one(s, **kw))(flat)
    return rm.reshape(shape[:-1]), cm.reshape(shape[:-2] + (m,))


# ---------------------------------------------------------------------------
# MultiBoxPrior
# ---------------------------------------------------------------------------
@register("_contrib_MultiBoxPrior", alias=("MultiBoxPrior",))
def _contrib_multibox_prior(attrs, data):
    """Anchor generation (multibox_prior.cc:31 MultiBoxPriorForward).

    Output (1, H*W*(num_sizes+num_ratios-1), 4) corner boxes; per
    location the order is [each size with ratio0, then each extra ratio
    with size0] in row-major (y, x) scan — byte-for-byte the reference's
    layout.
    """
    sizes = _floats(attrs.get("sizes"), (1.0,))
    ratios = _floats(attrs.get("ratios"), (1.0,))
    steps = _floats(attrs.get("steps"), (-1.0, -1.0))
    offsets = _floats(attrs.get("offsets"), (0.5, 0.5))
    clip = bool(attrs.get("clip", False))
    h, w = data.shape[-2], data.shape[-1]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    dt = data.dtype if jnp.issubdtype(data.dtype, jnp.floating) \
        else jnp.float32

    cy = (jnp.arange(h, dtype=dt) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=dt) + offsets[1]) * step_x
    # per-location anchor half-sizes, reference order
    half = []
    r0 = jnp.sqrt(jnp.asarray(ratios[0], dt))
    for s in sizes:
        half.append((s * h / w * r0 / 2, s / r0 / 2))
    for r in ratios[1:]:
        rs = jnp.sqrt(jnp.asarray(r, dt))
        half.append((sizes[0] * h / w * rs / 2, sizes[0] / rs / 2))
    hw = jnp.stack([jnp.asarray(a, dt) for a, _ in half])  # (K,) half-width
    hh = jnp.stack([jnp.asarray(b, dt) for _, b in half])  # (K,) half-height

    cyg = cy[:, None, None]      # (H,1,1)
    cxg = cx[None, :, None]      # (1,W,1)
    boxes = jnp.stack([
        jnp.broadcast_to(cxg - hw, (h, w, hw.shape[0])),
        jnp.broadcast_to(cyg - hh, (h, w, hw.shape[0])),
        jnp.broadcast_to(cxg + hw, (h, w, hw.shape[0])),
        jnp.broadcast_to(cyg + hh, (h, w, hw.shape[0])),
    ], axis=-1)                  # (H, W, K, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes.reshape(1, -1, 4)


# ---------------------------------------------------------------------------
# MultiBoxTarget
# ---------------------------------------------------------------------------
def _encode_loc(anchor, gt, variances):
    """(gx-ax)/aw/vx ... log(gw/aw)/vw (multibox_target.cc:32
    AssignLocTargets)."""
    vx, vy, vw, vh = variances
    aw = anchor[..., 2] - anchor[..., 0]
    ah = anchor[..., 3] - anchor[..., 1]
    ax = (anchor[..., 0] + anchor[..., 2]) * 0.5
    ay = (anchor[..., 1] + anchor[..., 3]) * 0.5
    gw = gt[..., 2] - gt[..., 0]
    gh = gt[..., 3] - gt[..., 1]
    gx = (gt[..., 0] + gt[..., 2]) * 0.5
    gy = (gt[..., 1] + gt[..., 3]) * 0.5
    eps = jnp.finfo(anchor.dtype).tiny
    return jnp.stack([
        (gx - ax) / aw / vx,
        (gy - ay) / ah / vy,
        jnp.log(jnp.maximum(gw / aw, eps)) / vw,
        jnp.log(jnp.maximum(gh / ah, eps)) / vh,
    ], axis=-1)


def _mbox_target_one(anchors, label, cls_pred, *, overlap_threshold,
                     ignore_label, negative_mining_ratio,
                     negative_mining_thresh, variances):
    """One batch element of MultiBoxTargetForward (multibox_target.cc:71).

    anchors (A,4) corner, label (L,>=5) [cls,x1,y1,x2,y2,...] with -1
    padding rows, cls_pred (C,A).  Returns loc_target (A*4), loc_mask
    (A*4), cls_target (A).
    """
    a, l = anchors.shape[0], label.shape[0]
    dt = anchors.dtype
    # reference stops scanning labels at the first -1 class row
    valid_gt = jnp.cumprod(label[:, 0] != -1.0).astype(bool)
    n_valid = valid_gt.sum()

    iou = _pairwise_iou(anchors, label[:, 1:5], "corner")   # (A, L)
    iou = jnp.where(valid_gt[None, :], iou, -1.0)

    # stage 1: greedy bipartite matching, one gt per iteration
    def body(carry, _):
        a_free, g_free, match_gt, match_iou = carry
        masked = jnp.where(a_free[:, None] & g_free[None, :], iou, -1e9)
        flat = jnp.argmax(masked)
        ai, gi = flat // l, flat % l
        val = masked.reshape(-1)[flat]
        ok = val > 1e-6
        a_sel = (jnp.arange(a) == ai) & ok
        g_sel = (jnp.arange(l) == gi) & ok
        return (a_free & ~a_sel, g_free & ~g_sel,
                jnp.where(a_sel, gi, match_gt),
                jnp.where(a_sel, val, match_iou)), 0

    init = (jnp.ones(a, bool), jnp.ones(l, bool),
            jnp.zeros(a, jnp.int32), jnp.full(a, -1.0, dt))
    (a_free, _, match_gt, match_iou), _ = lax.scan(body, init, None,
                                                   length=l)

    # stage 2: threshold matching for still-free anchors
    best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)
    best_iou = jnp.max(iou, axis=1)
    stage2 = a_free & (best_iou > overlap_threshold) & (n_valid > 0)
    match_gt = jnp.where(stage2, best_gt, match_gt)
    pos = (~a_free) | stage2
    # per-anchor best overlap regardless of matching (negative mining key)
    any_iou = jnp.where(a_free, best_iou, match_iou)

    if negative_mining_ratio > 0:
        num_pos = pos.sum()
        num_neg = jnp.minimum(
            (num_pos * negative_mining_ratio).astype(jnp.int32),
            a - num_pos)
        cand = (~pos) & (any_iou < negative_mining_thresh)
        # hardest negatives = lowest background (class 0) probability
        logits = cls_pred.astype(jnp.float32)
        prob_bg = jax.nn.softmax(logits, axis=0)[0]
        key = jnp.where(cand, -prob_bg, -jnp.inf)
        desc = jnp.argsort(-key, stable=True)
        rank = jnp.argsort(desc, stable=True)
        neg = cand & (rank < num_neg)
    else:
        neg = ~pos

    gt_cls = label[match_gt, 0]
    gt_box = label[match_gt, 1:5]
    cls_target = jnp.where(
        pos, gt_cls + 1.0,
        jnp.where(neg, 0.0, float(ignore_label))).astype(dt)
    loc = _encode_loc(anchors, gt_box, variances)
    loc_target = jnp.where(pos[:, None], loc, 0.0).astype(dt)
    loc_mask = jnp.where(pos[:, None],
                         jnp.ones((a, 4), dt), jnp.zeros((a, 4), dt))
    # no valid gt: reference leaves everything at init
    # (loc 0 / mask 0 / cls ignore_label)
    has_gt = n_valid > 0
    cls_target = jnp.where(has_gt, cls_target, float(ignore_label))
    loc_target = jnp.where(has_gt, loc_target, 0.0)
    loc_mask = jnp.where(has_gt, loc_mask, 0.0)
    return loc_target.reshape(-1), loc_mask.reshape(-1), cls_target


@register("_contrib_MultiBoxTarget", alias=("MultiBoxTarget",),
          num_outputs=3)
def _contrib_multibox_target(attrs, anchor, label, cls_pred):
    kw = dict(
        overlap_threshold=float(attrs.get("overlap_threshold", 0.5)),
        ignore_label=float(attrs.get("ignore_label", -1.0)),
        negative_mining_ratio=float(attrs.get("negative_mining_ratio",
                                              -1.0)),
        negative_mining_thresh=float(attrs.get("negative_mining_thresh",
                                               0.5)),
        variances=_floats(attrs.get("variances"), (0.1, 0.1, 0.2, 0.2)),
    )
    anchors = anchor.reshape(-1, 4)
    lt, lm, ct = jax.vmap(
        lambda lb, cp: _mbox_target_one(anchors, lb, cp, **kw))(
            label, cls_pred)
    return lt, lm, ct


# ---------------------------------------------------------------------------
# MultiBoxDetection
# ---------------------------------------------------------------------------
def _decode_loc(anchors, loc_pred, variances, clip):
    """TransformLocations (multibox_detection.cc:46)."""
    vx, vy, vw, vh = variances
    al, at, ar, ab = (anchors[:, 0], anchors[:, 1],
                      anchors[:, 2], anchors[:, 3])
    aw, ah = ar - al, ab - at
    ax, ay = (al + ar) / 2, (at + ab) / 2
    p = loc_pred.reshape(-1, 4)
    ox = p[:, 0] * vx * aw + ax
    oy = p[:, 1] * vy * ah + ay
    ow = jnp.exp(p[:, 2] * vw) * aw / 2
    oh = jnp.exp(p[:, 3] * vh) * ah / 2
    out = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _mbox_detection_one(cls_prob, loc_pred, anchors, *, clip, threshold,
                        nms_threshold, force_suppress, variances, nms_topk):
    c, a = cls_prob.shape
    dt = cls_prob.dtype
    # class 0 is background (multibox_detection.cc:112 scans classes
    # from 1; the reference kernel likewise ignores its background_id
    # param — the python wrapper below rejects non-zero values instead
    # of silently mis-classifying)
    fg = cls_prob[1:, :]
    score = jnp.max(fg, axis=0)
    cid = jnp.argmax(fg, axis=0).astype(dt)           # 0-based fg class
    cid = jnp.where(score < threshold, -1.0, cid)
    boxes = _decode_loc(anchors, loc_pred, variances, clip)
    det = jnp.concatenate([cid[:, None], score[:, None], boxes], axis=1)

    valid = cid >= 0
    order = jnp.argsort(jnp.where(valid, -score, jnp.inf), stable=True)
    sdet = det[order]
    svalid = valid[order]
    nkeep = a if nms_topk < 0 else min(nms_topk, a)
    # beyond-topk detections are discarded (id -> -1), rows remain
    sdet = sdet.at[:, 0].set(
        jnp.where(svalid & (jnp.arange(a) >= nkeep), -1.0, sdet[:, 0]))
    # blank out invalid rows entirely (reference preinitialises out to -1)
    sdet = jnp.where(svalid[:, None], sdet, -1.0)

    iou = _pairwise_iou(sdet[:, 2:6], sdet[:, 2:6], "corner")
    if force_suppress:
        same = jnp.ones((a, a), bool)
    else:
        same = sdet[:, 0][:, None] == sdet[:, 0][None, :]
    sup_mat = (iou >= nms_threshold) & same
    later = jnp.arange(a)[None, :] > jnp.arange(a)[:, None]

    def body(i, ids):
        alive_i = ids[i] >= 0
        sup = sup_mat[i] & later[i] & (ids >= 0)
        return jnp.where(alive_i, jnp.where(sup, -1.0, ids), ids)

    ids = lax.fori_loop(0, nkeep, body, sdet[:, 0])
    return sdet.at[:, 0].set(ids)


@register("_contrib_MultiBoxDetection", alias=("MultiBoxDetection",))
def _contrib_multibox_detection(attrs, cls_prob, loc_pred, anchor):
    if int(attrs.get("background_id", 0)) != 0:
        raise NotImplementedError(
            "MultiBoxDetection: only background_id=0 is supported (the "
            "reference CPU/GPU kernels also hardcode class 0 as background)")
    kw = dict(
        clip=bool(attrs.get("clip", True)),
        threshold=float(attrs.get("threshold", 0.01)),
        nms_threshold=float(attrs.get("nms_threshold", 0.5)),
        force_suppress=bool(attrs.get("force_suppress", False)),
        variances=_floats(attrs.get("variances"), (0.1, 0.1, 0.2, 0.2)),
        nms_topk=int(attrs.get("nms_topk", -1)),
    )
    anchors = anchor.reshape(-1, 4)
    return jax.vmap(
        lambda cp, lp: _mbox_detection_one(cp, lp, anchors, **kw))(
            cls_prob, loc_pred)


# ---------------------------------------------------------------------------
# ROIAlign
# ---------------------------------------------------------------------------
def _roi_align_one(data, roi, *, pooled_h, pooled_w, spatial_scale,
                   sample_ratio, position_sensitive):
    """One ROI of ROIAlignForward (roi_align.cc:150): average of bilinear
    samples per bin; batch index in roi[0].

    Deviation (documented): sample_ratio <= 0 means an adaptive
    per-roi grid in the reference (ceil(roi_size/pooled)); XLA needs a
    static grid, so <=0 falls back to 2 samples per bin axis.
    """
    b, c, h, w = data.shape
    sg = sample_ratio if sample_ratio > 0 else 2
    feat = jnp.take(data, roi[0].astype(jnp.int32), axis=0,
                    mode="clip")                       # (C, H, W)
    start_w = roi[1] * spatial_scale
    start_h = roi[2] * spatial_scale
    end_w = roi[3] * spatial_scale
    end_h = roi[4] * spatial_scale
    roi_w = jnp.maximum(end_w - start_w, 1.0)
    roi_h = jnp.maximum(end_h - start_h, 1.0)
    bin_w = roi_w / pooled_w
    bin_h = roi_h / pooled_h

    def axis_coords(start, bin_sz, pooled):
        # sample centres: start + p*bin + (i+.5)*bin/sg
        p = jnp.arange(pooled, dtype=data.dtype)[:, None]
        i = jnp.arange(sg, dtype=data.dtype)[None, :]
        return (start + p * bin_sz + (i + 0.5) * bin_sz / sg).reshape(-1)

    ys = axis_coords(start_h, bin_h, pooled_h)          # (Ph*sg,)
    xs = axis_coords(start_w, bin_w, pooled_w)          # (Pw*sg,)

    def bilinear(coords, size):
        # outside [-1, size] contributes zero; clamp<0 to 0 (roi_align.cc
        # bilinear_interpolate edge handling)
        inside = (coords >= -1.0) & (coords <= size)
        cc = jnp.clip(coords, 0.0, size - 1)
        lo = jnp.floor(cc)
        hi = jnp.minimum(lo + 1, size - 1)
        frac = cc - lo
        return (lo.astype(jnp.int32), hi.astype(jnp.int32), frac,
                inside.astype(data.dtype))

    y0, y1, fy, my = bilinear(ys, h)
    x0, x1, fx, mx = bilinear(xs, w)

    def gather(yi, xi):
        return feat[:, yi[:, None], xi[None, :]]        # (C, Ny, Nx)

    val = ((1 - fy)[None, :, None] * (1 - fx)[None, None, :] * gather(y0, x0)
           + (1 - fy)[None, :, None] * fx[None, None, :] * gather(y0, x1)
           + fy[None, :, None] * (1 - fx)[None, None, :] * gather(y1, x0)
           + fy[None, :, None] * fx[None, None, :] * gather(y1, x1))
    val = val * my[None, :, None] * mx[None, None, :]
    val = val.reshape(-1, pooled_h, sg, pooled_w, sg).mean(axis=(2, 4))

    if position_sensitive:
        c_out = c // (pooled_h * pooled_w)
        ph = jnp.arange(pooled_h)[:, None]
        pw = jnp.arange(pooled_w)[None, :]
        chan = (jnp.arange(c_out)[:, None, None] * pooled_h * pooled_w
                + ph[None] * pooled_w + pw[None])       # (Co,Ph,Pw)
        val = jnp.take_along_axis(
            val[None].repeat(c_out, 0).reshape(c_out, c, pooled_h,
                                               pooled_w),
            chan[:, None], axis=1).squeeze(1)
    return val


@register("_contrib_ROIAlign", alias=("ROIAlign",))
def _contrib_roi_align(attrs, data, rois):
    pooled = attrs["pooled_size"]
    ph, pw = int(pooled[0]), int(pooled[1])
    kw = dict(pooled_h=ph, pooled_w=pw,
              spatial_scale=float(attrs.get("spatial_scale", 1.0)),
              sample_ratio=int(attrs.get("sample_ratio", -1)),
              position_sensitive=bool(attrs.get("position_sensitive",
                                                False)))
    return jax.vmap(lambda r: _roi_align_one(data, r, **kw))(rois)


# ---------------------------------------------------------------------------
# ROIPooling (legacy top-level op, src/operator/roi_pooling.cc)
# ---------------------------------------------------------------------------
def _roi_pool_one(data, roi, *, pooled_h, pooled_w, spatial_scale):
    b, c, h, w = data.shape
    dt = data.dtype
    feat = jnp.take(data, roi[0].astype(jnp.int32), axis=0, mode="clip")
    start_w = jnp.round(roi[1] * spatial_scale)
    start_h = jnp.round(roi[2] * spatial_scale)
    end_w = jnp.round(roi[3] * spatial_scale)
    end_h = jnp.round(roi[4] * spatial_scale)
    roi_h = jnp.maximum(end_h - start_h + 1, 1.0)
    roi_w = jnp.maximum(end_w - start_w + 1, 1.0)

    def bin_bounds(p, roi_sz, start, pooled, size):
        lo = jnp.floor(p * roi_sz / pooled) + start
        hi = jnp.ceil((p + 1) * roi_sz / pooled) + start
        return (jnp.clip(lo, 0, size), jnp.clip(hi, 0, size))

    prange_h = jnp.arange(pooled_h, dtype=dt)
    prange_w = jnp.arange(pooled_w, dtype=dt)
    h0, h1 = bin_bounds(prange_h, roi_h, start_h, pooled_h, h)  # (Ph,)
    w0, w1 = bin_bounds(prange_w, roi_w, start_w, pooled_w, w)
    hi = jnp.arange(h, dtype=dt)
    wi = jnp.arange(w, dtype=dt)
    mask_h = (hi[None, :] >= h0[:, None]) & (hi[None, :] < h1[:, None])
    mask_w = (wi[None, :] >= w0[:, None]) & (wi[None, :] < w1[:, None])
    m = mask_h[:, None, :, None] & mask_w[None, :, None, :]  # (Ph,Pw,H,W)
    neg = jnp.asarray(-jnp.inf, dt)
    vals = jnp.where(m[None], feat[:, None, None], neg)      # (C,Ph,Pw,H,W)
    out = vals.max(axis=(3, 4))
    empty = ~m.any(axis=(2, 3))
    return jnp.where(empty[None], jnp.zeros((), dt), out)


@register("ROIPooling")
def _roi_pooling(attrs, data, rois):
    pooled = attrs["pooled_size"]
    kw = dict(pooled_h=int(pooled[0]), pooled_w=int(pooled[1]),
              spatial_scale=float(attrs.get("spatial_scale", 1.0)))
    return jax.vmap(lambda r: _roi_pool_one(data, r, **kw))(rois)


# ---------------------------------------------------------------------------
# transformer helpers (src/operator/contrib/transformer.cc)
# ---------------------------------------------------------------------------
@register("_contrib_div_sqrt_dim", alias=("div_sqrt_dim",))
def _contrib_div_sqrt_dim(attrs, data):
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], data.dtype))


# ---------------------------------------------------------------------------
# Proposal / MultiProposal (RPN)
# ---------------------------------------------------------------------------
def _generate_base_anchors(stride, scales, ratios):
    """py_faster_rcnn anchor generation (proposal.cc GenerateAnchors):
    base box [0,0,stride-1,stride-1], ratio sweep then scale sweep."""
    base = stride
    x_ctr = (base - 1) * 0.5
    size = base * base
    anchors = []
    for r in ratios:
        size_r = size / r
        ws = round(size_r ** 0.5)
        hs = round(ws * r)
        for s in scales:
            w, h = ws * s, hs * s
            anchors.append([x_ctr - 0.5 * (w - 1), x_ctr - 0.5 * (h - 1),
                            x_ctr + 0.5 * (w - 1), x_ctr + 0.5 * (h - 1)])
    import numpy as np
    return np.asarray(anchors, np.float32)        # (A, 4)


def _proposal_one(scores, bbox_deltas, im_info, anchors, *, stride,
                  pre_nms, post_nms, nms_thresh, min_size):
    """One image of ProposalForward (proposal.cc:316-414).

    scores (A,H,W) foreground scores, bbox_deltas (4A,H,W), im_info
    (3,) = [height, width, scale]; anchors (A,4) base anchors.
    Returns rois (post_nms, 4) and scores (post_nms,)."""
    a, h, w = scores.shape
    sx = jnp.arange(w, dtype=jnp.float32) * stride
    sy = jnp.arange(h, dtype=jnp.float32) * stride
    shift = jnp.stack(
        [jnp.tile(sx[None, :], (h, 1)), jnp.tile(sy[:, None], (1, w)),
         jnp.tile(sx[None, :], (h, 1)), jnp.tile(sy[:, None], (1, w))],
        axis=-1)                                     # (H,W,4)
    all_anchors = (anchors[None, None] + shift[:, :, None]) \
        .reshape(-1, 4)                              # (H*W*A, 4)

    deltas = bbox_deltas.reshape(a, 4, h, w).transpose(2, 3, 0, 1) \
        .reshape(-1, 4)                              # (H*W*A, 4)
    score = scores.transpose(1, 2, 0).reshape(-1)    # (H*W*A,)

    # decode (pixel convention with the +1 widths, proposal.cc
    # BBoxTransformInv)
    ws = all_anchors[:, 2] - all_anchors[:, 0] + 1.0
    hs = all_anchors[:, 3] - all_anchors[:, 1] + 1.0
    cx = all_anchors[:, 0] + 0.5 * (ws - 1.0)
    cy = all_anchors[:, 1] + 0.5 * (hs - 1.0)
    pcx = deltas[:, 0] * ws + cx
    pcy = deltas[:, 1] * hs + cy
    pw = jnp.exp(deltas[:, 2]) * ws
    ph = jnp.exp(deltas[:, 3]) * hs
    x1 = pcx - 0.5 * (pw - 1.0)
    y1 = pcy - 0.5 * (ph - 1.0)
    x2 = pcx + 0.5 * (pw - 1.0)
    y2 = pcy + 0.5 * (ph - 1.0)
    # clip to image
    x1 = jnp.clip(x1, 0, im_info[1] - 1.0)
    y1 = jnp.clip(y1, 0, im_info[0] - 1.0)
    x2 = jnp.clip(x2, 0, im_info[1] - 1.0)
    y2 = jnp.clip(y2, 0, im_info[0] - 1.0)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)

    # min-size filter (scaled by im_info[2])
    msize = min_size * im_info[2]
    valid = ((x2 - x1 + 1.0) >= msize) & ((y2 - y1 + 1.0) >= msize)
    score = jnp.where(valid, score, -jnp.inf)

    n = boxes.shape[0]
    k_pre = min(pre_nms, n) if pre_nms > 0 else n
    order = jnp.argsort(-score)[:k_pre]
    sboxes = boxes[order]
    sscore = score[order]
    svalid = jnp.isfinite(sscore)

    # pixel-convention IoU (+1 widths) matching proposal.cc NMS, not the
    # normalised-corner IoU the rest of the contrib family uses
    tl = jnp.maximum(sboxes[:, None, :2], sboxes[None, :, :2])
    br = jnp.minimum(sboxes[:, None, 2:], sboxes[None, :, 2:])
    wh = jnp.maximum(br - tl + 1.0, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area = ((sboxes[:, 2] - sboxes[:, 0] + 1.0)
            * (sboxes[:, 3] - sboxes[:, 1] + 1.0))
    union = area[:, None] + area[None, :] - inter
    iou = jnp.where(union <= 0, 0.0, inter / union)
    later = jnp.arange(k_pre)[None, :] > jnp.arange(k_pre)[:, None]
    sup = (iou > nms_thresh) & later

    def body(i, keep):
        return jnp.where(keep[i], keep & ~sup[i], keep)

    keep = lax.fori_loop(0, k_pre, body, svalid)
    # compact kept indices to the front; pad by cycling (proposal.cc:414
    # keep[i % out_size])
    pos = jnp.cumsum(keep) - 1
    kept_idx = jnp.zeros(k_pre, jnp.int32).at[
        jnp.where(keep, pos, k_pre)].set(jnp.arange(k_pre),
                                         mode="drop")
    out_size = jnp.maximum(keep.sum(), 1)
    sel = kept_idx[jnp.mod(jnp.arange(post_nms), out_size)]
    return sboxes[sel], sscore[sel]


@register("_contrib_Proposal", alias=("Proposal", "_contrib_MultiProposal",
                                      "MultiProposal"),
          num_outputs="_dynamic")
def _contrib_proposal(attrs, cls_prob, bbox_pred, im_info):
    """RPN proposals (proposal.cc / multi_proposal.cc): cls_prob
    (B,2A,H,W) with foreground scores in the second half, bbox_pred
    (B,4A,H,W), im_info (B,3).  Returns rois (B*post_nms, 5) with batch
    index; + scores when output_score."""
    import numpy as np
    stride = int(attrs.get("feature_stride", 16))
    scales = tuple(float(s) for s in attrs.get("scales", (4, 8, 16, 32)))
    ratios = tuple(float(r) for r in attrs.get("ratios", (0.5, 1, 2)))
    pre_nms = int(attrs.get("rpn_pre_nms_top_n", 6000))
    post_nms = int(attrs.get("rpn_post_nms_top_n", 300))
    nms_thresh = float(attrs.get("threshold", 0.7))
    min_size = float(attrs.get("rpn_min_size", 16))
    if bool(attrs.get("iou_loss", False)):
        raise NotImplementedError("Proposal: iou_loss decoding is not "
                                  "supported")
    anchors = jnp.asarray(_generate_base_anchors(stride, scales, ratios))
    a = anchors.shape[0]
    fg = cls_prob[:, a:, :, :]                       # (B,A,H,W)

    rois, scores = jax.vmap(
        lambda s, d, ii: _proposal_one(
            s, d, ii, anchors, stride=stride, pre_nms=pre_nms,
            post_nms=post_nms, nms_thresh=nms_thresh,
            min_size=min_size))(fg, bbox_pred, im_info)
    b = rois.shape[0]
    batch_idx = jnp.repeat(jnp.arange(b, dtype=rois.dtype), post_nms)
    rois_out = jnp.concatenate(
        [batch_idx[:, None], rois.reshape(-1, 4)], axis=1)
    if bool(attrs.get("output_score", False)):
        return rois_out, scores.reshape(-1, 1)
    return rois_out
