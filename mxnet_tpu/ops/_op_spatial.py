"""Spatial-transformer op family + FFT.

Reference: src/operator/bilinear_sampler.cc (BilinearSampler),
grid_generator-inl.h (GridGenerator affine/warp),
spatial_transformer-inl.h (SpatialTransformer: affine grid + bilinear
sampling, target grid -1..1 inclusive i.e. align-corners),
correlation-inl.h (FlowNet correlation volume), contrib/fft-inl.h +
ifft-inl.h (cuFFT C2C; ifft is UNNORMALIZED — the reference's
`out /= dim_` is commented out).

TPU redesign: sampling is gather-based bilinear interpolation (JAX AD
produces the scatter-add backward the reference hand-writes in
bilinear_sampler.cu); the correlation volume is a displacement loop of
fused multiply + box-filter convs (D² is small); FFT lowers to XLA's
native fft HLO instead of a cuFFT plan pool.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _bilinear_sample_zero_pad(data, x_src, y_src):
    """Sample data (B,C,H,W) at real-valued pixel coords x_src/y_src
    (B,Ho,Wo); out-of-bounds corners contribute zero (reference
    BilinearSamplerForward corner-validity checks)."""
    b, c, h, w = data.shape
    x0 = jnp.floor(x_src)
    y0 = jnp.floor(y_src)
    outs = 0.0
    for dy in (0, 1):
        for dx in (0, 1):
            xi = x0 + dx
            yi = y0 + dy
            wgt = ((1 - jnp.abs(x_src - xi)) *
                   (1 - jnp.abs(y_src - yi)))          # bilinear weight
            valid = (xi >= 0) & (xi <= w - 1) & (yi >= 0) & (yi <= h - 1)
            xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
            yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
            # gather per batch: (B,C,Ho,Wo)
            vals = jax.vmap(
                lambda d, yy, xx: d[:, yy, xx])(data, yc, xc)
            outs = outs + vals * (wgt * valid)[:, None]
    return outs


@register("BilinearSampler")
def _bilinear_sampler(attrs, data, grid):
    """data (B,C,H,W), grid (B,2,Ho,Wo) with x=grid[:,0], y=grid[:,1] in
    [-1,1] (align-corners normalisation, bilinear_sampler-inl.h)."""
    b, c, h, w = data.shape
    x_src = (grid[:, 0] + 1) * (w - 1) / 2
    y_src = (grid[:, 1] + 1) * (h - 1) / 2
    return _bilinear_sample_zero_pad(data, x_src, y_src)


def _affine_grid(theta, target_shape, dtype):
    """(B,6) affine params -> (B,2,H,W) source coords in [-1,1]
    (spatial_transformer-inl.h:99 target grid, align-corners)."""
    ho, wo = target_shape
    ys = jnp.linspace(-1.0, 1.0, ho, dtype=dtype)
    xs = jnp.linspace(-1.0, 1.0, wo, dtype=dtype)
    gx, gy = jnp.meshgrid(xs, ys)                      # (H,W)
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)  # (3, H*W)
    t = theta.reshape(-1, 2, 3)
    src = jnp.einsum("bij,jk->bik", t, base)           # (B,2,H*W)
    return src.reshape(-1, 2, ho, wo)


@register("GridGenerator")
def _grid_generator(attrs, data):
    ttype = attrs.get("transform_type", "affine")
    dtype = data.dtype
    if ttype == "affine":
        ho, wo = (int(s) for s in attrs["target_shape"])
        return _affine_grid(data, (ho, wo), dtype)
    if ttype == "warp":
        # data = flow (B,2,H,W) in pixels; normalised absolute coords out
        b, _, h, w = data.shape
        gx = jnp.arange(w, dtype=dtype)[None, None, :]
        gy = jnp.arange(h, dtype=dtype)[None, :, None]
        x = (data[:, 0] + gx) * 2 / max(w - 1, 1) - 1
        y = (data[:, 1] + gy) * 2 / max(h - 1, 1) - 1
        return jnp.stack([x, y], axis=1)
    raise ValueError(f"unknown transform_type {ttype}")


@register("SpatialTransformer")
def _spatial_transformer(attrs, data, loc):
    """Affine spatial transformer (data (B,C,H,W), loc (B,6))."""
    ho, wo = (int(s) for s in attrs["target_shape"])
    grid = _affine_grid(loc, (ho, wo), data.dtype)
    return _bilinear_sampler({}, data, grid)


@register("Correlation", num_outputs=3, num_visible=1)
def _correlation(attrs, data1, data2):
    """FlowNet correlation volume (correlation-inl.h).  Output channels
    enumerate the (2*max_displacement/stride2+1)^2 displacement grid;
    each is the kernel-window mean of data1·shift(data2) (is_multiply)
    or -|data1-shift(data2)|.  Hidden outputs tmp1/tmp2 mirror the
    reference's rearranged-patch workspaces (ListOutputs
    correlation-inl.h:175, NumVisibleOutputs 1)."""
    k = int(attrs.get("kernel_size", 1))
    max_d = int(attrs.get("max_displacement", 1))
    s1 = int(attrs.get("stride1", 1))
    s2 = int(attrs.get("stride2", 1))
    pad = int(attrs.get("pad_size", 0))
    multiply = bool(attrs.get("is_multiply", True))
    b, c, h, w = data1.shape
    kr = k // 2                                  # kernel radius
    border = max_d + kr
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    ph, pw = h + 2 * pad, w + 2 * pad
    # output spatial positions x1 = border + i*s1 (correlation-inl.h
    # top_height = ceil((paddedheight - border*2) / stride1))
    ho = -(-(ph - 2 * border) // s1)
    wo = -(-(pw - 2 * border) // s1)
    sumelems = k * k * c
    box = jnp.ones((1, 1, k, k), data1.dtype) / sumelems

    if max_d % s1:
        raise ValueError("Correlation: max_displacement must be a "
                         "multiple of stride1")
    maps = []
    for dy in range(-max_d, max_d + 1, s2):
        for dx in range(-max_d, max_d + 1, s2):
            shifted = jnp.roll(p2, (-dy, -dx), axis=(2, 3))
            prod = p1 * shifted if multiply else \
                -jnp.abs(p1 - shifted)
            prod = prod.sum(axis=1, keepdims=True)   # (B,1,ph,pw)
            # kernel-window mean at the output stride; conv output t has
            # window centre t*s1 + kr, we need centres border + i*s1
            m = lax.conv_general_dilated(
                prod, box, window_strides=(s1, s1),
                padding=[(0, 0), (0, 0)],
                dimension_numbers=lax.conv_dimension_numbers(
                    prod.shape, box.shape, ("NCHW", "OIHW", "NCHW")))
            start = max_d // s1
            maps.append(m[:, :, start:start + ho, start:start + wo])
    out = jnp.concatenate(maps, axis=1)
    return out, p1, p2


@register("_contrib_fft", alias=("fft",))
def _contrib_fft(attrs, data):
    """1D FFT over the last axis; complex output interleaved as
    [..., re0, im0, re1, im1, ...] (contrib/fft-inl.h layout)."""
    f = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    out = jnp.stack([f.real, f.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (2 * data.shape[-1],)) \
        .astype(jnp.float32)


@register("_contrib_ifft", alias=("ifft",))
def _contrib_ifft(attrs, data):
    """Inverse of _contrib_fft, UNNORMALIZED like the reference's cuFFT
    C2C inverse (ifft-inl.h:136 has the normalisation commented out):
    ifft(fft(x)) == d * x."""
    d = data.shape[-1] // 2
    c = data.reshape(data.shape[:-1] + (d, 2))
    z = c[..., 0] + 1j * c[..., 1]
    return (jnp.fft.ifft(z, axis=-1) * d).real.astype(jnp.float32)


@register("Crop", input_names=None)
def _crop_layer(attrs, data, *maybe_like):
    """Legacy spatial Crop layer (reference src/operator/crop.cc:43):
    crops dims 2/3 of NCHW data to h_w, or to the spatial size of a
    second crop_like input; offset=(y,x) or center_crop."""
    h_w = attrs.get("h_w")
    if maybe_like:
        th, tw = maybe_like[0].shape[2], maybe_like[0].shape[3]
    else:
        if not h_w:
            raise ValueError("Crop needs h_w when no crop_like input")
        th, tw = int(h_w[0]), int(h_w[1])
    H, W = data.shape[2], data.shape[3]
    if bool(attrs.get("center_crop", False)):
        y0 = (H - th) // 2
        x0 = (W - tw) // 2
    else:
        off = attrs.get("offset", (0, 0))
        y0, x0 = int(off[0]), int(off[1])
    return data[:, :, y0:y0 + th, x0:x0 + tw]
