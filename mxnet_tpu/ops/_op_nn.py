"""Neural-network ops.

Covers reference src/operator/nn/* (Convolution/Deconvolution + im2col CUDA,
cuDNN wrappers, Pooling pool.cuh, BatchNorm, LayerNorm, Dropout, Softmax
family, FullyConnected) and the fused RNN op (src/operator/rnn-inl.h:395).
TPU redesign: convs/matmuls lower to XLA conv_general_dilated/dot_general
which tile onto the MXU; the cuDNN autotuning layer has no equivalent because
XLA autotunes; fused RNN = lax.scan over a step function (compiled into one
loop on device, hidden-state in registers/VMEM instead of cuDNN descriptors).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import register
from ..base import MXNetError


def _attr_bool(v):
    """Robust bool attr: accepts reference-style string attrs
    ("True"/"False"/"1"/"0") as well as Python bools."""
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes")
    return bool(v)


def _wb_names(attrs):
    """data/weight/bias input roles, honoring no_bias (FListInputNames
    parity: reference nn/fully_connected.cc ListArguments)."""
    if _attr_bool(attrs.get("no_bias", False)):
        return ("data", "weight")
    return ("data", "weight", "bias")


# --- FullyConnected (reference: nn/fully_connected.cc) ----------------------
@register("FullyConnected", input_names=_wb_names)
def _fully_connected(attrs, x, weight, *maybe_bias):
    x = x.astype(weight.dtype)  # AMP contract: weight dtype is authoritative
    if not bool(attrs.get("flatten", True)):
        out = jnp.matmul(x, weight.T)
    else:
        # explicit product, not -1: jnp's -1 inference divides by the
        # other dims' product and breaks on 0-size batches
        flat = 1
        for d in x.shape[1:]:
            flat *= d
        x2 = x.reshape(x.shape[0], flat)
        out = jnp.matmul(x2, weight.T)
    if maybe_bias and not bool(attrs.get("no_bias", False)):
        out = out + maybe_bias[0]
    return out


# --- Convolution (reference: nn/convolution.cc:399-527, im2col.cuh) ---------
def _conv_dim_numbers(ndim, layout):
    if layout in (None, "NCHW", "NCW", "NCDHW"):
        spec = "NC" + "DHW"[3 - (ndim - 2):]
        return lax.conv_dimension_numbers((1,) * ndim, (1,) * ndim,
                                          (spec, "OI" + spec[2:], spec))
    if layout in ("NHWC", "NWC", "NDHWC"):
        spatial = "DHW"[3 - (ndim - 2):]
        spec = "N" + spatial + "C"
        return lax.conv_dimension_numbers((1,) * ndim, (1,) * ndim,
                                          (spec, spatial + "IO", spec))
    raise ValueError(f"unsupported layout {layout}")


def _tupleize(v, n):
    if v is None:
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    t = tuple(int(x) for x in v)
    return t if t else (1,) * n


@register("Convolution", input_names=_wb_names)
def _convolution(attrs, x, weight, *maybe_bias):
    kernel = tuple(attrs["kernel"])
    nd = len(kernel)
    stride = _tupleize(attrs.get("stride"), nd)
    dilate = _tupleize(attrs.get("dilate"), nd)
    pad = _tupleize(attrs.get("pad"), nd) if attrs.get("pad") else (0,) * nd
    groups = int(attrs.get("num_group", 1))
    layout = attrs.get("layout", None) or ("NCW", "NCHW", "NCDHW")[nd - 1]
    dn = _conv_dim_numbers(nd + 2, layout)
    x = x.astype(weight.dtype)  # AMP contract: weight dtype is authoritative
    if (max(stride) > 1 and all(k == 1 for k in kernel)
            and all(p == 0 for p in pad)):
        # Strided 1x1 conv == spatial subsample + stride-1 1x1 conv (the
        # kernel only ever reads positions s*o).  Same forward FLOPs, but
        # the autodiff backward-data becomes a stride-1 dgrad plus a
        # zero-scatter pad instead of a conv over the zero-dilated input,
        # which XLA executes (and charges) at stride^2 x the useful work
        # — measured 4x on ResNet-50's downsample convs, ~8% of the whole
        # train step (tools/hlo_flops.py, round-5 forensics).
        sp_axes = [i for i, ch in enumerate(layout) if ch in "DHW"]
        slicer = [slice(None)] * x.ndim
        for ax, s in zip(sp_axes, stride):
            slicer[ax] = slice(None, None, s)
        x = x[tuple(slicer)]
        stride = (1,) * nd
    # no preferred_element_type: TPU MXU accumulates bf16 convs in f32
    # already, and a mixed-dtype preferred type breaks the conv transpose
    # (backward) under jit
    out = lax.conv_general_dilated(
        x, weight, window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=groups)
    if maybe_bias and not bool(attrs.get("no_bias", False)):
        b = maybe_bias[0]
        if layout.endswith("C"):
            out = out + b
        else:
            out = out + b.reshape((1, -1) + (1,) * nd)
    return out


@register("Deconvolution", input_names=_wb_names)
def _deconvolution(attrs, x, weight, *maybe_bias):
    kernel = tuple(attrs["kernel"])
    nd = len(kernel)
    stride = _tupleize(attrs.get("stride"), nd)
    dilate = _tupleize(attrs.get("dilate"), nd)
    pad = _tupleize(attrs.get("pad"), nd) if attrs.get("pad") else (0,) * nd
    adj = _tupleize(attrs.get("adj"), nd) if attrs.get("adj") else (0,) * nd
    groups = int(attrs.get("num_group", 1))
    layout = attrs.get("layout", None) or ("NCW", "NCHW", "NCDHW")[nd - 1]
    dn = _conv_dim_numbers(nd + 2, layout)
    x = x.astype(weight.dtype)
    # transposed conv = lhs-dilated conv with flipped, IO-swapped kernel
    k_eff = [(k - 1) * d + 1 for k, d in zip(kernel, dilate)]
    tshape = attrs.get("target_shape")
    if tshape:
        # target_shape overrides pad/adj (reference deconvolution-inl.h:
        # InferPad — pad/adj attrs are IGNORED when a target is given)
        tshape = (tshape,) if isinstance(tshape, int) else tuple(tshape)
        if len(tshape) != nd:
            raise MXNetError(
                f"target_shape {tshape} must have {nd} dims to match "
                f"kernel {kernel}")
        in_sp = x.shape[2:] if not layout.endswith("C") else x.shape[1:-1]
        # reference InferPad (deconvolution-inl.h:138): total excess =
        # s*(i-1) + k_eff - target; odd totals put the EXTRA row in pad
        # (pad = (total+1)/2) and compensate with adj = total % 2
        totals = [stride[j] * (in_sp[j] - 1) + k_eff[j] - int(tshape[j])
                  for j in range(nd)]
        if any(t < 0 for t in totals):
            raise MXNetError(f"too big target shape {tshape}")
        pad = tuple((t + 1) // 2 for t in totals)
        adj = tuple(t % 2 for t in totals)
    padding = [(ke - 1 - p, ke - 1 - p + a) for ke, p, a in zip(k_eff, pad, adj)]
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    w = jnp.swapaxes(w, 0, 1)
    if groups > 1:
        # weight layout (Cin, Cout/g, *k) -> regroup for grouped transpose conv
        cin, coutg = weight.shape[0], weight.shape[1]
        w = weight.reshape((groups, cin // groups, coutg) + kernel)
        w = jnp.flip(w, axis=tuple(range(3, 3 + nd)))
        w = jnp.swapaxes(w, 1, 2)
        w = w.reshape((coutg * groups, cin // groups) + kernel)
    out = lax.conv_general_dilated(
        x, w, window_strides=(1,) * nd, padding=padding,
        lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=groups)
    if maybe_bias and not bool(attrs.get("no_bias", False)):
        b = maybe_bias[0]
        out = out + (b if layout.endswith("C")
                     else b.reshape((1, -1) + (1,) * nd))
    return out


# --- Pooling (reference: nn/pooling.cc, pool.cuh) ---------------------------
@register("Pooling")
def _pooling(attrs, x):
    pool_type = attrs.get("pool_type", "max")
    global_pool = bool(attrs.get("global_pool", False))
    nd = x.ndim - 2
    layout = attrs.get("layout", None) or ("NCW", "NCHW", "NCDHW")[nd - 1]
    channel_last = layout.endswith("C")
    sp_axes = tuple(range(1, 1 + nd)) if channel_last else tuple(range(2, 2 + nd))
    if global_pool:
        if pool_type == "max":
            return jnp.max(x, axis=sp_axes, keepdims=True)
        return jnp.mean(x, axis=sp_axes, keepdims=True)
    kernel = tuple(attrs["kernel"])
    stride = _tupleize(attrs.get("stride"), nd)
    pad = _tupleize(attrs.get("pad"), nd) if attrs.get("pad") else (0,) * nd
    conv = attrs.get("pooling_convention", "valid")

    if channel_last:  # normalize to channel-first for the window extraction
        perm = (0, x.ndim - 1) + tuple(range(1, x.ndim - 1))
        x = x.transpose(perm)

    pad_lohi = [(p, p) for p in pad]
    if conv == "full":
        # ceil-mode: extend padding on the high side so the last window fits
        for i in range(nd):
            size = x.shape[2 + i] + 2 * pad[i]
            rem = (size - kernel[i]) % stride[i]
            if rem:
                pad_lohi[i] = (pad[i], pad[i] + stride[i] - rem)

    # lax.reduce_window is THE TPU pooling primitive: fwd fuses into a
    # windowed reduce, max-pool backward lowers to select_and_scatter_add
    # (hardware path) instead of a scatter. Measured on TPU v5e at the
    # ResNet stem shape (32,64,112,112): gather-windows fwd+bwd 4.62 ms vs
    # reduce_window 0.36 ms — the scatter-add backward was 13x slower.
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    padding = [(0, 0), (0, 0)] + [tuple(p) for p in pad_lohi]
    # init values MUST be python scalars: jax only recognizes the
    # max/add monoid (and so attaches the autodiff rule) for literal
    # identity inits — an array init falls back to the generic
    # reduce_window primitive, which the whole-graph vjp cannot linearize
    if pool_type == "max":
        if jnp.issubdtype(x.dtype, jnp.floating):
            init = -jnp.inf
        else:
            init = int(jnp.iinfo(x.dtype).min)
        out = lax.reduce_window(x, init, lax.max, window, strides, padding)
    elif pool_type in ("avg", "sum"):
        zero = 0.0 if jnp.issubdtype(x.dtype, jnp.floating) else 0
        summed = lax.reduce_window(x, zero, lax.add, window, strides,
                                   padding)
        if pool_type == "sum":
            out = summed
        elif bool(attrs.get("count_include_pad", True)):
            out = summed / jnp.asarray(float(np.prod(kernel)), x.dtype)
        else:
            # counts are identical across batch/channel — pool a (1,1,...)
            # ones tensor and broadcast
            ones = jnp.ones((1, 1) + x.shape[2:], x.dtype)
            counts = lax.reduce_window(ones, zero, lax.add,
                                       (1, 1) + tuple(kernel),
                                       strides, padding)
            out = summed / counts
    else:
        raise ValueError(f"pool_type {pool_type}")

    if channel_last:
        inv = (0,) + tuple(range(2, out.ndim)) + (1,)
        out = out.transpose(inv)
    return out


@register("UpSampling")
def _upsampling(attrs, x, *weights):
    scale = int(attrs["scale"])
    if attrs.get("sample_type", "nearest") == "nearest":
        return jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
    n, c, h, w = x.shape
    return jax.image.resize(x, (n, c, h * scale, w * scale), method="bilinear")


# --- normalisation ----------------------------------------------------------
@register("BatchNorm", num_outputs=3, mutate_aux=(3, 4),
          input_names=("data", "gamma", "beta", "moving_mean", "moving_var"))
def _batch_norm(attrs, x, gamma, beta, moving_mean, moving_var):
    """Returns (out, new_moving_mean, new_moving_var).

    Reference nn/batch_norm.cc mutates the aux states in-place during
    training; here updated aux are explicit outputs (functional) and the
    caller writes them back (see gluon.nn.BatchNorm / executor aux handling).
    """
    eps = float(attrs.get("eps", 1e-3))
    momentum = float(attrs.get("momentum", 0.9))
    axis = int(attrs.get("axis", 1)) % x.ndim  # axis=-1 == channels-last
    training = bool(attrs.get("_training", False)) and not bool(
        attrs.get("use_global_stats", False))
    fix_gamma = bool(attrs.get("fix_gamma", True))
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    red_axes = tuple(i for i in range(x.ndim) if i != axis)
    bshape = tuple(x.shape[i] if i == axis else 1 for i in range(x.ndim))
    if training:
        # two-pass (x - mean)^2 statistics in f32: the one-pass
        # E[x^2]-E[x]^2 form catastrophically cancels for large-mean/
        # small-variance channels (measured: mean 1e3, std 1e-2 gives
        # var 0.0), corrupting inv AND the moving stats
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=red_axes)
        var = jnp.var(xf, axis=red_axes)
        new_mm = moving_mean * momentum + mean.astype(moving_mean.dtype) * (1 - momentum)
        new_mv = moving_var * momentum + var.astype(moving_var.dtype) * (1 - momentum)
    else:
        mean, var = moving_mean, moving_var
        new_mm, new_mv = moving_mean, moving_var
    # normalization folded to one per-channel affine (a, b). The
    # elementwise pass computes in f32 and casts the result back: XLA
    # fuses the converts, so a bf16 input still costs one bf16 read +
    # one bf16 write of HBM while the a*x+b arithmetic (which cancels
    # ~|mean|-sized terms) happens at f32 in registers.
    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    a = gamma.astype(jnp.float32) * inv
    b = beta.astype(jnp.float32) - mean.astype(jnp.float32) * a
    out = (x.astype(jnp.float32) * a.reshape(bshape)
           + b.reshape(bshape)).astype(x.dtype)
    return out, new_mm, new_mv


_LN_PROBED = {}


def _fused_ln_ok(n_rows, d, x_dtype, g_dtype, b_dtype):
    """Decide once per tile configuration whether the Pallas LN kernel is
    safe.  The probe compiles the SAME (block_rows, d) tile and the same
    input dtypes a real call would use, so a Mosaic rejection (VMEM
    overflow, unsupported width) is caught here and the op falls back to
    plain XLA.  MXNET_FUSED_LAYERNORM=0/1 forces the choice; default
    'auto' probes.
    """
    import os
    flag = os.environ.get("MXNET_FUSED_LAYERNORM", "auto").lower()
    if flag in ("0", "false", "off"):
        return False
    if flag in ("1", "true", "on"):
        return True
    from .pallas_norm import _pick_block_rows, fused_layer_norm
    block_rows = _pick_block_rows(int(n_rows))
    key = (block_rows, int(d), jnp.dtype(x_dtype).name,
           jnp.dtype(g_dtype).name, jnp.dtype(b_dtype).name)
    if key not in _LN_PROBED:
        try:
            import numpy as _np
            probe = fused_layer_norm(jnp.ones((block_rows, d), x_dtype),
                                     jnp.ones((d,), g_dtype),
                                     jnp.zeros((d,), b_dtype))
            _np.asarray(probe)
            _LN_PROBED[key] = True
        except Exception as e:  # noqa: BLE001 — Mosaic rejection gates off
            import logging
            logging.getLogger("mxnet_tpu.ops").debug(
                "fused layernorm gated off for tile %s (%s: %s); "
                "falling back to plain XLA", key, type(e).__name__, e)
            _LN_PROBED[key] = False
    return _LN_PROBED[key]


@register("LayerNorm", input_names=("data", "gamma", "beta"))
def _layer_norm(attrs, x, gamma, beta):
    axis = int(attrs.get("axis", -1))
    eps = float(attrs.get("eps", 1e-5))
    from .pallas_norm import plain_layer_norm
    if axis in (-1, x.ndim - 1) and gamma.ndim == 1:
        # the kernels subsystem owns the choice when opted in
        # (MXNET_KERNELS=reference|tuned); off returns None and the
        # legacy per-op gate below keeps its seed-era behavior
        from .. import kernels as _kernels
        kb = _kernels.get("layernorm", x.shape, x.dtype)
        if kb is not None:
            return kb(x, gamma, beta, eps)
        # trailing-axis LN takes the fused Pallas kernel (one HBM
        # read+write per element; pallas_norm.py) — the hot
        # transformer configuration
        if _fused_ln_ok(int(np.prod(x.shape[:-1])), x.shape[-1],
                        x.dtype, gamma.dtype, beta.dtype):
            from .pallas_norm import fused_layer_norm
            return fused_layer_norm(x, gamma, beta, eps=eps)
    return plain_layer_norm(x, gamma, beta, eps=eps, axis=axis)


@register("GroupNorm", input_names=("data", "gamma", "beta"))
def _group_norm(attrs, x, gamma, beta):
    ng = int(attrs.get("num_groups", 1))
    eps = float(attrs.get("eps", 1e-5))
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, ng, c // ng) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
    bshape = (1, c) + (1,) * (x.ndim - 2)
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("InstanceNorm", input_names=("data", "gamma", "beta"))
def _instance_norm(attrs, x, gamma, beta):
    eps = float(attrs.get("eps", 1e-3))
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + eps)
    bshape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("L2Normalization")
def _l2_normalization(attrs, x):
    eps = float(attrs.get("eps", 1e-10))
    mode = attrs.get("mode", "instance")
    if mode == "instance":
        axes = tuple(range(1, x.ndim))
    elif mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, x.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
    return x / norm


@register("LRN")
def _lrn(attrs, x):
    nsize = int(attrs.get("nsize", 5))
    alpha = float(attrs.get("alpha", 1e-4))
    beta = float(attrs.get("beta", 0.75))
    knorm = float(attrs.get("knorm", 2.0))
    sq = jnp.square(x)
    pad = nsize // 2
    sq_pad = jnp.pad(sq, ((0, 0), (pad, pad)) + ((0, 0),) * (x.ndim - 2))
    acc = sum(sq_pad[:, i:i + x.shape[1]] for i in range(nsize))
    return x / jnp.power(knorm + alpha / nsize * acc, beta)


# --- activations ------------------------------------------------------------
@register("Activation")
def _activation(attrs, x):
    act = attrs["act_type"]
    return {
        "relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
        "softrelu": jax.nn.softplus, "softsign": jax.nn.soft_sign,
        "log_sigmoid": jax.nn.log_sigmoid,
    }[act](x)


@register("LeakyReLU")
def _leaky_relu(attrs, x, *maybe_gamma):
    act = attrs.get("act_type", "leaky")
    slope = float(attrs.get("slope", 0.25))
    if act == "leaky":
        return jnp.where(x > 0, x, slope * x)
    if act == "prelu":
        gamma = maybe_gamma[0]
        g = gamma.reshape((1, -1) + (1,) * (x.ndim - 2)) if gamma.ndim == 1 and x.ndim > 1 else gamma
        return jnp.where(x > 0, x, g * x)
    if act == "elu":
        return jnp.where(x > 0, x, slope * jnp.expm1(x))
    if act == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))
    if act == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if act == "rrelu":  # eval-mode deterministic (mean slope)
        lower, upper = float(attrs.get("lower_bound", 0.125)), float(attrs.get("upper_bound", 0.334))
        return jnp.where(x > 0, x, (lower + upper) / 2 * x)
    raise ValueError(act)


# --- softmax family (reference: nn/softmax-inl.h) ---------------------------
@register("softmax")
def _softmax(attrs, x, *maybe_length):
    axis = int(attrs.get("axis", -1))
    temp = attrs.get("temperature", None)
    if temp:
        x = x / float(temp)
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax")
def _log_softmax(attrs, x):
    axis = int(attrs.get("axis", -1))
    temp = attrs.get("temperature", None)
    if temp:
        x = x / float(temp)
    return jax.nn.log_softmax(x, axis=axis)


@register("softmin")
def _softmin(attrs, x):
    return jax.nn.softmax(-x, axis=int(attrs.get("axis", -1)))


def _softmax_output_grad(attrs, primals, cotangents):
    """Custom gradient matching reference softmax_output-inl.h: grad wrt data
    is (softmax - one_hot(label)) * grad_scale, label gets no grad."""
    data, label = primals
    grad_scale = float(attrs.get("grad_scale", 1.0))
    prob = jax.nn.softmax(data, axis=-1)
    if bool(attrs.get("multi_output", False)):
        oh = jax.nn.one_hot(label.astype(jnp.int32), data.shape[1], axis=1)
    else:
        oh = jax.nn.one_hot(label.astype(jnp.int32), data.shape[-1])
    ignore = attrs.get("ignore_label", None)
    g = (prob - oh) * grad_scale
    if ignore is not None and bool(attrs.get("use_ignore", False)):
        mask = (label != float(ignore)).astype(data.dtype)
        g = g * mask[..., None]
    norm = attrs.get("normalization", "null")
    if norm == "batch":
        g = g / data.shape[0]
    elif norm == "valid" and ignore is not None:
        g = g / jnp.maximum((label != float(ignore)).sum(), 1)
    return (g * cotangents[0].sum() if cotangents[0].ndim == 0 else g, None)


@register("SoftmaxOutput", fgradient=_softmax_output_grad, alias=("Softmax",),
          input_names=("data", "label"))
def _softmax_output(attrs, data, label):
    return jax.nn.softmax(data, axis=-1)


# --- regression outputs (reference: src/operator/regression_output.cc) ------
def _regression_grad(link, err_fn):
    def grad(attrs, primals, cotangents):
        data, label = primals
        grad_scale = float(attrs.get("grad_scale", 1.0))
        pred = link(data)
        g = err_fn(pred, label.reshape(pred.shape))
        # reference scales by grad_scale / num_output, where num_output is the
        # per-sample output width label.Size()/label.shape_[0]
        # (regression_output-inl.h:200-206) — NOT by batch size.
        num_output = 1
        for d in label.shape[1:]:
            num_output *= d
        g = g * (grad_scale / max(num_output, 1))
        ct = cotangents[0]
        return (g * (ct.sum() if ct.ndim == 0 else 1.0), None)
    return grad


@register("LinearRegressionOutput", input_names=("data", "label"),
          fgradient=_regression_grad(lambda x: x, lambda p, l: p - l))
def _linear_regression_output(attrs, data, label):
    return data


@register("MAERegressionOutput", input_names=("data", "label"),
          fgradient=_regression_grad(lambda x: x,
                                     lambda p, l: jnp.sign(p - l)))
def _mae_regression_output(attrs, data, label):
    return data


@register("LogisticRegressionOutput", input_names=("data", "label"),
          fgradient=_regression_grad(jax.nn.sigmoid, lambda p, l: p - l))
def _logistic_regression_output(attrs, data, label):
    return jax.nn.sigmoid(data)


@register("softmax_cross_entropy")
def _softmax_cross_entropy(attrs, data, label):
    """Total softmax CE over the batch (reference loss_binary_op.cc:30).
    The kernels subsystem (MXNET_KERNELS=reference|tuned) owns the
    implementation when opted in; otherwise the legacy fused Pallas row
    kernel (pallas_softmax_ce.py, gated by MXNET_FUSED_SOFTMAX_CE) —
    one HBM pass over the logits either way."""
    from .pallas_softmax_ce import fused_softmax_ce
    if data.ndim == 2 and data.shape[0] > 0:
        from .. import kernels as _kernels
        kb = _kernels.get("softmax_ce", data.shape, data.dtype)
        if kb is not None:
            return jnp.sum(kb(data, label))
    return jnp.sum(fused_softmax_ce(data, label))


@register("CTCLoss", alias=("ctc_loss",))
def _ctc_loss(attrs, data, label, *lengths):
    """CTC via log-semiring dynamic program under lax.scan (reference uses
    warp-ctc / cudnn CTC, src/operator/nn/ctc_loss.cc)."""
    # data: (T, N, C) alphabet incl. blank at index 0 (MXNet convention)
    T, N, C = data.shape
    logp = jax.nn.log_softmax(data, axis=-1)
    L = label.shape[1]
    blank = 0
    lab = label.astype(jnp.int32)
    # extended label sequence: blank l1 blank l2 ... blank, length 2L+1
    ext = jnp.full((N, 2 * L + 1), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    neg_inf = jnp.asarray(-1e30, dtype=data.dtype)
    alpha0 = jnp.full((N, 2 * L + 1), neg_inf)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
    alpha0 = alpha0.at[:, 1].set(jnp.take_along_axis(logp[0], lab[:, :1], axis=-1)[:, 0])

    same_as_prev2 = jnp.concatenate(
        [jnp.ones((N, 2), dtype=bool),
         ext[:, 2:] == ext[:, :-2]], axis=1)

    if lengths and len(lengths) >= 1 and lengths[0] is not None:
        data_len = lengths[0].astype(jnp.int32)
    else:
        data_len = jnp.full((N,), T, dtype=jnp.int32)

    def step(alpha, inp):
        logp_t, t = inp
        a = alpha
        a1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
        a2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
        a2 = jnp.where(same_as_prev2, neg_inf, a2)
        m = jnp.maximum(jnp.maximum(a, a1), a2)
        s = m + jnp.log(jnp.exp(a - m) + jnp.exp(a1 - m) + jnp.exp(a2 - m) + 1e-30)
        emit = jnp.take_along_axis(logp_t, ext, axis=-1)
        # padded timesteps (t >= data_len) leave alpha untouched
        active = (t < data_len)[:, None]
        return jnp.where(active, s + emit, alpha), None

    alpha, _ = lax.scan(step, alpha0, (logp[1:], jnp.arange(1, T)))
    if lengths and len(lengths) >= 2:
        lab_len = lengths[1].astype(jnp.int32)
    else:
        lab_len = jnp.full((N,), L, dtype=jnp.int32)
    endp = 2 * lab_len - 1
    last = jnp.take_along_axis(alpha, endp[:, None], axis=1)[:, 0]
    last_b = jnp.take_along_axis(alpha, (2 * lab_len)[:, None], axis=1)[:, 0]
    m = jnp.maximum(last, last_b)
    ll = m + jnp.log(jnp.exp(last - m) + jnp.exp(last_b - m))
    return -ll


# --- sequence ops (reference: sequence_{mask,last,reverse}.cc) --------------
@register("SequenceMask")
def _sequence_mask(attrs, data, *maybe_len):
    if not bool(attrs.get("use_sequence_length", False)) or not maybe_len:
        return data
    value = float(attrs.get("value", 0.0))
    axis = int(attrs.get("axis", 0))  # time axis
    slen = maybe_len[0].astype(jnp.int32)
    T = data.shape[axis]
    pos = jnp.arange(T)
    if axis == 0:
        mask = pos[:, None] < slen[None, :]
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    else:
        mask = pos[None, :] < slen[:, None]
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register("SequenceLast")
def _sequence_last(attrs, data, *maybe_len):
    axis = int(attrs.get("axis", 0))
    if bool(attrs.get("use_sequence_length", False)) and maybe_len:
        idx = maybe_len[0].astype(jnp.int32) - 1
        if axis == 0:
            return jnp.take_along_axis(
                data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0)[0]
        return jnp.take_along_axis(
            data, idx.reshape((-1, 1) + (1,) * (data.ndim - 2)), axis=1)[:, 0]
    return jnp.take(data, data.shape[axis] - 1, axis=axis)


@register("SequenceReverse")
def _sequence_reverse(attrs, data, *maybe_len):
    if bool(attrs.get("use_sequence_length", False)) and maybe_len:
        slen = maybe_len[0].astype(jnp.int32)
        T = data.shape[0]
        pos = jnp.arange(T)[:, None]
        rev = jnp.where(pos < slen[None, :], slen[None, :] - 1 - pos, pos)
        return jnp.take_along_axis(
            data, rev.reshape(rev.shape + (1,) * (data.ndim - 2)), axis=0)
    return jnp.flip(data, axis=0)


# --- Dropout (reference: nn/dropout-inl.h) ----------------------------------
@register("Dropout", is_random=True)
def _dropout(attrs, key, x):
    p = float(attrs.get("p", 0.5))
    training = bool(attrs.get("_training", False))
    mode = attrs.get("mode", "training")
    if (not training and mode != "always") or p <= 0.0:
        return x
    axes = tuple(attrs.get("axes", ()) or ())
    shape = tuple(1 if i in axes else s for i, s in enumerate(x.shape)) if axes else x.shape
    keep = jax.random.bernoulli(key, 1.0 - p, shape)
    return jnp.where(keep, x / (1.0 - p), jnp.zeros_like(x))


# --- fused RNN (reference: rnn-inl.h RNNOp — cuDNN descr. on GPU) -----------
def _rnn_cell_step(mode, W_ih, W_hh, b_ih, b_hh):
    def lstm(carry, x_t):
        h, c = carry
        gates = x_t @ W_ih.T + h @ W_hh.T + b_ih + b_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c2 = f * c + i * g
        h2 = o * jnp.tanh(c2)
        return (h2, c2), h2

    def gru(carry, x_t):
        (h,) = carry
        gi = x_t @ W_ih.T + b_ih
        gh = h @ W_hh.T + b_hh
        ir, iz, inew = jnp.split(gi, 3, axis=-1)
        hr, hz, hnew = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(ir + hr)
        z = jax.nn.sigmoid(iz + hz)
        n = jnp.tanh(inew + r * hnew)
        h2 = (1 - z) * n + z * h
        return (h2,), h2

    def rnn_tanh(carry, x_t):
        (h,) = carry
        h2 = jnp.tanh(x_t @ W_ih.T + h @ W_hh.T + b_ih + b_hh)
        return (h2,), h2

    def rnn_relu(carry, x_t):
        (h,) = carry
        h2 = jax.nn.relu(x_t @ W_ih.T + h @ W_hh.T + b_ih + b_hh)
        return (h2,), h2

    return {"lstm": lstm, "gru": gru, "rnn_tanh": rnn_tanh,
            "rnn_relu": rnn_relu}[mode]


def _rnn_gate_count(mode):
    return {"lstm": 4, "gru": 3, "rnn_tanh": 1, "rnn_relu": 1}[mode]


def rnn_unpack_params(params, mode, num_layers, input_size, hidden, bidirectional):
    """Slice the flat cuDNN-style parameter vector into per-layer weights.

    Layout matches reference rnn-inl.h (cuDNN canonical): all W_ih,W_hh per
    layer/direction first, then all b_ih,b_hh.
    """
    ng = _rnn_gate_count(mode)
    dirs = 2 if bidirectional else 1
    offset = 0
    weights, biases = [], []
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else hidden * dirs
        for _ in range(dirs):
            wih = params[offset:offset + ng * hidden * in_sz].reshape(ng * hidden, in_sz)
            offset += ng * hidden * in_sz
            whh = params[offset:offset + ng * hidden * hidden].reshape(ng * hidden, hidden)
            offset += ng * hidden * hidden
            weights.append((wih, whh))
    for layer in range(num_layers):
        for _ in range(dirs):
            bih = params[offset:offset + ng * hidden]
            offset += ng * hidden
            bhh = params[offset:offset + ng * hidden]
            offset += ng * hidden
            biases.append((bih, bhh))
    return weights, biases


def rnn_param_size(mode, num_layers, input_size, hidden, bidirectional):
    ng = _rnn_gate_count(mode)
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else hidden * dirs
        size += dirs * (ng * hidden * in_sz + ng * hidden * hidden + 2 * ng * hidden)
    return size


@register("RNN", num_outputs="_dynamic")
def _rnn(attrs, data, params, state, *maybe_state_cell):
    """Fused multi-layer (bi)RNN. data: (T, N, I) [seq-major like cuDNN]."""
    mode = attrs["mode"]
    hidden = int(attrs["state_size"])
    num_layers = int(attrs["num_layers"])
    bidir = bool(attrs.get("bidirectional", False))
    dirs = 2 if bidir else 1
    T, N, I = data.shape
    weights, biases = rnn_unpack_params(params, mode, num_layers, I, hidden, bidir)
    is_lstm = mode == "lstm"
    cell = maybe_state_cell[0] if is_lstm and maybe_state_cell else None

    x = data
    out_h, out_c = [], []
    for layer in range(num_layers):
        layer_outs = []
        for d in range(dirs):
            li = layer * dirs + d
            W_ih, W_hh = weights[li]
            b_ih, b_hh = biases[li]
            step = _rnn_cell_step(mode, W_ih, W_hh, b_ih, b_hh)
            h0 = state[li]
            carry0 = (h0, cell[li]) if is_lstm else (h0,)
            seq = jnp.flip(x, axis=0) if d == 1 else x
            carry, ys = lax.scan(step, carry0, seq)
            if d == 1:
                ys = jnp.flip(ys, axis=0)
            layer_outs.append(ys)
            out_h.append(carry[0])
            if is_lstm:
                out_c.append(carry[1])
        x = jnp.concatenate(layer_outs, axis=-1) if dirs == 2 else layer_outs[0]
        pdrop = float(attrs.get("p", 0.0))
        del pdrop  # inter-layer dropout handled at the gluon layer
    hN = jnp.stack(out_h, axis=0)
    if not bool(attrs.get("state_outputs", False)):
        return x
    if is_lstm:
        return x, hN, jnp.stack(out_c, axis=0)
    return x, hN


# --- SVMOutput (reference: src/operator/svm_output.cc) ----------------------
def _svm_output_grad(attrs, primals, cotangents):
    data, label = primals
    margin = float(attrs.get("margin", 1.0))
    reg = float(attrs.get("regularization_coefficient", 1.0))
    use_linear = bool(attrs.get("use_linear", False))
    out = data  # forward is identity
    k = jax.nn.one_hot(label.reshape(-1).astype(jnp.int32),
                       data.shape[-1], dtype=jnp.bool_)
    if use_linear:
        # L1-SVM (svm_output.cc L1_SVM): hinge subgradient
        g_true = -(margin > out).astype(data.dtype) * reg
        g_other = (margin > -out).astype(data.dtype) * reg
    else:
        # L2-SVM (svm_output.cc L2_SVM): squared hinge
        g_true = jnp.where(margin > out, -2 * reg * (margin - out), 0.0)
        g_other = jnp.where(margin > -out, 2 * reg * (margin + out), 0.0)
    g = jnp.where(k, g_true, g_other).astype(data.dtype)
    ct = cotangents[0]
    return (g * (ct.sum() if ct.ndim == 0 else 1.0), None)


@register("SVMOutput", fgradient=_svm_output_grad)
def _svm_output(attrs, data, label):
    return data


# --- SoftmaxActivation (reference: src/operator/softmax_activation.cc) ------
@register("SoftmaxActivation")
def _softmax_activation(attrs, x):
    mode = attrs.get("mode", "instance")
    if mode == "channel":
        return jax.nn.softmax(x, axis=1)
    flat = x.reshape(x.shape[0], -1)
    return jax.nn.softmax(flat, axis=-1).reshape(x.shape)
