"""Fused softmax + cross-entropy — Pallas TPU kernel #3.

Reference capability anchor: softmax_output-inl.h computes softmax and
the CE loss/gradient as separate passes over HBM. The fused row kernel
keeps each logit row resident in VMEM and emits BOTH the per-row loss
and the softmax probabilities in one pass (one HBM read of the logits),
with the max-subtraction done in f32 regardless of input dtype
(bf16-safe) — the classifier-head bandwidth floor.

Forward runs as a Pallas kernel (interpret mode off-TPU so the suite
exercises the same code path); backward is the analytic
``(softmax - onehot) * ct`` in plain XLA from the saved probs (no 1/N —
the registered op SUMS per-row losses, reference loss_binary_op.cc).
Out-of-range labels (the -1 ignore/padding convention) contribute zero
loss and zero gradient, matching the one_hot semantics of the plain
path. Gated like the LayerNorm kernel: MXNET_FUSED_SOFTMAX_CE=1/true/on
forces on, 0/false/off forces plain XLA, auto (default) probes once on
TPU and falls back on Mosaic rejection.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _smce_kernel(x_ref, lab_ref, loss_ref, prob_ref):
    x = x_ref[:].astype(jnp.float32)              # (B, D)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    s = jnp.sum(e, axis=-1, keepdims=True)
    logp = x - m - jnp.log(s)
    prob = e / s
    lab = lab_ref[:].astype(jnp.int32)            # (B,)
    # invalid labels (e.g. -1 padding) contribute zero, like one_hot
    valid = (lab >= 0) & (lab < x.shape[-1])
    picked = jnp.take_along_axis(
        logp, jnp.clip(lab, 0, x.shape[-1] - 1)[:, None], axis=-1)[:, 0]
    loss_ref[:] = jnp.where(valid, -picked, 0.0)
    prob_ref[:] = prob.astype(prob_ref.dtype)


def _use_interpret():
    return jax.default_backend() != "tpu"


def _pick_block_rows(n):
    for b in (256, 128, 64, 32, 16, 8, 4, 2, 1):
        if n % b == 0:
            return b
    return 1


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _smce_fwd(x2, labels, *, block_rows, interpret):
    n, d = x2.shape
    grid = (n // block_rows,)
    loss, prob = pl.pallas_call(
        _smce_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((block_rows,), lambda i: (i,)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows,), lambda i: (i,)),
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n,), jnp.float32),
            jax.ShapeDtypeStruct((n, d), x2.dtype),
        ],
        interpret=interpret,
    )(x2, labels)
    return loss, prob


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _softmax_ce(logits, labels, block_rows):
    loss, _prob = _smce_core(logits, labels, block_rows)
    return loss


def _resolve_block_rows(n, block_rows):
    # a tuned block size only applies when it tiles THIS n exactly (a
    # shard_map body sees the shard-local row count, not the tuned one)
    if block_rows and n % block_rows == 0:
        return block_rows
    return _pick_block_rows(n)


def _smce_core(logits, labels, block_rows=None):
    return _smce_fwd(logits, labels,
                     block_rows=_resolve_block_rows(logits.shape[0],
                                                    block_rows),
                     interpret=_use_interpret())


def _smce_vjp_fwd(logits, labels, block_rows):
    loss, prob = _smce_core(logits, labels, block_rows)
    return loss, (prob, labels)


def _smce_vjp_bwd(block_rows, res, ct):
    prob, labels = res
    lab = labels.astype(jnp.int32)
    onehot = jax.nn.one_hot(lab, prob.shape[-1], dtype=jnp.float32)
    valid = ((lab >= 0) & (lab < prob.shape[-1])).astype(jnp.float32)
    # invalid (padding) rows get ZERO gradient, matching their zero loss
    d_logits = (prob.astype(jnp.float32) - onehot) \
        * (ct * valid)[:, None]
    return d_logits.astype(prob.dtype), None


_softmax_ce.defvjp(_smce_vjp_fwd, _smce_vjp_bwd)


_GATE_CACHE = {}


def fused_softmax_ce_available(n, d, dtype):
    """Gate identical in spirit to MXNET_FUSED_LAYERNORM: env override,
    else probe this exact tile config once on TPU (Mosaic can reject a
    layout) and remember the answer."""
    flag = os.environ.get("MXNET_FUSED_SOFTMAX_CE", "auto").lower()
    if flag in ("1", "true", "on"):
        return True
    if flag in ("0", "false", "off"):
        return False
    if _use_interpret():
        return True  # interpret mode always works
    key = (_pick_block_rows(n), d, str(dtype))
    hit = _GATE_CACHE.get(key)
    if hit is None:
        try:
            import numpy as _np
            probe = _smce_fwd(jnp.zeros((key[0], d), dtype),
                              jnp.zeros((key[0],), jnp.int32),
                              block_rows=key[0], interpret=False)
            # materialize: execution-time Mosaic failures must be
            # caught HERE, not at the first real call
            _np.asarray(probe[0])
            hit = True
        except Exception as e:  # noqa: BLE001 — Mosaic rejection gates off
            import logging
            logging.getLogger("mxnet_tpu.ops").debug(
                "fused softmax-ce gated off for tile %s (%s: %s); "
                "falling back to plain XLA", key, type(e).__name__, e)
            hit = False
        _GATE_CACHE[key] = hit
    return hit


def softmax_ce_kernel(logits, labels, block_rows=None):
    """The Pallas row kernel with an explicit (tunable) row tile — the
    kernels-registry entry point.  No availability gate: the caller
    (kernels.get / fused_softmax_ce) owns that decision."""
    return _softmax_ce(logits, labels.astype(jnp.int32), block_rows)


def plain_softmax_ce(logits, labels):
    """Pure-XLA per-row softmax CE — the gated-off fallback and, verbatim,
    the kernel registry's reference implementation (one definition so
    ``MXNET_KERNELS=reference`` lowers the same jaxpr as kernels-off)."""
    labels = labels.astype(jnp.int32)
    d = logits.shape[-1]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    valid = (labels >= 0) & (labels < d)
    picked = jnp.take_along_axis(
        logp, jnp.clip(labels, 0, d - 1)[:, None], axis=-1)[:, 0]
    return jnp.where(valid, -picked, 0.0)


def fused_softmax_ce(logits, labels):
    """Per-row softmax cross-entropy loss, differentiable.

    logits: (n, d); labels: (n,) integer class ids. Returns (n,) f32
    losses. Falls back to plain XLA when the kernel is gated off."""
    labels = labels.astype(jnp.int32)
    n, d = logits.shape
    if n == 0:
        return jnp.zeros((0,), jnp.float32)
    if fused_softmax_ce_available(n, d, logits.dtype):
        return _softmax_ce(logits, labels, None)
    return plain_softmax_ce(logits, labels)
