"""Int8 quantization operator family.

Reference: src/operator/quantization/ — quantize{,_v2}.cc, dequantize.cc,
requantize.cc, quantized_conv.cc, quantized_fully_connected.cc,
quantized_pooling.cc, quantized_flatten.cc.  Conventions kept from the
reference: int8 is SYMMETRIC (scale = 127 / max|range|, kInt8Range),
int32 accumulators use kInt32Range = 2^31-1, every quantized tensor
travels with explicit (min, max) float scalars.

TPU redesign: the int8 GEMM/conv is one lax.dot_general /
conv_general_dilated with int8 operands and preferred_element_type=int32
— XLA lowers it onto the MXU's native int8 path (2x bf16 throughput on
v5e-class chips); no cuDNN/MKLDNN kernel zoo needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_INT8_RANGE = 127.0
_INT32_RANGE = float(2 ** 31 - 1)


def _amax(mn, mx):
    return jnp.maximum(jnp.abs(mn), jnp.abs(mx))


def _scalar(x, dtype=jnp.float32):
    return jnp.asarray(x, dtype).reshape(())


@register("_contrib_quantize_v2", alias=("quantize_v2",), num_outputs=3)
def _quantize_v2(attrs, data):
    """f32 -> (int8, min, max); calibrated range from attrs or data."""
    mn = attrs.get("min_calib_range")
    mx = attrs.get("max_calib_range")
    if (mn is None) != (mx is None):
        from ..base import MXNetError
        raise MXNetError(
            "quantize_v2: min_calib_range and max_calib_range must be "
            "given together (one-sided ranges would silently fall back "
            "to per-batch dynamic scales)")
    if mn is None:
        mn = jnp.min(data).astype(jnp.float32)
        mx = jnp.max(data).astype(jnp.float32)
    else:
        mn = _scalar(float(mn))
        mx = _scalar(float(mx))
    amax = jnp.maximum(_amax(mn, mx), 1e-10)
    scale = _INT8_RANGE / amax
    q = jnp.clip(jnp.rint(data.astype(jnp.float32) * scale),
                 -_INT8_RANGE, _INT8_RANGE).astype(jnp.int8)
    return q, -amax, amax


@register("_contrib_quantize", alias=("quantize",), num_outputs=3)
def _quantize(attrs, data, min_range, max_range):
    amax = jnp.maximum(_amax(min_range.reshape(()),
                             max_range.reshape(())), 1e-10)
    scale = _INT8_RANGE / amax
    q = jnp.clip(jnp.rint(data.astype(jnp.float32) * scale),
                 -_INT8_RANGE, _INT8_RANGE).astype(jnp.int8)
    return q, -amax.reshape(()), amax.reshape(())


@register("_contrib_dequantize", alias=("dequantize",))
def _dequantize(attrs, q, min_range, max_range):
    amax = _amax(min_range.reshape(()), max_range.reshape(()))
    qrange = _INT32_RANGE if q.dtype == jnp.int32 else _INT8_RANGE
    return q.astype(jnp.float32) * (amax / qrange)


@register("_contrib_requantize", alias=("requantize",), num_outputs=3)
def _requantize(attrs, q, min_range, max_range):
    """int32 -> int8 against a calibrated output range."""
    mn = attrs.get("min_calib_range")
    mx = attrs.get("max_calib_range")
    real = _dequantize({}, q, min_range, max_range)
    if mn is None or mx is None:
        amax = jnp.maximum(jnp.max(jnp.abs(real)), 1e-10)
    else:
        amax = jnp.maximum(_amax(_scalar(float(mn)), _scalar(float(mx))),
                           1e-10)
    q8 = jnp.clip(jnp.rint(real * (_INT8_RANGE / amax)),
                  -_INT8_RANGE, _INT8_RANGE).astype(jnp.int8)
    return q8, -amax.reshape(()), amax.reshape(())


def _i32_out_range(min_d, max_d, min_w, max_w):
    """Output (min, max) such that dequantize(i32, min, max) recovers the
    float product (quantized_conv.cc output-range convention)."""
    scale_prod = (_INT8_RANGE / jnp.maximum(_amax(min_d, max_d), 1e-10)) * \
        (_INT8_RANGE / jnp.maximum(_amax(min_w, max_w), 1e-10))
    amax_out = _INT32_RANGE / scale_prod
    return -amax_out.reshape(()), amax_out.reshape(())


@register("_contrib_quantized_conv", alias=("quantized_conv",),
          num_outputs=3)
def _quantized_conv(attrs, qdata, qweight, min_d, max_d, min_w, max_w):
    """int8 NCHW conv -> int32 (+ its float range).  Bias handling stays
    f32 outside (the gluon wrapper adds it after dequantize)."""
    from ._op_nn import _conv_dim_numbers, _tupleize
    kernel = tuple(attrs["kernel"])
    ndim = len(kernel)
    stride = _tupleize(attrs.get("stride"), ndim)
    dilate = _tupleize(attrs.get("dilate"), ndim)
    pad = _tupleize(attrs.get("pad"), ndim) if attrs.get("pad") \
        else (0,) * ndim
    groups = int(attrs.get("num_group", 1))
    dn = _conv_dim_numbers(ndim + 2, attrs.get("layout") or
                           ("NCW", "NCHW", "NCDHW")[ndim - 1])
    out = lax.conv_general_dilated(
        qdata.astype(jnp.int8), qweight.astype(jnp.int8),
        window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=jnp.int32)
    mn, mx = _i32_out_range(min_d.reshape(()), max_d.reshape(()),
                            min_w.reshape(()), max_w.reshape(()))
    return out, mn, mx


@register("_contrib_quantized_fully_connected",
          alias=("quantized_fully_connected",), num_outputs=3)
def _quantized_fc(attrs, qdata, qweight, min_d, max_d, min_w, max_w):
    """int8 FC -> int32: y = x @ w.T with int32 accumulation."""
    flatten = bool(attrs.get("flatten", True))
    x = qdata.reshape(qdata.shape[0], -1) if flatten else qdata
    out = lax.dot_general(
        x.astype(jnp.int8), qweight.astype(jnp.int8),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    mn, mx = _i32_out_range(min_d.reshape(()), max_d.reshape(()),
                            min_w.reshape(()), max_w.reshape(()))
    return out, mn, mx


@register("_contrib_quantized_pooling", alias=("quantized_pooling",),
          num_outputs=3)
def _quantized_pooling(attrs, qdata, min_d, max_d):
    """Pooling on int8 values; range passes through unchanged."""
    from .registry import get as _get
    pool = _get("Pooling").fcompute
    ptype = attrs.get("pool_type", "max")
    if ptype == "max":
        out = pool(dict(attrs), qdata.astype(jnp.int32)).astype(jnp.int8)
    else:
        # avg pool rounds back to int8 (reference quantized_pooling.cc)
        out = jnp.rint(pool(dict(attrs), qdata.astype(jnp.float32))
                       ).astype(jnp.int8)
    return out, min_d.reshape(()), max_d.reshape(())


@register("_contrib_quantized_flatten", alias=("quantized_flatten",),
          num_outputs=3)
def _quantized_flatten(attrs, qdata, min_d, max_d):
    return (qdata.reshape(qdata.shape[0], -1),
            min_d.reshape(()), max_d.reshape(()))


@register("_contrib_quantized_act", alias=("quantized_act",), num_outputs=3)
def _quantized_act(attrs, qdata, min_d, max_d):
    """int8 activation (reference: quantization/quantized_activation.cc —
    relu only, as there). Ranges pass through; negative values are
    clamped in the int8 domain directly."""
    act = attrs.get("act_type", "relu")
    if act != "relu":
        from ..base import MXNetError
        raise MXNetError(f"quantized_act supports relu only, got {act}")
    return (jnp.maximum(qdata, 0).astype(qdata.dtype),
            min_d.reshape(()), max_d.reshape(()))


@register("_contrib_quantized_concat", alias=("quantized_concat",),
          num_outputs=3)
def _quantized_concat(attrs, *args):
    """Concat int8 inputs quantized with different scales (reference:
    quantization/mkldnn/mkldnn_quantized_concat.cc): pick the widest
    range, rescale every input onto it, concat. Inputs are laid out as
    [d0..dn-1, min0, max0, min1, max1, ...]."""
    n = (len(args)) // 3
    datas, ranges = args[:n], args[n:]
    amaxes = [jnp.maximum(_amax(ranges[2 * i].reshape(()),
                                ranges[2 * i + 1].reshape(())), 1e-10)
              for i in range(n)]
    out_amax = amaxes[0]
    for a in amaxes[1:]:
        out_amax = jnp.maximum(out_amax, a)
    dim = int(attrs.get("dim", 1))
    parts = [jnp.clip(jnp.rint(d.astype(jnp.float32) * (a / out_amax)),
                      -127, 127).astype(jnp.int8)
             for d, a in zip(datas, amaxes)]
    return jnp.concatenate(parts, axis=dim), -out_amax, out_amax


@register("_contrib_quantized_elemwise_add", alias=("quantized_elemwise_add",),
          num_outputs=3)
def _quantized_elemwise_add(attrs, a, b, min_a, max_a, min_b, max_b):
    """int8 + int8 -> int32 (reference:
    quantization/quantized_elemwise_add.cc): the exact sum is
    representable at int32 with out_range = range_a + range_b."""
    amax_a = jnp.maximum(_amax(min_a.reshape(()), max_a.reshape(())), 1e-10)
    amax_b = jnp.maximum(_amax(min_b.reshape(()), max_b.reshape(())), 1e-10)
    out_amax = amax_a + amax_b
    va = a.astype(jnp.float32) * (amax_a / _INT8_RANGE)
    vb = b.astype(jnp.float32) * (amax_b / _INT8_RANGE)
    out = jnp.rint((va + vb) / out_amax * _INT32_RANGE).astype(jnp.int32)
    return out, -out_amax, out_amax
