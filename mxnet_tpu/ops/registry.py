"""Operator registry.

TPU-native re-design of the NNVM op registry (reference:
include/mxnet/op_attr_types.h:124-294, src/operator/* NNVM_REGISTER_OP). In the
reference each op carries FInferShape/FInferType/FCompute<cpu|gpu>/FGradient
attributes; kernels are hand-written CUDA/mshadow. Here an op's ``fcompute`` is
a JAX emission (jax.numpy / lax / pallas):

- shape+dtype inference = ``jax.eval_shape`` over fcompute (always consistent
  with the kernel, unlike hand-written FInferShape);
- gradient = ``jax.vjp`` over fcompute (an op can override with a custom
  fgradient for numerically-better or cheaper rules);
- CPU/GPU/TPU dispatch = XLA backends — one registration covers all devices
  (the reference needs .cc + .cu per op);
- per-op kernel fusion/scheduling = XLA; the imperative path jit-caches each
  (op, attrs) pair so steady-state dispatch is a cache hit.

Op attrs are plain keyword arguments, normalised to a hashable canonical tuple
(the role dmlc::Parameter plays in the reference).
"""
from __future__ import annotations

import functools

import jax
import numpy as np

from ..base import MXNetError

_OPS = {}


def _amp_cast(arrays, mode):
    """Input casting for mixed precision, applied INSIDE the op's traced
    function so jax.vjp transposes the casts (low-precision compute, full-
    precision gradient accumulation).  The '_amp' attr rides the jit-cache
    key, so amp-on and amp-off programs never collide.

    Role parity: src/nnvm/low_precision_pass.cc inserts amp_cast/
    amp_multicast nodes by allow/deny list; here the cast is attached at
    dispatch by mxnet_tpu.amp.
    """
    import jax.numpy as jnp
    low = jnp.bfloat16 if mode.endswith("bfloat16") else jnp.float16
    out = []
    for a in arrays:
        dt = getattr(a, "dtype", None)
        if dt is None or not jnp.issubdtype(a.dtype, jnp.floating):
            out.append(a)
        elif mode.startswith("low"):
            out.append(a.astype(low) if a.dtype == jnp.float32 else a)
        elif mode.startswith("f32"):
            out.append(a.astype(jnp.float32)
                       if a.dtype in (jnp.bfloat16, jnp.float16) else a)
        else:  # widest
            out.append(a)
    if mode.startswith("widest"):
        f = [a for a in out if getattr(a, "dtype", None) is not None and
             jnp.issubdtype(a.dtype, jnp.floating)]
        if f:
            widest = jnp.result_type(*[a.dtype for a in f])
            out = [a.astype(widest)
                   if getattr(a, "dtype", None) is not None and
                   jnp.issubdtype(a.dtype, jnp.floating) else a
                   for a in out]
    return tuple(out)


def _canon_attr(v):
    """Make an attr value hashable + jit-stable."""
    if isinstance(v, (list, tuple)):
        return tuple(_canon_attr(x) for x in v)
    if isinstance(v, np.ndarray):
        return tuple(v.ravel().tolist()) + ("__shape__",) + v.shape
    if isinstance(v, np.dtype):
        return v.name
    if isinstance(v, type) and issubclass(v, np.generic):
        return np.dtype(v).name
    return v


class Operator:
    """A registered operator.

    fcompute(attrs: dict, *inputs: jax.Array) -> jax.Array | tuple[jax.Array]
    """

    def __init__(self, name, fcompute, num_outputs=1, is_random=False,
                 mutate_aux=(), fgradient=None, alias=(), scalar_args=("scalar",),
                 num_visible=None, input_names=None, eager_only=False):
        self.name = name
        self.fcompute = fcompute
        self.num_outputs = num_outputs
        # eager_only: op produces data-dependent (dynamic) shapes — legal in
        # eager jax, illegal under jit/trace. The imperative path runs it
        # unjitted; traced paths (CachedOp/executor) reject it with a clear
        # error. Parity: the reference's dynamic-shape FComputeEx ops
        # (contrib.boolean_mask, np_nonzero-class).
        self.eager_only = eager_only
        # outputs beyond num_visible are internal (parity: the reference's
        # FNumVisibleOutputs, e.g. box_nms hides its index record)
        self.num_visible = num_visible
        self.is_random = is_random
        self.mutate_aux = mutate_aux  # indices of inputs that receive updated state
        self.fgradient = fgradient
        self.alias = alias
        # names assigned, in order, to positional non-array args in the
        # generated imperative wrapper (e.g. nd.clip(x, 0, 1))
        self.scalar_args = scalar_args
        # declared input roles (FListInputNames parity). The symbol layer
        # auto-creates `{instance}_{suffix}` variables for trailing inputs
        # the user did not supply — reference behavior, e.g.
        # sym.FullyConnected(data, num_hidden=k) synthesizes fc_weight/
        # fc_bias. Tuple, or callable(attrs) -> tuple (no_bias handling).
        self.input_names = input_names
        self._jit_cache = {}

    def resolve_input_names(self, attrs):
        n = self.input_names
        if n is None:
            return None
        return tuple(n(attrs)) if callable(n) else tuple(n)

    # -- dynamic arity (multi-tensor ops: num_weights-driven) --------------
    def resolve_num_outputs(self, attrs):
        """Output count for given attrs. num_outputs may be an int, the
        name of an attr holding the count (e.g. split's "num_outputs"), or
        a callable(attrs) -> int (multi_sgd_*: 2*num_weights)."""
        n = self.num_outputs
        if isinstance(n, str):
            return int(attrs.get(n, 1))
        if callable(n):
            return int(n(attrs))
        return int(n)

    def resolve_mutate_aux(self, attrs):
        """Mutated-state input indices for given attrs; tuple or
        callable(attrs) -> tuple (multi_sgd_mom: one momentum per weight)."""
        ma = self.mutate_aux
        return tuple(ma(attrs)) if callable(ma) else tuple(ma)

    # -- compiled execution ------------------------------------------------
    def jitted(self, attrs_key, attrs):
        fn = self._jit_cache.get(attrs_key)
        if fn is None:
            fcompute = self.fcompute
            amp_mode = attrs.get("_amp")

            def call(*arrays):
                if amp_mode:
                    arrays = _amp_cast(arrays, amp_mode)
                out = fcompute(
                    {k: v for k, v in attrs.items() if k != "_amp"},
                    *arrays)
                return out

            fn = jax.jit(call)
            self._jit_cache[attrs_key] = fn
        return fn

    def bind(self, **attrs):
        """Return (jitted_fn, attrs_key) for the given attrs."""
        key = tuple(sorted((k, _canon_attr(v)) for k, v in attrs.items()))
        return self.jitted(key, attrs), key

    def raw(self, attrs):
        """Unjitted closure — used under jax.vjp (jax 0.9 cannot linearize
        some primitives, e.g. reduce_window, through an inner jit)."""
        fcompute = self.fcompute
        amp_mode = attrs.get("_amp")

        def call(*arrays):
            if amp_mode:
                arrays = _amp_cast(arrays, amp_mode)
            return fcompute(
                {k: v for k, v in attrs.items() if k != "_amp"}, *arrays)

        return call

    def grad_aware(self, attrs):
        """Compute closure that honors a registered custom ``fgradient``
        under jax transforms (jax.custom_vjp wrapper).

        The imperative tape applies fgradient itself (ndarray.py); every
        TRACED path — symbol executor, group2ctx runner, fused subgraph
        bodies — must use this so whole-graph jax.vjp picks up the custom
        rule instead of differentiating fcompute literally (e.g.
        SoftmaxOutput's forward is plain softmax; its training gradient
        is softmax - one_hot(label), reference softmax_output-inl.h).
        Wrappers are cached per canonical attrs key (this sits on the
        per-node hot loop of every executor forward)."""
        if self.fgradient is None:
            return self.raw(attrs)
        cache = getattr(self, "_grad_aware_cache", None)
        if cache is None:
            cache = self._grad_aware_cache = {}
        key = tuple(sorted((k, _canon_attr(v)) for k, v in attrs.items()))
        f = cache.get(key)
        if f is not None:
            return f
        base = self.raw(attrs)
        fg = self.fgradient
        clean = {k: v for k, v in attrs.items() if k != "_amp"}

        @jax.custom_vjp
        def f(*arrays):
            return base(*arrays)

        def fwd(*arrays):
            return f(*arrays), arrays

        def bwd(primals, cts):
            cts_t = tuple(cts) if isinstance(cts, (tuple, list)) else (cts,)
            gs = fg(clean, primals, cts_t)
            import jax.numpy as jnp
            out = []
            for g, p in zip(gs, primals):
                if g is None:
                    g = jnp.zeros_like(p)
                elif hasattr(g, "dense"):
                    # SparseCot (row-sparse tape gradient, e.g. Embedding
                    # sparse_grad): custom_vjp needs dense jax cotangents;
                    # the traced-graph path has no sparse gradient storage
                    g = g.dense()
                out.append(g)
            return tuple(out)

        f.defvjp(fwd, bwd)
        cache[key] = f
        return f

    def infer(self, attrs, *avals):
        """Shape/dtype inference via abstract evaluation."""
        fn, _ = self.bind(**attrs)
        return jax.eval_shape(fn, *avals)

    def __repr__(self):
        return f"Operator({self.name})"


def register(name, num_outputs=1, is_random=False, mutate_aux=(),
             fgradient=None, alias=(), scalar_args=("scalar",),
             num_visible=None, input_names=None, eager_only=False):
    """Decorator: register fcompute under ``name`` (+ aliases)."""

    def deco(fcompute):
        op = Operator(name, fcompute, num_outputs=num_outputs,
                      is_random=is_random, mutate_aux=mutate_aux,
                      fgradient=fgradient, alias=alias, scalar_args=scalar_args,
                      num_visible=num_visible, input_names=input_names,
                      eager_only=eager_only)
        if name in _OPS:
            raise MXNetError(f"op {name} already registered")
        _OPS[name] = op
        for a in alias:
            _OPS[a] = op
        return fcompute

    return deco


def register_simple(name, fn, **kw):
    """Register an op whose fcompute ignores attrs: fn(*inputs)."""
    register(name, **kw)(lambda attrs, *ins: fn(*ins))


def get(name):
    op = _OPS.get(name)
    if op is None:
        raise MXNetError(f"operator {name} is not registered")
    return op


def exists(name):
    return name in _OPS


def list_ops():
    return sorted(_OPS)
