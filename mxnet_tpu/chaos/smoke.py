"""Chaos smoke for CI: replay the composed fault scenarios.

Asserted per scenario (the ISSUE 8 acceptance contract):

1. worker kill/revive — the chaos ``kill`` arm SIGKILLed the worker at
   its Nth RPC, the revived worker's bounded retry healed two injected
   transient faults, and training committed steps PAST the kill.
2. corrupt checkpoint under serving load — zero non-shed request
   failures, the corrupt step quarantined with the alarm counter
   raised, the old version served throughout, the next good step
   hot-reloaded.
3. wedged batcher — the watchdog fired naming the wedged frame,
   /healthz went 503 (naming the section) and back to 200, the wedged
   batch resolved as typed timeouts, p99 of served requests stayed
   bounded.
4. SIGKILL mid-scan-window — restore from the last boundary checkpoint
   continued BIT-identically to an uninterrupted run.
5. mesh collective stall + kill-resize (ISSUE 9) — the wedged
   ``parallel/collective`` boundary fired the watchdog naming the
   stalled mesh step and the fit self-healed; the SIGKILLed dp=4 mesh
   fit restored onto a RESIZED dp=2 mesh and continued BIT-identically
   to a planned resize.
6. replica kill mid-burst (ISSUE 10) — injected router dispatch faults
   spilled to sibling replicas, the replica removed under load drained
   everything it admitted, the survivors kept serving, and zero
   non-shed requests were dropped or hung.
7. replica kill mid-generation (ISSUE 16) — an injected
   ``serving/generation/decode`` fault killed one of two generation
   engines past its restart budget mid-stream: victim sessions failed
   typed-retryable and resumed on the sibling, survivors streamed on,
   and the KV slot pools + resource-ledger pages ended provably zero
   (no leaked slots, no leaked pages, no hangs).
8. multi-host peer loss mid-window (ISSUE 11) — host 1 of a 2-process
   jax.distributed mesh SIGKILLed at window 3: the survivor took a
   TYPED exit from the deadline-bounded rendezvous (zero hangs, zero
   untyped failures), the boundary checkpoint committed, the elastic
   launcher respawned the dp/2 survivor world, and the continued fit
   was BITWISE identical to a planned resize.

Plus the standing invariants: no scenario hangs (every wait here is
bounded) and the disabled-failpoint overhead stays under the 1 us bar.

Run: JAX_PLATFORMS=cpu python -m mxnet_tpu.chaos.smoke
"""
from __future__ import annotations

import shutil
import tempfile
import time


def _assert_disabled_overhead():
    from .failpoints import failpoint
    n = 100000
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            failpoint("smoke/disabled")
        best = min(best, (time.perf_counter() - t0) / n)
    assert best < 1e-6, f"disabled failpoint costs {best * 1e9:.0f} ns"
    return best


def main():
    from . import harness, reset
    reset()
    overhead_ns = _assert_disabled_overhead() * 1e9
    print(f"chaos smoke: disabled failpoint {overhead_ns:.0f} ns "
          "(< 1000 ns budget)", flush=True)

    base = tempfile.mkdtemp(prefix="chaos-smoke-")
    try:
        results = harness.run_all(base)
    finally:
        shutil.rmtree(base, ignore_errors=True)

    failed = {n: r for n, r in results.items() if not r.get("ok")}
    for name, r in results.items():
        print(f"  {name}: {'OK' if r.get('ok') else 'FAIL'} — "
              f"{ {k: v for k, v in r.items() if k != 'ok'} }",
              flush=True)
    assert not failed, f"chaos scenarios failed: {sorted(failed)}"
    print("chaos smoke OK: worker kill/revive committed past the kill, "
          "corrupt reload served the old version with zero non-shed "
          "failures, wedged batcher stayed bounded under a named "
          "watchdog stall, the replica killed mid-burst drained with "
          "zero non-shed drops while siblings absorbed the load, "
          "mid-window SIGKILL resumed bit-identically, "
          "the stalled mesh step self-healed + resumed "
          "bit-identically onto a resized mesh, and the multi-host "
          "peer loss recovered typed onto the dp/2 survivor world "
          "bit-identically to a planned resize")


if __name__ == "__main__":
    main()
