"""mxnet_tpu.chaos — failpoint injection and composed fault scenarios.

The robustness harness (ISSUE 8): :mod:`failpoints` plants named,
deterministic injection sites across the checkpoint writer, serving
stack, compile cache, kvstore transport and io staging;
:mod:`harness` composes them into the end-to-end outage scenarios
CI replays (``python -m mxnet_tpu.chaos.smoke``); :mod:`soak`
(ISSUE 13) applies the same ratchet to wall-clock time — a
bounded-minutes train + checkpoint + hot-reload + traffic loop under
a seeded benign fault mix, gated by the in-process alert engine
(``python -m mxnet_tpu.chaos.soak``).  Every weakness a scenario
exposes becomes a permanent fix + a graftlint rule or an alert rule —
the same ratchet loop graftlint (ISSUE 3) runs for static invariants,
applied to dynamic ones.

Usage::

    import mxnet_tpu.chaos as chaos
    chaos.arm("serving/batcher/worker", "raise", count=1)
    ...                       # the next worker pass dies and restarts
    chaos.reset()

or from the environment (child processes, CI)::

    MXNET_CHAOS="checkpoint/writer/pre_rename=kill" python train.py

See docs/chaos.md for the failpoint catalog, the spec grammar, the
scenario runbook, and how a found failure becomes a lint rule/alarm.
"""
from __future__ import annotations

from .failpoints import (ACTIONS, SITES, ChaosInjectedError,
                         ChaosSpecError, active, arm, arms, configure,
                         configure_from_env, disarm, failpoint,
                         failpoint_bytes, fatal_site, hit_counts, release,
                         reset, sites)

__all__ = [
    "ACTIONS", "SITES", "ChaosInjectedError", "ChaosSpecError", "active",
    "arm", "arms", "configure", "configure_from_env", "disarm",
    "failpoint", "failpoint_bytes", "fatal_site", "hit_counts", "release",
    "reset", "sites",
]

# arm from MXNET_CHAOS at import: sites call failpoint() through this
# package, so the first instrumented subsystem to load activates any
# environment-specified fault schedule (zero effect when unset)
configure_from_env()
