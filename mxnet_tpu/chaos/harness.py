"""Composed chaos scenarios — the outages unit tests cannot see.

Each scenario function is self-contained, deterministic (failpoints are
hit-count triggered, subprocess fault schedules ride in ``MXNET_CHAOS``
env specs), and returns a plain result dict; ``tests/test_chaos.py``
asserts on the dicts and ``python -m mxnet_tpu.chaos.smoke`` replays
them in CI.  The four scenarios compose faults that PRs 1-7 only ever
tested alone:

1. **worker kill/revive** — a dist kvstore worker SIGKILLs itself
   mid-epoch (chaos ``kill`` at the Nth client RPC); a replacement
   attaches, restores the rank-0 checkpoint, heals two injected
   transient RPC faults through the bounded retry, and training commits
   steps past the kill.
2. **corrupt checkpoint under serving load** — a corrupt step commits
   into a watched checkpoint directory while clients hammer the server;
   the poller quarantines it (alarm counter), the old version keeps
   serving with zero non-shed failures, and the next good step hot-
   reloads normally.
3. **wedged batcher worker** — one of two workers wedges; the watchdog
   fires naming the wedged section, ``/healthz`` flips to 503 (and back
   after release), the in-flight sweep resolves the wedged batch as
   typed timeouts, and the surviving worker keeps p99 bounded.
4. **SIGKILL mid-scan-window** — a K-step scanned fit dies between
   window boundaries; restore continues from the last boundary
   checkpoint bit-identically to an uninterrupted run.
5. **mesh collective stall + kill-resize** — the mesh fused step's
   ``parallel/collective`` boundary wedges (watchdog names the stalled
   mesh step, the fit self-heals through the wedge timeout), then a
   dp=4 mesh fit SIGKILLs mid-run and a boundary-checkpoint restore
   onto a RESIZED dp=2 mesh continues bit-identically to a planned
   resize (elastic restore as the resize mechanism).
6. **replica kill mid-burst** (ISSUE 10) — injected
   ``serving/router/dispatch`` faults spill to sibling replicas, then
   one replica of the pool is removed under load: it drains everything
   it admitted, the survivors absorb the traffic, and zero non-shed
   requests are dropped or hung.
7. **replica kill mid-generation** (ISSUE 16) — an injected
   ``serving/generation/decode`` fault kills one of two generation
   engines past its restart budget mid-stream: every victim session
   fails typed-retryable (never hangs) and resumes on the sibling from
   ``prompt + tokens-so-far``, survivor sessions stream untouched, and
   both engines' KV slots and ledger pages are provably released
   (zero-leak asserted).
8. **reader death mid-epoch** (ISSUE 19) — one reader worker of the
   streaming data plane dies at the Nth ``io/reader/read``: the
   pipeline rebalances its shards onto the survivors, the epoch
   completes with every sample delivered exactly once in the seeded
   shard order, zero stalls; a slow reader (delay arm) is absorbed the
   same way; killing ALL readers raises a typed ``DataReaderError`` —
   never a hang.

Every scenario ends in recovery or a typed error — the assertions
include "no hang" (bounded waits everywhere) and "no silent loss"
(every request/save is accounted for).  docs/chaos.md is the runbook.
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

from . import failpoints as chaos

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _child_env(**extra):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # children must not dial the TPU
    env.pop("MXNET_CHAOS", None)           # each child gets its own spec
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


# ---------------------------------------------------------------------------
# scenario 1: kvstore worker kill/revive mid-epoch
# ---------------------------------------------------------------------------
_KV_WORKER = """
import os, sys, time
import numpy as np
import mxnet_tpu as mx
import mxnet_tpu.chaos  # arms MXNET_CHAOS from this child's environment
from mxnet_tpu import kvstore as kvs
from mxnet_tpu import nd
from mxnet_tpu.checkpoint import CheckpointManager, restore

rank = int(os.environ["DMLC_RANK"])
steps = int(sys.argv[1])
ckdir = sys.argv[2]
out = sys.argv[3]
resume = int(sys.argv[4])
target = np.array([0.5, -1.25, 2.0, 0.125], np.float32)

kv = kvs.create("dist_async")
start = 0
if resume:
    kv.attach("w", nd.zeros((4,)))
    ck = restore(ckdir)
    start = ck.step
    blob = ck.blobs.get("optimizer_states")
    if blob is not None:
        kv.set_optimizer_states(blob)
else:
    kv.init("w", nd.zeros((4,)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.05))

mgr = CheckpointManager(ckdir, keep_last=3) if rank == 0 else None
w = nd.zeros((4,))
for step in range(start, steps):
    kv.pull("w", out=w)
    grad = 2.0 * (w.asnumpy() - target)
    kv.push("w", nd.array(grad))
    if rank == 0:
        blobs = {"optimizer_states": kv.get_optimizer_states()}
        mgr.save(step + 1, arrays={"w": w}, blobs=blobs, block=True)
    time.sleep(0.02)
kv.pull("w", out=w)
np.save(out, w.asnumpy())
if mgr is not None:
    mgr.close()
"""


def scenario_worker_kill_revive(workdir, port=19733, steps=30,
                                timeout=180.0):
    """Kill a kvstore worker mid-epoch via a chaos ``kill`` arm at its
    Nth client RPC; revive it with an elastic attach + checkpoint
    restore (its retry path additionally heals two injected transient
    RPC faults); assert training commits steps PAST the kill."""
    import numpy as np

    from ..checkpoint import latest_step
    from ..kvstore_server import KVServer

    workdir = str(workdir)
    os.makedirs(workdir, exist_ok=True)
    script = os.path.join(workdir, "kv_worker.py")
    with open(script, "w") as f:  # graftlint: disable=torn-write -- ephemeral scenario script, single consumer
        f.write(_KV_WORKER)
    ckdir = os.path.join(workdir, "ckpt")
    outs = [os.path.join(workdir, f"w{r}.npy") for r in range(2)]

    server = KVServer(port=port, num_workers=2)
    threading.Thread(target=server.run, daemon=True).start()
    time.sleep(0.2)

    def spawn(rank, resume, chaos_spec=""):
        env = _child_env(
            DMLC_RANK=rank, DMLC_NUM_WORKER=2,
            DMLC_PS_ROOT_URI="127.0.0.1", DMLC_PS_ROOT_PORT=port,
            MXNET_KVSTORE_HEARTBEAT_INTERVAL="0.2",
            MXNET_KVSTORE_RETRY_BACKOFF_S="0.02")
        if chaos_spec:
            env["MXNET_CHAOS"] = chaos_spec
        return subprocess.Popen(
            [sys.executable, script, str(steps), ckdir, outs[rank],
             str(int(resume))], env=env)

    result = {"ok": False}
    deadline = time.time() + timeout
    # rank 1 SIGKILLs itself deterministically at its 25th client RPC
    # (mid-epoch: each train step is at least 2 RPCs)
    procs = [spawn(0, False),
             spawn(1, False, chaos_spec="kvstore/client/rpc=kill:hits=25")]
    try:
        procs[1].wait(timeout=max(10.0, timeout / 2))
        result["victim_exit"] = procs[1].returncode
        kill_step = None
        while kill_step is None and time.time() < deadline:
            kill_step = latest_step(ckdir)
            time.sleep(0.1)
        result["kill_step"] = kill_step
        # revive: elastic attach + restore, WITH two transient RPC
        # faults injected — the bounded retry must absorb them
        procs[1] = spawn(
            1, True,
            chaos_spec="kvstore/client/rpc=raise(ConnectionError)"
                       ":hits=10:count=2")
        for p in procs:
            p.wait(timeout=max(1.0, deadline - time.time()))
        result["exit_codes"] = [p.returncode for p in procs]
        final_step = latest_step(ckdir)
        result["final_step"] = final_step
        finals = [np.load(o) for o in outs if os.path.exists(o)]
        target = np.array([0.5, -1.25, 2.0, 0.125], np.float32)
        result["n_finished"] = len(finals)
        result["converged"] = bool(
            len(finals) == 2
            and all(np.allclose(f, target, atol=0.05) for f in finals))
        result["ok"] = bool(
            result["victim_exit"] == -9          # the kill arm fired
            and result["exit_codes"] == [0, 0]   # both survivors finished
            and final_step == steps              # committed past the kill
            and kill_step is not None and final_step > kill_step
            and result["converged"])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server._stop.set()
    return result


# ---------------------------------------------------------------------------
# scenario 2: corrupt checkpoint during a serving hot-reload under load
# ---------------------------------------------------------------------------
def _tiny_model(seed=0, scale=0.05, in_dim=16, width=32, classes=10):
    import numpy as np

    import mxnet_tpu as mx
    h = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(h, num_hidden=width, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    sym = mx.sym.FullyConnected(h, num_hidden=classes, name="out")
    rng = np.random.RandomState(seed)
    params = {
        "fc1_weight": mx.nd.array(
            rng.randn(width, in_dim).astype(np.float32) * scale),
        "fc1_bias": mx.nd.zeros((width,)),
        "out_weight": mx.nd.array(
            rng.randn(classes, width).astype(np.float32) * scale),
        "out_bias": mx.nd.zeros((classes,)),
    }
    return sym, params


def scenario_corrupt_reload_under_load(workdir, seconds=2.5,
                                       n_clients=4):
    """Commit a CORRUPT checkpoint step into a watched directory while
    clients hammer the server: the poller must quarantine it (alarm
    counter), keep serving the old version with zero non-shed request
    failures, and pick up the next GOOD step normally."""
    import numpy as np

    import mxnet_tpu as mx
    from .. import serving, telemetry
    from ..checkpoint import CheckpointManager
    from ..checkpoint.core import MANIFEST, step_dir
    from ..serving.batcher import ServingOverloadError
    from ..telemetry import watchdog as wd

    workdir = str(workdir)
    ckdir = os.path.join(workdir, "ckpt")
    # the watchdog runs ARMED through this scenario and must stay
    # silent: a corrupt reload degrades, it never stalls the stack
    os.environ["MXNET_WATCHDOG_S"] = "5.0"
    fires0 = wd.fires()
    sym, params = _tiny_model()
    mgr = CheckpointManager(ckdir, async_save=False, keep_last=0)
    mgr.save(1, arrays=params, symbol=sym, block=True)

    alarm = telemetry.REGISTRY.counter("mxnet_serving_corrupt_ckpt_total")
    alarm0 = alarm.value(labels={"model": "m"})

    server = serving.ModelServer(max_batch_size=8, name="chaos-reload")
    result = {"ok": False, "non_shed_failures": [], "shed": 0,
              "served": 0}
    lock = threading.Lock()
    stop = threading.Event()
    try:
        server.repository.watch("m", ckdir, interval=0.05)
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                server.repository.get("m")
                break
            except mx.base.MXNetError:
                time.sleep(0.05)
        x = np.ones((16,), np.float32)

        def client():
            while not stop.is_set():
                try:
                    server.predict("m", {"data": x}, wait_s=30.0)
                    with lock:
                        result["served"] += 1
                except ServingOverloadError:
                    with lock:
                        result["shed"] += 1
                except Exception as e:  # noqa: BLE001 — gate-fatal bucket
                    with lock:
                        result["non_shed_failures"].append(
                            f"{type(e).__name__}: {e}")
                # graftlint: disable=naked-retry -- paced load generator; lifetime is bounded by the stop event the scenario always sets
                time.sleep(0.002)

        clients = [threading.Thread(target=client)
                   for _ in range(n_clients)]
        for t in clients:
            t.start()
        time.sleep(seconds / 3)

        # craft a COMMITTED-but-corrupt step 2: clone step 1, flip bytes
        # in the data file, fix the manifest step, commit atomically (the
        # watcher can never see a half-built dir)
        src = step_dir(ckdir, 1)
        build = step_dir(ckdir, 2) + ".build"
        shutil.copytree(src, build)
        with open(os.path.join(build, MANIFEST)) as f:
            manifest = json.load(f)
        manifest["step"] = 2
        data_name = next(iter(manifest["files"]))
        with open(os.path.join(build, data_name), "r+b") as f:
            f.seek(10)
            f.write(b"\xff\xff\xff\xff")  # checksum now lies
        with open(os.path.join(build, MANIFEST), "w") as f:
            json.dump(manifest, f)
        os.rename(build, step_dir(ckdir, 2))
        result["corrupt_committed_at"] = 2

        time.sleep(seconds / 3)  # several polls hit the corrupt step
        with lock:
            result["version_during_corruption"] = \
                server.repository.latest_version("m")

        # the next GOOD step must still hot-reload (fresh param values
        # so the swap is observable)
        _sym, params3 = _tiny_model(seed=7, scale=0.07)
        mgr.save(3, arrays=params3, symbol=sym, block=True)
        deadline = time.time() + 15
        while server.repository.latest_version("m") < 3 and \
                time.time() < deadline:
            time.sleep(0.05)
        time.sleep(seconds / 3)
        stop.set()
        for t in clients:
            t.join(timeout=30)
        result["final_version"] = server.repository.latest_version("m")
        result["quarantined"] = server.repository.corrupt_steps(
            "m", ckdir)
        result["alarm_count"] = alarm.value(labels={"model": "m"}) - alarm0
        result["watchdog_silent"] = wd.fires() == fires0
        result["ok"] = bool(
            not result["non_shed_failures"]
            and result["served"] > 0
            and result["version_during_corruption"] == 1
            and result["final_version"] == 3
            and result["quarantined"] == [2]
            and result["alarm_count"] >= 1
            and result["watchdog_silent"])
    finally:
        stop.set()
        server.repository.stop_watches()
        server.shutdown()
        mgr.close()
        os.environ.pop("MXNET_WATCHDOG_S", None)
    return result


# ---------------------------------------------------------------------------
# scenario 3: wedged batcher worker — watchdog + shedding + liveness
# ---------------------------------------------------------------------------
def scenario_wedged_batcher(seconds=2.0, watchdog_s=0.4, n_clients=6):
    """Wedge one of two batcher workers; assert the watchdog fires
    naming the wedged section, /healthz flips 503 -> 200 around the
    stall, the wedged batch resolves as typed timeouts (nothing lost),
    and the surviving worker + shedding keep p99 bounded."""
    import numpy as np

    from .. import telemetry
    from ..serving.batcher import (DynamicBatcher, RequestTimeoutError,
                                   ServingOverloadError)
    from ..telemetry import watchdog as wd
    from ..telemetry.exporter import start_exporter, stop_exporter

    os.environ["MXNET_WATCHDOG_S"] = str(watchdog_s)
    dump_dir = tempfile.mkdtemp(prefix="mx-chaos-wd-")
    os.environ["MXNET_WATCHDOG_DIR"] = dump_dir
    fires0 = wd.fires()
    chaos.reset()
    chaos.arm("serving/batcher/worker", "wedge", hits=1, count=1)

    def runner(feed, n_real):
        time.sleep(0.002)
        return [feed["x"] * 2.0]

    def healthz(port):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=5) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    result = {"ok": False, "non_typed_failures": [], "shed": 0,
              "timeouts": 0, "served": 0}
    lat_ms = []
    lock = threading.Lock()
    stop_t = time.perf_counter() + seconds
    port = start_exporter(0)
    b = DynamicBatcher(runner, max_batch_size=8, max_latency_ms=2.0,
                       num_workers=2, max_queue_depth=64,
                       shed_watermark=16, name="chaos-wedge")
    try:
        def client():
            x = np.ones((8,), np.float32)
            while time.perf_counter() < stop_t:
                t0 = time.perf_counter()
                try:
                    b.submit({"x": x}, timeout_ms=400.0).result(10.0)
                    with lock:
                        lat_ms.append((time.perf_counter() - t0) * 1e3)
                        result["served"] += 1
                except ServingOverloadError:
                    with lock:
                        result["shed"] += 1
                    time.sleep(0.001)
                except RequestTimeoutError:
                    with lock:
                        result["timeouts"] += 1
                except Exception as e:  # noqa: BLE001 — gate-fatal bucket
                    with lock:
                        result["non_typed_failures"].append(
                            f"{type(e).__name__}: {e}")

        clients = [threading.Thread(target=client)
                   for _ in range(n_clients)]
        for t in clients:
            t.start()
        # the watchdog must fire for the wedged section mid-load
        deadline = time.time() + max(10.0, 6 * watchdog_s)
        while wd.fires() <= fires0 and time.time() < deadline:
            time.sleep(0.05)
        result["watchdog_fired"] = wd.fires() > fires0
        result["stalled_sections"] = wd.stalled_sections()
        code, body = healthz(port)
        result["healthz_during_stall"] = (code, body.strip())
        dump = wd.last_dump()
        dump_text = ""
        if dump and os.path.exists(dump):
            with open(dump) as f:
                dump_text = f.read()
        result["dump_names_wedge"] = bool(
            "serving/chaos-wedge" in dump_text
            and "failpoints" in dump_text)
        for t in clients:
            t.join(timeout=30)
        # release the wedge: the worker resumes, progress beats end the
        # stall episode, liveness returns to 200
        chaos.release("serving/batcher/worker")
        x = np.ones((8,), np.float32)
        b.submit({"x": x}).result(10.0)
        deadline = time.time() + 10
        while wd.stalled_sections() and time.time() < deadline:
            b.submit({"x": x}).result(10.0)
            time.sleep(0.05)
        code2, body2 = healthz(port)
        result["healthz_after_release"] = (code2, body2.strip())
        lat_ms.sort()
        result["p99_ms"] = _percentile(lat_ms, 99)
        result["ok"] = bool(
            result["watchdog_fired"]
            and result["dump_names_wedge"]
            and code == 503 and "serving/chaos-wedge" in body
            and code2 == 200
            and not result["non_typed_failures"]
            and result["served"] > 0
            and result["p99_ms"] is not None
            and result["p99_ms"] < 1000.0)
    finally:
        chaos.reset()
        b.close(timeout=5.0)
        stop_exporter()
        os.environ.pop("MXNET_WATCHDOG_S", None)
        os.environ.pop("MXNET_WATCHDOG_DIR", None)
        shutil.rmtree(dump_dir, ignore_errors=True)
    return result


# ---------------------------------------------------------------------------
# scenario: replica killed mid-burst — the router drains it, siblings
# absorb, zero non-shed requests dropped (ISSUE 10)
# ---------------------------------------------------------------------------
def scenario_replica_kill_mid_burst(seconds=2.5, n_replicas=3,
                                    n_clients=8):
    """Chaos over the ReplicaPool router: injected dispatch faults must
    SPILL to siblings (``serving/router/dispatch`` raises, the rescued
    requests still answer), then one replica is killed mid-burst
    (``remove_replica`` = drain + drop, the kill path an autoscaler or
    an operator takes) — its admitted requests all complete, the
    surviving replicas absorb the load, p99 stays bounded, and not one
    non-shed request is dropped or left hanging."""
    import numpy as np

    from .. import telemetry
    from ..serving.batcher import (RequestTimeoutError,
                                   ServingOverloadError)
    from ..serving.metrics import ServingMetrics
    from ..serving.router import ReplicaPool

    def factory(rid):
        def run(feed, n_real):
            time.sleep(0.002)
            return [feed["x"] * 2.0]
        return run

    chaos.reset()
    # 12 injected dispatch faults, probabilistic so siblings rescue
    # (an arm firing on EVERY attempt would fail all K hops of one
    # request — that is the all-replicas-refused path, not spill)
    chaos.arm("serving/router/dispatch", "raise", prob=0.5, count=12)
    spill_counter = telemetry.REGISTRY.counter(
        "mxnet_serving_router_spill_total")
    spills0 = spill_counter.value(labels={"model": "chaos-pool"})

    pool = ReplicaPool(factory, num_replicas=n_replicas,
                       name="chaos-pool", model="chaos-pool",
                       metrics=ServingMetrics("chaos-pool"),
                       max_batch_size=8, max_latency_ms=2.0,
                       num_workers=1, max_queue_depth=64,
                       shed_watermark=32)
    result = {"ok": False, "non_typed_failures": [], "shed": 0,
              "served": 0, "injected_refusals": 0}
    lat_ms = []
    lock = threading.Lock()
    stop_t = time.perf_counter() + seconds
    try:
        def client():
            x = np.ones((8,), np.float32)
            while time.perf_counter() < stop_t:
                t0 = time.perf_counter()
                try:
                    pool.submit({"x": x}, timeout_ms=2000.0).result(10.0)
                    with lock:
                        lat_ms.append((time.perf_counter() - t0) * 1e3)
                        result["served"] += 1
                except ServingOverloadError:
                    with lock:
                        result["shed"] += 1
                    time.sleep(0.001)
                except chaos.ChaosInjectedError:
                    # every replica's dispatch took the injected fault:
                    # typed + retryable — the client retries, nothing
                    # is silently lost
                    with lock:
                        result["injected_refusals"] += 1
                except Exception as e:  # noqa: BLE001 — gate-fatal bucket
                    with lock:
                        result["non_typed_failures"].append(
                            f"{type(e).__name__}: {e}")

        clients = [threading.Thread(target=client)
                   for _ in range(n_clients)]
        for t in clients:
            t.start()
        # mid-burst: kill replica 0 (drain-on-removal — the router
        # finishes everything it admitted, then drops it from routing)
        time.sleep(seconds / 2)
        victim_rid = pool.replica_ids()[0]
        victim = pool.remove_replica(victim_rid, drain=True)
        result["victim_drained"] = victim.occupancy() == 0
        result["survivors"] = pool.replica_ids()
        for t in clients:
            t.join(timeout=30)
        # every admitted request resolved: one more round trip proves
        # the survivors still serve
        x = np.ones((8,), np.float32)
        pool.submit({"x": x}).result(10.0)
        lat_ms.sort()
        result["p99_ms"] = _percentile(lat_ms, 99)
        result["spills"] = (spill_counter.value(
            labels={"model": "chaos-pool"}) - spills0)
        result["ok"] = bool(
            result["victim_drained"]
            and len(result["survivors"]) == n_replicas - 1
            and result["served"] > 0
            and result["spills"] >= 1
            and not result["non_typed_failures"]
            and result["p99_ms"] is not None
            and result["p99_ms"] < 1000.0)
    finally:
        chaos.reset()
        pool.close(timeout=5.0)
    return result


# ---------------------------------------------------------------------------
# scenario: replica death mid-generation (ISSUE 16)
# ---------------------------------------------------------------------------
def scenario_replica_kill_mid_generation(n_sessions=6, max_new=10):
    """Chaos over the stateful serving plane: two generation engines
    (the "replicas") stream concurrent sessions; an injected
    ``serving/generation/decode`` fault kills one engine's loop past
    its restart budget mid-generation.  Contract: every session on the
    victim fails TYPED-retryable (``ServingWorkerError``) — never
    hangs — and the client resumes it on the sibling engine with
    ``prompt + tokens-so-far`` as the new prompt (the sibling's prefix
    cache makes the resume cheap); sessions on the survivor stream to
    completion untouched.  Afterwards both engines' slot pools and the
    resource ledger's ``kv_pages``/``prefix_cache`` rows are PROVABLY
    zero — a dead replica leaks nothing."""
    import numpy as np

    from ..serving import generation
    from ..serving.batcher import (RequestTimeoutError, ServingClosedError,
                                   ServingOverloadError,
                                   ServingWorkerError)
    from ..telemetry.resources import LEDGER

    chaos.reset()
    engine_kw = dict(slots=4, page_tokens=8, kv_budget_mb=8,
                     prefix_cache_entries=8, max_len=96,
                     loop_restarts=0, session_timeout_s=30.0)
    # identical seeds: the sibling holds the same weights, so a greedy
    # resume continues the victim's stream deterministically
    eng_a = generation.GenerationEngine(
        generation.tiny_lm(vocab=24, d_model=8, max_len=96, seed=11),
        name="chaos-gen-a", **engine_kw)
    eng_b = generation.GenerationEngine(
        generation.tiny_lm(vocab=24, d_model=8, max_len=96, seed=11),
        name="chaos-gen-b", **engine_kw)
    eng_a.warm()
    eng_b.warm()
    # one engine dies: the site is shared, hits-triggered, count=1 —
    # whichever loop reaches the Nth decode dispatch first is the victim
    chaos.arm("serving/generation/decode", "raise", hits=4, count=1)

    result = {"ok": False, "completed": 0, "resumed": 0, "shed": 0,
              "hung": 0, "non_typed_failures": []}
    lock = threading.Lock()
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, 24, size=rng.randint(4, 12)).astype(np.int32)
               for _ in range(n_sessions)]
    engines = [eng_a, eng_b]

    def client(i):
        eng = engines[i % 2]
        sibling = engines[(i + 1) % 2]
        try:
            sess = eng.start_session(prompts[i], max_new_tokens=max_new,
                                     greedy=True)
        except (ServingOverloadError, ServingClosedError):
            with lock:
                result["shed"] += 1
            return
        try:
            sess.result(30.0)
            with lock:
                result["completed"] += 1
            return
        except ServingWorkerError:
            pass  # the replica died under this session: resume below
        except (ServingOverloadError, ServingClosedError):
            with lock:
                result["shed"] += 1
            return
        except RequestTimeoutError:
            with lock:
                result["hung"] += 1
            return
        except Exception as e:  # noqa: BLE001 — gate-fatal bucket
            with lock:
                result["non_typed_failures"].append(
                    f"{type(e).__name__}: {e}")
            return
        # typed-retryable death: resume on the sibling from where the
        # stream stopped
        done = list(sess.tokens)
        resume_prompt = np.concatenate(
            [prompts[i], np.asarray(done, np.int32)])
        try:
            rest = sibling.generate(resume_prompt,
                                    max_new_tokens=max_new - len(done)
                                    or 1, greedy=True)
            with lock:
                result["resumed"] += 1
                result["completed"] += bool(done + rest)
        except (ServingOverloadError, ServingClosedError,
                ServingWorkerError):
            with lock:
                result["shed"] += 1
        except Exception as e:  # noqa: BLE001 — gate-fatal bucket
            with lock:
                result["non_typed_failures"].append(
                    f"resume: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_sessions)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        result["hung"] += sum(t.is_alive() for t in threads)
        result["victim"] = ("chaos-gen-a" if eng_a.stats()["failed"]
                            else "chaos-gen-b" if eng_b.stats()["failed"]
                            else None)
    finally:
        chaos.reset()
        eng_a.close()
        eng_b.close()
    # zero-leak assertion: slots, pages and ledger rows all returned
    owners = LEDGER.snapshot()["owners"]
    leaks = {}
    for eng in engines:
        pool_stats = eng.pool.stats()
        row = owners.get(f"generation/{eng.name}", {})
        leaks[eng.name] = {
            "slots_in_use": pool_stats["slots_in_use"],
            "kv_bytes": pool_stats["kv_bytes"],
            "ledger_kv": row.get("kv_pages", 0),
            "ledger_prefix": row.get("prefix_cache", 0)}
    result["leaks"] = leaks
    result["zero_leak"] = all(
        not any(v.values()) for v in leaks.values())
    result["ok"] = bool(
        result["victim"] is not None
        and result["completed"] + result["shed"] == n_sessions
        and result["resumed"] >= 1
        and result["hung"] == 0
        and result["zero_leak"]
        and not result["non_typed_failures"])
    return result


# ---------------------------------------------------------------------------
# scenario 4: SIGKILL mid-scan-window, bit-identical resume
# ---------------------------------------------------------------------------
_SCAN_VICTIM = """
import os, sys
import numpy as np
import mxnet_tpu as mx
import mxnet_tpu.chaos  # arms the kill at window 3 from MXNET_CHAOS
from mxnet_tpu import io as mxio
from mxnet_tpu.checkpoint import CheckpointManager

ckdir = sys.argv[1]
K = int(os.environ["MXNET_SCAN_STEPS"])
mgr = CheckpointManager(ckdir, async_save=False, keep_last=0)
saved = set()

def boundary_save(param):
    mod = param.locals["self"]
    step = mod._optimizer.num_update
    if step % K == 0 and step not in saved:
        saved.add(step)
        mgr.save_module(mod, step, block=True)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import chaos_scan_common as common
common.fit(boundary_save)
print("FINISHED", flush=True)  # must never print: the kill fires first
"""

_SCAN_COMMON = """
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import io as mxio

N, FEAT, BATCH = 256, 20, 16

def mlp():
    d = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(d, num_hidden=32, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")

def init_params(seed=5):
    rng = np.random.RandomState(seed)
    return {"fc1_weight": mx.nd.array(rng.randn(32, FEAT) * 0.1),
            "fc1_bias": mx.nd.zeros((32,)),
            "fc2_weight": mx.nd.array(rng.randn(10, 32) * 0.1),
            "fc2_bias": mx.nd.zeros((10,))}

def dataset():
    rng = np.random.RandomState(3)
    x = rng.randn(N, FEAT).astype(np.float32)
    y = rng.randint(0, 10, N).astype(np.float32)
    return x, y

OPT = {"learning_rate": 0.05, "momentum": 0.9}

def fit(batch_end_callback=None, start_batch=0, module=None):
    mx.random.seed(0)
    x, y = dataset()
    x, y = x[start_batch * BATCH:], y[start_batch * BATCH:]
    it = mxio.NDArrayIter(mx.nd.array(x), mx.nd.array(y),
                          batch_size=BATCH, label_name="softmax_label")
    mod = module or mx.mod.Module(mlp(), context=mx.cpu())
    kwargs = {} if module is not None else {
        "arg_params": {k: v.copy() for k, v in init_params().items()}}
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params=dict(OPT), eval_metric="acc",
            batch_end_callback=batch_end_callback, **kwargs)
    params, _ = mod.get_params()
    return mod, {k: v.asnumpy() for k, v in params.items()}
"""


def scenario_sigkill_mid_scan(workdir, scan_k=4, timeout=180.0):
    """A K-step scanned fit SIGKILLs itself (chaos ``kill``) before its
    third window dispatches; the parent restores the last boundary
    checkpoint and continues the fit — the final weights must be
    BIT-IDENTICAL to an uninterrupted run."""
    import numpy as np

    from ..checkpoint import CheckpointManager, latest_step

    workdir = str(workdir)
    os.makedirs(workdir, exist_ok=True)
    with open(os.path.join(workdir, "chaos_scan_common.py"), "w") as f:  # graftlint: disable=torn-write -- ephemeral scenario script, single consumer
        f.write(_SCAN_COMMON)
    victim = os.path.join(workdir, "scan_victim.py")
    with open(victim, "w") as f:  # graftlint: disable=torn-write -- ephemeral scenario script, single consumer
        f.write(_SCAN_VICTIM)
    ckdir = os.path.join(workdir, "ckpt")

    result = {"ok": False}
    # windows 1 and 2 run (boundaries K and 2K committed); the kill arm
    # fires as window 3 is about to stage — "mid-window" by construction
    proc = subprocess.Popen(
        [sys.executable, victim, ckdir],
        env=_child_env(MXNET_SCAN_STEPS=scan_k, MXNET_FUSED_STEP=1,
                       MXNET_CHAOS="train/scan_window=kill:hits=3"),
        stdout=subprocess.PIPE, text=True)
    try:
        out, _ = proc.communicate(timeout=timeout)
    finally:
        if proc.poll() is None:
            proc.kill()
    result["victim_exit"] = proc.returncode
    result["victim_finished"] = "FINISHED" in (out or "")
    resume_step = latest_step(ckdir)
    result["resume_step"] = resume_step
    if resume_step != 2 * scan_k or result["victim_finished"]:
        return result

    # run the scenario's fit shapes in-process: the uninterrupted
    # reference, then the boundary-restore continuation
    sys.path.insert(0, workdir)
    try:
        import importlib

        import chaos_scan_common as common
        importlib.reload(common)
        os.environ["MXNET_SCAN_STEPS"] = str(scan_k)
        os.environ["MXNET_FUSED_STEP"] = "1"
        try:
            _ref_mod, ref_params = common.fit()

            mgr = CheckpointManager(ckdir, async_save=False, keep_last=0)
            mod, _ckpt = mgr.restore_module(resume_step)
            mgr.close()
            _mod, resumed = common.fit(start_batch=resume_step,
                                       module=mod)
        finally:
            os.environ.pop("MXNET_SCAN_STEPS", None)
            os.environ.pop("MXNET_FUSED_STEP", None)
    finally:
        sys.path.remove(workdir)
    diverged = [k for k in ref_params
                if not np.array_equal(ref_params[k], resumed[k])]
    result["diverged_params"] = diverged
    result["ok"] = bool(result["victim_exit"] == -9 and not diverged)
    return result


# ---------------------------------------------------------------------------
# scenario 5: mesh collective stall + kill, restore onto a RESIZED mesh

_MESH_COMMON = """
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import io as mxio
from mxnet_tpu.parallel.mesh import make_mesh

N, FEAT, BATCH = 128, 20, 16

def mlp():
    d = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(d, num_hidden=32, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")

def init_params(seed=5):
    rng = np.random.RandomState(seed)
    return {"fc1_weight": mx.nd.array(rng.randn(32, FEAT) * 0.1),
            "fc1_bias": mx.nd.zeros((32,)),
            "fc2_weight": mx.nd.array(rng.randn(10, 32) * 0.1),
            "fc2_bias": mx.nd.zeros((10,))}

def dataset():
    rng = np.random.RandomState(3)
    x = rng.randn(N, FEAT).astype(np.float32)
    y = rng.randint(0, 10, N).astype(np.float32)
    return x, y

OPT = {"learning_rate": 0.05, "momentum": 0.9}

def fit(dp, batch_end_callback=None, start_batch=0, end_batch=None,
        module=None):
    mx.random.seed(0)
    x, y = dataset()
    stop = None if end_batch is None else end_batch * BATCH
    x, y = x[start_batch * BATCH:stop], y[start_batch * BATCH:stop]
    it = mxio.NDArrayIter(mx.nd.array(x), mx.nd.array(y),
                          batch_size=BATCH, label_name="softmax_label")
    mod = module or mx.mod.Module(mlp(), context=mx.cpu())
    kwargs = {} if module is not None else {
        "arg_params": {k: v.copy() for k, v in init_params().items()}}
    with make_mesh(dp=dp):
        mod.fit(it, num_epoch=1, optimizer="sgd",
                optimizer_params=dict(OPT), eval_metric="acc",
                kvstore="dist_device_sync",
                batch_end_callback=batch_end_callback, **kwargs)
    assert mod._mesh is not None, "mesh fused path did not engage"
    params, _ = mod.get_params()
    return mod, {k: v.asnumpy() for k, v in params.items()}
"""

_MESH_WEDGE = """
import json, os, sys
import mxnet_tpu as mx
import mxnet_tpu.chaos  # arms the wedge from MXNET_CHAOS
from mxnet_tpu.telemetry import watchdog

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import chaos_mesh_common as common
common.fit(2)  # wedge releases via timeout -> scan path self-heals
dump = watchdog.last_dump()
txt = ""
if dump and os.path.exists(dump):
    with open(dump) as f:
        txt = f.read()
print("RESULT " + json.dumps({
    "fires": watchdog.fires(),
    "names_fit_section": "train/fit" in txt,
    "names_collective_frame": "parallel/collective" in txt
                              or "failpoints" in txt,
}), flush=True)
"""

_MESH_VICTIM = """
import os, sys
import mxnet_tpu as mx
import mxnet_tpu.chaos  # arms the kill at window 3 from MXNET_CHAOS
from mxnet_tpu.checkpoint import CheckpointManager

ckdir = sys.argv[1]
K = int(os.environ["MXNET_SCAN_STEPS"])
mgr = CheckpointManager(ckdir, async_save=False, keep_last=0)
saved = set()

def boundary_save(param):
    mod = param.locals["self"]
    step = mod._optimizer.num_update
    if step % K == 0 and step not in saved:
        saved.add(step)
        mgr.save_module(mod, step, block=True)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import chaos_mesh_common as common
common.fit(4, boundary_save)
print("FINISHED", flush=True)  # must never print: the kill fires first
"""

_MESH_REF = """
import os, sys
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu.checkpoint import CheckpointManager

ckdir, out = sys.argv[1], sys.argv[2]
K = int(os.environ["MXNET_SCAN_STEPS"])
S = 2 * K  # the boundary the victim dies after

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import chaos_mesh_common as common
mgr = CheckpointManager(ckdir, async_save=False, keep_last=0)
saved = set()

def boundary_save(param):
    mod = param.locals["self"]
    step = mod._optimizer.num_update
    if step % K == 0 and step not in saved:
        saved.add(step)
        mgr.save_module(mod, step, block=True)

# the no-fault reference: dp=4 to the boundary, then a planned
# restore-resize onto dp=2 for the rest — the exact trajectory the
# faulted run must reproduce
common.fit(4, boundary_save, end_batch=S)
mod, _ckpt = mgr.restore_module(S)
mgr.close()
_m, params = common.fit(2, start_batch=S, module=mod)
np.savez(out, **params)
"""

_MESH_RESUME = """
import os, sys
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu.checkpoint import CheckpointManager

ckdir, out = sys.argv[1], sys.argv[2]
K = int(os.environ["MXNET_SCAN_STEPS"])
S = 2 * K

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import chaos_mesh_common as common
mgr = CheckpointManager(ckdir, async_save=False, keep_last=0)
mod, _ckpt = mgr.restore_module(S)
mgr.close()
_m, params = common.fit(2, start_batch=S, module=mod)
np.savez(out, **params)
"""


def scenario_mesh_collective_stall(workdir, scan_k=2, timeout=240.0):
    """The mesh fused step under composed faults, two phases:

    1. **stall**: the ``parallel/collective`` failpoint wedges the
       window boundary of a dp=2 mesh fit; the hang watchdog must fire
       naming the stalled mesh step (``train/fit`` section + the wedged
       failpoint frame in the dump), the wedge timeout must turn the
       stall into a typed error, and the fit must SELF-HEAL by falling
       back to per-batch steps and completing.
    2. **kill + resize**: a dp=4 mesh fit SIGKILLs itself (chaos
       ``kill``) before its third window; a fresh process restores the
       last boundary checkpoint onto a RESIZED dp=2 mesh and continues —
       bit-identical to a no-fault run that performed the same planned
       dp=4 → dp=2 restore-resize at that boundary (PR 2's elastic
       restore as the resize mechanism).
    """
    import numpy as np

    from ..checkpoint import latest_step

    workdir = str(workdir)
    os.makedirs(workdir, exist_ok=True)
    for fname, src in (("chaos_mesh_common.py", _MESH_COMMON),
                       ("mesh_wedge.py", _MESH_WEDGE),
                       ("mesh_victim.py", _MESH_VICTIM),
                       ("mesh_ref.py", _MESH_REF),
                       ("mesh_resume.py", _MESH_RESUME)):
        with open(os.path.join(workdir, fname), "w") as f:  # graftlint: disable=torn-write -- ephemeral scenario scripts, single consumer
            f.write(src)
    mesh_env = dict(
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        MXNET_SCAN_STEPS=scan_k, MXNET_MESH_FUSED_STEP=1)
    result = {"ok": False}

    # phase 1: wedge the window boundary; watchdog names it, the fit
    # self-heals through the wedge-timeout error
    proc = subprocess.Popen(
        [sys.executable, os.path.join(workdir, "mesh_wedge.py")],
        env=_child_env(MXNET_CHAOS="parallel/collective=wedge:hits=2",
                       MXNET_CHAOS_WEDGE_TIMEOUT_S=1.5,
                       MXNET_WATCHDOG_S=0.3, MXNET_WATCHDOG_DIR=workdir,
                       **mesh_env),
        stdout=subprocess.PIPE, text=True)
    try:
        out, _ = proc.communicate(timeout=timeout)
    finally:
        if proc.poll() is None:
            proc.kill()
    result["wedge_exit"] = proc.returncode
    payload = {}
    for line in (out or "").splitlines():
        if line.startswith("RESULT "):
            payload = json.loads(line[len("RESULT "):])
    result["wedge"] = payload
    wedge_ok = (proc.returncode == 0 and payload.get("fires", 0) >= 1
                and payload.get("names_fit_section")
                and payload.get("names_collective_frame"))
    result["wedge_ok"] = bool(wedge_ok)

    # phase 2: kill before window 3, restore onto a resized mesh
    ckdir = os.path.join(workdir, "ckpt")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(workdir, "mesh_victim.py"), ckdir],
        env=_child_env(MXNET_CHAOS="parallel/collective=kill:hits=3",
                       **mesh_env),
        stdout=subprocess.PIPE, text=True)
    try:
        out, _ = proc.communicate(timeout=timeout)
    finally:
        if proc.poll() is None:
            proc.kill()
    result["victim_exit"] = proc.returncode
    result["victim_finished"] = "FINISHED" in (out or "")
    resume_step = latest_step(ckdir)
    result["resume_step"] = resume_step
    if resume_step != 2 * scan_k or result["victim_finished"]:
        return result

    def run_child(script, *args):
        proc = subprocess.run(
            [sys.executable, os.path.join(workdir, script)] + list(args),
            env=_child_env(**mesh_env), capture_output=True, text=True,
            timeout=timeout)
        if proc.returncode != 0:
            raise RuntimeError(f"{script} failed: "
                               f"{proc.stderr.strip()[-500:]}")

    ref_out = os.path.join(workdir, "ref.npz")
    res_out = os.path.join(workdir, "resumed.npz")
    run_child("mesh_ref.py", ckdir + "-ref", ref_out)
    run_child("mesh_resume.py", ckdir, res_out)
    ref = dict(np.load(ref_out))
    resumed = dict(np.load(res_out))
    diverged = [k for k in ref
                if not np.array_equal(ref[k], resumed[k])]
    result["diverged_params"] = diverged
    result["ok"] = bool(wedge_ok and result["victim_exit"] == -9
                        and not diverged)
    return result


# ---------------------------------------------------------------------------
# scenario 7: multi-host peer loss mid-window — survivors checkpoint,
# the elastic launcher respawns the dp/2 survivor mesh, the continued
# fit is bitwise identical to a planned resize (ISSUE 11)
# ---------------------------------------------------------------------------
def scenario_peer_loss_mid_window(workdir, scan_k=2, timeout=240.0):
    """Kill host 1 of a 2-process × 4-fake-device jax.distributed mesh
    at its window-3 boundary (chaos ``kill`` at ``multihost/peer_loss``)
    and assert the whole elastic contract:

    * the survivor takes a **typed** exit (PeerLostError → the
      ELASTIC_RESTART code) from the deadline-bounded rendezvous — no
      straggler kill, no hang, no untyped crash;
    * the boundary checkpoint commits and the launcher respawns the
      dp/2 survivor world, which finishes training;
    * the final weights are BITWISE identical to a planned resize (the
      same host *leaving* via the preemption path at the same
      boundary);
    * recovery wall time was measured (the launcher's clock ran);
    * the fault generation left ONE postmortem bundle whose merged
      event rings name the injected site (``multihost/peer_loss``) as
      the FIRST anomalous event, and whose fleet snapshot tags the
      killed rank ``lost`` (ISSUE 12).
    """
    import json as _json

    import numpy as np

    from ..parallel import elastic as E

    workdir = str(workdir)
    os.makedirs(workdir, exist_ok=True)
    K, NB, BS = scan_k, 4 * scan_k, 32
    result = {"ok": False}

    sa, pa, la = E._launch(
        os.path.join(workdir, "faulted"), 2, NB, BS, K,
        rank_env={1: {"MXNET_CHAOS": "multihost/peer_loss=kill:hits=3"}})
    result["postmortems"] = list(la.postmortems)
    result["postmortem_rings"] = 0
    result["first_anomaly_site"] = None
    result["fleet_lost_tagged"] = False
    if la.postmortems:
        with open(la.postmortems[0], encoding="utf-8") as f:
            bundle = _json.load(f)
        result["postmortem_rings"] = len(bundle.get("rings", {}))
        anomaly = bundle.get("first_anomaly") or {}
        result["first_anomaly_site"] = \
            (anomaly.get("fields") or {}).get("site")
        result["fleet_lost_tagged"] = (
            bundle.get("fleet", {}).get("ranks", {})
            .get("1", {}).get("state") == "lost")
    sb, pb, _lb = E._launch(
        os.path.join(workdir, "planned"), 2, NB, BS, K,
        leave_at=2 * K)
    result["faulted"] = {k: v for k, v in sa.items()}
    result["planned_ok"] = bool(sb.get("ok"))
    gen0 = sa["history"][0]["exits"]
    result["gen0_exits"] = gen0
    result["typed_only"] = sorted(gen0) == [-9, E.ELASTIC_RESTART]
    result["survivor_world"] = sa["history"][-1]["world"]
    result["recovery_s"] = (sa.get("recovery_s") or [None])[0]
    try:
        p_fault = E._final_params(pa)
        p_plan = E._final_params(pb)
        diverged = [k for k in p_plan
                    if not np.array_equal(p_fault[k], p_plan[k])]
    except Exception as e:  # noqa: BLE001 — gate-fatal bucket
        result["error"] = f"{type(e).__name__}: {e}"
        return result
    result["diverged_params"] = diverged
    result["ok"] = bool(
        sa.get("ok") and sb.get("ok")
        and result["typed_only"]
        and sa.get("restarts") == 1
        and result["survivor_world"] == 1
        and result["recovery_s"] is not None
        and not diverged
        and result["postmortem_rings"] >= 2
        and result["first_anomaly_site"] == "multihost/peer_loss"
        and result["fleet_lost_tagged"])
    return result


# ---------------------------------------------------------------------------
# scenario: reader death mid-epoch — the streaming data plane rebalances,
# the epoch completes exactly-once, all-dead is a typed error (ISSUE 19)
# ---------------------------------------------------------------------------
def scenario_reader_death_mid_epoch(workers=4, shards=16,
                                    batches_per_shard=4, kill_at=13):
    """Chaos over the streaming data plane (``io_pipeline``):

    1. one of ``workers`` reader workers dies at its ``kill_at``-th
       ``io/reader/read`` — the pipeline requeues the victim's shards
       onto the survivors, the epoch completes with every sample row
       delivered exactly once IN THE SAME seeded order as the serial
       baseline, the rebalance counter ticks, and no single ``next()``
       stalls;
    2. a slow reader (delay arm) is absorbed the same way — order
       unchanged, nothing dropped;
    3. every reader dying raises a typed :class:`DataReaderError` on
       the train thread — never a hang (asserted via a joined helper
       thread, not hope).
    """
    import numpy as np

    from .. import io_pipeline as pipe
    from .. import telemetry

    batch_size = 8
    n_rows = shards * batches_per_shard * batch_size
    data = np.arange(n_rows * 3, dtype=np.float32).reshape(n_rows, 3)
    label = np.arange(n_rows, dtype=np.float32)

    def make_pipe(n_workers):
        src = pipe.NDArraySource(data, label, batch_size=batch_size,
                                 batches_per_shard=batches_per_shard)
        return pipe.DataPipeline(src, workers=n_workers, seed=7)

    def drain(p, stall_box=None):
        """One full epoch; returns the concatenated row-index sequence."""
        idx = []
        while True:
            t0 = time.perf_counter()
            try:
                batch = p.next()
            except StopIteration:
                break
            if stall_box is not None:
                stall_box[0] = max(stall_box[0],
                                   time.perf_counter() - t0)
            idx.append(np.asarray(batch.index))
        return np.concatenate(idx) if idx else np.empty((0,), np.int64)

    result = {"ok": False, "non_typed_failures": [], "rebalances": 0,
              "max_next_stall_s": 0.0}
    reb0 = telemetry._DATA_REBALANCE.value()
    chaos.reset()
    p_base = p_kill = p_slow = p_dead = None
    try:
        # serial baseline: the seeded shard order, workers=0
        p_base = make_pipe(0)
        baseline = drain(p_base)
        result["batches"] = len(baseline) // batch_size
        if sorted(baseline.tolist()) != list(range(n_rows)):
            result["non_typed_failures"].append(
                "baseline is not a permutation of the dataset")

        # pass 1: kill one reader mid-epoch
        chaos.arm("io/reader/read", "raise", hits=kill_at, count=1)
        p_kill = make_pipe(workers)
        stall = [0.0]
        try:
            seq = drain(p_kill, stall)
        except pipe.DataReaderError as e:
            result["non_typed_failures"].append(
                f"one dead reader must rebalance, not raise: {e}")
            seq = np.empty((0,), np.int64)
        result["max_next_stall_s"] = round(stall[0], 3)
        result["exactly_once"] = bool(np.array_equal(seq, baseline))
        result["rebalances"] = telemetry._DATA_REBALANCE.value() - reb0
        chaos.reset()

        # pass 2: a slow reader is absorbed, order unchanged
        chaos.arm("io/reader/read", "delay", value=0.01, hits=3, count=6)
        p_slow = make_pipe(workers)
        slow_seq = drain(p_slow)
        result["slow_reader_order_ok"] = bool(
            np.array_equal(slow_seq, baseline))
        chaos.reset()

        # pass 3: ALL readers dead -> typed DataReaderError, no hang
        chaos.arm("io/reader/read", "raise", hits=1)
        p_dead = make_pipe(workers)
        box = {"raised": None}

        def all_dead():
            try:
                drain(p_dead)
                box["raised"] = "completed-without-error"
            except pipe.DataReaderError:
                box["raised"] = "typed"
            except Exception as e:  # noqa: BLE001 — gate-fatal bucket
                box["raised"] = f"{type(e).__name__}: {e}"

        t = threading.Thread(target=all_dead, name="chaos-all-dead")
        t.start()
        t.join(timeout=30)
        result["all_dead_hung"] = t.is_alive()
        result["all_dead_outcome"] = box["raised"]
        if box["raised"] not in (None, "typed"):
            result["non_typed_failures"].append(
                f"all-dead pass: {box['raised']}")

        result["ok"] = bool(
            result["exactly_once"]
            and result["rebalances"] >= 1
            and result["max_next_stall_s"] < 10.0
            and result["slow_reader_order_ok"]
            and not result["all_dead_hung"]
            and result["all_dead_outcome"] == "typed"
            and not result["non_typed_failures"])
    finally:
        chaos.reset()
        for p in (p_base, p_kill, p_slow, p_dead):
            if p is not None:
                p.close()
    return result


def scenario_rollup_under_churn(ranks=64, cycles=24):
    """[fleet/push] The fleet telemetry plane under membership churn on
    a lossy push path (ISSUE 20): ``ranks`` in-process synthetic
    reporters drive ONE real leader (KVServer + FleetStore + summary
    rollup, virtual clock) while 10% of pushes are chaos-dropped at the
    ``fleet/push`` site, 8 ranks die mid-run and 8 join late.

    Gates: the leader loop takes ZERO exceptions (a dropped delta must
    resolve via resync, never a merge error); the rollup stays bounded
    (a scrape never blocks the push path); every dead rank is tagged
    lost/stale in the summary within the peer timeout; the dropped
    pushes are actually counted (the arm fired, not a no-op run)."""
    from ..telemetry import fleet_sim

    result = {"ok": False, "ranks": ranks, "cycles": cycles}
    # dying/joining ranks live at the top of the rank space so the
    # simulator's scripted anomaly ranks (low) stay out of the churn
    churn = {"die": list(range(ranks - 16, ranks - 8)),
             "die_at": cycles // 2,
             "join": list(range(ranks - 8, ranks)),
             "join_at": cycles // 4}
    chaos.arm("fleet/push", "raise", prob=0.1, count=None)
    try:
        r = fleet_sim.run_sim(ranks=ranks, cycles=cycles,
                              interval_s=5.0, seed=7, delta=True,
                              churn=churn, alloc_window=0)
    finally:
        chaos.reset()
    peers = r["final_summary"]["peers"] or {}
    anomalous = set(r["final_summary"]["anomalous"] or ())
    dead_tagged = all(str(rank) in anomalous for rank in churn["die"])
    result.update({
        "leader_exceptions": r["leader_exceptions"],
        "dropped_pushes": r["merge"]["dropped"],
        "resyncs": r["merge"]["resync"],
        "merge_p99_ms": round(r["merge"]["p99_ms"], 3),
        "rollup_max_ms": round(r["rollup"]["max_ms"], 2),
        "peers": peers,
        "dead_ranks_tagged": dead_tagged,
        "silent_rank_state": r["alerts"]["silent_rank_state"],
    })
    result["ok"] = bool(
        not r["leader_exceptions"]
        and r["merge"]["dropped"] > 0
        and dead_tagged
        and r["alerts"]["silent_rank_state"] in ("lost", "stale")
        and peers.get("alive", 0) >= ranks - 16 - 1
        and r["rollup"]["max_ms"] < 250.0)
    return result


def run_all(workdir=None, verbose=True):
    """Run the composed scenarios sequentially; returns
    {name: result dict}.  The smoke asserts every ``ok``."""
    base = workdir or tempfile.mkdtemp(prefix="mx-chaos-")
    results = {}
    scenarios = [
        ("worker_kill_revive",
         lambda: scenario_worker_kill_revive(os.path.join(base, "s1"))),
        ("corrupt_reload_under_load",
         lambda: scenario_corrupt_reload_under_load(
             os.path.join(base, "s2"))),
        ("wedged_batcher", scenario_wedged_batcher),
        ("replica_kill_mid_burst", scenario_replica_kill_mid_burst),
        ("replica_kill_mid_generation",
         scenario_replica_kill_mid_generation),
        ("reader_death_mid_epoch", scenario_reader_death_mid_epoch),
        ("sigkill_mid_scan",
         lambda: scenario_sigkill_mid_scan(os.path.join(base, "s4"))),
        ("mesh_collective_stall",
         lambda: scenario_mesh_collective_stall(os.path.join(base, "s5"))),
        ("peer_loss_mid_window",
         lambda: scenario_peer_loss_mid_window(os.path.join(base, "s7"))),
        ("rollup_under_churn", scenario_rollup_under_churn),
    ]
    for name, fn in scenarios:
        t0 = time.perf_counter()
        chaos.reset()
        try:
            results[name] = fn()
        finally:
            chaos.reset()
        results[name]["elapsed_s"] = round(time.perf_counter() - t0, 1)
        if verbose:
            print(f"[chaos] {name}: "
                  f"{'OK' if results[name].get('ok') else 'FAIL'} "
                  f"({results[name]['elapsed_s']}s)", flush=True)
    return results
