"""Soak harness: bounded-minutes end-to-end run gated by the alert
engine (ISSUE 13 tentpole, half three; ROADMAP item 5b).

Every other CI phase exercises the stack for *seconds* — leaks, drifts
and slow ratchets are invisible at that horizon.  The soak is the
ratchet loop applied to wall-clock time: for ``--seconds`` (default
``MXNET_SOAK_SECONDS`` = 90) it runs

* **train windows** — repeated fit epochs on one persistent Module;
* **checkpoint commits** — the module's params committed each round
  (retention GC live);
* **serving hot-reload** — a 2-replica ``ModelServer`` watching the
  checkpoint directory, flipping to each newly committed step under
  load;
* **Poisson traffic** — client threads at ``MXNET_SOAK_QPS``;
* **a seeded benign chaos mix** (``MXNET_SOAK_CHAOS``) — transient
  router-dispatch faults the spill path must heal, io-stage and
  checkpoint-GC delays — deliberately *below* every default alert
  threshold, because the gate is that the stack absorbs them quietly;

with the resource sampler, the alert engine (default rule pack), and
the exporter all armed.  It passes only if the judgment layer stayed
quiet:

* **zero firing alerts at exit** and zero page-severity fires ever
  (no leak-slope page, no watchdog, no shed burn);
* **RSS leak slope** below ``MXNET_SOAK_RSS_SLOPE_MAX`` (the
  least-squares estimator over the whole measured window);
* **numerics quiet** (ISSUE 14): the observatory runs armed
  (``MXNET_NUMERICS=warn``) through every train window — the soak
  passes only with zero non-finite windows and bounded grad-norm drift
  (max over the run within 50x the median: a slow exploding-gradient
  ratchet fails the soak before it ever reaches NaN);
* the watchdog never fired, no non-shed request failures;
* a final ``/alerts.json`` + ``/fleet.json`` + ``/healthz`` scrape
  parses (200).

Run: ``JAX_PLATFORMS=cpu python -m mxnet_tpu.chaos.soak --seconds 90``
(the ci/run.sh soak smoke phase).  docs/chaos.md has the runbook.
"""
from __future__ import annotations

import argparse
import json
import os
import random as _pyrandom
import shutil
import sys
import tempfile
import threading
import time
import urllib.request

from . import failpoints as chaos


def _build_model(seed=0, in_dim=16, width=32, classes=10, scale=0.05):
    """(train_symbol, serve_symbol, init_params): the fit loop trains
    the SoftmaxOutput graph; the label-free logits graph is what each
    checkpoint commits for the serving hot-reload."""
    import numpy as np

    import mxnet_tpu as mx
    d = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(d, num_hidden=width, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    logits = mx.sym.FullyConnected(h, num_hidden=classes, name="fc2")
    sym = mx.sym.SoftmaxOutput(logits, name="softmax")
    rng = np.random.RandomState(seed)
    params = {
        "fc1_weight": mx.nd.array(
            rng.randn(width, in_dim).astype(np.float32) * scale),
        "fc1_bias": mx.nd.zeros((width,)),
        "fc2_weight": mx.nd.array(
            rng.randn(classes, width).astype(np.float32) * scale),
        "fc2_bias": mx.nd.zeros((classes,)),
    }
    return sym, logits, params


def _rearm_chaos(rng):
    """One round of the benign fault mix — transient, count-bounded,
    and sized BELOW the default alert thresholds (spill_storm wants a
    sustained > 1/s rate; this injects at most 2 spills per ~4 s
    round).  The soak's claim is that the stack heals these without a
    judgment."""
    arms = chaos.arms()
    if "serving/router/dispatch" not in arms:
        chaos.arm("serving/router/dispatch", "raise",
                  prob=0.05 + 0.05 * rng.random(), count=2)
    if "io/stage" not in arms:
        chaos.arm("io/stage", "delay", value=0.002, prob=0.2, count=4)
    if "io/reader/read" not in arms:
        # slow reader: the data-plane workers absorb it below the
        # data_starved rate threshold (0.3 s/s over 30 s)
        chaos.arm("io/reader/read", "delay", value=0.002, prob=0.2,
                  count=4)
    if "checkpoint/gc/remove" not in arms:
        chaos.arm("checkpoint/gc/remove", "delay", value=0.002,
                  prob=0.5, count=2)


def _scrape(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.read().decode("utf-8")


def run(seconds=None, qps=None, chaos_on=None, rss_slope_max=None,
        n_clients=4, verbose=True, alert_interval_s=0.5,
        sample_interval_s=0.5):
    """Run the soak; returns a result dict with ``ok``."""
    import numpy as np

    import mxnet_tpu as mx
    from .. import config as _config
    from .. import io as mxio
    from .. import serving, telemetry
    from ..checkpoint import CheckpointManager
    from ..serving.batcher import (RequestTimeoutError,
                                   ServingOverloadError)
    from ..telemetry import alerts, numerics, resources
    from ..telemetry import watchdog as wd

    seconds = float(_config.get("MXNET_SOAK_SECONDS")
                    if seconds is None else seconds)
    qps = float(_config.get("MXNET_SOAK_QPS") if qps is None else qps)
    chaos_on = bool(_config.get("MXNET_SOAK_CHAOS")
                    if chaos_on is None else chaos_on)
    rss_slope_max = float(_config.get("MXNET_SOAK_RSS_SLOPE_MAX")
                          if rss_slope_max is None else rss_slope_max)
    rng = _pyrandom.Random(int(_config.get("MXNET_CHAOS_SEED")) or 13)

    workdir = tempfile.mkdtemp(prefix="mx-soak-")
    ckdir = os.path.join(workdir, "ckpt")
    # the watchdog runs ARMED through the soak and must stay silent
    watchdog_was = os.environ.get("MXNET_WATCHDOG_S")
    os.environ.setdefault("MXNET_WATCHDOG_S", "30")
    fires0 = wd.fires()
    # the numerics observatory runs ARMED through every train window
    # (warn mode: detection without intervention) — the gate below
    # requires zero non-finite windows and bounded grad-norm drift
    numerics_was = os.environ.get("MXNET_NUMERICS")
    os.environ.setdefault("MXNET_NUMERICS", "warn")
    numerics.configure()
    chaos.reset()

    result = {"ok": False, "seconds": seconds, "qps": qps,
              "chaos": chaos_on, "served": 0, "shed": 0, "timeouts": 0,
              "chaos_refusals": 0, "non_shed_failures": [],
              "train_steps": 0, "commits": 0, "reloads": 0}
    stop = threading.Event()
    lock = threading.Lock()

    sym, serve_sym, params = _build_model()
    rng_np = np.random.RandomState(7)
    x = rng_np.randn(128, 16).astype(np.float32)
    y = rng_np.randint(0, 10, 128).astype(np.float32)

    mgr = CheckpointManager(ckdir, async_save=False, keep_last=3)
    server = serving.ModelServer(max_batch_size=8, max_latency_ms=2.0,
                                 num_replicas=2, name="soak")
    port = telemetry.start_exporter(0)
    resources.SAMPLER.start(sample_interval_s)

    def client():
        xq = rng_np.randn(16).astype(np.float32)
        per_client = max(0.5, qps / max(1, n_clients))
        while not stop.is_set():
            # Poisson arrivals: exponential inter-arrival per client
            stop.wait(rng.expovariate(per_client))
            if stop.is_set():
                return
            try:
                server.predict("m", {"data": xq}, wait_s=30.0)
                with lock:
                    result["served"] += 1
            except ServingOverloadError:
                with lock:
                    result["shed"] += 1
            except RequestTimeoutError:
                with lock:
                    result["timeouts"] += 1
            except chaos.ChaosInjectedError:
                # every replica took the injected transient — typed and
                # retryable; the next arrival retries organically
                with lock:
                    result["chaos_refusals"] += 1
            except Exception as e:  # noqa: BLE001 — gate-fatal bucket
                with lock:
                    result["non_shed_failures"].append(
                        f"{type(e).__name__}: {e}")

    clients = []
    step = 0
    it = None
    mod = mx.mod.Module(sym, context=mx.cpu())
    try:
        # -- warmup (outside the measured window): first fit epoch,
        # first commit, watch engaged, first served request — compile
        # transients must not pollute the leak-slope estimator
        # the training feed is the streaming data plane itself (2 reader
        # workers) so the soak's io/reader/read delays land on real
        # reader threads, not an armed-but-idle site
        from .. import io_pipeline as mxpipe
        it = mxpipe.DataPipeline(
            mxpipe.NDArraySource(x, y, batch_size=16,
                                 batches_per_shard=2),
            workers=2, seed=0)
        mod.fit(it, num_epoch=1, optimizer="sgd",
                optimizer_params={"learning_rate": 0.05},
                arg_params={k: v.copy() for k, v in params.items()})
        step += 1
        p, _ = mod.get_params()
        mgr.save(step, arrays=p, symbol=serve_sym, block=True)
        server.repository.watch("m", ckdir, interval=0.2)
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                server.repository.get("m")
                break
            except mx.base.MXNetError:
                time.sleep(0.05)
        server.predict("m", {"data": x[0]}, wait_s=30.0)

        # -- measured window: reset the sampler history, arm the engine
        resources.SAMPLER.reset()
        alerts.start(alert_interval_s)
        page_fires0 = {
            r["name"]: r["fired_total"]
            for r in alerts.alerts_json()["rules"]
            if r["severity"] == "page"}
        clients = [threading.Thread(target=client, daemon=True)
                   for _ in range(n_clients)]
        for t in clients:
            t.start()

        t_end = time.monotonic() + seconds
        last_log = 0.0
        last_rearm = 0.0
        last_commit = 0.0
        while time.monotonic() < t_end:
            if chaos_on and time.monotonic() - last_rearm >= 4.0:
                last_rearm = time.monotonic()
                _rearm_chaos(rng)
            it.reset()
            mod.fit(it, num_epoch=1, optimizer="sgd",
                    optimizer_params={"learning_rate": 0.05})
            result["train_steps"] += len(x) // 16
            if time.monotonic() - last_commit >= 2.5:
                # a commit triggers a serving hot-reload + ladder warmup
                # — a periodic publish, not a per-window spin
                last_commit = time.monotonic()
                step += 1
                p, _ = mod.get_params()
                mgr.save(step, arrays=p, symbol=serve_sym, block=True)
                result["commits"] += 1
            if verbose and time.monotonic() - last_log > 10:
                last_log = time.monotonic()
                with lock:
                    served = result["served"]
                print(f"[soak] t-{t_end - time.monotonic():.0f}s: "
                      f"{result['commits']} commits, {served} served, "
                      f"firing={alerts.firing()}", flush=True)
            # pace the loop: commits are periodic events, not a spin
            stop.wait(0.5)
        stop.set()
        for t in clients:
            t.join(timeout=30)
        chaos.reset()

        # -- judgment ---------------------------------------------------
        alerts.tick()  # one final evaluation with traffic stopped
        ajson = alerts.alerts_json()
        result["firing"] = ajson["firing"]
        result["page_fires"] = {
            r["name"]: r["fired_total"] - page_fires0.get(r["name"], 0)
            for r in ajson["rules"] if r["severity"] == "page"
            and r["fired_total"] > page_fires0.get(r["name"], 0)}
        result["warn_fires"] = {
            r["name"]: r["fired_total"] for r in ajson["rules"]
            if r["severity"] == "warn" and r["fired_total"] > 0}
        result["rss_slope_bytes_per_s"] = round(resources.leak_slope(), 1)
        result["rss_slope_max"] = rss_slope_max
        result["reloads"] = server.repository.latest_version("m") - 1
        result["watchdog_fires"] = wd.fires() - fires0

        # numerics gate (ISSUE 14): every window stayed finite and the
        # grad norm never drifted beyond 50x its run median
        nsum = numerics.summary()
        result["numerics_steps"] = nsum.get("steps", 0)
        result["numerics_nonfinite_windows"] = nsum.get(
            "nonfinite_windows", 0)
        gn_max = nsum.get("grad_norm_max")
        gn_med = nsum.get("grad_norm_median")
        drift_ok = True
        if gn_max is not None and gn_med is not None:
            result["grad_norm_max"] = gn_max
            result["grad_norm_median"] = gn_med
            drift_ok = gn_max <= 50.0 * max(gn_med, 1e-9)
        result["numerics_ok"] = bool(
            result["numerics_steps"] > 0
            and result["numerics_nonfinite_windows"] == 0
            and drift_ok)

        code_a, body_a = _scrape(port, "/alerts.json")
        code_f, body_f = _scrape(port, "/fleet.json")
        code_h, _body_h = _scrape(port, "/healthz")
        alerts_doc = json.loads(body_a)
        fleet_doc = json.loads(body_f)
        result["alerts_scrape_ok"] = bool(
            code_a == 200 and alerts_doc.get("rules"))
        result["fleet_scrape_ok"] = bool(
            code_f == 200 and fleet_doc.get("ranks"))
        result["healthz"] = code_h

        result["ok"] = bool(
            not result["firing"]
            and not result["page_fires"]
            and abs(result["rss_slope_bytes_per_s"]) <= rss_slope_max
            and result["watchdog_fires"] == 0
            and result["numerics_ok"]
            and not result["non_shed_failures"]
            and result["served"] > 0
            and result["commits"] >= 2
            and result["reloads"] >= 1
            and result["alerts_scrape_ok"]
            and result["fleet_scrape_ok"]
            and result["healthz"] == 200)
    finally:
        stop.set()
        chaos.reset()
        if it is not None:
            it.close()
        alerts.stop()
        resources.stop()
        try:
            server.repository.stop_watches()
            server.shutdown()
        except Exception as e:  # noqa: BLE001 — teardown must not mask the verdict
            result.setdefault("teardown_errors", []).append(str(e))
        mgr.close()
        telemetry.stop_exporter()
        if watchdog_was is None:
            os.environ.pop("MXNET_WATCHDOG_S", None)
        else:
            os.environ["MXNET_WATCHDOG_S"] = watchdog_was
        if numerics_was is None:
            os.environ.pop("MXNET_NUMERICS", None)
        else:
            os.environ["MXNET_NUMERICS"] = numerics_was
        numerics.configure()
        shutil.rmtree(workdir, ignore_errors=True)
    return result


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="bounded-minutes soak gated by the alert engine")
    ap.add_argument("--seconds", type=float, default=None)
    ap.add_argument("--qps", type=float, default=None)
    ap.add_argument("--no-chaos", action="store_true")
    ap.add_argument("--json", action="store_true",
                    help="print the result dict as JSON")
    args = ap.parse_args(argv)
    result = run(seconds=args.seconds, qps=args.qps,
                 chaos_on=False if args.no_chaos else None)
    if args.json:
        print(json.dumps(result, sort_keys=True, default=str))
    else:
        printable = {k: v for k, v in result.items() if k != "ok"}
        print(f"soak {'OK' if result['ok'] else 'FAIL'}: {printable}",
              flush=True)
    if not result["ok"]:
        print("FAIL: soak gate did not hold", file=sys.stderr)
        sys.exit(1)
    print(f"soak OK: {result['seconds']:.0f}s quiet — "
          f"{result['served']} served, {result['commits']} commits, "
          f"{result['reloads']} hot-reloads, "
          f"rss slope {result['rss_slope_bytes_per_s']} B/s "
          f"(max {result['rss_slope_max']:.0f}), zero firing alerts, "
          f"numerics quiet ({result['numerics_steps']} steps, 0 "
          "non-finite windows), watchdog silent, scrapes parsed")


if __name__ == "__main__":
    main()
