"""Failpoints: named, deterministic fault-injection sites (ISSUE 8).

Every failure mode this stack handles — SIGKILL-mid-save, corrupt
manifests, wedged batcher workers, dead kvstore peers — used to be
reproduced ad hoc (sleep-widened races, parent-timed kills).  A
failpoint turns the injection point into a NAME:

    from ..chaos.failpoints import failpoint
    ...
    failpoint("checkpoint/writer/pre_rename")

Disabled (the default, and always when ``MXNET_CHAOS`` is unset) a call
is one module-global check — the same near-zero bar as a disabled
telemetry span (< 1 us, test-asserted), so the hooks stay in the hot
paths unconditionally and production behavior is bit-identical.

Armed — programmatically (:func:`arm`) or via ``MXNET_CHAOS`` spec
strings (:func:`configure`) — a site fires one of five actions:

* ``raise``      — raise a typed error (:class:`ChaosInjectedError` by
                   default, or any builtin exception by name);
* ``delay``      — sleep the calling thread for N seconds;
* ``wedge``      — block until :func:`release` (or the wedge timeout,
                   after which it raises — no scenario may end in a
                   hang, see docs/chaos.md);
* ``corrupt``    — for byte-producing sites (:func:`failpoint_bytes`):
                   deterministically flip bytes, or truncate;
* ``kill``       — SIGKILL the current process (``kill(mark)`` only
                   records the fatal site, for in-process tests of the
                   machinery around a kill).

Determinism: triggers are **hit-count based** (``hits=N`` fires from the
Nth call on, ``count=M`` fires at most M times) and any probabilistic
trigger (``prob=p``) draws from a per-site ``random.Random`` seeded by
``MXNET_CHAOS_SEED`` — the same spec string replays the same faults at
the same call counts, every run.

Spec grammar (``;``-separated arms)::

    site=action[(value)][:key=val[:key=val...]]

    MXNET_CHAOS="checkpoint/writer/pre_rename=kill"
    MXNET_CHAOS="serving/batcher/worker=raise(RuntimeError):hits=3:count=1"
    MXNET_CHAOS="kvstore/client/rpc=delay(0.2):prob=0.5"
    MXNET_CHAOS="checkpoint/writer/manifest=corrupt(flip):hits=2"

Every injection lands in the ``mxnet_chaos_injections_total{site,action}``
telemetry lane, so a chaos run's fault schedule is auditable from the
same ``/metrics`` scrape as its effects.
"""
from __future__ import annotations

import builtins
import logging
import os
import random
import signal
import threading
import time
import zlib

from ..base import MXNetError

log = logging.getLogger("mxnet_tpu.chaos")

ACTIONS = ("raise", "delay", "wedge", "corrupt", "kill")

# module-global fast gate: the ONLY thing a disabled failpoint() touches
_any_armed = False

_lock = threading.Lock()
_arms = {}          # site -> _Arm
_hits = {}          # site -> total failpoint() calls while armed
_fatal_site = None  # site whose kill action fired (mark or pre-SIGKILL)

# the static site catalog (docs/chaos.md renders this); calling a site
# not listed here still works — it self-registers with an empty doc, so
# ad-hoc scenario sites never error
SITES = {
    "checkpoint/writer/pre_tmp_write":
        "background writer, before any byte of step-NNNNNN.tmp is written",
    "checkpoint/writer/post_tmp_write":
        "background writer, after the data file is written+fsynced but "
        "before the manifest",
    "checkpoint/writer/manifest":
        "bytes hook on the serialized MANIFEST.json (corrupt-bytes "
        "exercises the checksum/verify path)",
    "checkpoint/writer/pre_rename":
        "background writer, immediately before the atomic commit rename",
    "checkpoint/gc/remove":
        "retention GC, before each old step directory is removed",
    "serving/batcher/submit":
        "DynamicBatcher.submit, after validation, before enqueue",
    "serving/batcher/worker":
        "batcher worker loop, inside the watchdog arm, before the batch "
        "runs (raise kills the worker; wedge stalls it)",
    "serving/router/dispatch":
        "ReplicaPool.submit, before the request is handed to the chosen "
        "replica (raise exercises the spill path: the router re-routes "
        "to the next-least-loaded sibling)",
    "serving/generation/decode":
        "generation engine loop, before the fixed-shape decode dispatch "
        "(raise kills the loop: active sessions fail typed-retryable "
        "and resume on a sibling engine, slots and ledger pages "
        "provably release — the replica_kill_mid_generation scenario)",
    "serving/repository/poll":
        "ModelRepository.poll_checkpoint, before the committed-step scan",
    "serving/repository/warm_hook":
        "repository warm hooks, before each hook runs",
    "compile/cache/artifact":
        "inside guarded_compile: a raise here simulates a corrupt/"
        "truncated persistent-compile-cache artifact failing "
        "deserialization",
    "compile/ladder/load":
        "planner.load_ladder, before the persisted ladder file is read",
    "kernels/tune":
        "kernel autotuner: call hook fires before each candidate config "
        "is gated+measured (raise aborts the search — partial results "
        "discarded, lookup falls down the ladder); bytes hook fires on "
        "the serialized winners json (corrupt exercises the "
        "quarantine-on-load path)",
    "kvstore/client/rpc":
        "KVClient, before each RPC frame is sent (raise exercises the "
        "bounded-retry path; kill drops the worker mid-epoch)",
    "kvstore/server/heartbeat":
        "KVServer, on receipt of each worker heartbeat (raise drops the "
        "connection, so the worker reads as dead)",
    "fleet/push":
        "fleet telemetry push path (FleetReporter.push_now and the "
        "fleet simulator's synthetic ranks), after delta encoding, "
        "before the push reaches the leader (raise = the push is "
        "dropped and the rank's snapshot ages; delay = the push "
        "arrives late — the rollup_under_churn scenario)",
    "io/stage":
        "io.stage_batch / stage_super_batch, before the host->device put",
    "io/reader/read":
        "io_pipeline reader worker, per batch read (delay = slow "
        "reader; raise = the reader dies and its shards rebalance onto "
        "the survivors — exactly-once, typed DataReaderError only when "
        "ALL readers are gone)",
    "train/scan_window":
        "Module scanned fit, at each window boundary before the scan "
        "dispatch (kill here is the SIGKILL-mid-window scenario)",
    "train/poison_grad":
        "numerics observatory injection: a raise arm poisons THIS "
        "window's gradients with NaN inside the donated trace (raise "
        "with value 'inf' injects Inf) — armed only while "
        "MXNET_NUMERICS watches, proving non-finite detection, the "
        "nonfinite_window alert, and the forensic dump end to end",
    "parallel/collective":
        "mesh fused train step, at the host-side window boundary before "
        "the donated shard_map dispatch (delay/wedge stalls the mesh "
        "step under the watchdog's eye; kill + boundary-checkpoint "
        "restore onto a RESIZED mesh is the elastic-resume scenario)",
    "multihost/heartbeat":
        "multi-host runtime heartbeat loop, before each beat to the "
        "control server (raise skips beats so this rank ages toward "
        "'lost' — survivors must take typed PeerLostError paths)",
    "multihost/peer_loss":
        "multi-host fused step, at the window-boundary probe before "
        "the rendezvous (kill here is the host-vanishes-mid-training "
        "preemption scenario: survivors checkpoint the boundary and "
        "the elastic launcher respawns the survivor mesh)",
}


class ChaosInjectedError(MXNetError):
    """The typed error an armed ``raise`` failpoint injects.

    Carries ``site`` so handlers (and assertions) can tell an injected
    fault from an organic one; ``retryable`` is True — the injection
    models a transient fault.
    """

    retryable = True

    def __init__(self, site, detail=""):
        self.site = site
        super().__init__(
            f"chaos: injected fault at failpoint {site!r}"
            + (f" ({detail})" if detail else ""))


class ChaosSpecError(MXNetError):
    """A MXNET_CHAOS spec string failed to parse."""


class _Arm:
    __slots__ = ("site", "action", "value", "hits", "count", "prob",
                 "timeout", "fired", "rng", "event")

    def __init__(self, site, action, value=None, hits=1, count=None,
                 prob=1.0, timeout=None, seed=None):
        if action not in ACTIONS:
            raise ChaosSpecError(
                f"chaos: unknown action {action!r} for site {site!r}; "
                f"expected one of {ACTIONS}")
        self.site = site
        self.action = action
        self.value = value
        self.hits = max(1, int(hits))
        self.count = None if count is None else max(1, int(count))
        self.prob = float(prob)
        self.timeout = timeout
        self.fired = 0
        if seed is None:
            seed = _seed()
        # per-site deterministic stream: the same spec replays the same
        # probabilistic schedule and the same corruption bytes (crc32,
        # not hash() — PYTHONHASHSEED must not change the schedule)
        self.rng = random.Random((seed << 32)
                                 ^ zlib.crc32(site.encode("utf-8")))
        self.event = threading.Event()  # wedge release


def _seed():
    from .. import config as _config
    return int(_config.get("MXNET_CHAOS_SEED"))


def _wedge_timeout():
    from .. import config as _config
    return float(_config.get("MXNET_CHAOS_WEDGE_TIMEOUT_S"))


def _injection_counter():
    from .. import telemetry as _telemetry
    return _telemetry.REGISTRY.counter(
        "mxnet_chaos_injections_total",
        "chaos failpoint injections fired, by site and action")


# -- arming ------------------------------------------------------------------
def arm(site, action, value=None, hits=1, count=None, prob=1.0,
        timeout=None):
    """Arm one failpoint.  ``hits``: fire from the Nth call on (1-based);
    ``count``: auto-disarm after firing this many times (None = every
    eligible hit); ``prob``: per-eligible-hit firing probability, drawn
    from the seeded per-site stream; ``timeout``: wedge-only override of
    ``MXNET_CHAOS_WEDGE_TIMEOUT_S``."""
    global _any_armed
    a = _Arm(str(site), action, value=value, hits=hits, count=count,
             prob=prob, timeout=timeout)
    with _lock:
        SITES.setdefault(a.site, "")
        _arms[a.site] = a
        _hits.setdefault(a.site, 0)
        _any_armed = True
    log.info("chaos: armed %s=%s%s hits=%d count=%s prob=%g", a.site,
             a.action, f"({a.value})" if a.value is not None else "",
             a.hits, a.count, a.prob)
    return a


def disarm(site):
    """Disarm one site (releasing any thread wedged on it)."""
    global _any_armed
    with _lock:
        a = _arms.pop(str(site), None)
        if not _arms:
            _any_armed = False
    if a is not None:
        a.event.set()
    return a is not None


def release(site):
    """Release threads wedged at ``site`` (the arm stays armed; with a
    ``count`` it has already been consumed by the firing)."""
    with _lock:
        a = _arms.get(str(site))
    if a is not None:
        a.event.set()


def reset():
    """Disarm everything, release every wedge, forget hit counts and the
    fatal marker — the between-scenarios (and between-tests) broom."""
    global _any_armed, _fatal_site
    with _lock:
        arms = list(_arms.values())
        _arms.clear()
        _hits.clear()
        _fatal_site = None
        _any_armed = False
    for a in arms:
        a.event.set()


def configure(spec):
    """Parse and arm a ``MXNET_CHAOS``-style spec string; returns the
    list of armed sites.  An empty/None spec arms nothing."""
    armed = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ChaosSpecError(
                f"chaos: bad arm {part!r} (expected site=action[...])")
        site, rhs = part.split("=", 1)
        fields = rhs.split(":")
        head, opts = fields[0].strip(), fields[1:]
        value = None
        if "(" in head:
            if not head.endswith(")"):
                raise ChaosSpecError(f"chaos: unbalanced parens in {part!r}")
            head, value = head.split("(", 1)
            value = value[:-1]
        kw = {}
        for opt in opts:
            if "=" not in opt:
                raise ChaosSpecError(
                    f"chaos: bad option {opt!r} in {part!r} "
                    "(expected key=val)")
            k, v = opt.split("=", 1)
            k = k.strip()
            if k in ("hits", "count"):
                kw[k] = int(v)
            elif k in ("prob", "timeout"):
                kw[k] = float(v)
            else:
                raise ChaosSpecError(
                    f"chaos: unknown option {k!r} in {part!r} (expected "
                    "hits/count/prob/timeout)")
        arm(site.strip(), head.strip(), value=value, **kw)
        armed.append(site.strip())
    return armed


def configure_from_env():
    """Arm from ``MXNET_CHAOS`` (no-op when unset) — called at chaos
    package import, so a child process armed via its environment needs
    no code change."""
    from .. import config as _config
    spec = _config.get("MXNET_CHAOS")
    if spec:
        return configure(spec)
    return []


# -- introspection -----------------------------------------------------------
def active():
    """True when at least one site is armed."""
    return _any_armed


def arms():
    """{site: {action, hits, count, fired, ...}} for every armed site."""
    with _lock:
        return {s: {"action": a.action, "value": a.value, "hits": a.hits,
                    "count": a.count, "prob": a.prob, "fired": a.fired}
                for s, a in _arms.items()}


def hit_counts():
    """{site: total failpoint() calls observed while armed}."""
    with _lock:
        return dict(_hits)


def fatal_site():
    """The site whose ``kill`` action fired (None otherwise).  Set just
    before the SIGKILL lands (and is all a ``kill(mark)`` arm does), so
    liveness surfaces — ``/healthz`` — can report the process as doomed."""
    with _lock:
        return _fatal_site


def sites():
    """The failpoint catalog: {site: doc} (docs/chaos.md table source)."""
    with _lock:
        return dict(SITES)


# -- the hooks ---------------------------------------------------------------
def failpoint(site):
    """The injection hook — a no-op global check unless chaos is armed."""
    if not _any_armed:
        return
    _fire(site, None)


def failpoint_bytes(site, data):
    """Byte-producing sites route their payload through this hook so a
    ``corrupt`` arm can mangle it; identity when chaos is off."""
    if not _any_armed:
        return data
    return _fire(site, data)


def _eligible(site):
    """Trigger bookkeeping under the lock; returns the arm iff it should
    fire for this call."""
    global _any_armed
    with _lock:
        a = _arms.get(site)
        if a is None:
            return None
        _hits[site] = n = _hits.get(site, 0) + 1
        if n < a.hits:
            return None
        if a.count is not None and a.fired >= a.count:
            return None
        if a.prob < 1.0 and a.rng.random() >= a.prob:
            return None
        a.fired += 1
        if a.count is not None and a.fired >= a.count and \
                a.action != "wedge":
            # consumed: drop the arm so the fast gate can re-close
            del _arms[site]
            if not _arms:
                _any_armed = False
        return a


def _fire(site, data):
    global _fatal_site
    a = _eligible(site)
    if a is None:
        return data
    try:
        _injection_counter().inc(labels={"site": site, "action": a.action})
    except Exception:  # graftlint: disable=swallowed-error -- injection accounting must never mask the injection itself
        pass
    try:
        from ..telemetry import flight as _flight
        _flight.record("chaos", "inject", severity="error", site=site,
                       action=a.action, hit=_hits.get(site, 0))
    except Exception:  # graftlint: disable=swallowed-error -- flight accounting must never mask the injection itself
        pass
    log.warning("chaos: firing %s at %s (hit %d)", a.action, site,
                _hits.get(site, 0))
    if a.action == "raise":
        raise _make_error(site, a.value)
    if a.action == "delay":
        time.sleep(float(a.value or 0.05))
        return data
    if a.action == "wedge":
        timeout = a.timeout if a.timeout is not None else _wedge_timeout()
        if not a.event.wait(timeout):
            raise ChaosInjectedError(
                site, f"wedge exceeded {timeout}s without release() — "
                "raising instead of hanging forever")
        return data
    if a.action == "corrupt":
        if data is None:
            raise ChaosInjectedError(
                site, "corrupt action armed on a non-bytes failpoint; "
                "use failpoint_bytes sites (see docs/chaos.md catalog)")
        return _corrupt(a, data)
    if a.action == "kill":
        with _lock:
            _fatal_site = site
        if a.value == "mark":
            return data
        log.error("chaos: SIGKILL self at %s", site)
        # flush the flight ring BEFORE the SIGKILL lands: even a
        # vanished host leaves its event history for the postmortem
        # bundle (the injection above is the ring's last entry)
        try:
            from ..telemetry import flight as _flight
            _flight.auto_dump(f"chaos-kill:{site}")
        except Exception:  # graftlint: disable=swallowed-error -- the kill must land even if the dump path is broken
            pass
        os.kill(os.getpid(), signal.SIGKILL)
    return data


def _make_error(site, name):
    if not name:
        return ChaosInjectedError(site)
    cls = getattr(builtins, str(name), None)
    if isinstance(cls, type) and issubclass(cls, BaseException):
        return cls(f"chaos: injected {name} at failpoint {site!r}")
    return ChaosInjectedError(site, f"unknown error class {name!r}")


def _corrupt(a, data):
    data = bytes(data)
    if a.value == "truncate":
        return data[:len(data) // 2]
    if not data:
        return data
    # deterministic bit damage: ~1% of bytes (at least one) XOR 0xFF,
    # positions drawn from the arm's seeded stream
    out = bytearray(data)
    n = max(1, len(out) // 100)
    for _ in range(n):
        out[a.rng.randrange(len(out))] ^= 0xFF
    return bytes(out)
