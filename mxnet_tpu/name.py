"""Automatic naming support (parity: python/mxnet/name.py NameManager/Prefix)."""
from __future__ import annotations

import threading


class NameManager:
    """Name manager to do automatic naming."""

    _current = threading.local()

    def __init__(self, prefix=None):
        self._counter = {}
        self._old_manager = None
        self._prefix = prefix

    def get(self, name, hint):
        if name:
            # scope prefix applies to explicit names too (parity: name.py
            # Prefix.get used by gluon _BlockScope)
            return self._prefix + name if self._prefix else name
        if hint not in self._counter:
            self._counter[hint] = 0
        name = f"{hint}{self._counter[hint]}"
        self._counter[hint] += 1
        if self._prefix:
            name = self._prefix + name
        return name

    def __enter__(self):
        if not hasattr(NameManager._current, "value"):
            NameManager._current.value = NameManager()
        self._old_manager = NameManager._current.value
        NameManager._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_manager
        NameManager._current.value = self._old_manager

    @staticmethod
    def _current_value():
        if not hasattr(NameManager._current, "value"):
            NameManager._current.value = NameManager()
        return NameManager._current.value


class Prefix(NameManager):
    """Always prepend a prefix to all names."""

    def __init__(self, prefix):
        super().__init__()
        self._name_prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._name_prefix + name
