"""Testing utilities.

Parity with reference python/mxnet/test_utils.py: numpy-as-oracle forward
checks, central numeric-gradient checker for backward, tolerance helper, and
a check_consistency-style cross-dtype harness (SURVEY.md §4 key takeaway).
"""
from __future__ import annotations

import numpy as np

from . import autograd
from . import ndarray as nd
from .context import cpu, current_context


def default_context():
    return current_context()


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-7, names=("a", "b")):
    a = a.asnumpy() if isinstance(a, nd.NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, nd.NDArray) else np.asarray(b)
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                               err_msg=f"{names[0]} vs {names[1]}")


def same(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


def rand_ndarray(shape, stype="default", density=None, dtype=None, ctx=None):
    dtype = dtype or np.float32
    dense = np.random.uniform(-1, 1, size=shape).astype(dtype)
    if stype == "default":
        return nd.array(dense, ctx=ctx)
    if density is not None:
        mask = np.random.uniform(0, 1, size=shape) < density
        dense = dense * mask
    from .ndarray import sparse
    return sparse.array(dense, stype=stype, ctx=ctx, dtype=dtype)


def numeric_grad(f, inputs, eps=1e-4):
    """Central-difference numeric gradient of scalar-valued f(list[np]) -> float."""
    grads = []
    for i, x in enumerate(inputs):
        g = np.zeros_like(x, dtype=np.float64)
        flat = x.reshape(-1)
        gf = g.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = f(inputs)
            flat[j] = orig - eps
            fm = f(inputs)
            flat[j] = orig
            gf[j] = (fp - fm) / (2 * eps)
        grads.append(g)
    return grads


def check_numeric_gradient(op_fn, input_arrays, rtol=1e-2, atol=1e-3, eps=1e-3):
    """Compare autograd backward of sum(op_fn(*inputs)) against numeric grads.

    Parity: check_numeric_gradient (reference test_utils.py:860), but the
    oracle loop runs the same jitted op on float64-upcast host values.
    """
    np_inputs = [np.asarray(a, dtype=np.float64) for a in input_arrays]

    def scalar_f(nps):
        args = [nd.array(x.astype(np.float32)) for x in nps]
        out = op_fn(*args)
        return float(out.sum().asscalar())

    expected = numeric_grad(scalar_f, [x.copy() for x in np_inputs], eps=eps)

    args = [nd.array(x.astype(np.float32)) for x in np_inputs]
    for a in args:
        a.attach_grad()
    with autograd.record():
        out = op_fn(*args)
        s = out.sum()
    s.backward()
    for a, e in zip(args, expected):
        assert_almost_equal(a.grad, e.astype(np.float32), rtol=rtol, atol=atol)


def consistency_devices():
    """The jax devices check_consistency crosses: the host CPU always,
    plus the TPU chip when its backend is initialized and reachable
    (skipped cleanly otherwise — the reference pattern is
    tests/python/gpu/test_operator_gpu.py rerunning the CPU suite on
    GPU; here one harness crosses backends in-process)."""
    import jax
    devs = []
    try:
        devs.append(jax.devices("cpu")[0])
    except RuntimeError:
        pass
    for plat in ("tpu", "axon"):  # axon = the TPU relay platform name
        try:
            devs.append(jax.devices(plat)[0])
            break
        except Exception:
            pass  # backend absent/unreachable: cpu-only run
    return devs


def rand_shape_2d(dim0=10, dim1=10):
    """Random 2-D shape (parity: test_utils.py rand_shape_2d)."""
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=num_dim))


def almost_equal(a, b, rtol=1e-5, atol=1e-7):
    a = a.asnumpy() if isinstance(a, nd.NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, nd.NDArray) else np.asarray(b)
    return np.allclose(a, b, rtol=rtol, atol=atol)


def almost_equal_ignore_nan(a, b, rtol=1e-5, atol=1e-7):
    """Equality where positions that are NaN in BOTH arrays match
    (parity: test_utils.py almost_equal_ignore_nan)."""
    a = a.asnumpy() if isinstance(a, nd.NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, nd.NDArray) else np.asarray(b)
    nan_mask = np.isnan(a)
    if not np.array_equal(nan_mask, np.isnan(b)):
        return False
    return np.allclose(a[~nan_mask], b[~nan_mask], rtol=rtol, atol=atol)


def assert_exception(f, exception_type, *args, **kwargs):
    """f(*args, **kwargs) must raise exception_type (parity:
    test_utils.py assert_exception)."""
    try:
        f(*args, **kwargs)
    except exception_type:
        return
    raise AssertionError(
        f"{f} did not raise {exception_type.__name__}")


def check_symbolic_forward(sym, inputs, expected, rtol=1e-4, atol=1e-5,
                           aux_states=None, ctx=None):
    """Bind a symbol with the given input arrays and compare every output
    (parity: test_utils.py check_symbolic_forward — the workhorse of the
    reference's test_operator.py)."""
    from .context import cpu as _cpu
    ctx = ctx or _cpu()
    arg_names = sym.list_arguments()
    args = {n: nd.array(np.asarray(x, np.float32))
            for n, x in zip(arg_names, inputs)}
    aux = None
    if aux_states is not None:
        aux = {n: nd.array(np.asarray(x, np.float32))
               for n, x in zip(sym.list_auxiliary_states(), aux_states)}
    ex = sym.bind(ctx, args, aux_states=aux)
    outs = ex.forward()
    expected = expected if isinstance(expected, (list, tuple)) else [expected]
    for o, w in zip(outs, expected):
        np.testing.assert_allclose(o.asnumpy().astype(np.float64),
                                   np.asarray(w, np.float64),
                                   rtol=rtol, atol=atol)
    return [o.asnumpy() for o in outs]


def check_symbolic_backward(sym, inputs, out_grads, expected_grads,
                            rtol=1e-4, atol=1e-5, ctx=None):
    """Bind, forward, backward with given head gradients, compare arg
    grads in list_arguments order (parity: test_utils.py
    check_symbolic_backward)."""
    from .context import cpu as _cpu
    ctx = ctx or _cpu()
    arg_names = sym.list_arguments()
    args = {n: nd.array(np.asarray(x, np.float32))
            for n, x in zip(arg_names, inputs)}
    grads = {n: nd.zeros(a.shape, dtype=a.dtype)
             for n, a in args.items()}
    ex = sym.bind(ctx, args, args_grad=grads, grad_req="write")
    ex.forward(is_train=True)
    ograds = [nd.array(np.asarray(g, np.float32))
              for g in (out_grads if isinstance(out_grads, (list, tuple))
                        else [out_grads])]
    ex.backward(ograds if len(ograds) > 1 else ograds[0])
    expected = expected_grads if isinstance(expected_grads, (list, tuple)) \
        else [expected_grads]
    got = []
    for n, w in zip(arg_names, expected):
        if w is None:
            continue
        g = ex.grad_dict[n]
        np.testing.assert_allclose(g.asnumpy().astype(np.float64),
                                   np.asarray(w, np.float64),
                                   rtol=rtol, atol=atol,
                                   err_msg=f"grad mismatch for {n}")
        got.append(g.asnumpy())
    return got


def get_mnist_like(num_train=3000, num_val=500, translate=False, seed=7):
    """Synthetic MNIST-shaped classification data for convergence gates.

    Zero-egress stand-in for test_utils.get_mnist() (reference
    test_utils.py:1565, which downloads the real files). Two flavors:

    * ``translate=False``: each class is a fixed random 28x28 prototype
      plus gaussian noise — linearly separable, the MLP gate.
    * ``translate=True``: each class is a fixed 10x10 patch stamped at a
      random position on an empty 28x28 canvas plus noise — translation
      invariance is required, so convolution+pooling genuinely matters
      (a same-budget MLP plateaus well below the conv gate's threshold).

    Returns dict(train_data, train_label, test_data, test_label) with
    data shaped (N, 1, 28, 28) float32 in [0, 1], matching get_mnist().
    """
    rng = np.random.RandomState(seed)
    n = num_train + num_val
    y = rng.randint(0, 10, n)
    if not translate:
        protos = rng.rand(10, 1, 28, 28).astype(np.float32)
        x = protos[y] + rng.randn(n, 1, 28, 28).astype(np.float32) * 0.35
    else:
        patches = (rng.rand(10, 10, 10) > 0.5).astype(np.float32)
        x = rng.rand(n, 1, 28, 28).astype(np.float32) * 0.15
        rows = rng.randint(0, 28 - 10, n)
        cols = rng.randint(0, 28 - 10, n)
        for i in range(n):
            x[i, 0, rows[i]:rows[i] + 10, cols[i]:cols[i] + 10] += \
                patches[y[i]] * 0.85
    x = np.clip(x, 0.0, 1.0)
    y = y.astype(np.float32)
    return {"train_data": x[:num_train], "train_label": y[:num_train],
            "test_data": x[num_train:], "test_label": y[num_train:]}


def check_consistency(op_fn, input_shapes, dtypes=(np.float32, np.float16),
                      rtol=None, atol=None, devices=None):
    """Run the same op across devices × dtypes and cross-check every leg
    against the (cpu, dtypes[0]) reference (parity: check_consistency
    test_utils.py:1283, which ran [cpu, gpu] × [fp16, fp32, fp64])."""
    import jax
    devices = devices if devices is not None else consistency_devices()
    base_inputs = [np.random.uniform(-1, 1, size=s) for s in input_shapes]
    tol = {np.dtype(np.float16): 1e-2, np.dtype(np.float32): 1e-5}
    try:
        import ml_dtypes
        tol[np.dtype(ml_dtypes.bfloat16)] = 2e-2
    except ImportError:
        pass
    ref = None
    for dev in devices:
        for dt in dtypes:
            args = []
            for x in base_inputs:
                arr = jax.device_put(x.astype(dt), dev)
                args.append(nd.NDArray(arr, current_context()))
            out = op_fn(*args).asnumpy().astype(np.float64)
            if ref is None:
                ref = out    # (devices[0], dtypes[0]) is the oracle leg
                continue
            t = tol.get(np.dtype(dt), 1e-2)
            if dev is not devices[0]:
                # cross-DEVICE legs compare at accelerator matmul
                # precision (TPU f32 dots default to bf16-ish internals)
                t = max(t, 2e-3)
            np.testing.assert_allclose(
                ref, out, rtol=rtol or t, atol=atol or t,
                err_msg=f"inconsistent on {dev.platform}/{dt}")
    return ref
