"""Testing utilities.

Parity with reference python/mxnet/test_utils.py: numpy-as-oracle forward
checks, central numeric-gradient checker for backward, tolerance helper, and
a check_consistency-style cross-dtype harness (SURVEY.md §4 key takeaway).
"""
from __future__ import annotations

import numpy as np

from . import autograd
from . import ndarray as nd
from .context import cpu, current_context


def default_context():
    return current_context()


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-7, names=("a", "b")):
    a = a.asnumpy() if isinstance(a, nd.NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, nd.NDArray) else np.asarray(b)
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                               err_msg=f"{names[0]} vs {names[1]}")


def same(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


def rand_ndarray(shape, stype="default", density=None, dtype=None, ctx=None):
    dtype = dtype or np.float32
    dense = np.random.uniform(-1, 1, size=shape).astype(dtype)
    if stype == "default":
        return nd.array(dense, ctx=ctx)
    if density is not None:
        mask = np.random.uniform(0, 1, size=shape) < density
        dense = dense * mask
    from .ndarray import sparse
    return sparse.array(dense, stype=stype, ctx=ctx, dtype=dtype)


def numeric_grad(f, inputs, eps=1e-4):
    """Central-difference numeric gradient of scalar-valued f(list[np]) -> float."""
    grads = []
    for i, x in enumerate(inputs):
        g = np.zeros_like(x, dtype=np.float64)
        flat = x.reshape(-1)
        gf = g.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = f(inputs)
            flat[j] = orig - eps
            fm = f(inputs)
            flat[j] = orig
            gf[j] = (fp - fm) / (2 * eps)
        grads.append(g)
    return grads


def check_numeric_gradient(op_fn, input_arrays, rtol=1e-2, atol=1e-3, eps=1e-3):
    """Compare autograd backward of sum(op_fn(*inputs)) against numeric grads.

    Parity: check_numeric_gradient (reference test_utils.py:860), but the
    oracle loop runs the same jitted op on float64-upcast host values.
    """
    np_inputs = [np.asarray(a, dtype=np.float64) for a in input_arrays]

    def scalar_f(nps):
        args = [nd.array(x.astype(np.float32)) for x in nps]
        out = op_fn(*args)
        return float(out.sum().asscalar())

    expected = numeric_grad(scalar_f, [x.copy() for x in np_inputs], eps=eps)

    args = [nd.array(x.astype(np.float32)) for x in np_inputs]
    for a in args:
        a.attach_grad()
    with autograd.record():
        out = op_fn(*args)
        s = out.sum()
    s.backward()
    for a, e in zip(args, expected):
        assert_almost_equal(a.grad, e.astype(np.float32), rtol=rtol, atol=atol)


def check_consistency(op_fn, input_shapes, dtypes=(np.float32, np.float16),
                      rtol=None, atol=None):
    """Run the same op across dtypes and cross-check (parity:
    check_consistency test_utils.py:1283, which ran cpu/gpu × fp16/32/64)."""
    base_inputs = [np.random.uniform(-1, 1, size=s) for s in input_shapes]
    outs = []
    for dt in dtypes:
        args = [nd.array(x.astype(dt)) for x in base_inputs]
        outs.append(op_fn(*args).asnumpy().astype(np.float64))
    ref = outs[0]
    tol = {np.dtype(np.float16): 1e-2, np.dtype(np.float32): 1e-5}
    for o, dt in zip(outs[1:], dtypes[1:]):
        t = tol.get(np.dtype(dt), 1e-2)
        np.testing.assert_allclose(ref, o, rtol=rtol or t, atol=atol or t)
