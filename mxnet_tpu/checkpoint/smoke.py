"""Checkpoint smoke for CI: save -> SIGKILL the writer mid-save ->
restore -> verify (ci/run.sh).

A child process commits step 1, then starts saving step 2 with
MXNET_CKPT_WRITE_DELAY_MS widening the ``step-000002.tmp`` window; the
parent SIGKILLs it the moment the tmp directory appears.  The atomic-
commit contract under test: ``latest()`` still points at step 1, its
checksums verify, and a fresh manager over the same directory sweeps the
residue and commits step 2 cleanly.

Run: JAX_PLATFORMS=cpu python -m mxnet_tpu.checkpoint.smoke
"""
from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time

import numpy as np

_VICTIM = """
import os, sys
import numpy as np
from mxnet_tpu.checkpoint import CheckpointManager

d = sys.argv[1]
mgr = CheckpointManager(d, keep_last=0)
arrs = {"w%d" % i: np.full((256, 256), float(i), np.float32)
        for i in range(8)}
mgr.save(1, arrays=arrs, extra={"phase": "committed"}, block=True)
print("STEP1-COMMITTED", flush=True)
os.environ["MXNET_CKPT_WRITE_DELAY_MS"] = "400"
mgr.save(2, arrays=arrs, block=True)   # parent kills us mid-write
print("STEP2-COMMITTED", flush=True)   # must never print
"""


def main():
    from . import (CheckpointCorruptError, CheckpointManager,
                   committed_steps, restore, step_dir)
    tmpdir = tempfile.mkdtemp(prefix="ckpt-smoke-")
    script = os.path.join(tmpdir, "victim.py")
    # graftlint: disable=torn-write -- ephemeral script in a fresh tmpdir, consumed once below
    with open(script, "w") as f:
        f.write(_VICTIM)
    ckdir = os.path.join(tmpdir, "ckpt")
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.Popen([sys.executable, script, ckdir], env=env,
                            stdout=subprocess.PIPE, text=True)
    try:
        tmp_step2 = step_dir(ckdir, 2) + ".tmp"
        deadline = time.time() + 120
        while not os.path.isdir(tmp_step2):
            assert proc.poll() is None, "victim exited before step-2 save"
            assert time.time() < deadline, "step-2 tmp dir never appeared"
            time.sleep(0.005)
        proc.kill()  # SIGKILL mid-write: no cleanup, no atexit
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()

    # the torn step-2 attempt must be invisible; step 1 must verify
    assert committed_steps(ckdir) == [1], committed_steps(ckdir)
    ckpt = restore(ckdir)  # checksum-verified
    assert ckpt.step == 1 and ckpt.metadata["extra"]["phase"] == "committed"
    np.testing.assert_array_equal(ckpt.arrays["w3"],
                                  np.full((256, 256), 3.0, np.float32))

    # a fresh manager sweeps the residue and step 2 commits cleanly
    with CheckpointManager(ckdir, keep_last=0) as mgr:
        assert not os.path.isdir(tmp_step2)
        mgr.save(2, arrays={"w": np.ones((4,), np.float32)}, block=True)
        assert mgr.steps() == [1, 2]
        mgr.restore(2)
    print("checkpoint smoke OK: torn save invisible, committed step "
          "verified, recovery clean")


if __name__ == "__main__":
    main()
