"""mxnet_tpu.checkpoint — async, atomic, sharded checkpointing.

The Orbax/TensorStore-shaped answer to the north star's failure-survival
requirement: saves snapshot device state on the train thread (cheap
device->host copy) and serialize/fsync on a background writer; commits
are write-into-``step-NNNNNN.tmp/`` + manifest-with-checksums + atomic
rename, so a torn checkpoint is never discoverable; sharded writes put
only host-owned shards on disk and restore re-assembles + re-shards onto
any other dp×tp×pp layout (elastic restore).  See docs/checkpoint.md.
"""
from .core import (Checkpoint, CheckpointCorruptError, CheckpointError,
                   CheckpointNotFoundError, committed_steps, latest_step,
                   load_step, restore, step_dir, step_dirname)
from .manager import CheckpointManager

__all__ = [
    "Checkpoint", "CheckpointCorruptError", "CheckpointError",
    "CheckpointManager", "CheckpointNotFoundError", "committed_steps",
    "latest_step", "load_step", "restore", "step_dir", "step_dirname",
]
