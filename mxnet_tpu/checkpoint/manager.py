"""CheckpointManager: async, atomic, sharded checkpointing.

Save lifecycle (ISSUE 2 tentpole):

1. **snapshot** (caller thread, the only part the train loop pays for):
   every tensor is copied device->host.  jax Arrays are snapshotted
   shard-by-shard — each process copies only the shards it can address,
   deduplicating replicas — so under a ``parallel`` mesh a host writes
   only what it owns.
2. **serialize + commit** (background writer thread): shards stream into
   ``step-NNNNNN.tmp/data-*.bin`` with running sha256, the manifest is
   written last, everything is fsynced, and the tmp directory is
   atomically renamed to ``step-NNNNNN/``.  ``latest()`` therefore only
   ever sees committed steps.
3. **retention**: after each commit, old steps are garbage-collected
   under the ``keep_last`` / ``keep_every`` policy.

Restore re-assembles full host arrays from the shard table and hands
them back as numpy/NDArray — the caller re-shards onto whatever mesh
layout it is running now (elastic restore; see TrainStep.These
restore_checkpoint and docs/checkpoint.md).

One manager instance owns a directory (single writer per directory);
stale ``.tmp``/``.gc``/``.old`` residue from a killed writer is swept on
construction.
"""
from __future__ import annotations

import json
import logging
import os
import pickle
import queue
import shutil
import threading
import time

import numpy as np

from ..chaos.failpoints import failpoint as _failpoint
from ..chaos.failpoints import failpoint_bytes as _failpoint_bytes
from .core import (MANIFEST, SCHEMA_VERSION, TMP_SUFFIX, Checkpoint,
                   CheckpointCorruptError, CheckpointError,
                   CheckpointNotFoundError, _fsync_path, _sha256,
                   committed_steps, latest_step, restore, step_dir,
                   step_dirname)

_STALE_SUFFIXES = (TMP_SUFFIX, ".gc", ".old")


def _cfg(name):
    from ..config import get
    return get(name)


class _SaveFuture:
    """Completion handle for an async save."""

    def __init__(self, step):
        self.step = int(step)
        self._done = threading.Event()
        self._exc = None

    def _set(self, exc):
        self._exc = exc
        self._done.set()

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Block until the save committed; raises the writer's error."""
        if not self._done.wait(timeout):
            raise CheckpointError(
                f"save of step {self.step} not committed within {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self.step


class _SaveJob:
    __slots__ = ("step", "tensors", "blobs", "symbol_json", "metadata",
                 "mesh", "future", "snapshot_ms", "nbytes")

    def __init__(self, step, tensors, blobs, symbol_json, metadata, mesh,
                 future, snapshot_ms, nbytes):
        self.step = step
        self.tensors = tensors      # [(name, dtype_str, shape, shards)]
        self.blobs = blobs          # {name: bytes}
        self.symbol_json = symbol_json
        self.metadata = metadata
        self.mesh = mesh
        self.future = future
        self.snapshot_ms = snapshot_ms
        self.nbytes = nbytes


def _norm_index(index, shape):
    """jax shard index (tuple of slices) -> [[start, stop], ...]."""
    out = []
    for d, sl in enumerate(index):
        start = 0 if sl.start is None else int(sl.start)
        stop = shape[d] if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    for d in range(len(index), len(shape)):
        out.append([0, shape[d]])
    return out


def _snapshot_one(name, value):
    """-> (name, dtype_str, shape, [(index, host np.ndarray)]).

    The device->host copy happens HERE, on the caller thread — that is
    the entirety of what a save blocks the train loop for.  jax Arrays
    contribute only their addressable shards (replicas deduplicated by
    index), so multi-host meshes naturally partition the write.
    """
    from ..ndarray import NDArray
    if isinstance(value, NDArray):
        value = value._data
    try:
        import jax
        is_jax = isinstance(value, jax.Array)
    except ImportError:
        is_jax = False
    if is_jax:
        shape = tuple(int(s) for s in value.shape)
        dtype = np.dtype(value.dtype)
        shards = []
        seen = set()
        for sh in value.addressable_shards:
            index = _norm_index(sh.index, shape)
            key = tuple(map(tuple, index))
            if key in seen:
                continue  # replica of a shard already snapshotted
            seen.add(key)
            shards.append((index, np.asarray(sh.data)))
        if not shards:
            raise CheckpointError(
                f"tensor {name!r} has no addressable shards on this host")
        return (name, dtype.name, shape, shards)
    arr = np.array(value)  # owns its memory: caller may mutate theirs
    shape = tuple(arr.shape)
    return (name, arr.dtype.name, shape,
            [([[0, s] for s in shape], arr)])


class CheckpointManager:
    """Owns the save/restore lifecycle for one checkpoint directory.

    Parameters default from the ``MXNET_CKPT_*`` config tier
    (``mx.config.describe()``):

    * ``async_save``  — serialize/fsync on a background writer so
      ``save()`` blocks only for the device->host snapshot.
    * ``keep_last``   — committed steps retained (0 = keep everything).
    * ``keep_every``  — additionally keep every Nth step forever.
    * ``legacy_prefix`` — also mirror each commit to
      ``{prefix}-symbol.json`` / ``{prefix}-{step:04d}.params`` (the
      reference checkpoint format) so legacy tooling keeps working.
    """

    def __init__(self, directory, keep_last=None, keep_every=None,
                 async_save=None, legacy_prefix=None, host_id=None,
                 num_hosts=None, logger=None):
        self.directory = str(directory)
        self.keep_last = (_cfg("MXNET_CKPT_KEEP_LAST") if keep_last is None
                          else int(keep_last))
        self.keep_every = (_cfg("MXNET_CKPT_KEEP_EVERY") if keep_every is None
                           else int(keep_every))
        self.async_save = (_cfg("MXNET_CKPT_ASYNC") if async_save is None
                           else bool(async_save))
        self.legacy_prefix = legacy_prefix
        if host_id is None or num_hosts is None:
            host_id, num_hosts = self._detect_hosts(host_id, num_hosts)
        self.host_id = int(host_id)
        self.num_hosts = int(num_hosts)
        self.logger = logger or logging.getLogger("mxnet_tpu.checkpoint")
        os.makedirs(self.directory, exist_ok=True)
        if self.host_id == 0:
            self._sweep_stale()
        self._stats_data = {"saves": 0, "failures": 0, "gc_removed": 0,
                            "gc_errors": 0,
                            "last_save_blocking_ms": None,
                            "last_save_total_ms": None,
                            "last_save_bytes": None,
                            "last_commit_step": None}
        self._last_commit_t = None  # monotonic time of the last commit
        self._pending = []
        self._lock = threading.Lock()
        self._queue = queue.Queue(maxsize=1)
        self._writer = None
        self._closed = False
        from .. import telemetry as _telemetry
        _telemetry.register_checkpoint_manager(self)  # weakly held

    @property
    def _stats(self):
        """Deprecated: read :meth:`stats` instead.  Kept (as a locked
        COPY — external mutation never lands) so pre-ISSUE-5 callers
        keep working one release."""
        import warnings
        warnings.warn(
            "direct CheckpointManager._stats access is deprecated; use "
            "the public stats() (locked copy + writer-queue/commit-age "
            "gauges)", DeprecationWarning, stacklevel=2)
        with self._lock:
            return dict(self._stats_data)

    @staticmethod
    def _detect_hosts(host_id, num_hosts):
        try:
            import jax
            return (jax.process_index() if host_id is None else host_id,
                    jax.process_count() if num_hosts is None else num_hosts)
        except (ImportError, RuntimeError):
            # jax absent or its runtime not initialized: single host
            return (host_id or 0, num_hosts or 1)

    def _sweep_stale(self):
        """Remove residue a killed writer left behind (single-writer dirs)."""
        for name in os.listdir(self.directory):
            if name.endswith(_STALE_SUFFIXES):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    # -- save ---------------------------------------------------------------
    def save(self, step, arrays=None, blobs=None, symbol=None, epoch=None,
             rng=None, extra=None, mesh=None, block=None):
        """Checkpoint ``arrays`` (+ ``blobs``/``symbol``/metadata) as ``step``.

        ``arrays``: {name: NDArray | np.ndarray | jax.Array} — jax arrays
        are saved shard-wise per their current sharding.  ``blobs``:
        {name: bytes} for opaque state (optimizer pickles, RNG).  Returns
        a future; ``block=True`` (or sync mode) waits for the commit.
        The caller thread only pays for the device->host snapshot; with a
        save already in flight, the next ``save()`` backpressures until
        the writer frees up.
        """
        if self._closed:
            raise CheckpointError("CheckpointManager is closed")
        step = int(step)
        if step < 0:
            raise CheckpointError(f"step must be >= 0, got {step}")
        t0 = time.perf_counter()
        tensors = [_snapshot_one(name, value)
                   for name, value in (arrays or {}).items()]
        job_blobs = {str(k): bytes(v) for k, v in (blobs or {}).items()}
        if rng is not None:
            job_blobs.setdefault("rng", pickle.dumps(rng))
        symbol_json = None
        if symbol is not None:
            symbol_json = symbol if isinstance(symbol, str) else \
                symbol.tojson()
        metadata = {"wall_time": time.time()}
        if epoch is not None:
            metadata["epoch"] = int(epoch)
        if extra is not None:
            metadata["extra"] = extra
        nbytes = sum(arr.nbytes for _n, _d, _s, shards in tensors
                     for _i, arr in shards)
        nbytes += sum(len(b) for b in job_blobs.values())
        fut = _SaveFuture(step)
        mesh_meta = dict(getattr(mesh, "axes", mesh)) if mesh else None
        job = _SaveJob(step, tensors, job_blobs, symbol_json, metadata,
                       mesh_meta, fut, 0.0, nbytes)
        with self._lock:
            self._pending.append(fut)
        if self.async_save:
            self._ensure_writer()
            self._queue.put(job)  # backpressure: one save in flight
            # graftlint: disable=raw-phase-timing -- this IS the save_blocking_ms collection point; it feeds telemetry's ckpt_block lane below
            blocking_ms = (time.perf_counter() - t0) * 1e3
        else:
            blocking_ms = None  # set below: sync save blocks for everything
            try:
                self._write_step(job)
                fut._set(None)
            except BaseException as e:
                fut._set(e if isinstance(e, Exception) else
                         CheckpointError(str(e)))
            # graftlint: disable=raw-phase-timing -- same collection point, sync path
            blocking_ms = (time.perf_counter() - t0) * 1e3
        job.snapshot_ms = blocking_ms
        # _stats_data is shared with the writer thread — every access locks
        with self._lock:
            self._stats_data["last_save_blocking_ms"] = blocking_ms
        self._record_counter("checkpoint:save_blocking_ms",
                             round(blocking_ms, 3))
        # charge the train thread's blocking share to the fit loop's
        # ckpt_block lane (no-op when no step timer is live on this thread)
        from .. import telemetry as _telemetry
        _telemetry.current_step_timer().add("ckpt_block", blocking_ms / 1e3)
        if block or not self.async_save:
            # graftlint: disable=unbounded-wait -- block=True is the caller's explicit completion contract; the writer resolves EVERY future (success or error) per job, and a wall-clock bound here would fail legitimately huge saves
            fut.result()
        return fut

    def _ensure_writer(self):
        # under the lock: concurrent save() callers must not both spawn
        # a writer (two writers would race the same step directories)
        with self._lock:
            if self._writer is None or not self._writer.is_alive():
                self._writer = threading.Thread(
                    target=self._writer_loop, name="ckpt-writer",
                    daemon=True)
                self._writer.start()

    def _writer_loop(self):
        while True:
            job = self._queue.get()
            if job is None:
                return
            try:
                self._write_step(job)
                job.future._set(None)
            except BaseException as e:  # surface via future, keep writing
                with self._lock:
                    self._stats_data["failures"] += 1
                from ..telemetry import flight as _flight
                _flight.record("checkpoint", "save_failed",
                               severity="error", step=job.step,
                               cause=type(e).__name__)
                self.logger.exception(
                    "checkpoint: save of step %d failed", job.step)
                job.future._set(e if isinstance(e, Exception) else
                                CheckpointError(str(e)))

    # -- the write/commit protocol ------------------------------------------
    def _write_step(self, job):
        t0 = time.perf_counter()
        _failpoint("checkpoint/writer/pre_tmp_write")
        delay_s = _cfg("MXNET_CKPT_WRITE_DELAY_MS") / 1e3
        final = step_dir(self.directory, job.step)
        tmp = final + TMP_SUFFIX
        if self.host_id == 0:
            if os.path.isdir(tmp):
                shutil.rmtree(tmp)  # stale attempt for this very step
            os.makedirs(tmp, exist_ok=True)
        else:
            deadline = time.time() + _cfg("MXNET_CKPT_COMMIT_TIMEOUT_S")
            while not os.path.isdir(tmp):  # host 0 creates the tmp dir
                if time.time() > deadline:
                    raise CheckpointError(
                        f"host {self.host_id}: step dir never appeared")
                time.sleep(0.05)

        data_name = f"data-{self.host_id:05d}-of-{self.num_hosts:05d}.bin"
        tensor_entries, blob_entries = {}, {}
        sha = None
        offset = 0
        import hashlib
        sha = hashlib.sha256()
        with open(os.path.join(tmp, data_name), "wb") as f:
            for name, dtype_str, shape, shards in job.tensors:
                entry = tensor_entries.setdefault(
                    name, {"dtype": dtype_str, "shape": list(shape),
                           "shards": []})
                for index, arr in shards:
                    raw = np.ascontiguousarray(arr).tobytes()
                    entry["shards"].append(
                        {"file": data_name, "offset": offset,
                         "nbytes": len(raw), "index": index})
                    f.write(raw)
                    sha.update(raw)
                    offset += len(raw)
                if delay_s:
                    f.flush()
                    time.sleep(delay_s)  # test/debug: widen the tmp window
            for name, raw in job.blobs.items():
                blob_entries[name] = {"file": data_name, "offset": offset,
                                      "nbytes": len(raw)}
                f.write(raw)
                sha.update(raw)
                offset += len(raw)
            f.flush()
            os.fsync(f.fileno())
        files = {data_name: {"sha256": sha.hexdigest(), "bytes": offset}}
        _failpoint("checkpoint/writer/post_tmp_write")

        if self.num_hosts > 1:
            self._write_shard_manifest(tmp, files, tensor_entries,
                                       blob_entries)
            if self.host_id != 0:
                return  # host 0 merges and commits
            files, tensor_entries, blob_entries = self._merge_shards(tmp)

        symbol_file = None
        if job.symbol_json is not None:
            symbol_file = "symbol.json"
            raw = job.symbol_json.encode("utf-8")
            with open(os.path.join(tmp, symbol_file), "wb") as f:
                f.write(raw)
                f.flush()
                os.fsync(f.fileno())
            files[symbol_file] = {"sha256": _sha256(raw), "bytes": len(raw)}

        manifest = {
            "schema_version": SCHEMA_VERSION,
            "step": job.step,
            "metadata": job.metadata,
            "mesh": job.mesh,
            "num_hosts": self.num_hosts,
            "files": files,
            "tensors": tensor_entries,
            "blobs": blob_entries,
        }
        if symbol_file:
            manifest["symbol"] = symbol_file
        if delay_s:
            time.sleep(delay_s)
        # the chaos bytes hook lets a scenario corrupt the manifest as
        # written (the verify path must catch it at restore/poll time)
        raw_manifest = _failpoint_bytes(
            "checkpoint/writer/manifest",
            json.dumps(manifest, indent=1).encode("utf-8"))
        with open(os.path.join(tmp, MANIFEST), "wb") as f:
            f.write(raw_manifest)
            f.flush()
            os.fsync(f.fileno())
        _fsync_path(tmp)

        _failpoint("checkpoint/writer/pre_rename")
        # the commit point: after this rename (atomic on POSIX) the step
        # is discoverable; before it, latest() cannot see it
        if os.path.isdir(final):
            old = final + ".old"
            shutil.rmtree(old, ignore_errors=True)
            os.rename(final, old)
            os.rename(tmp, final)
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.rename(tmp, final)
        _fsync_path(self.directory)

        if self.legacy_prefix is not None:
            self._mirror_legacy(job)
        self._gc()

        # graftlint: disable=raw-phase-timing -- writer-thread commit latency feeds stats()/save_total_ms, which telemetry's checkpoint collector exports
        total_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            self._stats_data["saves"] += 1
            self._stats_data["last_save_total_ms"] = total_ms
            self._stats_data["last_save_bytes"] = job.nbytes
            self._stats_data["last_commit_step"] = job.step
            self._last_commit_t = time.monotonic()
        self._record_counter("checkpoint:save_total_ms", round(total_ms, 3))
        self._record_counter("checkpoint:save_bytes", job.nbytes)
        from ..telemetry import flight as _flight
        _flight.record("checkpoint", "commit", step=job.step,
                       nbytes=job.nbytes, ms=round(total_ms, 1),
                       directory=self.directory)
        self.logger.info("checkpoint: committed step %d (%.1f MB, %.0f ms)",
                         job.step, job.nbytes / 1e6, total_ms)

    def _write_shard_manifest(self, tmp, files, tensors, blobs):
        name = f"shard-{self.host_id:05d}.json"
        with open(os.path.join(tmp, name), "w") as f:
            json.dump({"files": files, "tensors": tensors, "blobs": blobs},
                      f)
            f.flush()
            os.fsync(f.fileno())

    def _merge_shards(self, tmp):
        """Host 0: wait for every host's shard manifest and merge them."""
        deadline = time.time() + _cfg("MXNET_CKPT_COMMIT_TIMEOUT_S")
        paths = [os.path.join(tmp, f"shard-{h:05d}.json")
                 for h in range(self.num_hosts)]
        while not all(os.path.isfile(p) for p in paths):
            if time.time() > deadline:
                missing = [p for p in paths if not os.path.isfile(p)]
                raise CheckpointError(
                    f"commit timed out waiting for host shards: {missing}")
            time.sleep(0.05)
        files, tensors, blobs = {}, {}, {}
        for p in paths:
            with open(p) as f:
                part = json.load(f)
            files.update(part["files"])
            for name, entry in part["tensors"].items():
                tgt = tensors.setdefault(
                    name, {"dtype": entry["dtype"], "shape": entry["shape"],
                           "shards": []})
                tgt["shards"].extend(entry["shards"])
            blobs.update(part["blobs"])
        return files, tensors, blobs

    def _mirror_legacy(self, job):
        """Also emit ``{prefix}-symbol.json`` + ``{prefix}-{step:04d}.params``
        (+ ``.states``) so reference-format consumers keep working."""
        if self.num_hosts > 1:
            return  # mirror is a single-host convenience
        from ..ndarray import array
        from ..ndarray import utils as nd_utils
        prefix = self.legacy_prefix
        if job.symbol_json is not None:
            tmp = f"{prefix}-symbol.json.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(job.symbol_json)
            os.replace(tmp, f"{prefix}-symbol.json")
        save_dict = {}
        for name, _dtype, shape, shards in job.tensors:
            full = np.empty(shape,
                            dtype=shards[0][1].dtype) if shape else None
            if full is None:
                full = shards[0][1].reshape(())
            else:
                for index, arr in shards:
                    full[tuple(slice(b, e) for b, e in index)] = arr
            save_dict[name] = array(full)
        nd_utils.save(f"{prefix}-{job.step:04d}.params", save_dict)
        states = job.blobs.get("optimizer_states")
        if states is not None:
            tmp = f"{prefix}-{job.step:04d}.states.tmp-{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(states)
            os.replace(tmp, f"{prefix}-{job.step:04d}.states")

    def _gc(self):
        """Delete committed steps outside the retention policy.

        Best-effort by contract (ISSUE 8 satellite): a rename/rmtree
        failure must never fail the commit that triggered this GC — it
        is logged, counted in ``gc_errors`` and the
        ``mxnet_ckpt_gc_errors_total`` telemetry lane, and retried on
        the next commit (including leftover ``.gc`` trash directories
        whose contents could not be unlinked last time).
        """
        if self.keep_last <= 0:
            return
        removed = errors = 0
        try:
            steps = committed_steps(self.directory)
            keep = set(steps[-self.keep_last:])
            if self.keep_every > 0:
                keep.update(s for s in steps if s % self.keep_every == 0)
            # leftover trash from earlier failed removals retries first
            trash_dirs = [os.path.join(self.directory, n)
                          for n in os.listdir(self.directory)
                          if n.endswith(".gc")]
            for s in steps:
                if s in keep:
                    continue
                path = step_dir(self.directory, s)
                trash = path + ".gc"
                try:
                    _failpoint("checkpoint/gc/remove")
                    os.rename(path, trash)  # instantly invisible to latest()
                except OSError as e:
                    errors += 1
                    self.logger.warning(
                        "checkpoint: GC of step %d failed (%s); the step "
                        "stays; retrying on the next commit", s, e)
                    continue
                removed += 1
                trash_dirs.append(trash)
            for trash in trash_dirs:
                shutil.rmtree(trash, ignore_errors=True)
                if os.path.isdir(trash):
                    errors += 1
                    self.logger.warning(
                        "checkpoint: GC could not fully remove %s; "
                        "retrying on the next commit", trash)
        except Exception as e:  # noqa: BLE001 — GC must never fail a commit
            errors += 1
            self.logger.warning("checkpoint: retention GC pass failed "
                                "(%s: %s); retrying on the next commit",
                                type(e).__name__, e)
        if removed:
            with self._lock:
                self._stats_data["gc_removed"] += removed
            self._record_counter("checkpoint:gc_removed", removed)
        if errors:
            with self._lock:
                self._stats_data["gc_errors"] += errors
            try:
                from .. import telemetry as _telemetry
                _telemetry.REGISTRY.counter(
                    "mxnet_ckpt_gc_errors_total",
                    "checkpoint retention-GC removal failures (best-"
                    "effort: logged and retried on the next commit, "
                    "never failing the commit itself)").inc(
                        errors, labels={"directory": self.directory})
            except Exception:  # graftlint: disable=swallowed-error -- best-effort metrics must never fail a save
                pass

    @staticmethod
    def _record_counter(name, value):
        try:
            from .. import profiler
            profiler.record_counter(name, value)
        except Exception:  # graftlint: disable=swallowed-error -- best-effort metrics must never fail a save
            pass

    # -- module / symbolic glue ---------------------------------------------
    def save_module(self, module, step, save_optimizer_states=True,
                    epoch=None, extra=None, block=None):
        """Checkpoint a Module: params + aux + optimizer state + graph."""
        module._sync_params_from_exec()
        arrays = {f"arg:{n}": v for n, v in
                  (module._arg_params or {}).items()}
        arrays.update({f"aux:{n}": v for n, v in
                       (module._aux_params or {}).items()})
        blobs = {}
        if save_optimizer_states and module.optimizer_initialized:
            states = module.get_optimizer_states()
            if states is not None:
                blobs["optimizer_states"] = states
        return self.save(step, arrays=arrays, blobs=blobs,
                         symbol=module.symbol, epoch=epoch, extra=extra,
                         block=block)

    def restore_module(self, step=None, load_optimizer_states=True,
                       **module_kwargs):
        """(Module, Checkpoint) rebuilt from a committed step.

        The module arrives with params installed (bind + init_optimizer
        as usual); optimizer state is applied on ``init_optimizer``.
        """
        ckpt = self.restore(step)
        if ckpt.symbol_json is None:
            raise CheckpointError(
                f"step {ckpt.step} holds no symbol; restore_module needs "
                "a checkpoint written by save_module")
        from ..module import Module
        from ..symbol import load_json
        mod = Module(symbol=load_json(ckpt.symbol_json), **module_kwargs)
        mod._arg_params = ckpt.arg_params
        mod._aux_params = ckpt.aux_params
        mod.params_initialized = True
        states = ckpt.blobs.get("optimizer_states")
        if load_optimizer_states and states is not None:
            mod._preload_opt_states_bytes = states
        return mod, ckpt

    # -- read side ----------------------------------------------------------
    def restore(self, step=None, verify=None, fallback=True):
        """Load a committed checkpoint (latest when ``step`` is None),
        verifying checksums and falling back to the previous committed
        step on corruption (auto-latest only)."""
        t0 = time.perf_counter()
        if verify is None:
            verify = _cfg("MXNET_CKPT_VERIFY_ON_LOAD")
        ckpt = restore(self.directory, step=step, verify=verify,
                       fallback=fallback, logger=self.logger)
        with self._lock:
            # graftlint: disable=raw-phase-timing -- restore latency feeds stats()/last_restore_s, exported by telemetry's checkpoint collector
            self._stats_data["last_restore_s"] = time.perf_counter() - t0
        return ckpt

    def latest(self):
        """Newest committed step number (None when empty)."""
        return latest_step(self.directory)

    def steps(self):
        """All committed step numbers, ascending."""
        return committed_steps(self.directory)

    # -- lifecycle ----------------------------------------------------------
    def wait(self, timeout=None):
        """Block until every pending async save committed; re-raises the
        first writer failure."""
        with self._lock:
            pending = list(self._pending)
            self._pending = [f for f in self._pending if not f.done()]
        exc = None
        for fut in pending:
            try:
                fut.result(timeout)
            except Exception as e:
                if exc is None:
                    exc = e
        with self._lock:
            self._pending = [f for f in self._pending if not f.done()]
        if exc is not None:
            raise exc

    def stats(self):
        """Public observability surface: save/restore latency + volume
        counters (a locked COPY), plus live gauges — writer-queue depth,
        pending async saves, and the age of the last commit.  Feeds
        ``telemetry.snapshot()["checkpoint"]`` and the Prometheus
        ``mxnet_checkpoint_*`` families.  (Direct ``_stats`` access is
        deprecated.)"""
        with self._lock:
            out = dict(self._stats_data)
            last_commit_t = self._last_commit_t
            out["pending_saves"] = sum(1 for f in self._pending
                                       if not f.done())
        out["writer_queue_depth"] = self._queue.qsize()
        out["last_commit_age_s"] = (
            None if last_commit_t is None
            else round(time.monotonic() - last_commit_t, 3))
        return out

    def close(self):
        """Flush pending saves and stop the writer thread."""
        if self._closed:
            return
        try:
            # graftlint: disable=unbounded-wait -- close() flushes every pending save by contract (dropping them would lose committed-step guarantees); each queued job resolves its future even on failure, and the writer join below is bounded
            self.wait()
        finally:
            self._closed = True
            with self._lock:
                writer = self._writer
            if writer is not None and writer.is_alive():
                self._queue.put(None)
                writer.join(timeout=30)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
