"""Checkpoint layout, manifest schema and the read/verify path.

Directory layout (docs/checkpoint.md):

    ckpt_dir/
      step-000010/              # committed: the rename made it visible
        MANIFEST.json           # schema, per-file sha256, shard layout
        data-00000-of-00001.bin # raw tensor shards + opaque blobs
        symbol.json             # optional: the graph that produced them
      step-000012.tmp/          # in progress — never discoverable

The commit protocol is write-into-tmp -> fsync files -> fsync tmp dir ->
rename(tmp, final) -> fsync parent.  ``committed_steps``/``latest_step``
only ever see directories whose rename completed AND that contain a
manifest, so a writer killed at any instant leaves either the previous
step or the new one — never a torn checkpoint.

Tensors are stored as raw bytes (dtype recorded by name, so bfloat16 and
friends survive) with an explicit shard table: each shard carries the
half-open index ``[[start, stop], ...]`` it covers in the global array.
A checkpoint saved from one dp×tp×pp layout is therefore re-assembled
into full host arrays on load and can be re-sharded onto any other
layout (elastic restore).
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import re

import numpy as np

from ..base import MXNetError

SCHEMA_VERSION = 1
MANIFEST = "MANIFEST.json"
TMP_SUFFIX = ".tmp"
_STEP_RE = re.compile(r"^step-(\d{6,})$")


class CheckpointError(MXNetError):
    """Base class for checkpoint failures."""


class CheckpointNotFoundError(CheckpointError):
    """No committed checkpoint matches the request."""


class CheckpointCorruptError(CheckpointError):
    """A committed checkpoint failed checksum/structure verification."""


def step_dirname(step):
    return f"step-{int(step):06d}"


def step_dir(directory, step):
    return os.path.join(directory, step_dirname(step))


def committed_steps(directory):
    """Sorted committed step numbers under ``directory``.

    A step counts as committed only when its final (non-``.tmp``)
    directory exists AND contains a manifest — the last file written
    before the atomic rename, so partial states are invisible here.
    """
    try:
        names = os.listdir(directory)
    except (FileNotFoundError, NotADirectoryError):
        return []
    steps = []
    for name in names:
        m = _STEP_RE.match(name)
        if m and os.path.isfile(os.path.join(directory, name, MANIFEST)):
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(directory):
    """Newest committed step, or None when there is none."""
    steps = committed_steps(directory)
    return steps[-1] if steps else None


def _np_dtype(name):
    """np.dtype from its saved name; bfloat16 etc. resolve via ml_dtypes."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        try:
            return np.dtype(getattr(ml_dtypes, name))
        except AttributeError:
            raise CheckpointCorruptError(
                f"checkpoint tensor has unknown dtype {name!r}") from None


def _fsync_path(path):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _sha256(data):
    return hashlib.sha256(data).hexdigest()


class Checkpoint:
    """One restored checkpoint: host arrays + blobs + metadata.

    ``arrays`` maps tensor name -> np.ndarray (fully assembled global
    arrays, whatever mesh layout saved them).  ``blobs`` maps name ->
    bytes (e.g. ``"optimizer_states"``).  ``symbol_json`` is the graph
    JSON when the saver provided one.
    """

    def __init__(self, step, metadata, mesh, arrays, blobs, symbol_json):
        self.step = int(step)
        self.metadata = metadata or {}
        self.mesh = mesh
        self.arrays = arrays
        self.blobs = blobs
        self.symbol_json = symbol_json

    @property
    def epoch(self):
        return self.metadata.get("epoch")

    def as_ndarrays(self):
        """All tensors as NDArrays (keys unchanged)."""
        from ..ndarray import array
        return {k: array(v) for k, v in self.arrays.items()}

    def _prefixed(self, prefix):
        from ..ndarray import array
        return {k.split(":", 1)[1]: array(v) for k, v in self.arrays.items()
                if k.startswith(prefix)}

    @property
    def arg_params(self):
        """``arg:``-prefixed tensors as {name: NDArray} (module convention)."""
        return self._prefixed("arg:")

    @property
    def aux_params(self):
        """``aux:``-prefixed tensors as {name: NDArray}."""
        return self._prefixed("aux:")

    def __repr__(self):
        return (f"Checkpoint(step={self.step}, tensors={len(self.arrays)}, "
                f"blobs={sorted(self.blobs)})")


def _assemble_tensor(name, entry, file_bytes):
    """Re-assemble one global array from its recorded shards."""
    dtype = _np_dtype(entry["dtype"])
    shape = tuple(int(s) for s in entry["shape"])
    out = np.empty(shape, dtype=dtype)
    covered = 0
    for sh in entry["shards"]:
        data = file_bytes.get(sh["file"])
        if data is None:
            raise CheckpointCorruptError(
                f"tensor {name!r} references missing file {sh['file']!r}")
        index = tuple((int(b), int(e)) for b, e in sh["index"])
        shard_shape = tuple(e - b for b, e in index)
        n = int(np.prod(shard_shape)) if shard_shape else 1
        nbytes = n * dtype.itemsize
        if sh["offset"] + nbytes > len(data):
            raise CheckpointCorruptError(
                f"tensor {name!r} shard overruns file {sh['file']!r}")
        flat = np.frombuffer(data, dtype=dtype, count=n,
                             offset=int(sh["offset"]))
        if shape == ():
            out = flat.reshape(())
            covered = 1
            continue
        out[tuple(slice(b, e) for b, e in index)] = flat.reshape(shard_shape)
        covered += n
    total = int(np.prod(shape)) if shape else 1
    if covered < total:
        raise CheckpointCorruptError(
            f"tensor {name!r}: shards cover {covered} of {total} elements "
            "(checkpoint saved by a partial host set?)")
    return out


def load_step(directory, step, verify=True):
    """Load one committed step into a :class:`Checkpoint`.

    Raises CheckpointNotFoundError when the step is not committed and
    CheckpointCorruptError on checksum/structure mismatch.
    """
    path = step_dir(directory, step)
    mpath = os.path.join(path, MANIFEST)
    if not os.path.isfile(mpath):
        raise CheckpointNotFoundError(
            f"no committed checkpoint for step {step} in {directory!r}")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            f"unreadable manifest for step {step}: {e}") from e
    if manifest.get("schema_version") != SCHEMA_VERSION:
        raise CheckpointCorruptError(
            f"manifest schema {manifest.get('schema_version')!r} not "
            f"supported (expected {SCHEMA_VERSION})")

    file_bytes = {}
    for fname, finfo in manifest.get("files", {}).items():
        fpath = os.path.join(path, fname)
        try:
            with open(fpath, "rb") as f:
                data = f.read()
        except OSError as e:
            raise CheckpointCorruptError(
                f"step {step}: cannot read {fname!r}: {e}") from e
        if len(data) != int(finfo["bytes"]):
            raise CheckpointCorruptError(
                f"step {step}: {fname!r} is {len(data)} bytes, manifest "
                f"says {finfo['bytes']}")
        if verify and _sha256(data) != finfo["sha256"]:
            raise CheckpointCorruptError(
                f"step {step}: checksum mismatch for {fname!r}")
        file_bytes[fname] = data

    arrays = {}
    for name, entry in manifest.get("tensors", {}).items():
        arrays[name] = _assemble_tensor(name, entry, file_bytes)
    blobs = {}
    for name, entry in manifest.get("blobs", {}).items():
        data = file_bytes.get(entry["file"])
        if data is None:
            raise CheckpointCorruptError(
                f"blob {name!r} references missing file {entry['file']!r}")
        off, n = int(entry["offset"]), int(entry["nbytes"])
        if off + n > len(data):
            raise CheckpointCorruptError(f"blob {name!r} overruns its file")
        blobs[name] = bytes(data[off:off + n])
    symbol_json = None
    sym_file = manifest.get("symbol")
    if sym_file and sym_file in file_bytes:
        symbol_json = file_bytes[sym_file].decode("utf-8")
    return Checkpoint(manifest["step"], manifest.get("metadata"),
                      manifest.get("mesh"), arrays, blobs, symbol_json)


def restore(directory, step=None, verify=True, fallback=True,
            logger=logging):
    """Restore a checkpoint from ``directory``.

    With ``step=None`` the newest committed step is loaded; if it fails
    verification and ``fallback`` is true, earlier committed steps are
    tried (newest first) with a warning — the ISSUE-2 contract that a
    corrupt latest step degrades to the previous good one instead of
    killing the resume.  An explicitly requested step never falls back.
    """
    import time as _time
    t0 = _time.perf_counter()
    steps = committed_steps(directory)
    if not steps:
        raise CheckpointNotFoundError(
            f"no committed checkpoints in {directory!r}")
    if step is not None:
        ckpt = load_step(directory, int(step), verify=verify)
        _record_restore(t0)
        return ckpt
    last_err = None
    for s in reversed(steps):
        try:
            ckpt = load_step(directory, s, verify=verify)
            if last_err is not None:
                logger.warning(
                    "checkpoint: fell back to step %d after corruption: %s",
                    s, last_err)
            _record_restore(t0)
            return ckpt
        except CheckpointCorruptError as e:
            if not fallback:
                raise
            last_err = e
            logger.warning("checkpoint: step %d failed verification (%s); "
                           "trying previous committed step", s, e)
    raise CheckpointCorruptError(
        f"every committed checkpoint in {directory!r} failed "
        f"verification; last error: {last_err}")


def _record_restore(t0):
    import time as _time
    try:
        from .. import profiler
        profiler.record_counter("checkpoint:restore_s",
                                round(_time.perf_counter() - t0, 4))
    except Exception:  # graftlint: disable=swallowed-error -- best-effort metrics must never fail a restore
        pass
