"""Network visualization (parity: python/mxnet/visualization.py):
print_summary ASCII table + plot_network graphviz export."""
from __future__ import annotations

import json

from .base import MXNetError


def print_summary(symbol, shape=None, line_length=120, positions=(.44, .64,
                                                                  .74, 1.)):
    """Print a symbol's layer summary table
    (parity: visualization.py print_summary)."""
    if shape is not None:
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape(**shape)
        if out_shapes is None:
            raise ValueError("Input shape is incomplete")
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #",
                  "Previous Layer"]

    def print_row(fields, positions):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(to_display, positions)
    print("=" * line_length)
    total_params = 0

    def print_layer_summary(node, out_shape):
        op = node["op"]
        pre_node = []
        pre_filter = 0
        if op != "null":
            inputs = node["inputs"]
            for item in inputs:
                input_node = nodes[item[0]]
                input_name = input_node["name"]
                is_data = input_node["op"] == "null" and \
                    shape is not None and input_name in shape
                if input_node["op"] != "null" or item[0] in heads:
                    pre_node.append(input_name)
                    if input_node["op"] != "null":
                        key = input_name + "_output"
                        if key in shape_dict:
                            pre_filter = pre_filter + int(shape_dict[key][1])
                    elif is_data and input_name in shape_dict and \
                            len(shape_dict[input_name]) > 1:
                        # data inputs (user-bound shapes) contribute their
                        # feature dim; weight/bias variables do not
                        pre_filter = pre_filter + \
                            int(shape_dict[input_name][1])
        cur_param = 0
        attrs = node.get("attrs", {})
        if op == "Convolution":
            num_group = int(attrs.get("num_group", "1"))
            k = _parse_tuple(attrs["kernel"])
            cur_param = pre_filter * int(attrs["num_filter"]) // num_group
            for kk in k:
                cur_param *= kk
            if attrs.get("no_bias", "False") not in ("True", "true", "1"):
                cur_param += int(attrs["num_filter"])
        elif op == "FullyConnected":
            if attrs.get("no_bias", "False") in ("True", "true", "1"):
                cur_param = pre_filter * int(attrs["num_hidden"])
            else:
                cur_param = (pre_filter + 1) * int(attrs["num_hidden"])
        elif op == "BatchNorm":
            key = node["name"] + "_output"
            if shape is not None and key in shape_dict:
                num_filter = shape_dict[key][1]
                cur_param = int(num_filter) * 2
        elif op == "Embedding":
            cur_param = int(attrs["input_dim"]) * int(attrs["output_dim"])
        first_connection = not pre_node
        fields = [node["name"] + "(" + op + ")",
                  "x".join(str(x) for x in out_shape),
                  cur_param,
                  pre_node[0] if pre_node else ""]
        print_row(fields, positions)
        for i in range(1, len(pre_node)):
            fields = ["", "", "", pre_node[i]]
            print_row(fields, positions)
        return cur_param

    heads = set(conf["arg_nodes"])
    for i, node in enumerate(nodes):
        out_shape = []
        op = node["op"]
        if op == "null" and i > 0:
            continue
        if op != "null" or i in heads:
            if shape is not None:
                key = node["name"] + "_output"
                if key in shape_dict:
                    out_shape = shape_dict[key][1:]
        total_params += print_layer_summary(node, out_shape)
        if i == len(nodes) - 1:
            print("=" * line_length)
        else:
            print("_" * line_length)
    print(f"Total params: {total_params}")
    print("_" * line_length)


def _parse_tuple(s):
    if isinstance(s, (tuple, list)):
        return tuple(int(x) for x in s)
    return tuple(int(x) for x in s.strip("()[] ").split(",") if x.strip())


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 dtype=None, node_attrs=None, hide_weights=True):
    """Build a graphviz Digraph of the symbol
    (parity: visualization.py plot_network). Requires the graphviz package;
    raises a clear error if absent (no egress to install it)."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise MXNetError(
            "plot_network requires the graphviz python package") from e
    node_attrs = node_attrs or {}
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    node_attr = {"shape": "box", "fixedsize": "true", "width": "1.3",
                 "height": "0.8034", "style": "filled"}
    node_attr.update(node_attrs)
    dot = Digraph(name=title, format=save_format)
    hidden_nodes = set()
    for i, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null":
            if name.endswith("_weight") or name.endswith("_bias") or \
                    name.endswith("_gamma") or name.endswith("_beta") or \
                    name.endswith("_moving_var") or \
                    name.endswith("_moving_mean") or \
                    name.endswith("_running_var") or \
                    name.endswith("_running_mean"):
                if hide_weights:
                    hidden_nodes.add(i)
                continue
            dot.node(name=name, label=name,
                     **dict(node_attr, fillcolor="#8dd3c7"))
        else:
            dot.node(name=name, label=f"{name}\n({op})",
                     **dict(node_attr, fillcolor="#fb8072"))
    for i, node in enumerate(nodes):
        if node["op"] == "null":
            continue
        for item in node["inputs"]:
            src = item[0]
            if src in hidden_nodes:
                continue
            dot.edge(tail_name=nodes[src]["name"], head_name=node["name"])
    return dot
