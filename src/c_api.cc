// General C API for mxnet_tpu — the training-capable ABI.
//
// Parity: the reference's include/mxnet/c_api.h fronts (subset: the ~40
// functions that make TRAINING reachable from C, not just predict):
//   NDArray  — MXNDArrayCreateEx/Free/SyncCopy{From,To}CPU/GetShape/
//              GetDType/WaitAll/Save/Load/GetGrad        (c_api.h:560+)
//   Invoke   — MXImperativeInvokeEx                      (c_api.h:1063)
//   Autograd — MXAutogradSetIsRecording/SetIsTraining/
//              MarkVariables/BackwardEx                  (c_api.h:1152)
//   Symbol   — MXSymbolCreateVariable/CreateFromJSON/SaveToJSON/
//              CreateOp(compose)/ListArguments/ListOutputs/Free
//   Executor — MXExecutorBind/Forward/Backward/Outputs/ArgGrad/Free
//              (c_api.h:1993 MXExecutorBindEX)
//   KVStore  — MXKVStoreCreate/Init/Push/Pull/GetRank/GetGroupSize/Free
//   Misc     — MXGetVersion, MXListAllOpNames, MXGetLastError
//
// Architecture: same embedded-CPython pattern as c_predict_api.cc (the
// reference's C API fronts a C++ core; this framework's core is
// Python-over-JAX).  Every handle is a borrowed PyObject* owned by this
// shim; helpers live in mxnet_tpu/c_api_impl.py.  Data crosses as raw
// C-order bytes, so any language with a C FFI can train a model.
//
// Build: make -C src capi    (links libpython3; see src/Makefile)

#include "c_embed.h"

#include <cstdint>
#include <cstring>
#include <set>
#include <string>
#include <vector>

using mxtpu::Gil;
using mxtpu::ensure_python;
using mxtpu::fail;
using mxtpu::fail_from_python;

typedef void* NDArrayHandle;
typedef void* SymbolHandle;
typedef void* ExecutorHandle;
typedef void* KVStoreHandle;
typedef void* CachedOpHandle;
typedef void* DataIterHandle;
typedef void* RecordIOHandle;
typedef uint32_t mx_uint;

namespace {

// string/array returns must outlive the call (reference keeps per-thread
// return buffers in MXAPIThreadLocalEntry); same scheme here
thread_local std::vector<std::string> g_ret_strs;
thread_local std::vector<const char*> g_ret_cstrs;
thread_local std::vector<mx_uint> g_ret_shape;
thread_local std::vector<NDArrayHandle> g_ret_handles;
thread_local std::string g_ret_json;
thread_local std::string g_ret_record;

// MXSymbolInferShape returns three (ndim[], data[][]) groups; each group's
// backing storage lives here until the next call on this thread
struct ShapeGroup {
  std::vector<std::vector<mx_uint>> shapes;
  std::vector<mx_uint> ndims;
  std::vector<const mx_uint*> ptrs;
  void load(PyObject* seq) {
    Py_ssize_t n = PySequence_Size(seq);
    shapes.assign(n, {});
    ndims.assign(n, 0);
    ptrs.assign(n, nullptr);
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* shp = PySequence_GetItem(seq, i);
      Py_ssize_t d = (shp && shp != Py_None) ? PySequence_Size(shp) : 0;
      for (Py_ssize_t j = 0; j < d; ++j) {
        PyObject* it = PySequence_GetItem(shp, j);
        shapes[i].push_back(static_cast<mx_uint>(PyLong_AsUnsignedLong(it)));
        Py_XDECREF(it);
      }
      Py_XDECREF(shp);
      ndims[i] = static_cast<mx_uint>(shapes[i].size());
      ptrs[i] = shapes[i].data();
    }
  }
};
thread_local ShapeGroup g_in_shapes, g_out_shapes, g_aux_shapes;

PyObject* impl() {
  static thread_local PyObject* mod = nullptr;
  if (!mod) mod = mxtpu::import_helper("mxnet_tpu.c_api_impl");
  return mod;
}

// call helper fn with args tuple (steals nothing); returns new ref
PyObject* call(const char* fn, PyObject* args) {
  PyObject* m = impl();
  if (!m) return nullptr;
  PyObject* f = PyObject_GetAttrString(m, fn);
  if (!f) return nullptr;
  PyObject* r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  return r;
}

PyObject* list_from_handles(int n, void* const* handles) {
  PyObject* lst = PyList_New(n);
  for (int i = 0; i < n; ++i) {
    PyObject* o = static_cast<PyObject*>(handles[i]);
    if (!o) o = Py_None;
    Py_INCREF(o);
    PyList_SET_ITEM(lst, i, o);
  }
  return lst;
}

PyObject* list_from_strs(int n, const char* const* strs) {
  PyObject* lst = PyList_New(n);
  for (int i = 0; i < n; ++i) {
    PyList_SET_ITEM(lst, i, PyUnicode_FromString(strs[i] ? strs[i] : ""));
  }
  return lst;
}

int strlist_out(PyObject* seq, mx_uint* out_size, const char*** out_strs) {
  Py_ssize_t n = PySequence_Size(seq);
  g_ret_strs.clear();
  g_ret_cstrs.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* it = PySequence_GetItem(seq, i);
    const char* c = it ? PyUnicode_AsUTF8(it) : nullptr;
    g_ret_strs.emplace_back(c ? c : "");
    Py_XDECREF(it);
  }
  for (auto& s : g_ret_strs) g_ret_cstrs.push_back(s.c_str());
  *out_size = static_cast<mx_uint>(n);
  *out_strs = g_ret_cstrs.data();
  return 0;
}

int handlelist_out(PyObject* seq, mx_uint* out_size, NDArrayHandle** out) {
  Py_ssize_t n = PySequence_Size(seq);
  g_ret_handles.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* it = PySequence_GetItem(seq, i);  // new ref, kept as handle
    g_ret_handles.push_back(it);
  }
  *out_size = static_cast<mx_uint>(n);
  *out = g_ret_handles.data();
  return 0;
}

}  // namespace

extern "C" {

const char* MXGetLastError() { return mxtpu::last_error().c_str(); }

int MXGetVersion(int* out) {
  ensure_python();
  Gil gil;
  PyObject* r = call("version", nullptr);
  if (!r) return fail_from_python();
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXListAllOpNames(mx_uint* out_size, const char*** out_array) {
  ensure_python();
  Gil gil;
  PyObject* r = call("list_all_op_names", nullptr);
  if (!r) return fail_from_python();
  strlist_out(r, out_size, out_array);
  Py_DECREF(r);
  return 0;
}

// --- NDArray ---------------------------------------------------------------
int MXNDArrayCreateEx(const mx_uint* shape, mx_uint ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle* out) {
  (void)delay_alloc;
  ensure_python();
  Gil gil;
  PyObject* shp = PyTuple_New(ndim);
  for (mx_uint i = 0; i < ndim; ++i) {
    PyTuple_SET_ITEM(shp, i, PyLong_FromUnsignedLong(shape[i]));
  }
  PyObject* args = Py_BuildValue("(Oiii)", shp, dev_type, dev_id, dtype);
  Py_DECREF(shp);
  PyObject* r = args ? call("ndarray_create", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out = r;  // handle owns the reference
  return 0;
}

int MXNDArrayCreate(const mx_uint* shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, NDArrayHandle* out) {
  return MXNDArrayCreateEx(shape, ndim, dev_type, dev_id, delay_alloc, 0,
                           out);
}

int MXNDArrayFree(NDArrayHandle handle) {
  if (!handle) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t size_bytes) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* buf = PyBytes_FromStringAndSize(
      static_cast<const char*>(data),
      static_cast<Py_ssize_t>(size_bytes));
  PyObject* args = Py_BuildValue("(OO)", handle, buf);
  Py_XDECREF(buf);
  PyObject* r = args ? call("ndarray_set_bytes", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data,
                           size_t size_bytes) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("ndarray_get_bytes", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  char* src = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(r, &src, &n) != 0) {
    Py_DECREF(r);
    return fail_from_python();
  }
  if (static_cast<size_t>(n) != size_bytes) {
    Py_DECREF(r);
    return fail("MXNDArraySyncCopyToCPU: size mismatch");
  }
  std::memcpy(data, src, n);
  Py_DECREF(r);
  return 0;
}

int MXNDArrayGetShape(NDArrayHandle handle, mx_uint* out_dim,
                      const mx_uint** out_pdata) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("ndarray_shape", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_ssize_t n = PyTuple_Size(r);
  g_ret_shape.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    g_ret_shape[i] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(r, i)));
  }
  Py_DECREF(r);
  *out_dim = static_cast<mx_uint>(n);
  *out_pdata = g_ret_shape.data();
  return 0;
}

int MXNDArrayGetDType(NDArrayHandle handle, int* out) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("ndarray_dtype_code", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXNDArrayWaitAll() {
  ensure_python();
  Gil gil;
  PyObject* r = call("ndarray_wait_all", nullptr);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXNDArraySave(const char* fname, mx_uint num_args,
                  NDArrayHandle* args_h, const char** keys) {
  ensure_python();
  Gil gil;
  PyObject* arrs = list_from_handles(num_args, args_h);
  PyObject* names = keys ? list_from_strs(num_args, keys)
                         : (Py_INCREF(Py_None), Py_None);
  PyObject* args = Py_BuildValue("(sOO)", fname, arrs, names);
  Py_DECREF(arrs);
  Py_DECREF(names);
  PyObject* r = args ? call("ndarray_save", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXNDArrayLoad(const char* fname, mx_uint* out_size,
                  NDArrayHandle** out_arr, mx_uint* out_name_size,
                  const char*** out_names) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", fname);
  PyObject* r = args ? call("ndarray_load", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  PyObject* arrs = PyTuple_GetItem(r, 0);
  PyObject* names = PyTuple_GetItem(r, 1);
  handlelist_out(arrs, out_size, out_arr);
  strlist_out(names, out_name_size, out_names);
  Py_DECREF(r);
  return 0;
}

int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle* out) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("ndarray_get_grad", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  if (r == Py_None) {
    Py_DECREF(r);
    *out = nullptr;
    return 0;
  }
  *out = r;
  return 0;
}

// --- imperative invoke -----------------------------------------------------
int MXImperativeInvokeEx(const char* op_name, int num_inputs,
                         NDArrayHandle* inputs, int* num_outputs,
                         NDArrayHandle** outputs, int num_params,
                         const char** param_keys,
                         const char** param_vals) {
  ensure_python();
  Gil gil;
  PyObject* ins = list_from_handles(num_inputs, inputs);
  PyObject* keys = list_from_strs(num_params, param_keys);
  PyObject* vals = list_from_strs(num_params, param_vals);
  // write-to-existing-outputs form: *num_outputs > 0 with caller handles
  PyObject* outs;
  if (*num_outputs > 0 && *outputs) {
    outs = list_from_handles(*num_outputs, *outputs);
  } else {
    outs = Py_None;
    Py_INCREF(Py_None);
  }
  bool provided = (*num_outputs > 0 && *outputs);
  PyObject* args = Py_BuildValue("(sOOOO)", op_name, ins, keys, vals, outs);
  Py_DECREF(ins);
  Py_DECREF(keys);
  Py_DECREF(vals);
  Py_DECREF(outs);
  PyObject* r = args ? call("imperative_invoke", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  if (provided) {
    // results were written into the caller's handles in place; handing
    // back new references here would leak one ref per output per call
    *num_outputs = static_cast<int>(PySequence_Size(r));
    Py_DECREF(r);
    return 0;
  }
  mx_uint n = 0;
  handlelist_out(r, &n, outputs);
  *num_outputs = static_cast<int>(n);
  Py_DECREF(r);
  return 0;
}

// --- autograd --------------------------------------------------------------
int MXAutogradSetIsRecording(int is_recording, int* prev) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", is_recording);
  PyObject* r = args ? call("autograd_set_recording", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  if (prev) *prev = PyObject_IsTrue(r);
  Py_DECREF(r);
  return 0;
}

int MXAutogradSetIsTraining(int train_mode, int* prev) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", train_mode);
  PyObject* r = args ? call("autograd_set_training", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  if (prev) *prev = PyObject_IsTrue(r);
  Py_DECREF(r);
  return 0;
}

int MXAutogradMarkVariables(mx_uint num_var, NDArrayHandle* var_handles,
                            mx_uint* reqs_array,
                            NDArrayHandle* grad_handles) {
  (void)reqs_array;
  ensure_python();
  Gil gil;
  PyObject* vars = list_from_handles(num_var, var_handles);
  PyObject* grads = list_from_handles(num_var, grad_handles);
  PyObject* args = Py_BuildValue("(OO)", vars, grads);
  Py_DECREF(vars);
  Py_DECREF(grads);
  PyObject* r = args ? call("autograd_mark_variables", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXAutogradBackwardEx(mx_uint num_output, NDArrayHandle* output_handles,
                         NDArrayHandle* ograd_handles, mx_uint num_variables,
                         NDArrayHandle* var_handles, int retain_graph,
                         int create_graph, int is_train,
                         NDArrayHandle** grad_handles, int** grad_stypes) {
  (void)num_variables;
  (void)var_handles;
  (void)create_graph;
  (void)is_train;
  (void)grad_handles;
  (void)grad_stypes;
  ensure_python();
  Gil gil;
  PyObject* outs = list_from_handles(num_output, output_handles);
  PyObject* ogs;
  if (ograd_handles) {
    ogs = list_from_handles(num_output, ograd_handles);
  } else {
    ogs = Py_None;
    Py_INCREF(Py_None);
  }
  PyObject* args = Py_BuildValue("(OOi)", outs, ogs, retain_graph);
  Py_DECREF(outs);
  Py_DECREF(ogs);
  PyObject* r = args ? call("autograd_backward", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXAutogradBackward(mx_uint num_output, NDArrayHandle* output_handles,
                       NDArrayHandle* ograd_handles, int retain_graph) {
  return MXAutogradBackwardEx(num_output, output_handles, ograd_handles, 0,
                              nullptr, retain_graph, 0, 1, nullptr, nullptr);
}

// --- symbol ----------------------------------------------------------------
int MXSymbolCreateVariable(const char* name, SymbolHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", name);
  PyObject* r = args ? call("symbol_create_variable", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out = r;
  return 0;
}

// create an op node and compose it with inputs in one call (covers the
// reference's MXSymbolCreateAtomicSymbol + MXSymbolCompose pair)
int MXSymbolCreateOp(const char* op_name, mx_uint num_param,
                     const char** keys, const char** vals,
                     mx_uint num_inputs, SymbolHandle* input_symbols,
                     const char* name, SymbolHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* ins = list_from_handles(num_inputs, input_symbols);
  PyObject* k = list_from_strs(num_param, keys);
  PyObject* v = list_from_strs(num_param, vals);
  PyObject* args = Py_BuildValue("(sOOOs)", op_name, ins, k, v,
                                 name ? name : "");
  Py_DECREF(ins);
  Py_DECREF(k);
  Py_DECREF(v);
  PyObject* r = args ? call("symbol_create", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out = r;
  return 0;
}

int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", json);
  PyObject* r = args ? call("symbol_from_json", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out = r;
  return 0;
}

int MXSymbolSaveToJSON(SymbolHandle sym, const char** out_json) {
  if (!sym) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", sym);
  PyObject* r = args ? call("symbol_to_json", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  const char* c = PyUnicode_AsUTF8(r);
  g_ret_json = c ? c : "";
  Py_DECREF(r);
  *out_json = g_ret_json.c_str();
  return 0;
}

int MXSymbolListArguments(SymbolHandle sym, mx_uint* out_size,
                          const char*** out_str_array) {
  if (!sym) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", sym);
  PyObject* r = args ? call("symbol_list_arguments", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  strlist_out(r, out_size, out_str_array);
  Py_DECREF(r);
  return 0;
}

int MXSymbolListOutputs(SymbolHandle sym, mx_uint* out_size,
                        const char*** out_str_array) {
  if (!sym) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", sym);
  PyObject* r = args ? call("symbol_list_outputs", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  strlist_out(r, out_size, out_str_array);
  Py_DECREF(r);
  return 0;
}

int MXSymbolListAuxiliaryStates(SymbolHandle sym, mx_uint* out_size,
                                const char*** out_str_array) {
  if (!sym) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", sym);
  PyObject* r = args ? call("symbol_list_aux", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  strlist_out(r, out_size, out_str_array);
  Py_DECREF(r);
  return 0;
}

int MXSymbolFree(SymbolHandle sym) {
  if (!sym) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(sym));
  return 0;
}

// --- executor --------------------------------------------------------------
int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                   mx_uint num_args, const char** arg_names,
                   NDArrayHandle* arg_arrays, const char** grad_reqs,
                   mx_uint num_aux, const char** aux_names,
                   NDArrayHandle* aux_arrays, ExecutorHandle* out) {
  if (!sym) return fail("null handle");
  Gil gil;
  PyObject* names = list_from_strs(num_args, arg_names);
  PyObject* arrs = list_from_handles(num_args, arg_arrays);
  PyObject* reqs = list_from_strs(num_args, grad_reqs);
  PyObject* anames = list_from_strs(num_aux, aux_names);
  PyObject* aarrs = list_from_handles(num_aux, aux_arrays);
  PyObject* args = Py_BuildValue("(OiiOOOOO)", sym, dev_type, dev_id,
                                 names, arrs, reqs, anames, aarrs);
  Py_DECREF(names);
  Py_DECREF(arrs);
  Py_DECREF(reqs);
  Py_DECREF(anames);
  Py_DECREF(aarrs);
  PyObject* r = args ? call("executor_bind", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out = r;
  return 0;
}

int MXExecutorForward(ExecutorHandle handle, int is_train) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", handle, is_train);
  PyObject* r = args ? call("executor_forward", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXExecutorBackward(ExecutorHandle handle, mx_uint num_grads,
                       NDArrayHandle* head_grads) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* hg = list_from_handles(num_grads, head_grads);
  PyObject* args = Py_BuildValue("(OO)", handle, hg);
  Py_DECREF(hg);
  PyObject* r = args ? call("executor_backward", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXExecutorOutputs(ExecutorHandle handle, mx_uint* out_size,
                      NDArrayHandle** out) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("executor_outputs", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  handlelist_out(r, out_size, out);
  Py_DECREF(r);
  return 0;
}

int MXExecutorArgGrad(ExecutorHandle handle, const char* arg_name,
                      NDArrayHandle* out) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", handle, arg_name);
  PyObject* r = args ? call("executor_arg_grad", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  if (r == Py_None) {
    Py_DECREF(r);
    *out = nullptr;
    return 0;
  }
  *out = r;
  return 0;
}

int MXExecutorFree(ExecutorHandle handle) {
  if (!handle) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}

// --- NDArray views / misc --------------------------------------------------
namespace {
// one-arg helper call returning a fresh handle
int handle_out_call(const char* fn, PyObject* args, void** out) {
  PyObject* r = args ? call(fn, args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out = r;
  return 0;
}
}  // namespace

int MXNDArrayReshape(NDArrayHandle handle, int ndim, const int* dims,
                     NDArrayHandle* out) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* shp = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    PyTuple_SET_ITEM(shp, i, PyLong_FromLong(dims[i]));
  }
  return handle_out_call("ndarray_reshape",
                         Py_BuildValue("(ON)", handle, shp), out);
}

int MXNDArraySlice(NDArrayHandle handle, mx_uint slice_begin,
                   mx_uint slice_end, NDArrayHandle* out) {
  if (!handle) return fail("null handle");
  Gil gil;
  return handle_out_call(
      "ndarray_slice",
      Py_BuildValue("(OII)", handle, slice_begin, slice_end), out);
}

int MXNDArrayAt(NDArrayHandle handle, mx_uint idx, NDArrayHandle* out) {
  if (!handle) return fail("null handle");
  Gil gil;
  return handle_out_call("ndarray_at", Py_BuildValue("(OI)", handle, idx),
                         out);
}

int MXNDArrayGetContext(NDArrayHandle handle, int* out_dev_type,
                        int* out_dev_id) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("ndarray_context", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out_dev_type = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(r, 0)));
  *out_dev_id = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(r, 1)));
  Py_DECREF(r);
  return 0;
}

int MXRandomSeed(int seed) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", seed);
  PyObject* r = args ? call("random_seed", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

// --- symbol shape inference ------------------------------------------------
static int infer_shape_common(const char* helper, SymbolHandle sym,
                       mx_uint num_args,
                       const char** keys, const mx_uint* arg_ind_ptr,
                       const mx_uint* arg_shape_data,
                       mx_uint* in_shape_size,
                       const mx_uint** in_shape_ndim,
                       const mx_uint*** in_shape_data,
                       mx_uint* out_shape_size,
                       const mx_uint** out_shape_ndim,
                       const mx_uint*** out_shape_data,
                       mx_uint* aux_shape_size,
                       const mx_uint** aux_shape_ndim,
                       const mx_uint*** aux_shape_data,
                       int* complete) {
  if (!sym) return fail("null handle");
  Gil gil;
  PyObject* names = list_from_strs(num_args, keys);
  PyObject* shapes = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    mx_uint b = arg_ind_ptr[i], e = arg_ind_ptr[i + 1];
    PyObject* shp = PyTuple_New(e - b);
    for (mx_uint j = b; j < e; ++j) {
      PyTuple_SET_ITEM(shp, j - b,
                       PyLong_FromUnsignedLong(arg_shape_data[j]));
    }
    PyList_SET_ITEM(shapes, i, shp);
  }
  PyObject* args = Py_BuildValue("(OOO)", sym, names, shapes);
  Py_DECREF(names);
  Py_DECREF(shapes);
  PyObject* r = args ? call(helper, args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  g_in_shapes.load(PyTuple_GetItem(r, 0));
  g_out_shapes.load(PyTuple_GetItem(r, 1));
  g_aux_shapes.load(PyTuple_GetItem(r, 2));
  *complete = PyObject_IsTrue(PyTuple_GetItem(r, 3));
  Py_DECREF(r);
  *in_shape_size = static_cast<mx_uint>(g_in_shapes.ndims.size());
  *in_shape_ndim = g_in_shapes.ndims.data();
  *in_shape_data = g_in_shapes.ptrs.data();
  *out_shape_size = static_cast<mx_uint>(g_out_shapes.ndims.size());
  *out_shape_ndim = g_out_shapes.ndims.data();
  *out_shape_data = g_out_shapes.ptrs.data();
  *aux_shape_size = static_cast<mx_uint>(g_aux_shapes.ndims.size());
  *aux_shape_ndim = g_aux_shapes.ndims.data();
  *aux_shape_data = g_aux_shapes.ptrs.data();
  return 0;
}

// --- symbol type inference / attrs / views ---------------------------------
namespace {
thread_local std::vector<int> g_in_types, g_out_types, g_aux_types;
thread_local std::string g_ret_attr;
thread_local std::string g_ret_raw;

void intlist_from_py(PyObject* seq, std::vector<int>* out) {
  Py_ssize_t n = PySequence_Size(seq);
  out->assign(n, -1);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* it = PySequence_GetItem(seq, i);
    (*out)[i] = static_cast<int>(PyLong_AsLong(it));
    Py_XDECREF(it);
  }
}
}  // namespace

int MXSymbolInferType(SymbolHandle sym, mx_uint num_args,
                      const char** keys, const int* arg_type_data,
                      mx_uint* in_type_size, const int** in_type_data,
                      mx_uint* out_type_size, const int** out_type_data,
                      mx_uint* aux_type_size, const int** aux_type_data,
                      int* complete) {
  if (!sym) return fail("null handle");
  Gil gil;
  PyObject* names = list_from_strs(num_args, keys);
  PyObject* types = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyList_SET_ITEM(types, i, PyLong_FromLong(arg_type_data[i]));
  }
  PyObject* args = Py_BuildValue("(OOO)", sym, names, types);
  Py_DECREF(names);
  Py_DECREF(types);
  PyObject* r = args ? call("symbol_infer_type", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  intlist_from_py(PyTuple_GetItem(r, 0), &g_in_types);
  intlist_from_py(PyTuple_GetItem(r, 1), &g_out_types);
  intlist_from_py(PyTuple_GetItem(r, 2), &g_aux_types);
  *complete = PyObject_IsTrue(PyTuple_GetItem(r, 3));
  Py_DECREF(r);
  *in_type_size = static_cast<mx_uint>(g_in_types.size());
  *in_type_data = g_in_types.data();
  *out_type_size = static_cast<mx_uint>(g_out_types.size());
  *out_type_data = g_out_types.data();
  *aux_type_size = static_cast<mx_uint>(g_aux_types.size());
  *aux_type_data = g_aux_types.data();
  return 0;
}

int MXSymbolGetAttr(SymbolHandle sym, const char* key, const char** out,
                    int* success) {
  if (!sym) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", sym, key);
  PyObject* r = args ? call("symbol_get_attr", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  if (r == Py_None) {
    *out = nullptr;
    *success = 0;
  } else {
    const char* c = PyUnicode_AsUTF8(r);
    g_ret_attr = c ? c : "";
    *out = g_ret_attr.c_str();
    *success = 1;
  }
  Py_DECREF(r);
  return 0;
}

int MXSymbolSetAttr(SymbolHandle sym, const char* key, const char* value) {
  if (!sym) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(Oss)", sym, key, value);
  PyObject* r = args ? call("symbol_set_attr", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXSymbolGetInternals(SymbolHandle sym, SymbolHandle* out) {
  if (!sym) return fail("null handle");
  Gil gil;
  return handle_out_call("symbol_get_internals",
                         Py_BuildValue("(O)", sym), out);
}

int MXSymbolGetOutput(SymbolHandle sym, mx_uint index, SymbolHandle* out) {
  if (!sym) return fail("null handle");
  Gil gil;
  return handle_out_call("symbol_get_output",
                         Py_BuildValue("(OI)", sym, index), out);
}

// --- executor reshape ------------------------------------------------------
int MXExecutorReshape(ExecutorHandle handle, int partial_shaping,
                      int allow_up_sizing, mx_uint num_args,
                      const char** keys, const mx_uint* arg_ind_ptr,
                      const mx_uint* arg_shape_data, ExecutorHandle* out) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* names = list_from_strs(num_args, keys);
  PyObject* shapes = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    mx_uint b = arg_ind_ptr[i], e = arg_ind_ptr[i + 1];
    PyObject* shp = PyTuple_New(e - b);
    for (mx_uint j = b; j < e; ++j) {
      PyTuple_SET_ITEM(shp, j - b,
                       PyLong_FromUnsignedLong(arg_shape_data[j]));
    }
    PyList_SET_ITEM(shapes, i, shp);
  }
  PyObject* args = Py_BuildValue("(OiiOO)", handle, partial_shaping,
                                 allow_up_sizing, names, shapes);
  Py_DECREF(names);
  Py_DECREF(shapes);
  return handle_out_call("executor_reshape", args, out);
}

// --- kvstore string keys ---------------------------------------------------
namespace {
int kv_op_ex(const char* fn, KVStoreHandle handle, mx_uint num,
             const char** keys, NDArrayHandle* vals, int priority) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* k = list_from_strs(num, keys);
  PyObject* v = list_from_handles(num, vals);
  PyObject* args = std::string(fn) == "kvstore_init"
                       ? Py_BuildValue("(OOO)", handle, k, v)
                       : Py_BuildValue("(OOOi)", handle, k, v, priority);
  Py_DECREF(k);
  Py_DECREF(v);
  PyObject* r = args ? call(fn, args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}
}  // namespace

int MXKVStoreInitEx(KVStoreHandle handle, mx_uint num, const char** keys,
                    NDArrayHandle* vals) {
  return kv_op_ex("kvstore_init", handle, num, keys, vals, 0);
}

int MXKVStorePushEx(KVStoreHandle handle, mx_uint num, const char** keys,
                    NDArrayHandle* vals, int priority) {
  return kv_op_ex("kvstore_push", handle, num, keys, vals, priority);
}

int MXKVStorePullEx(KVStoreHandle handle, mx_uint num, const char** keys,
                    NDArrayHandle* vals, int priority) {
  return kv_op_ex("kvstore_pull", handle, num, keys, vals, priority);
}

// --- raw-bytes serialization -----------------------------------------------
int MXNDArraySaveRawBytes(NDArrayHandle handle, size_t* out_size,
                          const char** out_buf) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("ndarray_save_raw", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  char* src = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(r, &src, &n) != 0) {
    Py_DECREF(r);
    return fail_from_python();
  }
  g_ret_raw.assign(src, static_cast<size_t>(n));
  Py_DECREF(r);
  *out_buf = g_ret_raw.data();
  *out_size = g_ret_raw.size();
  return 0;
}

int MXNDArrayLoadFromRawBytes(const void* buf, size_t size,
                              NDArrayHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* data = PyBytes_FromStringAndSize(
      static_cast<const char*>(buf), static_cast<Py_ssize_t>(size));
  return handle_out_call("ndarray_load_raw", Py_BuildValue("(N)", data),
                         out);
}

// --- device discovery ------------------------------------------------------
int MXGetGPUCount(int* out) {
  ensure_python();
  Gil gil;
  PyObject* r = call("accelerator_count", nullptr);
  if (!r) return fail_from_python();
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

// --- cached op -------------------------------------------------------------
int MXCreateCachedOp(SymbolHandle sym, CachedOpHandle* out) {
  if (!sym) return fail("null handle");
  Gil gil;
  return handle_out_call("cached_op_create", Py_BuildValue("(O)", sym),
                         out);
}

int MXInvokeCachedOp(CachedOpHandle handle, int num_inputs,
                     NDArrayHandle* inputs, int* num_outputs,
                     NDArrayHandle** outputs) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* ins = list_from_handles(num_inputs, inputs);
  PyObject* args = Py_BuildValue("(OO)", handle, ins);
  Py_DECREF(ins);
  PyObject* r = args ? call("cached_op_invoke", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  mx_uint n = 0;
  handlelist_out(r, &n, outputs);
  *num_outputs = static_cast<int>(n);
  Py_DECREF(r);
  return 0;
}

int MXFreeCachedOp(CachedOpHandle handle) {
  if (!handle) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}

// --- data iterators --------------------------------------------------------
int MXListDataIters(mx_uint* out_size, const char*** out_array) {
  ensure_python();
  Gil gil;
  PyObject* r = call("list_data_iters", nullptr);
  if (!r) return fail_from_python();
  strlist_out(r, out_size, out_array);
  Py_DECREF(r);
  return 0;
}

int MXDataIterCreateIter(const char* iter_name, mx_uint num_param,
                         const char** keys, const char** vals,
                         DataIterHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* k = list_from_strs(num_param, keys);
  PyObject* v = list_from_strs(num_param, vals);
  PyObject* args = Py_BuildValue("(sOO)", iter_name, k, v);
  Py_DECREF(k);
  Py_DECREF(v);
  return handle_out_call("data_iter_create", args, out);
}

int MXDataIterBeforeFirst(DataIterHandle handle) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("data_iter_reset", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXDataIterNext(DataIterHandle handle, int* out) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("data_iter_next", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out = PyObject_IsTrue(r);
  Py_DECREF(r);
  return 0;
}

namespace {
int iter_field(const char* fn, DataIterHandle handle, NDArrayHandle* out) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call(fn, args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  if (r == Py_None) {
    Py_DECREF(r);
    *out = nullptr;
    return 0;
  }
  *out = r;
  return 0;
}
}  // namespace

int MXDataIterGetData(DataIterHandle handle, NDArrayHandle* out) {
  return iter_field("data_iter_data", handle, out);
}

int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle* out) {
  return iter_field("data_iter_label", handle, out);
}

int MXDataIterGetPadNum(DataIterHandle handle, int* pad) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("data_iter_pad", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *pad = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXDataIterFree(DataIterHandle handle) {
  if (!handle) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}

// --- RecordIO --------------------------------------------------------------
int MXRecordIOWriterCreate(const char* uri, RecordIOHandle* out) {
  ensure_python();
  Gil gil;
  return handle_out_call("recordio_writer_create",
                         Py_BuildValue("(s)", uri), out);
}

int MXRecordIOWriterWriteRecord(RecordIOHandle handle, const char* buf,
                                size_t size) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* data = PyBytes_FromStringAndSize(
      buf, static_cast<Py_ssize_t>(size));
  PyObject* args = Py_BuildValue("(ON)", handle, data);
  PyObject* r = args ? call("recordio_write", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXRecordIOWriterFree(RecordIOHandle handle) {
  if (!handle) return 0;
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("recordio_close", args) : nullptr;
  Py_XDECREF(args);
  Py_DECREF(static_cast<PyObject*>(handle));
  if (!r) {
    // close can fail for real (ENOSPC on final flush) — report it and
    // clear the error indicator so the next call on this thread is clean
    return fail_from_python();
  }
  Py_DECREF(r);
  return 0;
}

int MXRecordIOReaderCreate(const char* uri, RecordIOHandle* out) {
  ensure_python();
  Gil gil;
  return handle_out_call("recordio_reader_create",
                         Py_BuildValue("(s)", uri), out);
}

int MXRecordIOReaderReadRecord(RecordIOHandle handle, const char** buf,
                               size_t* size) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("recordio_read", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  if (r == Py_None) {
    Py_DECREF(r);
    *buf = nullptr;
    *size = 0;
    return 0;
  }
  char* src = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(r, &src, &n) != 0) {
    Py_DECREF(r);
    return fail_from_python();
  }
  g_ret_record.assign(src, static_cast<size_t>(n));
  Py_DECREF(r);
  *buf = g_ret_record.data();
  *size = g_ret_record.size();
  return 0;
}

int MXRecordIOReaderFree(RecordIOHandle handle) {
  return MXRecordIOWriterFree(handle);
}

// --- profiler --------------------------------------------------------------
int MXSetProcessProfilerConfig(int num_params, const char** keys,
                               const char** vals) {
  ensure_python();
  Gil gil;
  PyObject* k = list_from_strs(num_params, keys);
  PyObject* v = list_from_strs(num_params, vals);
  PyObject* args = Py_BuildValue("(OO)", k, v);
  Py_DECREF(k);
  Py_DECREF(v);
  PyObject* r = args ? call("profiler_config", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXSetProcessProfilerState(int state) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", state);
  PyObject* r = args ? call("profiler_state", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXDumpProcessProfile(int finished) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", finished);
  PyObject* r = args ? call("profiler_dump", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXAggregateProfileStatsPrint(const char** out_str, int reset) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", reset);
  PyObject* r = args ? call("profiler_stats", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  const char* c = PyUnicode_AsUTF8(r);
  g_ret_json = c ? c : "";
  Py_DECREF(r);
  *out_str = g_ret_json.c_str();
  return 0;
}

// --- kvstore ---------------------------------------------------------------
int MXKVStoreCreate(const char* type, KVStoreHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", type);
  PyObject* r = args ? call("kvstore_create", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out = r;
  return 0;
}

namespace {
int kv_op(const char* fn, KVStoreHandle handle, mx_uint num,
          const int* keys, NDArrayHandle* vals, int priority) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* k = PyList_New(num);
  for (mx_uint i = 0; i < num; ++i) {
    PyList_SET_ITEM(k, i, PyLong_FromLong(keys[i]));
  }
  PyObject* v = list_from_handles(num, vals);
  PyObject* args = std::string(fn) == "kvstore_init"
                       ? Py_BuildValue("(OOO)", handle, k, v)
                       : Py_BuildValue("(OOOi)", handle, k, v, priority);
  Py_DECREF(k);
  Py_DECREF(v);
  PyObject* r = args ? call(fn, args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}
}  // namespace

int MXKVStoreInit(KVStoreHandle handle, mx_uint num, const int* keys,
                  NDArrayHandle* vals) {
  return kv_op("kvstore_init", handle, num, keys, vals, 0);
}

int MXKVStorePush(KVStoreHandle handle, mx_uint num, const int* keys,
                  NDArrayHandle* vals, int priority) {
  return kv_op("kvstore_push", handle, num, keys, vals, priority);
}

int MXKVStorePull(KVStoreHandle handle, mx_uint num, const int* keys,
                  NDArrayHandle* vals, int priority) {
  return kv_op("kvstore_pull", handle, num, keys, vals, priority);
}

int MXKVStoreGetRank(KVStoreHandle handle, int* rank) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("kvstore_rank_size", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *rank = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(r, 0)));
  Py_DECREF(r);
  return 0;
}

int MXKVStoreGetGroupSize(KVStoreHandle handle, int* size) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("kvstore_rank_size", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *size = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(r, 1)));
  Py_DECREF(r);
  return 0;
}

int MXKVStoreFree(KVStoreHandle handle) {
  if (!handle) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}



/* ---- op discovery / symbol extras (round-5 width; reference c_api.h:963,
   974, 1002, 1126, 1145, 1168, 1511, 1562) ------------------------------- */

// creator handles must stay valid for the PROCESS lifetime (binding
// generators cache them across unrelated C API calls), so names are
// interned in a node-based container whose element addresses never move.
static std::set<std::string>& creator_intern() {
  static std::set<std::string>* s = new std::set<std::string>();
  return *s;
}

int MXSymbolListAtomicSymbolCreators(mx_uint* out_size, void*** out_array) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("()");
  PyObject* r = args ? call("atomic_symbol_creators", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_ssize_t n = PySequence_Size(r);
  static thread_local std::vector<void*> creators;
  creators.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* it = PySequence_GetItem(r, i);
    auto ins = creator_intern().insert(PyUnicode_AsUTF8(it));
    Py_XDECREF(it);
    creators.push_back(const_cast<char*>(ins.first->c_str()));
  }
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(n);
  *out_array = creators.data();
  return 0;
}

int MXSymbolGetAtomicSymbolName(void* creator, const char** name) {
  /* creators ARE their interned names in this ABI */
  *name = static_cast<const char*>(creator);
  return 0;
}

int MXSymbolGetAtomicSymbolInfo(void* creator, const char** name,
                                const char** description, mx_uint* num_args,
                                const char*** arg_names,
                                const char*** arg_type_infos,
                                const char*** arg_descriptions,
                                const char** key_var_num_args,
                                const char** return_type) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", static_cast<const char*>(creator));
  PyObject* r = args ? call("atomic_symbol_info", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  // two-phase: materialize EVERY string first, take pointers after —
  // c_str() captured mid-growth dangles once the vector reallocates
  static thread_local std::vector<std::string> strs;
  static thread_local std::vector<const char*> names_v, types_v, descs_v;
  strs.clear(); names_v.clear(); types_v.clear(); descs_v.clear();
  auto S = [](PyObject* o) -> std::string {
    return (o && PyUnicode_Check(o)) ? PyUnicode_AsUTF8(o) : "";
  };
  strs.push_back(S(PyTuple_GetItem(r, 0)));  // [0] name
  strs.push_back(S(PyTuple_GetItem(r, 1)));  // [1] description
  strs.push_back(S(PyTuple_GetItem(r, 5)));  // [2] key_var_num_args
  strs.push_back(S(PyTuple_GetItem(r, 6)));  // [3] return_type
  PyObject *an = PyTuple_GetItem(r, 2), *at = PyTuple_GetItem(r, 3),
           *ad = PyTuple_GetItem(r, 4);
  Py_ssize_t n = PySequence_Size(an);
  for (Py_ssize_t i = 0; i < n; ++i) {
    for (PyObject* seq : {an, at, ad}) {
      PyObject* it = PySequence_GetItem(seq, i);
      strs.push_back(S(it));
      Py_XDECREF(it);
    }
  }
  Py_DECREF(r);
  for (Py_ssize_t i = 0; i < n; ++i) {
    names_v.push_back(strs[4 + 3 * i].c_str());
    types_v.push_back(strs[4 + 3 * i + 1].c_str());
    descs_v.push_back(strs[4 + 3 * i + 2].c_str());
  }
  if (name) *name = strs[0].c_str();
  if (description) *description = strs[1].c_str();
  if (num_args) *num_args = static_cast<mx_uint>(n);
  if (arg_names) *arg_names = names_v.data();
  if (arg_type_infos) *arg_type_infos = types_v.data();
  if (arg_descriptions) *arg_descriptions = descs_v.data();
  if (key_var_num_args) *key_var_num_args = strs[2].c_str();
  if (return_type) *return_type = strs[3].c_str();
  return 0;
}

int MXSymbolCopy(SymbolHandle symbol, SymbolHandle* out) {
  if (!symbol) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", symbol);
  PyObject* r = args ? call("symbol_copy", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out = r;
  return 0;
}

int MXSymbolGetName(SymbolHandle symbol, const char** out, int* success) {
  if (!symbol) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", symbol);
  PyObject* r = args ? call("symbol_name", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  g_ret_json = PyUnicode_AsUTF8(r);
  Py_DECREF(r);
  *out = g_ret_json.c_str();
  if (success) *success = g_ret_json.empty() ? 0 : 1;
  return 0;
}

int MXSymbolGetNumOutputs(SymbolHandle symbol, mx_uint* output_count) {
  if (!symbol) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", symbol);
  PyObject* r = args ? call("symbol_num_outputs", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *output_count = static_cast<mx_uint>(PyLong_AsUnsignedLong(r));
  Py_DECREF(r);
  return 0;
}

int MXSymbolCompose(SymbolHandle sym, const char* name, mx_uint num_args,
                    const char** keys, SymbolHandle* args_handles) {
  if (!sym) return fail("null handle");
  Gil gil;
  PyObject* ks = list_from_strs(keys ? num_args : 0, keys);
  PyObject* ins = list_from_handles(num_args, args_handles);
  PyObject* args = Py_BuildValue("(OsOO)", sym, name ? name : "", ks, ins);
  Py_DECREF(ks);
  Py_DECREF(ins);
  PyObject* r = args ? call("symbol_compose", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

/* ---- autograd / ndarray extras ------------------------------------------ */

int MXAutogradIsRecording(bool* curr) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("()");
  PyObject* r = args ? call("autograd_is_recording", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *curr = PyObject_IsTrue(r);
  Py_DECREF(r);
  return 0;
}

int MXAutogradIsTraining(bool* curr) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("()");
  PyObject* r = args ? call("autograd_is_training", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *curr = PyObject_IsTrue(r);
  Py_DECREF(r);
  return 0;
}

int MXNDArrayDetach(NDArrayHandle handle, NDArrayHandle* out) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("ndarray_detach", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out = r;
  return 0;
}

int MXNDArrayLoadFromBuffer(const void* ndarray_buffer, size_t size,
                            mx_uint* out_size, NDArrayHandle** out_arr,
                            mx_uint* out_name_size,
                            const char*** out_names) {
  ensure_python();
  Gil gil;
  PyObject* buf = PyBytes_FromStringAndSize(
      static_cast<const char*>(ndarray_buffer),
      static_cast<Py_ssize_t>(size));
  PyObject* args = Py_BuildValue("(O)", buf);
  Py_XDECREF(buf);
  PyObject* r = args ? call("ndarray_load_from_buffer", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  PyObject* arrs = PyTuple_GetItem(r, 0);
  PyObject* names = PyTuple_GetItem(r, 1);
  handlelist_out(arrs, out_size, out_arr);
  strlist_out(names, out_name_size, out_names);
  Py_DECREF(r);
  return 0;
}

/* ---- kvstore extras ----------------------------------------------------- */

int MXKVStoreBarrier(KVStoreHandle handle) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("kvstore_barrier", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXKVStoreGetType(KVStoreHandle handle, const char** type) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("kvstore_type", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  g_ret_json = PyUnicode_AsUTF8(r);
  Py_DECREF(r);
  *type = g_ret_json.c_str();
  return 0;
}

int MXKVStoreSendCommmandToServers(KVStoreHandle handle, int cmd_id,
                                   const char* cmd_body) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(Ois)", handle, cmd_id,
                                 cmd_body ? cmd_body : "");
  PyObject* r = args ? call("kvstore_send_command", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXKVStoreGetNumDeadNode(KVStoreHandle handle, const int node_id,
                            int* number, const int timeout_sec) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(Oii)", handle, node_id, timeout_sec);
  PyObject* r = args ? call("kvstore_num_dead_node", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *number = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXKVStorePushPull(KVStoreHandle handle, mx_uint num, const int* keys,
                      NDArrayHandle* in_vals, NDArrayHandle* out_vals,
                      int priority) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* ks = PyList_New(num);
  for (mx_uint i = 0; i < num; ++i) {
    PyList_SET_ITEM(ks, i, PyLong_FromLong(keys[i]));
  }
  PyObject* ins = list_from_handles(num, in_vals);
  PyObject* outs = list_from_handles(num, out_vals);
  PyObject* args = Py_BuildValue("(OOOOi)", handle, ks, ins, outs,
                                 priority);
  Py_DECREF(ks);
  Py_DECREF(ins);
  Py_DECREF(outs);
  PyObject* r = args ? call("kvstore_pushpull", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

/* ---- misc extras -------------------------------------------------------- */

int MXGetGPUMemoryInformation64(int dev, uint64_t* free_mem,
                                uint64_t* total_mem) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(ii)", 2, dev);
  PyObject* r = args ? call("device_memory_info", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *free_mem = PyLong_AsUnsignedLongLong(PyTuple_GetItem(r, 0));
  *total_mem = PyLong_AsUnsignedLongLong(PyTuple_GetItem(r, 1));
  Py_DECREF(r);
  return 0;
}

int MXNotifyShutdown(void) {
  return 0;  /* engine shutdown is XLA/atexit-owned in this runtime */
}




int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                       const char** keys, const mx_uint* arg_ind_ptr,
                       const mx_uint* arg_shape_data,
                       mx_uint* in_shape_size,
                       const mx_uint** in_shape_ndim,
                       const mx_uint*** in_shape_data,
                       mx_uint* out_shape_size,
                       const mx_uint** out_shape_ndim,
                       const mx_uint*** out_shape_data,
                       mx_uint* aux_shape_size,
                       const mx_uint** aux_shape_ndim,
                       const mx_uint*** aux_shape_data,
                       int* complete) {
  return infer_shape_common("symbol_infer_shape", sym, num_args, keys,
                            arg_ind_ptr, arg_shape_data, in_shape_size,
                            in_shape_ndim, in_shape_data, out_shape_size,
                            out_shape_ndim, out_shape_data, aux_shape_size,
                            aux_shape_ndim, aux_shape_data, complete);
}

int MXSymbolInferShapePartial(SymbolHandle sym, mx_uint num_args,
                              const char** keys,
                              const mx_uint* arg_ind_ptr,
                              const mx_uint* arg_shape_data,
                              mx_uint* in_shape_size,
                              const mx_uint** in_shape_ndim,
                              const mx_uint*** in_shape_data,
                              mx_uint* out_shape_size,
                              const mx_uint** out_shape_ndim,
                              const mx_uint*** out_shape_data,
                              mx_uint* aux_shape_size,
                              const mx_uint** aux_shape_ndim,
                              const mx_uint*** aux_shape_data,
                              int* complete) {
  return infer_shape_common("symbol_infer_shape_partial4", sym, num_args,
                            keys, arg_ind_ptr, arg_shape_data,
                            in_shape_size, in_shape_ndim, in_shape_data,
                            out_shape_size, out_shape_ndim, out_shape_data,
                            aux_shape_size, aux_shape_ndim, aux_shape_data,
                            complete);
}

/* ---- final width batch: file serde, 64-bit view aliases, invoke alias,
   gradient compression, iterator info ------------------------------------ */

int MXSymbolSaveToFile(SymbolHandle symbol, const char* fname) {
  if (!symbol) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", symbol, fname);
  PyObject* r = args ? call("symbol_save_file", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXSymbolCreateFromFile(const char* fname, SymbolHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", fname);
  PyObject* r = args ? call("symbol_load_file", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out = r;
  return 0;
}

int MXImperativeInvoke(const char* op_name, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, int num_params,
                       const char** param_keys, const char** param_vals) {
  return MXImperativeInvokeEx(op_name, num_inputs, inputs, num_outputs,
                              outputs, num_params, param_keys, param_vals);
}

int MXNDArrayAt64(NDArrayHandle handle, int64_t idx, NDArrayHandle* out) {
  /* the int32 narrowing contract is LOUD (mxnet_tpu/base.py): refuse
     rather than truncate */
  if (idx < 0 || idx > UINT32_MAX) return fail("index beyond uint32 range");
  return MXNDArrayAt(handle, static_cast<mx_uint>(idx), out);
}

int MXNDArraySlice64(NDArrayHandle handle, int64_t begin, int64_t end,
                     NDArrayHandle* out) {
  if (begin < 0 || begin > UINT32_MAX || end < 0 || end > UINT32_MAX) {
    return fail("slice bound beyond uint32 range");
  }
  return MXNDArraySlice(handle, static_cast<mx_uint>(begin),
                        static_cast<mx_uint>(end), out);
}

int MXKVStoreSetGradientCompression(KVStoreHandle handle, mx_uint num_params,
                                    const char** keys, const char** vals) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* ks = list_from_strs(num_params, keys);
  PyObject* vs = list_from_strs(num_params, vals);
  PyObject* args = Py_BuildValue("(OOO)", handle, ks, vs);
  Py_DECREF(ks);
  Py_DECREF(vs);
  PyObject* r = args ? call("kvstore_set_gradient_compression", args)
                     : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXDataIterGetIterInfo(void* creator, const char** name,
                          const char** description, mx_uint* num_args,
                          const char*** arg_names,
                          const char*** arg_type_infos,
                          const char*** arg_descriptions) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", static_cast<const char*>(creator));
  PyObject* r = args ? call("data_iter_list_info", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  static thread_local std::string nm, doc;
  nm = PyUnicode_AsUTF8(PyTuple_GetItem(r, 0));
  doc = PyUnicode_AsUTF8(PyTuple_GetItem(r, 1));
  Py_DECREF(r);
  if (name) *name = nm.c_str();
  if (description) *description = doc.c_str();
  /* arg metadata from the iterator class's constructor signature */
  static thread_local std::vector<std::string> astrs;
  static thread_local std::vector<const char*> anames, atypes, adescs;
  astrs.clear(); anames.clear(); atypes.clear(); adescs.clear();
  {
    Gil gil2;
    PyObject* a2 = Py_BuildValue("(s)", nm.c_str());
    PyObject* r2 = a2 ? call("data_iter_arg_names", a2) : nullptr;
    Py_XDECREF(a2);
    if (r2) {
      Py_ssize_t na = PySequence_Size(r2);
      for (Py_ssize_t i = 0; i < na; ++i) {
        PyObject* it = PySequence_GetItem(r2, i);
        astrs.emplace_back(PyUnicode_AsUTF8(it));
        Py_XDECREF(it);
      }
      Py_DECREF(r2);
      for (auto& s2 : astrs) {
        anames.push_back(s2.c_str());
        atypes.push_back("");
        adescs.push_back("");
      }
    } else {
      PyErr_Clear();
    }
  }
  if (num_args) *num_args = static_cast<mx_uint>(anames.size());
  if (arg_names) *arg_names = anames.data();
  if (arg_type_infos) *arg_type_infos = atypes.data();
  if (arg_descriptions) *arg_descriptions = adescs.data();
  return 0;
}

/* ---- misc batch 4: profiler aliases, feature flags, numpy-shape toggle,
   engine knobs (reference c_api.h:235+, 2618+, profiler legacy names) ---- */

int MXSetProfilerConfig(int num_params, const char** keys,
                        const char** vals) {
  return MXSetProcessProfilerConfig(num_params, keys, vals);
}

int MXSetProfilerState(int state) { return MXSetProcessProfilerState(state); }

int MXDumpProfile(int finished) { return MXDumpProcessProfile(finished); }

struct LibFeature {
  const char* name;
  bool enabled;
};

int MXLibInfoFeatures(const struct LibFeature** libFeature, size_t* size) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("()");
  PyObject* r = args ? call("lib_features", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  static thread_local std::vector<std::string> names;
  static thread_local std::vector<LibFeature> feats;
  names.clear();
  feats.clear();
  Py_ssize_t n = PySequence_Size(r);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* it = PySequence_GetItem(r, i);
    names.emplace_back(PyUnicode_AsUTF8(PyTuple_GetItem(it, 0)));
    Py_XDECREF(it);
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* it = PySequence_GetItem(r, i);
    feats.push_back({names[i].c_str(),
                     PyObject_IsTrue(PyTuple_GetItem(it, 1)) == 1});
    Py_XDECREF(it);
  }
  Py_DECREF(r);
  *libFeature = feats.data();
  *size = static_cast<size_t>(n);
  return 0;
}

int MXSetIsNumpyShape(int is_np_shape, int* prev) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", is_np_shape);
  PyObject* r = args ? call("set_numpy_shape", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  /* tri-state (0/1/2=GlobalOn): PyLong, not truthiness */
  if (prev) *prev = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXIsNumpyShape(int* curr) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("()");
  PyObject* r = args ? call("is_numpy_shape", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *curr = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXEngineSetBulkSize(int bulk_size, int* prev_bulk_size) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", bulk_size);
  PyObject* r = args ? call("engine_set_bulk_size", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  if (prev_bulk_size) {
    *prev_bulk_size = static_cast<int>(PyLong_AsLong(r));
  }
  Py_DECREF(r);
  return 0;
}

int MXRandomSeedContext(int seed, int dev_type, int dev_id) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(iii)", seed, dev_type, dev_id);
  PyObject* r = args ? call("random_seed_context", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXStorageEmptyCache(int dev_type, int dev_id) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(ii)", dev_type, dev_id);
  PyObject* r = args ? call("storage_empty_cache", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXGetGPUMemoryInformation(int dev, int* free_mem, int* total_mem) {
  uint64_t f = 0, t = 0;
  int rc = MXGetGPUMemoryInformation64(dev, &f, &t);
  if (rc) return rc;
  *free_mem = static_cast<int>(f >> 20);   /* MiB, like the reference */
  *total_mem = static_cast<int>(t >> 20);
  return 0;
}

int MXKVStoreSetBarrierBeforeExit(KVStoreHandle handle,
                                  const int barrier_before_exit) {
  (void)handle; (void)barrier_before_exit;
  return 0;  /* exit barriers are the launcher's job in this runtime */
}

/* ---- PS env / roles / server loop (reference c_api.h:2290, 2559+) ------- */

int MXInitPSEnv(mx_uint num_vars, const char** keys, const char** vals) {
  ensure_python();
  Gil gil;
  PyObject* ks = list_from_strs(num_vars, keys);
  PyObject* vs = list_from_strs(num_vars, vals);
  PyObject* args = Py_BuildValue("(OO)", ks, vs);
  Py_DECREF(ks);
  Py_DECREF(vs);
  PyObject* r = args ? call("init_ps_env", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

static int role_is(const char* want, int* ret) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("()");
  PyObject* r = args ? call("kvstore_role", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *ret = strcmp(PyUnicode_AsUTF8(r), want) == 0 ? 1 : 0;
  Py_DECREF(r);
  return 0;
}

int MXKVStoreIsWorkerNode(int* ret) { return role_is("worker", ret); }
int MXKVStoreIsServerNode(int* ret) { return role_is("server", ret); }
int MXKVStoreIsSchedulerNode(int* ret) { return role_is("scheduler", ret); }

typedef void (MXKVStoreServerController)(int head, const char* body,
                                         void* controller_handle);

int MXKVStoreRunServer(KVStoreHandle handle,
                       MXKVStoreServerController controller,
                       void* controller_handle) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKK)", handle,
      reinterpret_cast<unsigned long long>(controller),
      reinterpret_cast<unsigned long long>(controller_handle));
  PyObject* r = args ? call("kvstore_run_server", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

/* ---- SimpleBind (reference c_api.h:2046 MXExecutorSimpleBindEx; the
   g2c/stype/shared-buffer channels of the full signature are accepted
   and ignored — shape/dtype/grad_req drive allocation) ------------------- */

int MXExecutorSimpleBindEx(
    SymbolHandle symbol_handle, int dev_type, int dev_id,
    const mx_uint num_g2c_keys, const char** g2c_keys,
    const int* g2c_dev_types, const int* g2c_dev_ids,
    const mx_uint provided_grad_req_list_len,
    const char** provided_grad_req_names,
    const char** provided_grad_req_types,
    const mx_uint num_provided_arg_shapes,
    const char** provided_arg_shape_names,
    const int* provided_arg_shape_data,
    const mx_uint* provided_arg_shape_idx,
    const mx_uint num_provided_arg_dtypes,
    const char** provided_arg_dtype_names, const int* provided_arg_dtypes,
    const mx_uint num_provided_arg_stypes,
    const char** provided_arg_stype_names, const int* provided_arg_stypes,
    const mx_uint num_shared_arg_names, const char** shared_arg_name_list,
    int* shared_buffer_len, const char** shared_buffer_name_list,
    NDArrayHandle* shared_buffer_handle_list,
    const char*** updated_shared_buffer_name_list,
    NDArrayHandle** updated_shared_buffer_handle_list,
    mx_uint* num_in_args, NDArrayHandle** in_args, NDArrayHandle** arg_grads,
    mx_uint* num_aux_states, NDArrayHandle** aux_states,
    ExecutorHandle shared_exec_handle, ExecutorHandle* out) {
  (void)num_g2c_keys; (void)g2c_keys; (void)g2c_dev_types; (void)g2c_dev_ids;
  (void)num_provided_arg_stypes; (void)provided_arg_stype_names;
  (void)provided_arg_stypes; (void)num_shared_arg_names;
  (void)shared_arg_name_list; (void)shared_buffer_len;
  (void)shared_buffer_name_list; (void)shared_buffer_handle_list;
  (void)updated_shared_buffer_name_list;
  (void)updated_shared_buffer_handle_list; (void)shared_exec_handle;
  if (!symbol_handle) return fail("null symbol");
  Gil gil;
  PyObject* req_ns = list_from_strs(provided_grad_req_list_len,
                                    provided_grad_req_names);
  /* reference convention: list_len == 0 with a non-NULL types pointer
     means ONE global grad_req string for every argument */
  mx_uint n_req_types = provided_grad_req_list_len;
  if (n_req_types == 0 && provided_grad_req_types != nullptr) {
    n_req_types = 1;
  }
  PyObject* req_ts = list_from_strs(n_req_types, provided_grad_req_types);
  PyObject* shp_ns = list_from_strs(num_provided_arg_shapes,
                                    provided_arg_shape_names);
  PyObject* shp_vs = PyList_New(num_provided_arg_shapes);
  for (mx_uint i = 0; i < num_provided_arg_shapes; ++i) {
    mx_uint lo = provided_arg_shape_idx[i];
    mx_uint hi = provided_arg_shape_idx[i + 1];
    PyObject* t = PyTuple_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j) {
      PyTuple_SET_ITEM(t, j - lo,
                       PyLong_FromLong(provided_arg_shape_data[j]));
    }
    PyList_SET_ITEM(shp_vs, i, t);
  }
  PyObject* dt_ns = list_from_strs(num_provided_arg_dtypes,
                                   provided_arg_dtype_names);
  PyObject* dt_vs = PyList_New(num_provided_arg_dtypes);
  for (mx_uint i = 0; i < num_provided_arg_dtypes; ++i) {
    PyList_SET_ITEM(dt_vs, i, PyLong_FromLong(provided_arg_dtypes[i]));
  }
  PyObject* args = Py_BuildValue("(OiiOOOOOO)", symbol_handle, dev_type,
                                 dev_id, req_ns, req_ts, shp_ns, shp_vs,
                                 dt_ns, dt_vs);
  Py_DECREF(req_ns); Py_DECREF(req_ts); Py_DECREF(shp_ns);
  Py_DECREF(shp_vs); Py_DECREF(dt_ns); Py_DECREF(dt_vs);
  PyObject* r = args ? call("executor_simple_bind", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  /* r = (executor, in_args, arg_grads_with_None, aux_states).
     THREE separate out-arrays from one call: each needs its own backing
     store (handlelist_out's shared g_ret_handles would clobber the
     earlier out-param on every later call). */
  PyObject* ex = PyTuple_GetItem(r, 0);
  Py_INCREF(ex);
  static thread_local std::vector<NDArrayHandle> in_v, grads_v, aux_v;
  auto fill = [](PyObject* seq, std::vector<NDArrayHandle>* dst,
                 bool allow_none) {
    dst->clear();
    Py_ssize_t n = PySequence_Size(seq);
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* it = PySequence_GetItem(seq, i);
      if (allow_none && it == Py_None) {
        dst->push_back(nullptr);
        Py_XDECREF(it);
      } else {
        dst->push_back(it);  /* owned ref kept for the caller */
      }
    }
    return static_cast<mx_uint>(n);
  };
  *num_in_args = fill(PyTuple_GetItem(r, 1), &in_v, false);
  *in_args = in_v.data();
  fill(PyTuple_GetItem(r, 2), &grads_v, true);
  *arg_grads = grads_v.data();
  *num_aux_states = fill(PyTuple_GetItem(r, 3), &aux_v, false);
  *aux_states = aux_v.data();
  Py_DECREF(r);
  *out = ex;
  return 0;
}

/* ---- symbol attr listing (reference c_api.h MXSymbolListAttr) ----------- */

static int list_attr_impl(SymbolHandle symbol, int shallow, mx_uint* out_size,
                          const char*** out) {
  if (!symbol) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", symbol, shallow);
  PyObject* r = args ? call("symbol_list_attr", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  mx_uint n = 0;
  strlist_out(r, &n, out);
  *out_size = n / 2;  /* reference counts PAIRS */
  Py_DECREF(r);
  return 0;
}

int MXSymbolListAttr(SymbolHandle symbol, mx_uint* out_size,
                     const char*** out) {
  return list_attr_impl(symbol, 0, out_size, out);
}

int MXSymbolListAttrShallow(SymbolHandle symbol, mx_uint* out_size,
                            const char*** out) {
  return list_attr_impl(symbol, 1, out_size, out);
}

/* ---- sparse NDArray (round-5; reference c_api.h:577+) ------------------- */

int MXNDArrayCreateSparseEx(int storage_type, const mx_uint* shape,
                            mx_uint ndim, int dev_type, int dev_id,
                            int delay_alloc, int dtype, mx_uint num_aux,
                            int* aux_type, mx_uint* aux_ndims,
                            const mx_uint* aux_shape, NDArrayHandle* out) {
  (void)delay_alloc; (void)num_aux; (void)aux_type; (void)aux_ndims;
  (void)aux_shape;  /* aux blobs arrive later via SyncCopyFromNDArray */
  ensure_python();
  Gil gil;
  PyObject* shp = PyTuple_New(ndim);
  for (mx_uint i = 0; i < ndim; ++i) {
    PyTuple_SET_ITEM(shp, i, PyLong_FromUnsignedLong(shape[i]));
  }
  PyObject* args = Py_BuildValue("(iOiii)", storage_type, shp, dev_type,
                                 dev_id, dtype);
  Py_DECREF(shp);
  PyObject* r = args ? call("ndarray_create_sparse", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out = r;
  return 0;
}

int MXNDArrayGetStorageType(NDArrayHandle handle, int* out_storage_type) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("ndarray_storage_type", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out_storage_type = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCopyFromNDArray(NDArrayHandle handle_dst,
                                 const NDArrayHandle handle_src,
                                 const int i) {
  if (!handle_dst || !handle_src) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(OOi)", handle_dst, handle_src, i);
  PyObject* r = args ? call("ndarray_sync_copy_from_ndarray", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCheckFormat(NDArrayHandle handle, const bool full_check) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", handle, full_check ? 1 : 0);
  PyObject* r = args ? call("ndarray_check_format", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXNDArrayGetAuxType(NDArrayHandle handle, mx_uint i, int* out_type) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(OI)", handle, i);
  PyObject* r = args ? call("ndarray_get_aux_type", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out_type = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXNDArrayGetAuxNDArray(NDArrayHandle handle, mx_uint i,
                           NDArrayHandle* out) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(OI)", handle, i);
  PyObject* r = args ? call("ndarray_get_aux_ndarray", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out = r;
  return 0;
}

int MXNDArrayGetDataNDArray(NDArrayHandle handle, NDArrayHandle* out) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = args ? call("ndarray_get_data_ndarray", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  *out = r;
  return 0;
}

/* ---- kvstore updaters (reference c_api.h:2503+) ------------------------- */

typedef void (MXKVStoreUpdater)(int key, NDArrayHandle recv,
                                NDArrayHandle local, void* handle);
typedef void (MXKVStoreStrUpdater)(const char* key, NDArrayHandle recv,
                                   NDArrayHandle local, void* handle);

int MXKVStoreSetUpdater(KVStoreHandle handle, MXKVStoreUpdater updater,
                        void* updater_handle) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKKi)", handle,
      reinterpret_cast<unsigned long long>(updater),
      reinterpret_cast<unsigned long long>(updater_handle), 0);
  PyObject* r = args ? call("kvstore_set_updater", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXKVStoreSetStrUpdater(KVStoreHandle handle, MXKVStoreStrUpdater updater,
                           void* updater_handle) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKKi)", handle,
      reinterpret_cast<unsigned long long>(updater),
      reinterpret_cast<unsigned long long>(updater_handle), 1);
  PyObject* r = args ? call("kvstore_set_updater", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXKVStoreSetUpdaterEx(KVStoreHandle handle, MXKVStoreUpdater updater,
                          MXKVStoreStrUpdater str_updater,
                          void* updater_handle) {
  /* int-keyed stores use `updater`, string-keyed use `str_updater`; this
     framework's kvstore normalizes keys, so install whichever is given
     (int wins when both are). */
  if (updater) return MXKVStoreSetUpdater(handle, updater, updater_handle);
  return MXKVStoreSetStrUpdater(handle, str_updater, updater_handle);
}

/* ---- executor monitor callback (reference c_api.h:2170) ----------------- */

typedef void (*ExecutorMonitorCallback)(const char*, NDArrayHandle, void*);

int MXExecutorSetMonitorCallbackEX(ExecutorHandle handle,
                                   ExecutorMonitorCallback callback,
                                   void* callback_handle, bool monitor_all) {
  if (!handle) return fail("null handle");
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OKKi)", handle,
      reinterpret_cast<unsigned long long>(callback),
      reinterpret_cast<unsigned long long>(callback_handle),
      monitor_all ? 1 : 0);
  PyObject* r = args ? call("executor_set_monitor_callback", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXExecutorSetMonitorCallback(ExecutorHandle handle,
                                 ExecutorMonitorCallback callback,
                                 void* callback_handle) {
  return MXExecutorSetMonitorCallbackEX(handle, callback, callback_handle,
                                        false);
}

/* ---- custom op registration (reference c_api.h:2745) -------------------- */

typedef int (*CustomOpPropCreator)(const char*, const int, const char**,
                                   const char**, struct MXCallbackList*);

int MXCustomOpRegister(const char* op_type, CustomOpPropCreator creator) {
  if (!op_type || !creator) return fail("null op_type/creator");
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(sK)", op_type, reinterpret_cast<unsigned long long>(creator));
  PyObject* r = args ? call("custom_op_register", args) : nullptr;
  Py_XDECREF(args);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

}  // extern "C"
