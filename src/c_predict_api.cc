// C predict API for mxnet_tpu (parity: include/mxnet/c_predict_api.h —
// the reference's standalone inference ABI that every language binding
// wraps: MXPredCreate/SetInput/Forward/GetOutput/Free + MXGetLastError).
//
// Architecture: the reference's C API fronts a C++ core; this framework's
// core is Python-over-JAX, so the ABI embeds CPython (or joins an already
// initialized interpreter when loaded INTO a Python process) and drives
// the helper module mxnet_tpu.c_predict under the GIL. Any C-capable
// language links this exactly like the reference's libmxnet_predict.
//
// Build: make -C src predict   (links libpython3; see src/Makefile)

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

typedef void* PredictorHandle;
typedef uint32_t mx_uint;

namespace {

std::mutex g_mutex;
thread_local std::string g_last_error;
bool g_we_initialized = false;

struct Predictor {
  PyObject* py_pred = nullptr;          // mxnet_tpu.c_predict.Predictor
  std::vector<std::vector<mx_uint>> out_shapes;
};

class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

void ensure_python() {
  std::lock_guard<std::mutex> lk(g_mutex);
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized = true;
    // release the GIL acquired by Py_Initialize so Gil{} works uniformly
    PyEval_SaveThread();
  }
}

int fail_from_python() {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  PyObject* s = value ? PyObject_Str(value) : nullptr;
  const char* c = s ? PyUnicode_AsUTF8(s) : nullptr;
  g_last_error = c ? c : "unknown python error";
  Py_XDECREF(s);
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return -1;
}

int fail(const std::string& msg) {
  g_last_error = msg;
  return -1;
}

}  // namespace

extern "C" {

const char* MXGetLastError() { return g_last_error.c_str(); }

// Create a predictor from symbol JSON + serialized params (the bytes of a
// .params file), binding input shapes (CSR layout via indptr, as in the
// reference signature c_predict_api.h:87).
int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char** input_keys,
                 const mx_uint* input_shape_indptr,
                 const mx_uint* input_shape_data, PredictorHandle* out) {
  (void)dev_type;
  (void)dev_id;
  ensure_python();
  Gil gil;
  PyObject* mod = PyImport_ImportModule("mxnet_tpu.c_predict");
  if (!mod) return fail_from_python();
  PyObject* cls = PyObject_GetAttrString(mod, "Predictor");
  Py_DECREF(mod);
  if (!cls) return fail_from_python();

  PyObject* shapes = PyDict_New();
  for (mx_uint i = 0; i < num_input_nodes; ++i) {
    PyObject* shp = PyTuple_New(input_shape_indptr[i + 1]
                                - input_shape_indptr[i]);
    for (mx_uint j = input_shape_indptr[i], k = 0;
         j < input_shape_indptr[i + 1]; ++j, ++k) {
      PyTuple_SET_ITEM(shp, k, PyLong_FromUnsignedLong(
          input_shape_data[j]));
    }
    PyDict_SetItemString(shapes, input_keys[i], shp);
    Py_DECREF(shp);
  }
  PyObject* args = Py_BuildValue(
      "(s y# O)", symbol_json_str,
      static_cast<const char*>(param_bytes),
      static_cast<Py_ssize_t>(param_size), shapes);
  Py_DECREF(shapes);
  PyObject* pred = args ? PyObject_CallObject(cls, args) : nullptr;
  Py_XDECREF(args);
  Py_DECREF(cls);
  if (!pred) return fail_from_python();

  Predictor* p = new Predictor;
  p->py_pred = pred;
  *out = p;
  return 0;
}

int MXPredSetInput(PredictorHandle handle, const char* key,
                   const float* data, mx_uint size) {
  Predictor* p = static_cast<Predictor*>(handle);
  if (!p) return fail("null handle");
  Gil gil;
  PyObject* buf = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data),
      static_cast<Py_ssize_t>(size) * sizeof(float));
  if (!buf) return fail_from_python();
  PyObject* r = PyObject_CallMethod(p->py_pred, "set_input", "sO", key, buf);
  Py_DECREF(buf);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXPredForward(PredictorHandle handle) {
  Predictor* p = static_cast<Predictor*>(handle);
  if (!p) return fail("null handle");
  Gil gil;
  PyObject* r = PyObject_CallMethod(p->py_pred, "forward", nullptr);
  if (!r) return fail_from_python();
  Py_DECREF(r);
  return 0;
}

int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint** shape_data, mx_uint* shape_ndim) {
  Predictor* p = static_cast<Predictor*>(handle);
  if (!p) return fail("null handle");
  Gil gil;
  PyObject* shp = PyObject_CallMethod(p->py_pred, "output_shape", "I",
                                      index);
  if (!shp) return fail_from_python();
  Py_ssize_t n = PyTuple_Size(shp);
  if (p->out_shapes.size() <= index) p->out_shapes.resize(index + 1);
  auto& vec = p->out_shapes[index];
  vec.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    vec[i] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(shp, i)));
  }
  Py_DECREF(shp);
  *shape_data = vec.data();
  *shape_ndim = static_cast<mx_uint>(n);
  return 0;
}

int MXPredGetOutput(PredictorHandle handle, mx_uint index, float* data,
                    mx_uint size) {
  Predictor* p = static_cast<Predictor*>(handle);
  if (!p) return fail("null handle");
  Gil gil;
  PyObject* buf = PyObject_CallMethod(p->py_pred, "output_bytes", "I",
                                      index);
  if (!buf) return fail_from_python();
  char* src = nullptr;
  Py_ssize_t nbytes = 0;
  if (PyBytes_AsStringAndSize(buf, &src, &nbytes) != 0) {
    Py_DECREF(buf);
    return fail_from_python();
  }
  if (static_cast<size_t>(nbytes) != size * sizeof(float)) {
    Py_DECREF(buf);
    return fail("MXPredGetOutput: size mismatch");
  }
  std::memcpy(data, src, nbytes);
  Py_DECREF(buf);
  return 0;
}

int MXPredFree(PredictorHandle handle) {
  Predictor* p = static_cast<Predictor*>(handle);
  if (!p) return 0;
  {
    Gil gil;
    Py_XDECREF(p->py_pred);
  }
  delete p;
  return 0;
}

}  // extern "C"
