/*
 * General C API for mxnet_tpu — the training-capable ABI.
 *
 * Parity: reference include/mxnet/c_api.h (training-critical subset:
 * MXNDArray* c_api.h:560+, MXImperativeInvokeEx:1063,
 * MXAutograd*:1152, MXSymbol*, MXExecutorBind:1993, MXKVStore*).
 * Implemented by src/c_api.cc over an embedded CPython (see that file).
 *
 * Every function returns 0 on success, -1 on error (then
 * MXGetLastError() describes it) — the reference ABI convention.
 */
#ifndef MXNET_TPU_C_API_H_
#define MXNET_TPU_C_API_H_

#include <stdbool.h>
#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* NDArrayHandle;
typedef void* SymbolHandle;
typedef void* ExecutorHandle;
typedef void* KVStoreHandle;
typedef void* CachedOpHandle;
typedef void* DataIterHandle;
typedef void* RecordIOHandle;
typedef uint32_t mx_uint;

/* ---- misc --------------------------------------------------------------- */
const char* MXGetLastError(void);
int MXGetVersion(int* out);
int MXListAllOpNames(mx_uint* out_size, const char*** out_array);

/* ---- NDArray ------------------------------------------------------------ */
/* dev_type: 1 cpu, 2 gpu, 6 tpu (context.py codes); delay_alloc ignored.
 * dtype: 0 f32, 1 f64, 2 f16, 3 u8, 4 i32, 5 i8, 6 i64 (mshadow codes). */
int MXNDArrayCreateEx(const mx_uint* shape, mx_uint ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle* out);
int MXNDArrayCreate(const mx_uint* shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, NDArrayHandle* out);
int MXNDArrayFree(NDArrayHandle handle);
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t size);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data, size_t size);
int MXNDArrayGetShape(NDArrayHandle handle, mx_uint* out_dim,
                      const mx_uint** out_pdata);
int MXNDArrayGetDType(NDArrayHandle handle, int* out);
int MXNDArrayWaitAll(void);
int MXNDArraySave(const char* fname, mx_uint num_args,
                  NDArrayHandle* args, const char** keys);
int MXNDArrayLoad(const char* fname, mx_uint* out_size,
                  NDArrayHandle** out_arr, mx_uint* out_name_size,
                  const char*** out_names);
int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle* out);

/* ---- imperative invoke -------------------------------------------------- */
/* num_outputs/outputs: *num_outputs > 0 with pre-created handles writes
 * in place; else *outputs receives fresh handles and *num_outputs the
 * count (reference MXImperativeInvokeEx contract). */
int MXImperativeInvokeEx(const char* op_name, int num_inputs,
                         NDArrayHandle* inputs, int* num_outputs,
                         NDArrayHandle** outputs, int num_params,
                         const char** param_keys, const char** param_vals);

/* ---- autograd ----------------------------------------------------------- */
int MXAutogradSetIsRecording(int is_recording, int* prev);
int MXAutogradSetIsTraining(int train_mode, int* prev);
int MXAutogradMarkVariables(mx_uint num_var, NDArrayHandle* var_handles,
                            mx_uint* reqs_array,
                            NDArrayHandle* grad_handles);
int MXAutogradBackwardEx(mx_uint num_output, NDArrayHandle* output_handles,
                         NDArrayHandle* ograd_handles, mx_uint num_variables,
                         NDArrayHandle* var_handles, int retain_graph,
                         int create_graph, int is_train,
                         NDArrayHandle** grad_handles, int** grad_stypes);
int MXAutogradBackward(mx_uint num_output, NDArrayHandle* output_handles,
                       NDArrayHandle* ograd_handles, int retain_graph);

/* ---- symbol ------------------------------------------------------------- */
int MXSymbolCreateVariable(const char* name, SymbolHandle* out);
int MXSymbolCreateOp(const char* op_name, mx_uint num_param,
                     const char** keys, const char** vals,
                     mx_uint num_inputs, SymbolHandle* inputs,
                     const char* name, SymbolHandle* out);
int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out);
int MXSymbolSaveToJSON(SymbolHandle sym, const char** out_json);
int MXSymbolListArguments(SymbolHandle sym, mx_uint* out_size,
                          const char*** out_array);
int MXSymbolListOutputs(SymbolHandle sym, mx_uint* out_size,
                        const char*** out_array);
int MXSymbolListAuxiliaryStates(SymbolHandle sym, mx_uint* out_size,
                                const char*** out_array);
int MXSymbolFree(SymbolHandle sym);

/* ---- executor ----------------------------------------------------------- */
int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                   mx_uint num_args, const char** arg_names,
                   NDArrayHandle* arg_arrays, const char** grad_reqs,
                   mx_uint num_aux, const char** aux_names,
                   NDArrayHandle* aux_arrays, ExecutorHandle* out);
int MXExecutorForward(ExecutorHandle handle, int is_train);
int MXExecutorBackward(ExecutorHandle handle, mx_uint num_grads,
                       NDArrayHandle* head_grads);
int MXExecutorOutputs(ExecutorHandle handle, mx_uint* out_size,
                      NDArrayHandle** out);
int MXExecutorArgGrad(ExecutorHandle handle, const char* arg_name,
                      NDArrayHandle* out);
int MXExecutorFree(ExecutorHandle handle);

/* ---- NDArray views / misc ----------------------------------------------- */
int MXNDArrayReshape(NDArrayHandle handle, int ndim, const int* dims,
                     NDArrayHandle* out);
int MXNDArraySlice(NDArrayHandle handle, mx_uint slice_begin,
                   mx_uint slice_end, NDArrayHandle* out);
int MXNDArrayAt(NDArrayHandle handle, mx_uint idx, NDArrayHandle* out);
int MXNDArrayGetContext(NDArrayHandle handle, int* out_dev_type,
                        int* out_dev_id);
int MXRandomSeed(int seed);

/* ---- symbol shape inference --------------------------------------------- */
/* Reference MXSymbolInferShape (c_api.h:1482): known arg shapes arrive in
 * CSR layout (arg_ind_ptr has num_args+1 offsets into arg_shape_data);
 * results come back as three (size, ndim[], data[][]) groups valid until
 * the next call on this thread. */
int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                       const char** keys, const mx_uint* arg_ind_ptr,
                       const mx_uint* arg_shape_data,
                       mx_uint* in_shape_size,
                       const mx_uint** in_shape_ndim,
                       const mx_uint*** in_shape_data,
                       mx_uint* out_shape_size,
                       const mx_uint** out_shape_ndim,
                       const mx_uint*** out_shape_data,
                       mx_uint* aux_shape_size,
                       const mx_uint** aux_shape_ndim,
                       const mx_uint*** aux_shape_data,
                       int* complete);

/* ---- symbol type inference / attrs / views ------------------------------ */
/* Reference MXSymbolInferType (c_api.h:1553): known arg dtypes arrive as
 * mshadow codes (-1 = unknown) keyed by name. */
int MXSymbolInferType(SymbolHandle sym, mx_uint num_args,
                      const char** keys, const int* arg_type_data,
                      mx_uint* in_type_size, const int** in_type_data,
                      mx_uint* out_type_size, const int** out_type_data,
                      mx_uint* aux_type_size, const int** aux_type_data,
                      int* complete);
int MXSymbolGetAttr(SymbolHandle sym, const char* key, const char** out,
                    int* success);
int MXSymbolSetAttr(SymbolHandle sym, const char* key, const char* value);
int MXSymbolGetInternals(SymbolHandle sym, SymbolHandle* out);
int MXSymbolGetOutput(SymbolHandle sym, mx_uint index, SymbolHandle* out);

/* ---- executor reshape (reference MXExecutorReshapeEx) ------------------- */
/* CSR layout like MXSymbolInferShape; returns a NEW executor sharing
 * parameters with the old one (bucketing contract). */
int MXExecutorReshape(ExecutorHandle handle, int partial_shaping,
                      int allow_up_sizing, mx_uint num_args,
                      const char** keys, const mx_uint* arg_ind_ptr,
                      const mx_uint* arg_shape_data, ExecutorHandle* out);

/* ---- kvstore string keys (reference MXKVStoreInitEx/PushEx/PullEx) ------ */
int MXKVStoreInitEx(KVStoreHandle handle, mx_uint num, const char** keys,
                    NDArrayHandle* vals);
int MXKVStorePushEx(KVStoreHandle handle, mx_uint num, const char** keys,
                    NDArrayHandle* vals, int priority);
int MXKVStorePullEx(KVStoreHandle handle, mx_uint num, const char** keys,
                    NDArrayHandle* vals, int priority);

/* ---- raw-bytes serialization (reference MXNDArraySaveRawBytes) ---------- */
/* buffer valid until the next call on this thread */
int MXNDArraySaveRawBytes(NDArrayHandle handle, size_t* out_size,
                          const char** out_buf);
int MXNDArrayLoadFromRawBytes(const void* buf, size_t size,
                              NDArrayHandle* out);

/* ---- device discovery --------------------------------------------------- */
int MXGetGPUCount(int* out);   /* accelerator (TPU) count here */

/* ---- cached op (hybridize from C; reference MXCreateCachedOpEx) --------- */
int MXCreateCachedOp(SymbolHandle sym, CachedOpHandle* out);
int MXInvokeCachedOp(CachedOpHandle handle, int num_inputs,
                     NDArrayHandle* inputs, int* num_outputs,
                     NDArrayHandle** outputs);
int MXFreeCachedOp(CachedOpHandle handle);

/* ---- data iterators (reference MXDataIter*, c_api.h:2195+) -------------- */
int MXListDataIters(mx_uint* out_size, const char*** out_array);
int MXDataIterCreateIter(const char* iter_name, mx_uint num_param,
                         const char** keys, const char** vals,
                         DataIterHandle* out);
int MXDataIterBeforeFirst(DataIterHandle handle);
int MXDataIterNext(DataIterHandle handle, int* out);
int MXDataIterGetData(DataIterHandle handle, NDArrayHandle* out);
int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle* out);
int MXDataIterGetPadNum(DataIterHandle handle, int* pad);
int MXDataIterFree(DataIterHandle handle);

/* ---- RecordIO (reference MXRecordIO*, c_api.h:2283+) -------------------- */
int MXRecordIOWriterCreate(const char* uri, RecordIOHandle* out);
int MXRecordIOWriterWriteRecord(RecordIOHandle handle, const char* buf,
                                size_t size);
int MXRecordIOWriterFree(RecordIOHandle handle);
int MXRecordIOReaderCreate(const char* uri, RecordIOHandle* out);
/* *buf NULL + *size 0 at end of stream; buffer valid until next read */
int MXRecordIOReaderReadRecord(RecordIOHandle handle, const char** buf,
                               size_t* size);
int MXRecordIOReaderFree(RecordIOHandle handle);

/* ---- profiler (reference MXSetProcessProfilerConfig/State) -------------- */
int MXSetProcessProfilerConfig(int num_params, const char** keys,
                               const char** vals);
int MXSetProcessProfilerState(int state);  /* 0 stop, 1 run */
int MXDumpProcessProfile(int finished);
/* aggregate stats table; reset!=0 clears accumulated records */
int MXAggregateProfileStatsPrint(const char** out_str, int reset);

/* ---- kvstore ------------------------------------------------------------ */
int MXKVStoreCreate(const char* type, KVStoreHandle* out);
int MXKVStoreInit(KVStoreHandle handle, mx_uint num, const int* keys,
                  NDArrayHandle* vals);
int MXKVStorePush(KVStoreHandle handle, mx_uint num, const int* keys,
                  NDArrayHandle* vals, int priority);
int MXKVStorePull(KVStoreHandle handle, mx_uint num, const int* keys,
                  NDArrayHandle* vals, int priority);
int MXKVStoreGetRank(KVStoreHandle handle, int* rank);
int MXKVStoreGetGroupSize(KVStoreHandle handle, int* size);
int MXKVStoreFree(KVStoreHandle handle);






/* ---- final width batch -------------------------------------------------- */
int MXSymbolInferShapePartial(SymbolHandle sym, mx_uint num_args,
                              const char** keys,
                              const mx_uint* arg_ind_ptr,
                              const mx_uint* arg_shape_data,
                              mx_uint* in_shape_size,
                              const mx_uint** in_shape_ndim,
                              const mx_uint*** in_shape_data,
                              mx_uint* out_shape_size,
                              const mx_uint** out_shape_ndim,
                              const mx_uint*** out_shape_data,
                              mx_uint* aux_shape_size,
                              const mx_uint** aux_shape_ndim,
                              const mx_uint*** aux_shape_data,
                              int* complete);
int MXSymbolSaveToFile(SymbolHandle symbol, const char* fname);
int MXSymbolCreateFromFile(const char* fname, SymbolHandle* out);
int MXImperativeInvoke(const char* op_name, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, int num_params,
                       const char** param_keys, const char** param_vals);
int MXNDArrayAt64(NDArrayHandle handle, int64_t idx, NDArrayHandle* out);
int MXNDArraySlice64(NDArrayHandle handle, int64_t begin, int64_t end,
                     NDArrayHandle* out);
int MXKVStoreSetGradientCompression(KVStoreHandle handle, mx_uint num_params,
                                    const char** keys, const char** vals);
int MXDataIterGetIterInfo(void* creator, const char** name,
                          const char** description, mx_uint* num_args,
                          const char*** arg_names,
                          const char*** arg_type_infos,
                          const char*** arg_descriptions);

/* ---- misc batch 4 ------------------------------------------------------- */
int MXSetProfilerConfig(int num_params, const char** keys,
                        const char** vals);
int MXSetProfilerState(int state);
int MXDumpProfile(int finished);
struct LibFeature { const char* name; bool enabled; };
int MXLibInfoFeatures(const struct LibFeature** libFeature, size_t* size);
int MXSetIsNumpyShape(int is_np_shape, int* prev);
int MXIsNumpyShape(int* curr);
int MXEngineSetBulkSize(int bulk_size, int* prev_bulk_size);
int MXRandomSeedContext(int seed, int dev_type, int dev_id);
int MXStorageEmptyCache(int dev_type, int dev_id);
int MXGetGPUMemoryInformation(int dev, int* free_mem, int* total_mem);
int MXKVStoreSetBarrierBeforeExit(KVStoreHandle handle,
                                  const int barrier_before_exit);

/* ---- PS env / roles / server loop / SimpleBind / attr listing ----------- */
int MXInitPSEnv(mx_uint num_vars, const char** keys, const char** vals);
int MXKVStoreIsWorkerNode(int* ret);
int MXKVStoreIsServerNode(int* ret);
int MXKVStoreIsSchedulerNode(int* ret);
typedef void (MXKVStoreServerController)(int head, const char* body,
                                         void* controller_handle);
int MXKVStoreRunServer(KVStoreHandle handle,
                       MXKVStoreServerController controller,
                       void* controller_handle);
int MXExecutorSimpleBindEx(
    SymbolHandle symbol_handle, int dev_type, int dev_id,
    const mx_uint num_g2c_keys, const char** g2c_keys,
    const int* g2c_dev_types, const int* g2c_dev_ids,
    const mx_uint provided_grad_req_list_len,
    const char** provided_grad_req_names,
    const char** provided_grad_req_types,
    const mx_uint num_provided_arg_shapes,
    const char** provided_arg_shape_names,
    const int* provided_arg_shape_data,
    const mx_uint* provided_arg_shape_idx,
    const mx_uint num_provided_arg_dtypes,
    const char** provided_arg_dtype_names, const int* provided_arg_dtypes,
    const mx_uint num_provided_arg_stypes,
    const char** provided_arg_stype_names, const int* provided_arg_stypes,
    const mx_uint num_shared_arg_names, const char** shared_arg_name_list,
    int* shared_buffer_len, const char** shared_buffer_name_list,
    NDArrayHandle* shared_buffer_handle_list,
    const char*** updated_shared_buffer_name_list,
    NDArrayHandle** updated_shared_buffer_handle_list,
    mx_uint* num_in_args, NDArrayHandle** in_args, NDArrayHandle** arg_grads,
    mx_uint* num_aux_states, NDArrayHandle** aux_states,
    ExecutorHandle shared_exec_handle, ExecutorHandle* out);
int MXSymbolListAttr(SymbolHandle symbol, mx_uint* out_size,
                     const char*** out);
int MXSymbolListAttrShallow(SymbolHandle symbol, mx_uint* out_size,
                            const char*** out);

/* ---- op discovery / symbol extras (round-5 width) ----------------------- */
int MXSymbolListAtomicSymbolCreators(mx_uint* out_size, void*** out_array);
int MXSymbolGetAtomicSymbolName(void* creator, const char** name);
int MXSymbolGetAtomicSymbolInfo(void* creator, const char** name,
                                const char** description, mx_uint* num_args,
                                const char*** arg_names,
                                const char*** arg_type_infos,
                                const char*** arg_descriptions,
                                const char** key_var_num_args,
                                const char** return_type);
int MXSymbolCopy(SymbolHandle symbol, SymbolHandle* out);
int MXSymbolGetName(SymbolHandle symbol, const char** out, int* success);
int MXSymbolGetNumOutputs(SymbolHandle symbol, mx_uint* output_count);
int MXSymbolCompose(SymbolHandle sym, const char* name, mx_uint num_args,
                    const char** keys, SymbolHandle* args_handles);

/* ---- autograd / ndarray extras ------------------------------------------ */
int MXAutogradIsRecording(bool* curr);
int MXAutogradIsTraining(bool* curr);
int MXNDArrayDetach(NDArrayHandle handle, NDArrayHandle* out);
int MXNDArrayLoadFromBuffer(const void* ndarray_buffer, size_t size,
                            mx_uint* out_size, NDArrayHandle** out_arr,
                            mx_uint* out_name_size, const char*** out_names);

/* ---- kvstore extras ----------------------------------------------------- */
int MXKVStoreBarrier(KVStoreHandle handle);
int MXKVStoreGetType(KVStoreHandle handle, const char** type);
int MXKVStoreSendCommmandToServers(KVStoreHandle handle, int cmd_id,
                                   const char* cmd_body);
int MXKVStoreGetNumDeadNode(KVStoreHandle handle, const int node_id,
                            int* number, const int timeout_sec);
int MXKVStorePushPull(KVStoreHandle handle, mx_uint num, const int* keys,
                      NDArrayHandle* in_vals, NDArrayHandle* out_vals,
                      int priority);

/* ---- misc extras -------------------------------------------------------- */
int MXGetGPUMemoryInformation64(int dev, uint64_t* free_mem,
                                uint64_t* total_mem);
int MXNotifyShutdown(void);

/* ---- sparse NDArray (round-5; reference c_api.h:577+) ------------------- */
int MXNDArrayCreateSparseEx(int storage_type, const mx_uint* shape,
                            mx_uint ndim, int dev_type, int dev_id,
                            int delay_alloc, int dtype, mx_uint num_aux,
                            int* aux_type, mx_uint* aux_ndims,
                            const mx_uint* aux_shape, NDArrayHandle* out);
int MXNDArrayGetStorageType(NDArrayHandle handle, int* out_storage_type);
/* i == -1 copies the data blob, i >= 0 the ith aux blob */
int MXNDArraySyncCopyFromNDArray(NDArrayHandle handle_dst,
                                 const NDArrayHandle handle_src, const int i);
int MXNDArraySyncCheckFormat(NDArrayHandle handle, const bool full_check);
int MXNDArrayGetAuxType(NDArrayHandle handle, mx_uint i, int* out_type);
int MXNDArrayGetAuxNDArray(NDArrayHandle handle, mx_uint i,
                           NDArrayHandle* out);
int MXNDArrayGetDataNDArray(NDArrayHandle handle, NDArrayHandle* out);

/* ---- kvstore updaters / monitor / custom op (round-5) ------------------- */
typedef void (MXKVStoreUpdater)(int key, NDArrayHandle recv,
                                NDArrayHandle local, void* handle);
typedef void (MXKVStoreStrUpdater)(const char* key, NDArrayHandle recv,
                                   NDArrayHandle local, void* handle);
int MXKVStoreSetUpdater(KVStoreHandle handle, MXKVStoreUpdater updater,
                        void* updater_handle);
int MXKVStoreSetStrUpdater(KVStoreHandle handle, MXKVStoreStrUpdater updater,
                           void* updater_handle);
int MXKVStoreSetUpdaterEx(KVStoreHandle handle, MXKVStoreUpdater updater,
                          MXKVStoreStrUpdater str_updater,
                          void* updater_handle);

typedef void (*ExecutorMonitorCallback)(const char*, NDArrayHandle, void*);
int MXExecutorSetMonitorCallback(ExecutorHandle handle,
                                 ExecutorMonitorCallback callback,
                                 void* callback_handle);
int MXExecutorSetMonitorCallbackEX(ExecutorHandle handle,
                                   ExecutorMonitorCallback callback,
                                   void* callback_handle, bool monitor_all);

struct MXCallbackList {
  int num_callbacks;
  int (**callbacks)(void);
  void** contexts;
};
typedef int (*CustomOpPropCreator)(const char* op_type, const int num_kwargs,
                                   const char** keys, const char** values,
                                   struct MXCallbackList* ret);
int MXCustomOpRegister(const char* op_type, CustomOpPropCreator creator);

#ifdef __cplusplus
}  /* extern "C" */
#endif

#endif  /* MXNET_TPU_C_API_H_ */
