// Native data-plane kernels for mxnet_tpu.
//
// TPU-native counterpart of the reference's C++ IO hot path:
//  * RecordIO frame scan        (dmlc recordio framing; reference
//    src/io/iter_image_recordio_2.cc reads shards of these)
//  * fused batch pack           (crop already done host-side; this fuses
//    cast + mean/std normalize + mirror + HWC->NCHW + batch copy in one
//    OpenMP pass — reference equivalent: image_aug_default.cc output stage
//    writing straight into the pinned batch, iter_image_recordio_2.cc:708)
//
// Built as libmxnet_tpu_io.so by src/Makefile; loaded via ctypes from
// mxnet_tpu/_native.py with a pure-Python fallback when unavailable.

#include <cstdint>
#include <cstdio>
#include <cstring>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

// shared pixel kernel: one HWC uint8 image -> NCHW float32 with optional
// mirror and per-channel (x - mean) * inv_std (the per-image body of
// mxio_batch_transform AND the pipe workers; one copy on purpose)
inline void pack_image_u8(const uint8_t* src, int64_t h, int64_t w,
                          int64_t c, bool mirror, const float* mean,
                          const float* inv_std, float* dst) {
  const int64_t plane = h * w;
  for (int64_t y = 0; y < h; ++y) {
    for (int64_t x = 0; x < w; ++x) {
      const int64_t sx = mirror ? (w - 1 - x) : x;
      const uint8_t* px = src + (y * w + sx) * c;
      for (int64_t ch = 0; ch < c; ++ch) {
        dst[ch * plane + y * w + x] =
            (static_cast<float>(px[ch]) - mean[ch]) * inv_std[ch];
      }
    }
  }
}

}  // namespace

extern "C" {

// Scan the framed records of a .rec file.
// Fills payload offsets / lengths / continuation flags for up to max_n
// frames. Returns the number of frames, or -1 on IO/format error.
// cflag semantics (dmlc recordio): 0 whole record, 1 first part,
// 2 middle, 3 last.
int64_t mxio_scan_records(const char* path, int64_t* offsets,
                          int64_t* lengths, int32_t* cflags,
                          int64_t max_n) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int64_t n = 0;
  uint32_t header[2];
  while (n < max_n) {
    int64_t pos = static_cast<int64_t>(std::ftell(f));
    size_t got = std::fread(header, sizeof(uint32_t), 2, f);
    if (got == 0) break;  // clean EOF
    if (got != 2 || header[0] != kMagic) {
      std::fclose(f);
      return -1;
    }
    uint32_t cflag = header[1] >> 29;
    uint32_t len = header[1] & kLenMask;
    offsets[n] = pos + 8;
    lengths[n] = static_cast<int64_t>(len);
    cflags[n] = static_cast<int32_t>(cflag);
    ++n;
    uint32_t pad = (4 - (len % 4)) % 4;
    if (std::fseek(f, static_cast<long>(len + pad), SEEK_CUR) != 0) {
      std::fclose(f);
      return -1;
    }
  }
  std::fclose(f);
  return n;
}

// Gather n byte ranges of a file into one contiguous buffer.
// out_offsets[i] is the destination offset of range i in `out`.
// Returns 0 on success, -1 on error. Parallel pread-style gather.
int32_t mxio_gather(const char* path, const int64_t* offsets,
                    const int64_t* lengths, int64_t n, uint8_t* out,
                    const int64_t* out_offsets) {
  int32_t err = 0;
#ifdef _OPENMP
#pragma omp parallel reduction(| : err)
#endif
  {
    // per-thread handle; the worksharing loop below must be encountered
    // by EVERY thread of the team (OpenMP requirement), so a failed open
    // only guards the body, never skips the construct
    FILE* f = std::fopen(path, "rb");
    if (!f) err = -1;
#ifdef _OPENMP
#pragma omp for schedule(dynamic, 16)
#endif
    for (int64_t i = 0; i < n; ++i) {
      if (!f) continue;
      if (std::fseek(f, static_cast<long>(offsets[i]), SEEK_SET) != 0 ||
          std::fread(out + out_offsets[i], 1,
                     static_cast<size_t>(lengths[i]),
                     f) != static_cast<size_t>(lengths[i])) {
        err = -1;
      }
    }
    if (f) std::fclose(f);
  }
  return err;
}

// Fused batch pack: n same-shape HWC uint8 images -> NCHW float32 batch,
// applying optional per-image horizontal mirror and per-channel
// (x - mean[c]) / std[c]. mirror/mean/stdr may be null.
void mxio_batch_transform(const uint8_t* src, int64_t n, int64_t h,
                          int64_t w, int64_t c, const uint8_t* mirror,
                          const float* mean, const float* stdr,
                          float* out) {
  const int64_t img = h * w * c;
  float mbuf[16] = {0};
  float sbuf[16];
  for (int64_t ch = 0; ch < c && ch < 16; ++ch) {
    mbuf[ch] = mean ? mean[ch] : 0.0f;
    sbuf[ch] = stdr ? 1.0f / stdr[ch] : 1.0f;
  }
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < n; ++i) {
    pack_image_u8(src + i * img, h, w, c, mirror && mirror[i], mbuf, sbuf,
                  out + i * img);
  }
}

// Same fused pack but float32 HWC input (post-augmenter path).
void mxio_batch_transform_f32(const float* src, int64_t n, int64_t h,
                              int64_t w, int64_t c, const uint8_t* mirror,
                              const float* mean, const float* stdr,
                              float* out) {
  const int64_t img = h * w * c;
  const int64_t plane = h * w;
  float mbuf[16] = {0};
  float sbuf[16];
  for (int64_t ch = 0; ch < c && ch < 16; ++ch) {
    mbuf[ch] = mean ? mean[ch] : 0.0f;
    sbuf[ch] = stdr ? 1.0f / stdr[ch] : 1.0f;
  }
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < n; ++i) {
    const float* s = src + i * img;
    float* d = out + i * img;
    const bool mir = mirror && mirror[i];
    for (int64_t y = 0; y < h; ++y) {
      for (int64_t x = 0; x < w; ++x) {
        const int64_t sx = mir ? (w - 1 - x) : x;
        const float* px = s + (y * w + sx) * c;
        for (int64_t ch = 0; ch < c; ++ch) {
          d[ch * plane + y * w + x] = (px[ch] - mbuf[ch]) * sbuf[ch];
        }
      }
    }
  }
}

int32_t mxio_version() { return 1; }

}  // extern "C"

// ===========================================================================
// Threaded record pipeline (reference: src/io/iter_image_recordio_2.cc —
// ImageRecordIOParser2: sharded read + parallel decode + batch assembly
// into ready buffers overlapping the consumer).  This TPU-native version
// handles RAW-pixel records (im2rec raw packing; JPEG decode needs a
// codec library the image lacks — the reference used OpenCV there) and
// fuses read + IRHeader parse + mirror/normalize + HWC->NCHW pack into
// prepared float batches produced by a worker pool behind a ring buffer.
// ===========================================================================

#include <atomic>
#include <condition_variable>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

namespace {

constexpr int64_t kIRHeaderSize = 24;  // IfQQ: flag,label,id,id2

struct Slot {
  std::vector<float> data;
  std::vector<float> label;
  int64_t batch_id = -1;     // which sequential batch occupies the slot
  bool ready = false;
};

struct Pipe {
  // immutable config
  std::string path;
  int64_t batch, h, w, c, label_width;
  bool shuffle, rand_mirror;
  uint64_t seed;
  float mbuf[16] = {0};
  float sbuf[16];
  // record table (from the scan)
  std::vector<int64_t> offsets, lengths;
  // per-epoch state (batch/slot claims live under mu)
  std::vector<int64_t> order;
  int64_t n_batches = 0;
  int64_t next_batch = 0;               // producers claim batches (mu)
  int64_t consumer_batch = 0;           // consumer's sequential cursor
  int64_t epoch = 0;
  // ring
  std::vector<Slot> slots;
  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  bool stopping = false;
  int32_t error = 0;
  int n_threads = 2;
  std::vector<std::thread> workers;
};

void pipe_worker(Pipe* p) {
  FILE* f = std::fopen(p->path.c_str(), "rb");
  if (!f) {
    std::lock_guard<std::mutex> lk(p->mu);
    p->error = -1;
    p->cv_ready.notify_all();
    return;
  }
  const int64_t img = p->h * p->w * p->c;
  std::vector<uint8_t> rec;
  while (true) {
    // claim slot AND batch id under ONE lock: claiming the id first
    // would let fast workers fill every slot with later ready batches
    // while the worker owning the consumer's next sequential batch
    // starves for a slot — a deadlock (caught in review)
    Slot* slot = nullptr;
    int64_t b = -1;
    {
      std::unique_lock<std::mutex> lk(p->mu);
      for (;;) {
        if (p->stopping || p->next_batch >= p->n_batches) {
          std::fclose(f);
          return;
        }
        for (auto& s : p->slots) {
          if (s.batch_id < 0) { slot = &s; break; }
        }
        if (slot) break;
        p->cv_free.wait(lk);
      }
      b = p->next_batch++;
      slot->batch_id = b;
      slot->ready = false;
    }
    std::mt19937_64 rng(p->seed * 2654435761u + p->epoch * 97 + b);
    // assemble the batch
    std::memset(slot->label.data(), 0, slot->label.size() * 4);
    for (int64_t i = 0; i < p->batch; ++i) {
      int64_t si = b * p->batch + i;
      int64_t rec_i = p->order[si % (int64_t)p->order.size()];
      int64_t len = p->lengths[rec_i];
      rec.resize((size_t)len);
      if (std::fseek(f, (long)p->offsets[rec_i], SEEK_SET) != 0 ||
          std::fread(rec.data(), 1, (size_t)len, f) != (size_t)len ||
          len < kIRHeaderSize) {
        std::lock_guard<std::mutex> lk(p->mu);
        p->error = -2;
        continue;
      }
      uint32_t flag;
      float label0;
      std::memcpy(&flag, rec.data(), 4);
      std::memcpy(&label0, rec.data() + 4, 4);
      // validate the FULL expected length before touching the body:
      // header + flag extra label floats + raw pixels
      if (len != kIRHeaderSize + (int64_t)flag * 4 + img) {
        std::lock_guard<std::mutex> lk(p->mu);
        p->error = -3;  // not a raw-pixel record (or truncated)
        continue;
      }
      const uint8_t* body = rec.data() + kIRHeaderSize;
      float* lbl = slot->label.data() + i * p->label_width;
      if (flag > 0) {
        int64_t nl = (int64_t)flag < p->label_width ? flag : p->label_width;
        std::memcpy(lbl, body, (size_t)nl * 4);
        body += (int64_t)flag * 4;
      } else {
        lbl[0] = label0;
      }
      const bool mir = p->rand_mirror && (rng() & 1);
      pack_image_u8(body, p->h, p->w, p->c, mir, p->mbuf, p->sbuf,
                    slot->data.data() + i * img);
    }
    {
      std::lock_guard<std::mutex> lk(p->mu);
      slot->ready = true;
      p->cv_ready.notify_all();
    }
  }
}

void pipe_join_workers(Pipe* p) {
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stopping = true;
    p->cv_free.notify_all();
  }
  for (auto& t : p->workers)
    if (t.joinable()) t.join();
  p->workers.clear();
  p->stopping = false;
}

void pipe_start_epoch(Pipe* p) {
  // shuffled sample order for this epoch; drop-last batching
  if (p->shuffle) {
    std::mt19937_64 rng(p->seed + 1315423911u * (uint64_t)p->epoch);
    for (int64_t i = (int64_t)p->order.size() - 1; i > 0; --i) {
      std::swap(p->order[(size_t)i], p->order[rng() % (uint64_t)(i + 1)]);
    }
  }
  p->n_batches = (int64_t)p->order.size() / p->batch;
  p->next_batch = 0;
  p->consumer_batch = 0;
  for (auto& s : p->slots) { s.batch_id = -1; s.ready = false; }
  for (int i = 0; i < p->n_threads; ++i)
    p->workers.emplace_back(pipe_worker, p);
}

}  // namespace

extern "C" {

// Create a pipelined raw-record reader. Returns a handle (opaque), or
// null on failure (bad file / no whole records / c > 16).
// shuffle: per-epoch record reshuffling. rand_mirror: random horizontal
// flip augmentation (independent of shuffle).
void* mxio_pipe_create(const char* path, int64_t batch, int64_t h,
                       int64_t w, int64_t c, int64_t label_width,
                       int32_t shuffle, int32_t rand_mirror, uint64_t seed,
                       const float* mean, const float* stdr,
                       int32_t prefetch, int32_t nthreads) {
  if (c > 16) return nullptr;  // mbuf/sbuf channel limit
  Pipe* p = new Pipe();
  p->path = path;
  p->batch = batch; p->h = h; p->w = w; p->c = c;
  p->label_width = label_width > 0 ? label_width : 1;
  p->shuffle = shuffle != 0;
  p->rand_mirror = rand_mirror != 0;
  p->seed = seed;
  for (int64_t ch = 0; ch < c && ch < 16; ++ch) {
    p->mbuf[ch] = mean ? mean[ch] : 0.0f;
    p->sbuf[ch] = stdr ? 1.0f / stdr[ch] : 1.0f;
  }
  // scan the record table; every frame is >= 8 bytes, so file_size/8 is
  // an exact upper bound — no silent truncation possible
  FILE* fsz = std::fopen(path, "rb");
  if (!fsz) { delete p; return nullptr; }
  std::fseek(fsz, 0, SEEK_END);
  int64_t max_n = std::ftell(fsz) / 8 + 1;
  std::fclose(fsz);
  std::vector<int64_t> off((size_t)max_n), len((size_t)max_n);
  std::vector<int32_t> cfl((size_t)max_n);
  int64_t n = mxio_scan_records(path, off.data(), len.data(), cfl.data(),
                                max_n);
  if (n <= 0) { delete p; return nullptr; }
  for (int64_t i = 0; i < n; ++i) {
    if (cfl[i] == 0) {  // whole records only (multipart = not raw)
      p->offsets.push_back(off[i]);
      p->lengths.push_back(len[i]);
    }
  }
  if ((int64_t)p->offsets.size() < batch) { delete p; return nullptr; }
  p->order.resize(p->offsets.size());
  for (size_t i = 0; i < p->order.size(); ++i) p->order[i] = (int64_t)i;
  int np = prefetch > 0 ? prefetch : 4;
  p->slots.resize((size_t)np);
  for (auto& s : p->slots) {
    s.data.resize((size_t)(batch * h * w * c));
    s.label.resize((size_t)(batch * p->label_width));
  }
  p->n_threads = nthreads > 0 ? nthreads : 2;
  // invariant: every in-flight batch owns a slot, so workers must not
  // outnumber slots or the worker holding the consumer's next sequential
  // batch can starve behind ready-but-unconsumable ones
  if (p->n_threads > (int)p->slots.size())
    p->n_threads = (int)p->slots.size();
  pipe_start_epoch(p);
  return p;
}

// Copy the next sequential batch into data/label. Returns the batch
// index, -1 at epoch end (call mxio_pipe_reset), or -2 on IO error.
int64_t mxio_pipe_next(void* handle, float* data, float* label) {
  Pipe* p = (Pipe*)handle;
  if (p->consumer_batch >= p->n_batches) return -1;
  std::unique_lock<std::mutex> lk(p->mu);
  Slot* slot = nullptr;
  for (;;) {
    if (p->error) return -2;
    for (auto& s : p->slots) {
      if (s.batch_id == p->consumer_batch && s.ready) { slot = &s; break; }
    }
    if (slot) break;
    p->cv_ready.wait(lk);
  }
  std::memcpy(data, slot->data.data(), slot->data.size() * 4);
  std::memcpy(label, slot->label.data(), slot->label.size() * 4);
  slot->batch_id = -1;
  slot->ready = false;
  p->cv_free.notify_all();
  return p->consumer_batch++;
}

void mxio_pipe_reset(void* handle) {
  Pipe* p = (Pipe*)handle;
  pipe_join_workers(p);
  p->epoch += 1;
  pipe_start_epoch(p);
}

int64_t mxio_pipe_num_batches(void* handle) {
  return ((Pipe*)handle)->n_batches;
}

void mxio_pipe_destroy(void* handle) {
  Pipe* p = (Pipe*)handle;
  pipe_join_workers(p);
  delete p;
}

}  // extern "C"
