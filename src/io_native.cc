// Native data-plane kernels for mxnet_tpu.
//
// TPU-native counterpart of the reference's C++ IO hot path:
//  * RecordIO frame scan        (dmlc recordio framing; reference
//    src/io/iter_image_recordio_2.cc reads shards of these)
//  * fused batch pack           (crop already done host-side; this fuses
//    cast + mean/std normalize + mirror + HWC->NCHW + batch copy in one
//    OpenMP pass — reference equivalent: image_aug_default.cc output stage
//    writing straight into the pinned batch, iter_image_recordio_2.cc:708)
//
// Built as libmxnet_tpu_io.so by src/Makefile; loaded via ctypes from
// mxnet_tpu/_native.py with a pure-Python fallback when unavailable.

#include <cstdint>
#include <cstdio>
#include <cstring>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

}  // namespace

extern "C" {

// Scan the framed records of a .rec file.
// Fills payload offsets / lengths / continuation flags for up to max_n
// frames. Returns the number of frames, or -1 on IO/format error.
// cflag semantics (dmlc recordio): 0 whole record, 1 first part,
// 2 middle, 3 last.
int64_t mxio_scan_records(const char* path, int64_t* offsets,
                          int64_t* lengths, int32_t* cflags,
                          int64_t max_n) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int64_t n = 0;
  uint32_t header[2];
  while (n < max_n) {
    int64_t pos = static_cast<int64_t>(std::ftell(f));
    size_t got = std::fread(header, sizeof(uint32_t), 2, f);
    if (got == 0) break;  // clean EOF
    if (got != 2 || header[0] != kMagic) {
      std::fclose(f);
      return -1;
    }
    uint32_t cflag = header[1] >> 29;
    uint32_t len = header[1] & kLenMask;
    offsets[n] = pos + 8;
    lengths[n] = static_cast<int64_t>(len);
    cflags[n] = static_cast<int32_t>(cflag);
    ++n;
    uint32_t pad = (4 - (len % 4)) % 4;
    if (std::fseek(f, static_cast<long>(len + pad), SEEK_CUR) != 0) {
      std::fclose(f);
      return -1;
    }
  }
  std::fclose(f);
  return n;
}

// Gather n byte ranges of a file into one contiguous buffer.
// out_offsets[i] is the destination offset of range i in `out`.
// Returns 0 on success, -1 on error. Parallel pread-style gather.
int32_t mxio_gather(const char* path, const int64_t* offsets,
                    const int64_t* lengths, int64_t n, uint8_t* out,
                    const int64_t* out_offsets) {
  int32_t err = 0;
#ifdef _OPENMP
#pragma omp parallel reduction(| : err)
#endif
  {
    // per-thread handle; the worksharing loop below must be encountered
    // by EVERY thread of the team (OpenMP requirement), so a failed open
    // only guards the body, never skips the construct
    FILE* f = std::fopen(path, "rb");
    if (!f) err = -1;
#ifdef _OPENMP
#pragma omp for schedule(dynamic, 16)
#endif
    for (int64_t i = 0; i < n; ++i) {
      if (!f) continue;
      if (std::fseek(f, static_cast<long>(offsets[i]), SEEK_SET) != 0 ||
          std::fread(out + out_offsets[i], 1,
                     static_cast<size_t>(lengths[i]),
                     f) != static_cast<size_t>(lengths[i])) {
        err = -1;
      }
    }
    if (f) std::fclose(f);
  }
  return err;
}

// Fused batch pack: n same-shape HWC uint8 images -> NCHW float32 batch,
// applying optional per-image horizontal mirror and per-channel
// (x - mean[c]) / std[c]. mirror/mean/stdr may be null.
void mxio_batch_transform(const uint8_t* src, int64_t n, int64_t h,
                          int64_t w, int64_t c, const uint8_t* mirror,
                          const float* mean, const float* stdr,
                          float* out) {
  const int64_t img = h * w * c;
  const int64_t plane = h * w;
  float mbuf[16] = {0};
  float sbuf[16];
  for (int64_t ch = 0; ch < c && ch < 16; ++ch) {
    mbuf[ch] = mean ? mean[ch] : 0.0f;
    sbuf[ch] = stdr ? 1.0f / stdr[ch] : 1.0f;
  }
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* s = src + i * img;
    float* d = out + i * img;
    const bool mir = mirror && mirror[i];
    for (int64_t y = 0; y < h; ++y) {
      for (int64_t x = 0; x < w; ++x) {
        const int64_t sx = mir ? (w - 1 - x) : x;
        const uint8_t* px = s + (y * w + sx) * c;
        for (int64_t ch = 0; ch < c; ++ch) {
          d[ch * plane + y * w + x] =
              (static_cast<float>(px[ch]) - mbuf[ch]) * sbuf[ch];
        }
      }
    }
  }
}

// Same fused pack but float32 HWC input (post-augmenter path).
void mxio_batch_transform_f32(const float* src, int64_t n, int64_t h,
                              int64_t w, int64_t c, const uint8_t* mirror,
                              const float* mean, const float* stdr,
                              float* out) {
  const int64_t img = h * w * c;
  const int64_t plane = h * w;
  float mbuf[16] = {0};
  float sbuf[16];
  for (int64_t ch = 0; ch < c && ch < 16; ++ch) {
    mbuf[ch] = mean ? mean[ch] : 0.0f;
    sbuf[ch] = stdr ? 1.0f / stdr[ch] : 1.0f;
  }
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < n; ++i) {
    const float* s = src + i * img;
    float* d = out + i * img;
    const bool mir = mirror && mirror[i];
    for (int64_t y = 0; y < h; ++y) {
      for (int64_t x = 0; x < w; ++x) {
        const int64_t sx = mir ? (w - 1 - x) : x;
        const float* px = s + (y * w + sx) * c;
        for (int64_t ch = 0; ch < c; ++ch) {
          d[ch * plane + y * w + x] = (px[ch] - mbuf[ch]) * sbuf[ch];
        }
      }
    }
  }
}

int32_t mxio_version() { return 1; }

}  // extern "C"
