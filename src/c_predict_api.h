/*
 * C predict API for mxnet_tpu (parity: include/mxnet/c_predict_api.h).
 *
 * Standalone inference ABI: link libmxnet_tpu_predict.so (build with
 * `make -C src predict`) from any C-capable language. The library embeds
 * CPython when loaded into a non-Python host, or joins the running
 * interpreter when loaded into a Python process.
 *
 * All functions return 0 on success, -1 on error; MXGetLastError()
 * returns the thread-local message for the last failure.
 */
#ifndef MXNET_TPU_C_PREDICT_API_H_
#define MXNET_TPU_C_PREDICT_API_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef void* PredictorHandle;
typedef unsigned int mx_uint;

const char* MXGetLastError(void);

/* Create a predictor from symbol JSON + the bytes of a .params file.
 * Input shapes use CSR layout: input_shape_indptr has num_input_nodes+1
 * entries delimiting each input's dims in input_shape_data.
 * dev_type/dev_id are accepted for signature parity (the runtime places
 * computation via its own context rules). */
int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char** input_keys,
                 const mx_uint* input_shape_indptr,
                 const mx_uint* input_shape_data, PredictorHandle* out);

int MXPredSetInput(PredictorHandle handle, const char* key,
                   const float* data, mx_uint size);

int MXPredForward(PredictorHandle handle);

/* shape_data points into predictor-owned storage; valid until the next
 * MXPredGetOutputShape call for the same index or MXPredFree. */
int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint** shape_data, mx_uint* shape_ndim);

int MXPredGetOutput(PredictorHandle handle, mx_uint index, float* data,
                    mx_uint size);

int MXPredFree(PredictorHandle handle);

#ifdef __cplusplus
}
#endif

#endif  /* MXNET_TPU_C_PREDICT_API_H_ */
