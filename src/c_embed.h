// Shared CPython-embedding plumbing for the C ABIs (c_predict_api.cc and
// c_api.cc).  Role parity: the reference's src/c_api/c_api_error.cc
// (MXGetLastError TLS) + engine init; here the "engine" is an embedded (or
// joined) CPython interpreter driving mxnet_tpu under the GIL.
#ifndef MXNET_TPU_C_EMBED_H_
#define MXNET_TPU_C_EMBED_H_

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <mutex>
#include <string>

namespace mxtpu {

inline std::mutex& init_mutex() {
  static std::mutex m;
  return m;
}

inline std::string& last_error() {
  thread_local std::string err;
  return err;
}

class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

inline void ensure_python() {
  std::lock_guard<std::mutex> lk(init_mutex());
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // release the GIL acquired by Py_Initialize so Gil{} works uniformly
    PyEval_SaveThread();
  }
}

inline int fail(const std::string& msg) {
  last_error() = msg;
  return -1;
}

inline int fail_from_python() {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  PyObject* s = value ? PyObject_Str(value) : nullptr;
  const char* c = s ? PyUnicode_AsUTF8(s) : nullptr;
  last_error() = c ? c : "unknown python error";
  Py_XDECREF(s);
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return -1;
}

// import mxnet_tpu.<submodule>; returns new reference or nullptr
inline PyObject* import_helper(const char* mod_name) {
  return PyImport_ImportModule(mod_name);
}

}  // namespace mxtpu

#endif  // MXNET_TPU_C_EMBED_H_
