#!/usr/bin/env python
"""Headline benchmark: ResNet-50 ImageNet-shape training throughput + MFU.

Baseline (BASELINE.md / reference docs/faq/perf.md:231-243):
ResNet-50 train @ bs32 fp32 on 1x V100 = 298.51 img/s.

TPU recipe: the whole train step (fwd+bwd+SGD-momentum update) is ONE
compiled XLA program; bf16 compute with fp32 master weights & BatchNorm
statistics (mxnet_tpu.amp recipe).  Model build / functionalization happens
on the host CPU backend with jit disabled so NOTHING compiles for the
device except that single program — round 1 died doing one remote compile
per imperative op over the axon link.

Prints ONE JSON line:
  {"metric", "value", "unit", "vs_baseline", "mfu", ...}
Always prints the line — on failure or budget exhaustion with whatever was
measured (value 0.0 and an "error" field if nothing was).

Env knobs: BENCH_DTYPE, BENCH_WARMUP, BENCH_ITERS, BENCH_TIME_BUDGET (s),
BENCH_BATCH.
"""
import json
import os
import sys
import time

BASELINE_IMG_S = 298.51
T_START = time.perf_counter()


def log(msg):
    print(f"[bench +{time.perf_counter() - T_START:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


def emit(payload):
    print(json.dumps(payload), flush=True)


# bf16 peak FLOP/s by TPU generation (public numbers); fallback is v5e.
_PEAK_FLOPS = [
    ("v2", 45e12), ("v3", 123e12), ("v4", 275e12),
    ("v5 lite", 197e12), ("v5litepod", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12), ("v6", 918e12), ("trillium", 918e12),
]


def peak_flops_for(device_kind: str):
    dk = device_kind.lower()
    for key, val in _PEAK_FLOPS:
        if key in dk:
            return val, key
    return 197e12, f"unknown({device_kind})->assumed v5e"


def main():
    budget = float(os.environ.get("BENCH_TIME_BUDGET", 1200))
    batch = int(os.environ.get("BENCH_BATCH", 32))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    n_warm = int(os.environ.get("BENCH_WARMUP", 2))
    n_iter = int(os.environ.get("BENCH_ITERS", 20))

    result = {
        "metric": "resnet50_train_img_per_sec_bs32",
        "value": 0.0,
        "unit": "img/s",
        "vs_baseline": 0.0,
    }

    try:
        # persistent compilation cache: reruns skip the big compile
        cache_dir = os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_cache"))
        os.makedirs(cache_dir, exist_ok=True)

        log("importing jax")
        import numpy as np
        import jax
        import jax.numpy as jnp
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception:
            pass

        import mxnet_tpu as mx
        from mxnet_tpu.gluon.model_zoo import vision
        from mxnet_tpu.parallel.spmd import functionalize, merge_params
        from mxnet_tpu.ops import registry as _registry
        from mxnet_tpu import random as _random
        from mxnet_tpu import autograd as _ag
        from mxnet_tpu import amp

        dev = jax.devices()[0]
        log(f"device: {dev.platform}/{getattr(dev, 'device_kind', '?')}")

        if dtype == "bfloat16":
            # framework AMP: MXU ops compute in bf16, fp32 master weights
            # and norm statistics — the recipe lives in mxnet_tpu.amp, not
            # hand-rolled here
            amp.init(target_dtype="bfloat16")

        log("building ResNet-50 on host CPU (no device compiles)")
        from mxnet_tpu.parallel.spmd import host_cpu_scope
        with host_cpu_scope(), jax.disable_jit():
            net = vision.resnet50_v1()
            net.initialize(mx.initializer.Xavier())
            x_ex = mx.nd.zeros((batch, 3, 224, 224))
            fb = functionalize(net, x_ex)
            apply_fn, param_arrays, names = fb
            x_sds = jax.ShapeDtypeStruct((batch, 3, 224, 224),
                                         np.dtype(np.float32))
            train_idx, aux_list = fb.split_train_aux((x_sds,))
        n_params = sum(int(np.prod(a.shape)) for a in param_arrays)
        log(f"functionalized: {len(param_arrays)} params "
            f"({n_params / 1e6:.1f}M), {len(aux_list)} aux")

        compute_dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32

        sgd_attrs = {"lr": 0.01, "wd": 1e-4, "momentum": 0.9,
                     "rescale_grad": 1.0}
        sgd_mom = _registry.get("sgd_mom_update").fcompute

        def step(key, tparams, aparams, moms, x, y):
            def loss_fn(tps):
                ps = merge_params(train_idx, aux_list, tps, aparams)
                with _ag.train_mode():
                    outs, mutated = apply_fn(key, ps, (x,))
                logits = outs[0].astype(jnp.float32)
                logp = jax.nn.log_softmax(logits, axis=-1)
                oh = jax.nn.one_hot(y.astype(jnp.int32), 1000)
                return -(oh * logp).sum(axis=-1).mean(), mutated

            (loss, mutated), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(tparams)
            new_p, new_m = [], []
            for w, g, m in zip(tparams, grads, moms):
                nw, nm = sgd_mom(sgd_attrs, w, g.astype(w.dtype), m)
                new_p.append(nw)
                new_m.append(nm)
            new_aux = tuple(mu.astype(a.dtype)
                            for mu, a in zip(mutated, aparams))
            return tuple(new_p), new_aux, tuple(new_m), loss

        log("placing params on device")
        tparams = tuple(jax.device_put(param_arrays[i], dev)
                        for i in train_idx)
        aparams = tuple(jax.device_put(param_arrays[i], dev)
                        for i in aux_list)
        moms = tuple(jnp.zeros_like(p) for p in tparams)
        x = jax.device_put(
            np.random.randn(batch, 3, 224, 224).astype(np.float32), dev
        ).astype(compute_dtype)
        y = jax.device_put(
            np.random.randint(0, 1000, (batch,)).astype(np.float32), dev)
        key = _random.next_key()

        log("lowering + compiling ONE train-step program")
        t0 = time.perf_counter()
        step_jit = jax.jit(step, donate_argnums=(1, 2, 3))
        lowered = step_jit.lower(key, tparams, aparams, moms, x, y)
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
        log(f"compiled in {compile_s:.1f}s")
        result["compile_seconds"] = round(compile_s, 1)

        flops_per_step = None
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0]
            flops_per_step = float(ca.get("flops", 0.0)) or None
        except Exception:
            pass
        if not flops_per_step:
            # analytic fallback: ~3.86 GFLOP fwd/img * 3 (fwd+bwd)
            flops_per_step = 3.86e9 * 3 * batch

        log(f"warmup x{n_warm}")
        loss = None
        for _ in range(n_warm):
            tparams, aparams, moms, loss = compiled(
                key, tparams, aparams, moms, x, y)
        if loss is not None:
            loss.block_until_ready()

        # timed loop, chunked so a budget overrun still reports
        log(f"timing (target {n_iter} iters, budget {budget:.0f}s)")
        done = 0
        t0 = time.perf_counter()
        while done < n_iter:
            chunk = min(5, n_iter - done)
            for _ in range(chunk):
                tparams, aparams, moms, loss = compiled(
                    key, tparams, aparams, moms, x, y)
            loss.block_until_ready()
            done += chunk
            if time.perf_counter() - T_START > budget * 0.9:
                log(f"time budget; stopping at {done} iters")
                break
        dt = time.perf_counter() - t0
        img_s = batch * done / dt

        peak, kind = peak_flops_for(getattr(dev, "device_kind", ""))
        mfu = (flops_per_step * done / dt) / peak
        log(f"{img_s:.1f} img/s, mfu {mfu:.3f} "
            f"(flops/step {flops_per_step / 1e9:.1f}G, peak {kind})")

        result.update({
            "value": round(img_s, 2),
            "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
            "mfu": round(mfu, 4),
            "mfu_peak_flops_assumed": f"{kind}:{peak:.3g}",
            "flops_per_step": round(flops_per_step, 0),
            "iters": done,
            "batch": batch,
            "dtype": dtype,
            "final_loss": float(loss),
        })
    except Exception as e:  # always emit the JSON line
        import traceback
        traceback.print_exc(file=sys.stderr)
        result["error"] = f"{type(e).__name__}: {e}"
    emit(result)


if __name__ == "__main__":
    main()
