#!/usr/bin/env python
"""Headline benchmark: ResNet-50 ImageNet-shape training throughput + MFU.

Baseline (BASELINE.md / reference docs/faq/perf.md:231-243):
ResNet-50 train @ bs32 fp32 on 1x V100 = 298.51 img/s.

TPU recipe: the whole train step (fwd+bwd+SGD-momentum update) is ONE
compiled XLA program; bf16 compute with fp32 master weights & BatchNorm
statistics (mxnet_tpu.amp recipe).  Model build / functionalization happens
on the host CPU backend with jit disabled so NOTHING compiles for the
device except the few programs we time.

Timing methodology (round-4 REWRITE — measured facts about the axon relay
drove every choice; see docs/perf_notes.md "round-4 timing forensics"):

  * ``block_until_ready()`` is NOT a sync barrier on the axon relay — it
    returns immediately (measured: a 40-rep 4096^3 matmul chain "timed" at
    0.2 ms = 31,000 TF/s, 160x the chip's physical peak).  Every r02/r03
    throughput number that relied on it measured dispatch pipelining, not
    device time.  The ONLY reliable barrier is a device->host TRANSFER, so
    every timed call here ends in float(scalar).
  * the relay adds a large fixed cost per call (~60-70 ms measured).  The
    train loop runs K steps inside one jitted ``lax.fori_loop`` with a
    DYNAMIC trip count (one compile, any K); device step time is the
    DIFFERENCE quotient (T(2K) - T(K)) / K, which cancels the fixed
    roundtrip exactly.  Same differencing for peak calibration.
  * the K-step loop returns ONLY the final scalar loss — params never
    transfer back, so the transfer in the barrier is 4 bytes.
  * loop-carried sequential dependence (params_{i+1} = f(params_i)) makes
    the K iterations non-hoistable; fused-loop correctness was verified
    against K sequential single-step calls (bit-identical losses).
  * MFU uses ANALYTIC model FLOPs (ResNet-50 v1 fwd = 2*MACs =
    7.72 GFLOP/img at 224x224, train = 3x fwd) — the standard
    convention; XLA's compiled.cost_analysis() is reported alongside
    for diagnosis.  (r5 fix: earlier rounds used the 3.86 GMAC count
    as if it were FLOPs, halving every reported MFU.)
  * BOTH MFU ratios are emitted: "mfu_table" (vs the public table number
    for the reported device_kind) and "mfu_calibrated" (vs the measured
    matmul peak); headline "mfu" uses the larger denominator
    (conservative).  MFU > 1.0 is reported as an "anomaly", never as mfu.
  * remat is OFF by default at every batch size: honest timing showed the
    r03 "bs128 cliff" was a dispatch artifact, and remat costs ~20% real
    step time at bs128 (no HBM pressure at these sizes).

Prints ONE JSON line:
  {"metric", "value", "unit", "vs_baseline", "mfu", ...}
Always prints the line — on failure or budget exhaustion with whatever was
measured (value 0.0 and an "error" field if nothing was).

Env knobs: BENCH_DTYPE, BENCH_K (steps per timed dispatch, default 8),
BENCH_TIME_BUDGET (s), BENCH_BATCH, BENCH_BATCH2 (second MFU point, 0
disables), BENCH_CALIB_N (comma-separated matmul sizes, default
"4096,8192"), BENCH_CALIB_REPS (base rep count R; timing differences 2R vs
R, default 40), BENCH_REMAT_FROM_BS (rematerialize at batch >= this; 0 =
never, the default), BENCH_INIT_TIMEOUT (s; fail fast if device init
hangs; 0 disables the watchdog — init errors still stop after 8 retries).
"""
import functools
import json
import os
import sys
import time

BASELINE_IMG_S = 298.51
# ResNet-50 v1, 224x224, fwd pass: gluon resnet50_v1 = 3.86 GMACs
# (torchvision's 4.09 is the v1.5 variant), and model FLOPs = 2*MACs =
# 7.72e9/img.  Training step ~= 3x forward.
#
# ROUND-5 CORRECTION: r2-r4 used 3.86e9 here — the MAC count, not
# 2*MACs — understating every reported MFU by exactly 2x.  The HLO-level
# audit (tools/hlo_flops.py) shows the compiled step executes 1.09x the
# 2*MAC analytic (the 9% being stride-2 backward-data convs XLA charges
# over the zero-dilated input), so cost_analysis ~715 GF @ bs32 vs
# 3*7.72e9*32 = 741 GF analytic was never a 2x waste: r4's honest
# "mfu 0.135" was really ~0.27.
ANALYTIC_FWD_FLOPS_PER_IMG = 7.72e9
T_START = time.perf_counter()


def log(msg):
    print(f"[bench +{time.perf_counter() - T_START:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


def emit(payload):
    print(json.dumps(payload), flush=True)


# bf16 peak FLOP/s by TPU generation (public numbers); fallback is v5e.
_PEAK_FLOPS = [
    ("v2", 45e12), ("v3", 123e12), ("v4", 275e12),
    ("v5 lite", 197e12), ("v5litepod", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12), ("v6", 918e12), ("trillium", 918e12),
]


def peak_flops_for(device_kind: str):
    dk = device_kind.lower()
    for key, val in _PEAK_FLOPS:
        if key in dk:
            return val, key
    return 197e12, f"unknown({device_kind})->assumed v5e"


def calibrate_peak(dev, reps=None):
    """Empirical peak bf16 FLOP/s: chained NxN matmuls on-device.

    One compiled program with a dynamic rep count; timed by transferring a
    scalar element of the result (the only real barrier on this relay);
    per-matmul time is (T(2R) - T(R)) / R so the fixed relay roundtrip
    cancels.  Measured on TPU v5 lite: 181 TF/s at n=4096 (92% of the 197
    table peak) — the differencing recovers a physical number where the
    old block_until_ready timing produced 7-31000 TF/s depending on queue
    state.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    reps = reps or int(os.environ.get("BENCH_CALIB_REPS", 40))
    sweep_env = os.environ.get("BENCH_CALIB_N", "4096,8192")
    sizes = [int(s) for s in str(sweep_env).split(",") if s.strip()]
    budget = float(os.environ.get("BENCH_TIME_BUDGET", 1200))
    key = jax.random.PRNGKey(0)
    sweep = {}
    best = 0.0

    for n in sizes:
        if time.perf_counter() - T_START > budget * 0.85:
            sweep[f"skipped_{n}"] = "time budget"
            continue
        @functools.partial(jax.jit, device=dev)
        def init(k, n=n):
            ka, kb = jax.random.split(k)
            a = jax.random.normal(ka, (n, n), jnp.bfloat16)
            b = jax.random.normal(kb, (n, n), jnp.bfloat16)
            return a, b

        @functools.partial(jax.jit, device=dev)
        def chain(r, salt, a, b):
            # b_{i+1} = a @ b_i: sequential dependence, nothing hoistable;
            # returns one scalar so the sync transfer is 4 bytes.
            # salt: fresh per call — the relay caches repeated identical
            # (executable, args) executions (measured: "1022 TF/s" on a
            # 4096^3 chain), a live unique input defeats that
            def body(_, ab):
                a_, b_ = ab
                return a_, a_ @ b_
            b = b + (salt * 1e-30).astype(b.dtype)
            out = lax.fori_loop(0, r, body, (a, b))[1]
            return out[0, 0].astype(jnp.float32)

        a, b = init(key)
        float(chain(jnp.int32(2), jnp.float32(1), a, b))  # compile + warm
        calls = [1]

        def timed(r, tries=3):
            ts = []
            for _ in range(tries):
                calls[0] += 1
                t0 = time.perf_counter()
                float(chain(jnp.int32(r), jnp.float32(calls[0]), a, b))
                ts.append(time.perf_counter() - t0)
            return min(ts)

        t1 = timed(reps)
        t2 = timed(2 * reps)
        per_matmul = (t2 - t1) / reps
        if per_matmul <= 0:
            sweep[n] = {"anomaly": f"T(2R)={t2:.4f}s <= T(R)={t1:.4f}s"}
            continue
        fl = 2.0 * n * n * n / per_matmul
        sweep[n] = {"tflops": round(fl / 1e12, 2),
                    "ms_per_matmul": round(per_matmul * 1e3, 4),
                    "fixed_overhead_ms": round(
                        (t1 - per_matmul * reps) * 1e3, 1)}
        best = max(best, fl)
    return best, {"base_reps": reps, "method": "transfer-sync differenced",
                  "sweep": sweep}


def measure_checkpoint():
    """Time-to-safe metrics: how long a checkpoint save blocks the train
    loop (async manager: device->host snapshot only) vs the equivalent
    synchronous save, and restore latency — on BENCH_CKPT_MB of state.

    Emits ckpt_save_blocking_ms (async headline), ckpt_save_sync_ms
    (the serialize+sha256+fsync+commit cost the writer thread hides),
    blocking_fraction, and ckpt_restore_s (checksum-verified load).
    Best-of-3 each, so one fs hiccup doesn't skew the trajectory.
    """
    import shutil
    import tempfile

    import numpy as np
    from mxnet_tpu import config as mxcfg
    from mxnet_tpu.checkpoint import CheckpointManager

    mb = max(1, mxcfg.get("BENCH_CKPT_MB"))
    n = mb * 1024 * 1024 // 4 // 8
    arrays = {f"w{i}": np.random.randn(n).astype(np.float32)
              for i in range(8)}
    nbytes = sum(a.nbytes for a in arrays.values())
    root = tempfile.mkdtemp(prefix="bench-ckpt-")
    try:
        sync_ms, blocking_ms, restore_s = [], [], []
        with CheckpointManager(os.path.join(root, "sync"), keep_last=1,
                               async_save=False) as mgr:
            for i in range(3):
                t0 = time.perf_counter()
                mgr.save(i + 1, arrays=arrays, block=True)
                sync_ms.append((time.perf_counter() - t0) * 1e3)
        with CheckpointManager(os.path.join(root, "async"), keep_last=1,
                               async_save=True) as mgr:
            for i in range(3):
                t0 = time.perf_counter()
                mgr.save(i + 1, arrays=arrays)  # returns after the snapshot
                blocking_ms.append((time.perf_counter() - t0) * 1e3)
                mgr.wait()
            for _ in range(3):
                t0 = time.perf_counter()
                mgr.restore()  # checksum-verified
                restore_s.append(time.perf_counter() - t0)
        blk, syn = min(blocking_ms), min(sync_ms)
        return {
            "metric": "ckpt_save_blocking_ms",
            "value": round(blk, 2),
            "ckpt_save_sync_ms": round(syn, 2),
            "blocking_fraction": round(blk / syn, 4) if syn else None,
            "ckpt_restore_s": round(min(restore_s), 4),
            "state_bytes": nbytes,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def measure_serving():
    """Inference serving throughput: ResNet-18 through the DynamicBatcher
    under synthetic Poisson arrivals (open loop).

    Three phases: (1) warm the full bucket so the XLA compile is outside
    the window; (2) a short closed-loop probe to find the saturated
    throughput; (3) a BENCH_SERVE_SECONDS open-loop run with exponential
    inter-arrivals at BENCH_SERVE_RATE (0 = auto: 1.2x the probe, i.e.
    deliberately slightly over capacity so queueing + shedding engage).
    Headline value is completed img/s over the open-loop window; p50/p99
    and batch occupancy come from serving metrics.
    """
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import config as mxcfg
    from mxnet_tpu import serving
    from mxnet_tpu.gluon.model_zoo import vision

    max_batch = mxcfg.get("BENCH_SERVE_BATCH")
    lat_ms = mxcfg.get("BENCH_SERVE_LATENCY_MS")
    seconds = mxcfg.get("BENCH_SERVE_SECONDS")
    rate = mxcfg.get("BENCH_SERVE_RATE")

    net = vision.resnet18_v1()
    net.initialize(mx.initializer.Xavier())
    net(mx.nd.zeros((1, 3, 224, 224)))  # materialize deferred-init params
    server = serving.ModelServer(
        max_batch_size=max_batch, max_latency_ms=lat_ms,
        max_queue_depth=max(256, 4 * max_batch), name="bench")
    server.load("resnet18", block=net)
    sample = np.random.randn(3, 224, 224).astype(np.float32)

    def fire(n):
        futs = []
        for _ in range(n):
            futs.append(server.predict_async("resnet18", {"data": sample}))
        for f in futs:
            f.result(600)

    log(f"[serving] warmup: bucket {max_batch} compile + first batch")
    fire(max_batch)
    t0 = time.perf_counter()
    fire(4 * max_batch)
    probe_rps = 4 * max_batch / (time.perf_counter() - t0)
    lam = rate or 1.2 * probe_rps
    log(f"[serving] probe {probe_rps:.1f} img/s closed-loop; "
        f"Poisson arrivals at {lam:.1f} req/s for {seconds:.0f}s")

    rng = np.random.default_rng(0)
    futures, shed = [], 0
    t_begin = time.perf_counter()
    t_next, t_end = t_begin, t_begin + seconds
    while True:
        now = time.perf_counter()
        if now >= t_end:
            break
        t_next += rng.exponential(1.0 / lam)
        if t_next > now:
            time.sleep(t_next - now)
        try:
            futures.append(
                server.predict_async("resnet18", {"data": sample}))
        except serving.ServingOverloadError:
            shed += 1
    completed = 0
    for f in futures:
        try:
            f.result(600)
            completed += 1
        except Exception:
            pass
    elapsed = time.perf_counter() - t_begin
    snap = server.stats()
    server.shutdown()
    return {
        "metric": "resnet18_serve_img_per_sec",
        "value": round(completed / elapsed, 2),
        "unit": "img/s",
        "window_s": round(elapsed, 2),
        "arrival_rate_rps": round(lam, 2),
        "probe_closed_loop_rps": round(probe_rps, 2),
        "offered": len(futures) + shed,
        "completed": completed,
        "shed": shed,
        "p50_ms": snap["latency_ms"]["p50"],
        "p99_ms": snap["latency_ms"]["p99"],
        "batch_occupancy": snap.get("batch_occupancy"),
        "max_batch_size": max_batch,
        "max_latency_ms": lat_ms,
    }


def _resnet50_symbol(num_classes=1000):
    """Symbolic ResNet-50 v1 (bottleneck 3-4-6-3) for the Module-API
    dispatch phases — the symbol/Module path is what the fused train
    step optimizes, unlike the functionalized gluon net timed above."""
    import mxnet_tpu as mx
    sym = mx.sym

    def conv_bn(x, f, k, s, p, name, act=True):
        x = sym.Convolution(x, num_filter=f, kernel=(k, k), stride=(s, s),
                            pad=(p, p), no_bias=True, name=name + "_conv")
        x = sym.BatchNorm(x, fix_gamma=False, name=name + "_bn")
        return sym.Activation(x, act_type="relu") if act else x

    def bottleneck(x, f, stride, dim_match, name):
        body = conv_bn(x, f // 4, 1, 1, 0, name + "_a")
        body = conv_bn(body, f // 4, 3, stride, 1, name + "_b")
        body = conv_bn(body, f, 1, 1, 0, name + "_c", act=False)
        if dim_match:
            sc = x
        else:
            sc = sym.Convolution(x, num_filter=f, kernel=(1, 1),
                                 stride=(stride, stride), no_bias=True,
                                 name=name + "_sc_conv")
            sc = sym.BatchNorm(sc, fix_gamma=False, name=name + "_sc_bn")
        return sym.Activation(body + sc, act_type="relu")

    data = sym.Variable("data")
    body = conv_bn(data, 64, 7, 2, 3, "stem")
    body = sym.Pooling(body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max")
    for st, (units, f) in enumerate(zip((3, 4, 6, 3),
                                        (256, 512, 1024, 2048))):
        for u in range(units):
            stride = 2 if (st > 0 and u == 0) else 1
            body = bottleneck(body, f, stride, u != 0, f"s{st}_u{u}")
    pool = sym.Pooling(body, global_pool=True, pool_type="avg",
                       kernel=(7, 7))
    fc = sym.FullyConnected(sym.Flatten(pool), num_hidden=num_classes,
                            name="fc1")
    return sym.SoftmaxOutput(fc, name="softmax")


def _module_steps(symbol, data_shape, fused, steps, warmup=2,
                  optimizer_params=None):
    """Train `steps` Module steps on CPU; returns (ms/step,
    dispatches/step).  Runs entirely on the jax CPU backend — no TPU
    relay involved — so this is measurable in every environment."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import io as mxio, profiler as prof

    os.environ["MXNET_FUSED_STEP"] = "1" if fused else "0"
    bs = data_shape[0]
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(*data_shape).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 10, bs).astype(np.float32))
    batch = mxio.DataBatch(data=[x], label=[y])
    mod = mx.mod.Module(symbol, context=mx.cpu())
    mod.bind(data_shapes=[("data", x.shape)],
             label_shapes=[("softmax_label", y.shape)])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params=optimizer_params or
                       {"learning_rate": 0.01, "momentum": 0.9})
    probe = mod._exec.arg_dict[mod._param_names[0]]
    for _ in range(warmup):
        mod.forward_backward(batch)
        mod.update()
    mod._exec.arg_dict[mod._param_names[0]]._data.block_until_ready()
    prof.reset_dispatch_counts()
    t0 = time.perf_counter()
    for _ in range(steps):
        mod.forward_backward(batch)
        mod.update()
    mod._exec.arg_dict[mod._param_names[0]]._data.block_until_ready()
    ms = (time.perf_counter() - t0) / steps * 1e3
    disp = prof.dispatch_counts().get("total", 0) / steps
    del probe
    return ms, disp


def measure_telemetry_overhead():
    """Disabled-path cost of one telemetry.span (ISSUE 5): the span
    tracer annotates fit/serving hot loops unconditionally, so the
    disabled path must stay well under 1 us — this phase keeps that
    budget measured alongside the step-time numbers it protects."""
    import time as _t

    from mxnet_tpu import telemetry
    was_enabled = telemetry.enabled()
    telemetry.disable()
    try:
        n = 50000
        best = float("inf")
        for _ in range(3):
            t0 = _t.perf_counter()
            for _ in range(n):
                with telemetry.span("bench/noop"):
                    pass
            best = min(best, (_t.perf_counter() - t0) / n)
    finally:
        if was_enabled:
            telemetry.enable()
    return {"telemetry": {"metric": "telemetry_disabled_span_ns",
                          "value": round(best * 1e9, 1), "unit": "ns",
                          "budget_ns": 1000}}


def measure_trace_overhead():
    """Disabled-path cost of the ISSUE-12 observability hooks: one
    trace start+stage (the per-request/per-window tracing) plus one
    flight-recorder record (the decision-event ring).  Both are wired
    into hot paths unconditionally, so — like a disabled span or chaos
    failpoint — the off path must stay well under 1 us per event."""
    import time as _t

    from mxnet_tpu.telemetry import flight, trace

    was_trace = trace.enabled()
    was_flight = flight.enabled()
    trace.disable()
    flight.disable()
    try:
        n = 50000
        best = float("inf")
        for _ in range(3):
            t0 = _t.perf_counter()
            for _ in range(n):
                tr = trace.start("bench")
                with tr.stage("noop"):
                    pass
                flight.record("bench", "noop", value=1)
            # three hook events per iteration: start+stage, record
            best = min(best, (_t.perf_counter() - t0) / (3 * n))
    finally:
        if was_trace:
            trace.enable()
        if was_flight:
            flight.enable()
    return {"trace": {"metric": "trace_disabled_overhead_ns",
                      "value": round(best * 1e9, 1), "unit": "ns",
                      "budget_ns": 1000}}


def measure_alert_overhead():
    """ISSUE-13 observatory overheads, three numbers:

    * ``alert_tick_overhead_us`` — one evaluation pass of the DEFAULT
      rule pack on an armed engine (< 1 ms: the engine may tick at 1 Hz
      on a serving box without showing up in p99);
    * ``resource_sample_overhead_us`` — one host resource sample
      (RSS + fds + threads; < 1 ms for the same reason — checkpoint-dir
      disk walks excluded here, they are sampled on the slow thread);
    * ``alerts_disabled_tick_ns`` — the module-level tick with the
      engine DISARMED (< 1 µs, the span/trace/failpoint bar: callers
      may pulse it opportunistically from hot paths)."""
    import time as _t

    from mxnet_tpu.telemetry import alerts, resources

    # disabled path first: module state must be pristine
    assert not alerts.enabled()
    n = 50000
    best_off = float("inf")
    for _ in range(3):
        t0 = _t.perf_counter()
        for _ in range(n):
            alerts.tick()
        best_off = min(best_off, (_t.perf_counter() - t0) / n)

    eng = alerts.AlertEngine()  # the default pack, real sampler
    eng.tick()  # warm: metric families + probes resolve once
    best_tick = float("inf")
    for _ in range(5):
        t0 = _t.perf_counter()
        eng.tick()
        best_tick = min(best_tick, _t.perf_counter() - t0)

    best_sample = float("inf")
    for _ in range(5):
        t0 = _t.perf_counter()
        resources.sample_now(disk=False)
        best_sample = min(best_sample, _t.perf_counter() - t0)

    return {
        "alerts": {"metric": "alert_tick_overhead_us",
                   "value": round(best_tick * 1e6, 2), "unit": "us",
                   "budget_us": 1000,
                   "disabled_tick_ns": round(best_off * 1e9, 1),
                   "disabled_budget_ns": 1000},
        "resource_sample": {"metric": "resource_sample_overhead_us",
                            "value": round(best_sample * 1e6, 2),
                            "unit": "us", "budget_us": 1000},
    }


def measure_degraded_p99():
    """Relay-proof host phase ``degraded_p99_ms`` (ISSUE 8): serving p99
    with one of two batcher workers WEDGED (chaos failpoint) versus
    healthy, with load shedding live.  Opara's concurrency argument cut
    down to a gate: a wedged worker must degrade p99 by at most 3x —
    the healthy worker + the bounded queue + shedding absorb the loss,
    they don't queue it.  Pure-host numpy runner: no device, no relay."""
    import threading as _th
    import time as _t

    import numpy as _np

    import mxnet_tpu.chaos as _chaos
    from mxnet_tpu.serving.batcher import (DynamicBatcher,
                                           RequestTimeoutError,
                                           ServingOverloadError)

    w = _np.random.RandomState(0).randn(64, 64).astype(_np.float32) * 0.1

    def runner(feed, n_real):
        _t.sleep(0.002)  # a ~2 ms model: service time dominates jitter
        return [feed["x"] @ w]

    def drive(batcher, seconds, n_clients=8):
        lat_ms, sheds, timeouts, failures = [], [0], [0], []
        stop = _t.perf_counter() + seconds
        lock = _th.Lock()

        def client():
            x = _np.ones((64,), _np.float32)
            while _t.perf_counter() < stop:
                t0 = _t.perf_counter()
                try:
                    # per-request deadline: requests claimed by a wedged
                    # worker resolve as typed RequestTimeoutError via the
                    # in-flight sweep — degraded mode sheds and times
                    # out, it never silently loses a request
                    batcher.submit({"x": x},
                                   timeout_ms=500.0).result(10.0)
                    with lock:
                        lat_ms.append((_t.perf_counter() - t0) * 1e3)
                except ServingOverloadError:
                    with lock:
                        sheds[0] += 1
                    _t.sleep(0.001)
                except RequestTimeoutError:
                    with lock:
                        timeouts[0] += 1
                except Exception as e:  # non-shed failure: gate-fatal
                    with lock:
                        failures.append(f"{type(e).__name__}: {e}")
            return None

        threads = [_th.Thread(target=client) for _ in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        lat_ms.sort()
        p99 = lat_ms[min(len(lat_ms) - 1,
                         int(0.99 * (len(lat_ms) - 1)))] if lat_ms else None
        return {"p99_ms": p99, "served": len(lat_ms), "shed": sheds[0],
                "timeouts": timeouts[0], "failures": failures}

    kw = dict(max_batch_size=8, max_latency_ms=2.0, num_workers=2,
              max_queue_depth=64, shed_watermark=16)
    healthy_b = DynamicBatcher(runner, name="bench-healthy", **kw)
    try:
        drive(healthy_b, 0.5)  # warm the code paths
        healthy = drive(healthy_b, 2.0)
    finally:
        healthy_b.close()

    _chaos.reset()
    _chaos.arm("serving/batcher/worker", "wedge", hits=1, count=1)
    degraded_b = DynamicBatcher(runner, name="bench-degraded", **kw)
    try:
        degraded = drive(degraded_b, 2.0)
    finally:
        _chaos.release("serving/batcher/worker")
        _chaos.reset()
        degraded_b.close()

    bar = 3.0
    ratio = (degraded["p99_ms"] / healthy["p99_ms"]
             if healthy["p99_ms"] and degraded["p99_ms"] else None)
    return {"degraded": {
        "metric": "degraded_p99_ms",
        "value": degraded["p99_ms"], "unit": "ms",
        "healthy_p99_ms": healthy["p99_ms"],
        "ratio_vs_healthy": round(ratio, 3) if ratio else None,
        "bar_ratio": bar,
        "served_degraded": degraded["served"],
        "shed_degraded": degraded["shed"],
        "timeouts_degraded": degraded["timeouts"],
        "non_shed_failures": degraded["failures"] + healthy["failures"],
        "passed": bool(ratio is not None and ratio <= bar
                       and not degraded["failures"]
                       and not healthy["failures"]),
    }}


def measure_serve_pool():
    """Relay-proof host phases ``serve_sustained_img_per_sec`` and
    ``serve_spike_p99_ms`` (ISSUE 10): replica-pool serving vs the
    single batcher, and tail latency under a 10x Poisson load spike.

    Runner is pure-host (per-item sleep — models per-sample device
    compute, releases the GIL so replicas genuinely overlap): no
    device, no relay.  Gates:

    * sustained: a BENCH_SERVE_SPIKE_REPLICAS-replica pool sustains
      >= 2x the closed-loop throughput of the single batcher;
    * spike: with SLO admission armed (slo self-tuned to 2.5x the
      measured steady p99), the p99 of ADMITTED requests inside a
      BENCH_SERVE_SPIKE_X (10x) arrival spike stays <= 3x the
      steady-state p99, every refusal is a typed ServingOverloadError,
      and zero admitted requests time out or drop.
    """
    import sys as _sys
    import threading as _th
    import time as _t

    import numpy as _np

    from mxnet_tpu import config as mxcfg
    from mxnet_tpu.serving.batcher import (RequestTimeoutError,
                                           ServingOverloadError)
    from mxnet_tpu.serving.metrics import ServingMetrics
    from mxnet_tpu.serving.router import ReplicaPool

    # a 10x-overload submit loop degenerates into a GIL-hogging tight
    # loop at the default 5 ms switch interval, starving the dispatch
    # threads it is supposed to measure — a load-GENERATOR artifact.
    # Real clients live on other hosts; shrink the GIL slice so the
    # in-process generator approximates them.
    prev_switch = _sys.getswitchinterval()
    _sys.setswitchinterval(0.0005)

    n_replicas = max(2, mxcfg.get("BENCH_SERVE_SPIKE_REPLICAS"))
    steady_s = float(mxcfg.get("BENCH_SERVE_SPIKE_SECONDS"))
    spike_x = float(mxcfg.get("BENCH_SERVE_SPIKE_X"))

    def factory(rid):
        def run(feed, n_real):
            # a ~2 ms/sample model: service time dominates framework
            # overhead (the regime replica scaling is for), and the
            # per-sample cost is what makes the >= 2x pool gate measure
            # added CAPACITY rather than batching-overhead amortization
            _t.sleep(0.002 * n_real + 0.001)
            return [feed["x"] * 2.0]
        return run

    kw = dict(max_batch_size=8, max_latency_ms=2.0, num_workers=1,
              max_queue_depth=256, shed_watermark=128)

    def closed_loop(pool, seconds, n_clients=16):
        done = [0]
        lock = _th.Lock()
        stop = _t.perf_counter() + seconds

        def client():
            x = _np.ones((16,), _np.float32)
            while _t.perf_counter() < stop:
                try:
                    pool.submit({"x": x}).result(10.0)
                    with lock:
                        done[0] += 1
                except ServingOverloadError:
                    _t.sleep(0.001)

        threads = [_th.Thread(target=client) for _ in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return done[0] / seconds

    # -- sustained: single batcher vs replica pool (closed loop) ---------
    single = ReplicaPool(factory, num_replicas=1, name="bench-single",
                         model="bench-single",
                         metrics=ServingMetrics("bench-single"), **kw)
    try:
        closed_loop(single, 0.4)  # warm the code paths
        single_rps = closed_loop(single, steady_s)
    finally:
        single.close()
    pool_metrics = ServingMetrics("bench-pool")
    pool = ReplicaPool(factory, num_replicas=n_replicas,
                       name="bench-pool", model="bench-pool",
                       metrics=pool_metrics, **kw)
    sustained_rps = closed_loop(pool, steady_s, n_clients=8 * n_replicas)
    sustained_bar = 2.0
    sustained = {
        "metric": "serve_sustained_img_per_sec",
        "value": round(sustained_rps, 1), "unit": "img/s",
        "single_batcher_img_per_sec": round(single_rps, 1),
        "ratio_vs_single": round(sustained_rps / max(single_rps, 1e-9), 2),
        "replicas": n_replicas,
        "bar_ratio": sustained_bar,
        "passed": bool(sustained_rps >= sustained_bar * single_rps),
    }

    # -- spike: Poisson steady window, then a 10x window -----------------
    def open_loop(seconds, lam):
        """Poisson arrivals at ``lam``; returns (submitted futures,
        sheds, other-typed-refusals)."""
        rng = _np.random.default_rng(0)
        x = _np.ones((16,), _np.float32)
        futures, sheds, refused = [], 0, []
        t_next = _t.perf_counter()
        t_end = t_next + seconds
        while True:
            now = _t.perf_counter()
            if now >= t_end:
                return futures, sheds, refused
            t_next += rng.exponential(1.0 / lam)
            # open-loop discipline: arrivals the generator could not
            # keep up with are dropped from the schedule, not burst as
            # a GIL-bound backlog (the rate cap is the generator's)
            t_next = max(t_next, now - 0.002)
            if t_next > now:
                _t.sleep(t_next - now)
            try:
                futures.append(pool.submit({"x": x}, timeout_ms=1000.0))
            except ServingOverloadError:
                sheds += 1
            except Exception as e:  # noqa: BLE001 — gate-fatal bucket
                refused.append(f"{type(e).__name__}: {e}")

    def settle(futures):
        """Resolve every submitted future; returns (ok, timeouts,
        failures) — an unresolved future is a DROP and gate-fatal."""
        ok, timeouts, failures = 0, 0, []
        for f in futures:
            try:
                f.result(10.0)
                ok += 1
            except RequestTimeoutError:
                timeouts += 1
            except Exception as e:  # noqa: BLE001 — gate-fatal bucket
                failures.append(f"{type(e).__name__}: {e}")
        return ok, timeouts, failures

    def p99(vals):
        vals.sort()
        return vals[min(len(vals) - 1,
                        int(0.99 * (len(vals) - 1)))] if vals else None

    try:
        steady_lam = 0.5 * sustained_rps
        pool_metrics.drain_latencies()
        futs, steady_sheds, steady_refused = open_loop(steady_s,
                                                       steady_lam)
        s_ok, s_to, s_fail = settle(futs)
        steady_p99 = p99(pool_metrics.drain_latencies())
        # arm SLO admission, self-tuned from the measured steady p99:
        # the controller sheds on PREDICTED p99 so the spike's tail is
        # bounded by refusals, not by queueing (2.0x leaves the last
        # admitted request's own service time inside the 3x gate)
        slo_ms = max(10.0, 2.0 * (steady_p99 or 10.0))
        pool.admission.slo_p99_ms = slo_ms
        futs, spike_sheds, spike_refused = open_loop(
            max(1.0, steady_s / 2), spike_x * steady_lam)
        k_ok, k_to, k_fail = settle(futs)
        spike_p99 = p99(pool_metrics.drain_latencies())
    finally:
        pool.close()
        _sys.setswitchinterval(prev_switch)

    bar = 3.0
    ratio = (spike_p99 / steady_p99
             if steady_p99 and spike_p99 else None)
    spike = {
        "metric": "serve_spike_p99_ms",
        "value": spike_p99, "unit": "ms",
        "steady_p99_ms": steady_p99,
        "ratio_vs_steady": round(ratio, 3) if ratio else None,
        "bar_ratio": bar,
        "spike_x": spike_x,
        "steady_rate_rps": round(steady_lam, 1),
        "slo_p99_ms": round(slo_ms, 1),
        "served_steady": s_ok, "served_spike": k_ok,
        "shed_steady": steady_sheds, "shed_spike": spike_sheds,
        "timeouts": s_to + k_to,
        "non_shed_failures": (steady_refused + spike_refused
                              + s_fail + k_fail),
        "passed": bool(ratio is not None and ratio <= bar
                       and spike_sheds > 0
                       and s_to + k_to == 0
                       and not (steady_refused + spike_refused
                                + s_fail + k_fail)),
    }
    return {"serve_sustained": sustained, "serve_spike": spike}


def measure_generation():
    """Relay-proof host phases ``generate_tokens_per_sec`` and
    ``generate_p99_intertoken_ms`` (ISSUE 16): stateful autoregressive
    sessions over the paged-KV GenerationEngine under Poisson arrivals.

    Runner is pure-host (``tiny_lm(jit=False)`` with a fixed
    per-decode-tick sleep — models a fixed per-step device cost that
    the whole slot cohort SHARES, which is exactly what continuous
    decode batching amortizes): no device, no relay.  Gates:

    * batching: the multi-slot engine sustains >= 1.5x the token
      throughput of a closed-loop single-session run (same model, same
      per-tick cost) — continuous decode batching must buy capacity;
    * prefix reuse: with half the arrivals sharing a common prompt
      head, the content-hash prefix cache ends the run with a hit rate
      >= 0.25 (hits / lookups);
    * health: zero non-shed session failures, and every intertoken
      gap sampled on the engine's emit path lands in the reservoir
      (p99 reported as ``generate_p99_intertoken_ms``).
    """
    import threading as _th
    import time as _t

    import numpy as _np

    from mxnet_tpu import config as mxcfg
    from mxnet_tpu.serving.batcher import (RequestTimeoutError,
                                           ServingOverloadError)
    from mxnet_tpu.serving.generation import GenerationEngine, tiny_lm

    seconds = float(mxcfg.get("BENCH_GENERATE_SECONDS"))
    rate = float(mxcfg.get("BENCH_GENERATE_RATE"))
    max_new = max(2, mxcfg.get("BENCH_GENERATE_TOKENS"))
    tick_s = 0.0005   # modeled fixed device cost per decode dispatch
    slots = 8

    def build_engine(name, prefix_entries):
        return GenerationEngine(
            tiny_lm(vocab=64, d_model=16, max_len=256, seed=0, jit=False,
                    per_token_cost_s=tick_s),
            name=name, slots=slots, page_tokens=16, kv_budget_mb=16,
            prefix_cache_entries=prefix_entries, max_len=256,
            session_timeout_s=60.0)

    rng = _np.random.default_rng(0)
    shared = rng.integers(1, 63, size=32).astype(_np.int32)

    def prompt_for(i):
        tail = rng.integers(1, 63, size=int(rng.integers(2, 10)))
        tail = tail.astype(_np.int32)
        return _np.concatenate([shared, tail]) if i % 2 else tail

    # -- closed-loop single session: the unbatched baseline --------------
    single = build_engine("bench-gen-single", prefix_entries=0)
    single.warm()
    try:
        t_end = _t.perf_counter() + max(0.5, seconds / 2)
        single_tokens, i = 0, 0
        t0 = _t.perf_counter()
        while _t.perf_counter() < t_end:
            single_tokens += len(single.generate(
                prompt_for(i), max_new_tokens=max_new))
            i += 1
        single_tps = single_tokens / (_t.perf_counter() - t0)
    finally:
        single.close()

    # -- open loop: Poisson session arrivals against the full engine -----
    eng = build_engine("bench-gen", prefix_entries=32)
    eng.warm()
    # default rate: ~60% of the slot pool's modeled token capacity
    lam = rate or 0.6 * slots * single_tps / max_new
    sessions, sheds, refused = [], 0, []
    try:
        t_next = _t.perf_counter()
        t_end = t_next + seconds
        i = 0
        while True:
            now = _t.perf_counter()
            if now >= t_end:
                break
            t_next += rng.exponential(1.0 / lam)
            t_next = max(t_next, now - 0.002)  # open-loop discipline
            if t_next > now:
                _t.sleep(t_next - now)
            try:
                sessions.append(eng.start_session(
                    prompt_for(i), max_new_tokens=max_new))
            except ServingOverloadError:
                sheds += 1
            except Exception as e:  # noqa: BLE001 — gate-fatal bucket
                refused.append(f"{type(e).__name__}: {e}")
            i += 1
        t0_drain = _t.perf_counter()
        ok, failures = 0, list(refused)
        for s in sessions:
            try:
                toks = s.result(timeout=30.0)
                ok += 1
                if len(toks) != max_new:
                    failures.append(f"short session: {len(toks)} tokens")
            except RequestTimeoutError:
                failures.append("session timed out (drop)")
            except Exception as e:  # noqa: BLE001 — gate-fatal bucket
                failures.append(f"{type(e).__name__}: {e}")
        wall = t0_drain - (t_end - seconds)
        stats = eng.stats()
        gaps = sorted(eng.metrics.drain_observations("intertoken_ms"))
        p99_inter = (gaps[min(len(gaps) - 1, int(0.99 * (len(gaps) - 1)))]
                     if gaps else None)
        tps = stats["tokens_emitted"] / max(wall, 1e-9)
    finally:
        eng.close()

    px = stats["prefix_cache"]
    lookups = px["hits"] + px["misses"]
    hit_rate = px["hits"] / lookups if lookups else 0.0
    ratio = tps / max(single_tps, 1e-9)
    throughput = {
        "metric": "generate_tokens_per_sec",
        "value": round(tps, 1), "unit": "tok/s",
        "single_session_tok_per_sec": round(single_tps, 1),
        "ratio_vs_single": round(ratio, 2),
        "bar_ratio": 1.5,
        "slots": slots, "arrival_rate_sessions_per_s": round(lam, 1),
        "sessions_ok": ok, "sessions_shed": sheds,
        "max_active": stats["max_active"],
        "prefix_hit_rate": round(hit_rate, 3),
        "prefix_hit_bar": 0.25,
        "non_shed_failures": failures,
        "passed": bool(ratio >= 1.5 and hit_rate >= 0.25
                       and ok > 0 and not failures),
    }
    intertoken = {
        "metric": "generate_p99_intertoken_ms",
        "value": round(p99_inter, 3) if p99_inter is not None else None,
        "unit": "ms",
        "samples": len(gaps),
        "modeled_tick_ms": tick_s * 1e3,
        "passed": bool(p99_inter is not None),
    }
    return {"generate_throughput": throughput,
            "generate_intertoken": intertoken}


_COLD_START_CHILD = r'''
import json, os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import compile as mxc
from mxnet_tpu import serving

LAYERS, WIDTH, IN_DIM = 24, 128, 64

def build():
    h = mx.sym.Variable("data")
    for i in range(LAYERS):
        h = mx.sym.FullyConnected(h, num_hidden=WIDTH, name=f"fc{i}")
        h = mx.sym.Activation(h, act_type="relu")
    return mx.sym.FullyConnected(h, num_hidden=10, name="out")

rng = np.random.RandomState(0)
params, prev = {}, IN_DIM
for i in range(LAYERS):
    params[f"fc{i}_weight"] = mx.nd.array(
        rng.randn(WIDTH, prev).astype(np.float32) * 0.05)
    params[f"fc{i}_bias"] = mx.nd.zeros((WIDTH,))
    prev = WIDTH
params["out_weight"] = mx.nd.array(
    rng.randn(10, prev).astype(np.float32) * 0.05)
params["out_bias"] = mx.nd.zeros((10,))

server = serving.ModelServer(max_batch_size=8, name="coldstart")
server.load("mlp", symbol=build(), params=params)
x = rng.randn(IN_DIM).astype(np.float32)
t0 = time.perf_counter()
server.predict("mlp", {"data": x}, wait_s=600.0)
first_ms = (time.perf_counter() - t0) * 1e3
counts = mxc.LEDGER.counts()
print(json.dumps({"first_request_ms": round(first_ms, 2),
                  "compiles": mxc.LEDGER.compiles(),
                  "jax": counts["jax"]}))
server.shutdown()
'''


def measure_cold_start():
    """Relay-proof CPU phase ``cold_start_first_request_ms`` (ISSUE 7):
    time-to-first-response of a freshly started serving process, with a
    cold persistent-cache dir vs a warm restart reusing it.

    Two identical subprocesses publish a 24-layer MLP and time the first
    ``predict``: the first populates ``MXNET_COMPILE_CACHE_DIR``, the
    second deserializes executables instead of compiling.  Gate: warm
    restart must be >= 2x faster to first response (the bar below), and
    the warm child's ledger must report 0 backend compiles.
    """
    import shutil
    import subprocess
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="bench-coldstart-")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               MXNET_COMPILE_CACHE="1",
               MXNET_COMPILE_CACHE_DIR=cache_dir,
               MXNET_COMPILE_CACHE_MIN_COMPILE_S="0")
    env.pop("XLA_FLAGS", None)  # single-device child, fastest startup

    def run_child(tag):
        t0 = time.perf_counter()
        proc = subprocess.run([sys.executable, "-c", _COLD_START_CHILD],
                              env=env, capture_output=True, text=True,
                              timeout=600)
        wall = time.perf_counter() - t0
        if proc.returncode != 0:
            raise RuntimeError(
                f"cold-start child ({tag}) failed: "
                f"{proc.stderr.strip()[-800:]}")
        payload = json.loads(proc.stdout.strip().splitlines()[-1])
        log(f"[cold_start] {tag}: first request "
            f"{payload['first_request_ms']:.0f} ms, "
            f"{payload['compiles']} compiles "
            f"(child wall {wall:.1f}s)")
        return payload

    try:
        cold = run_child("cold cache")
        warm = run_child("warm restart")
        speedup = cold["first_request_ms"] / max(1e-9,
                                                 warm["first_request_ms"])
        return {"cold_start": {
            "metric": "cold_start_first_request_ms",
            "value": warm["first_request_ms"],
            "unit": "ms",
            "cold_first_request_ms": cold["first_request_ms"],
            "speedup_warm_vs_cold": round(speedup, 2),
            "bar_speedup": 2.0,
            "passed": speedup >= 2.0,
            "warm_backend_compiles": warm["compiles"],
            "cold_backend_compiles": cold["compiles"],
            "model": "mlp24x128 via ModelServer",
        }}
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)


def measure_multichip():
    """Relay-proof CPU phase for the mesh fused distributed step
    (ISSUE 9): a subprocess forced to 8 fake CPU devices runs
    ``python -m mxnet_tpu.parallel.fused --bench-json`` — a dp=2,tp=2
    Module.fit with a dist_device_sync kvstore routed through the
    donated shard_map window.

    * ``multichip_dispatches_per_step`` — gate <= (1+eps)/K at
      K=BENCH_MULTICHIP_K: one donated dispatch per K-step window.
    * ``multichip_comm_blocking_pct`` — gate <= 30: the differential
      between the bucketed-collective window and the same window with
      collectives compiled out isolates communication's share of step
      wall.
    """
    import subprocess

    from mxnet_tpu import config as mxcfg

    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               BENCH_MULTICHIP_K=str(mxcfg.get("BENCH_MULTICHIP_K")))
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU relay
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.parallel.fused",
         "--bench-json"],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(f"multichip child failed: "
                           f"{proc.stderr.strip()[-800:]}")
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    disp = payload["multichip_dispatches_per_step"]
    blocking = payload["multichip_comm_blocking_pct"]
    return {
        "multichip_dispatch": {
            "metric": "multichip_dispatches_per_step",
            "value": disp,
            "budget": payload["budget"],
            "gate_pass": bool(disp <= payload["budget"]),
            "k": payload["k"], "mesh": payload["mesh"],
            "note": "Module.fit dispatches/step with a dist_device_sync "
                    "kvstore on a dp=2,tp=2 fake-device mesh (one "
                    "donated shard_map window per K steps; the "
                    "per-param push/pull loop is off the hot path)",
        },
        "multichip_comm": {
            "metric": "multichip_comm_blocking_pct",
            "value": blocking,
            "budget_pct": payload["blocking_budget_pct"],
            "gate_pass": bool(blocking <= payload["blocking_budget_pct"]),
            "step_ms": payload["step_ms"],
            "step_ms_comm_off": payload["step_ms_comm_off"],
            "comm_standalone_ms_per_step":
                payload["comm_standalone_ms_per_step"],
            "note": "share of mesh step wall attributable to the "
                    "bucketed gradient collectives (differential vs "
                    "MXNET_COLLECTIVE_MODE=off)",
        },
    }


def measure_multihost():
    """Relay-proof CPU phases for the elastic multi-host runtime
    (ISSUE 11): a subprocess supervisor runs 2 worker processes × 4
    fake CPU devices each through ``python -m
    mxnet_tpu.parallel.elastic --bench-json``.

    * ``multihost_dispatches_per_step`` — gate <= (1+eps)/K per
      process at K=BENCH_MULTIHOST_K: the donated shard_map window
      spans the cross-process mesh, so the budget holds across hosts.
    * ``multihost_recovery_s`` — gate <= 60: SIGTERM one host mid-run;
      wall time from the preemption notice to the respawned survivor
      world advancing training progress past the pre-fault mark.
    * ``collective_compression_ratio_2bit`` — gate >= 3x: 2-bit
      error-feedback codec's wire-byte shrink vs the dense psum on the
      same model (``mxnet_collective_bytes``).
    """
    import subprocess

    from mxnet_tpu import config as mxcfg

    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               BENCH_MULTIHOST_K=str(mxcfg.get("BENCH_MULTIHOST_K")))
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU relay
    env.pop("XLA_FLAGS", None)  # the launcher sets per-worker devices
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.parallel.elastic",
         "--bench-json"],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(f"multihost child failed: "
                           f"{proc.stderr.strip()[-800:]}")
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    disp = payload["multihost_dispatches_per_step"]
    recovery = payload["multihost_recovery_s"]
    ratio = payload["collective_compression_ratio_2bit"]
    return {
        "multihost_dispatch": {
            "metric": "multihost_dispatches_per_step",
            "value": disp,
            "budget": payload["budget"],
            "gate_pass": bool(disp <= payload["budget"]),
            "k": payload["k"], "world": payload["world"],
            "note": "per-process Module.fit dispatches/step on a "
                    "2-process x 4-fake-device jax.distributed mesh "
                    "(gloo collectives inside the donated shard_map "
                    "window; elastic launcher supervised)",
        },
        "multihost_recovery": {
            "metric": "multihost_recovery_s",
            "value": recovery,
            "budget_s": payload["recovery_budget_s"],
            "gate_pass": bool(recovery <= payload["recovery_budget_s"]),
            "restarts": payload["restarts"],
            "note": "SIGTERM of host 1/2 mid-run -> survivors boundary-"
                    "checkpoint, launcher respawns the dp/2 world, "
                    "clock stops when training progress advances",
        },
        "multihost_compression": {
            "metric": "collective_compression_ratio_2bit",
            "value": ratio,
            "budget_x": payload["compression_budget_x"],
            "gate_pass": bool(ratio >= payload["compression_budget_x"]),
            "note": "dense psum wire bytes / 2-bit packed all_gather "
                    "wire bytes per rank (ring schedules), same model "
                    "(mxnet_collective_bytes)",
        },
    }


def measure_fleet():
    """Relay-proof CPU phase for the fleet observability plane
    (ISSUE 20): one subprocess runs ``python -m
    mxnet_tpu.telemetry.fleet_sim --ranks 1000 --json`` — 1000
    in-process synthetic reporters (delta pushes, scripted anomalies)
    against one real leader on a virtual clock, with an internal
    rank=100 reference run for the sublinearity ratio and the rank<=8
    byte-compat pin.

    * ``fleet_merge_p99_ms``   — gate < 1: per-push leader merge p99.
    * ``fleet_rollup_cpu_ms``  — gate < 50: summary rollup at scrape.
    * ``fleet_scrape_kib``     — gate < 256: summary /fleet.json bytes.
    * ``fleet_sublinearity``   — gate <= 3x: rank=1000 merge p99 over
      the rank=100 reference.
    """
    import subprocess

    from mxnet_tpu import config as mxcfg

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU relay
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.telemetry.fleet_sim",
         "--ranks", str(mxcfg.get("MXNET_FLEET_SIM_RANKS")),
         "--cycles", str(mxcfg.get("MXNET_FLEET_SIM_CYCLES")),
         "--json"],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0 and not proc.stdout.strip():
        raise RuntimeError(f"fleet sim child failed: "
                           f"{proc.stderr.strip()[-800:]}")
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    res, gates = payload["result"], payload["gates"]
    sub = gates.get("sublinear_vs_ref", {})
    return {
        "fleet_merge": {
            "metric": "fleet_merge_p99_ms",
            "value": round(res["merge"]["p99_ms"], 4),
            "budget_ms": gates["merge_p99_ms"]["limit"],
            "gate_pass": bool(gates["merge_p99_ms"]["ok"]),
            "pushes": res["merge"]["pushes"],
            "delta_pushes": res["merge"]["delta"],
            "resyncs": res["merge"]["resync"],
            "note": "per-push leader merge latency p99 at rank="
                    f"{res['ranks']} (delta upsert into the sharded "
                    "FleetStore; virtual clock, pure host CPU)",
        },
        "fleet_rollup": {
            "metric": "fleet_rollup_cpu_ms",
            "value": round(res["rollup"]["max_ms"], 3),
            "budget_ms": gates["rollup_ms"]["limit"],
            "gate_pass": bool(gates["rollup_ms"]["ok"]),
            "p50_ms": round(res["rollup"]["p50_ms"], 3),
            "note": "summary rollup cost at scrape time, worst cycle "
                    "(bounded-staleness cache + incremental family "
                    "catalog; O(families + anomalous ranks))",
        },
        "fleet_scrape": {
            "metric": "fleet_scrape_kib",
            "value": round(res["scrape"]["summary_kib"], 2),
            "budget_kib": gates["scrape_kib"]["limit"],
            "gate_pass": bool(gates["scrape_kib"]["ok"]),
            "note": "summary-mode /fleet.json bytes at rank="
                    f"{res['ranks']} (per-rank detail stays behind "
                    "?detail=rank)",
        },
        "fleet_sublinear": {
            "metric": "fleet_sublinearity",
            "value": round(sub.get("value", 0.0), 3),
            "budget_x": sub.get("limit"),
            "gate_pass": bool(sub.get("ok", False)),
            "ref_ranks": sub.get("ref_ranks"),
            "backcompat_identical": bool(
                payload["backcompat"]["identical"]),
            "alert_lag_intervals": res["alerts"]["lag_intervals"],
            "note": "rank=1000 merge p99 over the rank=100 reference "
                    "run (plus the rank<=8 byte-compat pin and the "
                    "breach->leader alert propagation lag)",
        },
    }


def measure_train_dispatch():
    """CPU-measurable perf signal for the fused train step (no TPU relay
    needed, unlike resnet50_train_img_per_sec which has been
    relay-blocked since BENCH_r02):

    * ``resnet50_step_dispatches`` — XLA computation launches per
      Module train step on symbolic ResNet-50, fused vs per-param loop.
      The count is shape-independent, so it runs at a small image size
      (BENCH_DISPATCH_IMAGE) to keep CPU conv time out of the budget.
    * ``train_step_ms_bs32`` — wall time per step at batch 32 on a
      deep-narrow MLP (49 dispatch-bound layers) where launch overhead,
      not FLOPs, dominates — the quantity the fused step eliminates.
      ResNet-50 at bs32 on CPU is conv-bound (~1 min/step), which would
      measure Eigen, not dispatch.
    """
    import mxnet_tpu as mx
    from mxnet_tpu import config as mxcfg

    img = mxcfg.get("BENCH_DISPATCH_IMAGE")
    dbs = mxcfg.get("BENCH_DISPATCH_BATCH")
    steps = mxcfg.get("BENCH_DISPATCH_STEPS")

    log(f"[dispatch] resnet50 dispatch count @ {dbs}x3x{img}x{img}")
    rn50 = _resnet50_symbol()
    f_ms, f_disp = _module_steps(rn50, (dbs, 3, img, img), True, 2)
    l_ms, l_disp = _module_steps(rn50, (dbs, 3, img, img), False, 2)

    log(f"[dispatch] deep-MLP train_step_ms @ bs32 x{steps}")

    def deep_mlp(layers=24, width=64):
        h = mx.sym.Variable("data")
        for i in range(layers):
            h = mx.sym.FullyConnected(h, num_hidden=width, name=f"fc{i}")
            h = mx.sym.Activation(h, act_type="relu")
        h = mx.sym.FullyConnected(h, num_hidden=10, name="fc_out")
        return mx.sym.SoftmaxOutput(h, name="softmax")

    mf_ms, mf_disp = _module_steps(deep_mlp(), (32, 64), True, steps)
    ml_ms, ml_disp = _module_steps(deep_mlp(), (32, 64), False, steps)

    return {
        "dispatch": {
            "metric": "resnet50_step_dispatches",
            "value": f_disp,
            "unfused_dispatches_per_step": l_disp,
            "fused_step_ms": round(f_ms, 1),
            "unfused_step_ms": round(l_ms, 1),
            "image": img, "batch": dbs,
            "note": "Module-API XLA launches/step; count is "
                    "shape-independent (small image keeps CPU convs "
                    "out of the budget)",
        },
        "train_step": {
            "metric": "train_step_ms_bs32",
            "value": round(mf_ms, 3),
            "unfused_ms": round(ml_ms, 3),
            "improvement_vs_loop": round(1.0 - mf_ms / ml_ms, 3),
            "fused_dispatches_per_step": mf_disp,
            "unfused_dispatches_per_step": ml_disp,
            "model": "mlp24x64 (dispatch-bound)",
            "steps": steps,
        },
    }


def measure_graftlint():
    """ISSUE-15 lint-cost phase: ``graftlint_full_tree_s`` — one
    whole-tree run of the two-phase engine (lexical rules + summary
    collection + call-graph resolution + flow rules) in a fresh
    subprocess, gated under the same 15 s wall budget ci/run.sh
    enforces.  Lint runs before every test phase, so its cost is a hot
    path like any other: the per-rule breakdown rides along from
    ``--timings`` so a regression names its rule."""
    import json as _json
    import subprocess as _sp
    import sys as _sys
    import time as _t

    budget_s = 15.0
    best = float("inf")
    timings = {}
    for _ in range(2):
        t0 = _t.perf_counter()
        r = _sp.run(
            [_sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "graftlint.py"),
             "--fail-on-new", "--timings", "--json"],
            capture_output=True, text=True, timeout=120)
        wall = _t.perf_counter() - t0
        if r.returncode != 0:
            raise RuntimeError(
                f"graftlint --fail-on-new failed during bench: "
                f"{r.stdout[-500:]}")
        best = min(best, wall)
        timings = _json.loads(r.stdout).get("timings", {})
    slowest = sorted(((v, k) for k, v in timings.items()
                      if not k.startswith("(")), reverse=True)[:3]
    return {"graftlint": {
        "metric": "graftlint_full_tree_s",
        "value": round(best, 2), "unit": "s",
        "budget_s": budget_s,
        "gate_pass": bool(best < budget_s),
        "slowest_rules": {k: round(v, 3) for v, k in slowest},
    }}


def measure_kernels():
    """ISSUE-17 kernels-layer phases (BENCH_KERNELS), relay-proof:

    * ``kernel_tuner_overhead_s`` — a cold measured tune of every
      registered kernel on a bench shape (grid capped by
      MXNET_KERNELS_TUNE_BUDGET) into a throwaway namespace, gated
      under a fixed wall budget.  Every search must commit a ``tuned``
      winner, and re-resolving every kernel afterwards must be pure
      ladder work: ZERO new tune traces on the PR 7 ledger;
    * ``kernel_device`` — tuned-vs-reference device latency ships
      relay-ARMED: on a CPU backend it reports ``relay-dormant``
      (interpreted Pallas measures the interpreter, not the kernel)
      and the ratio gate arms itself the first run a TPU backend is
      live.
    """
    import tempfile as _tf
    import time as _t

    import numpy as _np

    import jax as _jax
    from mxnet_tpu import kernels as _k
    from mxnet_tpu.compile.ledger import LEDGER
    from mxnet_tpu.kernels import autotune as _at

    budget_s = 60.0
    shapes = {"layernorm": (256, 128), "softmax_ce": (256, 64),
              "attention": (2, 2, 64, 16)}
    prev = {k: os.environ.get(k)
            for k in ("MXNET_COMPILE_CACHE_DIR", "MXNET_KERNELS")}
    os.environ["MXNET_COMPILE_CACHE_DIR"] = _tf.mkdtemp(
        prefix="bench-kernels-")
    os.environ["MXNET_KERNELS"] = "tuned"
    try:
        _k.reset_for_tests()
        before = LEDGER.trace_count("kernels/tune")
        t0 = _t.perf_counter()
        winners = {}
        for name, shape in shapes.items():
            cfg, src = _k.tune(name, shape, _np.float32, repeats=1)
            winners[name] = {"config": cfg, "source": src}
        tune_s = _t.perf_counter() - t0
        tunes = LEDGER.trace_count("kernels/tune") - before
        for name, shape in shapes.items():
            _k.get(name, shape, _np.float32)
        retunes = LEDGER.trace_count("kernels/tune") - before - tunes
        all_tuned = all(w["source"] == "tuned" for w in winners.values())

        backend = _jax.default_backend()
        if backend == "tpu":
            spec = _k.get_spec("layernorm")
            rng = _np.random.RandomState(7)
            args, kwargs = spec.example_inputs(shapes["layernorm"],
                                               _np.float32, rng)
            cfg = winners["layernorm"]["config"]
            tuned_ms = _at._measure(spec.make(dict(cfg)), args, kwargs, 20)
            ref_ms = _at._measure(spec.reference, args, kwargs, 20)
            device = {
                "metric": "kernel_layernorm_speedup_vs_reference",
                "value": round(ref_ms / max(tuned_ms, 1e-9), 3),
                "unit": "x", "status": "relay-live", "backend": backend,
                "tuned_ms": round(tuned_ms, 4),
                "reference_ms": round(ref_ms, 4),
                "gate_pass": bool(tuned_ms <= ref_ms * 1.1),
            }
        else:
            device = {
                "metric": "kernel_layernorm_speedup_vs_reference",
                "value": 0.0, "unit": "x", "status": "relay-dormant",
                "backend": backend,
                "note": "armed; measures tuned-vs-reference dispatch "
                        "latency once a TPU backend is live",
                "gate_pass": True,
            }
        return {
            "kernel_tuner": {
                "metric": "kernel_tuner_overhead_s",
                "value": round(tune_s, 2), "unit": "s",
                "budget_s": budget_s,
                "tunes": tunes, "retunes_on_reresolve": retunes,
                "winners": winners,
                "gate_pass": bool(tune_s < budget_s and tunes ==
                                  len(shapes) and retunes == 0 and
                                  all_tuned),
            },
            "kernel_device": device,
        }
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        _k.reset_for_tests()


def measure_numerics_overhead():
    """ISSUE-14 numerics-observatory overheads, two gates:

    * ``numerics_overhead_pct`` — armed (MXNET_NUMERICS=warn) K=8
      scanned-window step wall vs numerics-off on a compute-
      representative MLP (width 256 @ bs 512 — NOT the synthetic
      dispatch-bound width-64/bs-32 model, which exists to magnify
      per-step overheads: there the CPU backend's memory-bound reduce
      throughput, not the design, dominates.  At training-shaped
      batches the stat reductions amortize into real compute).
      Gate < 5%: the in-trace stats are two fused reductions per
      parameter riding the donated window, with the dispatches/step
      REQUIRED identical (the stats add zero dispatches);
    * ``numerics_disabled_ns`` — the disarmed hot-path gate
      (``numerics.armed()`` + the boundary check's early-out; < 1 µs,
      the span/trace/failpoint bar)."""
    import time as _t

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import io as mxio, profiler as prof
    from mxnet_tpu.telemetry import numerics

    # disabled-path cost first: module state pristine
    assert not numerics.armed()
    n = 100000
    best_off = float("inf")
    for _ in range(3):
        t0 = _t.perf_counter()
        for _ in range(n):
            numerics.armed()
            numerics.observe_window(None, "bench", 0, 0)
        best_off = min(best_off, (_t.perf_counter() - t0) / n)

    K, steps, bs = 8, 8, 512

    def mlp(layers=16, width=256):
        h = mx.sym.Variable("data")
        for i in range(layers):
            h = mx.sym.FullyConnected(h, num_hidden=width, name=f"fc{i}")
            h = mx.sym.Activation(h, act_type="relu")
        h = mx.sym.FullyConnected(h, num_hidden=10, name="fc_out")
        return mx.sym.SoftmaxOutput(h, name="softmax")

    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(steps * bs, 64).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 10, steps * bs).astype(np.float32))

    os.environ["MXNET_FUSED_STEP"] = "1"
    os.environ["MXNET_SCAN_STEPS"] = str(K)
    opt = {"learning_rate": 0.01, "momentum": 0.9}

    def make_runner(mode):
        os.environ["MXNET_NUMERICS"] = mode
        numerics.configure()
        it = mxio.NDArrayIter(x, y, batch_size=bs,
                              label_name="softmax_label")
        mod = mx.mod.Module(mlp(), context=mx.cpu())
        mod.fit(it, num_epoch=1, optimizer="sgd", optimizer_params=opt,
                initializer=mx.initializer.Xavier())  # warm: compiles
        return mod, it

    def epoch_ms(mod, it):
        it.reset()
        prof.reset_dispatch_counts()
        t0 = _t.perf_counter()
        mod.fit(it, num_epoch=1, optimizer="sgd", optimizer_params=opt)
        return ((_t.perf_counter() - t0) / steps * 1e3,
                prof.dispatch_counts().get("total", 0) / steps)

    # alternate BLOCKS per mode (the mode is baked into the trace, so
    # each toggle retraces — pay one throwaway epoch per block), judge
    # per ROUND (one adjacent off-block + on-block pair), and keep the
    # round with the smallest on/off ratio: a machine-load spike can
    # only INFLATE a round's ratio, so the min round is the cleanest
    # measurement a noisy box yields
    try:
        best = None  # (ratio, off_ms, on_ms, off_disp, on_disp)
        for _round in range(3):
            _mod, _it = make_runner("off")
            epoch_ms(_mod, _it)  # retrace settles
            r_off = sorted((epoch_ms(_mod, _it) for _ in range(3)),
                           key=lambda t: t[0])[1]  # median of 3
            _mod, _it = make_runner("warn")
            epoch_ms(_mod, _it)
            r_on = sorted((epoch_ms(_mod, _it) for _ in range(3)),
                          key=lambda t: t[0])[1]  # median of 3
            ratio = r_on[0] / r_off[0] if r_off[0] else 1.0
            if best is None or ratio < best[0]:
                best = (ratio, r_off[0], r_on[0], r_off[1], r_on[1])
    finally:
        os.environ.pop("MXNET_NUMERICS", None)
        os.environ.pop("MXNET_SCAN_STEPS", None)
        numerics.configure()
    _ratio, off_ms, on_ms, off_disp, on_disp = best
    overhead = max(0.0, _ratio - 1.0) * 100.0
    return {
        "numerics": {
            "metric": "numerics_overhead_pct",
            "value": round(overhead, 2),
            "unit": "%",
            "budget_pct": 5.0,
            "gate_pass": bool(overhead <= 5.0 and on_disp == off_disp),
            "k": K,
            "step_ms_armed": round(on_ms, 3),
            "step_ms_off": round(off_ms, 3),
            "dispatches_per_step_armed": round(on_disp, 4),
            "dispatches_per_step_off": round(off_disp, 4),
            "disabled_ns": round(best_off * 1e9, 1),
            "disabled_budget_ns": 1000,
        }}


def measure_data_pipeline():
    """ISSUE-19 streaming-data-plane gate (``BENCH_DATA``): a K=8
    scanned fit fed by the multi-worker window feed must hide the data
    plane behind compute —

    * ``data_wait_pct`` — total train-thread blocked-on-data time
      (the ``mxnet_data_wait_seconds`` histogram, recorded at the one
      place the train thread can block: ``WindowFeed.get``) as a
      percentage of epoch wall, on the compute-representative MLP
      (width 256 @ bs 512, same model as the numerics phase).  Gate
      < 5%: window N+1 stages on the feed thread while window N
      executes, so the train thread should almost never wait;
    * ``serial_ratio`` — pipelined epoch wall over the serial baseline
      (``workers=0``: same seeded shard order, read + staged inline on
      the train thread).  Reported, not gated (CPU-backend compute
      dominates both sides; the ratio is the relay proof, the 5% wait
      gate is the contract);
    * dispatches/step REQUIRED identical on vs off — the pipeline
      feeds the same donated window dispatch, it never adds one."""
    import time as _t

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import io_pipeline as mxpipe, profiler as prof
    from mxnet_tpu import telemetry as _tel

    K, steps, bs = 8, 16, 512

    def mlp(layers=16, width=256):
        h = mx.sym.Variable("data")
        for i in range(layers):
            h = mx.sym.FullyConnected(h, num_hidden=width, name=f"fc{i}")
            h = mx.sym.Activation(h, act_type="relu")
        h = mx.sym.FullyConnected(h, num_hidden=10, name="fc_out")
        return mx.sym.SoftmaxOutput(h, name="softmax")

    rng = np.random.RandomState(0)
    x = rng.randn(steps * bs, 64).astype(np.float32)
    y = rng.randint(0, 10, steps * bs).astype(np.float32)

    os.environ["MXNET_FUSED_STEP"] = "1"
    os.environ["MXNET_SCAN_STEPS"] = str(K)
    opt = {"learning_rate": 0.01, "momentum": 0.9}

    def make_runner(workers):
        if workers:
            os.environ["MXNET_DATA_WORKERS"] = str(workers)
        else:
            os.environ.pop("MXNET_DATA_WORKERS", None)
        it = mxpipe.DataPipeline(
            mxpipe.NDArraySource(x, y, batch_size=bs,
                                 batches_per_shard=1),
            workers=workers, seed=0)
        mod = mx.mod.Module(mlp(), context=mx.cpu())
        mod.fit(it, num_epoch=1, optimizer="sgd", optimizer_params=opt,
                initializer=mx.initializer.Xavier())  # warm: compiles
        return mod, it

    def epoch(mod, it):
        it.reset()
        prof.reset_dispatch_counts()
        wait0 = _tel._DATA_WAIT.stats()["sum"]
        t0 = _t.perf_counter()
        mod.fit(it, num_epoch=1, optimizer="sgd", optimizer_params=opt)
        wall = _t.perf_counter() - t0
        return (wall / steps * 1e3,
                prof.dispatch_counts().get("total", 0) / steps,
                _tel._DATA_WAIT.stats()["sum"] - wait0, wall)

    try:
        # serial baseline (workers=0: inline read + stage)
        mod0, it0 = make_runner(0)
        epoch(mod0, it0)  # settle
        off = sorted((epoch(mod0, it0) for _ in range(3)),
                     key=lambda t: t[0])[1]  # median of 3
        it0.close()
        # pipelined (2 readers + the window feed double-buffer)
        mod1, it1 = make_runner(2)
        epoch(mod1, it1)  # settle
        runs = sorted((epoch(mod1, it1) for _ in range(3)),
                      key=lambda t: t[0])
        on = runs[1]  # median of 3
        it1.close()
    finally:
        os.environ.pop("MXNET_DATA_WORKERS", None)
        os.environ.pop("MXNET_SCAN_STEPS", None)
    off_ms, off_disp, _w, _off_wall = off
    on_ms, on_disp, wait_s, on_wall = on
    wait_pct = (wait_s / on_wall * 100.0) if on_wall else 0.0
    return {
        "data_pipeline": {
            "metric": "data_wait_pct",
            "value": round(wait_pct, 2),
            "unit": "%",
            "budget_pct": 5.0,
            "gate_pass": bool(wait_pct < 5.0 and on_disp == off_disp),
            "k": K,
            "workers": 2,
            "step_ms_pipelined": round(on_ms, 3),
            "step_ms_serial": round(off_ms, 3),
            "serial_ratio": round(on_ms / off_ms, 3) if off_ms else 1.0,
            "data_wait_s_per_epoch": round(wait_s, 4),
            "dispatches_per_step_pipelined": round(on_disp, 4),
            "dispatches_per_step_serial": round(off_disp, 4),
        }}


def measure_scan_dispatch(fused_step_ms=None):
    """CPU-measurable perf signal for the K-step scanned train window
    (ISSUE 6): the same dispatch-bound deep MLP as train_step_ms_bs32,
    but driven through Module.fit so MXNET_SCAN_STEPS batches run as ONE
    donated lax.scan dispatch.

    * ``scan_dispatches_per_step`` — framework dispatches per train step
      at K=BENCH_SCAN_K (gate: <= (1+eps)/K; eps=0.25).
    * ``train_step_ms_scan_k<K>`` — amortized wall per step (bar: >=25%
      below the PR-4 fused per-step figure measured in the same run).
    """
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import config as mxcfg, io as mxio, profiler as prof

    K = max(2, mxcfg.get("BENCH_SCAN_K"))
    steps = max(K, (mxcfg.get("BENCH_DISPATCH_STEPS") // K) * K)

    def deep_mlp(layers=24, width=64):
        h = mx.sym.Variable("data")
        for i in range(layers):
            h = mx.sym.FullyConnected(h, num_hidden=width, name=f"fc{i}")
            h = mx.sym.Activation(h, act_type="relu")
        h = mx.sym.FullyConnected(h, num_hidden=10, name="fc_out")
        return mx.sym.SoftmaxOutput(h, name="softmax")

    log(f"[scan] deep-MLP fit @ bs32, K={K}, {steps} steps/epoch")
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(steps * 32, 64).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 10, steps * 32).astype(np.float32))

    def fit_epoch_ms(scan_k):
        os.environ["MXNET_FUSED_STEP"] = "1"
        os.environ["MXNET_SCAN_STEPS"] = str(scan_k)
        it = mxio.NDArrayIter(x, y, batch_size=32,
                              label_name="softmax_label")
        mod = mx.mod.Module(deep_mlp(), context=mx.cpu())
        opt = {"learning_rate": 0.01, "momentum": 0.9}
        mod.fit(it, num_epoch=1, optimizer="sgd", optimizer_params=opt,
                initializer=mx.initializer.Xavier())  # warm: compiles
        it.reset()
        prof.reset_dispatch_counts()
        t0 = time.perf_counter()
        mod.fit(it, num_epoch=1, optimizer="sgd", optimizer_params=opt)
        ms = (time.perf_counter() - t0) / steps * 1e3
        return ms, prof.dispatch_counts().get("total", 0) / steps

    scan_ms, scan_disp = fit_epoch_ms(K)
    seq_ms, seq_disp = fit_epoch_ms(1)
    budget = (1 + 0.25) / K
    fused_ref = fused_step_ms if fused_step_ms else seq_ms
    return {
        "scan_dispatch": {
            "metric": "scan_dispatches_per_step",
            "value": round(scan_disp, 4),
            "budget": round(budget, 4),
            "gate_pass": bool(scan_disp <= budget),
            "k": K,
            "sequential_dispatches_per_step": round(seq_disp, 2),
            "note": "Module.fit dispatches/step with MXNET_SCAN_STEPS "
                    "windows (one donated lax.scan per K steps)",
        },
        "train_step_scan": {
            "metric": f"train_step_ms_scan_k{K}",
            "value": round(scan_ms, 3),
            "sequential_fused_ms": round(seq_ms, 3),
            "fused_per_step_ref_ms": round(fused_ref, 3),
            "improvement_vs_fused": round(1.0 - scan_ms / fused_ref, 3)
            if fused_ref else None,
            "bar": "amortized >= 25% below the per-step fused figure",
            "model": "mlp24x64 (dispatch-bound)",
            "steps": steps,
        },
    }


_MODEL_CACHE = {}


def build_train_step(batch, dtype="bfloat16", use_remat=False,
                     loss_mode="fused"):
    """Build the benchmarked ResNet-50 train step (fwd+bwd+SGD-momentum).

    Shared by main() and tools/hlo_flops.py so the FLOP forensics always
    analyze the exact program being timed.  Returns
    ``(step_fn, (tparams, aparams), n_params)`` with the param tuples as
    host arrays; callers place them on their own device and create the
    momentum buffers (``jnp.zeros_like``) themselves.

    The functionalized model is batch-polymorphic, so it is built ONCE
    per dtype and cached — multi-batch-size runs (bs32/128/256) pay the
    host-side functionalize + init exactly once.

    loss_mode: "fused" routes softmax-CE through the Pallas kernel
    (mxnet_tpu.ops.pallas_softmax_ce, XLA fallback built in);
    "onehot" keeps the r2-r4 one-hot formulation for A/B.
    """
    import numpy as np
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel.spmd import (functionalize, merge_params,
                                         host_cpu_scope, remat_wrap)
    from mxnet_tpu.ops import registry as _registry
    from mxnet_tpu.ops.pallas_softmax_ce import fused_softmax_ce
    from mxnet_tpu import autograd as _ag
    from mxnet_tpu import amp

    if dtype == "bfloat16":
        # framework AMP: MXU ops compute in bf16, fp32 master weights
        # and norm statistics — the recipe lives in mxnet_tpu.amp
        amp.init(target_dtype="bfloat16")

    if dtype in _MODEL_CACHE:
        apply_fn, param_arrays, train_idx, aux_list = _MODEL_CACHE[dtype]
    else:
        with host_cpu_scope(), jax.disable_jit():
            net = vision.resnet50_v1()
            net.initialize(mx.initializer.Xavier())
            x_ex = mx.nd.zeros((batch, 3, 224, 224))
            fb = functionalize(net, x_ex)
            apply_fn, param_arrays, _names = fb
            x_sds = jax.ShapeDtypeStruct((batch, 3, 224, 224),
                                         np.dtype(np.float32))
            train_idx, aux_list = fb.split_train_aux((x_sds,))
        _MODEL_CACHE[dtype] = (apply_fn, param_arrays, train_idx, aux_list)

    sgd_attrs = {"lr": 0.01, "wd": 1e-4, "momentum": 0.9,
                 "rescale_grad": 1.0}
    sgd_mom = _registry.get("sgd_mom_update").fcompute

    def step(key, tparams, aparams, moms, x, y):
        def fwd(tps, x_):
            ps = merge_params(train_idx, aux_list, tps, aparams)
            with _ag.train_mode():
                outs, mutated = apply_fn(key, ps, (x_,))
            return outs[0], mutated

        if use_remat:
            fwd = remat_wrap(fwd)

        def loss_fn(tps):
            logits, mutated = fwd(tps, x)
            logits = logits.astype(jnp.float32)
            if loss_mode == "fused":
                loss = fused_softmax_ce(logits, y.astype(jnp.int32)).mean()
            else:
                logp = jax.nn.log_softmax(logits, axis=-1)
                oh = jax.nn.one_hot(y.astype(jnp.int32), 1000)
                loss = -(oh * logp).sum(axis=-1).mean()
            return loss, mutated

        (loss, mutated), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(tparams)
        new_p, new_m = [], []
        for w, g, m in zip(tparams, grads, moms):
            nw, nm = sgd_mom(sgd_attrs, w, g.astype(w.dtype), m)
            new_p.append(nw)
            new_m.append(nm)
        new_aux = tuple(mu.astype(a.dtype) for mu, a in zip(mutated, aparams))
        return tuple(new_p), new_aux, tuple(new_m), loss

    tparams = tuple(param_arrays[i] for i in train_idx)
    aparams = tuple(param_arrays[i] for i in aux_list)
    n_params = sum(int(np.prod(a.shape)) for a in param_arrays)
    return step, (tparams, aparams), n_params


def main():
    budget = float(os.environ.get("BENCH_TIME_BUDGET", 1200))
    batch = int(os.environ.get("BENCH_BATCH", 32))
    batch2 = int(os.environ.get("BENCH_BATCH2", 128))
    batch3 = int(os.environ.get("BENCH_BATCH3", 256))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    k_steps = max(2, int(os.environ.get("BENCH_K", 8)))

    result = {
        "metric": f"resnet50_train_img_per_sec_bs{batch}",
        "value": 0.0,
        "unit": "img/s",
        # baseline is bs32 fp32 on 1x V100; only a like-for-like batch is
        # a meaningful ratio
        "vs_baseline": 0.0,
    }

    try:
        # --- dispatch phases (CPU-only) ---------------------------------
        # Run FIRST, before any TPU relay contact: these phases measure
        # the fused-train-step dispatch win on the jax CPU backend, so a
        # dead relay (which hard-exits the process via the init watchdog
        # below) can never starve them — the perf trajectory keeps a
        # locally measurable signal either way.
        from mxnet_tpu import config as _cfg0
        if _cfg0.get("BENCH_DISPATCH"):
            _prev_fused = os.environ.get("MXNET_FUSED_STEP")
            try:
                result.update(measure_train_dispatch())
                d, t = result["dispatch"], result["train_step"]
                log(f"[dispatch] fused {d['value']}/step vs loop "
                    f"{d['unfused_dispatches_per_step']}/step; "
                    f"step {t['value']}ms vs {t['unfused_ms']}ms "
                    f"({t['improvement_vs_loop']:.0%} faster)")
            except Exception as e:
                log(f"dispatch phase failed: {type(e).__name__}: {e}")
                result["dispatch"] = {
                    "metric": "resnet50_step_dispatches",
                    "error": f"{type(e).__name__}: {e}"}
            finally:
                if _prev_fused is None:
                    os.environ.pop("MXNET_FUSED_STEP", None)
                else:
                    os.environ["MXNET_FUSED_STEP"] = _prev_fused

        if _cfg0.get("BENCH_SCAN"):
            _prev = {k: os.environ.get(k)
                     for k in ("MXNET_FUSED_STEP", "MXNET_SCAN_STEPS")}
            try:
                fused_ref = (result.get("train_step") or {}).get("value")
                result.update(measure_scan_dispatch(fused_ref))
                sd, st = result["scan_dispatch"], result["train_step_scan"]
                log(f"[scan] {sd['value']}/step dispatches at K={sd['k']} "
                    f"(budget {sd['budget']}); step {st['value']}ms vs "
                    f"fused {st['fused_per_step_ref_ms']}ms "
                    f"({st['improvement_vs_fused']:.0%} faster)")
            except Exception as e:
                log(f"scan phase failed: {type(e).__name__}: {e}")
                result["scan_dispatch"] = {
                    "metric": "scan_dispatches_per_step",
                    "error": f"{type(e).__name__}: {e}"}
            finally:
                for k, v in _prev.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v

        if _cfg0.get("BENCH_MULTICHIP"):
            try:
                result.update(measure_multichip())
                md, mc = result["multichip_dispatch"], \
                    result["multichip_comm"]
                log(f"[multichip] {md['value']}/step dispatches at "
                    f"K={md['k']} on {md['mesh']} (budget "
                    f"{md['budget']}, "
                    f"{'PASS' if md['gate_pass'] else 'FAIL'}); comm "
                    f"blocking {mc['value']}% (budget "
                    f"{mc['budget_pct']}%, "
                    f"{'PASS' if mc['gate_pass'] else 'FAIL'})")
            except Exception as e:
                log(f"multichip phase failed: {type(e).__name__}: {e}")
                result["multichip_dispatch"] = {
                    "metric": "multichip_dispatches_per_step",
                    "error": f"{type(e).__name__}: {e}"}

        if _cfg0.get("BENCH_MULTIHOST"):
            try:
                result.update(measure_multihost())
                mh, mr, mx_ = (result["multihost_dispatch"],
                               result["multihost_recovery"],
                               result["multihost_compression"])
                log(f"[multihost] {mh['value']}/step dispatches/proc "
                    f"at K={mh['k']} world={mh['world']} (budget "
                    f"{mh['budget']}, "
                    f"{'PASS' if mh['gate_pass'] else 'FAIL'}); "
                    f"recovery {mr['value']}s (budget {mr['budget_s']}s, "
                    f"{'PASS' if mr['gate_pass'] else 'FAIL'}); "
                    f"2bit wire shrink {mx_['value']}x (bar "
                    f"{mx_['budget_x']}x, "
                    f"{'PASS' if mx_['gate_pass'] else 'FAIL'})")
            except Exception as e:
                log(f"multihost phase failed: {type(e).__name__}: {e}")
                result["multihost_dispatch"] = {
                    "metric": "multihost_dispatches_per_step",
                    "error": f"{type(e).__name__}: {e}"}

        if _cfg0.get("BENCH_FLEET"):
            try:
                result.update(measure_fleet())
                fm, fr, fs, fx = (result["fleet_merge"],
                                  result["fleet_rollup"],
                                  result["fleet_scrape"],
                                  result["fleet_sublinear"])
                log(f"[fleet] merge p99 {fm['value']}ms (budget "
                    f"{fm['budget_ms']}ms, "
                    f"{'PASS' if fm['gate_pass'] else 'FAIL'}); rollup "
                    f"{fr['value']}ms (budget {fr['budget_ms']}ms, "
                    f"{'PASS' if fr['gate_pass'] else 'FAIL'}); scrape "
                    f"{fs['value']}KiB (budget {fs['budget_kib']}KiB, "
                    f"{'PASS' if fs['gate_pass'] else 'FAIL'}); "
                    f"sublinear {fx['value']}x vs rank="
                    f"{fx['ref_ranks']} (bar {fx['budget_x']}x, "
                    f"{'PASS' if fx['gate_pass'] else 'FAIL'})")
            except Exception as e:
                log(f"fleet phase failed: {type(e).__name__}: {e}")
                result["fleet_merge"] = {
                    "metric": "fleet_merge_p99_ms",
                    "error": f"{type(e).__name__}: {e}"}

        if _cfg0.get("BENCH_COLD_START"):
            try:
                result.update(measure_cold_start())
                cs = result["cold_start"]
                log(f"[cold_start] warm {cs['value']}ms vs cold "
                    f"{cs['cold_first_request_ms']}ms "
                    f"({cs['speedup_warm_vs_cold']}x, bar "
                    f"{cs['bar_speedup']}x, "
                    f"{'PASS' if cs['passed'] else 'FAIL'})")
            except Exception as e:
                log(f"cold_start phase failed: {type(e).__name__}: {e}")
                result["cold_start"] = {
                    "metric": "cold_start_first_request_ms",
                    "error": f"{type(e).__name__}: {e}"}

        if _cfg0.get("BENCH_TELEMETRY"):
            try:
                result.update(measure_telemetry_overhead())
                log(f"[telemetry] disabled span "
                    f"{result['telemetry']['value']} ns "
                    f"(budget {result['telemetry']['budget_ns']})")
            except Exception as e:
                log(f"telemetry phase failed: {type(e).__name__}: {e}")
                result["telemetry"] = {
                    "metric": "telemetry_disabled_span_ns",
                    "error": f"{type(e).__name__}: {e}"}

        if _cfg0.get("BENCH_TRACE"):
            try:
                result.update(measure_trace_overhead())
                log(f"[trace] disabled trace/flight hook "
                    f"{result['trace']['value']} ns "
                    f"(budget {result['trace']['budget_ns']})")
            except Exception as e:
                log(f"trace phase failed: {type(e).__name__}: {e}")
                result["trace"] = {
                    "metric": "trace_disabled_overhead_ns",
                    "error": f"{type(e).__name__}: {e}"}

        if _cfg0.get("BENCH_ALERTS"):
            try:
                result.update(measure_alert_overhead())
                al, rs = result["alerts"], result["resource_sample"]
                log(f"[alerts] tick {al['value']} us "
                    f"(budget {al['budget_us']}), disabled "
                    f"{al['disabled_tick_ns']} ns (budget "
                    f"{al['disabled_budget_ns']}); host sample "
                    f"{rs['value']} us (budget {rs['budget_us']})")
            except Exception as e:
                log(f"alerts phase failed: {type(e).__name__}: {e}")
                result["alerts"] = {
                    "metric": "alert_tick_overhead_us",
                    "error": f"{type(e).__name__}: {e}"}

        if _cfg0.get("BENCH_NUMERICS"):
            try:
                result.update(measure_numerics_overhead())
                nm = result["numerics"]
                log(f"[numerics] armed K={nm['k']} overhead "
                    f"{nm['value']}% (budget {nm['budget_pct']}%), "
                    f"dispatches {nm['dispatches_per_step_armed']} vs "
                    f"{nm['dispatches_per_step_off']} off, disabled "
                    f"path {nm['disabled_ns']} ns (budget "
                    f"{nm['disabled_budget_ns']}), "
                    f"{'PASS' if nm['gate_pass'] else 'FAIL'}")
            except Exception as e:
                log(f"numerics phase failed: {type(e).__name__}: {e}")
                result["numerics"] = {
                    "metric": "numerics_overhead_pct",
                    "error": f"{type(e).__name__}: {e}"}

        if _cfg0.get("BENCH_DATA"):
            try:
                result.update(measure_data_pipeline())
                dp = result["data_pipeline"]
                log(f"[data] K={dp['k']} x{dp['workers']} workers: "
                    f"data_wait {dp['value']}% of wall (budget "
                    f"{dp['budget_pct']}%), step "
                    f"{dp['step_ms_pipelined']}ms vs serial "
                    f"{dp['step_ms_serial']}ms "
                    f"({dp['serial_ratio']}x), dispatches "
                    f"{dp['dispatches_per_step_pipelined']} vs "
                    f"{dp['dispatches_per_step_serial']} serial, "
                    f"{'PASS' if dp['gate_pass'] else 'FAIL'}")
            except Exception as e:
                log(f"data phase failed: {type(e).__name__}: {e}")
                result["data_pipeline"] = {
                    "metric": "data_wait_pct",
                    "error": f"{type(e).__name__}: {e}"}

        if _cfg0.get("BENCH_LINT"):
            try:
                result.update(measure_graftlint())
                gl = result["graftlint"]
                log(f"[graftlint] full tree {gl['value']}s (budget "
                    f"{gl['budget_s']}s, "
                    f"{'PASS' if gl['gate_pass'] else 'FAIL'}); "
                    f"slowest rules {gl['slowest_rules']}")
            except Exception as e:
                log(f"graftlint phase failed: {type(e).__name__}: {e}")
                result["graftlint"] = {
                    "metric": "graftlint_full_tree_s",
                    "error": f"{type(e).__name__}: {e}"}

        if _cfg0.get("BENCH_KERNELS"):
            try:
                result.update(measure_kernels())
                kt, kd = result["kernel_tuner"], result["kernel_device"]
                log(f"[kernels] tuner {kt['value']}s for {kt['tunes']} "
                    f"searches (budget {kt['budget_s']}s, "
                    f"{kt['retunes_on_reresolve']} re-tunes on "
                    f"re-resolve, "
                    f"{'PASS' if kt['gate_pass'] else 'FAIL'}); device "
                    f"latency {kd['status']}")
            except Exception as e:
                log(f"kernels phase failed: {type(e).__name__}: {e}")
                result["kernel_tuner"] = {
                    "metric": "kernel_tuner_overhead_s",
                    "error": f"{type(e).__name__}: {e}"}

        if _cfg0.get("BENCH_SERVE_SPIKE"):
            try:
                result.update(measure_serve_pool())
                ss, sp = result["serve_sustained"], result["serve_spike"]
                log(f"[serve_pool] sustained {ss['value']} img/s vs "
                    f"single {ss['single_batcher_img_per_sec']} "
                    f"({ss['ratio_vs_single']}x, bar {ss['bar_ratio']}x, "
                    f"{'PASS' if ss['passed'] else 'FAIL'}); spike p99 "
                    f"{sp['value']}ms vs steady {sp['steady_p99_ms']}ms "
                    f"({sp['ratio_vs_steady']}x, bar {sp['bar_ratio']}x, "
                    f"shed {sp['shed_spike']}, "
                    f"{'PASS' if sp['passed'] else 'FAIL'})")
            except Exception as e:
                log(f"serve_pool phase failed: {type(e).__name__}: {e}")
                result["serve_spike"] = {
                    "metric": "serve_spike_p99_ms",
                    "error": f"{type(e).__name__}: {e}"}

        if _cfg0.get("BENCH_GENERATE"):
            try:
                result.update(measure_generation())
                gt = result["generate_throughput"]
                gi = result["generate_intertoken"]
                log(f"[generate] {gt['value']} tok/s vs single "
                    f"{gt['single_session_tok_per_sec']} "
                    f"({gt['ratio_vs_single']}x, bar {gt['bar_ratio']}x), "
                    f"prefix hit rate {gt['prefix_hit_rate']} "
                    f"(bar {gt['prefix_hit_bar']}), p99 intertoken "
                    f"{gi['value']}ms, "
                    f"{'PASS' if gt['passed'] else 'FAIL'}")
            except Exception as e:
                log(f"generate phase failed: {type(e).__name__}: {e}")
                result["generate_throughput"] = {
                    "metric": "generate_tokens_per_sec",
                    "error": f"{type(e).__name__}: {e}"}

        if _cfg0.get("BENCH_CHAOS"):
            try:
                result.update(measure_degraded_p99())
                dg = result["degraded"]
                log(f"[chaos] degraded p99 {dg['value']}ms vs healthy "
                    f"{dg['healthy_p99_ms']}ms "
                    f"({dg['ratio_vs_healthy']}x, bar {dg['bar_ratio']}x, "
                    f"{'PASS' if dg['passed'] else 'FAIL'})")
            except Exception as e:
                log(f"chaos phase failed: {type(e).__name__}: {e}")
                result["degraded"] = {
                    "metric": "degraded_p99_ms",
                    "error": f"{type(e).__name__}: {e}"}

        # persistent compilation cache: reruns skip the big compile
        cache_dir = os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_cache"))
        os.makedirs(cache_dir, exist_ok=True)

        # watchdog: a dead TPU relay can hang device init in a sleep-retry
        # loop for hours (observed r03). If the device list hasn't
        # resolved within BENCH_INIT_TIMEOUT, emit the JSON error line and
        # hard-exit — an immediate structured failure beats the driver's
        # rc=124 after its full timeout.
        import threading
        init_done = threading.Event()
        init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", 300))

        def _watchdog():
            if not init_done.wait(init_timeout):
                emit({**result,
                      "error": f"device init exceeded {init_timeout:.0f}s "
                               "(TPU relay unreachable)",
                      "note": "relay unreachable at bench time; the last "
                              "self-measured numbers and the corrected-"
                              "accounting MFU expectations are tabulated "
                              "in docs/perf_notes.md"})
                os._exit(3)

        if init_timeout > 0:  # 0 disables, matching the other BENCH_* knobs
            threading.Thread(target=_watchdog, daemon=True).start()

        log("importing jax")
        import numpy as np
        import jax
        import jax.numpy as jnp
        from jax import lax
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception:
            pass

        from mxnet_tpu import random as _random

        # bounded retry inside the init window: a relay FLAP surfaces as a
        # fast exception from device enumeration — re-dial with backoff
        # until the deadline instead of failing one-shot. (A relay HANG is
        # the watchdog's job above.)
        attempt = 0
        while True:
            try:
                devs = jax.devices()
                break
            except Exception as e:
                attempt += 1
                left = init_timeout - (time.perf_counter() - T_START)
                if (init_timeout > 0 and left < 20) or attempt >= 8:
                    raise  # bounded even with the watchdog disabled
                wait = min(15, 2 ** attempt)
                log(f"device init attempt {attempt} failed "
                    f"({type(e).__name__}: {e}); retrying in {wait}s "
                    f"({left:.0f}s left)")
                time.sleep(wait)
        init_done.set()  # relay answered: disarm the watchdog
        dev = devs[0]
        kind = getattr(dev, "device_kind", "?")
        log(f"devices: {len(devs)}x {dev.platform}/{kind}")
        result["n_devices"] = len(devs)
        result["device_kind"] = str(kind)

        compute_dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32

        # remat parity hook (MXNET_BACKWARD_DO_MIRROR). Default OFF: honest
        # timing shows no activation-spill cliff at these sizes and remat
        # costs ~20% real step time at bs128 (measured r4).
        remat_from = int(os.environ.get("BENCH_REMAT_FROM_BS", 0))
        loss_mode = os.environ.get("BENCH_LOSS", "fused")

        def measure(bs):
            """Compile + time the train step at batch size bs.

            One program: a dynamic-trip-count fori_loop over the train
            step, returning only the final scalar loss.  Device step time
            = (T(2K) - T(K)) / K with transfer sync (see module docstring
            for why nothing weaker is trustworthy on this relay).
            """
            log(f"[bs{bs}] building ResNet-50 on host CPU "
                "(no device compiles)")
            step_fn, (tparams_h, aparams_h), n_params = build_train_step(
                bs, dtype, use_remat=(bs >= remat_from > 0),
                loss_mode=loss_mode)
            log(f"[bs{bs}] functionalized ({n_params / 1e6:.1f}M params)")
            tparams = tuple(jax.device_put(p, dev) for p in tparams_h)
            aparams = tuple(jax.device_put(p, dev) for p in aparams_h)
            moms = tuple(jnp.zeros_like(p) for p in tparams)
            x = jax.device_put(
                np.random.randn(bs, 3, 224, 224).astype(np.float32), dev
            ).astype(compute_dtype)
            y = jax.device_put(
                np.random.randint(0, 1000, (bs,)).astype(np.float32), dev)
            key = _random.next_key()

            def multi(k, salt, key, tp, ap, mm, x, y):
                # salt: per-call-unique live input (anti result-caching,
                # see calibrate_peak); folded into x at 1e-30 scale
                x = x + (salt * 1e-30).astype(x.dtype)
                def body(_, carry):
                    tp_, ap_, mm2, _l = carry
                    return step_fn(key, tp_, ap_, mm2, x, y)
                init = (tp, ap, mm, jnp.zeros((), jnp.float32))
                return lax.fori_loop(0, k, body, init)[3]

            log(f"[bs{bs}] lowering + compiling dynamic-K train loop"
                f"{' (remat)' if bs >= remat_from > 0 else ''}")
            t0 = time.perf_counter()
            compiled = jax.jit(multi).lower(
                jnp.int32(1), jnp.float32(0), key, tparams, aparams, moms,
                x, y).compile()
            compile_s = time.perf_counter() - t0
            log(f"[bs{bs}] compiled in {compile_s:.1f}s")

            ca_flops = None
            try:
                ca = compiled.cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0]
                ca_flops = float(ca.get("flops", 0.0)) or None
            except Exception:
                pass

            loss = float(compiled(jnp.int32(2), jnp.float32(1), key,
                                  tparams, aparams, moms, x, y))
            calls = [1]

            def timed(k, tries=3):
                ts = []
                for _ in range(tries):
                    calls[0] += 1
                    t0 = time.perf_counter()
                    nonlocal loss
                    loss = float(compiled(jnp.int32(k), jnp.float32(calls[0]),
                                          key, tparams, aparams, moms, x, y))
                    ts.append(time.perf_counter() - t0)
                    if time.perf_counter() - T_START > budget * 0.9:
                        break
                return min(ts)

            t1 = timed(k_steps)
            t2 = timed(2 * k_steps)
            per_step = (t2 - t1) / k_steps
            if per_step <= 0:
                raise RuntimeError(
                    f"differenced step time non-positive: T({k_steps})="
                    f"{t1:.4f}s T({2 * k_steps})={t2:.4f}s — relay timing "
                    "anomaly")
            fixed_ms = (t1 - per_step * k_steps) * 1e3
            return {
                "batch": bs,
                "img_s": bs / per_step,
                "step_ms": per_step * 1e3,
                # the relay's fixed per-dispatch cost, cancelled out of
                # step_ms by differencing; reported for transparency
                "dispatch_overhead_ms": round(fixed_ms, 1),
                "timed_steps": 3 * k_steps,
                "k": k_steps,
                "compile_seconds": round(compile_s, 1),
                "flops_analytic": ANALYTIC_FWD_FLOPS_PER_IMG * 3 * bs,
                "flops_cost_analysis": ca_flops,
                "final_loss": loss,
                "sync": "transfer (block_until_ready is a no-op on the "
                        "axon relay — measured r4)",
            }

        m1 = measure(batch)
        log(f"[bs{batch}] {m1['img_s']:.1f} img/s, "
            f"step {m1['step_ms']:.2f}ms "
            f"(dispatch overhead {m1['dispatch_overhead_ms']}ms, "
            f"cancelled)")

        # --- peak calibration -------------------------------------------
        table_peak, table_kind = peak_flops_for(str(kind))
        calibrated_peak, calib_info = None, None
        try:
            log("calibrating peak FLOP/s (chained bf16 matmuls)")
            calibrated_peak, calib_info = calibrate_peak(dev)
            log(f"calibrated peak: {calibrated_peak / 1e12:.1f} TFLOP/s "
                f"(table {table_kind}: {table_peak / 1e12:.0f})")
        except Exception as e:
            log(f"calibration failed: {type(e).__name__}: {e}")

        # Conservative headline denominator: whichever evidence says the
        # chip is FASTER (a mis-reported device_kind is exactly what
        # calibration catches). BOTH ratios are reported (r03 verdict) —
        # mfu_table may be deflated if the table kind overstates the relay
        # device; mfu_calibrated may be inflated if calibration is bound
        # by anything but the MXU.
        peak_used = max([p for p in (table_peak, calibrated_peak) if p])
        if calibrated_peak and calibrated_peak < 0.3 * table_peak:
            # the shared TPU pool throttles hard sometimes (observed r4:
            # the SAME calibration measured 190 TF/s and 7.2 TF/s hours
            # apart). When the model-independent matmul peak itself is
            # far below table, absolute img/s is about the pool, not the
            # framework — mfu_calibrated is the meaningful ratio then.
            result["throttled"] = {
                "calibrated_over_table": round(
                    calibrated_peak / table_peak, 3),
                "note": "chip throttled/contended during this run; "
                        "prefer mfu_calibrated over value/mfu_table",
            }

        def attach_mfu(m, res):
            achieved = m["flops_analytic"] / (m["step_ms"] / 1e3)
            mfu = achieved / peak_used
            res["step_ms"] = round(m["step_ms"], 3)
            res["dispatch_overhead_ms"] = m["dispatch_overhead_ms"]
            res["mfu_table"] = round(achieved / table_peak, 4)
            if calibrated_peak:
                res["mfu_calibrated"] = round(achieved / calibrated_peak, 4)
            if 0 < mfu <= 1.0:
                res["mfu"] = round(mfu, 4)
            else:
                res["anomaly"] = {
                    "reason": "computed MFU > 1.0 — physically impossible",
                    "mfu_raw": round(mfu, 4),
                    "achieved_flops_per_sec": achieved,
                    "peak_used": peak_used,
                }
            return mfu

        result.update({
            "value": round(m1["img_s"], 2),
            "vs_baseline": (round(m1["img_s"] / BASELINE_IMG_S, 3)
                            if batch == 32 else None),
            "compile_seconds": m1["compile_seconds"],
            "timed_steps": m1["timed_steps"],
            "batch": batch,
            "dtype": dtype,
            "loss": loss_mode,
            "final_loss": m1["final_loss"],
            "flops_per_step_analytic": m1["flops_analytic"],
            "flops_per_step_cost_analysis": m1["flops_cost_analysis"],
            "peak_flops_table": f"{table_kind}:{table_peak:.3g}",
            "peak_flops_calibrated": (
                round(calibrated_peak, 0) if calibrated_peak else None),
            "calibration": calib_info,
            "sync": m1["sync"],
        })
        attach_mfu(m1, result)

        # --- extra MFU points (bs128 per r3 verdict, bs256 per r4) -------
        for extra_bs in (batch2, batch3):
            if not extra_bs or extra_bs == batch:
                continue
            remaining = budget - (time.perf_counter() - T_START)
            if remaining <= 240:
                log(f"skipping bs{extra_bs}: only {remaining:.0f}s left")
                continue
            try:
                m2 = measure(extra_bs)
                log(f"[bs{extra_bs}] {m2['img_s']:.1f} img/s, "
                    f"step {m2['step_ms']:.2f}ms")
                sub = {"img_s": round(m2["img_s"], 2),
                       "compile_seconds": m2["compile_seconds"],
                       "final_loss": m2["final_loss"]}
                attach_mfu(m2, sub)
                result[f"bs{extra_bs}"] = sub
            except Exception as e:
                log(f"bs{extra_bs} phase failed: {type(e).__name__}: {e}")
                result[f"bs{extra_bs}"] = {"error": str(e)}

        # --- serving throughput (resnet18 via the DynamicBatcher) -------
        from mxnet_tpu import config as _mxcfg
        if _mxcfg.get("BENCH_SERVE"):
            remaining = budget - (time.perf_counter() - T_START)
            if remaining <= 180:
                log(f"skipping serving phase: only {remaining:.0f}s left")
            else:
                try:
                    srv = measure_serving()
                    result["serving"] = srv
                    log(f"[serving] {srv['value']} img/s "
                        f"(p99 {srv['p99_ms']}ms, shed {srv['shed']})")
                except Exception as e:
                    log(f"serving phase failed: {type(e).__name__}: {e}")
                    result["serving"] = {
                        "metric": "resnet18_serve_img_per_sec",
                        "error": f"{type(e).__name__}: {e}"}

        # --- checkpoint time-to-safe (save-blocking / restore) ----------
        if _mxcfg.get("BENCH_CKPT"):
            remaining = budget - (time.perf_counter() - T_START)
            if remaining <= 60:
                log(f"skipping checkpoint phase: only {remaining:.0f}s left")
            else:
                try:
                    ck = measure_checkpoint()
                    result["checkpoint"] = ck
                    log(f"[checkpoint] save blocks {ck['value']}ms async vs "
                        f"{ck['ckpt_save_sync_ms']}ms sync "
                        f"({ck['blocking_fraction']:.0%}), restore "
                        f"{ck['ckpt_restore_s']}s")
                except Exception as e:
                    log(f"checkpoint phase failed: {type(e).__name__}: {e}")
                    result["checkpoint"] = {
                        "metric": "ckpt_save_blocking_ms",
                        "error": f"{type(e).__name__}: {e}"}
    except Exception as e:  # always emit the JSON line
        import traceback
        traceback.print_exc(file=sys.stderr)
        result["error"] = f"{type(e).__name__}: {e}"
    # disarm the init watchdog on every exit path: a failure surfacing
    # near the deadline must not race this emit into two JSON lines
    try:
        init_done.set()
    except NameError:
        pass
    emit(result)


if __name__ == "__main__":
    main()
