#!/usr/bin/env python
"""Headline benchmark: ResNet-50 ImageNet-shape training throughput.

Baseline (BASELINE.md / docs/faq/perf.md:231-243 of the reference):
ResNet-50 train @ bs32 fp32 on 1x V100 = 298.51 img/s.

This bench runs the SAME model/batch on one TPU chip with the TPU-idiomatic
recipe: whole train step (fwd+bwd+SGD-momentum update) compiled to one XLA
program, bf16 compute with fp32 master weights & BatchNorm statistics.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import os
import sys
import time

BASELINE_IMG_S = 298.51
BATCH = 32


def main():
    import numpy as np
    import jax
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel.spmd import functionalize
    from mxnet_tpu.ops import registry as _registry
    from mxnet_tpu import random as _random

    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    n_warm = int(os.environ.get("BENCH_WARMUP", 3))
    n_iter = int(os.environ.get("BENCH_ITERS", 20))

    net = vision.resnet50_v1()
    net.initialize(mx.initializer.Xavier())

    x_ex = mx.nd.zeros((BATCH, 3, 224, 224))
    y_np = np.random.randint(0, 1000, (BATCH,)).astype(np.float32)

    apply_fn, param_arrays, names = functionalize(net, x_ex)
    # fp32 master weights; bf16 compute for conv/matmul params (
    # BatchNorm/bias vectors stay fp32 — standard TPU mixed precision)
    compute_dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32

    momentum = 0.9
    lr = 0.1
    sgd_attrs = {"lr": lr, "wd": 1e-4, "momentum": momentum,
                 "rescale_grad": 1.0}
    sgd_mom = _registry.get("sgd_mom_update").fcompute

    def cast_params(params):
        return tuple(
            p.astype(compute_dtype) if p.ndim > 1 else p for p in params)

    def step(key, params, moms, x, y):
        def loss_fn(ps):
            outs, mutated = apply_fn(key, cast_params(ps), (x,))
            logits = outs[0].astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            oh = jax.nn.one_hot(y.astype(jnp.int32), 1000)
            return -(oh * logp).sum(axis=-1).mean(), mutated

        (loss, mutated), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params)
        new_params, new_moms = [], []
        for w, g, m in zip(params, grads, moms):
            nw, nm = sgd_mom(sgd_attrs, w, g.astype(w.dtype), m)
            new_params.append(nw)
            new_moms.append(nm)
        return tuple(new_params), tuple(new_moms), loss

    step_jit = jax.jit(step, donate_argnums=(1, 2))

    params = tuple(jnp.asarray(a) for a in param_arrays)
    moms = tuple(jnp.zeros_like(p) for p in params)
    x = jnp.asarray(np.random.randn(BATCH, 3, 224, 224).astype(np.float32)
                    ).astype(compute_dtype)
    y = jnp.asarray(y_np)

    key = _random.next_key()
    for _ in range(n_warm):
        params, moms, loss = step_jit(key, params, moms, x, y)
    loss.block_until_ready()

    t0 = time.perf_counter()
    for _ in range(n_iter):
        params, moms, loss = step_jit(key, params, moms, x, y)
    loss.block_until_ready()
    dt = time.perf_counter() - t0

    img_s = BATCH * n_iter / dt
    print(json.dumps({
        "metric": "resnet50_train_img_per_sec_bs32",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
