#!/usr/bin/env python
"""Validate the three Pallas kernels ON THE REAL CHIP (VERDICT r4 item 2).

Per kernel (LayerNorm, flash attention, softmax-CE): compile with
interpret=False on the TPU, assert numerics against the XLA fallback, and
time both with the transfer-sync differencing methodology bench.py
established (block_until_ready is NOT a barrier on the axon relay; only a
device->host transfer is, and the fixed relay roundtrip is cancelled by
the (T(2R)-T(R))/R quotient).

Writes docs/tpu_kernel_table.json and prints a markdown table.  Exits
fast with a structured error when the relay is down — run it at every
relay-up window.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _relay_util import (T0, arm_watchdog, cpu_only_backend,
                         differenced_time, finish)
from _relay_util import log as _log

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "docs", "tpu_kernel_table.json")


def log(m):
    _log("kcheck", m)




def _record(shape, err, tol, time_pallas, time_xla):
    """Numerics verdict first; timing reported separately so a timing
    anomaly never masks (or fabricates) a numerics result."""
    rec = {"shape": shape, "max_abs_err": err,
           "numerics_ok": bool(err < tol)}
    tp, ap = time_pallas()
    tx, ax = time_xla()
    if ap or ax:
        rec["timing_anomaly"] = {"pallas": ap, "xla": ax}
    if tp and tx:
        rec["pallas_us"] = tp * 1e6
        rec["xla_us"] = tx * 1e6
        rec["speedup"] = tx / tp
    return rec


def main():
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(os.path.dirname(OUT), "..",
                                       ".jax_cache"))
    result = {"kernels": {}, "device": None}
    interp = os.environ.get("KCHECK_INTERPRET", "0") == "1"
    out_path = OUT if not interp else OUT.replace(".json", ".dryrun.json")
    result["dry_run"] = interp

    import numpy as np
    if interp:
        jax = cpu_only_backend()  # dry run: never dial the relay
        import jax.numpy as jnp
        dev = jax.devices("cpu")[0]
    else:
        import jax
        import jax.numpy as jnp
        timeout = float(os.environ.get("KCHECK_INIT_TIMEOUT", 300))
        disarm = arm_watchdog(timeout, {"error": "TPU relay unreachable"})
        devs = jax.devices()
        disarm()
        dev = devs[0]
        if dev.platform == "cpu":
            print(json.dumps({"error": "no TPU device (cpu backend); "
                              "set KCHECK_INTERPRET=1 for a dry run"}))
            finish(1)
        arm_watchdog(float(os.environ.get("KCHECK_BUDGET", 1800)),
                     {"error": "kernel check wedged", "partial": OUT})
    result["device"] = str(getattr(dev, "device_kind", dev))
    log(f"device: {result['device']}")
    rng = np.random.RandomState(0)
    reps = int(os.environ.get("KCHECK_REPS", 20))
    # interpret-mode dry runs shrink the shapes: the pallas interpreter is
    # orders of magnitude slower than the compiled kernel
    small = interp

    # ---- LayerNorm -------------------------------------------------------
    from mxnet_tpu.ops import pallas_norm as pn
    n, d = (256, 128) if small else (4096, 1024)
    x = jax.device_put(rng.randn(n, d).astype(np.float32), dev)
    g = jax.device_put(rng.rand(d).astype(np.float32) + 0.5, dev)
    b = jax.device_put(rng.randn(d).astype(np.float32), dev)

    def ln_pallas(x2, g2, b2):
        return pn._ln_fwd(x2, g2, b2, eps=1e-5,
                          block_rows=pn._pick_block_rows(x2.shape[0]),
                          interpret=interp)[0]

    def ln_xla(x2, g2, b2):
        mu = x2.mean(-1, keepdims=True)
        var = ((x2 - mu) ** 2).mean(-1, keepdims=True)
        return (x2 - mu) * jax.lax.rsqrt(var + 1e-5) * g2 + b2

    try:
        got = np.asarray(jax.jit(ln_pallas)(x, g, b))
        want = np.asarray(jax.jit(ln_xla)(x, g, b))
        err = float(np.abs(got - want).max())
        result["kernels"]["layer_norm"] = _record(
            [n, d], err, 1e-4,
            lambda: differenced_time(lambda c, g2, b2: ln_pallas(c, g2, b2),
                                (x, g, b), reps),
            lambda: differenced_time(lambda c, g2, b2: ln_xla(c, g2, b2),
                                (x, g, b), reps))
        log(f"layer_norm {result['kernels']['layer_norm']}")
    except Exception as e:
        result["kernels"]["layer_norm"] = {"error": f"{type(e).__name__}: {e}"}

    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)

    # ---- flash attention -------------------------------------------------
    from mxnet_tpu.ops import pallas_attention as pa
    B, H, S, D = (1, 2, 256, 32) if small else (4, 8, 1024, 64)
    q = jax.device_put(rng.randn(B, H, S, D).astype(np.float32) * .3, dev)
    k = jax.device_put(rng.randn(B, H, S, D).astype(np.float32) * .3, dev)
    v = jax.device_put(rng.randn(B, H, S, D).astype(np.float32) * .3, dev)

    def fa_pallas(qq, kk, vv):
        return pa._flash_fwd(qq, kk, vv, causal=True, sm_scale=D ** -0.5,
                             block_q=128, block_k=128, interpret=interp)[0]

    def fa_xla(qq, kk, vv):
        return pa._reference_attention(qq, kk, vv, True, D ** -0.5)

    try:
        got = np.asarray(jax.jit(fa_pallas)(q, k, v))
        want = np.asarray(jax.jit(fa_xla)(q, k, v))
        err = float(np.abs(got - want).max())
        result["kernels"]["flash_attention"] = _record(
            [B, H, S, D], err, 5e-3,
            lambda: differenced_time(lambda c, kk, vv: fa_pallas(c, kk, vv),
                                (q, k, v), reps),
            lambda: differenced_time(lambda c, kk, vv: fa_xla(c, kk, vv),
                                (q, k, v), reps))
        log(f"flash_attention {result['kernels']['flash_attention']}")
    except Exception as e:
        result["kernels"]["flash_attention"] = {
            "error": f"{type(e).__name__}: {e}"}

    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)

    # ---- softmax cross-entropy -------------------------------------------
    from mxnet_tpu.ops import pallas_softmax_ce as ps
    n, c = (256, 128) if small else (4096, 1000)
    logits = jax.device_put(rng.randn(n, c).astype(np.float32), dev)
    labels = jax.device_put(rng.randint(0, c, n).astype(np.int32), dev)

    def ce_pallas(lg, lb):
        return ps._smce_fwd(lg, lb, block_rows=ps._pick_block_rows(n),
                            interpret=interp)[0]

    def ce_xla(lg, lb):
        logp = jax.nn.log_softmax(lg, axis=-1)
        return -jnp.take_along_axis(logp, lb[:, None], axis=-1)[:, 0]

    try:
        got = np.asarray(jax.jit(ce_pallas)(logits, labels))
        want = np.asarray(jax.jit(ce_xla)(logits, labels))
        err = float(np.abs(got - want).max())
        # CE returns (n,) — fold it back to the (n, c) carry shape to keep
        # the timing chain sequential
        result["kernels"]["softmax_ce"] = _record(
            [n, c], err, 1e-4,
            lambda: differenced_time(
                lambda c2, lb: c2 + ce_pallas(c2, lb)[:, None] * 1e-30,
                (logits, labels), reps),
            lambda: differenced_time(
                lambda c2, lb: c2 + ce_xla(c2, lb)[:, None] * 1e-30,
                (logits, labels), reps))
        log(f"softmax_ce {result['kernels']['softmax_ce']}")
    except Exception as e:
        result["kernels"]["softmax_ce"] = {"error": f"{type(e).__name__}: {e}"}

    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print("| kernel | shape | max err | pallas | xla | speedup |")
    print("|---|---|---|---|---|---|")
    for nm, r in result["kernels"].items():
        if "error" in r:
            print(f"| {nm} | - | ERROR: {r['error']} | - | - | - |")
        elif "pallas_us" in r:
            print(f"| {nm} | {r['shape']} | {r['max_abs_err']:.2e} | "
                  f"{r['pallas_us']:.1f}us | {r['xla_us']:.1f}us | "
                  f"{r['speedup']:.2f}x |")
        else:
            print(f"| {nm} | {r['shape']} | {r['max_abs_err']:.2e} | "
                  f"timing anomaly: {r.get('timing_anomaly')} | - | - |")
    print(json.dumps({"metric": "tpu_kernel_check", "ok": all(
        r.get("numerics_ok") for r in result["kernels"].values())}))
    finish(0)


if __name__ == "__main__":
    main()
