"""Shared plumbing for the TPU-relay measurement tools.

The axon relay fails in two distinct ways and every tool must survive
both: a HANG at backend init (the relay accepts the dial and never
answers — only a watchdog thread + os._exit escapes it) and a FLAP
mid-run (individual device ops stall).  Tools also must end with
os._exit after flushing: a wedged relay client thread otherwise keeps
the interpreter alive after main() returns, eating one process per
relay-up window in automation.
"""
import json
import os
import sys
import threading
import time

T0 = time.perf_counter()


def log(tag, msg):
    print(f"[{tag} +{time.perf_counter() - T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


def arm_watchdog(seconds, payload):
    """Print ``payload`` as JSON and hard-exit unless disarm() is called
    within ``seconds``.  Returns the disarm callable; seconds <= 0 arms
    nothing."""
    done = threading.Event()
    if seconds > 0:
        def run():
            if not done.wait(seconds):
                print(json.dumps(payload), flush=True)
                os._exit(3)
        threading.Thread(target=run, daemon=True).start()
    return done.set


def finish(rc):
    """Flush and hard-exit: relay client threads must not keep a finished
    tool alive."""
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)


def cpu_only_backend():
    """Pin the CPU backend WITHOUT initializing the axon plugin (its init
    dials the relay and hangs when the tunnel is down)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax._src.xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")
    return jax
