"""Shared plumbing for the TPU-relay measurement tools.

The axon relay fails in two distinct ways and every tool must survive
both: a HANG at backend init (the relay accepts the dial and never
answers — only a watchdog thread + os._exit escapes it) and a FLAP
mid-run (individual device ops stall).  Tools also must end with
os._exit after flushing: a wedged relay client thread otherwise keeps
the interpreter alive after main() returns, eating one process per
relay-up window in automation.
"""
import json
import os
import sys
import threading
import time

T0 = time.perf_counter()


def log(tag, msg):
    print(f"[{tag} +{time.perf_counter() - T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


def arm_watchdog(seconds, payload):
    """Print ``payload`` as JSON and hard-exit unless disarm() is called
    within ``seconds``.  Returns the disarm callable; seconds <= 0 arms
    nothing."""
    done = threading.Event()
    if seconds > 0:
        def run():
            if not done.wait(seconds):
                print(json.dumps(payload), flush=True)
                os._exit(3)
        threading.Thread(target=run, daemon=True).start()
    return done.set


def finish(rc):
    """Flush and hard-exit: relay client threads must not keep a finished
    tool alive."""
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)


def cpu_only_backend():
    """Pin the CPU backend WITHOUT initializing the axon plugin (its init
    dials the relay and hangs when the tunnel is down)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax._src.xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")
    return jax


def differenced_time(fn, args, reps):
    """Per-call device time via the dynamic-R fori_loop differencing
    methodology ((T(2R) - T(R)) / R with a device->host transfer as the
    only trustworthy barrier on the relay).

    ``fn(carry, *rest)`` must return an array shaped like ``carry`` so
    iterations form a non-hoistable sequential chain.  Returns (seconds,
    anomaly_or_None): a non-positive difference is REPORTED, never
    silently clamped (a clamped 1e-9 published as data is how bogus
    sub-microsecond timings happen).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    def chain(r, salt, *a):
        a0 = a[0] + (salt * 1e-30).astype(a[0].dtype)

        def body(_, carry):
            return fn(carry, *a[1:]).astype(carry.dtype)

        out = lax.fori_loop(0, r, body, a0)
        return out.reshape(-1)[0].astype(jnp.float32)

    jitted = jax.jit(chain)
    float(jitted(2, jnp.float32(1), *args))  # compile + warm
    calls = [1]

    def t(r):
        best = None
        for _ in range(3):
            calls[0] += 1
            t0 = time.perf_counter()
            float(jitted(r, jnp.float32(calls[0]), *args))
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    t1, t2 = t(reps), t(2 * reps)
    per = (t2 - t1) / reps
    if per <= 0:
        return None, f"T(2R)={t2:.5f}s <= T(R)={t1:.5f}s"
    return per, None
