#!/usr/bin/env python
"""Attribute per-op FLOPs in the compiled ResNet-50 train step.

Round-5 perf forensics (VERDICT r4 item 1).  XLA ``cost_analysis``
reported ~715 GF/step at bs32 where bench.py's analytic model cost said
~371 GF — this tool was written to find the "2x waste".  What it found
(bs8 decomposition, CPU-compiled HLO; the op set is platform-independent
pre-layout):

  weight-shaped conv outputs (wgrad, 53 ops)          61.7 GF  = 1.00x fwd
  activation-shaped convs+dots (fwd + stride-1 dgrad) 115.4 GF ~ 1.9x fwd
  lhs-dilated convs (stride-2 dgrad, 6 ops)            24.7 GF  = 4x their fwd
  total                                               201.8 GF

i.e. the compiled step does EXACTLY the expected 3x-forward work — the
"2x" was bench.py's constant: 3.86e9 is gluon resnet50_v1's MAC count
(3.86 GMACs; torchvision's 4.09 is v1.5), and model FLOPs = 2*MACs =
7.72e9/img.  The only real overcount is the stride-2 backward-data
convs, which XLA charges (and executes) over the zero-inserted dilated
input: 4x their forward cost, ~18.5 GF/step = ~10% of the program.

FLOP convention per HLO op (matches xla::HloCostAnalysis):
  convolution: 2 * out_elements * (Cin/groups) * prod(kernel_spatial)
  dot:         2 * batch * M * N * K

Usage: JAX_PLATFORMS=cpu python tools/hlo_flops.py [--batch 32] [--json out]
       python tools/hlo_flops.py --from-hlo dump.hlo --batch 8
"""
import argparse
import collections
import json
import math
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def force_cpu_backend():
    """Drop the axon TPU plugin and pin the CPU backend (conftest.py recipe).

    The axon plugin registers at interpreter startup via sitecustomize;
    initializing it dials the TPU relay and HANGS when the tunnel is down.
    HLO op structure is platform-independent pre-layout, so CPU is fine.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax._src.xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")


def build_train_step(batch, dtype="bfloat16", loss_mode="fused"):
    """The EXACT bench.py train step (imported, not copied): returns
    (step_fn, example_args) ready to lower.  loss_mode defaults to
    "fused" — bench.py's default — so the analysis is of the program
    being timed; pass "onehot" to reproduce the r2-r4 loss for A/B."""
    import jax.numpy as jnp
    import bench
    from mxnet_tpu import random as _random

    step, (tparams_h, aparams_h), _n = bench.build_train_step(
        batch, dtype, use_remat=False, loss_mode=loss_mode)
    compute_dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    tparams = tuple(jnp.asarray(p) for p in tparams_h)
    aparams = tuple(jnp.asarray(p) for p in aparams_h)
    moms = tuple(jnp.zeros_like(p) for p in tparams)
    x = jnp.zeros((batch, 3, 224, 224), compute_dtype)
    y = jnp.zeros((batch,), jnp.float32)
    key = _random.next_key()
    return step, (key, tparams, aparams, moms, x, y)


_SHAPE_RE = re.compile(r"(bf16|f32|f16|s32|u32|s8|u8|pred|f64|s64)\[([\d,]*)\]")


def _parse_shape(text):
    m = _SHAPE_RE.search(text)
    if not m:
        return None, None
    dims = [int(d) for d in m.group(2).split(",") if d] if m.group(2) else []
    return m.group(1), dims


def analyze_hlo(hlo_text):
    """Bucket conv/dot FLOPs out of optimized HLO text.

    Two passes: first a symbol table name -> (dtype, dims) from every
    instruction's left-hand side (optimized dumps usually print operands
    as bare %names, so shapes must be resolved by definition), then the
    conv/dot walk using inline shapes when present and the table when not.
    """
    table = {}
    for line in hlo_text.splitlines():
        s = line.strip()
        if "= " not in s:
            continue
        name = s.split("= ", 1)[0].strip().lstrip("%")
        dt, dims = _parse_shape(s.split("= ", 1)[1])
        if dt is not None and name not in table:
            table[name] = (dt, dims)

    def operand_shapes(opstr):
        """Shapes of the operand list, inline or via the symbol table."""
        depth, args, cur = 0, [], ""
        for ch in opstr:
            if ch in "([{":
                depth += 1
            elif ch in ")]}":
                if depth == 0:
                    break
                depth -= 1
            if ch == "," and depth == 0:
                args.append(cur)
                cur = ""
            else:
                cur += ch
        if cur.strip():
            args.append(cur)
        out = []
        for a in args:
            dt, dims = _parse_shape(a)
            if dims is None:
                mn = re.search(r"%([\w.\-_]+)", a)
                if mn and mn.group(1) in table:
                    dt, dims = table[mn.group(1)]
            out.append((dt, dims))
        return out

    convs, dots, notes = [], [], collections.Counter()
    for line in hlo_text.splitlines():
        s = line.strip()
        if "= " not in s:
            continue
        # HLO form: %name = dtype[dims]{layout} opcode(operands), attrs
        rhs = s.split("= ", 1)[1]
        mop = re.match(r"(?:\([^)]*\)|\S+)\s+([\w-]+)", rhs)
        notes[mop.group(1) if mop else "?"] += 1
        if "convolution(" in rhs:
            out_dt, out_dims = _parse_shape(rhs.split("convolution(")[0])
            if out_dims is None:
                continue
            # window + dim_labels tell us kernel spatial size & feature dims
            mw = re.search(r"window=\{size=([\dx]+)[^}]*\}", s)
            kdims = [int(k) for k in mw.group(1).split("x")] if mw else []
            ml = re.search(r"dim_labels=([\w?]+)_(\w+)->(\w+)", s)
            mg = re.search(r"feature_group_count=(\d+)", s)
            groups = int(mg.group(1)) if mg else 1
            shapes = operand_shapes(s.split("convolution(")[1])
            if len(shapes) < 2 or not ml or shapes[1][1] is None:
                continue
            rhs_dims = shapes[1][1]
            rhs_labels = ml.group(2)
            cin_per_g = rhs_dims[rhs_labels.index("i")]
            out_el = math.prod(out_dims) if out_dims else 1
            fl = 2.0 * out_el * cin_per_g * math.prod(kdims or [1])
            lhs_dil = re.search(r"lhs_dilate=[\dx]+", s)
            convs.append({
                "flops": fl, "out": out_dims, "kernel": kdims,
                "groups": groups, "dtype": out_dt,
                "lhs_dilated": bool(lhs_dil),
                "window": (mw.group(0) if mw else ""),
                "line": s[:240],
            })
        elif " dot(" in rhs or rhs.startswith("dot("):
            out_dt, out_dims = _parse_shape(rhs.split("dot(")[0])
            shapes = operand_shapes(s.split("dot(")[1])
            if len(shapes) < 1 or out_dims is None or shapes[0][1] is None:
                continue
            lhs = shapes[0][1]
            mc = re.search(r"lhs_contracting_dims=\{([\d,]+)\}", s)
            k = 1
            if mc:
                for ci in mc.group(1).split(","):
                    k *= lhs[int(ci)]
            fl = 2.0 * math.prod(out_dims or [1]) * k
            dots.append({"flops": fl, "out": out_dims, "k": k,
                         "dtype": out_dt, "line": s[:240]})
    return convs, dots, notes


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--loss", default="fused", choices=["fused", "onehot"],
                    help="loss path; 'fused' matches bench.py's default")
    ap.add_argument("--json", default=None)
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--dump-hlo", default=None, help="write optimized HLO here")
    ap.add_argument("--from-hlo", default=None,
                    help="analyze an existing HLO dump instead of compiling")
    args = ap.parse_args()

    ca_flops = None
    if args.from_hlo:
        with open(args.from_hlo) as f:
            hlo = f.read()
    else:
        force_cpu_backend()
        import jax
        step, step_args = build_train_step(args.batch, args.dtype,
                                           loss_mode=args.loss)
        print("lowering + compiling ...", file=sys.stderr, flush=True)
        compiled = jax.jit(step).lower(*step_args).compile()
        hlo = compiled.as_text()
        if args.dump_hlo:
            tmp = f"{args.dump_hlo}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(hlo)
            os.replace(tmp, args.dump_hlo)
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0]
            ca_flops = float(ca.get("flops", 0.0))
        except Exception:
            ca_flops = None

    convs, dots, notes = analyze_hlo(hlo)
    total_conv = sum(c["flops"] for c in convs)
    total_dot = sum(d["flops"] for d in dots)
    # model FLOPs = 2*MACs; gluon resnet50_v1 = 3.86 GMACs -> 7.72 GF/img
    analytic = 7.72e9 * 3 * args.batch
    fwd_analytic = 7.72e9 * args.batch

    b = args.batch
    # ResNet-50 activation conv outputs are [b, H, W, C] (or NCHW): batch
    # leading, a feature-map spatial size present, AND a channel count
    # present.  Wgrad outputs are weight-shaped — [Cin, kh, kw, Cout] etc.
    # — which can collide with b on the leading dim (b=64/128/256/512) and
    # with the spatial set via 7x7 kernels ([64,3,7,7] at b=64), but never
    # carry a {spatial, channel} pair like an activation does (the only
    # 3-channel tensor is the input itself, which is not a conv output).
    spatial = {7, 14, 28, 56, 112, 224}
    channels = {3, 64, 128, 256, 512, 1024, 2048}

    def is_act_conv(c):
        dims = c["out"]
        return (dims[0] == b
                and any(d in spatial for d in dims[1:])
                and any(d in channels for d in dims[1:]))

    dil = [c for c in convs if c["lhs_dilated"]]
    fwd_c = [c for c in convs if not c["lhs_dilated"] and is_act_conv(c)]
    wg_c = [c for c in convs if not c["lhs_dilated"] and not is_act_conv(c)]
    # activation dots have batch * spatial-extent leading rows, where the
    # spatial extent is one of ResNet-50's feature-map sizes (1 for the
    # FC fwd [b,1000] / dgrad [b,2048]).  FC wgrad [2048,1000] has
    # weight-shaped rows (2048/b is not a feature-map size) -> weight-out.
    spatial_sizes = {1, 7 * 7, 14 * 14, 28 * 28, 56 * 56, 112 * 112,
                     224 * 224}

    def is_act_dot(d):
        rows = d["out"][0]
        return rows % b == 0 and rows // b in spatial_sizes
    fwd_d = [d for d in dots if is_act_dot(d)]
    wg_d = [d for d in dots if not is_act_dot(d)]
    gf = lambda xs: sum(x["flops"] for x in xs) / 1e9

    print(f"batch={args.batch} dtype={args.dtype}")
    print(f"analytic train FLOPs (3x fwd, 2*MAC convention): "
          f"{analytic/1e9:.1f} GF (fwd {fwd_analytic/1e9:.1f})")
    if ca_flops:
        print(f"cost_analysis flops: {ca_flops/1e9:.1f} GF "
              f"({ca_flops/analytic:.2f}x analytic)")
    print(f"parsed conv+dot = {(total_conv+total_dot)/1e9:.1f} GF "
          f"= {(total_conv+total_dot)/analytic:.2f}x analytic")
    print("decomposition:")
    print(f"  act-out convs+dots (fwd + stride-1 dgrad): "
          f"{gf(fwd_c)+gf(fwd_d):7.2f} GF n={len(fwd_c)+len(fwd_d)}")
    print(f"  weight-out convs+dots (wgrad):             "
          f"{gf(wg_c)+gf(wg_d):7.2f} GF n={len(wg_c)+len(wg_d)}")
    print(f"  lhs-dilated convs (stride-2 dgrad, 4x fwd):"
          f"{gf(dil):7.2f} GF n={len(dil)}")
    print(f"\ntop {args.top} FLOP ops:")
    every = ([("conv", c) for c in convs] + [("dot", d) for d in dots])
    every.sort(key=lambda t: -t[1]["flops"])
    for kind, op in every[:args.top]:
        tag = " LHS-DILATED" if op.get("lhs_dilated") else ""
        print(f"  {op['flops']/1e9:8.2f} GF  {kind}{tag}  out={op.get('out')} "
              f"k={op.get('kernel', op.get('k'))} {op['dtype']}")
    if args.json:
        tmp = f"{args.json}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"batch": args.batch, "analytic": analytic,
                       "cost_analysis": ca_flops, "conv_total": total_conv,
                       "dot_total": total_dot,
                       "lhs_dilated_total": sum(c["flops"] for c in dil),
                       "convs": convs, "dots": dots}, f, indent=1)
        os.replace(tmp, args.json)
    print("\nop histogram:", dict(notes.most_common(20)))


if __name__ == "__main__":
    main()
