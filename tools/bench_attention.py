#!/usr/bin/env python
"""Microbenchmark: Pallas flash attention vs plain-XLA attention.

Prints one JSON line per (seq_len, causal) point:
  {"metric": "flash_attention", "seq": S, "causal": bool,
   "flash_ms": ..., "xla_ms": ..., "speedup": ...}

Run on the TPU chip (default env) or CPU
(env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu — interpreter mode, for
plumbing checks only; interpreter timings are meaningless).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench(fn, *args, reps=10):
    out = fn(*args)
    jax_block(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax_block(out)
    return (time.perf_counter() - t0) / reps * 1e3


def jax_block(x):
    import jax
    jax.block_until_ready(x)


def main():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas_attention import (flash_attention,
                                                _reference_attention)

    b, h, d = int(os.environ.get("BENCH_B", 4)), 8, 128
    dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    seqs = [int(s) for s in
            os.environ.get("BENCH_SEQS", "512,1024,2048").split(",")]
    for s in seqs:
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (b, h, s, d), dtype)
        k = jax.random.normal(kk, (b, h, s, d), dtype)
        v = jax.random.normal(kv, (b, h, s, d), dtype)
        for causal in (False, True):
            flash = jax.jit(lambda q_, k_, v_, c=causal:
                            flash_attention(q_, k_, v_, c))
            xla = jax.jit(lambda q_, k_, v_, c=causal:
                          _reference_attention(q_, k_, v_, c, d ** -0.5))
            fm = bench(flash, q, k, v)
            xm = bench(xla, q, k, v)
            print(json.dumps({
                "metric": "flash_attention", "seq": s, "causal": causal,
                "batch": b, "heads": h, "head_dim": d,
                "dtype": str(dtype.__name__),
                "flash_ms": round(fm, 3), "xla_ms": round(xm, 3),
                "speedup": round(xm / fm, 3)}), flush=True)


if __name__ == "__main__":
    main()
