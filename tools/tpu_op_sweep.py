#!/usr/bin/env python
"""On-device op numerics sweep (VERDICT r4 item 3).

Runs the declarative CASES table (tests/test_op_coverage.py — the same
table the CPU suite sweeps) on BOTH the host CPU backend and the real
TPU, and records the per-op max abs/rel error of the TPU leg against the
CPU leg — the reference's backend-equivalence strategy
(tests/python/gpu/test_operator_gpu.py:1 re-imports the whole CPU suite;
python/mxnet/test_utils.py:1283 check_consistency).

Design for a flaky relay: results stream to the JSON report after EVERY
op, --resume skips ops already recorded, and a time budget bounds the
run.  Random/sampling ops compare moments rather than values (their
counter-key streams are device-independent by construction, but the
sweep stays conservative).

Usage:
  python tools/tpu_op_sweep.py [--budget 1200] [--resume]
  JAX_PLATFORMS=cpu python tools/tpu_op_sweep.py --self-test  # harness
"""
import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _relay_util import T0, arm_watchdog, cpu_only_backend, finish
from _relay_util import log as _log

OUT = os.path.join(_REPO, "docs", "tpu_op_sweep.json")


def log(m):
    _log("sweep", m)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=float, default=1200)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--out", default=OUT)
    ap.add_argument("--self-test", action="store_true",
                    help="cpu-vs-cpu harness check (no TPU needed)")
    args = ap.parse_args()

    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(_REPO, ".jax_cache"))

    import numpy as np
    if args.self_test:
        # harness check: never dial the relay at all
        jax = cpu_only_backend()
        cpu = target = jax.devices("cpu")[0]
    else:
        import jax
        init_timeout = float(os.environ.get("SWEEP_INIT_TIMEOUT", 300))
        disarm = arm_watchdog(init_timeout,
                              {"error": "TPU relay unreachable"})
        devs = jax.devices()
        disarm()
        cpu = jax.devices("cpu")[0]
        accels = [d for d in devs if d.platform != "cpu"]
        if not accels:
            print(json.dumps({"error": "no TPU device (cpu backend)"}))
            finish(1)
        target = accels[0]
        # a mid-sweep relay hang must not outlive the budget either
        arm_watchdog(args.budget * 1.25 + 120,
                     {"error": "sweep wedged past budget",
                      "partial_report": args.out})
    log(f"target device: {target}")

    import mxnet_tpu as mx  # noqa: F401
    from mxnet_tpu import nd
    from mxnet_tpu.ndarray import invoke
    from mxnet_tpu.ndarray.ndarray import NDArray
    import test_op_coverage as cov

    report = {"device": str(getattr(target, "device_kind", target)),
              "ops": {}}
    if args.resume and os.path.exists(args.out):
        with open(args.out) as f:
            report["ops"] = json.load(f).get("ops", {})
        log(f"resuming: {len(report['ops'])} ops already recorded")

    names = sorted(cov.CASES)
    n_ok = n_fail = 0
    for i, name in enumerate(names):
        if name in report["ops"] and "error" not in report["ops"][name]:
            continue
        if time.perf_counter() - T0 > args.budget:
            log(f"budget exhausted at {i}/{len(names)}")
            break
        case = cov.CASES[name]
        op = cov._resolve(name)
        rec = {"status": "ok"}
        try:
            legs = {}
            for tag, dev in (("cpu", cpu), ("tpu", target)):
                arrs = [NDArray(jax.device_put(np.asarray(x), dev))
                        for x in case.inputs]
                # zero-input ops (creation family) have no operand to
                # carry the device — pin the default device explicitly
                # or both legs silently run on the same backend
                with jax.default_device(dev):
                    out = invoke(op, arrs, dict(case.attrs))
                outs = out if isinstance(out, list) else [out]
                legs[tag] = [o.asnumpy().astype(np.float64) for o in outs]
            is_random = (name.startswith("_random")
                         or name.startswith("_sample")
                         or name in ("multinomial", "_shuffle"))
            if is_random:
                # moments, not values: samplers draw per-device streams
                m_cpu = [float(np.mean(o)) for o in legs["cpu"]]
                m_tpu = [float(np.mean(o)) for o in legs["tpu"]]
                rec["mean_cpu"], rec["mean_tpu"] = m_cpu, m_tpu
                rec["kind"] = "random-moments"
            else:
                max_abs = max_rel = 0.0
                for a, b in zip(legs["cpu"], legs["tpu"]):
                    diff = np.abs(a - b)
                    max_abs = max(max_abs, float(diff.max(initial=0.0)))
                    denom = np.maximum(np.abs(a), 1e-6)
                    max_rel = max(max_rel,
                                  float((diff / denom).max(initial=0.0)))
                rec["max_abs_err"] = max_abs
                rec["max_rel_err"] = max_rel
                # TPU f32 matmul internals run ~bf16ish; elementwise ops
                # should be (nearly) exact
                if max_rel > 5e-2 and max_abs > 1e-3:
                    rec["status"] = "MISMATCH"
            if rec["status"] == "ok":
                n_ok += 1
            else:
                n_fail += 1
        except Exception as e:
            rec = {"status": "error", "error": f"{type(e).__name__}: {e}"}
            n_fail += 1
        report["ops"][name] = rec
        # rewritten after every op: replace atomically so a killed sweep
        # still leaves a loadable report
        tmp = f"{args.out}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1)
        os.replace(tmp, args.out)
        if i % 25 == 0:
            log(f"{i}/{len(names)} swept ({n_ok} ok, {n_fail} errors)")

    bad = {k: v for k, v in report["ops"].items()
           if v.get("status") not in ("ok",)}
    summary = {"metric": "tpu_op_sweep", "swept": len(report["ops"]),
               "total": len(names), "mismatch_or_error": len(bad)}
    report["summary"] = summary
    tmp = f"{args.out}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1)
    os.replace(tmp, args.out)
    for k, v in sorted(bad.items()):
        log(f"BAD {k}: {v}")
    print(json.dumps(summary))
    finish(0)


if __name__ == "__main__":
    main()
