"""Convert Caffe .caffemodel weights to mxnet_tpu arg/aux params.

Parity: reference tools/caffe_converter/convert_model.py. The binary is
protobuf wire format; instead of a compiled caffe_pb2 this reuses the
framework's self-contained wire codec (mxnet_tpu/contrib/onnx/_proto.py
parse_fields) with the handful of Caffe field numbers hard-wired from
caffe.proto: NetParameter.layer = 100, LayerParameter
{name=1, type=2, blobs=7}, BlobProto {shape=7, data=5 packed-float,
num/channels/height/width = 1..4}, BlobShape.dim = 1.

Weight layout translation (as in the reference converter):
  Convolution blobs -> <name>_weight (num_filter, C, kh, kw), _bias
  InnerProduct blobs -> <name>_weight (out, in), _bias
  BatchNorm blobs [mean, var, scale_factor] -> moving stats / scale
  Scale blobs [gamma, beta] -> folded onto the preceding BatchNorm
"""
from __future__ import annotations

import os
import struct
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _wire():
    from mxnet_tpu.contrib.onnx import _proto
    return _proto


def _parse_blob(buf):
    """BlobProto -> np.float32 array with its declared shape."""
    p = _wire()
    dims, legacy = None, {}
    data = b""
    for field, wire, value in p.parse_fields(buf):
        if field == 7 and wire == 2:  # shape
            for f2, _w2, v2 in p.parse_fields(value):
                if f2 == 1:
                    # packed (bytes) or unpacked (one varint per field) —
                    # protobuf parsers must accept both; accumulate
                    new = p._unpack_ints(v2) if isinstance(v2, bytes) \
                        else [v2]
                    dims = (dims or []) + new
        elif field == 5 and wire == 2:  # packed float data
            data += value
        elif field == 5 and wire == 5:  # unpacked float element
            data += value
        elif field in (1, 2, 3, 4) and wire == 0:  # legacy NCHW dims
            legacy[field] = value
    arr = np.frombuffer(data, dtype="<f4").astype(np.float32)
    if dims:
        arr = arr.reshape([int(d) for d in dims])
    elif legacy:
        shape = [legacy.get(i, 1) for i in (1, 2, 3, 4)]
        arr = arr.reshape(shape)
    return arr


def parse_caffemodel(path_or_bytes):
    """caffemodel -> [(name, type, [blob arrays])]."""
    p = _wire()
    buf = path_or_bytes
    if isinstance(buf, (str, os.PathLike)):
        with open(buf, "rb") as f:
            buf = f.read()
    layers = []
    for field, wire, value in p.parse_fields(buf):
        if field == 100 and wire == 2:  # NetParameter.layer
            name = ltype = ""
            blobs = []
            for f2, w2, v2 in p.parse_fields(value):
                if f2 == 1 and w2 == 2:
                    name = v2.decode()
                elif f2 == 2 and w2 == 2:
                    ltype = v2.decode()
                elif f2 == 7 and w2 == 2:
                    blobs.append(_parse_blob(v2))
            layers.append((name, ltype, blobs))
    return layers


def convert_model(prototxt_text, caffemodel):
    """(prototxt text, caffemodel path/bytes) ->
    (Symbol, arg_params, aux_params, input_name, input_dim)."""
    from convert_symbol import convert_symbol
    from mxnet_tpu import nd

    symbol, input_name, input_dim = convert_symbol(prototxt_text)
    arg_names = set(symbol.list_arguments())
    aux_names = set(symbol.list_auxiliary_states())
    arg_params, aux_params = {}, {}
    pending_bn = None  # (layer name) awaiting a Scale companion

    for name, ltype, blobs in parse_caffemodel(caffemodel):
        if not blobs:
            continue
        if ltype in ("Convolution", "Deconvolution", "InnerProduct"):
            w = blobs[0]
            arg_params[f"{name}_weight"] = nd.array(w)
            if len(blobs) > 1:
                arg_params[f"{name}_bias"] = nd.array(blobs[1].reshape(-1))
        elif ltype == "BatchNorm":
            mean, var = blobs[0].reshape(-1), blobs[1].reshape(-1)
            if len(blobs) > 2:
                # caffe stores running stats scaled by a factor blob
                factor = float(blobs[2].reshape(-1)[0])
                if factor != 0:
                    mean = mean / factor
                    var = var / factor
            aux_params[f"{name}_moving_mean"] = nd.array(mean)
            aux_params[f"{name}_moving_var"] = nd.array(var)
            pending_bn = name
            # without a Scale companion the converter uses fix_gamma;
            # provide neutral gamma/beta so binding is complete
            arg_params.setdefault(f"{name}_gamma",
                                  nd.array(np.ones_like(mean)))
            arg_params.setdefault(f"{name}_beta",
                                  nd.array(np.zeros_like(mean)))
        elif ltype == "Scale" and pending_bn is not None:
            arg_params[f"{pending_bn}_gamma"] = nd.array(
                blobs[0].reshape(-1))
            if len(blobs) > 1:
                arg_params[f"{pending_bn}_beta"] = nd.array(
                    blobs[1].reshape(-1))
            pending_bn = None

    arg_params = {k: v for k, v in arg_params.items() if k in arg_names}
    aux_params = {k: v for k, v in aux_params.items() if k in aux_names}
    return symbol, arg_params, aux_params, input_name, input_dim


def main():
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prototxt")
    ap.add_argument("caffemodel")
    ap.add_argument("output_prefix")
    args = ap.parse_args()
    with open(args.prototxt) as f:
        sym_, arg_p, aux_p, _n, _d = convert_model(f.read(),
                                                   args.caffemodel)
    from mxnet_tpu import nd
    sym_path = args.output_prefix + "-symbol.json"
    tmp = f"{sym_path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(sym_.tojson())
    os.replace(tmp, sym_path)
    save = {f"arg:{k}": v for k, v in arg_p.items()}
    save.update({f"aux:{k}": v for k, v in aux_p.items()})
    nd.save(args.output_prefix + "-0000.params", save)
    print(f"saved {args.output_prefix}-symbol.json / -0000.params")


if __name__ == "__main__":
    main()
