"""Minimal Caffe prototxt (protobuf text-format) parser.

Role parity: reference tools/caffe_converter/caffe_parser.py, which
needs a compiled caffe_pb2; here the text format is parsed directly —
prototxt is a simple nested ``key: value`` / ``key { ... }`` grammar —
so the converter has zero Caffe dependency.

Returns plain dicts: repeated keys become lists, ``key { ... }`` blocks
become nested dicts, enum identifiers stay strings, numbers and
true/false are converted.
"""
from __future__ import annotations

import re

_TOKEN = re.compile(
    r"""(?:
      (?P<brace>[{}])
    | (?P<colon>:)
    | (?P<string>"(?:[^"\\]|\\.)*")
    | (?P<number>[-+]?(?:\d+\.\d*|\.\d+|\d+)(?:[eE][-+]?\d+)?)
    | (?P<ident>[A-Za-z_][A-Za-z0-9_.\-]*)
    )""",
    re.VERBOSE,
)


def _tokenize(text):
    pos, n = 0, len(text)
    while pos < n:
        while pos < n and text[pos].isspace():
            pos += 1
        if pos >= n:
            break
        if text[pos] == "#":  # comment to end of line
            nl = text.find("\n", pos)
            pos = n if nl == -1 else nl + 1
            continue
        m = _TOKEN.match(text, pos)
        if m is None:
            snippet = text[pos:pos + 20]
            raise ValueError(f"prototxt parse error at {snippet!r}")
        pos = m.end()
        yield m.lastgroup, m.group()


def _coerce(kind, raw):
    if kind == "string":
        return raw[1:-1].encode().decode("unicode_escape")
    if kind == "number":
        f = float(raw)
        return int(f) if f == int(f) and "." not in raw and "e" not in raw.lower() else f
    if raw in ("true", "false"):
        return raw == "true"
    return raw  # enum identifier (MAX, AVE, SUM, ...)


def _store(d, key, value):
    if key in d:
        cur = d[key]
        if isinstance(cur, list):
            cur.append(value)
        else:
            d[key] = [cur, value]
    else:
        d[key] = value


def parse(text):
    """Parse prototxt text into a nested dict."""
    tokens = list(_tokenize(text))
    pos = 0

    def block():
        nonlocal pos
        out = {}
        while pos < len(tokens):
            kind, tok = tokens[pos]
            if kind == "brace" and tok == "}":
                pos += 1
                return out
            if kind != "ident":
                raise ValueError(f"expected field name, got {tok!r}")
            key = tok
            pos += 1
            kind, tok = tokens[pos]
            if kind == "colon":
                pos += 1
                vkind, vtok = tokens[pos]
                pos += 1
                _store(out, key, _coerce(vkind, vtok))
            elif kind == "brace" and tok == "{":
                pos += 1
                _store(out, key, block())
            else:
                raise ValueError(f"expected ':' or '{{' after {key!r}")
        return out

    return block()


def as_list(v):
    """A possibly-repeated field as a list ([] for absent)."""
    if v is None:
        return []
    return v if isinstance(v, list) else [v]
