"""Convert a Caffe prototxt network definition to an mxnet_tpu Symbol.

Parity: reference tools/caffe_converter/convert_symbol.py (which walks
caffe_pb2 LayerParameters and emits mx.symbol calls; layer coverage and
attribute translation — ceil pooling => pooling_convention='full',
BatchNorm+Scale fusion, grouped convolution — follow it). This version
parses the prototxt text directly (prototxt.py) and builds Symbols
through the registry, no Caffe install required.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import prototxt  # noqa: E402


def _pair(param, base, default=0):
    """Caffe kernel/stride/pad: scalar `k`, repeated per-axis `k k`,
    or explicit k_h/k_w."""
    v = param.get(base)
    if v is not None:
        vals = [int(x) for x in prototxt.as_list(v)]
        if len(vals) == 1:
            return (vals[0], vals[0])
        if len(vals) == 2:
            return (vals[0], vals[1])
        raise ValueError(
            f"{base}: expected at most 2 repeated values, got {vals}")
    h = param.get(base + "_h")
    w = param.get(base + "_w")
    if h is not None or w is not None:
        return (int(h or default), int(w or default))
    return (default, default)


def _conv_attrs(param):
    attrs = {"num_filter": int(param["num_output"])}
    attrs["kernel"] = _pair(param, "kernel_size")
    attrs["stride"] = _pair(param, "stride", 1)
    attrs["pad"] = _pair(param, "pad", 0)
    group = int(param.get("group", 1))
    if group != 1:
        attrs["num_group"] = group
    if param.get("bias_term") is False:
        attrs["no_bias"] = True
    dil = param.get("dilation")
    if dil is not None:
        ds = [int(x) for x in prototxt.as_list(dil)]
        attrs["dilate"] = (ds[0], ds[-1]) if len(ds) <= 2 else None
        if attrs["dilate"] is None:
            raise ValueError(f"dilation: at most 2 values, got {ds}")
    return attrs


def convert_symbol(proto_text):
    """prototxt text -> (Symbol, input_name, input_dim).

    Supported layer types mirror the reference converter: Input/data,
    Convolution, Deconvolution, Pooling (MAX/AVE, global, ceil), LRN,
    InnerProduct, ReLU, Sigmoid, TanH, Dropout, Softmax,
    SoftmaxWithLoss, Concat, Eltwise (SUM/PROD/MAX), Flatten,
    BatchNorm (+ fused following Scale layer).
    """
    from mxnet_tpu import symbol as sym

    net = prototxt.parse(proto_text)
    layers = prototxt.as_list(net.get("layer")) or \
        prototxt.as_list(net.get("layers"))
    if not layers:
        raise ValueError("no layer/layers entries in prototxt")

    # -- input ---------------------------------------------------------------
    input_name, input_dim = "data", None
    if net.get("input"):
        input_name = prototxt.as_list(net["input"])[0]
        if net.get("input_dim"):
            input_dim = [int(d) for d in prototxt.as_list(net["input_dim"])]
        elif net.get("input_shape"):
            shp = prototxt.as_list(net["input_shape"])[0]
            input_dim = [int(d) for d in prototxt.as_list(shp["dim"])]
    elif layers and layers[0].get("type") == "Input":
        l0 = layers.pop(0)
        input_name = prototxt.as_list(l0["top"])[0]
        shp = l0["input_param"]["shape"]
        input_dim = [int(d) for d in
                     prototxt.as_list(prototxt.as_list(shp)[0]["dim"])]

    blobs = {input_name: sym.var(input_name)}
    last_top = input_name

    def bottom(layer):
        return [blobs[b] for b in prototxt.as_list(layer["bottom"])]

    skip_next_scale_of = None
    for i, layer in enumerate(layers):
        ltype = layer["type"]
        name = layer.get("name", f"layer{i}")
        tops = prototxt.as_list(layer["top"]) if layer.get("top") else [name]
        if ltype in ("Data", "ImageData", "HDF5Data", "Accuracy", "Silence"):
            continue
        if ltype == "Scale" and skip_next_scale_of is not None and \
                prototxt.as_list(layer["bottom"])[0] == skip_next_scale_of:
            # folded into the preceding BatchNorm (reference fuses too)
            blobs[tops[0]] = blobs[skip_next_scale_of]
            last_top = tops[0]
            skip_next_scale_of = None
            continue

        ins = bottom(layer)
        if ltype == "Convolution":
            out = sym.Symbol._create(
                "Convolution", ins, _conv_attrs(layer["convolution_param"]),
                name=name)
        elif ltype == "Deconvolution":
            out = sym.Symbol._create(
                "Deconvolution", ins,
                _conv_attrs(layer["convolution_param"]), name=name)
        elif ltype == "Pooling":
            p = layer["pooling_param"]
            pool_raw = p.get("pool", "MAX")
            pool = {0: "max", 1: "avg",
                    "MAX": "max", "AVE": "avg"}.get(pool_raw)
            if pool is None:
                # STOCHASTIC (=2) and anything newer have no analog here
                raise ValueError(
                    f"unsupported caffe pooling method {pool_raw!r} "
                    f"(layer {name!r}); only MAX/AVE convert")
            attrs = {"pool_type": pool}
            if p.get("global_pooling"):
                attrs["global_pool"] = True
                attrs["kernel"] = (1, 1)
            else:
                attrs["kernel"] = _pair(p, "kernel_size")
                attrs["stride"] = _pair(p, "stride", 1)
                attrs["pad"] = _pair(p, "pad", 0)
                # caffe pools with ceil — the reference converter maps
                # this to pooling_convention='full'
                attrs["pooling_convention"] = "full"
            out = sym.Symbol._create("Pooling", ins, attrs, name=name)
        elif ltype == "InnerProduct":
            p = layer["inner_product_param"]
            attrs = {"num_hidden": int(p["num_output"]), "flatten": True}
            if p.get("bias_term") is False:
                attrs["no_bias"] = True
            out = sym.Symbol._create("FullyConnected", ins, attrs,
                                     name=name)
        elif ltype in ("ReLU", "Sigmoid", "TanH"):
            act = {"ReLU": "relu", "Sigmoid": "sigmoid",
                   "TanH": "tanh"}[ltype]
            out = sym.Symbol._create("Activation", ins,
                                     {"act_type": act}, name=name)
        elif ltype == "LRN":
            p = layer.get("lrn_param", {})
            out = sym.Symbol._create(
                "LRN", ins,
                {"nsize": int(p.get("local_size", 5)),
                 "alpha": float(p.get("alpha", 1e-4)),
                 "beta": float(p.get("beta", 0.75)),
                 "knorm": float(p.get("k", 1.0))}, name=name)
        elif ltype == "Dropout":
            p = layer.get("dropout_param", {})
            out = sym.Symbol._create(
                "Dropout", ins,
                {"p": float(p.get("dropout_ratio", 0.5))}, name=name)
        elif ltype == "Softmax":
            p = layer.get("softmax_param", {})
            # caffe softmax normalizes over channels (axis=1) by default,
            # not the trailing axis
            out = sym.Symbol._create("softmax", ins,
                                     {"axis": int(p.get("axis", 1))},
                                     name=name)
        elif ltype == "SoftmaxWithLoss":
            label = sym.var("softmax_label")
            out = sym.Symbol._create("SoftmaxOutput", [ins[0], label], {},
                                     name=name)
        elif ltype == "Concat":
            p = layer.get("concat_param", {})
            out = sym.Symbol._create(
                "Concat", ins,
                {"dim": int(p.get("axis", 1)),
                 "num_args": len(ins)}, name=name)
        elif ltype == "Eltwise":
            p = layer.get("eltwise_param", {})
            op = p.get("operation", "SUM")
            opname = {0: "elemwise_mul", 1: "elemwise_add",
                      2: "broadcast_maximum", "PROD": "elemwise_mul",
                      "SUM": "elemwise_add",
                      "MAX": "broadcast_maximum"}[op]
            coeffs = [float(c) for c in prototxt.as_list(p.get("coeff"))]
            if coeffs and opname != "elemwise_add":
                raise ValueError("eltwise coeff is only valid with SUM")
            terms = list(ins)
            if coeffs:
                if len(coeffs) != len(terms):
                    raise ValueError(
                        f"eltwise: {len(coeffs)} coeffs for "
                        f"{len(terms)} inputs")
                terms = [t if c == 1.0 else
                         sym.Symbol._create("_mul_scalar", [t],
                                            {"scalar": c})
                         for t, c in zip(terms, coeffs)]
            out = terms[0]
            for extra in terms[1:]:
                out = sym.Symbol._create(opname, [out, extra], {})
        elif ltype == "Flatten":
            out = sym.Symbol._create("Flatten", ins, {}, name=name)
        elif ltype == "BatchNorm":
            p = layer.get("batch_norm_param", {})
            attrs = {"eps": float(p.get("eps", 1e-5)),
                     "use_global_stats":
                         bool(p.get("use_global_stats", True))}
            # a following Scale layer supplies gamma/beta; without one,
            # gamma is fixed (caffe BatchNorm has no affine part)
            nxt = layers[i + 1] if i + 1 < len(layers) else None
            if nxt is not None and nxt.get("type") == "Scale" and \
                    prototxt.as_list(nxt["bottom"])[0] == tops[0]:
                skip_next_scale_of = tops[0]
                # the Scale layer's gamma/beta are real parameters —
                # override BatchNorm's fix_gamma=True default
                attrs["fix_gamma"] = False
            else:
                attrs["fix_gamma"] = True
            out = sym.Symbol._create("BatchNorm", ins, attrs, name=name)
        else:
            raise ValueError(
                f"unsupported caffe layer type {ltype!r} (layer {name!r})"
                " — extend convert_symbol.py, the mapping table is small")
        blobs[tops[0]] = out
        last_top = tops[0]

    # the network output is the last COMPUTED top — trailing
    # Accuracy/Silence/data layers are skipped and never produce one
    return blobs[last_top], input_name, input_dim


def main():
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prototxt")
    ap.add_argument("output_json")
    args = ap.parse_args()
    with open(args.prototxt) as f:
        s, _name, _dim = convert_symbol(f.read())
    tmp = f"{args.output_json}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(s.tojson())
    os.replace(tmp, args.output_json)
    print(f"saved symbol to {args.output_json}")


if __name__ == "__main__":
    main()
