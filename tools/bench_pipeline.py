#!/usr/bin/env python
"""Input-pipeline microbenchmark: can the data path feed the chip?

Generates a synthetic .rec file (JPEG-packed, tools/im2rec format), then
measures:
  * ImageRecordIter decode+augment+batch rate (img/s)
  * gluon DataLoader (fork workers + shm + device prefetch) rate over a
    synthetic in-memory dataset

One JSON line per stage.  Compare against the train step's img/s from
bench.py — the pipeline must sustain at least that rate to not be the
bottleneck (reference: iter_image_recordio_2.cc fused pipeline).

Env: BENCH_REC_IMAGES (default 512), BENCH_BATCH (32), BENCH_WORKERS (4).
"""
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import numpy as np
    n_images = int(os.environ.get("BENCH_REC_IMAGES", 512))
    batch = int(os.environ.get("BENCH_BATCH", 32))
    workers = int(os.environ.get("BENCH_WORKERS", 4))

    import mxnet_tpu as mx
    from mxnet_tpu import recordio, image

    tmp = tempfile.mkdtemp(prefix="bench_rec_")
    rec_path = os.path.join(tmp, "data.rec")
    idx_path = os.path.join(tmp, "data.idx")

    # pack a synthetic JPEG dataset (im2rec format)
    try:
        import cv2
        enc = lambda a: cv2.imencode(".jpg", a)[1].tobytes()
    except ImportError:
        from PIL import Image
        import io as _io

        def enc(a):
            buf = _io.BytesIO()
            Image.fromarray(a[:, :, ::-1]).save(buf, format="JPEG")
            return buf.getvalue()

    rng = np.random.RandomState(0)
    writer = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(n_images):
        img = rng.randint(0, 255, (256, 256, 3), np.uint8)
        header = recordio.IRHeader(0, float(i % 10), i, 0)
        writer.write_idx(i, recordio.pack(header, enc(img)))
    writer.close()

    it = image.ImageIter(batch_size=batch, data_shape=(3, 224, 224),
                         path_imgrec=rec_path, path_imgidx=idx_path,
                         shuffle=False,
                         rand_crop=True, rand_mirror=True)
    # warm one epoch pass of a few batches
    it.reset()
    for _, _b in zip(range(2), it):
        pass
    it.reset()
    t0 = time.perf_counter()
    seen = 0
    for b in it:
        seen += batch
    dt = time.perf_counter() - t0
    print(json.dumps({"metric": "image_rec_pipeline_img_per_sec",
                      "value": round(seen / dt, 1), "unit": "img/s",
                      "images": seen, "batch": batch,
                      "decode": "host"}), flush=True)

    # DataLoader over an in-memory dataset with fork workers + shm +
    # device prefetch
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.dataset import ArrayDataset
    data = rng.randn(n_images, 3, 224, 224).astype(np.float32)
    label = (np.arange(n_images) % 10).astype(np.float32)
    ds = ArrayDataset(data, label)
    loader = DataLoader(ds, batch_size=batch, num_workers=workers,
                        device_prefetch=True)
    for _ in zip(range(2), loader):
        pass
    t0 = time.perf_counter()
    seen = 0
    for d, l in loader:
        seen += d.shape[0]
    dt = time.perf_counter() - t0
    print(json.dumps({"metric": "dataloader_img_per_sec",
                      "value": round(seen / dt, 1), "unit": "img/s",
                      "images": seen, "batch": batch,
                      "workers": workers, "shm": True,
                      "device_prefetch": True}), flush=True)


if __name__ == "__main__":
    main()
