#!/usr/bin/env python
"""Environment diagnostic (parity: reference tools/diagnose.py).

Prints platform, python, package versions, jax backend/devices, native
library availability, and the typed env-var configuration.
"""
import os
import platform
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    print("----------Python Info----------")
    print("Version      :", platform.python_version())
    print("Compiler     :", platform.python_compiler())
    print("Build        :", platform.python_build())
    print("Arch         :", platform.machine(), platform.architecture()[0])
    print("----------System Info----------")
    print("Platform     :", platform.platform())
    print("system       :", platform.system())
    print("release      :", platform.release())

    print("----------Framework Info----------")
    import mxnet_tpu as mx
    print("mxnet_tpu    :", mx.__version__)
    import jax
    print("jax          :", jax.__version__)
    import numpy as np
    print("numpy        :", np.__version__)
    try:
        import jaxlib
        print("jaxlib       :", jaxlib.__version__)
    except Exception:
        pass
    print("default bkend:", jax.default_backend())
    try:
        print("devices      :", jax.devices())
    except Exception as e:
        print("devices      : <unavailable:", e, ">")

    print("----------Native Libraries----------")
    from mxnet_tpu import _native
    print("io_native    :", "loaded" if _native.available() else "absent")
    predict = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "src", "build",
        "libmxnet_tpu_predict.so")
    print("predict ABI  :", "built" if os.path.exists(predict) else "absent")

    print("----------Environment----------")
    from mxnet_tpu import config
    for name in sorted(config._REGISTRY):
        cur = os.environ.get(name)
        if cur is not None:
            print(f"{name}={cur}")
    for var in ("JAX_PLATFORMS", "XLA_FLAGS", "PALLAS_AXON_TPU_GEN"):
        if os.environ.get(var):
            print(f"{var}={os.environ[var]}")


if __name__ == "__main__":
    main()
