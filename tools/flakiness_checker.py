#!/usr/bin/env python
"""Run a test repeatedly to expose flakiness
(parity: reference tools/flakiness_checker.py).

Usage:
    python tools/flakiness_checker.py tests/test_operator.py::test_foo -n 20
Runs the named test N times with different PYTHONHASHSEED/MXNET seeds and
reports the failure count.
"""
import argparse
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("test", help="pytest node id")
    ap.add_argument("-n", "--trials", type=int, default=10)
    ap.add_argument("--stop-on-fail", action="store_true")
    args = ap.parse_args()

    failures = 0
    ran = 0
    for trial in range(args.trials):
        env = dict(os.environ)
        env["PYTHONHASHSEED"] = str(trial)
        env["MXNET_TEST_SEED"] = str(trial * 1000 + 7)
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", args.test, "-q",
             "--no-header"],
            cwd=_REPO, env=env, capture_output=True, text=True)
        ran += 1
        status = "PASS" if proc.returncode == 0 else "FAIL"
        print(f"trial {trial + 1}/{args.trials}: {status}")
        if proc.returncode != 0:
            failures += 1
            # usage/collection errors report on stderr
            tail = (proc.stdout.strip().splitlines()[-5:]
                    + proc.stderr.strip().splitlines()[-3:])
            print("\n".join("    " + ln for ln in tail if ln))
            if args.stop_on_fail:
                break
    print(f"\n{failures}/{ran} trials failed")
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
