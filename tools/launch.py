#!/usr/bin/env python
"""Launch a distributed parameter-server job on localhost.

Role parity with /root/reference/tools/launch.py:128 + dmlc-tracker
'local' mode: spawns 1 server (the kvstore_server process), N workers,
each with the DMLC_* rendezvous env the dist kvstore reads
(kvstore.py KVStoreDist).  Multi-host TPU jobs use the SPMD path
(mxnet_tpu.parallel over ICI/DCN), not this launcher — this covers the
reference's `launch.py -n N --launcher local python train.py` workflow.

Usage:
  python tools/launch.py -n 4 [-p 9091] python train_script.py args...
"""
import argparse
import os
import signal
import subprocess
import sys
import time


def main():
    parser = argparse.ArgumentParser(
        description="Launch a distributed job on localhost "
                    "(parity: reference tools/launch.py local mode)")
    parser.add_argument("-n", "--num-workers", required=True, type=int)
    parser.add_argument("-s", "--num-servers", type=int, default=1,
                        help="only 1 server process is supported (it "
                        "owns the whole store)")
    parser.add_argument("-p", "--port", type=int, default=9091)
    parser.add_argument("--env", nargs="*", default=[],
                        help="extra KEY=VALUE env for all roles")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    if not args.command:
        parser.error("no command given")
    if args.num_servers != 1:
        parser.error("the TPU kvstore server is a single process "
                     "(aggregation is in-memory); use -s 1")

    base_env = dict(os.environ)
    for kv in args.env:
        k, _, v = kv.partition("=")
        base_env[k] = v
    base_env.update({
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(args.port),
    })

    procs = []

    def shutdown(*_):
        for p in procs:
            if p.poll() is None:
                p.terminate()

    signal.signal(signal.SIGINT, shutdown)
    signal.signal(signal.SIGTERM, shutdown)

    # server role (parity: DMLC_ROLE=server blocking in RunServer)
    senv = dict(base_env)
    senv["DMLC_ROLE"] = "server"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    senv["PYTHONPATH"] = repo + os.pathsep + senv.get("PYTHONPATH", "")
    server = subprocess.Popen(
        [sys.executable, "-m", "mxnet_tpu.kvstore_server"], env=senv)
    procs.append(server)
    time.sleep(0.3)

    # worker roles
    workers = []
    for rank in range(args.num_workers):
        wenv = dict(base_env)
        wenv.update({"DMLC_ROLE": "worker", "DMLC_RANK": str(rank),
                     "DMLC_WORKER_ID": str(rank)})
        wenv["PYTHONPATH"] = repo + os.pathsep + wenv.get("PYTHONPATH", "")
        w = subprocess.Popen(args.command, env=wenv)
        workers.append(w)
        procs.append(w)

    rc = 0
    for w in workers:
        rc = w.wait() or rc
    server.terminate()
    sys.exit(rc)


if __name__ == "__main__":
    main()
