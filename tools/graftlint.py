#!/usr/bin/env python
"""graftlint — project-native static analysis for the mxnet_tpu repo.

Rules encode invariants this codebase has already paid to learn (see
docs/lint.md): lock-discipline races, torn writes of durable artifacts,
device->host syncs in hot loops, tracer leaks in jit code, swallowed
errors, and env-knob drift against config.py.

Usage:
  python tools/graftlint.py                      # lint default paths
  python tools/graftlint.py --fail-on-new        # CI gate (baseline diff)
  python tools/graftlint.py --write-baseline     # accept current findings
  python tools/graftlint.py --json path/to.py    # machine-readable
  python tools/graftlint.py --list-rules

Exit codes: 0 clean (or only baselined findings with --fail-on-new),
1 gate failure, 2 usage/internal error.

The analysis package is loaded straight from its directory so that
linting never imports mxnet_tpu itself (no jax/numpy import cost).
"""
from __future__ import annotations

import argparse
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_PATHS = ("mxnet_tpu", "tools", "bench.py", "__graft_entry__.py")
DEFAULT_BASELINE = os.path.join("ci", "graftlint_baseline.json")


def _load_analysis():
    pkg_dir = os.path.join(REPO, "mxnet_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "graftlint_analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["graftlint_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to lint (default: {DEFAULT_PATHS})")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON path (repo-relative)")
    ap.add_argument("--fail-on-new", action="store_true",
                    help="exit 1 when findings exceed the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="commit current findings as the baseline")
    ap.add_argument("--select", default="",
                    help="comma-separated rule ids to run exclusively")
    ap.add_argument("--disable", default="",
                    help="comma-separated rule ids to skip")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    an = _load_analysis()

    if args.list_rules:
        for rid, cls in sorted(an.all_rules().items()):
            print(f"{rid:<22} [{cls.severity}] {cls.doc}")
        return 0

    try:
        rules = an.make_rules(
            select=[r for r in args.select.split(",") if r] or None,
            disable=[r for r in args.disable.split(",") if r])
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    paths = args.paths or [os.path.join(REPO, p) for p in DEFAULT_PATHS]
    findings, errors = an.analyze_paths(paths, rules=rules, root=REPO)

    baseline_path = (args.baseline if os.path.isabs(args.baseline)
                     else os.path.join(REPO, args.baseline))

    if args.write_baseline:
        an.write_baseline(baseline_path, findings)
        print(f"graftlint: baseline written to "
              f"{os.path.relpath(baseline_path, REPO)} "
              f"({len(findings)} finding(s))")
        return 0

    if args.fail_on_new:
        baseline = an.load_baseline(baseline_path)
        new, old = an.diff_baseline(findings, baseline)
        stale = sum(baseline.values()) - len(old)
        if args.json:
            print(an.render_json(new, errors))
        else:
            print(an.render_text(
                new, errors,
                title=f"graftlint --fail-on-new ({len(old)} baselined, "
                      f"{stale} baseline entr{'y' if stale == 1 else 'ies'} "
                      "now stale)"))
            if stale > 0:
                print("graftlint: note: the baseline over-counts — "
                      "shrink it with --write-baseline")
        if new or errors:
            return 1
        return 0

    if args.json:
        print(an.render_json(findings, errors))
    else:
        print(an.render_text(findings, errors))
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
