#!/usr/bin/env python
"""graftlint — project-native static analysis for the mxnet_tpu repo.

Rules encode invariants this codebase has already paid to learn (see
docs/lint.md): lock-discipline races, torn writes of durable artifacts,
device->host syncs in hot loops, tracer leaks in jit code, swallowed
errors, env-knob drift against config.py — plus the whole-program flow
rules the v2 call-graph engine runs: collective-divergence (the SPMD
deadlock shape), lock-order-cycle (AB/BA across the threaded
subsystems), and trace-host-escape (host work reachable from donated
jit/shard_map/scan bodies).

Usage:
  python tools/graftlint.py                      # lint default paths
  python tools/graftlint.py --fail-on-new        # CI gate (baseline diff)
  python tools/graftlint.py --write-baseline     # accept current findings
  python tools/graftlint.py --changed-only       # findings in files
                                                 # touched vs merge-base
  python tools/graftlint.py --timings            # per-rule wall-time table
  python tools/graftlint.py --json path/to.py    # machine-readable
  python tools/graftlint.py --sarif out.sarif    # SARIF 2.1.0 for CI
  python tools/graftlint.py --explain <rule>     # rule catalog entry
  python tools/graftlint.py --list-rules

Exit codes: 0 clean (or only baselined findings with --fail-on-new),
1 gate failure, 2 usage/internal error.

The analysis package is loaded straight from its directory so that
linting never imports mxnet_tpu itself (no jax/numpy import cost).
Note the whole tree is ALWAYS analyzed (the call graph needs every
summary); --changed-only only restricts which findings are reported.
"""
from __future__ import annotations

import argparse
import importlib.util
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_PATHS = ("mxnet_tpu", "tools", "bench.py", "__graft_entry__.py")
DEFAULT_BASELINE = os.path.join("ci", "graftlint_baseline.json")


def _load_analysis():
    pkg_dir = os.path.join(REPO, "mxnet_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "graftlint_analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["graftlint_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def _changed_files(base_ref="main"):
    """Repo-relative ``.py`` paths touched (committed or working tree)
    since ``git merge-base HEAD <base_ref>`` — or None when git cannot
    answer (not a repo, unknown ref): the caller falls back to
    full-tree reporting with a warning."""
    try:
        base = subprocess.run(
            ["git", "merge-base", "HEAD", base_ref], cwd=REPO,
            capture_output=True, text=True, timeout=30)
        if base.returncode != 0:
            return None
        diff = subprocess.run(
            ["git", "diff", "--name-only", base.stdout.strip()],
            cwd=REPO, capture_output=True, text=True, timeout=30)
        if diff.returncode != 0:
            return None
    except (OSError, subprocess.SubprocessError):
        return None
    return {ln.strip().replace(os.sep, "/")
            for ln in diff.stdout.splitlines()
            if ln.strip().endswith(".py")}


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to lint (default: {DEFAULT_PATHS})")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (schema v2: findings "
                         "+ call_graph stats + optional timings)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON path (repo-relative)")
    ap.add_argument("--fail-on-new", action="store_true",
                    help="exit 1 when findings exceed the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="commit current findings as the baseline")
    ap.add_argument("--changed-only", action="store_true",
                    help="report findings only in files touched vs "
                         "`git merge-base HEAD main` (the whole tree "
                         "is still analyzed for the call graph)")
    ap.add_argument("--diff-base", default="main",
                    help="ref --changed-only diffs against "
                         "(default: main)")
    ap.add_argument("--timings", action="store_true",
                    help="print a per-rule wall-time table (where "
                         "lint time goes)")
    ap.add_argument("--sarif", default="", metavar="PATH",
                    help="also write findings as SARIF 2.1.0 to PATH "
                         "(rule metadata from the catalog, graftlint "
                         "fingerprints as partialFingerprints)")
    ap.add_argument("--explain", default="", metavar="RULE",
                    help="print RULE's catalog entry (description, "
                         "origin bug, flag + near-miss examples) and "
                         "exit — the same source of truth docs/lint.md "
                         "embeds")
    ap.add_argument("--select", default="",
                    help="comma-separated rule ids to run exclusively")
    ap.add_argument("--disable", default="",
                    help="comma-separated rule ids to skip")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    an = _load_analysis()

    if args.explain:
        block = an.catalog.explain(args.explain)
        if block is None:
            known = sorted(set(an.all_rules()) | set(an.all_graph_rules()))
            print(f"graftlint: unknown rule {args.explain!r} "
                  f"(known: {', '.join(known)})", file=sys.stderr)
            return 2
        print(block, end="")
        return 0

    if args.list_rules:
        catalog = dict(an.all_rules())
        catalog.update(an.all_graph_rules())
        for rid, cls in sorted(catalog.items()):
            print(f"{rid:<24} [{cls.severity}] {cls.doc}")
        return 0

    select = [r for r in args.select.split(",") if r]
    disable = [r for r in args.disable.split(",") if r]
    known = set(an.all_rules()) | set(an.all_graph_rules())
    unknown = (set(select) | set(disable)) - known
    if unknown:
        print(f"graftlint: unknown rules: {sorted(unknown)}",
              file=sys.stderr)
        return 2
    lex_ids = set(an.all_rules())
    lex_disable = [r for r in disable if r in lex_ids]
    if select:
        lex_select = [r for r in select if r in lex_ids]
        rules = an.make_rules(select=lex_select,
                              disable=lex_disable) if lex_select else []
    else:
        rules = an.make_rules(disable=lex_disable)
    graph_rules = an.make_graph_rules(
        select=select or None, disable=disable)

    paths = args.paths or [os.path.join(REPO, p) for p in DEFAULT_PATHS]
    res = an.analyze_project(paths, rules=rules,
                             graph_rules=graph_rules, root=REPO,
                             timings=args.timings)
    findings, errors = res.findings, res.errors

    if args.changed_only:
        changed = _changed_files(args.diff_base)
        if changed is None:
            print("graftlint: --changed-only: git diff against "
                  f"{args.diff_base!r} unavailable; reporting the "
                  "full tree", file=sys.stderr)
        else:
            findings = [f for f in findings if f.path in changed]
            errors = [(p, m) for p, m in errors if p in changed]

    if args.sarif:
        import json as _json
        sarif_path = (args.sarif if os.path.isabs(args.sarif)
                      else os.path.join(os.getcwd(), args.sarif))
        tmp = f"{sarif_path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            _json.dump(an.render_sarif(findings), fh, indent=2,
                       sort_keys=True)
            fh.write("\n")
        os.replace(tmp, sarif_path)
        print(f"graftlint: SARIF written to {args.sarif} "
              f"({len(findings)} result(s))", file=sys.stderr)

    baseline_path = (args.baseline if os.path.isabs(args.baseline)
                     else os.path.join(REPO, args.baseline))

    if args.write_baseline:
        an.write_baseline(baseline_path, findings)
        print(f"graftlint: baseline written to "
              f"{os.path.relpath(baseline_path, REPO)} "
              f"({len(findings)} finding(s))")
        if args.timings and res.timings:
            print(an.render_timings(res.timings))
        return 0

    stats = res.program.stats()
    if args.fail_on_new:
        baseline = an.load_baseline(baseline_path)
        new, old = an.diff_baseline(findings, baseline)
        # under --changed-only the unfiltered debt is out of view, so
        # the baseline legitimately "over-counts" — no stale note
        stale = 0 if args.changed_only else \
            sum(baseline.values()) - len(old)
        if args.json:
            print(an.render_json(new, errors, call_graph=stats,
                                 timings=res.timings))
        else:
            print(an.render_text(
                new, errors,
                title=f"graftlint --fail-on-new ({len(old)} baselined, "
                      f"{stale} baseline entr{'y' if stale == 1 else 'ies'} "
                      "now stale)"))
            if stale > 0:
                print("graftlint: note: the baseline over-counts — "
                      "shrink it with --write-baseline")
            if args.timings and res.timings:
                print(an.render_timings(res.timings))
        if new or errors:
            return 1
        return 0

    if args.json:
        print(an.render_json(findings, errors, call_graph=stats,
                             timings=res.timings))
    else:
        print(an.render_text(findings, errors))
        if args.timings and res.timings:
            print(an.render_timings(res.timings))
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
