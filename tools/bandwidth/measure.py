#!/usr/bin/env python
"""Allreduce bandwidth measurement (parity: reference tools/bandwidth/
measure.py, which timed kvstore push+pull of ResNet/VGG-sized gradients
across GPUs).

TPU redesign: the collective is an XLA ``psum`` over a ``jax.sharding.Mesh``
(the same collective KVStoreICI and parallel.spmd ride), timed with the
transfer-sync + differenced-reps discipline shared with bench.py (an
async-dispatch timer measures queueing, not the wire).

Reported metric matches the reference: algorithmic bandwidth
  BW_alg = 2 * (n-1)/n * bytes / time
(the ring-allreduce wire optimum), per size in a sweep.

Runs anywhere jax has >1 device:
  * real multi-chip TPU: numbers are ICI bandwidth.
  * virtual CPU mesh (XLA_FLAGS=--xla_force_host_platform_device_count=8):
    numbers are host memcpy — useful only to validate the tool + shardings.

Usage:
  python tools/bandwidth/measure.py [--sizes 1e6,4e6,...] [--reps 10]
                                    [--dtype float32] [--output out.json]
"""
import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="1e5,1e6,1e7,2.5e7",
                    help="comma-separated element counts")
    ap.add_argument("--reps", type=int, default=10,
                    help="base rep count R; timing differences 2R vs R")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--output", default=None)
    args = ap.parse_args()

    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    devs = jax.devices()
    n = len(devs)
    if n < 2:
        print(json.dumps({"error": f"need >1 device, have {n} "
                          "(set XLA_FLAGS=--xla_force_host_platform_"
                          "device_count=8 for a virtual mesh)"}))
        return
    mesh = Mesh(np.array(devs), ("dp",))
    dtype = np.dtype(args.dtype)
    results = {"n_devices": n,
               "platform": devs[0].platform,
               "device_kind": getattr(devs[0], "device_kind", "?"),
               "dtype": str(dtype),
               "method": "psum over Mesh('dp'), dynamic-R fori_loop, "
                         "transfer-sync, differenced",
               "note": ("virtual CPU mesh measures host memcpy, not a "
                        "wire" if devs[0].platform == "cpu" else
                        "ICI allreduce"),
               "sweep": []}

    for size_s in args.sizes.split(","):
        size = int(float(size_s))

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(None, None, P("dp")),
                           out_specs=P("dp"), check_vma=False)
        def allreduce_chain(r, salt, x):
            # x: per-device shard; chain r psums, each data-dependent on
            # the previous (the *1e-30 fold keeps values stable but
            # unprovably so). salt: per-call-unique live input — some
            # relays cache repeated identical executions (see bench.py)
            x = x + (salt * 1e-30).astype(x.dtype)
            def body(_, acc):
                return lax.psum(acc * (1 + acc[0] * 1e-30).astype(acc.dtype),
                                "dp") / n
            return lax.fori_loop(0, r, body, x)

        def run(r, salt, x):
            return allreduce_chain(r, salt, x)[0].astype(jnp.float32)

        x = jnp.ones((size,), dtype)
        c = jax.jit(run).lower(jnp.int32(1), jnp.float32(0), x).compile()
        float(c(jnp.int32(2), jnp.float32(1), x))  # warm
        calls = [1]

        def timed(r, tries=3):
            ts = []
            for _ in range(tries):
                calls[0] += 1
                t0 = time.perf_counter()
                float(c(jnp.int32(r), jnp.float32(calls[0]), x))
                ts.append(time.perf_counter() - t0)
            return min(ts)

        t1 = timed(args.reps)
        t2 = timed(2 * args.reps)
        per = (t2 - t1) / args.reps
        nbytes = size * dtype.itemsize
        if per <= 0:
            results["sweep"].append({"elements": size, "anomaly":
                                     f"T(2R)={t2:.5f} <= T(R)={t1:.5f}"})
            continue
        bw_alg = 2 * (n - 1) / n * nbytes / per
        results["sweep"].append({
            "elements": size,
            "mbytes": round(nbytes / 1e6, 2),
            "ms_per_allreduce": round(per * 1e3, 4),
            "algbw_gbs": round(bw_alg / 1e9, 3),
        })
        print(f"{size:>12,} elems  {nbytes/1e6:8.1f} MB  "
              f"{per*1e3:8.3f} ms  {bw_alg/1e9:8.2f} GB/s", flush=True)

    if args.output:
        tmp = f"{args.output}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(results, f, indent=1)
        os.replace(tmp, args.output)
        print(f"wrote {args.output}")
    else:
        print(json.dumps(results))


if __name__ == "__main__":
    main()
