#!/usr/bin/env python
"""im2rec: pack an image folder into RecordIO (.rec/.idx).

Role parity with /root/reference/tools/im2rec.py: list generation
(prefix.lst: "index\tlabel[\tlabel...]\trelpath"), then a multiprocess
pack of encoded JPEG/PNG records in MXIndexedRecordIO format — the
.rec files interoperate with the reference's readers (recordio.py is
format-compatible).

Usage:
  python tools/im2rec.py --list prefix root          # make prefix.lst
  python tools/im2rec.py prefix root                 # pack prefix.rec/.idx
"""
import argparse
import multiprocessing
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def list_images(root, recursive, exts):
    """Yield (index, relpath, label) — label = sorted-subdir index
    (reference list_image)."""
    i = 0
    if recursive:
        cat = {}
        for path, dirs, files in sorted(os.walk(root, followlinks=True)):
            dirs.sort()
            files.sort()
            for fname in files:
                fpath = os.path.join(path, fname)
                suffix = os.path.splitext(fname)[1].lower()
                if os.path.isfile(fpath) and (suffix in exts):
                    if path not in cat:
                        cat[path] = len(cat)
                    yield (i, os.path.relpath(fpath, root), cat[path])
                    i += 1
    else:
        for fname in sorted(os.listdir(root)):
            fpath = os.path.join(root, fname)
            suffix = os.path.splitext(fname)[1].lower()
            if os.path.isfile(fpath) and (suffix in exts):
                yield (i, os.path.relpath(fpath, root), 0)
                i += 1


def write_list(path_out, image_list):
    tmp = f"{path_out}.tmp-{os.getpid()}"
    with open(tmp, "w") as fout:
        for i, item in enumerate(image_list):
            line = "%d\t" % item[0]
            for j in item[2:]:
                line += "%f\t" % j
            line += "%s\n" % item[1]
            fout.write(line)
    os.replace(tmp, path_out)


def read_list(path_in):
    with open(path_in) as fin:
        for line in fin:
            line = [i.strip() for i in line.strip().split("\t")]
            if len(line) < 3:
                continue
            yield (int(line[0]), line[-1],
                   [float(i) for i in line[1:-1]])


def _encode_image(args, item):
    """Load + (optionally) resize/crop + encode one image to bytes."""
    fullpath = os.path.join(args.root, item[1])
    if args.pass_through:
        with open(fullpath, "rb") as f:
            return f.read()
    import numpy as np
    try:
        import cv2
        img = cv2.imread(fullpath, args.color)
        if img is None:
            return None
        if args.center_crop and img.shape[0] != img.shape[1]:
            m = min(img.shape[:2])
            y0 = (img.shape[0] - m) // 2
            x0 = (img.shape[1] - m) // 2
            img = img[y0:y0 + m, x0:x0 + m]
        if args.resize:
            h, w = img.shape[:2]
            scale = args.resize / min(h, w)
            img = cv2.resize(img, (int(w * scale), int(h * scale)))
        if args.encoding == "raw":
            # fixed-shape HWC uint8 pixels: the io.RawRecordIter /
            # native RecordPipe fast-path format (requires --resize +
            # --center-crop so every record is the same size)
            return np.ascontiguousarray(img, np.uint8).tobytes()
        ok, buf = cv2.imencode(args.encoding, img,
                               [cv2.IMWRITE_JPEG_QUALITY, args.quality])
        return buf.tobytes() if ok else None
    except ImportError:
        from PIL import Image
        import io
        img = Image.open(fullpath).convert("RGB")
        if args.center_crop and img.size[0] != img.size[1]:
            m = min(img.size)
            x0 = (img.size[0] - m) // 2
            y0 = (img.size[1] - m) // 2
            img = img.crop((x0, y0, x0 + m, y0 + m))
        if args.resize:
            scale = args.resize / min(img.size)
            img = img.resize((int(img.size[0] * scale),
                              int(img.size[1] * scale)))
        if args.encoding == "raw":
            arr = np.asarray(img, dtype=np.uint8)
            return np.ascontiguousarray(arr).tobytes()
        out = io.BytesIO()
        img.save(out, format="JPEG" if args.encoding == ".jpg" else "PNG",
                 quality=args.quality)
        return out.getvalue()


def _pack_worker(args, item):
    from mxnet_tpu import recordio
    data = _encode_image(args, item)
    if data is None:
        return item[0], None
    if len(item[2]) > 1 or args.pack_label:
        header = recordio.IRHeader(0, item[2], item[0], 0)
    else:
        header = recordio.IRHeader(0, item[2][0], item[0], 0)
    return item[0], recordio.pack(header, data)


def make_rec(args, image_list):
    """Multiprocess encode, single-writer pack (reference im2rec.py
    read_worker/write_worker pipeline)."""
    from functools import partial
    from mxnet_tpu import recordio
    record = recordio.MXIndexedRecordIO(args.prefix + ".idx",
                                        args.prefix + ".rec", "w")
    t0 = time.time()
    count = 0
    worker = partial(_pack_worker, args)
    if args.num_thread > 1:
        # forkserver: the parent has imported mxnet_tpu (and therefore
        # jax, which is multithreaded) by the time workers start — a
        # plain fork() deadlocks. Same fix as gluon.data.DataLoader.
        ctx = multiprocessing.get_context("forkserver")
        with ctx.Pool(args.num_thread) as pool:
            for idx, payload in pool.imap(worker, image_list,
                                          chunksize=16):
                if payload is None:
                    print(f"imread failed for index {idx}",
                          file=sys.stderr)
                    continue
                record.write_idx(idx, payload)
                count += 1
                if count % 1000 == 0:
                    print(f"packed {count} images "
                          f"({count / (time.time() - t0):.1f}/s)")
    else:
        for item in image_list:
            idx, payload = worker(item)
            if payload is None:
                continue
            record.write_idx(idx, payload)
            count += 1
    record.close()
    print(f"wrote {count} records to {args.prefix}.rec "
          f"in {time.time() - t0:.1f}s")


def main():
    parser = argparse.ArgumentParser(
        description="Create an image list / RecordIO database "
                    "(parity: reference tools/im2rec.py)")
    parser.add_argument("prefix", help="prefix of .lst/.rec/.idx files")
    parser.add_argument("root", help="folder containing the images")
    parser.add_argument("--list", action="store_true",
                        help="create an image list, not a database")
    parser.add_argument("--exts", nargs="+",
                        default=[".jpeg", ".jpg", ".png"])
    parser.add_argument("--recursive", action="store_true",
                        help="label = sorted-subdir index")
    parser.add_argument("--no-shuffle", dest="shuffle",
                        action="store_false")
    parser.add_argument("--pass-through", action="store_true",
                        help="skip transcoding, pack raw bytes")
    parser.add_argument("--resize", type=int, default=0)
    parser.add_argument("--center-crop", action="store_true")
    parser.add_argument("--quality", type=int, default=95)
    parser.add_argument("--num-thread", type=int, default=1)
    parser.add_argument("--color", type=int, default=1,
                        choices=[-1, 0, 1])
    parser.add_argument("--encoding", type=str, default=".jpg",
                        choices=[".jpg", ".png", "raw"])
    parser.add_argument("--pack-label", action="store_true")
    args = parser.parse_args()

    if args.list:
        images = list(list_images(args.root, args.recursive, args.exts))
        image_list = [(i, rel, lab) for i, rel, lab in images]
        if args.shuffle:
            random.seed(100)
            random.shuffle(image_list)
            image_list = [(n, rel, lab) for n, (_, rel, lab)
                          in enumerate(image_list)]
        write_list(args.prefix + ".lst",
                   [(i, rel, lab) for i, rel, lab in image_list])
        print(f"wrote {len(image_list)} entries to {args.prefix}.lst")
        return

    lst = args.prefix + ".lst"
    if os.path.exists(lst):
        image_list = list(read_list(lst))
    else:
        image_list = [(i, rel, [float(lab)]) for i, rel, lab in
                      list_images(args.root, args.recursive, args.exts)]
    make_rec(args, image_list)


if __name__ == "__main__":
    main()
