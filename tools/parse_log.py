#!/usr/bin/env python
"""Parse training logs into a table (parity: reference tools/parse_log.py).

Understands the Module/Estimator log format:
    Epoch[3] Train-accuracy=0.914
    Epoch[3] Time cost=12.3
    Epoch[3] Validation-accuracy=0.897

Usage: python tools/parse_log.py train.log [--format markdown|csv]
"""
import argparse
import re
import sys


def parse(lines):
    rows = {}
    for line in lines:
        m = re.search(r"Epoch\[(\d+)\]\s+(.*)", line)
        if not m:
            continue
        epoch, rest = int(m.group(1)), m.group(2)
        prefix = ""
        if rest.lower().startswith("validation:"):
            # Estimator validation lines carry several k=v pairs after a
            # "validation:" marker
            prefix = "Validation-"
            rest = rest.split(":", 1)[1]
        for key, val in re.findall(
                r"([A-Za-z][\w .-]*?)=([0-9.eE+-]+)", rest):
            rows.setdefault(epoch, {})[prefix + key.strip()] = float(val)
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("logfile")
    ap.add_argument("--format", choices=("markdown", "csv"),
                    default="markdown")
    args = ap.parse_args()
    with open(args.logfile) as f:
        rows = parse(f)
    if not rows:
        sys.exit("no Epoch[...] lines found")
    cols = sorted({k for r in rows.values() for k in r})
    if args.format == "csv":
        print(",".join(["epoch"] + cols))
        for e in sorted(rows):
            print(",".join([str(e)] + [str(rows[e].get(c, ""))
                                       for c in cols]))
    else:
        print("| epoch | " + " | ".join(cols) + " |")
        print("|" + "---|" * (len(cols) + 1))
        for e in sorted(rows):
            print("| " + " | ".join(
                [str(e)] + [f"{rows[e][c]:g}" if c in rows[e] else ""
                            for c in cols]) + " |")


if __name__ == "__main__":
    main()
