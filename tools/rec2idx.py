#!/usr/bin/env python
"""Rebuild the .idx file for a RecordIO .rec file.

Parity: reference tools/rec2idx.py. Uses the native frame scanner
(src/io_native.cc) when built — a single sequential header pass — and
falls back to a Python read loop otherwise.

Usage: python tools/rec2idx.py data.rec [data.idx]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    if len(sys.argv) < 2:
        sys.exit(__doc__)
    from mxnet_tpu import recordio
    rec = sys.argv[1]
    idx = sys.argv[2] if len(sys.argv) > 2 else None
    n = recordio.rec2idx(rec, idx)
    print(f"wrote {n} index entries")


if __name__ == "__main__":
    main()
