/* XS glue for AI::MXNetTPU — binds the training-capable C ABI
 * (src/c_api.h) into Perl.  Parity: reference perl-package/AI-MXNet
 * wraps the same handles; here handles cross as IVs and the .pm layer
 * wraps them in objects with DESTROY.
 */
#define PERL_NO_GET_CONTEXT
#include "EXTERN.h"
#include "perl.h"
#include "XSUB.h"

#include <dlfcn.h>

#include "c_api.h"

static void croak_mx(pTHX) { croak("%s", MXGetLastError()); }

MODULE = AI::MXNetTPU  PACKAGE = AI::MXNetTPU  PREFIX = mxtpu_

PROTOTYPES: DISABLE

BOOT:
{
  /* perl dlopens this extension RTLD_LOCAL, which keeps libpython's
   * symbols private — numpy's own C extensions then fail to resolve
   * them and the embedded interpreter cannot import numpy.  Promote
   * libpython to RTLD_GLOBAL before the first C-API call initializes
   * Python (the same dance every libpython-embedding plugin does). */
  const char* candidates[] = {
    "libpython3.12.so.1.0", "libpython3.12.so",
    "libpython3.11.so.1.0", "libpython3.13.so.1.0", NULL};
  int i;
  for (i = 0; candidates[i]; ++i)
    if (dlopen(candidates[i], RTLD_NOW | RTLD_GLOBAL | RTLD_NOLOAD) ||
        dlopen(candidates[i], RTLD_NOW | RTLD_GLOBAL))
      break;
}

int
mxtpu__version()
  CODE:
    int v = 0;
    if (MXGetVersion(&v) != 0) croak_mx(aTHX);
    RETVAL = v;
  OUTPUT: RETVAL

IV
mxtpu__nd_create(shape_ref)
    SV* shape_ref
  CODE:
    AV* av = (AV*)SvRV(shape_ref);
    mx_uint ndim = (mx_uint)(av_len(av) + 1);
    mx_uint shape[32];
    for (mx_uint i = 0; i < ndim && i < 32; ++i)
      shape[i] = (mx_uint)SvUV(*av_fetch(av, i, 0));
    NDArrayHandle h = NULL;
    if (MXNDArrayCreateEx(shape, ndim, 1, 0, 0, 0, &h) != 0)
      croak_mx(aTHX);
    RETVAL = PTR2IV(h);
  OUTPUT: RETVAL

void
mxtpu__nd_free(h)
    IV h
  CODE:
    MXNDArrayFree(INT2PTR(NDArrayHandle, h));

void
mxtpu__nd_copy_from(h, data_ref)
    IV h
    SV* data_ref
  CODE:
    AV* av = (AV*)SvRV(data_ref);
    size_t n = (size_t)(av_len(av) + 1);
    float* buf = (float*)malloc(n * sizeof(float));
    for (size_t i = 0; i < n; ++i)
      buf[i] = (float)SvNV(*av_fetch(av, (SSize_t)i, 0));
    int rc = MXNDArraySyncCopyFromCPU(INT2PTR(NDArrayHandle, h), buf,
                                      n * sizeof(float));
    free(buf);
    if (rc != 0) croak_mx(aTHX);

SV*
mxtpu__nd_to_list(h)
    IV h
  CODE:
    NDArrayHandle nh = INT2PTR(NDArrayHandle, h);
    mx_uint ndim = 0;
    const mx_uint* shape = NULL;
    if (MXNDArrayGetShape(nh, &ndim, &shape) != 0) croak_mx(aTHX);
    size_t n = 1;
    for (mx_uint i = 0; i < ndim; ++i) n *= shape[i];
    float* buf = (float*)malloc(n * sizeof(float));
    if (MXNDArraySyncCopyToCPU(nh, buf, n * sizeof(float)) != 0) {
      free(buf);
      croak_mx(aTHX);
    }
    AV* out = newAV();
    for (size_t i = 0; i < n; ++i) av_push(out, newSVnv(buf[i]));
    free(buf);
    RETVAL = newRV_noinc((SV*)out);
  OUTPUT: RETVAL

SV*
mxtpu__nd_shape(h)
    IV h
  CODE:
    mx_uint ndim = 0;
    const mx_uint* shape = NULL;
    if (MXNDArrayGetShape(INT2PTR(NDArrayHandle, h), &ndim, &shape) != 0)
      croak_mx(aTHX);
    AV* out = newAV();
    for (mx_uint i = 0; i < ndim; ++i) av_push(out, newSVuv(shape[i]));
    RETVAL = newRV_noinc((SV*)out);
  OUTPUT: RETVAL

SV*
mxtpu__invoke(op_name, inputs_ref, attrs_ref)
    const char* op_name
    SV* inputs_ref
    SV* attrs_ref
  CODE:
    AV* in_av = (AV*)SvRV(inputs_ref);
    int n_in = (int)(av_len(in_av) + 1);
    NDArrayHandle inputs[64];
    for (int i = 0; i < n_in && i < 64; ++i)
      inputs[i] = INT2PTR(NDArrayHandle, SvIV(*av_fetch(in_av, i, 0)));
    HV* attrs = (HV*)SvRV(attrs_ref);
    const char* keys[64];
    const char* vals[64];
    int n_attr = 0;
    hv_iterinit(attrs);
    HE* he;
    while ((he = hv_iternext(attrs)) != NULL && n_attr < 64) {
      STRLEN klen;
      keys[n_attr] = HePV(he, klen);
      vals[n_attr] = SvPV_nolen(HeVAL(he));
      ++n_attr;
    }
    int n_out = 0;
    NDArrayHandle* outputs = NULL;
    if (MXImperativeInvokeEx(op_name, n_in, inputs, &n_out, &outputs,
                             n_attr, keys, vals) != 0)
      croak_mx(aTHX);
    AV* out = newAV();
    for (int i = 0; i < n_out; ++i) av_push(out, newSViv(PTR2IV(outputs[i])));
    RETVAL = newRV_noinc((SV*)out);
  OUTPUT: RETVAL

void
mxtpu__invoke_inplace(op_name, inputs_ref, attrs_ref, out_h)
    const char* op_name
    SV* inputs_ref
    SV* attrs_ref
    IV out_h
  CODE:
    AV* in_av = (AV*)SvRV(inputs_ref);
    int n_in = (int)(av_len(in_av) + 1);
    NDArrayHandle inputs[64];
    for (int i = 0; i < n_in && i < 64; ++i)
      inputs[i] = INT2PTR(NDArrayHandle, SvIV(*av_fetch(in_av, i, 0)));
    HV* attrs = (HV*)SvRV(attrs_ref);
    const char* keys[64];
    const char* vals[64];
    int n_attr = 0;
    hv_iterinit(attrs);
    HE* he;
    while ((he = hv_iternext(attrs)) != NULL && n_attr < 64) {
      STRLEN klen;
      keys[n_attr] = HePV(he, klen);
      vals[n_attr] = SvPV_nolen(HeVAL(he));
      ++n_attr;
    }
    int n_out = 1;
    NDArrayHandle pre[1] = {INT2PTR(NDArrayHandle, out_h)};
    NDArrayHandle* outputs = pre;
    if (MXImperativeInvokeEx(op_name, n_in, inputs, &n_out, &outputs,
                             n_attr, keys, vals) != 0)
      croak_mx(aTHX);

void
mxtpu__set_recording(flag)
    int flag
  CODE:
    int prev = 0;
    if (MXAutogradSetIsRecording(flag, &prev) != 0) croak_mx(aTHX);
    if (MXAutogradSetIsTraining(flag, &prev) != 0) croak_mx(aTHX);

void
mxtpu__mark_variable(var_h, grad_h)
    IV var_h
    IV grad_h
  CODE:
    NDArrayHandle v = INT2PTR(NDArrayHandle, var_h);
    NDArrayHandle g = INT2PTR(NDArrayHandle, grad_h);
    mx_uint req = 1;
    if (MXAutogradMarkVariables(1, &v, &req, &g) != 0) croak_mx(aTHX);

void
mxtpu__backward(h)
    IV h
  CODE:
    NDArrayHandle nh = INT2PTR(NDArrayHandle, h);
    if (MXAutogradBackward(1, &nh, NULL, 0) != 0) croak_mx(aTHX);

IV
mxtpu__grad(h)
    IV h
  CODE:
    NDArrayHandle out = NULL;
    if (MXNDArrayGetGrad(INT2PTR(NDArrayHandle, h), &out) != 0)
      croak_mx(aTHX);
    RETVAL = PTR2IV(out);
  OUTPUT: RETVAL

SV*
mxtpu__list_ops()
  CODE:
    mx_uint n = 0;
    const char** names = NULL;
    if (MXListAllOpNames(&n, &names) != 0) croak_mx(aTHX);
    AV* out = newAV();
    for (mx_uint i = 0; i < n; ++i) av_push(out, newSVpv(names[i], 0));
    RETVAL = newRV_noinc((SV*)out);
  OUTPUT: RETVAL
